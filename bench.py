"""Benchmark harness. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric: map_blocks model-scoring throughput in rows/sec/chip on
the real TPU (BASELINE.json: "map_blocks rows/sec/chip"). The model config
escalates as model families land (logreg → Inception-v3); sub-metrics are
printed as comment lines prefixed with '#' so the driver's JSON line stays
unambiguous.

The reference publishes no numbers (BASELINE.md) — the baseline here is
the first recorded value of this harness; vs_baseline is measured against
the "published" dict in BASELINE.json when present, else 1.0.
"""

from __future__ import annotations

import json
import time
from typing import Sequence

import numpy as np


def _sync(arr):
    """Force completion of device work. ``block_until_ready`` is a no-op on
    remote-tunnel platforms (observed on axon), so read a single element
    back to the host — O(1) transfer, full dependency barrier."""
    np.asarray(arr[(0,) * arr.ndim])


def _time_rows_per_sec(run_once, n_rows: int, iters: int) -> float:
    """Shared timing scaffold: one warmup/compile call, then the MEDIAN
    over ``iters`` timed calls — medians keep repeated runs within ~10%
    on a shared machine where a mean absorbs scheduler spikes (the r01
    vs r02 bert_tiny discrepancy the round-2 verdict flagged)."""
    run_once()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run_once()
        times.append(time.perf_counter() - t0)
    return n_rows / float(np.median(times))


def _record_mfu(name: str, program, rows_per_sec: float, n_rows: int) -> None:
    """Attach XLA-cost-model FLOPs to a profiling span so report() prints
    achieved GFLOP/s (and MFU when config.peak_flops is set). Best-iter
    seconds reconstructed from the returned throughput."""
    try:
        from tensorframes_tpu.utils import profiling

        fpr = program.flops_per_row()
        bpr = program.bytes_per_row()
        if fpr > 0 and rows_per_sec > 0:
            profiling.record(
                name,
                n_rows / rows_per_sec,
                rows=n_rows,
                flops=fpr * n_rows,
                bytes_accessed=bpr * n_rows,
            )
    except Exception as e:  # cost model unavailable on some backends
        print(f"# mfu accounting unavailable for {name}: {e}")


def _h2d_seconds(arrays, reps: int = 3) -> float:
    """Median wall-clock to ``device_put`` these host arrays and confirm
    arrival — the marshalling half of every transfer-bound metric,
    measured on its own so a slow link (the relay tunnel's ~70ms/8MB)
    is a NUMBER, not a narrative (VERDICT r3 #2)."""
    import jax

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        bufs = [jax.device_put(a) for a in arrays]
        for buf in bufs:
            _sync(buf)
        times.append(time.perf_counter() - t0)
        del bufs
    return float(np.median(times))


def _print_split(name: str, h2d_s: float, nbytes: int,
                 compute_s: float, total_s: float) -> None:
    """One ``# split |`` line per transfer-bound metric: h2d vs compute
    vs marshalling-included total, so blame is apportionable."""
    print(
        f"# split | {name} h2d_s={h2d_s:.6f} mb={nbytes / 1e6:.1f} "
        f"compute_s={compute_s:.6f} host_total_s={total_s:.6f}"
    )


def _bench_map_blocks_logreg(
    n_rows: int = 262_144, iters: int = 5, device: bool = True,
    num_blocks: int = 1,
):
    import tensorframes_tpu as tfs
    from tensorframes_tpu.models import logreg

    x, _ = logreg.make_synthetic_mnist(n_rows)
    frame = tfs.frame_from_arrays({"features": x}, num_blocks=num_blocks)
    if device:
        frame = frame.to_device()
    params = logreg.init_params()
    scoring = logreg.scoring_program(params)
    program = tfs.compile_program(lambda features: scoring(features), frame)

    def run_once():
        out = tfs.map_blocks(program, frame)
        for b in out.blocks():
            _sync(b["scores"])
            _sync(b["label"])

    rps = _time_rows_per_sec(run_once, n_rows, iters)
    if device:
        _record_mfu("bench.logreg", program, rps, n_rows)
    return rps


def _bench_add3(n_rows: int = 1_000_000, iters: int = 10,
                device: bool = True, num_blocks: int = 1):
    """README add-3 config (BASELINE config 1)."""
    import tensorframes_tpu as tfs

    frame = tfs.frame_from_arrays(
        {"x": np.arange(n_rows, dtype=np.float32)}, num_blocks=num_blocks
    )
    if device:
        frame = frame.to_device()
    program = tfs.compile_program(lambda x: {"z": x + 3.0}, frame)

    def run_once():
        out = tfs.map_blocks(program, frame)
        for b in out.blocks():
            _sync(b["z"])

    return _time_rows_per_sec(run_once, n_rows, iters)


def _bench_chain3(n_rows: int = 1_000_000, iters: int = 8,
                  num_blocks: int = 4):
    """3-stage chained elementwise map (ISSUE 4): the plan layer fuses
    the chain into ONE composed XLA program per block; TFTPU_FUSION=0
    re-runs the identical chain per-stage. Returns (fused_wall_s,
    unfused_wall_s); a ``# plan |`` summary (fused stages, intermediate
    bytes avoided) prints from main() after the timed run."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu import configure
    from tensorframes_tpu.config import get_config

    frame = tfs.frame_from_arrays(
        {"x": np.arange(n_rows, dtype=np.float32)}, num_blocks=num_blocks
    )
    # stage programs pre-compiled once (the steady-state serving shape);
    # each iteration rebuilds the chain, as a per-batch pipeline would
    p1 = tfs.compile_program(lambda x: {"y": x * 2.0 + 1.0}, frame)
    f1 = tfs.map_blocks(p1, frame)
    p2 = tfs.compile_program(lambda y: {"z": y * 0.5 - 3.0}, f1)
    f2 = tfs.map_blocks(p2, f1)
    p3 = tfs.compile_program(lambda z: {"w": z * z + 1.0}, f2)

    def run_once():
        out = tfs.map_blocks(
            p3, tfs.map_blocks(p2, tfs.map_blocks(p1, frame))
        ).select(["w"])
        for b in out.blocks():
            _sync(b["w"])

    def wall(iters_):
        run_once()  # warm the jit caches out of the timed region
        t0 = time.perf_counter()
        for _ in range(iters_):
            run_once()
        return (time.perf_counter() - t0) / iters_

    was = get_config().plan_fusion
    try:
        configure(plan_fusion=True)
        fused_s = wall(iters)
        configure(plan_fusion=False)  # the TFTPU_FUSION=0 path
        unfused_s = wall(iters)
    finally:
        configure(plan_fusion=was)
    return fused_s, unfused_s


def _bench_chain3_join(n_rows: int = 1_000_000, iters: int = 6,
                       num_blocks: int = 4, n_groups: int = 512):
    """3-stage map→join→aggregate pipeline (ISSUE 7): the probe-side
    map chain fuses into the probe dispatch, build-side pushdown prunes
    dead columns through the join on BOTH sides, and the aggregate's
    segment-reduce epilogue runs inside the same plan force — the
    mapped/joined intermediates the per-stage replay materializes never
    exist. TFTPU_FUSION=0 re-runs the identical pipeline per-stage.
    Data is chosen so every group sum is exactly representable in f32:
    fused and unfused outputs must be BIT-IDENTICAL (asserted here).
    Returns (fused_wall_s, unfused_wall_s, steady_state_compiles)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.config import get_config
    from tensorframes_tpu.ops.executor import _JIT_MISSES

    rng = np.random.default_rng(0)
    frame = tfs.frame_from_arrays(
        {
            "k": rng.integers(0, n_groups, n_rows).astype(np.int32),
            "x": (np.arange(n_rows) % 16).astype(np.float32),
            # dead probe-side columns — incl. an embedding-style wide
            # one: pushdown must keep them out of the map dispatches
            # and the join's match expansion entirely (the Flare
            # motivation: real pipelines carry far more columns than a
            # query touches)
            "a": np.arange(n_rows, dtype=np.float32),
            "b": np.ones(n_rows, np.float32),
            "e": np.ones((n_rows, 8), np.float32),
        },
        num_blocks=num_blocks,
    )
    dim = tfs.frame_from_arrays(
        {
            "k": np.arange(n_groups, dtype=np.int32),
            "w": np.arange(n_groups, dtype=np.float32),
            "tag": np.ones(n_groups, np.float32),  # dead build column
        },
        num_blocks=1,
    )
    p1 = tfs.compile_program(lambda x: {"y": x * 2.0 + 1.0}, frame)
    p2 = tfs.compile_program(
        lambda y: {"z": y * y}, tfs.map_blocks(p1, frame)
    )
    # the aggregate program compiles ONCE against the join schema (the
    # steady-state serving shape, like chain3's pre-compiled stages)
    j0 = tfs.map_blocks(p2, tfs.map_blocks(p1, frame)).join(dim, on="k")
    j0.blocks()
    with tfs.with_graph():
        z_in = tfs.block(j0, "z", tf_name="z_input")
        w_in = tfs.block(j0, "w", tf_name="w_input")
        fz = tfs.reduce_sum(z_in, axis=0, name="z")
        fw = tfs.reduce_sum(w_in, axis=0, name="w")
        agg_program = tfs.compile_program(
            [fz, fw], j0, reduce_mode="blocks"
        )

    def run_once():
        f2 = tfs.map_blocks(p2, tfs.map_blocks(p1, frame))
        out = tfs.aggregate(
            agg_program, f2.join(dim, on="k").group_by("k")
        )
        return out.blocks()

    def wall(iters_):
        run_once()  # warm the jit caches out of the timed region
        t0 = time.perf_counter()
        for _ in range(iters_):
            run_once()
        return (time.perf_counter() - t0) / iters_

    was = get_config().plan_fusion
    try:
        tfs.configure(plan_fusion=True)
        run_once()  # warm
        m0 = _JIT_MISSES.value
        fused_s = wall(iters)
        steady_compiles = int(_JIT_MISSES.value - m0)
        fused_rows = run_once()
        tfs.configure(plan_fusion=False)
        unfused_s = wall(iters)
        unfused_rows = run_once()
    finally:
        tfs.configure(plan_fusion=was)
    if len(fused_rows) != len(unfused_rows):
        raise AssertionError(
            f"chain3_join: fused produced {len(fused_rows)} block(s), "
            f"unfused {len(unfused_rows)} — the bit-identical contract "
            "is broken"
        )
    for fb, ub in zip(fused_rows, unfused_rows):
        if set(fb) != set(ub):
            raise AssertionError(
                f"chain3_join: fused columns {sorted(fb)} != unfused "
                f"{sorted(ub)} — the bit-identical contract is broken"
            )
        for name in fb:
            if not np.array_equal(
                np.asarray(fb[name]), np.asarray(ub[name])
            ):
                raise AssertionError(
                    f"chain3_join: fused and unfused outputs differ in "
                    f"column {name!r} — the bit-identical contract is "
                    "broken"
                )
    return fused_s, unfused_s, steady_compiles


def _bench_lifted_chain(n_rows: int = 1_000_000, iters: int = 6,
                        num_blocks: int = 4, n_groups: int = 512):
    """map→numpy-UDF→aggregate with verified lifting (ISSUE 18): the
    static pass lifts the host-callback numpy UDF into the plan IR, so
    the whole chain fuses into one dispatch; ``TFTPU_LIFT=0``
    (``configure(udf_lifting=False)``) replays the identical pipeline
    through the real ``pure_callback`` stage as the bit-identity
    oracle. UDF values are small odd integers and group sums stay well
    under 2^24, so every aggregate is exactly representable in f32:
    lifted and callback outputs must be BIT-IDENTICAL (asserted here),
    the lifted chain must report ZERO fusion barriers, and the steady
    state must run compile-free — all three are hard gates, not report
    lines. Returns (lifted_wall_s, callback_wall_s, steady_compiles)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.config import get_config
    from tensorframes_tpu.ops.executor import _JIT_MISSES
    from tensorframes_tpu.plan import ir as plan_ir
    from tensorframes_tpu.plan import lift as plan_lift

    rng = np.random.default_rng(0)
    frame = tfs.frame_from_arrays(
        {
            "k": rng.integers(0, n_groups, n_rows).astype(np.int32),
            "x": (np.arange(n_rows) % 16).astype(np.float32),
        },
        num_blocks=num_blocks,
    )
    p1 = tfs.compile_program(lambda x: {"y": x * 2.0 + 1.0}, frame)

    def score(y):
        # elementwise allowlist forms only: where/compare/arith — the
        # shape the lifter proves bit-exact and substitutes
        return {"s": np.where(y > 8.0, y - 8.0, 8.0 - y)}

    # ONE NumpyUDF capture reused every iteration (the steady-state
    # serving shape): its per-spec Program cache is what makes the
    # steady state compile-free
    udf = tfs.numpy_udf(score)
    f1 = tfs.map_blocks(p1, frame)
    plan_lift.clear_lift_log()
    f2 = tfs.map_blocks(udf, f1)
    recs = [r for r in plan_lift.lift_log() if r["udf"] == "score"]
    if not (recs and recs[-1]["lifted"]):
        raise AssertionError(
            f"lifted_chain: the score UDF did not lift "
            f"({recs[-1] if recs else 'no decision recorded'})"
        )
    n_maps, barriers = plan_ir.chain_barriers(f2)
    if barriers:
        raise AssertionError(
            f"lifted_chain: lifted chain still reports fusion "
            f"barriers: {barriers}"
        )
    # the aggregate program compiles ONCE against the mapped schema
    # (the steady-state serving shape, like chain3's stages)
    with tfs.with_graph():
        s_in = tfs.block(f2, "s", tf_name="s_input")
        fs = tfs.reduce_sum(s_in, axis=0, name="s")
        agg_program = tfs.compile_program(
            [fs], f2, reduce_mode="blocks"
        )

    def run_once():
        f = tfs.map_blocks(udf, tfs.map_blocks(p1, frame))
        out = tfs.aggregate(agg_program, f.group_by("k"))
        return out.blocks()

    def wall(iters_):
        run_once()  # warm the jit caches out of the timed region
        t0 = time.perf_counter()
        for _ in range(iters_):
            run_once()
        return (time.perf_counter() - t0) / iters_

    was = get_config().udf_lifting
    try:
        tfs.configure(udf_lifting=True)
        run_once()  # warm
        m0 = _JIT_MISSES.value
        lifted_s = wall(iters)
        steady_compiles = int(_JIT_MISSES.value - m0)
        lifted_rows = run_once()
        tfs.configure(udf_lifting=False)  # the TFTPU_LIFT=0 oracle
        callback_s = wall(iters)
        callback_rows = run_once()
    finally:
        tfs.configure(udf_lifting=was)
    if steady_compiles:
        raise AssertionError(
            f"lifted_chain: {steady_compiles} steady-state compile(s) "
            "— the lifted chain must be compile-free after warmup"
        )
    if len(lifted_rows) != len(callback_rows):
        raise AssertionError(
            f"lifted_chain: lifted produced {len(lifted_rows)} "
            f"block(s), callback {len(callback_rows)} — the "
            "bit-identity contract is broken"
        )
    for lb, cb in zip(lifted_rows, callback_rows):
        if set(lb) != set(cb):
            raise AssertionError(
                f"lifted_chain: lifted columns {sorted(lb)} != callback "
                f"{sorted(cb)} — the bit-identity contract is broken"
            )
        for name in lb:
            la, ca = np.asarray(lb[name]), np.asarray(cb[name])
            if la.dtype != ca.dtype or la.tobytes() != ca.tobytes():
                raise AssertionError(
                    f"lifted_chain: lifted and callback outputs differ "
                    f"in column {name!r} — the bit-identity contract "
                    "is broken"
                )
    return lifted_s, callback_s, steady_compiles


def _bench_multijoin(n_rows: int = 1_000_000, iters: int = 4,
                     num_blocks: int = 4, n_g1: int = 512,
                     n_g2: int = 64):
    """1M-row star-schema map→join→join→aggregate (ISSUE 14): the
    adaptive optimizer pushes the partial aggregate BELOW both dims
    (each inner join degenerates to a whole-group semi-join filter —
    1M rows never match-expand through either join) and the stats
    sidecar makes the second execution a counted ``reoptimized``
    lowering. ``TFTPU_REOPT=0`` re-runs the identical pipeline on the
    PR 7 static path (joins execute, aggregate above), and
    ``TFTPU_FUSION=0`` replays it per-stage. Values are int32 so every
    rewrite is reassoc-safe: all three modes must be BIT-IDENTICAL
    (asserted here — a mismatch raises). Returns
    (opt_wall_s, static_wall_s, unfused_wall_s, pushdowns)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.config import get_config
    from tensorframes_tpu.observability.metrics import REGISTRY

    rng = np.random.default_rng(0)
    fact = tfs.frame_from_arrays(
        {
            "k1": rng.integers(0, n_g1, n_rows).astype(np.int32),
            "k2": rng.integers(0, n_g2, n_rows).astype(np.int32),
            "x": (np.arange(n_rows) % 16).astype(np.int32),
            # dead fact columns incl. an embedding-style wide one:
            # pushdown + needed-columns pruning must keep them out of
            # the map dispatches, the joins, and the reduction
            "a": np.arange(n_rows, dtype=np.float32),
            "e": np.ones((n_rows, 8), np.float32),
        },
        num_blocks=num_blocks,
    )
    # star dims: unique keys (the m=1 condition); dim2 matches half the
    # key space so the inner join genuinely filters groups
    dim1 = tfs.frame_from_arrays(
        {"k1": np.arange(n_g1, dtype=np.int32),
         "w1": np.arange(n_g1, dtype=np.int32),
         "tag1": np.ones(n_g1, np.float32)},  # dead build column
        num_blocks=1,
    )
    dim2 = tfs.frame_from_arrays(
        {"k2": np.arange(0, n_g2, 2, dtype=np.int32),
         "w2": np.arange(n_g2 // 2, dtype=np.int32),
         "tag2": np.ones(n_g2 // 2, np.float32)},
        num_blocks=1,
    )
    p1 = tfs.compile_program(lambda x: {"y": x * 2 + 1}, fact)
    p2 = tfs.compile_program(
        lambda y: {"z": y * y}, tfs.map_blocks(p1, fact)
    )
    j0 = (
        tfs.map_blocks(p2, tfs.map_blocks(p1, fact))
        .join(dim1, on="k1").join(dim2, on="k2")
    )
    with tfs.with_graph():
        z_in = tfs.block(j0, "z", tf_name="z_input")
        fz = tfs.reduce_sum(z_in, axis=0, name="z")
        agg_program = tfs.compile_program([fz], j0, reduce_mode="blocks")

    def run_once():
        f2 = tfs.map_blocks(p2, tfs.map_blocks(p1, fact))
        j = f2.join(dim1, on="k1").join(dim2, on="k2")
        out = tfs.aggregate(agg_program, j.group_by("k1", "k2"))
        return out.blocks()

    def wall(iters_):
        run_once()  # warm jit caches (and the stats record) untimed
        t0 = time.perf_counter()
        for _ in range(iters_):
            run_once()
        return (time.perf_counter() - t0) / iters_

    def _counter_value(decision):
        for d in REGISTRY.snapshot():
            if (
                d["name"] == "tftpu_plan_cost_decisions_total"
                and d["labels"].get("decision") == decision
            ):
                return float(d.get("value", 0.0))
        return 0.0

    was_fusion = get_config().plan_fusion
    was_reopt = get_config().plan_reopt
    try:
        tfs.configure(plan_fusion=True, plan_reopt=True)
        p0 = _counter_value("pushdown_aggregate")
        opt_s = wall(iters)
        pushdowns = int(_counter_value("pushdown_aggregate") - p0)
        opt_rows = run_once()
        flips = _flip_smoke(run_once, opt_rows, _counter_value)
        tfs.configure(plan_reopt=False)  # the TFTPU_REOPT=0 path
        static_s = wall(iters)
        static_rows = run_once()
        tfs.configure(plan_fusion=False)  # the TFTPU_FUSION=0 path
        unfused_s = wall(iters)
        unfused_rows = run_once()
    finally:
        tfs.configure(plan_fusion=was_fusion, plan_reopt=was_reopt)
    for label, rows in (("static", static_rows), ("unfused", unfused_rows)):
        if len(opt_rows) != len(rows):
            raise AssertionError(
                f"multijoin: optimizer produced {len(opt_rows)} "
                f"block(s), {label} {len(rows)} — the bit-identical "
                "contract is broken"
            )
        for fb, ub in zip(opt_rows, rows):
            if set(fb) != set(ub):
                raise AssertionError(
                    f"multijoin: optimizer columns {sorted(fb)} != "
                    f"{label} {sorted(ub)} — the bit-identical "
                    "contract is broken"
                )
            for name in fb:
                if not np.array_equal(
                    np.asarray(fb[name]), np.asarray(ub[name])
                ):
                    raise AssertionError(
                        "multijoin: optimizer and "
                        f"{label} outputs differ in column {name!r} — "
                        "the bit-identical contract is broken"
                    )
    if pushdowns <= 0:
        raise AssertionError(
            "multijoin: the optimizer never recorded a "
            "pushdown_aggregate decision — the adaptive path did not "
            "engage"
        )
    return opt_s, static_s, unfused_s, pushdowns, flips


def _flip_smoke(run_once, baseline_rows, counter_value) -> int:
    """Latency-driven decision-flip smoke (ISSUE 17), hard-gated:
    invert the observed fuse-vs-per-stage walls in the stats sidecar
    and require the NEXT execution to (a) choose the per-stage replay
    (``split_single_stage`` decisions recorded where ``fuse`` was), (b)
    count each flip as ``reoptimized``, and (c) stay bit-identical —
    the replay IS the TFTPU_FUSION=0 path. The injected walls are
    dropped afterwards so no later leg (or a sidecar-sharing real run)
    acts on synthetic evidence."""
    from tensorframes_tpu.plan import stats as _pstats
    from tensorframes_tpu.plan.stats import STRATEGY_WALL_MIN_SAMPLES

    walls = _pstats.strategy_walls("fuse")
    if not walls.get("fuse", {}).get("n"):
        raise AssertionError(
            "multijoin flip: the warm executions never observed a "
            "'fuse' strategy wall — the latency feedback loop is dark"
        )
    try:
        # invert: the fused dispatch "measured" 10s, the per-stage
        # replay 0.1ms — enough samples on both sides to clear the
        # flip's hysteresis margin
        for _ in range(max(2, STRATEGY_WALL_MIN_SAMPLES) * 2):
            _pstats.observe_strategy_wall("fuse", "fuse", 10.0)
            _pstats.observe_strategy_wall("fuse", "split_single_stage",
                                          1e-4)
        s0 = counter_value("split_single_stage")
        r0 = counter_value("reoptimized")
        flip_rows = run_once()
        flipped = int(counter_value("split_single_stage") - s0)
        reopts = int(counter_value("reoptimized") - r0)
    finally:
        _pstats.reset_strategy_walls()
    if flipped <= 0:
        raise AssertionError(
            "multijoin flip: execution after inverted walls still "
            "chose the fused dispatch — the latency-driven decision "
            "never engaged"
        )
    if reopts <= 0:
        raise AssertionError(
            "multijoin flip: the flip engaged but was not counted as "
            "a reoptimized decision"
        )
    if len(flip_rows) != len(baseline_rows):
        raise AssertionError(
            "multijoin flip: block count changed across the flip — "
            "the bit-identical contract is broken"
        )
    for fb, bb in zip(flip_rows, baseline_rows):
        for name in fb:
            if not np.array_equal(np.asarray(fb[name]),
                                  np.asarray(bb[name])):
                raise AssertionError(
                    "multijoin flip: outputs differ in column "
                    f"{name!r} across the flip — the bit-identical "
                    "contract is broken"
                )
    return reopts


def _bench_inception(n_rows: int = 512, iters: int = 4, channel_scale: float = 1.0,
                     int8: bool = False, sweep: Sequence[int] = (),
                     side: int = 299, compute_dtype: str = "bfloat16",
                     mfu_label: str = None):
    """Inception-v3 batch inference via map_blocks (BASELINE config 4) —
    the headline metric named in BASELINE.json. ``sweep`` (TPU runs)
    times additional per-call batch sizes at 1 iter each and reports
    them as ``# sweep |`` rows; the headline batch keeps full iters so
    the published number is both the tuned-batch AND reproducible.
    ``side``/``compute_dtype`` exist for the like-for-like
    native-vs-frozen pair (VERDICT r4 #4)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.models import inception as inc

    cfg = inc.inception_v3(
        channel_scale=channel_scale, image_size=side,
        compute_dtype=compute_dtype,
    )
    params = inc.init_params(cfg, seed=0)
    if int8:
        params = inc.quantize_params(params)
    prog = inc.scoring_program(cfg, params)

    def time_batch(rows: int, n_iters: int):
        images = inc.synthetic_images(cfg, rows, seed=0)
        frame = tfs.frame_from_arrays(
            {"images": images}, num_blocks=1
        ).to_device()
        program = tfs.compile_program(lambda images: prog(images), frame)

        def run_once():
            out = tfs.map_blocks(program, frame)
            [b] = out.blocks()
            _sync(b["label"])

        rps = _time_rows_per_sec(run_once, rows, n_iters)
        return rps, program

    best_rows, best_rps = n_rows, None
    for rows in sweep:
        if rows == n_rows:
            continue
        srps, _ = time_batch(rows, 1)
        print(f"# sweep | inception_v3 batch={rows} rows_per_sec={srps:.1f}")
        if best_rps is None or srps > best_rps:
            best_rows, best_rps = rows, srps

    final_rows = n_rows
    rps, program = time_batch(n_rows, iters)
    if sweep:
        print(f"# sweep | inception_v3 batch={n_rows} rows_per_sec={rps:.1f}")
    if best_rps is not None and best_rps > rps:
        # a swept batch beat the default at 1 iter: re-time it at full
        # iters, but publish it only if it STILL beats the default's
        # full-iters number (a lucky 1-iter sample must not downgrade
        # the headline)
        re_rps, re_program = time_batch(best_rows, iters)
        if re_rps > rps:
            final_rows, rps, program = best_rows, re_rps, re_program
        print(
            f"# sweep | inception_v3 headline batch={final_rows} "
            f"rows_per_sec={rps:.1f}"
        )

    _record_mfu(
        mfu_label or f"bench.inception_v3{'_int8' if int8 else ''}",
        program, rps, final_rows,
    )
    return rps


_FROZEN_CACHE: dict = {}


def _frozen_inception_bytes(side: int) -> bytes:
    """Freeze a random-weight keras InceptionV3 once per image size —
    model build + freeze dominates CPU wall-clock, and the f32 and int8
    benches lower the same bytes."""
    if side not in _FROZEN_CACHE:
        import tensorflow as tf  # fixture construction only
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        tf.keras.utils.set_random_seed(0)
        model = tf.keras.applications.InceptionV3(
            weights=None, input_shape=(side, side, 3)
        )
        fn = tf.function(lambda x: model(x, training=False))
        cf = fn.get_concrete_function(
            tf.TensorSpec([None, side, side, 3], tf.float32)
        )
        _FROZEN_CACHE[side] = convert_variables_to_constants_v2(
            cf
        ).graph.as_graph_def().SerializeToString()
    return _FROZEN_CACHE[side]


def _bench_inception_frozen(n_rows: int = 64, iters: int = 3,
                            side: int = 299, int8: bool = False,
                            compute_dtype=None):
    """BASELINE config 4 in its literal form: a frozen TF GraphDef of
    Inception-v3 scored over an image frame — decoded by the bundled
    clean-room importer, lowered to jax, executed via map_blocks.
    Requires tensorflow only to BUILD the frozen fixture (random
    weights, no downloads); scoring itself is TF-free."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

    data = _frozen_inception_bytes(side)
    prog = program_from_graphdef(
        parse_graphdef(data), relax_lead_dim=True, quantize_weights=int8,
        compute_dtype=compute_dtype,
    )
    [inp] = prog.inputs
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n_rows, side, side, 3)).astype(np.float32)
    frame = tfs.frame_from_arrays({inp.name: x}, num_blocks=1).to_device()
    program = tfs.compile_program(prog, frame)

    def run_once():
        out = tfs.map_blocks(program, frame)
        [b] = out.blocks()
        _sync(b[prog.fetch_order[0]])

    rps = _time_rows_per_sec(run_once, n_rows, iters)
    variant = ("_int8" if int8 else "") + ("_bf16" if compute_dtype else "")
    _record_mfu(
        f"bench.inception_v3_frozen{variant}",
        program, rps, n_rows,
    )
    if compute_dtype is None:
        # XLA-cost-model absolute traffic: the number that makes the int8
        # weight-quantization claim checkable without hardware counters
        # (VERDICT r2 #7) — weights dominate at this tiny probe batch.
        # (bf16-variant runs must not clobber the f32 entry.)
        try:
            _FROZEN_BYTES["int8" if int8 else "f32"] = (
                program.total_bytes_accessed(probe=8)
            )
        except Exception as e:
            print(
                f"# {'int8' if int8 else 'f32'} bytes accounting "
                f"unavailable: {e}"
            )
    return rps


_FROZEN_BYTES: dict = {}


def _bench_bert_embed(n_rows: int = 1024, seq: int = 128, iters: int = 3,
                      full_scale: bool = True):
    """BERT-base embedding extraction via map_rows (BASELINE config 5)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.models import transformer as tr

    cfg = tr.bert_base() if full_scale else tr.tiny()
    seq = min(seq, cfg.max_seq_len)
    params = tr.init_params(cfg, seed=0)
    tokens, _ = tr.synthetic_batch(cfg, n_rows, seq, seed=0)
    frame = tfs.frame_from_arrays({"tokens": tokens}, num_blocks=1).to_device()
    prog = tr.embed_row_program(cfg, params)
    program = tfs.compile_program(
        lambda tokens: prog(tokens), frame, block=False
    )

    def run_once():
        out = tfs.map_rows(program, frame)
        [b] = out.blocks()
        _sync(b["embedding"])

    rps = _time_rows_per_sec(run_once, n_rows, iters)
    _record_mfu("bench.bert_embed", program, rps, n_rows)
    return rps


def _bench_attention(batch: int = 4, heads: int = 8, seq: int = 4096,
                     head_dim: int = 128, iters: int = 3):
    """Long-context attention throughput (tokens/sec) for the flash
    (pallas on TPU, blockwise fallback elsewhere) kernel."""
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops import attention as att

    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.standard_normal((batch, heads, seq, head_dim)), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()
    fn = jax.jit(lambda q, k, v: att.flash_attention(q, k, v, causal=True))

    def run_once():
        _sync(fn(q, k, v))

    try:
        return _time_rows_per_sec(run_once, batch * seq, iters)
    except Exception as e:
        # pallas flash failed at kernel-compile time (Mosaic/toolchain);
        # measure the pure-XLA blockwise kernel instead of dying — marked
        # so the recorded number is never mistaken for the flash kernel's
        print(
            f"# flash_attention_fallback=blockwise ({type(e).__name__}: "
            f"{str(e).splitlines()[0][:120]})"
        )
        fb = jax.jit(
            lambda q, k, v: att.blockwise_attention(q, k, v, causal=True)
        )

        def run_fb():
            _sync(fb(q, k, v))

        return _time_rows_per_sec(run_fb, batch * seq, iters)


def _bench_generate(batch: int = 8, prompt: int = 32, new: int = 64,
                    iters: int = 3, full_scale: bool = True,
                    int8: bool = False, sweep: Sequence[int] = ()):
    """Causal-LM decode throughput (generated tokens/sec): KV-cache
    lax.scan decode as ONE jitted XLA program (models/generation.py).
    ``int8=True`` measures the weight-only quantized tree (decode is
    weight-HBM-bound, so this is where int8 pays). ``sweep`` (TPU)
    times alternate batch sizes at 1 iter each — per-step weight
    traffic amortizes across the batch, so tok/s should scale well
    past batch 8 until the cache term dominates; the headline batch
    stays fixed for cross-round comparability."""
    import jax

    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr

    cfg = gen.gpt_small() if full_scale else gen.gpt_tiny()
    prompt = min(prompt, cfg.max_seq_len - new - 1)
    params = tr.init_params(cfg, seed=0)
    if int8:
        params = tr.quantize_params(params)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (batch, prompt)).astype(np.int32)
    # params as runtime ARGUMENTS, not closure constants: closure capture
    # embeds the full weight tree in the HLO payload (gpt-small f32 is
    # ~0.5 GB of literals — it crashed the remote-compile relay; it also
    # bloats any AOT artifact), device_put once and pass through.
    # int8 runs also quantize the KV cache — decode's HBM traffic that
    # GROWS with sequence, the config where int8 must pay (VERDICT r3 #4)
    d_params = jax.device_put(params)
    fn = jax.jit(
        lambda prms, p: gen.generate(cfg, prms, p, new, kv_quant=int8)
    )

    def run_once():
        _sync(fn(d_params, prompts))

    for b2 in sweep:
        if b2 == batch:
            continue
        p2 = rng.integers(0, cfg.vocab_size, (b2, prompt)).astype(np.int32)
        tps2 = _time_rows_per_sec(
            lambda: _sync(fn(d_params, p2)), b2 * new, 1
        )
        print(
            f"# sweep | decode{'_int8kv' if int8 else ''} batch={b2} "
            f"tokens_per_sec={tps2:.0f}"
        )
    return _time_rows_per_sec(run_once, batch * new, iters)


def _hist_delta_quantiles(h, before, qs=(0.5, 0.99)):
    """Quantiles of ONLY the observations since ``before`` (a
    ``Histogram.cumulative()`` snapshot) — the serving bench's timed
    window must not inherit warm-phase latencies."""
    from tensorframes_tpu.observability.metrics import (
        quantile_from_cumulative,
    )

    after = h.cumulative()
    delta = [(b, ca - cb) for (b, ca), (_, cb) in zip(after, before)]
    count = delta[-1][1]
    return {
        f"p{int(q * 100)}": quantile_from_cumulative(delta, count, q)
        for q in qs
    }


def _bench_serving(duration_s: float = 1.5, rate_rps: float = 300.0,
                   width: int = 16, max_batch_rows: int = 64,
                   rows_choices: Sequence[int] = (1, 2, 4)):
    """Open-loop synthetic serving load (ISSUE 9 acceptance): request
    arrivals follow a FIXED schedule — the generator never waits for
    completions, so queueing delay stays visible (a closed-loop harness
    self-throttles and hides overload). A warmed Server coalesces the
    1/2/4-row requests into bucket-ladder flushes; reported: sustained
    rows/sec over the window, request-latency p50/p99 from the serving
    histogram (timed window only), the steady-state XLA compile count
    (MUST be 0 — every flush hits an AOT/warmup bucket), and shed
    count (open loop may legitimately shed under overload)."""
    import jax.numpy as jnp

    import tensorframes_tpu as tfs
    from tensorframes_tpu.ops.executor import _JIT_MISSES
    from tensorframes_tpu.serving import RejectedError
    from tensorframes_tpu.serving import metrics as smet

    rng = np.random.default_rng(0)
    w = (rng.standard_normal((width, width)) / np.sqrt(width)).astype(
        np.float32
    )
    schema = tfs.Schema([
        tfs.ColumnInfo(
            "x", tfs.dtypes.float32, tfs.Shape((tfs.Unknown, width))
        )
    ])
    holder = type("S", (), {"schema": schema})()
    prog = tfs.compile_program(
        lambda x: {"y": jnp.tanh(x @ w)}, holder, block=False
    )
    srv = tfs.Server(tfs.ServingConfig(
        max_batch_rows=max_batch_rows, max_latency_s=0.002,
        max_queue_rows=64 * max_batch_rows,
    ))
    srv.register("score", prog)
    srv.start()  # warms the whole bucket ladder (AOT store if armed)
    try:
        for r in sorted(set(rows_choices)):  # pipeline warm, discarded
            srv.call(
                "score", {"x": np.zeros((r, width), np.float32)},
                timeout=60,
            )
        miss0 = _JIT_MISSES.value
        lat_before = smet.REQUEST_LATENCY.cumulative()
        n_req = max(1, int(duration_s * rate_rps))
        period = 1.0 / rate_rps
        futs = []
        shed = 0
        t0 = time.perf_counter()
        for i in range(n_req):
            target = t0 + i * period
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            rows = int(rows_choices[i % len(rows_choices)])
            try:
                futs.append(srv.submit(
                    "score",
                    {"x": np.full((rows, width), float(i % 7),
                                  np.float32)},
                ))
            except RejectedError:
                shed += 1
        for f in futs:
            f.result(120)
        elapsed = time.perf_counter() - t0
        q = _hist_delta_quantiles(smet.REQUEST_LATENCY, lat_before)
        return {
            "rows_per_sec": sum(f.rows for f in futs) / elapsed,
            "p50_s": q["p50"] or 0.0,
            "p99_s": q["p99"] or 0.0,
            "steady_state_compiles": int(_JIT_MISSES.value - miss0),
            "requests": len(futs),
            "shed": shed,
        }
    finally:
        srv.stop(drain=True, timeout=120)


def _bench_serving_decode(n_requests: int = 6, new_tokens: int = 8,
                          prompt_len: int = 16):
    """Continuous-batching decode — the ROADMAP #1 seed workload: each
    request is ONE prompt row; the batcher coalesces concurrent decode
    requests into a single vmapped gpt_tiny KV-cache decode per flush,
    with the int8-quantized KV cache in HBM (the config where int8
    pays — decode is weight/cache-HBM-bound). Generated tokens/sec over
    the whole submit→drain window, CPU-modest sizes everywhere."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr

    cfg = gen.gpt_tiny()
    params = tr.quantize_params(tr.init_params(cfg, seed=0))

    def decode(prompt):
        toks = gen.generate(
            cfg, params, prompt[None, :], new_tokens, kv_quant=True
        )
        return {"tokens": toks[0]}

    schema = tfs.Schema([
        tfs.ColumnInfo(
            "prompt", tfs.dtypes.int32,
            tfs.Shape((tfs.Unknown, prompt_len)),
        )
    ])
    holder = type("S", (), {"schema": schema})()
    prog = tfs.compile_program(decode, holder, block=False)
    # max_batch_rows = min_bucket: ONE warmed decode executable serves
    # every flush (decode compiles are the expensive kind)
    srv = tfs.Server(tfs.ServingConfig(
        max_batch_rows=8, max_latency_s=0.005,
    ))
    srv.register("decode", prog)
    srv.start()
    try:
        rng = np.random.default_rng(0)
        prompts = [
            rng.integers(0, cfg.vocab_size, (1, prompt_len)).astype(
                np.int32
            )
            for _ in range(n_requests)
        ]
        t0 = time.perf_counter()
        futs = [srv.submit("decode", {"prompt": p}) for p in prompts]
        outs = [f.result(300) for f in futs]
        dt = time.perf_counter() - t0
        for o in outs:
            assert o["tokens"].shape == (1, new_tokens)
        return n_requests * new_tokens / dt
    finally:
        srv.stop(drain=True, timeout=120)


def _bench_decode_engine(n_requests: int = 12, new_tokens: int = 8,
                         max_prompt_len: int = 16, max_slots: int = 8,
                         rate_rps: float = 60.0):
    """Open-loop ITERATIVE decode (ISSUE 11 acceptance): unlike
    ``_bench_serving_decode`` (whole sequences coalesced per flush),
    this drives the token-level engine — mixed-length prompts arrive on
    a fixed schedule and join/leave the running batch every step over
    the paged int8 KV pool. Reported: generated tokens/sec over the
    window, time-to-first-token p50/p99 (timed window only), and the
    steady-state XLA compile count. Hard gates (raise, so the smoke
    exits nonzero): every request completes, a warmed engine performs
    ZERO steady-state compiles, and each request's batched output is
    BIT-IDENTICAL to the same prompt decoded solo afterwards."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr
    from tensorframes_tpu.ops.executor import _JIT_MISSES
    from tensorframes_tpu.serving import metrics as smet

    cfg = gen.gpt_tiny()
    params = tr.quantize_params(tr.init_params(cfg, seed=0))
    srv = tfs.Server(tfs.ServingConfig(max_batch_rows=8))
    srv.register_decode(
        "decode", cfg, params,
        tfs.DecodeConfig(
            max_slots=max_slots, page_size=8,
            max_prompt_len=max_prompt_len, max_new_tokens=new_tokens,
        ),
    )
    srv.start()
    try:
        rng = np.random.default_rng(0)
        prompts = [
            rng.integers(
                0, cfg.vocab_size,
                (int(rng.integers(4, max_prompt_len + 1)),),
            ).astype(np.int32)
            for _ in range(n_requests)
        ]
        # pipeline warm through every phase, discarded
        srv.call("decode", {"prompt": prompts[0]}, timeout=600)
        miss0 = _JIT_MISSES.value
        pre0 = smet.DECODE_PREEMPTIONS.value
        ttft_before = smet.DECODE_TTFT.cumulative()
        period = 1.0 / rate_rps
        futs = []
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            target = t0 + i * period
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            futs.append(srv.submit("decode", {"prompt": p}))
        outs = [f.result(600)["tokens"] for f in futs]
        elapsed = time.perf_counter() - t0
        steady = int(_JIT_MISSES.value - miss0)
        assert len(outs) == n_requests, (
            f"lost requests: {len(outs)}/{n_requests} completed"
        )
        assert steady == 0, (
            f"warmed decode engine compiled {steady}x in steady state"
        )
        # TTFT quantiles over the open-loop window ONLY — the solo
        # gate calls below also observe DECODE_TTFT and would dilute
        # the gated p50/p99 with idle-queue joins
        q = _hist_delta_quantiles(smet.DECODE_TTFT, ttft_before)
        # bit-identity hard gate: solo decode of each prompt through
        # the SAME warmed engine must reproduce the batched output
        for i, p in enumerate(prompts):
            solo = srv.call("decode", {"prompt": p}, timeout=600)
            assert np.array_equal(outs[i], solo["tokens"]), (
                f"request {i}: batched iterative decode != solo decode "
                "(bit-identity gate)"
            )
        tokens = sum(int(o.shape[1]) for o in outs)
        return {
            "tokens_per_sec": tokens / elapsed,
            "ttft_p50_s": q["p50"] or 0.0,
            "ttft_p99_s": q["p99"] or 0.0,
            "steady_state_compiles": steady,
            "requests": n_requests,
            "completed": len(outs),
            # window delta; structurally 0 here (the auto-sized pool
            # holds every slot's horizon) — preemption pressure is
            # exercised by tests, this bench measures clean throughput
            "preemptions": int(smet.DECODE_PREEMPTIONS.value - pre0),
        }
    finally:
        srv.stop(drain=True, timeout=300)


def _bench_kv_hierarchy(n_samples: int = 12, new_tokens: int = 8):
    """KV memory hierarchy (ISSUE 19): content-addressed prefix-cache
    TTFT against cold prefill, and per-sequence host-swap resume on an
    undersized pool. TTFT samples are direct wall-clock of 1-token
    requests on an idle warmed engine (submit -> first token), not
    histogram-bucket quantiles, so the p50 comparison is exact. Hard
    gates (raise, so the smoke exits nonzero):

    * prefix-hit TTFT p50 strictly below cold-prefill TTFT p50, with
      hit outputs BIT-IDENTICAL to the dense-cache ``gen.generate``
      oracle (whole-prompt copy-on-extend AND shared-prefix+fresh-
      suffix both checked);
    * the undersized-pool leg sustains every request through
      swap-resume (``swap_resumes > 0``, zero corruption fallbacks)
      with outputs bit-identical to the oracle;
    * zero steady-state XLA compiles on both warmed engines."""
    import statistics

    import tensorframes_tpu as tfs
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr
    from tensorframes_tpu.ops.executor import _JIT_MISSES
    from tensorframes_tpu.serving import metrics as smet

    cfg = gen.gpt_tiny()
    params = tr.quantize_params(tr.init_params(cfg, seed=0))

    def oracle(p):
        return np.asarray(
            gen.generate(cfg, params, p[None], new_tokens, kv_quant=True)
        )

    rng = np.random.default_rng(11)
    plen, ps = 40, 8

    def fresh_prompt(n=plen):
        return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)

    # -- leg 1: prefix cache, cold vs hit TTFT --------------------------
    srv = tfs.Server(tfs.ServingConfig(max_batch_rows=8))
    eng = srv.register_decode(
        "prefix", cfg, params,
        tfs.DecodeConfig(
            max_slots=4, page_size=ps, max_prompt_len=plen,
            max_new_tokens=new_tokens, prefix_cache=True,
            # roomy pool: every cold request publishes its pages too,
            # and LRU reclaim under pressure would evict the shared
            # chain mid-leg — the TTFT comparison wants deterministic
            # hits, not cache-sizing noise
            num_pages=128,
        ),
    )
    srv.start()
    try:
        shared = fresh_prompt()
        srv.call("prefix", {"prompt": shared}, timeout=600)  # publishes
        miss0 = _JIT_MISSES.value

        def timed(p):
            t0 = time.perf_counter()
            srv.call(
                "prefix", {"prompt": p, "max_new_tokens": 1}, timeout=600
            )
            return time.perf_counter() - t0

        def suffix_prompt():
            # shared first 4 pages, fresh final page: a suffix-only hit
            return np.concatenate(
                [shared[:plen - ps], fresh_prompt(ps)]
            ).astype(np.int32)

        cold_ts = [timed(fresh_prompt()) for _ in range(n_samples)]
        h0 = smet.PREFIX_HITS.value
        hit_ts = [timed(suffix_prompt()) for _ in range(n_samples)]
        hits = int(smet.PREFIX_HITS.value - h0)
        # bit-identity: both hit shapes against the dense oracle
        out = srv.call("prefix", {"prompt": shared}, timeout=600)
        assert np.array_equal(out["tokens"], oracle(shared)), (
            "prefix-cache exact-repeat output != dense oracle "
            "(bit-identity gate)"
        )
        sfx = suffix_prompt()
        out = srv.call("prefix", {"prompt": sfx}, timeout=600)
        assert np.array_equal(out["tokens"], oracle(sfx)), (
            "prefix-cache suffix-hit output != dense oracle "
            "(bit-identity gate)"
        )
        steady = int(_JIT_MISSES.value - miss0)
        shared_pages = int(eng.counters()["shared_pages"])
    finally:
        srv.stop(drain=True, timeout=300)
    assert hits >= n_samples, (
        f"prefix cache hit only {hits}x over {n_samples} shared-prefix "
        "requests"
    )
    assert steady == 0, (
        f"warmed prefix-cache engine compiled {steady}x in steady state"
    )
    cold_p50 = statistics.median(cold_ts)
    hit_p50 = statistics.median(hit_ts)
    assert hit_p50 < cold_p50, (
        f"prefix-hit TTFT p50 {hit_p50:.6f}s not below cold-prefill "
        f"p50 {cold_p50:.6f}s"
    )

    # -- leg 2: host-swap resume on an undersized pool ------------------
    srv2 = tfs.Server(tfs.ServingConfig(max_batch_rows=8))
    srv2.register_decode(
        "swap", cfg, params,
        tfs.DecodeConfig(
            max_slots=4, page_size=ps, num_pages=1 + 2 * 3,
            max_prompt_len=16, max_new_tokens=new_tokens, kv_swap=True,
        ),
    )
    srv2.start()
    try:
        srv2.call("swap", {"prompt": fresh_prompt(9)}, timeout=600)
        miss0 = _JIT_MISSES.value
        o0 = smet.KVSWAP_OUTS.value
        r0 = smet.KVSWAP_RESUMES.value
        f0 = smet.KVSWAP_FALLBACKS.value
        prompts = [
            fresh_prompt(int(rng.integers(9, 17))) for _ in range(8)
        ]
        futs = [srv2.submit("swap", {"prompt": p}) for p in prompts]
        outs = [f.result(600)["tokens"] for f in futs]
        swap_outs = int(smet.KVSWAP_OUTS.value - o0)
        swap_resumes = int(smet.KVSWAP_RESUMES.value - r0)
        swap_fallbacks = int(smet.KVSWAP_FALLBACKS.value - f0)
        steady2 = int(_JIT_MISSES.value - miss0)
        for i, (p, o) in enumerate(zip(prompts, outs)):
            assert np.array_equal(o, oracle(p)), (
                f"swap-resume leg request {i}: output != dense oracle "
                "(bit-identity gate)"
            )
    finally:
        srv2.stop(drain=True, timeout=600)
    assert swap_resumes > 0, (
        "undersized pool never swap-resumed: the leg did not exercise "
        "the host-swap tier"
    )
    assert swap_fallbacks == 0, (
        f"{swap_fallbacks} swap segments failed CRC on a healthy store"
    )
    assert steady2 == 0, (
        f"warmed kv_swap engine compiled {steady2}x in steady state"
    )
    return {
        "prefix_hit_ttft_p50_s": hit_p50,
        "cold_ttft_p50_s": cold_p50,
        "prefix_hits": hits,
        "shared_pages": shared_pages,
        "swap_outs": swap_outs,
        "swap_resumes": swap_resumes,
        "swap_fallbacks": swap_fallbacks,
        "steady_state_compiles": steady + steady2,
    }


def _registered_query_build(f):
    """The bench's registered pipeline (module-level so the FUSION=0
    oracle subprocess rebuilds the IDENTICAL chain): dtype-preserving
    map → keyed sum/min/max aggregate, all int64 so the incremental
    fold is exact."""
    import tensorframes_tpu as tfs

    f1 = tfs.map_blocks(
        lambda v: {"ysum": v * 3 + 1, "ymin": v * 3 + 1,
                   "ymax": v * 3 + 1},
        f,
    )
    with tfs.with_graph():
        s_in = tfs.block(f1, "ysum", tf_name="ysum_input")
        mn_in = tfs.block(f1, "ymin", tf_name="ymin_input")
        mx_in = tfs.block(f1, "ymax", tf_name="ymax_input")
        return tfs.aggregate(
            [
                tfs.reduce_sum(s_in, axis=0, name="ysum"),
                tfs.reduce_min(mn_in, axis=0, name="ymin"),
                tfs.reduce_max(mx_in, axis=0, name="ymax"),
            ],
            f1.group_by("k"),
        )


def _registered_query_oracle(data_dir: str, out_npz: str) -> None:
    """Subprocess half of the bench's bit-identity gate: run under
    TFTPU_FUSION=0 (plan recording off → the endpoint degrades to full
    eager recompute), key-sort the table, save it for the parent to
    compare dtype+bytes. Sorting happens HERE because eager mode does
    not canonicalize output order."""
    from tensorframes_tpu.serving import QueryEndpoint, QuerySource

    q = QueryEndpoint(
        "oracle", QuerySource(path=data_dir, kind="csv"),
        _registered_query_build,
    )
    table = q.execute()
    order = np.argsort(table["k"], kind="stable")
    np.savez(out_npz, **{k: np.asarray(v)[order] for k, v in table.items()})


def _bench_registered_query(n_chunks: int = 56,
                            rows_per_chunk: int = 80_000,
                            check_fusion0: bool = True):
    """Registered query endpoint (ISSUE 20): plan-fingerprint result
    caching + incremental aggregate maintenance over a growing CSV scan
    directory. Equal-row chunks so every per-chunk execution shares ONE
    compiled shape. Measures: first (cold) execution, warm-repeat p50
    (the cache-hit path), steady-state compiles across the repeats, the
    incremental refresh after appending one chunk, and the full-
    recompute wall over the same post-append table — plus bit-identity
    of both answers against a TFTPU_FUSION=0 subprocess."""
    import os
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    import tensorframes_tpu as tfs
    from tensorframes_tpu.config import get_config
    from tensorframes_tpu.ops.executor import _JIT_MISSES
    from tensorframes_tpu.serving import QueryEndpoint, QuerySource

    tmp = tempfile.mkdtemp(prefix="tftpu_regq_")
    prev_cache = get_config().compilation_cache_dir
    rng = np.random.default_rng(0)
    try:
        data = os.path.join(tmp, "data")
        os.makedirs(data)
        tfs.configure(
            compilation_cache_dir=os.path.join(tmp, "cache")
        )

        def write_chunk(i):
            ks = rng.integers(0, 64, size=rows_per_chunk)
            vs = rng.integers(-1000, 1000, size=rows_per_chunk)
            with open(os.path.join(data, f"part-{i:05d}.csv"), "w") as fh:
                fh.write("k,v\n")
                fh.write("\n".join(f"{k},{v}" for k, v in zip(ks, vs)))
                fh.write("\n")

        for i in range(n_chunks):
            write_chunk(i)
        q = QueryEndpoint(
            "bench", QuerySource(path=data, kind="csv"),
            _registered_query_build,
        )
        assert q.cache_stats()["incremental"], (
            "int64 sum/min/max must be fold-eligible"
        )
        t0 = time.perf_counter()
        q.execute()
        first_s = time.perf_counter() - t0
        # warm repeats: p50 must be dominated by the cache lookup, with
        # ZERO compiles (hard gate) — hits never touch the executor
        miss0 = _JIT_MISSES.value
        reps = []
        for _ in range(20):
            t0 = time.perf_counter()
            q.execute()
            reps.append(time.perf_counter() - t0)
        steady = int(_JIT_MISSES.value - miss0)
        repeat_p50 = sorted(reps)[len(reps) // 2]
        hits = q.cache_stats()["hits"]
        assert hits >= 20, f"warm repeats missed the cache ({hits} hits)"
        # append ONE chunk: the refresh re-reads/re-executes only it
        write_chunk(n_chunks)
        ex0 = q.cache_stats()["chunks_executed"]
        t0 = time.perf_counter()
        table_inc = q.execute()
        refresh_s = time.perf_counter() - t0
        ex1 = q.cache_stats()["chunks_executed"]
        assert ex1 - ex0 == 1, (
            f"refresh re-executed {ex1 - ex0} chunks, not just the "
            "appended one"
        )
        # full recompute over the SAME post-append table, through the
        # endpoint's own oracle path (shared compiled executables;
        # warmed once so its one big-block compile stays out of the
        # timed wall — the comparison is steady-state work, not compile)
        manifest = q._manifest()
        q._execute_full(manifest)
        t0 = time.perf_counter()
        table_full = q._execute_full(manifest)
        full_s = time.perf_counter() - t0
        order = np.argsort(table_full["k"], kind="stable")
        for k in table_inc:
            a = np.asarray(table_inc[k])
            b = np.asarray(table_full[k])[order]
            assert a.dtype == b.dtype and np.array_equal(a, b), (
                f"incremental refresh diverged from full recompute on "
                f"column {k!r}"
            )
        fusion0_identical = None
        if check_fusion0:
            out_npz = os.path.join(tmp, "oracle.npz")
            env = dict(os.environ)
            env["TFTPU_FUSION"] = "0"
            env.setdefault("JAX_PLATFORMS", "cpu")
            env.pop("TFTPU_COMPILE_CACHE", None)
            subprocess.run(
                [_sys.executable, os.path.abspath(__file__),
                 "registered-query-oracle", data, out_npz],
                check=True, env=env, timeout=300,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            with np.load(out_npz) as ref:
                fusion0_identical = True
                for k in table_inc:
                    a = np.asarray(table_inc[k])
                    b = ref[k]
                    if a.dtype != b.dtype or not np.array_equal(a, b):
                        fusion0_identical = False
        cs = q.cache_stats()
        return {
            "chunks": n_chunks + 1,
            "rows": (n_chunks + 1) * rows_per_chunk,
            "first_execute_s": first_s,
            "repeat_p50_s": repeat_p50,
            "repeat_speedup": first_s / max(repeat_p50, 1e-9),
            "steady_state_compiles": steady,
            "refresh_s": refresh_s,
            "full_recompute_s": full_s,
            "refresh_frac": refresh_s / max(full_s, 1e-9),
            "fusion0_identical": fusion0_identical,
            "cache_hits": cs["hits"],
            "cache_invalidations": cs["invalidations"],
            "chunks_folded": cs["chunks_folded"],
            "chunks_executed": cs["chunks_executed"],
        }
    finally:
        tfs.configure(compilation_cache_dir=prev_cache)
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_read_csv(n_rows: int = 1_000_000):
    """CSV → frame ingestion (native C++ single-pass parser), s/call."""
    import os
    import tempfile

    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    a = rng.integers(0, 1000, n_rows)
    b = rng.standard_normal(n_rows)
    fd, path = tempfile.mkstemp(suffix=".csv")
    try:
        with os.fdopen(fd, "w") as f:
            f.write("a,b\n")
            f.write("\n".join(f"{x},{y:.6f}" for x, y in zip(a, b)))
        t0 = time.perf_counter()
        frame = tfs.read_csv(path)
        dt = time.perf_counter() - t0
        assert frame.num_rows == n_rows
        return dt
    finally:
        os.remove(path)


def _bench_convert(n_rows: int = 1_000_000):
    """Row→columnar convert + back (re-enabled equivalents of the
    reference's disabled µbenches, ConvertPerformanceSuite/
    ConvertBackPerformanceSuite): seconds per call over n scalar int rows,
    through the native C++ marshalling kernels when available."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu import native

    native.available()  # one-time g++ build stays out of the timer
    rows = [{"x": i} for i in range(n_rows)]
    t0 = time.perf_counter()
    frame = tfs.frame_from_rows(rows, num_blocks=1)
    convert_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = frame.collect()
    convertback_s = time.perf_counter() - t0
    assert out[-1]["x"] == n_rows - 1
    return convert_s, convertback_s


def _bench_aggregate_keyed(keys: "np.ndarray", n_rows: int,
                           device: bool = False):
    """Shared keyed-aggregate timing harness: reduce_sum over a float
    column grouped by ``keys``, warmup excluded. ``device=True`` shards
    the frame first, so the dense on-device plan runs with keys never
    leaving HBM (the host-frame variant pays a key+value upload per
    call — the dominant cost on relay-attached chips)."""
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    frame = tfs.frame_from_arrays(
        {"k": keys, "v": rng.standard_normal(n_rows).astype(np.float32)},
        num_blocks=1,
    )
    if device:
        frame = frame.to_device()
    with tfs.with_graph():
        v_input = tfs.block(frame, "v", tf_name="v_input")
        fetch = tfs.reduce_sum(v_input, axis=0, name="v")
        program = tfs.compile_program(fetch, frame, reduce_mode="blocks")

    def run_once():
        return tfs.aggregate(program, frame.group_by("k"))

    run_once().blocks()  # warmup/compile
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run_once().blocks()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _bench_aggregate(n_rows: int = 1_000_000, n_groups: int = 512):
    """Keyed aggregate wall-clock over the segment fast path (pallas
    one-hot MXU kernel on TPU, XLA segment scatter elsewhere)."""
    rng = np.random.default_rng(0)
    return _bench_aggregate_keyed(rng.integers(0, n_groups, n_rows), n_rows)


def _bench_aggregate_device(n_rows: int = 1_000_000, n_groups: int = 512):
    """Keyed aggregate over a DEVICE-sharded frame: the dense span plan
    (ops/device_agg.py) — per-shard one-hot reduce + one collective, no
    per-call host transfers."""
    rng = np.random.default_rng(0)
    return _bench_aggregate_keyed(
        rng.integers(0, n_groups, n_rows), n_rows, device=True
    )


def _bench_aggregate_strings(n_rows: int = 1_000_000, n_groups: int = 512):
    """Keyed aggregate with STRING keys: the host dictionary pass over
    the key column (ops/keys.py) now caches its encode ON THE FRAME
    (frame_group_ids), so steady-state repeated aggregates skip the 1M-
    object hash pass that made string keys 6-10x slower than numeric.
    The headline metric is the steady-state (dictionary-cached) wall;
    the ``# plan |`` line records the before/after — ``re-encode`` is
    the pre-cache behavior, measured by dropping the cache each call."""
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    ids = rng.integers(0, n_groups, n_rows)
    labels = np.array([f"key{i:04d}" for i in range(n_groups)], object)[ids]
    frame = tfs.frame_from_arrays(
        {"k": labels, "v": rng.standard_normal(n_rows).astype(np.float32)},
        num_blocks=1,
    )
    with tfs.with_graph():
        v_input = tfs.block(frame, "v", tf_name="v_input")
        fetch = tfs.reduce_sum(v_input, axis=0, name="v")
        program = tfs.compile_program(fetch, frame, reduce_mode="blocks")

    def run_once():
        tfs.aggregate(program, frame.group_by("k")).blocks()

    def timed():
        t0 = time.perf_counter()
        run_once()
        return time.perf_counter() - t0

    run_once()  # warmup/compile (also populates the key dictionary)
    warm_s = float(np.median([timed() for _ in range(3)]))
    cold_times = []
    for _ in range(3):
        frame._group_ids_cache = {}  # the pre-cache per-call encode
        cold_times.append(timed())
    cold_s = float(np.median(cold_times))
    print(
        "# plan | agg_strkey dict-cache warm={:.4f}s re-encode={:.4f}s "
        "speedup={:.1f}x".format(
            warm_s, cold_s, cold_s / max(warm_s, 1e-9)
        )
    )
    return warm_s


def _bench_segment_reduce(n_rows: int = 1_000_000, n_groups: int = 512,
                          gate_rows: int = 20_000):
    """Keyed segment reduce at 1M rows / 512 groups through the
    strategy dispatch (``_segment_reduce_best`` — host bincount, the
    pallas kernel, or the jitted scatter, whatever the cost model
    picks for this backend), median wall s/call. FIRST the ISSUE 12
    hard gate runs: the pallas kernel at a modest size must be
    bit-identical to its reference emulation, and to the XLA scatter
    on the exact op classes — a wrong kernel fails the bench run, not
    just a unit test."""
    import jax
    import jax.numpy as jnp
    from tensorframes_tpu.kernels import segment_reduce as ksr
    from tensorframes_tpu.ops.verbs import _segment_reduce_best

    rng = np.random.default_rng(0)
    ids = rng.integers(0, n_groups, gate_rows).astype(np.int32)
    cols = {
        "s": rng.standard_normal(gate_rows).astype(np.float32),
        "m": rng.integers(-100, 100, gate_rows).astype(np.int32),
    }
    ops = (("s", "reduce_sum"), ("m", "reduce_max"))
    got = ksr.segment_reduce_pallas(ops, n_groups, cols, ids)
    ref = ksr.segment_reduce_reference(ops, n_groups, cols, ids)
    for k in got:
        assert np.array_equal(got[k], ref[k], equal_nan=True), (
            f"segment-reduce kernel != reference emulation on {k!r} "
            "(bit-identity hard gate)"
        )
    assert np.array_equal(
        got["m"],
        np.asarray(jax.ops.segment_max(
            jnp.asarray(cols["m"]), jnp.asarray(ids),
            num_segments=n_groups,
        )),
    ), "segment-reduce kernel != XLA scatter on an exact op class"

    big_ids = rng.integers(0, n_groups, n_rows).astype(np.int32)
    vals = {"v": rng.standard_normal(n_rows).astype(np.float32)}
    ops1 = (("v", "reduce_sum"),)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        _segment_reduce_best(ops1, n_groups, vals, big_ids)
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _bench_map_rows_ragged(n_rows: int = 20_000, iters: int = 3):
    """Ragged map_rows throughput: grouped vmapped dispatch with
    bucketed lead dims (one dispatch per distinct cell shape, not one
    per row — the round-2 rewrite of the reference's per-row dynamic
    lead dim, TFDataOps.scala:90-103)."""
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    lens = rng.choice([8, 16, 24, 32], n_rows)
    rows = [
        {"v": np.arange(n, dtype=np.float32)} for n in lens
    ]
    frame = tfs.frame_from_rows(rows, num_blocks=4)
    program = tfs.compile_program(
        lambda v: {"s": v.sum()}, frame, block=False
    )

    def run_once():
        out = tfs.map_rows(program, frame)
        for b in out.blocks():
            _sync(b["s"])

    return _time_rows_per_sec(run_once, n_rows, iters)


def _bench_map_rows_ragged_device(n_rows: int = 20_000, iters: int = 3):
    """DEVICE twin of the ragged metric (VERDICT r4 #5): the exact
    shape-grouped, bucket-padded feeds the ragged wave path stages —
    pre-staged to HBM OUTSIDE the timer, run through the same compiled
    per-shape vmap entrypoints. The measured time is dispatch + compute
    + sync only: the ragged ``compute_s`` the ``# split |``
    apportionment printed as nan through round 4."""
    import jax
    import tensorframes_tpu as tfs
    from tensorframes_tpu.ops.executor import bucket_rows, pad_lead_dim

    rng = np.random.default_rng(0)
    widths = [8, 16, 24, 32]
    lens = rng.choice(widths, n_rows)
    # one ragged cell per shape is enough to compile the program; the
    # benched feeds are built dense per group (same bytes the wave path
    # would stage)
    tiny = tfs.frame_from_rows(
        [{"v": np.arange(w, dtype=np.float32)} for w in widths]
    )
    program = tfs.compile_program(
        lambda v: {"s": v.sum()}, tiny, block=False
    )
    compiled = program.compiled()
    feeds = []
    for w in widths:
        g = int((lens == w).sum())
        dense = np.broadcast_to(
            np.arange(w, dtype=np.float32), (g, w)
        ).copy()
        feeds.append(pad_lead_dim({"v": dense}, g, bucket_rows(g)))
    staged = jax.device_put(feeds)  # HBM-resident before the timer

    def run_once():
        in_flight = [
            compiled.run_rows(f, to_numpy=False) for f in staged
        ]
        for o in in_flight:
            _sync(o["s"])

    return _time_rows_per_sec(run_once, n_rows, iters)


def _bench_map_rows_fixed(n_rows: int = 20_000, width: int = 32,
                          iters: int = 3):
    """Fixed-shape map_rows over the same host-frame path and row count
    as the ragged metric — the zero-shape-dispatch upper bound that
    makes the ragged number judgeable (VERDICT r3 #5's done-check:
    ragged within ~3x of fixed-shape on device backends)."""
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    frame = tfs.frame_from_arrays(
        {"v": rng.standard_normal((n_rows, width)).astype(np.float32)},
        num_blocks=4,
    )
    program = tfs.compile_program(
        lambda v: {"s": v.sum()}, frame, block=False
    )

    def run_once():
        out = tfs.map_rows(program, frame)
        for b in out.blocks():
            _sync(b["s"])

    return _time_rows_per_sec(run_once, n_rows, iters)


def _bench_reduce_blocks(n_rows: int = 1_000_000, device: bool = True):
    """reduce_blocks wall-clock (BASELINE config 2 analogue)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu import dtypes as dt

    arr = np.stack([np.arange(n_rows, dtype=np.float32)] * 2, axis=1)
    frame = tfs.frame_from_arrays({"y": arr}, num_blocks=1)
    if device:
        frame = frame.to_device()
    with tfs.with_graph():
        y_input = tfs.block(frame, "y", tf_name="y_input")
        y = tfs.reduce_sum(y_input, axis=0, name="y")
        program = tfs.compile_program(y, frame, reduce_mode="blocks")

    def run_once():
        return tfs.reduce_blocks(program, frame)

    run_once()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run_once()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


_ERRORS: dict = {}


def _bench_compile_fullscale():
    """AOT lower+compile wall-clock for the FULL-SCALE BASELINE configs
    4-5 (299x299 full-width Inception, BERT-base) — works on any
    backend, so compile-time pathologies (constant-folding stalls of the
    ops/windows.py class) surface even when no TPU is reachable.
    Disable with TFTPU_BENCH_COMPILE=0."""
    import jax

    from tensorframes_tpu.models import inception as inc
    from tensorframes_tpu.models import transformer as tr

    from tensorframes_tpu.program import HoistedProgram

    # HoistedProgram lifts the weight trees to runtime arguments — the
    # same path the verbs execute through, and the only way BERT-base's
    # 440 MB of weights fit through a remote-compile relay (closure
    # capture would embed them as HLO literals)
    out = {}
    cfg = inc.inception_v3(channel_scale=1.0)
    prog = inc.scoring_program(cfg, inc.init_params(cfg, seed=0))
    x = jax.ShapeDtypeStruct((8, 299, 299, 3), np.float32)
    t0 = time.perf_counter()
    HoistedProgram(lambda d: prog(d["images"]), {"images": x}).aot_compile()
    out["inception299_fullwidth_compile_s"] = round(time.perf_counter() - t0, 1)

    cfg_b = tr.bert_base()
    rowprog = tr.embed_row_program(cfg_b, tr.init_params(cfg_b, seed=0))
    tok = jax.ShapeDtypeStruct((16, 128), np.int32)
    t0 = time.perf_counter()
    HoistedProgram(
        lambda d: jax.vmap(rowprog)(d["tokens"]), {"tokens": tok}
    ).aot_compile()
    out["bert_base_compile_s"] = round(time.perf_counter() - t0, 1)
    return out


_COMPILECACHE_CHILD = r'''
import json, os, sys, time
sys.path.insert(0, os.environ["TFTPU_REPO"])
import numpy as np
import jax
import tensorframes_tpu as tfs
from tensorframes_tpu.observability.metrics import REGISTRY

which = os.environ["TFTPU_CC_WHICH"]
if which == "inception":
    from tensorframes_tpu.models import inception as inc

    cfg = inc.inception_v3(channel_scale=1.0)
    prog = inc.scoring_program(cfg, inc.init_params(cfg, seed=0))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 299, 299, 3)).astype(np.float32)
    frame = tfs.frame_from_arrays({"images": x}, num_blocks=1)
    program = tfs.compile_program(lambda images: prog(images), frame)
else:
    from tensorframes_tpu.models import transformer as tr

    cfg = tr.bert_base()
    rowprog = tr.embed_row_program(cfg, tr.init_params(cfg, seed=0))
    tok = np.ones((16, 128), np.int32)
    frame = tfs.frame_from_arrays({"tokens": tok}, num_blocks=1)
    program = tfs.compile_program(
        lambda tokens: jax.vmap(rowprog)(tokens), frame
    )
t0 = time.perf_counter()
tfs.map_blocks(program, frame).blocks()
first_dispatch_s = time.perf_counter() - t0
vals = {}
for d in REGISTRY.snapshot():
    if d["name"] in ("tftpu_compilecache_hits_total",
                     "tftpu_compilecache_misses_total") and not d["labels"]:
        vals[d["name"]] = d["value"]
    if d["name"] == "tftpu_executor_compile_seconds":
        vals["compile_count"] = d["count"]
        vals["compile_s"] = d["sum"]
    if d["name"] == "tftpu_compilecache_load_seconds":
        vals["load_s"] = d["sum"]
print(json.dumps({"first_dispatch_s": first_dispatch_s, **vals}))
'''


def _bench_compilecache():
    """ISSUE 5 acceptance: cold-process compile vs warm-store first
    dispatch for the Inception-299 and BERT-base compile configs. Each
    model runs in a fresh subprocess twice against one temp store
    (``TFTPU_COMPILE_CACHE``): run 1 compiles and publishes, run 2
    deserializes — the speedup is the persistent cache's whole point.
    Disable with TFTPU_BENCH_COMPILE=0 (same knob as the compile
    bench)."""
    import os
    import subprocess
    import sys
    import tempfile

    out = {}
    repo = os.path.dirname(os.path.abspath(__file__))
    for which, label in (("inception", "inception299"),
                         ("bert", "bert_base")):
        with tempfile.TemporaryDirectory(prefix="tftpu-cc-bench-") as store:
            runs = []
            for _ in range(2):
                env = {
                    **os.environ,
                    "TFTPU_REPO": repo,
                    "TFTPU_CC_WHICH": which,
                    "TFTPU_COMPILE_CACHE": store,
                }
                r = subprocess.run(
                    [sys.executable, "-c", _COMPILECACHE_CHILD],
                    env=env, capture_output=True, text=True,
                    timeout=_SUBBENCH_TIMEOUT_S,
                )
                if r.returncode != 0:
                    raise RuntimeError(
                        f"compilecache child ({which}) failed: "
                        f"{r.stderr[-1000:]}"
                    )
                runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
            cold, warm = runs
            out[f"{label}_cold_first_dispatch_s"] = round(
                cold["first_dispatch_s"], 3
            )
            out[f"{label}_warm_first_dispatch_s"] = round(
                warm["first_dispatch_s"], 3
            )
            if warm["first_dispatch_s"] > 0:
                out[f"{label}_first_dispatch_speedup"] = round(
                    cold["first_dispatch_s"] / warm["first_dispatch_s"], 1
                )
            # what the store ELIMINATES is the compile phase: trace and
            # the model run itself are cache-invariant (and on this CPU
            # fallback the run is a visible fraction of the dispatch —
            # on a real TPU the 20-40s compile dwarfs both, and the
            # dispatch speedup converges to the compile/load ratio
            # below, which is the ≥5x acceptance number)
            out[f"{label}_cold_compile_s"] = round(
                cold.get("compile_s", 0.0), 3
            )
            out[f"{label}_warm_load_s"] = round(warm.get("load_s", 0.0), 4)
            if warm.get("load_s"):
                out[f"{label}_compile_vs_load_speedup"] = round(
                    cold.get("compile_s", 0.0) / warm["load_s"], 1
                )
            out[f"{label}_warm_disk_hits"] = int(
                warm.get("tftpu_compilecache_hits_total", 0)
            )
            out[f"{label}_warm_compiles"] = int(
                warm.get("compile_count", -1)
            )
    return out


_CC_MULTICHIP_CHILD = r'''
import json, os, sys, time

# platform setup BEFORE jax imports: a fleet child owns 1 CPU device
# (nproc processes form the global mesh); a sharded child owns 8
# virtual devices in one process
role = os.environ["TFTPU_CC_ROLE"]
os.environ["JAX_PLATFORMS"] = "cpu"
ndev = 1 if role == "fleet" else 8
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={ndev}"
).strip()
sys.path.insert(0, os.environ["TFTPU_REPO"])
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tensorframes_tpu as tfs
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.parallel import make_mesh

rank = 0
if role == "fleet":
    nproc = int(os.environ["TFTPU_CC_NPROC"])
    rank = int(sys.argv[1])
    from tensorframes_tpu.parallel import init_distributed

    init_distributed(
        coordinator_address=os.environ["TFTPU_CC_COORD"],
        num_processes=nproc, process_id=rank,
    )
mesh = make_mesh()  # every (global) device on the dp axis

# a representative verb-engine program: 6-layer MLP scoring over
# dp-sharded rows — big enough that XLA compile dominates load by a
# comfortable margin over the 5x acceptance gate
rng = np.random.default_rng(0)
W = [rng.standard_normal((512, 512)).astype(np.float32) * 0.05
     for _ in range(6)]

def mlp(x):
    h = x
    for w in W:
        h = jax.numpy.tanh(h @ w)
    return {"score": h.sum(axis=1)}

x = rng.standard_normal((len(jax.devices()) * 64, 512)).astype(np.float32)
frame = tfs.frame_from_arrays({"x": x}).to_device(mesh)
t0 = time.perf_counter()
out = tfs.map_blocks(mlp, frame)
got = np.asarray(out.column_values("score"))
first_dispatch_s = time.perf_counter() - t0
import hashlib
vals = {"first_dispatch_s": first_dispatch_s,
        "digest": hashlib.sha256(
            np.ascontiguousarray(got).tobytes()
        ).hexdigest()}
for d in REGISTRY.snapshot():
    if d["name"] in ("tftpu_compilecache_hits_total",
                     "tftpu_compilecache_misses_total",
                     "tftpu_executor_fallback_dispatch_total") \
            and not d["labels"]:
        vals[d["name"]] = d["value"]
    if d["name"] == "tftpu_executor_compile_seconds" and not d["labels"]:
        vals["compile_count"] = d["count"]
        vals["compile_s"] = d["sum"]
    if d["name"] == "tftpu_compilecache_load_seconds" and not d["labels"]:
        vals["load_s"] = d["sum"]
if rank == 0:
    print(json.dumps(vals))
'''


def _cc_multichip_fleet_run(store: str, repo: str):
    """One 2-process fleet generation against ``store``; returns rank
    0's metrics dict, or None when the backend cannot run multiprocess
    CPU computations (this jaxlib's pre-existing limitation — the
    sharded single-process mode below still proves the store path)."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        **os.environ,
        "TFTPU_REPO": repo,
        "TFTPU_CC_ROLE": "fleet",
        "TFTPU_CC_NPROC": "2",
        "TFTPU_CC_COORD": f"127.0.0.1:{port}",
        "TFTPU_COMPILE_CACHE": store,
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CC_MULTICHIP_CHILD, str(r)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for r in range(2)
    ]
    # stderr stays a SEPARATE stream: jax/grpc shutdown warnings often
    # land after the child's final print, and a merged stream would put
    # them on the last line the JSON parse below reads
    outs, errs = [], []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_SUBBENCH_TIMEOUT_S)
            outs.append(out)
            errs.append(err)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    if any(p.returncode != 0 for p in procs):
        text = "\n".join(outs + errs)
        if "Multiprocess computations aren't implemented" in text:
            return None
        raise RuntimeError(
            f"compilecache multichip fleet child failed: {text[-1000:]}"
        )
    return json.loads(outs[0].strip().splitlines()[-1])


def _bench_compilecache_multichip():
    """ISSUE 10 acceptance: cold-process vs warm-store first dispatch
    for a SHARDED program keyed by its mesh/topology fingerprint. The
    preferred shape is a 2-process CPU fleet sharing one temp store
    (one rank publishes, every rank's restart hits); where this jaxlib
    cannot run multiprocess CPU computations it degrades to the
    8-virtual-device sharded single-process fleet-in-time (two cold
    processes sharing the store), recorded in ``multichip_mode``. Hard
    gates, either mode: the warm run performs ZERO XLA compiles with
    bit-identical results, and compile-vs-load is >= 5x."""
    import os
    import subprocess
    import sys
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    out = {}
    with tempfile.TemporaryDirectory(prefix="tftpu-cc-multichip-") as store:
        mode = "fleet2"
        runs = []
        for _ in range(2):
            r = _cc_multichip_fleet_run(store, repo)
            if r is None:
                mode = "sharded8"
                runs = []
                break
            runs.append(r)
        if mode == "sharded8":
            for _ in range(2):
                env = {
                    **os.environ,
                    "TFTPU_REPO": repo,
                    "TFTPU_CC_ROLE": "sharded",
                    "TFTPU_COMPILE_CACHE": store,
                }
                r = subprocess.run(
                    [sys.executable, "-c", _CC_MULTICHIP_CHILD],
                    env=env, capture_output=True, text=True,
                    timeout=_SUBBENCH_TIMEOUT_S,
                )
                if r.returncode != 0:
                    raise RuntimeError(
                        "compilecache multichip child failed: "
                        f"{(r.stdout + r.stderr)[-1000:]}"
                    )
                runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    cold, warm = runs
    # hard gates (ISSUE 10 acceptance) — a miss here is a broken cache,
    # not a slow one, so fail the sub-bench rather than report it
    if warm.get("compile_count", -1) != 0:
        raise RuntimeError(
            f"warm multichip run compiled {warm.get('compile_count')} "
            "executable(s); the pre-warmed store must serve every "
            "sharded dispatch (0 compiles)"
        )
    if not warm.get("tftpu_compilecache_hits_total"):
        raise RuntimeError("warm multichip run recorded no store hits")
    if warm.get("tftpu_executor_fallback_dispatch_total"):
        raise RuntimeError(
            "multichip dispatches fell back to lazy jit — the unified "
            "AOT path must carry sharded feeds"
        )
    if cold["digest"] != warm["digest"]:
        raise RuntimeError(
            "store-served sharded results are not bit-identical: cold "
            f"sha256 {cold['digest'][:16]}… vs warm {warm['digest'][:16]}…"
        )
    ratio = (
        cold.get("compile_s", 0.0) / warm["load_s"]
        if warm.get("load_s") else float("inf")
    )
    if ratio < 5.0:
        raise RuntimeError(
            f"compile-vs-load speedup {ratio:.1f}x < 5x "
            f"(compile {cold.get('compile_s', 0):.3f}s, "
            f"load {warm.get('load_s', 0):.4f}s)"
        )
    out["multichip_mode"] = mode
    out["multichip_cold_first_dispatch_s"] = round(
        cold["first_dispatch_s"], 3
    )
    out["multichip_warm_first_dispatch_s"] = round(
        warm["first_dispatch_s"], 3
    )
    if warm["first_dispatch_s"] > 0:
        out["multichip_first_dispatch_speedup"] = round(
            cold["first_dispatch_s"] / warm["first_dispatch_s"], 1
        )
    out["multichip_cold_compile_s"] = round(cold.get("compile_s", 0.0), 3)
    out["multichip_warm_load_s"] = round(warm.get("load_s", 0.0), 4)
    out["multichip_compile_vs_load_speedup"] = round(ratio, 1)
    out["multichip_warm_disk_hits"] = int(
        warm.get("tftpu_compilecache_hits_total", 0)
    )
    out["multichip_warm_compiles"] = int(warm.get("compile_count", -1))
    return out


_SUBBENCH_TIMEOUT_S = 1200  # generous: sweep compiles run minutes, not hours


class _SubBenchTimeout(Exception):
    pass


def _try(name: str, fn, default=None, metric_keys=()):
    """Run one sub-bench; a failure becomes a comment line, never a crash —
    the driver must always receive the single JSON line. ``metric_keys``
    names the metric lines this sub-bench feeds: on failure they print
    as ``metric=ERROR <type>: …`` instead of a fake numeric fallback, so
    dev/bench_check.py can tell a missing fixture dep (ImportError on a
    runner without tensorflow) from a regression.

    A SIGALRM watchdog bounds each sub-bench: the axon tunnel can wedge
    MID-RUN (observed 2026-07-31 — healthy for Inception, dead by the
    decode benches), leaving the process in a python-level poll sleep
    forever; the alarm breaks that sleep so the remaining sub-benches
    and the final JSON line still happen. Main-thread/unix only — it
    degrades to no watchdog elsewhere."""
    import signal

    global _SUBBENCH_TIMEOUT_S
    use_alarm = hasattr(signal, "SIGALRM")
    if use_alarm:
        def _on_alarm(signum, frame):
            raise _SubBenchTimeout(
                f"sub-bench exceeded {_SUBBENCH_TIMEOUT_S}s (wedged backend?)"
            )

        try:
            prev = signal.signal(signal.SIGALRM, _on_alarm)
            signal.alarm(_SUBBENCH_TIMEOUT_S)
        except ValueError:  # not the main thread
            use_alarm = False
    try:
        try:
            return fn()
        finally:
            # cancel BEFORE any error formatting below: a pending alarm
            # firing inside the except block would escape _try and kill
            # the run this wrapper exists to protect
            if use_alarm:
                signal.alarm(0)
    except Exception as e:
        if isinstance(e, _SubBenchTimeout):
            # one wedge means the backend is gone for the whole rest of
            # the run — fail the remaining sub-benches fast instead of
            # burning the full budget ~15 more times
            _SUBBENCH_TIMEOUT_S = min(_SUBBENCH_TIMEOUT_S, 60)
        msg = f"{type(e).__name__}: {str(e).splitlines()[0][:200]}"
        print(f"# {name}=ERROR {msg}")
        for k in metric_keys:
            _ERRORS[k] = msg
        return default
    finally:
        if use_alarm:
            signal.signal(signal.SIGALRM, prev)


def _print_last_tpu_history():
    """On CPU fallback, surface the most recent REAL-TPU run from
    dev/bench_history.jsonl as provenance — the tunnel wedging between a
    healthy session and the driver's end-of-round run must not erase the
    already-measured chip numbers from the record."""
    import os

    path = os.path.join(os.path.dirname(__file__), "dev", "bench_history.jsonl")
    last = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(d, dict):
                    continue
                if d.get("platform") not in ("cpu", None):
                    last = d
    except OSError:
        return
    if last:
        print(
            f"# last_tpu | device_kind={last.get('device_kind')} "
            f"ts={last.get('ts')} metrics={json.dumps(last.get('metrics'))}"
        )


def _probe_backend(timeout_s: float = 150.0) -> bool:
    """Check the accelerator backend from a THROWAWAY subprocess.

    The axon TPU tunnel can wedge in a state where ``jax.devices()``
    blocks forever (observed after a remote-compile helper crash). A hung
    backend must degrade the bench to CPU, not hang the driver — and the
    probe must burn a subprocess, not this process, because backend init
    is uninterruptible C++.
    """
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        return r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    import os

    # probe unless explicitly pinned to cpu: an unset JAX_PLATFORMS still
    # auto-detects accelerators, which is exactly where a wedged backend
    # would hang jax.devices() forever
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # the env var alone is not enough — an accelerator sitecustomize
        # can re-pin the platform after import; force the config
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif not _probe_backend():
        print("# accelerator backend unresponsive; falling back to cpu")
        _print_last_tpu_history()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    n_chips = max(1, len(jax.devices()))
    # per-chip bf16 peak FLOP/s by device kind → MFU column in the report
    # (public spec sheets; MFU vs bf16 peak is the scaling-book convention)
    from tensorframes_tpu import configure

    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for pat, peak in (
        ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
        ("v4", 275e12), ("v6e", 918e12), ("v6 lite", 918e12),
    ):
        if pat in kind:
            # benched frames shard over every chip, so the recorded FLOPs
            # are fleet-aggregate — compare against the fleet peak
            configure(peak_flops=peak * n_chips)
            break
    logreg_rps = _try("logreg", _bench_map_blocks_logreg, 0.0,
                      metric_keys=("logreg_map_blocks_rows_per_sec",))
    add3_rps = _try("add3", _bench_add3, 0.0,
                    metric_keys=("add3_map_blocks_rows_per_sec",))
    chain3_fused_s, chain3_unfused_s = _try(
        "chain3", _bench_chain3, (float("nan"), float("nan")),
        metric_keys=("chain3_fused_1M_wall_s", "chain3_unfused_1M_wall_s"),
    )
    if chain3_fused_s == chain3_fused_s and chain3_unfused_s == chain3_unfused_s:
        print(
            "# plan | chain3 fused={:.4f}s unfused={:.4f}s ratio={:.2f}x "
            "(acceptance: >= 1.5x on the CPU-fallback config)".format(
                chain3_fused_s, chain3_unfused_s,
                chain3_unfused_s / chain3_fused_s,
            )
        )
    (
        chain3_join_fused_s, chain3_join_unfused_s, chain3_join_compiles,
    ) = _try(
        "chain3_join", _bench_chain3_join,
        (float("nan"), float("nan"), -1),
        metric_keys=(
            "chain3_join_fused_1M_wall_s", "chain3_join_unfused_1M_wall_s",
        ),
    )
    if (
        chain3_join_fused_s == chain3_join_fused_s
        and chain3_join_unfused_s == chain3_join_unfused_s
    ):
        print(
            "# plan | chain3_join fused={:.4f}s unfused={:.4f}s "
            "ratio={:.2f}x steady_state_compiles={} bit_identical=True "
            "(acceptance: >= 2x, 0 compiles)".format(
                chain3_join_fused_s, chain3_join_unfused_s,
                chain3_join_unfused_s / chain3_join_fused_s,
                chain3_join_compiles,
            )
        )
    (
        lifted_chain_s, lifted_chain_cb_s, lifted_chain_compiles,
    ) = _try(
        "lifted_chain", _bench_lifted_chain,
        (float("nan"), float("nan"), -1),
        metric_keys=(
            "lifted_chain_1M_wall_s", "lifted_chain_1M_callback_wall_s",
        ),
    )
    if (
        lifted_chain_s == lifted_chain_s
        and lifted_chain_cb_s == lifted_chain_cb_s
    ):
        print(
            "# plan | lift lifted={:.4f}s callback={:.4f}s ratio={:.2f}x "
            "steady_state_compiles={} bit_identical=True barriers=0 "
            "(acceptance: >= 1.5x, 0 compiles)".format(
                lifted_chain_s, lifted_chain_cb_s,
                lifted_chain_cb_s / lifted_chain_s,
                lifted_chain_compiles,
            )
        )
    (
        multijoin_opt_s, multijoin_static_s, multijoin_unfused_s,
        multijoin_pushdowns, multijoin_flips,
    ) = _try(
        "multijoin", _bench_multijoin,
        (float("nan"), float("nan"), float("nan"), 0, 0),
        metric_keys=(
            "multijoin_opt_1M_wall_s", "multijoin_static_1M_wall_s",
            "multijoin_unfused_1M_wall_s",
        ),
    )
    if (
        multijoin_opt_s == multijoin_opt_s
        and multijoin_static_s == multijoin_static_s
    ):
        print(
            "# plan | multijoin opt={:.4f}s static={:.4f}s "
            "unfused={:.4f}s ratio={:.2f}x pushdowns={} "
            "latency_flips={} bit_identical=True (acceptance: >= 1.5x "
            "opt vs TFTPU_REOPT=0, >= 1 counted flip after inverted "
            "walls)".format(
                multijoin_opt_s, multijoin_static_s,
                multijoin_unfused_s,
                multijoin_static_s / multijoin_opt_s,
                multijoin_pushdowns, multijoin_flips,
            )
        )
    try:
        from tensorframes_tpu.observability.metrics import (
            REGISTRY as _plan_reg,
        )

        _plan_lines = [
            ln for ln in _plan_reg.summary_lines()
            if ln.startswith("tftpu_plan_")
        ]
        for ln in _plan_lines:
            print(f"# plan | {ln}")
    except Exception as e:  # telemetry must never kill the JSON line
        print(f"# plan | snapshot unavailable: {e}")
    reduce_s = _try("reduce_blocks", _bench_reduce_blocks, float("nan"),
                    metric_keys=("reduce_blocks_1M_wall_s",))
    # HOST-frame variants: marshalling INCLUDED (the device-resident
    # metrics above exclude it), so each transfer-bound metric has an
    # included/excluded pair and `# split |` lines below apportion the
    # difference (VERDICT r3 #2). Host logreg uses 64k rows in 4 blocks:
    # per-block transfers stay under the relay tunnel's request limit
    # and exercise the map_blocks prefetch overlap.
    logreg_host_rows = 65_536
    logreg_host_rps = _try(
        "logreg_host",
        lambda: _bench_map_blocks_logreg(
            n_rows=logreg_host_rows, iters=3, device=False, num_blocks=4
        ),
        0.0,
        metric_keys=("logreg_host_map_blocks_rows_per_sec",),
    )
    add3_host_rps = _try(
        "add3_host", lambda: _bench_add3(device=False, num_blocks=4), 0.0,
        metric_keys=("add3_host_map_blocks_rows_per_sec",),
    )
    reduce_host_s = _try(
        "reduce_blocks_host",
        lambda: _bench_reduce_blocks(device=False), float("nan"),
        metric_keys=("reduce_blocks_host_1M_wall_s",),
    )
    aggregate_s = _try("aggregate", _bench_aggregate, float("nan"),
                       metric_keys=("aggregate_1M_512groups_wall_s",))
    aggregate_dev_s = _try(
        "aggregate_device", _bench_aggregate_device, float("nan"),
        metric_keys=("aggregate_device_1M_512groups_wall_s",),
    )
    aggregate_str_s = _try(
        "aggregate_strings", _bench_aggregate_strings, float("nan"),
        metric_keys=("aggregate_strings_1M_512groups_wall_s",),
    )
    segment_reduce_s = _try(
        "segment_reduce", _bench_segment_reduce, float("nan"),
        metric_keys=("segment_reduce_1M_wall_s",),
    )
    ragged_rps = _try("map_rows_ragged", _bench_map_rows_ragged, 0.0,
                      metric_keys=("map_rows_ragged_rows_per_sec",))
    ragged_dev_rps = _try(
        "map_rows_ragged_device", _bench_map_rows_ragged_device, 0.0,
        metric_keys=("map_rows_ragged_device_rows_per_sec",),
    )
    fixed_rps = _try("map_rows_fixed", _bench_map_rows_fixed, 0.0,
                     metric_keys=("map_rows_fixed_rows_per_sec",))
    if ragged_rps and fixed_rps:
        print(
            "# split | ragged_vs_fixed map_rows ratio="
            f"{fixed_rps / ragged_rps:.2f}x (done-check: <= ~3x on "
            "device backends)"
        )

    # transfer/compute apportionment (VERDICT r3 #2): one `# split |`
    # line per transfer-bound metric — h2d_s measured with a standalone
    # device_put probe of the metric's own input arrays, compute_s from
    # the device-resident variant, host_total_s from the host variant
    def _split(name, arrays, compute_s, total_s):
        try:
            nbytes = sum(int(a.nbytes) for a in arrays)
            _print_split(
                name, _h2d_seconds(arrays), nbytes, compute_s, total_s
            )
        except Exception as e:
            print(f"# split | {name} probe failed: {e}")

    _split(
        "add3",
        [np.arange(1_000_000, dtype=np.float32)],
        1e6 / add3_rps if add3_rps else float("nan"),
        1e6 / add3_host_rps if add3_host_rps else float("nan"),
    )
    try:
        from tensorframes_tpu.models import logreg as _lr

        # like-for-like: compute_s from a DEVICE-resident run at the
        # host variant's exact config (64k rows, 4 blocks) — the main
        # logreg metric's 262k/1-block rate would misattribute any
        # per-dispatch latency to transfer
        logreg_dev_small = _bench_map_blocks_logreg(
            n_rows=logreg_host_rows, iters=3, device=True, num_blocks=4
        )
        _split(
            "logreg",
            [_lr.make_synthetic_mnist(logreg_host_rows)[0]],
            (logreg_host_rows / logreg_dev_small
             if logreg_dev_small else float("nan")),
            (logreg_host_rows / logreg_host_rps
             if logreg_host_rps else float("nan")),
        )
    except Exception as e:
        print(f"# split | logreg probe failed: {e}")
    _split(
        "reduce_blocks",
        [np.stack([np.arange(1_000_000, dtype=np.float32)] * 2, axis=1)],
        reduce_s,
        reduce_host_s,
    )
    _rng = np.random.default_rng(0)
    _split(
        "aggregate",
        [_rng.integers(0, 512, 1_000_000),
         _rng.standard_normal(1_000_000).astype(np.float32)],
        aggregate_dev_s,
        aggregate_s,
    )
    _split(
        "map_rows_ragged",
        [np.zeros((5_000, n), np.float32) for n in (8, 16, 24, 32)],
        # compute_s from the HBM-pre-staged twin (VERDICT r4 #5 — this
        # printed nan through round 4 for lack of a device variant)
        20_000 / ragged_dev_rps if ragged_dev_rps else float("nan"),
        20_000 / ragged_rps if ragged_rps else float("nan"),
    )
    # full-scale Inception on the real chip; CPU fallback shrinks widths so
    # the harness stays runnable anywhere
    on_tpu = jax.devices()[0].platform != "cpu"
    inception_rps = _try(
        "inception",
        lambda: _bench_inception(
            n_rows=512 if on_tpu else 16,
            iters=4 if on_tpu else 1,
            channel_scale=1.0 if on_tpu else 0.125,
            # batch sweep (TPU only): one timing each at the alternate
            # per-call batches; headline re-times the winner at full iters
            sweep=(128, 1024) if on_tpu else (),
        ),
        0.0,
        metric_keys=("inception_v3_map_blocks_rows_per_sec",),
    )
    inception_rps_q = _try(
        "inception_int8",
        lambda: _bench_inception(
            n_rows=512 if on_tpu else 16,
            iters=4 if on_tpu else 1,
            channel_scale=1.0 if on_tpu else 0.125,
            int8=True,
        ),
        0.0,
        metric_keys=("inception_v3_int8_map_blocks_rows_per_sec",),
    )
    inception_frozen_rps = _try(
        "inception_frozen",
        lambda: _bench_inception_frozen(
            # 512 rows/call — the SAME per-call batch as the native
            # model (the r3 TPU run showed batch 64 leaving the MXU
            # ~5x under-fed; VERDICT r3 #3 wants like-for-like)
            n_rows=512 if on_tpu else 8,
            iters=3 if on_tpu else 1,
            side=299 if on_tpu else 75,
        ),
        0.0,
        metric_keys=("inception_v3_frozen_graphdef_rows_per_sec",),
    )
    inception_frozen_rps_q = _try(
        "inception_frozen_int8",
        lambda: _bench_inception_frozen(
            n_rows=512 if on_tpu else 8,
            iters=3 if on_tpu else 1,
            side=299 if on_tpu else 75,
            int8=True,
        ),
        0.0,
        metric_keys=("inception_v3_frozen_int8_graphdef_rows_per_sec",),
    )
    inception_frozen_rps_bf16 = _try(
        "inception_frozen_bf16",
        lambda: _bench_inception_frozen(
            n_rows=512 if on_tpu else 8,
            iters=3 if on_tpu else 1,
            side=299 if on_tpu else 75,
            compute_dtype="bfloat16",
        ),
        0.0,
        metric_keys=("inception_v3_frozen_bf16_graphdef_rows_per_sec",),
    )
    # like-for-like native-vs-frozen PAIR (VERDICT r4 #4): same input
    # side, same full width, same batch, same dtype policy — the ONLY
    # difference is native program vs importer-lowered program, so the
    # ratio isolates the importer's residual cost (target <= 1.5x on
    # device backends). The headline metrics above keep their historical
    # configs; these two exist solely for the comparison.
    pair_side = 299 if on_tpu else 75
    pair_rows = 512 if on_tpu else 64
    pair_native = _try(
        "pair_native",
        lambda: _bench_inception(
            n_rows=pair_rows, iters=2 if on_tpu else 1,
            channel_scale=1.0, side=pair_side,
            compute_dtype="bfloat16" if on_tpu else "float32",
            mfu_label="bench.pair_native",
        ),
        0.0,
        metric_keys=("pair_native_inception_rows_per_sec",),
    )
    pair_frozen = _try(
        "pair_frozen",
        lambda: _bench_inception_frozen(
            n_rows=pair_rows, iters=2 if on_tpu else 1, side=pair_side,
            compute_dtype="bfloat16" if on_tpu else None,
        ),
        0.0,
        metric_keys=("pair_frozen_inception_rows_per_sec",),
    )
    if pair_native and pair_frozen:
        print(
            f"# pair | inception native_vs_frozen side={pair_side} "
            f"batch={pair_rows} "
            f"dtype={'bf16' if on_tpu else 'f32'} "
            f"native={pair_native:.1f} frozen={pair_frozen:.1f} rows/s "
            f"ratio={pair_native / pair_frozen:.2f}x "
            "(target <= 1.5x on device backends)"
        )
    if on_tpu and "f32" in _FROZEN_BYTES and "int8" in _FROZEN_BYTES:
        # TPU only: XLA:CPU's fusion of the all-constant dequantize is
        # boot-sensitive (see tests/test_graphdef_frozen.py), so the CPU
        # ratio is noise; the env-independent weight-bytes claim lives in
        # the const_bytes unit test
        bf, bq = _FROZEN_BYTES["f32"], _FROZEN_BYTES["int8"]
        if bq > 0:
            print(
                "# int8 | inception_frozen bytes accessed (XLA cost model, "
                f"8 rows): f32={bf/1e6:.1f}MB int8={bq/1e6:.1f}MB "
                f"ratio={bf/bq:.2f}x"
            )
    bert_rps = _try(
        "bert",
        lambda: _bench_bert_embed(
            n_rows=1024 if on_tpu else 32,
            iters=3 if on_tpu else 1,
            full_scale=on_tpu,
        ),
        0.0,
        metric_keys=(
            f"bert_{'base' if on_tpu else 'tiny'}_map_rows_rows_per_sec",
        ),
    )
    attn_seq = 4096 if on_tpu else 512
    attn_tps = _try(
        "attention",
        lambda: _bench_attention(seq=attn_seq, iters=3 if on_tpu else 1),
        0.0,
        metric_keys=(f"flash_attention_{attn_seq}seq_tokens_per_sec",),
    )
    gen_tps = _try(
        "generate",
        lambda: _bench_generate(
            new=64 if on_tpu else 8,
            iters=3 if on_tpu else 1,
            full_scale=on_tpu,
            sweep=(16, 32) if on_tpu else (),
        ),
        0.0,
        metric_keys=(
            f"gpt_{'small' if on_tpu else 'tiny'}_decode_tokens_per_sec",
        ),
    )
    gen_tps_q = _try(
        "generate_int8",
        lambda: _bench_generate(
            new=64 if on_tpu else 8,
            iters=3 if on_tpu else 1,
            full_scale=on_tpu,
            int8=True,
            sweep=(16, 32) if on_tpu else (),
        ),
        0.0,
        metric_keys=(
            f"gpt_{'small' if on_tpu else 'tiny'}_int8kv_decode_tokens_per_sec",
        ),
    )

    if gen_tps and gen_tps_q:
        # the pre-registered int8 adjudication (BASELINE.md r5): >1x on
        # an HBM-bound device backend or the default flips back to f32
        print(
            f"# int8 | decode gpt_{'small' if on_tpu else 'tiny'} "
            f"f32={gen_tps:.0f} int8kv={gen_tps_q:.0f} tok/s "
            f"ratio={gen_tps_q / gen_tps:.2f}x "
            "(pre-registered: 1.5-2.1x HBM-bound device; <1x on CPU by design)"
        )

    # online serving (ISSUE 9): open-loop load through the continuous
    # batcher + the coalesced gpt_tiny int8-KV decode seed workload —
    # p50/p99 and rows/sec ride the BENCH json / snapshot schema
    serving_res = _try(
        "serving",
        lambda: _bench_serving(duration_s=2.0 if on_tpu else 1.0),
        {},
        metric_keys=(
            "serving_open_loop_rows_per_sec",
            "serving_request_p50_s",
            "serving_request_p99_s",
        ),
    ) or {}
    serving_dec_tps = _try(
        "serving_decode", _bench_serving_decode, 0.0,
        metric_keys=("serving_gpt_tiny_int8kv_decode_tokens_per_sec",),
    )
    # iterative decode engine (ISSUE 11): token-level continuous
    # batching over the paged int8 KV pool — tokens/sec + TTFT ride the
    # snapshot schema so `observability diff` gates regressions
    decode_res = _try(
        "serving_decode_engine", _bench_decode_engine, {},
        metric_keys=(
            "serving_decode_tokens_per_sec",
            "serving_decode_ttft_p50_s",
            "serving_decode_ttft_p99_s",
        ),
    ) or {}
    # KV memory hierarchy (ISSUE 19): prefix-hit vs cold TTFT and the
    # undersized-pool swap-resume leg — hard-gated inside the bench
    kvh_res = _try(
        "serving_kv_hierarchy", _bench_kv_hierarchy, {},
        metric_keys=(
            "serving_decode_prefix_hit_ttft_p50_s",
            "serving_decode_cold_ttft_p50_s",
            "serving_decode_swap_resumes_total",
        ),
    ) or {}
    # registered query endpoint (ISSUE 20): result-cache repeat speedup
    # + incremental-refresh fraction ride the snapshot schema; the
    # FUSION=0 subprocess bit-identity gate runs in the dedicated
    # `bench.py registered-query` CI leg, not here
    regq_res = _try(
        "registered_query",
        lambda: _bench_registered_query(check_fusion0=False), {},
        metric_keys=(
            "registered_query_repeat_speedup",
            "registered_query_repeat_p50_s",
            "registered_query_refresh_frac",
        ),
    ) or {}
    if serving_res:
        print(
            "# serving | open_loop rows_per_sec={:.0f} p50={:.6f}s "
            "p99={:.6f}s steady_state_compiles={} requests={} shed={} "
            "(acceptance: 0 steady-state compiles)".format(
                serving_res["rows_per_sec"], serving_res["p50_s"],
                serving_res["p99_s"],
                serving_res["steady_state_compiles"],
                serving_res["requests"], serving_res["shed"],
            )
        )
    if serving_dec_tps:
        print(
            f"# serving | decode_int8kv gpt_tiny coalesced "
            f"tokens_per_sec={serving_dec_tps:.1f}"
        )
    if decode_res:
        print(
            "# serving | decode_engine tokens_per_sec={:.1f} "
            "ttft_p50={:.6f}s ttft_p99={:.6f}s steady_state_compiles={} "
            "requests={} preemptions={} (gates: 0 steady compiles, "
            "batched==solo bit-identical, none lost)".format(
                decode_res["tokens_per_sec"], decode_res["ttft_p50_s"],
                decode_res["ttft_p99_s"],
                decode_res["steady_state_compiles"],
                decode_res["requests"], decode_res["preemptions"],
            )
        )
    if kvh_res:
        print(
            "# serving | kv_hierarchy prefix_hit_ttft_p50={:.6f}s "
            "cold_ttft_p50={:.6f}s prefix_hits={} shared_pages={} "
            "swap_resumes={} swap_fallbacks={} steady_state_compiles={} "
            "(gates: hit p50 < cold p50, swap_resumes > 0, outputs "
            "bit-identical to the dense oracle)".format(
                kvh_res["prefix_hit_ttft_p50_s"],
                kvh_res["cold_ttft_p50_s"], kvh_res["prefix_hits"],
                kvh_res["shared_pages"], kvh_res["swap_resumes"],
                kvh_res["swap_fallbacks"],
                kvh_res["steady_state_compiles"],
            )
        )
    if regq_res:
        print(
            "# serving | registered_query chunks={} first={:.4f}s "
            "repeat_p50={:.6f}s speedup={:.0f}x refresh_frac={:.3f} "
            "steady_state_compiles={} (gates ride `bench.py "
            "registered-query`)".format(
                regq_res["chunks"], regq_res["first_execute_s"],
                regq_res["repeat_p50_s"], regq_res["repeat_speedup"],
                regq_res["refresh_frac"],
                regq_res["steady_state_compiles"],
            )
        )

    # straggler-kernel family summary (ISSUE 12), the `# plan |`
    # convention — printed AFTER every kernel-exercising sub-bench
    # (segment_reduce, ragged map_rows, generate, the serving decode
    # engine) so the dispatch/selection counters reflect this run
    try:
        from tensorframes_tpu.observability.metrics import (
            REGISTRY as _kern_reg,
        )

        for ln in _kern_reg.summary_lines():
            if ln.startswith("tftpu_kernels_") or (
                ln.startswith("tftpu_plan_cost_decisions_total")
                and ("pallas_" in ln or "_attn" in ln
                     or "segment_reduce" in ln)
            ):
                print(f"# kernels | {ln}")
    except Exception as e:  # telemetry must never kill the JSON line
        print(f"# kernels | snapshot unavailable: {e}")

    from tensorframes_tpu import native

    convert_s, convertback_s = _try(
        "convert", _bench_convert, (float("nan"), float("nan")),
        metric_keys=("convert_1M_int_rows_s", "convertback_1M_int_cells_s"),
    )
    read_csv_s = _try("read_csv", _bench_read_csv, float("nan"),
                      metric_keys=("read_csv_1M_rows_s",))

    size = "small" if on_tpu else "tiny"
    metrics = {
        "convert_1M_int_rows_s": round(convert_s, 6),
        "convertback_1M_int_cells_s": round(convertback_s, 6),
        "read_csv_1M_rows_s": round(read_csv_s, 6),
        "add3_map_blocks_rows_per_sec": round(add3_rps),
        "add3_host_map_blocks_rows_per_sec": round(add3_host_rps),
        "chain3_fused_1M_wall_s": round(chain3_fused_s, 6),
        "chain3_unfused_1M_wall_s": round(chain3_unfused_s, 6),
        "chain3_join_fused_1M_wall_s": round(chain3_join_fused_s, 6),
        "chain3_join_unfused_1M_wall_s": round(chain3_join_unfused_s, 6),
        "lifted_chain_1M_wall_s": round(lifted_chain_s, 6),
        "lifted_chain_1M_callback_wall_s": round(lifted_chain_cb_s, 6),
        "multijoin_opt_1M_wall_s": round(multijoin_opt_s, 6),
        "multijoin_static_1M_wall_s": round(multijoin_static_s, 6),
        "multijoin_unfused_1M_wall_s": round(multijoin_unfused_s, 6),
        "logreg_host_map_blocks_rows_per_sec": round(logreg_host_rps),
        "reduce_blocks_1M_wall_s": round(reduce_s, 6),
        "reduce_blocks_host_1M_wall_s": round(reduce_host_s, 6),
        "aggregate_1M_512groups_wall_s": round(aggregate_s, 6),
        "aggregate_device_1M_512groups_wall_s": round(aggregate_dev_s, 6),
        "aggregate_strings_1M_512groups_wall_s": round(aggregate_str_s, 6),
        "segment_reduce_1M_wall_s": round(segment_reduce_s, 6),
        "map_rows_ragged_rows_per_sec": round(ragged_rps),
        # ISSUE 12 snapshot alias: the kernel-selection gate keys
        "ragged_map_rows_per_sec": round(ragged_rps),
        "map_rows_ragged_device_rows_per_sec": round(ragged_dev_rps),
        "map_rows_fixed_rows_per_sec": round(fixed_rps),
        "pair_native_inception_rows_per_sec": round(pair_native, 1),
        "pair_frozen_inception_rows_per_sec": round(pair_frozen, 1),
        "logreg_map_blocks_rows_per_sec": round(logreg_rps),
        "inception_v3_map_blocks_rows_per_sec": round(inception_rps),
        "inception_v3_int8_map_blocks_rows_per_sec": round(inception_rps_q),
        "inception_v3_frozen_graphdef_rows_per_sec": round(inception_frozen_rps),
        "inception_v3_frozen_int8_graphdef_rows_per_sec": round(
            inception_frozen_rps_q
        ),
        "inception_v3_frozen_bf16_graphdef_rows_per_sec": round(
            inception_frozen_rps_bf16
        ),
        f"bert_{'base' if on_tpu else 'tiny'}_map_rows_rows_per_sec": round(
            bert_rps
        ),
        f"flash_attention_{attn_seq}seq_tokens_per_sec": round(attn_tps),
        f"gpt_{size}_decode_tokens_per_sec": round(gen_tps),
        f"gpt_{size}_int8kv_decode_tokens_per_sec": round(gen_tps_q),
        "serving_open_loop_rows_per_sec": round(
            serving_res.get("rows_per_sec", 0.0)
        ),
        "serving_request_p50_s": round(
            serving_res.get("p50_s", 0.0), 6
        ),
        "serving_request_p99_s": round(
            serving_res.get("p99_s", 0.0), 6
        ),
        "serving_gpt_tiny_int8kv_decode_tokens_per_sec": round(
            serving_dec_tps or 0.0, 1
        ),
        "serving_decode_tokens_per_sec": round(
            decode_res.get("tokens_per_sec", 0.0), 1
        ),
        "serving_decode_ttft_p50_s": round(
            decode_res.get("ttft_p50_s", 0.0), 6
        ),
        "serving_decode_ttft_p99_s": round(
            decode_res.get("ttft_p99_s", 0.0), 6
        ),
        "serving_decode_prefix_hit_ttft_p50_s": round(
            kvh_res.get("prefix_hit_ttft_p50_s", 0.0), 6
        ),
        "serving_decode_cold_ttft_p50_s": round(
            kvh_res.get("cold_ttft_p50_s", 0.0), 6
        ),
        "serving_decode_swap_resumes_total": int(
            kvh_res.get("swap_resumes", 0)
        ),
        "registered_query_repeat_speedup": round(
            regq_res.get("repeat_speedup", 0.0), 1
        ),
        "registered_query_repeat_p50_s": round(
            regq_res.get("repeat_p50_s", 0.0), 6
        ),
        "registered_query_refresh_frac": round(
            regq_res.get("refresh_frac", 0.0), 4
        ),
    }
    print(f"# chips={n_chips} devices={jax.devices()}")
    print(f"# native_marshalling={'on' if native.available() else 'off'}")
    for name_, v_ in metrics.items():
        if name_ in _ERRORS:
            print(f"# {name_}=ERROR {_ERRORS[name_]}")
        else:
            print(f"# {name_}={v_}")
    if os.environ.get("TFTPU_BENCH_COMPILE", "1") != "0":
        compile_times = _try(
            "compile_fullscale", _bench_compile_fullscale, {}
        ) or {}
        for k, v in compile_times.items():
            print(f"# compile | {k}={v}")
        # persistent-store cold vs warm first dispatch (ISSUE 5): each
        # model twice in fresh subprocesses sharing one temp store
        cc_times = _try("compilecache", _bench_compilecache, {}) or {}
        for k, v in cc_times.items():
            print(f"# compilecache | {k}={v}")
        # sharded/multi-process store round-trip (ISSUE 10): a 2-process
        # CPU fleet (or the 8-device sharded fallback) sharing one temp
        # store — warm run hard-gated to 0 compiles, >=5x compile/load
        cc_mc = _try(
            "compilecache_multichip", _bench_compilecache_multichip, {},
            metric_keys=(
                "multichip_cold_first_dispatch_s",
                "multichip_warm_first_dispatch_s",
                "multichip_compile_vs_load_speedup",
            ),
        ) or {}
        for k, v in cc_mc.items():
            print(f"# compilecache | {k}={v}")
        # the multichip line rides the snapshot schema so committed
        # rounds gate it through `observability diff`
        metrics.update({
            k: v for k, v in cc_mc.items() if isinstance(v, (int, float))
        })

    # per-metric history (VERDICT r2 #5): every run appends one JSON line
    # so cross-round drift (the r01→r02 bert_tiny −26% the gate couldn't
    # see) is reconstructable from the repo itself. Appended AFTER the
    # compile-cache benches so the multichip line is in the history too.
    # Rehearsal/CI runs set TFTPU_BENCH_NO_HISTORY=1: a contended dry
    # run is not provenance.
    try:
        if os.environ.get("TFTPU_BENCH_NO_HISTORY") == "1":
            raise OSError("history append disabled (TFTPU_BENCH_NO_HISTORY)")
        hist_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "dev", "bench_history.jsonl",
        )
        with open(hist_path, "a") as hist:
            hist.write(json.dumps({
                "ts": round(time.time(), 1),
                "device_kind": getattr(
                    jax.devices()[0], "device_kind", "cpu"
                ),
                "platform": jax.devices()[0].platform,
                "chips": n_chips,
                "metrics": {
                    k: v for k, v in metrics.items() if k not in _ERRORS
                },
            }) + "\n")
    except OSError as e:
        print(f"# history append failed: {e}")

    from tensorframes_tpu.utils import profiling

    mfu_rows = [
        ln for ln in profiling.report().splitlines() if "bench." in ln or "GFLOP" in ln
    ]
    for ln in mfu_rows:
        print(f"# mfu | {ln}")

    # observability snapshot: the run's jit-cache hit/miss + compile
    # counts (and any retry/guard/prefetch activity) ride along in
    # BENCH_*.json rounds as comment lines, so a rows/sec movement can
    # be cross-read against recompile behavior from the record alone
    try:
        from tensorframes_tpu.observability.metrics import REGISTRY

        for ln in REGISTRY.summary_lines():
            print(f"# obs | {ln}")
    except Exception as e:  # never let telemetry kill the JSON line
        print(f"# obs | snapshot unavailable: {e}")

    # per-verb dispatch latency quantiles (ISSUE 6): the p50/p95/p99
    # rows `observability diff` gates on, printed in the same parseable
    # shape as `# obs |` so committed BENCH rounds carry them
    try:
        from tensorframes_tpu.observability import latency as _lat

        for ln in _lat.summary_lines():
            print(f"# latency | {ln}")
    except Exception as e:  # never let telemetry kill the JSON line
        print(f"# latency | unavailable: {e}")

    # structured snapshot (TFTPU_BENCH_SNAPSHOT=path): the machine-
    # checkable form of this run — metrics dict + latency quantiles +
    # run context — that `observability diff` compares against a
    # committed BENCH_r*.json round or another snapshot
    snap_path = os.environ.get("TFTPU_BENCH_SNAPSHOT")
    if snap_path:
        try:
            from tensorframes_tpu.observability import snapshot as _snap

            ok_metrics = {
                k: v for k, v in metrics.items() if k not in _ERRORS
            }
            _snap.write_snapshot(snap_path, ok_metrics, meta={
                "platform": jax.devices()[0].platform,
                "device_kind": getattr(
                    jax.devices()[0], "device_kind", "cpu"
                ),
                "chips": n_chips,
            })
            print(f"# snapshot | wrote {snap_path}")
        except Exception as e:
            print(f"# snapshot | failed: {e}")

    # static-analysis posture of a benched program (ISSUE 3): lint the
    # logreg scoring program (config 3's fixture — cheap to rebuild, and
    # the lint is tracing-only so it never compiles or dispatches) and
    # record diagnostic counts by severity, so BENCH rounds carry lint
    # posture next to throughput
    try:
        import tensorframes_tpu as tfs
        from tensorframes_tpu.analysis import lint_program
        from tensorframes_tpu.models import logreg as _logreg

        x_a, _ = _logreg.make_synthetic_mnist(64)
        a_frame = tfs.frame_from_arrays({"features": x_a})
        a_scoring = _logreg.scoring_program(_logreg.init_params())
        a_prog = tfs.compile_program(
            lambda features: a_scoring(features), a_frame
        )
        a_rep = lint_program(a_prog, subject="bench.logreg")
        a_counts = a_rep.counts_by_severity()
        codes = sorted({d.code for d in a_rep}) or ["-"]
        print(
            "# analysis | bench.logreg "
            f"errors={a_counts['error']} warnings={a_counts['warn']} "
            f"info={a_counts['info']} codes={','.join(codes)}"
        )
    except Exception as e:  # never let lint kill the JSON line
        print(f"# analysis | unavailable: {e}")

    # The published baseline is full-scale-on-TPU (BASELINE.json). The
    # ratio is only meaningful TPU-vs-TPU: a CPU fallback run uses a
    # shrunken model, so it carries the recorded TPU baseline alongside
    # its own number and NULLS the ratio — never 1.0 against itself
    # (VERDICT r3 #6).
    baseline = None
    try:
        with open(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BASELINE.json")
        ) as f:
            baseline = json.load(f).get("published", {}).get(
                "inception_v3_map_blocks_rows_per_sec_per_chip"
            )
    except Exception:
        pass
    value = inception_rps / n_chips
    out = {
        "metric": "map_blocks rows/sec/chip (Inception-v3)",
        "value": round(value, 1),
        "unit": "rows/s/chip",
    }
    if on_tpu:
        out["vs_baseline"] = (
            round(value / baseline, 3) if baseline else None
        )
    else:
        out["metric"] += " [cpu-fallback, 1/8 width]"
        out["value_cpu_fallback"] = out["value"]
        if baseline:
            out["tpu_baseline_on_record"] = baseline
            out["note"] = (
                "TPU baseline on record: "
                f"{baseline:g} rows/s/chip (not comparable to the "
                "shrunken cpu-fallback config)"
            )
        out["vs_baseline"] = None
    print(json.dumps(out))


def serving_main():
    """``python bench.py serving`` — the CI serving smoke: a short
    open-loop CPU load plus the coalesced decode workload, with tracing
    ON so the run's serving spans are real. Writes
    ``serving_metrics.jsonl`` + ``serving_trace.json`` into
    ``TFTPU_OBS_EXPORT`` (riding CI's always-uploaded observability
    artifact) and prints one JSON line for scripting. Exits nonzero if
    a warmed server compiled in steady state — the zero-compile
    acceptance is a hard gate here, where the full bench only reports."""
    import os
    import sys

    from tensorframes_tpu.observability import events as ev

    ev.enable()
    res = _try(
        "serving", lambda: _bench_serving(duration_s=1.0), {}
    ) or {}
    dec = _try("serving_decode", _bench_serving_decode, 0.0)
    if res:
        print(
            "# serving | open_loop rows_per_sec={:.0f} p50={:.6f}s "
            "p99={:.6f}s steady_state_compiles={} requests={} "
            "shed={}".format(
                res["rows_per_sec"], res["p50_s"], res["p99_s"],
                res["steady_state_compiles"], res["requests"],
                res["shed"],
            )
        )
    if dec:
        print(
            f"# serving | decode_int8kv gpt_tiny coalesced "
            f"tokens_per_sec={dec:.1f}"
        )
    out_dir = os.environ.get("TFTPU_OBS_EXPORT")
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        from tensorframes_tpu.observability.metrics import REGISTRY

        REGISTRY.write_jsonl(os.path.join(out_dir, "serving_metrics.jsonl"))
        ev.save(os.path.join(out_dir, "serving_trace.json"))
        print(f"# serving | artifacts -> {out_dir}")
    print(json.dumps({
        "metric": "serving open-loop rows/sec",
        "value": round(res.get("rows_per_sec", 0.0), 1),
        "unit": "rows/s",
        "p50_s": res.get("p50_s"),
        "p99_s": res.get("p99_s"),
        "steady_state_compiles": res.get("steady_state_compiles"),
        "decode_int8kv_tokens_per_sec": round(dec or 0.0, 1),
    }))
    if not res or res.get("steady_state_compiles", 1) != 0:
        print("# serving | FAILED: steady-state compiles != 0")
        sys.exit(1)


def serving_decode_main():
    """``python bench.py serving-decode`` — the CI iterative-decode
    smoke: a short open-loop mixed-length prompt load through the
    token-level engine, tracing ON. Exits nonzero if a warmed engine
    compiled in steady state, lost a request, or a batched result
    diverged from solo decode (the in-bench hard gates raise). Writes
    ``serving_decode_metrics.jsonl`` (the ``tftpu_decode_*`` family
    rides it) + ``serving_decode_trace.json`` into ``TFTPU_OBS_EXPORT``
    and prints one JSON line for scripting."""
    import os
    import sys

    from tensorframes_tpu.observability import events as ev

    ev.enable()
    res = _try(
        "serving_decode_engine", _bench_decode_engine, {}
    ) or {}
    if res:
        print(
            "# serving-decode | tokens_per_sec={:.1f} ttft_p50={:.6f}s "
            "ttft_p99={:.6f}s steady_state_compiles={} requests={} "
            "completed={} preemptions={}".format(
                res["tokens_per_sec"], res["ttft_p50_s"],
                res["ttft_p99_s"], res["steady_state_compiles"],
                res["requests"], res["completed"], res["preemptions"],
            )
        )
    # KV memory hierarchy (ISSUE 19): its own hard gates raise inside
    # (hit p50 < cold p50, swap_resumes > 0, bit-identity, 0 compiles)
    # so a regression fails this smoke; the tftpu_kvswap_* and
    # tftpu_prefix_* counters it drives ride the metrics artifact below
    kvh = _try("serving_kv_hierarchy", _bench_kv_hierarchy, {}) or {}
    if kvh:
        print(
            "# serving-decode | kv_hierarchy prefix_hit_ttft_p50={:.6f}s"
            " cold_ttft_p50={:.6f}s prefix_hits={} swap_resumes={} "
            "swap_fallbacks={}".format(
                kvh["prefix_hit_ttft_p50_s"], kvh["cold_ttft_p50_s"],
                kvh["prefix_hits"], kvh["swap_resumes"],
                kvh["swap_fallbacks"],
            )
        )
    out_dir = os.environ.get("TFTPU_OBS_EXPORT")
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        from tensorframes_tpu.observability.metrics import REGISTRY

        REGISTRY.write_jsonl(
            os.path.join(out_dir, "serving_decode_metrics.jsonl")
        )
        ev.save(os.path.join(out_dir, "serving_decode_trace.json"))
        print(f"# serving-decode | artifacts -> {out_dir}")
    print(json.dumps({
        "metric": "serving iterative decode tokens/sec",
        "value": round(res.get("tokens_per_sec", 0.0), 1),
        "unit": "tokens/s",
        "ttft_p50_s": res.get("ttft_p50_s"),
        "ttft_p99_s": res.get("ttft_p99_s"),
        "steady_state_compiles": res.get("steady_state_compiles"),
        "requests": res.get("requests"),
        "completed": res.get("completed"),
        "prefix_hit_ttft_p50_s": kvh.get("prefix_hit_ttft_p50_s"),
        "cold_ttft_p50_s": kvh.get("cold_ttft_p50_s"),
        "prefix_hits": kvh.get("prefix_hits"),
        "swap_resumes": kvh.get("swap_resumes"),
        "swap_fallbacks": kvh.get("swap_fallbacks"),
    }))
    if not res or res.get("steady_state_compiles", 1) != 0 \
            or res.get("completed") != res.get("requests"):
        print(
            "# serving-decode | FAILED: steady-state compiles != 0, "
            "lost requests, or a hard gate raised"
        )
        sys.exit(1)
    if not kvh or kvh.get("swap_resumes", 0) <= 0 \
            or kvh.get("prefix_hits", 0) <= 0:
        print(
            "# serving-decode | FAILED: kv hierarchy leg — no swap "
            "resumes, no prefix hits, or a hard gate raised"
        )
        sys.exit(1)


def _bench_serving_fleet(num_replicas: int = 2, duration_s: float = 2.5,
                         rate_rps: float = 60.0, kill_at_s: float = 0.8,
                         deadline_s: float = 30.0):
    """Open-loop load through a supervised 2-replica fleet with a
    ``kill -9`` of one replica mid-window — the ISSUE 13 acceptance:

    * every admitted request gets EXACTLY ONE response (success or a
      counted error — never silence): ``lost`` must be 0;
    * p99 over the post-kill window stays bounded (the router cuts the
      dead replica and redrives; survivors absorb the load);
    * the restarted replica rejoins with ZERO XLA compiles (warmed
      purely from the shared ``TFTPU_COMPILE_CACHE`` store — the PR 10
      property asserted for serving warmup).

    Arrivals follow a FIXED schedule (one thread per request at its
    slot — the generator never waits for completions, so queueing and
    failover delay stay visible)."""
    import signal
    import sys
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from tensorframes_tpu.serving import ServingFleet

    cmd = [
        sys.executable, "-m", "tensorframes_tpu.serving.replica_main",
        "--demo", "--max-batch-rows", "8",
    ]
    tmp = tempfile.mkdtemp(prefix="tftpu-fleet-bench-")
    fleet = ServingFleet(
        cmd, num_replicas,
        rendezvous_dir=tmp,
        heartbeat_timeout_s=3.0,
        env={
            "JAX_PLATFORMS": "cpu",
            "TFTPU_HEARTBEAT_INTERVAL_S": "0.1",
            # children must not inherit the parent's obs export or
            # flight spool knobs in surprising ways; the fleet arms its
            # own flight dir under the rendezvous
        },
    )
    fleet.start()
    results = []  # (t_submit_rel, status_or_None, latency_s)
    lock = threading.Lock()
    victim = num_replicas - 1

    def one(i, t_rel):
        body = json.dumps({
            "inputs": {"x": [[float(i % 7)] * 8] * (1 + i % 3)},
            "deadline_s": deadline_s,
        }).encode()
        req = urllib.request.Request(
            fleet.url + "/v1/score", data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=deadline_s * 2) as r:
                status = r.status
                r.read()
        except urllib.error.HTTPError as e:
            status = e.code  # a counted error IS a response
            e.read()
        except Exception:
            status = None  # transport-level silence: a LOST request
        with lock:
            results.append((t_rel, status, time.perf_counter() - t0))

    try:
        n_req = max(1, int(duration_s * rate_rps))
        period = 1.0 / rate_rps
        threads = []
        killed_pid = None
        t_start = time.perf_counter()
        for i in range(n_req):
            target = t_start + i * period
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            if killed_pid is None and now - t_start >= kill_at_s:
                killed_pid = fleet.kill_replica(victim, signal.SIGKILL)
            t = threading.Thread(target=one, args=(i, i * period))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=deadline_s * 2 + 30)
        elapsed = time.perf_counter() - t_start
        # wait out the restart so the zero-compile report lands
        deadline = time.monotonic() + 90.0
        while victim not in fleet.restart_reports \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        report = dict(fleet.restart_reports.get(victim) or {})
        status = fleet.status()
        with lock:
            rows = list(results)
        lost = sum(1 for _, st, _ in rows if st is None)
        ok = sum(1 for _, st, _ in rows if st == 200)
        errors = len(rows) - ok - lost
        post_kill = sorted(
            lat for t_rel, st, lat in rows
            if st is not None and t_rel >= kill_at_s
        )

        def _q(xs, q):
            return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0

        return {
            "requests": n_req,
            "responses": len(rows),
            "ok": ok,
            "errors": errors,
            "lost": lost,
            "rows_per_sec": ok / elapsed if elapsed > 0 else 0.0,
            "p50_s": _q(post_kill, 0.50),
            "p99_post_kill_s": _q(post_kill, 0.99),
            "redrives": status["router"]["redrives"],
            "restarts": status["restarts"],
            "killed_pid": killed_pid,
            "restart_xla_compiles": report.get("xla_compiles"),
            "restart_store_hits": report.get("compile_cache_hits"),
            "recovery_s": report.get("recovery_s"),
            "live_after": status["live"],
        }
    finally:
        fleet.stop()


def serving_fleet_main():
    """``python bench.py serving-fleet`` — the CI scale-out smoke: a
    2-replica supervised fleet under open-loop load with one replica
    SIGKILLed mid-window. Exits nonzero on ANY lost request (a request
    that got silence instead of a response), an unbounded post-kill p99
    window, or a restarted replica that compiled instead of warming
    from the shared store. Writes ``serving_fleet_metrics.jsonl``
    (the ``tftpu_router_*`` family rides it) + ``serving_fleet_trace.json``
    into ``TFTPU_OBS_EXPORT`` and prints one JSON line for scripting."""
    import os
    import sys

    from tensorframes_tpu.observability import events as ev

    ev.enable()
    res = _try("serving_fleet", _bench_serving_fleet, {}) or {}
    if res:
        print(
            "# serving-fleet | requests={} ok={} errors={} lost={} "
            "redrives={} restarts={} p99_post_kill={:.4f}s "
            "restart_xla_compiles={} restart_store_hits={} "
            "recovery={}s".format(
                res["requests"], res["ok"], res["errors"], res["lost"],
                res["redrives"], res["restarts"],
                res["p99_post_kill_s"], res["restart_xla_compiles"],
                res["restart_store_hits"], res["recovery_s"],
            )
        )
    out_dir = os.environ.get("TFTPU_OBS_EXPORT")
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        from tensorframes_tpu.observability.metrics import REGISTRY

        REGISTRY.write_jsonl(
            os.path.join(out_dir, "serving_fleet_metrics.jsonl")
        )
        ev.save(os.path.join(out_dir, "serving_fleet_trace.json"))
        print(f"# serving-fleet | artifacts -> {out_dir}")
    print(json.dumps({
        "metric": "serving fleet open-loop rows/sec (through kill -9)",
        "value": round(res.get("rows_per_sec", 0.0), 1),
        "unit": "rows/s",
        "p99_post_kill_s": res.get("p99_post_kill_s"),
        "lost": res.get("lost"),
        "redrives": res.get("redrives"),
        "restarts": res.get("restarts"),
        "restart_xla_compiles": res.get("restart_xla_compiles"),
        "restart_store_hits": res.get("restart_store_hits"),
    }))
    # CPU CI boxes are contended: the p99 bound is generous — the gate
    # is "bounded vs the 30s deadline", not a latency SLO
    failed = (
        not res
        or res.get("lost", 1) != 0
        or res.get("responses") != res.get("requests")
        or (res.get("p99_post_kill_s") or 99.0) >= 10.0
        or res.get("restart_xla_compiles") != 0
        or (res.get("restart_store_hits") or 0) < 1
    )
    if failed:
        print(
            "# serving-fleet | FAILED: lost requests, unbounded "
            "post-kill p99, or a restarted replica that compiled "
            "(warm store should have served it)"
        )
        sys.exit(1)


def _proc_kb(field: str) -> int:
    """Read one kB-valued field (VmRSS / VmHWM) from /proc/self/status;
    0 when the field is unavailable (sandboxed kernels omit VmHWM)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(field + ":"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


def _peak_rss_bytes() -> int:
    """Process peak RSS: VmHWM where the kernel exposes it, else
    ``ru_maxrss`` (kB on Linux) — one of the two is available
    everywhere the bench runs, so the out-of-core RSS gate is always
    enforced."""
    hwm = _proc_kb("VmHWM")
    if hwm:
        return hwm << 10
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss << 10


def _bench_out_of_core(budget_mb: int = 32, data_factor: float = 5.0):
    """The out-of-core acceptance drill (ISSUE 15 / ROADMAP #3): a CSV
    dataset whose MATERIALIZED size is ~2x its on-disk bytes — and
    several times the enforced block budget — streams a fused
    map→filter→aggregate chain through ``blockstore.stream_chain``
    with the peak-RSS delta hard-bounded, then the identical chain
    runs fully in memory and the results must match bit for bit
    (values are int-valued f64, so every sum is exact)."""
    import os
    import shutil
    import tempfile

    import numpy as np

    import tensorframes_tpu as tfs
    from tensorframes_tpu.blockstore import BlockStore, stream_chain
    from tensorframes_tpu.io import scan_csv

    budget = budget_mb << 20
    target_csv_bytes = int(data_factor * budget)
    work = tempfile.mkdtemp(prefix="tftpu-ooc-")
    parts_dir = os.path.join(work, "parts")
    os.makedirs(parts_dir)
    try:
        # deterministic data, written as a repeated pre-rendered blob so
        # generating 100+ MB of CSV costs file IO, not python loops
        rng = np.random.default_rng(11)
        m = 131_072
        ks = rng.integers(0, 1000, size=m)
        vs = rng.integers(0, 100_000, size=m)
        lines = np.char.add(
            np.char.add(ks.astype(str), ","), vs.astype(str)
        )
        blob = ("\n".join(lines.tolist()) + "\n").encode()
        part_bytes = 10 << 20
        reps_per_part = max(1, part_bytes // len(blob))
        written = 0
        p = 0
        while written < target_csv_bytes:
            path = os.path.join(parts_dir, f"part-{p:04d}.csv")
            with open(path, "wb") as f:
                f.write(b"k,v\n")
                for _ in range(reps_per_part):
                    f.write(blob)
            written += reps_per_part * len(blob)
            p += 1
        n_rows = (written // len(blob)) * m
        mat_bytes = n_rows * 16  # k,v int64

        def agg(f):
            with tfs.with_graph():
                w_in = tfs.block(f, "w", tf_name="w_input")
                return tfs.aggregate(
                    tfs.reduce_sum(w_in, axis=0, name="w"),
                    f.group_by("k"),
                )

        def chain(f):
            g = tfs.map_blocks(lambda v: {"w": v * 3.0}, f)
            g = g.filter(lambda w: w > 150_000.0)
            return agg(g)

        def mapfilter(f):
            g = tfs.map_blocks(lambda v: {"w": v * 3.0}, f)
            return g.filter(lambda w: w > 150_000.0)

        store = BlockStore(
            root=os.path.join(work, "store"), budget_bytes=budget
        )
        # warmup pass over ONE part before the RSS baseline: the first
        # chain executions pay one-time process constants (XLA compile
        # arenas, jax caches, the allocator's high-water) that belong
        # to the process, not the stream — the gate measures what
        # GROWS with the walk, which is what "bounded peak RSS,
        # independent of frame size" means
        first_part = os.path.join(parts_dir, "part-0000.csv")
        with BlockStore(
            root=os.path.join(work, "warm"), budget_bytes=budget
        ) as warm_store:
            stream_chain(
                scan_csv([first_part], rows_per_chunk=m),
                chain_fn=chain, fold_fn=agg, store=warm_store,
            )
            stream_chain(
                scan_csv([first_part], rows_per_chunk=m),
                chain_fn=mapfilter, store=warm_store,
            ).drop()
        rss0 = _proc_kb("VmRSS") << 10
        hwm0 = _peak_rss_bytes()
        t0 = time.perf_counter()
        # phase A — the acceptance chain: fused map→filter→aggregate,
        # streamed end to end (partials spill as they land, the fold
        # merges them once)
        res = stream_chain(
            scan_csv(parts_dir, rows_per_chunk=m),
            chain_fn=chain, fold_fn=agg, store=store,
        )
        stream_s = time.perf_counter() - t0
        # phase B — a result as big as the data: the same map/filter
        # WITHOUT the aggregate, so the spilled output is ~half the
        # materialized table and the LRU spill path genuinely runs —
        # still inside the RSS gate window
        sf = stream_chain(
            scan_csv(parts_dir, rows_per_chunk=m),
            chain_fn=mapfilter, store=store,
        )
        hwm1 = _peak_rss_bytes()
        peak_delta = max(0, hwm1 - max(hwm0, rss0))
        resident = store.resident_bytes
        spilled = store.spilled_bytes
        stream_k = np.asarray(res.column_values("k"))
        stream_w = np.asarray(res.column_values("w"))

        # the in-memory oracle (AFTER the RSS gate window): full
        # materialization, same chains
        cols = {"k": [], "v": []}
        for chunk in scan_csv(parts_dir, rows_per_chunk=1 << 20):
            cols["k"].append(chunk["k"])
            cols["v"].append(chunk["v"])
        full = tfs.frame_from_arrays(
            {k: np.concatenate(v) for k, v in cols.items()}
        )
        assert full.num_rows == n_rows, (full.num_rows, n_rows)
        del cols
        t1 = time.perf_counter()
        oracle = chain(full)
        oracle.blocks()
        mem_s = time.perf_counter() - t1
        mem_mf = mapfilter(full)
        spilled_back = sf.to_frame(mmap=True)
        bit_identical = (
            stream_k.dtype == oracle.column_values("k").dtype
            and np.array_equal(stream_k, oracle.column_values("k"))
            and np.array_equal(stream_w, oracle.column_values("w"))
            and np.array_equal(
                spilled_back.column_values("w"),
                mem_mf.column_values("w"),
            )
        )
        del spilled_back, mem_mf
        sf.drop()
        store.close()
        rss_cap = int(3.5 * budget)
        return {
            "rows": int(n_rows),
            "csv_bytes": int(written),
            "materialized_bytes": int(mat_bytes),
            "budget_bytes": int(budget),
            "rss_cap_bytes": int(rss_cap),
            "peak_rss_delta_bytes": int(peak_delta),
            "rss_gate_available": True,
            "spilled_bytes": int(spilled),
            "resident_bytes": int(resident),
            "groups": int(len(stream_k)),
            "stream_wall_s": stream_s,
            "in_memory_wall_s": mem_s,
            "rows_per_sec": n_rows / stream_s if stream_s else 0.0,
            "bit_identical": bool(bit_identical),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def out_of_core_main():
    """``python bench.py out-of-core`` — the CI data-plane smoke: a
    frame ~5x larger than the enforced block budget (materialized
    ~10x) runs a fused map→filter→aggregate chain end to end through
    the streaming partitioner. Hard gates (exit nonzero): peak RSS
    delta under 3.5x the budget — a fraction of the materialized
    table — with blocks actually spilling, and the streamed result
    bit-identical to the in-memory path. Writes
    ``out_of_core_metrics.jsonl`` (the ``tftpu_blockstore_*`` family
    rides it) into ``TFTPU_OBS_EXPORT`` and prints one JSON line for
    scripting."""
    import os
    import sys

    res = _try("out_of_core", _bench_out_of_core, {}) or {}
    if res:
        print(
            "# out-of-core | rows={:,} csv={:.0f}MB materialized={:.0f}MB "
            "budget={:.0f}MB peak_rss_delta={:.0f}MB (cap {:.0f}MB) "
            "spilled={:.0f}MB groups={} stream={:.2f}s in_memory={:.2f}s "
            "bit_identical={}".format(
                res["rows"], res["csv_bytes"] / 1e6,
                res["materialized_bytes"] / 1e6,
                res["budget_bytes"] / 1e6,
                res["peak_rss_delta_bytes"] / 1e6,
                res["rss_cap_bytes"] / 1e6, res["spilled_bytes"] / 1e6,
                res["groups"], res["stream_wall_s"],
                res["in_memory_wall_s"], res["bit_identical"],
            )
        )
    out_dir = os.environ.get("TFTPU_OBS_EXPORT")
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        from tensorframes_tpu.observability.metrics import REGISTRY

        REGISTRY.write_jsonl(
            os.path.join(out_dir, "out_of_core_metrics.jsonl")
        )
        print(f"# out-of-core | artifacts -> {out_dir}")
    print(json.dumps({
        "metric": "out-of-core streamed rows/sec (5x-budget CSV scan)",
        "value": round(res.get("rows_per_sec", 0.0), 1),
        "unit": "rows/s",
        "peak_rss_delta_bytes": res.get("peak_rss_delta_bytes"),
        "rss_cap_bytes": res.get("rss_cap_bytes"),
        "spilled_bytes": res.get("spilled_bytes"),
        "bit_identical": res.get("bit_identical"),
    }))
    failed = (
        not res
        or not res.get("bit_identical")
        or res.get("spilled_bytes", 0) <= 0
        or res.get("resident_bytes", 1 << 60) > res.get("budget_bytes", 0)
        or (
            res.get("rss_gate_available")
            and res.get("peak_rss_delta_bytes", 1 << 60)
            > res.get("rss_cap_bytes", 0)
        )
    )
    if failed:
        print(
            "# out-of-core | FAILED: peak RSS exceeded the cap, nothing "
            "spilled, or the streamed result diverged from the "
            "in-memory path"
        )
        sys.exit(1)


def registered_query_main():
    """``python bench.py registered-query`` — the CI registered-query
    smoke: a map→aggregate endpoint over a 56-chunk CSV scan directory.
    Hard gates (exit nonzero): warm repeat p50 ≥10x faster than the
    first execution with ZERO steady-state compiles; the incremental
    refresh after appending one chunk under 10% of the full-recompute
    wall over the same table; and both answers bit-identical to a
    TFTPU_FUSION=0 full recompute in a subprocess. Writes
    ``registered_query_metrics.jsonl`` (the ``tftpu_result_cache_*``
    family rides it) into ``TFTPU_OBS_EXPORT`` and prints one JSON line
    for scripting."""
    import os
    import sys

    res = _try("registered_query", _bench_registered_query, {}) or {}
    if res:
        print(
            "# registered-query | chunks={} rows={:,} first={:.4f}s "
            "repeat_p50={:.6f}s speedup={:.0f}x refresh={:.4f}s "
            "full={:.4f}s refresh_frac={:.3f} steady_compiles={} "
            "fusion0_identical={}".format(
                res["chunks"], res["rows"], res["first_execute_s"],
                res["repeat_p50_s"], res["repeat_speedup"],
                res["refresh_s"], res["full_recompute_s"],
                res["refresh_frac"], res["steady_state_compiles"],
                res["fusion0_identical"],
            )
        )
        for k in ("cache_hits", "cache_invalidations", "chunks_folded",
                  "chunks_executed"):
            print(f"# registered_query_{k}={res[k]}")
    out_dir = os.environ.get("TFTPU_OBS_EXPORT")
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        from tensorframes_tpu.observability.metrics import REGISTRY

        REGISTRY.write_jsonl(
            os.path.join(out_dir, "registered_query_metrics.jsonl")
        )
        print(f"# registered-query | artifacts -> {out_dir}")
    print(json.dumps({
        "metric": "registered-query warm repeat speedup",
        "value": round(res.get("repeat_speedup", 0.0), 1),
        "unit": "x",
        "repeat_p50_s": res.get("repeat_p50_s"),
        "refresh_frac": res.get("refresh_frac"),
        "steady_state_compiles": res.get("steady_state_compiles"),
        "fusion0_identical": res.get("fusion0_identical"),
    }))
    failed = (
        not res
        or res.get("repeat_speedup", 0.0) < 10.0
        or res.get("refresh_frac", 1.0) >= 0.10
        or res.get("steady_state_compiles", 1) != 0
        or res.get("fusion0_identical") is not True
    )
    if failed:
        print(
            "# registered-query | FAILED: repeat speedup < 10x, refresh "
            ">= 10% of full recompute, steady-state compiles != 0, or "
            "divergence from the TFTPU_FUSION=0 oracle"
        )
        sys.exit(1)


if __name__ == "__main__":
    import sys as _sys

    if len(_sys.argv) > 1 and _sys.argv[1] == "registered-query":
        registered_query_main()
        _sys.exit(0)
    if len(_sys.argv) > 1 and _sys.argv[1] == "registered-query-oracle":
        _registered_query_oracle(_sys.argv[2], _sys.argv[3])
        _sys.exit(0)
    if len(_sys.argv) > 1 and _sys.argv[1] == "serving":
        serving_main()
    elif len(_sys.argv) > 1 and _sys.argv[1] == "serving-decode":
        serving_decode_main()
    elif len(_sys.argv) > 1 and _sys.argv[1] == "serving-fleet":
        serving_fleet_main()
    elif len(_sys.argv) > 1 and _sys.argv[1] == "out-of-core":
        out_of_core_main()
    else:
        main()
