"""Geometric and harmonic means through keyed ``aggregate``.

≙ tensorframes_snippets/geom_mean.py:26-49: non-algebraic means become
algebraic in transformed space — sum of logs (geometric) and sum of
reciprocals (harmonic) — so a keyed aggregate covers them. The transform
runs in the same XLA program as the block pass (fused elementwise), and
the per-key sums ride the segment-reduction fast path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import tensorframes_tpu as tfs


def _keyed_mean(frame, key: str, col: str, fwd, inv):
    """Per-key mean in ``fwd``-transformed space, mapped back with ``inv``."""
    with tfs.with_graph():
        x = tfs.block(frame, col)
        t = tfs.apply_fn(fwd, x, name="t")
        one = tfs.apply_fn(lambda v: v * 0 + 1.0, x, name="one")
        transformed = tfs.map_blocks([t, one], frame)
    agg = tfs.aggregate(
        lambda t_input, one_input: {
            "t": t_input.sum(axis=0),
            "one": one_input.sum(axis=0),
        },
        transformed.group_by(key),
    )
    keys = np.asarray(agg.column_values(key))
    means = inv(
        np.asarray(agg.column_values("t")), np.asarray(agg.column_values("one"))
    )
    return dict(zip(keys.tolist(), np.asarray(means).tolist()))


def geometric_mean_by_key(frame: "tfs.TensorFrame", key: str, col: str):
    """Per-key geometric mean of ``col``: exp(mean(log x))."""
    return _keyed_mean(
        frame, key, col, jnp.log, lambda s, n: np.exp(s / n)
    )


def harmonic_mean_by_key(frame: "tfs.TensorFrame", key: str, col: str):
    """Per-key harmonic mean of ``col``: n / sum(1/x)."""
    return _keyed_mean(
        frame, key, col, lambda v: 1.0 / v, lambda s, n: n / s
    )


if __name__ == "__main__":  # pragma: no cover
    frame = tfs.frame_from_arrays(
        {
            "key": np.array([1, 1, 1, 2, 2]),
            "x": np.array([1.0, 2.0, 4.0, 3.0, 27.0]),
        }
    )
    print("geometric:", geometric_mean_by_key(frame, "key", "x"))
    print("harmonic:", harmonic_mean_by_key(frame, "key", "x"))
