"""Telemetry walkthrough: trace a training run, export every format.

One ``train_on_frame`` run with the observability subsystem fully armed
produces the three artifacts the subsystem exists for:

* ``trace.json`` — Chrome ``trace_event`` timeline (verb spans,
  executor dispatches, checkpoint saves, per-step train events; open it
  at https://ui.perfetto.dev or chrome://tracing),
* ``metrics.jsonl`` — one-JSON-object-per-metric registry snapshot
  (jit-cache hits/misses, compile seconds, prefetch waits, retry/guard
  counters, …),
* ``steps.jsonl`` — the per-step log (step seconds, loss, rows/s)
  written live by :class:`~tensorframes_tpu.observability.StepTelemetry`,

plus a Prometheus exposition printed to stdout — the same text a
scraper would pull from ``observability.metrics_server(port)``.

Artifacts land in ``$TFTPU_OBS_EXPORT`` (or a temp directory).

Run: ``python -m examples.telemetry``
"""

from __future__ import annotations

import json
import os
import tempfile

import jax
import optax

import tensorframes_tpu as tfs
from tensorframes_tpu import training
from tensorframes_tpu.models import logreg
from tensorframes_tpu.observability import REGISTRY, StepTelemetry, events


def run(out_dir: str, num_steps: int = 30) -> dict:
    """Train a small logreg off a frame with telemetry armed; returns
    {artifact name: path}."""
    events.enable()

    x, y = logreg.make_synthetic_mnist(2048, seed=0)
    frame = tfs.frame_from_arrays({"features": x, "label_true": y})
    params = logreg.init_params(seed=0)
    tx = optax.adam(1e-2)

    @jax.jit
    def step(state, batch):
        p, o = state
        p, o, loss = logreg.train_step(
            p, o, batch["features"], batch["label_true"], tx
        )
        return (p, o), loss

    steps_path = os.path.join(out_dir, "steps.jsonl")
    with StepTelemetry(jsonl_path=steps_path) as telemetry:
        training.train_on_frame(
            step,
            (params, tx.init(params)),
            frame,
            ["features", "label_true"],
            batch_size=128,
            num_steps=num_steps,
            checkpointer=tfs.Checkpointer(
                os.path.join(out_dir, "ckpt"), backend="npz"
            ),
            save_every=10,
            guard="skip",
            telemetry=telemetry,
        )

    # a scoring pass through the verb layer: map_blocks dispatches show
    # up as executor jit-cache misses (first call) then hits (second)
    _, (trained, _opt) = tfs.Checkpointer(
        os.path.join(out_dir, "ckpt"), backend="npz"
    ).restore_latest(like=(params, tx.init(params)))
    for _ in range(2):
        tfs.map_blocks(
            lambda features: logreg.scoring_program(trained)(features), frame
        ).collect()

    trace_path = events.save(os.path.join(out_dir, "trace.json"))
    metrics_path = os.path.join(out_dir, "metrics.jsonl")
    REGISTRY.write_jsonl(metrics_path)
    return {
        "trace": trace_path,
        "metrics": metrics_path,
        "steps": steps_path,
    }


def main():
    out_dir = os.environ.get("TFTPU_OBS_EXPORT")
    tmp = None
    if not out_dir:
        tmp = tempfile.TemporaryDirectory()
        out_dir = tmp.name
    os.makedirs(out_dir, exist_ok=True)

    artifacts = run(out_dir)

    rows = [
        json.loads(line) for line in open(artifacts["steps"])
    ]
    print(
        f"steps.jsonl: {len(rows)} rows — first loss "
        f"{rows[0]['loss']:.3f}, last loss {rows[-1]['loss']:.3f}, "
        f"last rows/s {rows[-1]['rows_per_sec']:.0f}"
    )
    trace = json.load(open(artifacts["trace"]))
    print(
        f"trace.json: {len(trace['traceEvents'])} events "
        "(open in https://ui.perfetto.dev)"
    )

    print("\nPrometheus exposition (excerpt):")
    for line in REGISTRY.to_prometheus().splitlines():
        if line.startswith((
            "tftpu_executor_jit_cache", "tftpu_train_steps_total",
            "tftpu_prefetch_batches_total", "tftpu_checkpoint_save_seconds_count",
            "tftpu_guard_trips_total",
        )):
            print(f"  {line}")
    for name, path in artifacts.items():
        print(f"artifact {name}: {path}")
    if tmp is not None:
        tmp.cleanup()


if __name__ == "__main__":
    main()
