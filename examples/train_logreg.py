"""Train a model straight off a frame — the loop the reference never had.

The reference froze variables client-side and only ever ran inference
(SURVEY §2.7: "Model training: No"). Here the same columnar frame that
feeds the five verbs feeds a resumable training loop: epoch-reshuffled
minibatches, background host→device prefetch, periodic checkpoints, and
resume-after-preemption — then the trained params score back through
``map_blocks``.

Run: ``python -m examples.train_logreg``
"""

from __future__ import annotations

import tempfile

import jax
import numpy as np
import optax

import tensorframes_tpu as tfs
from tensorframes_tpu import training
from tensorframes_tpu.models import logreg


def train(frame, num_steps: int = 60, checkpoint_dir: str | None = None):
    """Returns (params, losses). Re-running with the same checkpoint_dir
    resumes from the latest step instead of restarting."""
    params = logreg.init_params(seed=0)
    tx = optax.adam(1e-2)

    @jax.jit
    def step(state, batch):
        p, o = state
        p, o, loss = logreg.train_step(
            p, o, batch["features"], batch["label_true"], tx
        )
        return (p, o), loss

    losses: list = []
    ck = (
        tfs.Checkpointer(checkpoint_dir, backend="npz")
        if checkpoint_dir
        else None
    )
    (params, _), _ = training.train_on_frame(
        step,
        (params, tx.init(params)),
        frame,
        ["features", "label_true"],
        batch_size=128,
        num_steps=num_steps,
        checkpointer=ck,
        save_every=20,
        on_step=lambda i, l: losses.append(float(l)),
    )
    return params, losses


def main():
    x, y = logreg.make_synthetic_mnist(2048, seed=0)
    frame = tfs.frame_from_arrays({"features": x, "label_true": y})
    with tempfile.TemporaryDirectory() as ckdir:
        params, losses = train(frame, checkpoint_dir=ckdir)
        print(f"trained {len(losses)} steps: loss {losses[0]:.3f} -> {losses[-1]:.3f}")

    # score with the trained params through the same verb layer
    scored = tfs.map_blocks(
        lambda features: logreg.scoring_program(params)(features), frame
    )
    pred = scored.column_values("label")
    acc = float((pred == np.asarray(y)).mean())
    print(f"train accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
