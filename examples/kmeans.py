"""Distributed k-means through the frame verbs.

≙ tensorframes_snippets/kmeans.py:85-162 / kmeans_demo.py: the reference
runs one TF graph per block to find each row's closest centroid and then
aggregates per-centroid sums with a groupBy. Here the same two verbs do
the same job, TPU-native: the assignment program is one XLA program per
block (distance matrix on the MXU), and the centroid update is a keyed
``aggregate`` (segment-sum fast path) instead of a Catalyst shuffle.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

import tensorframes_tpu as tfs


def assignment_program(centers: np.ndarray):
    """map_blocks program: features [n, d] → closest-center index + a count
    column (the aggregate's denominator)."""
    c = jnp.asarray(centers)

    def program(features):
        # pairwise squared distances without materializing [n, k, d]:
        # |x - c|^2 = |x|^2 - 2 x·c + |c|^2 — one MXU matmul
        x2 = jnp.sum(features * features, axis=1, keepdims=True)
        c2 = jnp.sum(c * c, axis=1)
        d2 = x2 - 2.0 * (features @ c.T) + c2
        return {
            "cluster": jnp.argmin(d2, axis=1).astype(jnp.int64),
            "one": jnp.ones(features.shape[0], features.dtype),
        }

    return program


def kmeans_step(frame: "tfs.TensorFrame", centers: np.ndarray) -> np.ndarray:
    """One Lloyd iteration: assign, then per-cluster mean via aggregate."""
    assigned = tfs.map_blocks(assignment_program(centers), frame)
    agg = tfs.aggregate(
        lambda features_input, one_input: {
            "features": features_input.sum(axis=0),
            "one": one_input.sum(axis=0),
        },
        assigned.group_by("cluster"),
    )
    sums = np.asarray(agg.column_values("features"), dtype=np.float64)
    counts = np.asarray(agg.column_values("one"), dtype=np.float64)
    clusters = np.asarray(agg.column_values("cluster"))
    new = centers.copy()
    new[clusters] = (sums / counts[:, None]).astype(centers.dtype)
    return new


def kmeans(
    frame: "tfs.TensorFrame",
    k: int,
    num_iters: int = 10,
    seed: int = 0,
    tol: float = 1e-6,
) -> Tuple[np.ndarray, int]:
    """Lloyd's k-means over the frame's ``features`` column.

    Returns (centers [k, d], iterations actually run)."""
    feats = np.asarray(frame.column_values("features"))
    rng = np.random.default_rng(seed)
    centers = feats[rng.choice(len(feats), size=k, replace=False)].copy()
    for it in range(num_iters):
        new = kmeans_step(frame, centers)
        if np.max(np.abs(new - centers)) < tol:
            return new, it + 1
        centers = new
    return centers, num_iters


def _demo():  # pragma: no cover
    rng = np.random.default_rng(0)
    true = np.array([[0.0, 0.0], [5.0, 5.0], [-5.0, 5.0]], np.float32)
    pts = np.concatenate(
        [t + rng.standard_normal((200, 2)).astype(np.float32) * 0.5 for t in true]
    )
    frame = tfs.frame_from_arrays({"features": pts}, num_blocks=4)
    centers, iters = kmeans(frame, k=3, num_iters=20, seed=1)
    print(f"converged in {iters} iters:\n{np.sort(centers, axis=0)}")


if __name__ == "__main__":  # pragma: no cover
    _demo()
