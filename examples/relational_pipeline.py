"""A relational pipeline without Spark: filter → join → aggregate → sort.

The reference leaned on Spark for everything relational — `where`,
`join`, `orderBy` ran in Catalyst before tensorframes saw the data
(its snippets all assume a pre-shaped DataFrame). A standalone frame
needs those verbs native; this example runs the classic
events-joined-to-users rollup end to end:

1. ``filter`` — drop low-score events (mask computed ON DEVICE via
   ``map_blocks``);
2. ``join`` — attach user attributes by id (inner hash join through the
   aggregate key encoder — string or int keys alike);
3. ``aggregate`` — per-country score totals on the segment-reduction
   fast path;
4. ``sort_values`` + ``limit`` — the top countries.
"""

from __future__ import annotations

import numpy as np

import tensorframes_tpu as tfs


def top_countries(
    events, users, min_score: float = 0.0, top: int = 3
) -> list:
    """Total event score per user country, highest first."""
    good = events.filter(lambda score: {"keep": score >= min_score})
    joined = good.join(users, on="uid")
    with tfs.with_graph():
        score_input = tfs.block(joined, "score", tf_name="score_input")
        per_country = tfs.aggregate(
            tfs.reduce_sum(score_input, axis=0, name="score"),
            joined.group_by("country"),
        )
    return per_country.sort_values(
        "score", ascending=False
    ).limit(top).collect()


def make_data(n_users: int, n_events: int, seed: int):
    """Synthetic users/events — exposed so tests can golden the PIPELINE
    against the same raw arrays rather than replaying the RNG."""
    rng = np.random.default_rng(seed)
    countries = ["jp", "br", "de", "ke", "nz"]
    ctry = [
        countries[int(rng.integers(len(countries)))] for _ in range(n_users)
    ]
    uid = rng.integers(0, n_users, n_events)
    score = rng.standard_normal(n_events).astype(np.float32) + 1.0
    return ctry, uid, score


def run(n_users: int = 50, n_events: int = 2000, seed: int = 0) -> dict:
    ctry, uid, score = make_data(n_users, n_events, seed)
    users = tfs.frame_from_rows(
        [{"uid": i, "country": c} for i, c in enumerate(ctry)]
    )
    events = tfs.frame_from_arrays({"uid": uid, "score": score})
    rows = top_countries(events, users, min_score=0.5, top=3)
    return {
        "top": [(r["country"], round(float(r["score"]), 2)) for r in rows]
    }


if __name__ == "__main__":
    print(run())
