"""Multi-process (multi-host) demo: launch N OS processes, build a global
sharded frame from per-process rows, and run verbs whose reductions cross
process boundaries through compiler collectives.

This is the user-facing shape of what a Spark user did with a cluster:
one process per host (here: per local process, each pinned to one CPU
device), `init_distributed` as the cluster join, `frame_from_process_local`
as "my partition lives on this executor", sharded persistence as the
output sink.

Run: ``python -m examples.multihost_demo`` (spawns 2 worker processes).
On a real TPU fleet the launcher is your orchestrator (GKE/xmanager);
each worker runs ``worker_main`` with the coordinator address set.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys


def worker_main(coordinator: str, num_processes: int, process_id: int) -> None:
    """What each host runs. On TPU pods, jax.distributed picks up the
    topology automatically; args are explicit here for the local demo."""
    import numpy as np

    import tensorframes_tpu as tfs
    from tensorframes_tpu import parallel

    parallel.init_distributed(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(jax.devices(), ("dp",))
    pid = parallel.process_index()

    # each process contributes ITS rows; the frame is global
    local_rows = np.asarray([100.0 * pid + r for r in range(4)])
    frame = parallel.frame_from_process_local(
        {"v": local_rows}, mesh=mesh, axis="dp"
    )

    doubled = tfs.map_blocks(lambda v: {"w": v * 2.0}, frame)
    total = tfs.reduce_blocks(
        lambda w_input: {"w": w_input.sum(axis=0)}, doubled
    )
    print(f"[proc {pid}] global rows={frame.num_rows} total(w)={float(total)}")

    # the relational layer across the fleet: attach a per-key attribute
    # (broadcast hash join — the right side is tiny), then CO-PARTITION
    # both sides once and join process-locally (no further collectives)
    keys = np.arange(pid * 4, pid * 4 + 4)  # spread across the hash space
    kf = parallel.frame_from_process_local(
        {"k": keys, "v": local_rows}, mesh=mesh, axis="dp",
    )
    dims = parallel.frame_from_process_local(
        {"k": keys[::-1].copy(), "weight": keys[::-1] * 0.5},
        mesh=mesh, axis="dp",
    )
    joined = kf.join(dims, on="k")  # process-local share of the join
    co_l = kf.repartition_by_key("k")    # each key's rows now colocate…
    co_r = dims.repartition_by_key("k")  # …on the SAME process
    local_join = co_l.join(co_r, on="k")  # plain local frames: no collective
    print(
        f"[proc {pid}] join rows={len(joined.collect())} "
        f"co-partitioned local rows={co_l.num_rows} "
        f"local-join rows={len(local_join.collect())}"
    )


def main() -> None:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"
    n = 2
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    code = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import sys; sys.path.insert(0, {root!r});"
        "from examples.multihost_demo import worker_main;"
        "worker_main({coord!r}, {n}, int(sys.argv[1]))"
    ).format(root=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
             coord=coord, n=n)
    procs = [
        subprocess.Popen([sys.executable, "-c", code, str(i)], env=env)
        for i in range(n)
    ]
    try:
        codes = [p.wait(timeout=120) for p in procs]
        if any(codes):
            raise SystemExit(f"worker exit codes: {codes}")
    finally:
        # a hung coordinator rendezvous must not orphan workers
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


if __name__ == "__main__":
    main()
