"""Batched text generation over a frame of prompts.

The generation analogue of the image-inference demo: a frame holds one
prompt row per record (plus pass-through metadata columns); a causal-LM
``generate_program`` appends a continuation column through ``map_blocks``.
The whole decode loop (KV-cache prefill + per-token scan) compiles to one
XLA program per block shape — see models/generation.py.

Run: ``python -m examples.text_generation``
"""

from __future__ import annotations

import numpy as np

import tensorframes_tpu as tfs
from tensorframes_tpu.models import generation as gen
from tensorframes_tpu.models import transformer as tr


def generate_over_frame(
    frame: "tfs.TensorFrame",
    cfg: "tr.TransformerConfig",
    params,
    max_new_tokens: int = 16,
    temperature: float = 0.0,
    prompt_col: str = "prompts",
) -> "tfs.TensorFrame":
    """Append a ``generated`` int32 column of shape [max_new_tokens]."""
    feed = {"prompts": prompt_col} if prompt_col != "prompts" else None
    return tfs.map_blocks(
        gen.generate_program(cfg, params, max_new_tokens, temperature),
        frame,
        feed_dict=feed,
    )


def main():
    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (8, 8)).astype(np.int32)
    frame = tfs.frame_from_arrays(
        {"prompts": prompts, "doc_id": np.arange(8)}, num_blocks=2
    )
    out = generate_over_frame(frame, cfg, params, max_new_tokens=12)
    for row in out.collect()[:3]:
        print(f"doc {row['doc_id']}: {list(row['generated'])}")


if __name__ == "__main__":
    main()
