"""Fault-injection walkthrough: break the trainer on purpose, watch the
resilience subsystem absorb it.

Three drills, all deterministic (seeded/counted injections, so a failure
replays exactly):

1. **Transient IO faults** — every 2nd checkpoint write raises OSError;
   a retrying ``Checkpointer`` absorbs all of them and the run finishes
   with the same loss trajectory as a fault-free run.
2. **Poisoned batch** — one minibatch of NaNs mid-stream; the
   ``guard="skip"`` policy discards that single update instead of letting
   NaN propagate into every parameter.
3. **Corrupted checkpoint** — the newest step's payload is truncated on
   disk; ``restore`` logs the integrity failure and falls back to the
   previous intact step.

Run: ``python -m examples.fault_injection``
"""

from __future__ import annotations

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

import tensorframes_tpu as tfs
from tensorframes_tpu.resilience import RetryPolicy, StepGuard, inject
from tensorframes_tpu.training import run_resumable


@jax.jit
def _step(state, batch):
    new = {"w": state["w"] * 0.99 + batch}
    return new, {"loss": jnp.abs(new["w"]).sum()}


def _batches(n, poison_at=None):
    out = [jnp.full((4,), float(i % 5), jnp.float32) for i in range(n)]
    if poison_at is not None:
        out[poison_at] = jnp.full((4,), np.nan, jnp.float32)
    return out


def drill_transient_io(root: str) -> None:
    ck = tfs.Checkpointer(
        os.path.join(root, "io"), backend="npz",
        retry=RetryPolicy(max_attempts=3, backoff=0.01),
    )
    with inject("checkpoint.save", OSError("simulated disk wobble"),
                every_n=2) as inj:
        _, ran = run_resumable(
            _step, {"w": jnp.zeros(4, jnp.float32)}, ck,
            _batches(10), num_steps=10, save_every=2,
        )
    print(f"[io] {ran} steps, {inj.fired} injected save faults, "
          f"all absorbed; latest checkpoint = step {ck.latest_step()}")


def drill_poison_batch(root: str) -> None:
    guard = StepGuard(policy="skip", max_consecutive=3)
    ck = tfs.Checkpointer(os.path.join(root, "nan"), backend="npz")
    state, ran = run_resumable(
        _step, {"w": jnp.zeros(4, jnp.float32)}, ck,
        _batches(10, poison_at=5), num_steps=10, save_every=0, guard=guard,
    )
    finite = bool(np.isfinite(np.asarray(state["w"])).all())
    print(f"[nan] {ran} steps, {guard.skipped} skipped, "
          f"final state finite = {finite}")


def drill_corrupted_checkpoint(root: str) -> None:
    ck = tfs.Checkpointer(os.path.join(root, "corrupt"), backend="npz")
    for s in (2, 4, 6):
        ck.save(s, {"w": jnp.full((4,), float(s), jnp.float32)})
    payload = os.path.join(ck.root, "step_6", "arrays.npz")
    data = open(payload, "rb").read()
    with open(payload, "wb") as f:
        f.write(data[: len(data) // 2])  # simulate a torn write
    print(f"[corrupt] audit: "
          f"{ {s: r['ok'] for s, r in ck.verify().items()} }")
    got = ck.restore(like={"w": jnp.zeros(4, jnp.float32)})
    print(f"[corrupt] restore fell back to w={float(np.asarray(got['w'])[0])} "
          f"(step 4's value) — the torn step 6 was rejected")


def main() -> None:
    with tempfile.TemporaryDirectory() as root:
        drill_transient_io(root)
        drill_poison_batch(root)
        drill_corrupted_checkpoint(root)
    print("fault_injection: all drills recovered")


if __name__ == "__main__":
    main()
