"""Demo workloads driven through the frame verbs.

≙ the reference's non-packaged ``tensorframes_snippets`` (SURVEY.md §2.4):
distributed k-means via map_blocks+aggregate (kmeans.py:85-162), harmonic
and geometric means via aggregate (geom_mean.py:26-49), and model inference
over an image frame (read_image.py's VGG sketch → VGG-16 + Inception here,
f32 and int8). Beyond the reference's snippets: batched text generation
(text_generation), a multi-process launcher (multihost_demo), and
resumable training off a frame (train_logreg), and scoring a foreign
frozen TF ``GraphDef`` through the bundled decoder (foreign_graph). Each
is a library function with tests, not just a script — but every one is
also runnable as ``python -m examples.<name>``.
"""
