"""Batch image-model inference over a frame.

≙ tensorframes_snippets/read_image.py (the VGG-16 sketch), upgraded to the
BASELINE's named model: score an image column with Inception-v3 through
``map_blocks``, frozen-graph style (params are closure-captured
constants), entirely on the accelerator once the frame is device-resident.
"""

from __future__ import annotations

import numpy as np

import tensorframes_tpu as tfs
from tensorframes_tpu.models import inception as inc


def score_images(
    frame: "tfs.TensorFrame",
    cfg: "inc.InceptionConfig",
    params,
    image_col: str = "images",
    to_device: bool = True,
) -> "tfs.TensorFrame":
    """Append ``scores`` (softmax) and ``label`` (argmax) columns."""
    if image_col != "images":
        frame = frame.with_column_renamed(image_col, "images")
    if to_device and not frame.is_sharded:
        frame = frame.to_device()
    prog = inc.scoring_program(cfg, params)
    program = tfs.compile_program(lambda images: prog(images), frame)
    return tfs.map_blocks(program, frame)


def score_images_int8(frame, cfg, params, **kw):
    """Same scoring with weight-only int8 params (4× less weight HBM
    traffic; see ops/quantize.py)."""
    return score_images(frame, cfg, inc.quantize_params(params), **kw)


def _demo():  # pragma: no cover
    cfg = inc.tiny()
    params = inc.init_params(cfg, seed=0)
    images = inc.synthetic_images(cfg, 8, seed=0)
    frame = tfs.frame_from_arrays({"images": images}, num_blocks=2)
    scored = score_images(frame, cfg, params)
    for row in scored.collect()[:4]:
        print("label:", row["label"], "top prob:", float(np.max(row["scores"])))


if __name__ == "__main__":  # pragma: no cover
    _demo()
