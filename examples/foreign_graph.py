"""Scoring a foreign frozen TF graph over a frame — no TensorFlow needed.

≙ the reference's core ingestion promise (a serialized ``GraphDef`` from
*any* TF program runs over DataFrame columns — PythonInterface.scala:115-118
``graphFromFile``): here the bundled clean-room GraphDef decoder lowers
the frozen graph to jax and the verbs execute it like any traced program.
Falls back to building the fixture bytes inline when the reference
fixtures are absent, so the example is self-contained.
"""

from __future__ import annotations

import os

import numpy as np

import tensorframes_tpu as tfs

_FIXTURE = "/root/reference/src/test/resources/graph2.pb"


def _inline_add_graph() -> bytes:
    """A hand-assembled GraphDef: out = Add(z_1, z_2), float32 [2,2]
    placeholders — byte-equivalent to the reference's graph2.pb fixture."""

    def node(name: bytes, op: bytes, inputs=(), attrs=b"") -> bytes:
        body = b"\x0a" + bytes([len(name)]) + name
        body += b"\x12" + bytes([len(op)]) + op
        for i in inputs:
            body += b"\x1a" + bytes([len(i)]) + i
        body += attrs
        return b"\x0a" + bytes([len(body)]) + body

    dtype_attr = b"\x2a\x0b\x0a\x05dtype\x12\x02\x30\x01"
    shape_attr = b"\x2a\x13\x0a\x05shape\x12\x0a\x3a\x08\x12\x02\x08\x02\x12\x02\x08\x02"
    t_attr = b"\x2a\x07\x0a\x01T\x12\x02\x30\x01"
    return (
        node(b"z_1", b"Placeholder", attrs=dtype_attr + shape_attr)
        + node(b"z_2", b"Placeholder", attrs=dtype_attr + shape_attr)
        + node(b"out", b"Add", inputs=[b"z_1", b"z_2"], attrs=t_attr)
    )


def run() -> dict:
    if os.path.exists(_FIXTURE):
        program = tfs.load_graphdef(
            _FIXTURE, fetches=["out"], relax_lead_dim=True
        )
    else:
        program = tfs.program_from_graphdef(
            tfs.parse_graphdef(_inline_add_graph()),
            fetches=["out"],
            relax_lead_dim=True,
        )
    a = np.arange(20, dtype=np.float32).reshape(10, 2)
    b = np.full((10, 2), 0.5, np.float32)
    frame = tfs.frame_from_arrays({"z_1": a, "z_2": b}, num_blocks=2)
    scored = tfs.map_blocks(program, frame)
    total = float(np.asarray(scored.column_values("out")).sum())
    return {"rows": 10, "sum": total, "inputs": program.input_names}


if __name__ == "__main__":
    print(run())
