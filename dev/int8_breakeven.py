"""int8 KV-cache decode: cost-model break-even analysis (VERDICT r4 #3).

The int8 thesis: single-token decode is HBM-bandwidth-bound, so halving
(vs bf16) / quartering (vs f32) the bytes of the two traffic terms that
dominate — the weights (read once per step) and the KV cache (read in
full per step) — buys wall-clock roughly in proportion, while the
quantize/dequantize ALU work rides for free under the memory roofline.
On CPU there is no such roofline gap, which is why the CPU bench shows
int8kv LOSING (r4: 18.7e3 vs 31.6e3 tok/s) — overhead with no byte win
to buy it back.

This script makes the byte claim checkable WITHOUT hardware counters:
it lowers one cached decode step (`generation._forward_cached` + LM
head — the exact fn `generate`'s scan body runs) for f32 and int8kv
variants and reads XLA's cost model (`compiled.cost_analysis()`s
"bytes accessed"), alongside the analytic traffic model
(weights + kv_cache_nbytes). Run on any backend; the TPU numbers are
the ones that matter and get appended to the pre-registered table in
BASELINE.md when a healthy window runs this.

CAVEAT on the cost-model column: XLA charges every
dynamic_update_slice as a full-array write at cost-analysis time —
in-place aliasing happens later, at buffer assignment — so the cache
updates over-count by roughly (num_layers × cache bytes) per step.
The ANALYTIC ratio is the defensible HBM-roofline bound; the
cost-model ratio brackets it from above. (Round-5 change: collapsing
the per-layer slice-out/.at[li].set chains to single 5-D DUS ops cut
the charged int8 bytes 7.0 GB → 2.7 GB for gpt_small.)

Usage: [JAX_PLATFORMS=cpu] python dev/int8_breakeven.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def step_bytes(cfg, batch: int, horizon: int, kv_quant: bool,
               int8_weights: bool):
    """(cost-model bytes, analytic weight bytes, analytic cache bytes)
    for ONE cached decode step at position horizon-1."""
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr

    params = tr.init_params(cfg, seed=0)
    if int8_weights:
        params = tr.quantize_params(params)
    cache = gen.init_kv_cache(cfg, batch, length=horizon, quant=kv_quant)
    tok = jnp.zeros((batch, 1), jnp.int32)

    def one_step(p, c, t):
        hs, c2 = gen._forward_cached(cfg, p, t, c, horizon - 1)
        return gen._logits(cfg, p, hs[:, -1]), c2

    lowered = jax.jit(one_step).lower(params, cache, tok)
    ca = lowered.compile().cost_analysis()
    if isinstance(ca, list):  # older jax returns [dict]
        ca = ca[0]
    model_bytes = float(ca.get("bytes accessed", float("nan")))

    w_bytes = sum(
        int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
        for x in jax.tree.leaves(params)
    )
    c_bytes = gen.kv_cache_nbytes(cache)
    return model_bytes, w_bytes, c_bytes


def main() -> int:
    from tensorframes_tpu.models import generation as gen

    print(f"# backend={jax.default_backend()} devices={jax.devices()}")
    rows = []
    for name, cfg, batch, horizon in (
        ("gpt_tiny", gen.gpt_tiny(), 8, 48),
        ("gpt_small", gen.gpt_small(), 8, 1024),
    ):
        f32 = step_bytes(cfg, batch, horizon, kv_quant=False,
                         int8_weights=False)
        q = step_bytes(cfg, batch, horizon, kv_quant=True,
                       int8_weights=True)
        ratio_model = f32[0] / q[0] if q[0] else float("nan")
        ratio_analytic = (f32[1] + f32[2]) / (q[1] + q[2])
        rows.append((name, batch, horizon, f32, q, ratio_model,
                     ratio_analytic))
        print(
            f"# int8_breakeven | {name} b={batch} S={horizon} "
            f"cost_model_bytes f32={f32[0] / 1e6:.1f}MB "
            f"int8={q[0] / 1e6:.1f}MB ratio={ratio_model:.2f}x ; "
            f"analytic (weights+cache) f32={(f32[1] + f32[2]) / 1e6:.1f}MB "
            f"int8={(q[1] + q[2]) / 1e6:.1f}MB ratio={ratio_analytic:.2f}x"
        )
    print(
        "# int8_breakeven | reading: the ratio bounds the HBM-roofline "
        "decode speedup; int8 pays on a device where decode is "
        "bandwidth-bound AND the ratio-sized byte saving exceeds the "
        "quant/dequant ALU cost. CPU has no such roofline — the CPU "
        "int8kv decode number is an overhead measurement by design."
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
