#!/bin/bash
# Wait for the tunnel prober to mark the backend healthy, then capture
# EVERYTHING the round-3/4 verdicts' TPU re-validation items ask for —
# smoke first, then the full bench (appends a platform=tpu entry to
# dev/bench_history.jsonl with the device-frame aggregate, native
# string-hash, bf16 frozen serving, bert_base, gpt_small f32+int8kv
# decode, batch-swept headline, transfer/compute splits), then refresh
# the TPU regression baseline so the gate tracks the new configuration
# set. Written so a heal window is never missed while the operator is
# elsewhere — and so a FLAPPING tunnel (healthy probe, wedged again by
# smoke time) re-arms instead of consuming the one-shot watcher on a
# dead backend.
#
# SINGLETON: flock guards against two watchers racing (round-4 verdict
# item 1 — two probe loops were observed racing; the same failure mode
# applies here).
#
# REHEARSAL: TFTPU_HEAL_REHEARSAL=1 runs the entire pipeline once on
# the CPU backend (simulated heal): it plants its own TPU_ALIVE marker,
# tells the smoke to accept CPU (pallas interpreted), skips the
# CpuDevice re-arm check (a rehearsal IS a CPU run), writes all logs
# with a .rehearsal suffix, refreshes into a throwaway baseline copy,
# and exits after one pass leaving the real state untouched.
cd /root/repo
REH="${TFTPU_HEAL_REHEARSAL:-0}"
LOCK=dev/.tpu_heal.lock
[ "$REH" = "1" ] && LOCK=dev/.tpu_heal_rehearsal.lock
exec 8>"$LOCK"
flock -n 8 || { echo "tpu_bench_on_heal: another watcher holds the lock" >&2; exit 0; }

if [ "$REH" = "1" ]; then
  export JAX_PLATFORMS=cpu
  export TFTPU_SMOKE_ALLOW_CPU=1
  # the axon sitecustomize dials the TPU relay at EVERY interpreter
  # start when this is set; against a wedged tunnel that call can hang
  # 90s+, which timed out the rehearsal's probe subprocesses (observed
  # round 5). A CPU rehearsal needs no axon backend at all.
  export PALLAS_AXON_POOL_IPS=
  # a contended CPU dry run is not provenance — keep it out of
  # dev/bench_history.jsonl
  export TFTPU_BENCH_NO_HISTORY=1
  SUF=".rehearsal"
  ALIVE=dev/TPU_ALIVE.rehearsal
  BASELINE_ARGS=(--baseline dev/bench_baseline_rehearsal.json)
  cp dev/bench_baseline.json dev/bench_baseline_rehearsal.json 2>/dev/null || true
  touch "$ALIVE"
else
  SUF=""
  ALIVE=dev/TPU_ALIVE
  BASELINE_ARGS=()
fi

while true; do
  while [ ! -f "$ALIVE" ]; do sleep 60; done
  echo "$(date -u +%H:%M:%S) TPU healed — smoke" >> dev/tpu_probe.log
  timeout 900 python dev/tpu_smoke.py > "dev/tpu_smoke_heal.log$SUF" 2>&1
  src=$?
  echo "$(date -u +%H:%M:%S) smoke exit=$src (dev/tpu_smoke_heal.log$SUF)" >> dev/tpu_probe.log
  if [ $src -ne 0 ]; then
    # transient heal: drop the marker, resume probing, keep waiting
    rm -f "$ALIVE"
    [ "$REH" = "1" ] && exit 1
    nohup bash dev/tpu_probe_loop.sh >/dev/null 2>&1 8>&- 9>&- &
    continue
  fi
  python bench.py > "dev/bench_tpu_heal.log$SUF" 2>&1
  rc=$?
  echo "$(date -u +%H:%M:%S) bench exit=$rc (dev/bench_tpu_heal.log$SUF)" >> dev/tpu_probe.log
  if [ $rc -ne 0 ] || { [ "$REH" != "1" ] && grep -q "devices=\[CpuDevice" "dev/bench_tpu_heal.log$SUF"; }; then
    # bench failed, or self-degraded to CPU because the backend
    # re-wedged mid-run: that run captured nothing TPU — re-arm and
    # keep waiting for the next genuine window (same as smoke failure)
    echo "$(date -u +%H:%M:%S) bench was not a TPU run — re-arming" >> dev/tpu_probe.log
    rm -f "$ALIVE"
    [ "$REH" = "1" ] && exit 1
    nohup bash dev/tpu_probe_loop.sh >/dev/null 2>&1 8>&- 9>&- &
    continue
  fi
  python dev/bench_check.py "dev/bench_tpu_heal.log$SUF" --refresh "${BASELINE_ARGS[@]}" \
    >> dev/tpu_probe.log 2>&1
  # bonus capture while the window is open: the TPU cost-model int8
  # break-even (the CPU cost model over-counts DUS; BASELINE.md r5) —
  # best-effort, the window may close mid-run. Runs in rehearsal too
  # (CPU backend) so script bugs here surface in dry runs, not in the
  # one real window.
  timeout 900 python dev/int8_breakeven.py > "dev/int8_breakeven_tpu.log$SUF" 2>&1 \
    && echo "$(date -u +%H:%M:%S) int8_breakeven captured (dev/int8_breakeven_tpu.log$SUF)" >> dev/tpu_probe.log \
    || echo "$(date -u +%H:%M:%S) int8_breakeven did not finish" >> dev/tpu_probe.log
  if [ "$REH" = "1" ]; then
    rm -f "$ALIVE"
    echo "$(date -u +%H:%M:%S) rehearsal complete (logs: *.rehearsal)" >> dev/tpu_probe.log
    exit 0
  fi
  break
done
