#!/bin/bash
# Wait for the tunnel prober to mark the backend healthy, then capture
# EVERYTHING the round-3 verdict's TPU re-validation item asks for —
# smoke first, then the full bench (appends a platform=tpu entry to
# dev/bench_history.jsonl with the device-frame aggregate, native
# string-hash, bf16 frozen serving, bert_base, gpt_small f32+int8kv
# decode, batch-swept headline), then refresh the TPU regression
# baseline so the gate tracks the new configuration set. Written so a
# heal window is never missed while the operator is elsewhere — and so
# a FLAPPING tunnel (healthy probe, wedged again by smoke time) re-arms
# instead of consuming the one-shot watcher on a dead backend.
cd /root/repo
while true; do
  while [ ! -f dev/TPU_ALIVE ]; do sleep 60; done
  echo "$(date -u +%H:%M:%S) TPU healed — smoke" >> dev/tpu_probe.log
  timeout 900 python dev/tpu_smoke.py > dev/tpu_smoke_heal.log 2>&1
  src=$?
  echo "$(date -u +%H:%M:%S) smoke exit=$src (dev/tpu_smoke_heal.log)" >> dev/tpu_probe.log
  if [ $src -ne 0 ]; then
    # transient heal: drop the marker, resume probing, keep waiting
    rm -f dev/TPU_ALIVE
    nohup bash dev/tpu_probe_loop.sh >/dev/null 2>&1 &
    continue
  fi
  python bench.py > dev/bench_tpu_heal.log 2>&1
  rc=$?
  echo "$(date -u +%H:%M:%S) bench exit=$rc (dev/bench_tpu_heal.log)" >> dev/tpu_probe.log
  if [ $rc -ne 0 ] || grep -q "devices=\[CpuDevice" dev/bench_tpu_heal.log; then
    # bench failed, or self-degraded to CPU because the backend
    # re-wedged mid-run: that run captured nothing TPU — re-arm and
    # keep waiting for the next genuine window (same as smoke failure)
    echo "$(date -u +%H:%M:%S) bench was not a TPU run — re-arming" >> dev/tpu_probe.log
    rm -f dev/TPU_ALIVE
    nohup bash dev/tpu_probe_loop.sh >/dev/null 2>&1 &
    continue
  fi
  python dev/bench_check.py dev/bench_tpu_heal.log --refresh \
    >> dev/tpu_probe.log 2>&1
  break
done
