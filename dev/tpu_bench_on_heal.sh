#!/bin/bash
# Wait for the tunnel prober to mark the backend healthy, then capture a
# full TPU bench run + refresh the TPU regression baseline. Written so a
# heal window is never missed while the operator is elsewhere.
cd /root/repo
while [ ! -f dev/TPU_ALIVE ]; do sleep 60; done
echo "$(date -u +%H:%M:%S) TPU healed — running bench" >> dev/tpu_probe.log
python bench.py > dev/bench_tpu_heal.log 2>&1
echo "$(date -u +%H:%M:%S) bench exit=$? (dev/bench_tpu_heal.log)" >> dev/tpu_probe.log
