"""Bench regression gate.

Parses the ``# key=value`` lines bench.py prints and compares each metric
against the recorded baseline for the SAME platform
(``dev/bench_baseline.json`` holds one section per platform — CPU and
TPU runs emit different metric names and incomparable values). The
round-2 verdict showed ~20x floors cannot see a 25% regression — the
default factor is 2.0 against a same-machine baseline, so an injected
2x slowdown trips the gate while ordinary run-to-run variance (median
timing in bench.py holds repeats to ~10%) does not.

Usage: python dev/bench_check.py bench_output.txt [--factor F]
       [--require-all] [--refresh] [--baseline PATH]

* ``--factor`` widens the allowance for alien runners (CI uses 10).
* A metric whose bench line reads ``name=ERROR ImportError...`` is
  SKIPPED with a note unless ``--require-all``: CI installs no
  tensorflow, so the frozen-graph fixtures legitimately can't build
  there (ADVICE r2).
* ``--refresh`` records this run as the baseline for its platform.
* ``--baseline PATH`` reads/writes an alternate baseline file (the heal
  rehearsal refreshes into a throwaway copy so a CPU dry run can never
  clobber the real per-platform baselines).
* No baseline recorded yet for this platform → pass with a notice (the
  first run on new hardware cannot regress against anything).
"""

from __future__ import annotations

import json
import os
import re
import sys

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "bench_baseline.json")

# metrics measured in seconds regress UPWARD; rates regress DOWNWARD
_IS_WALL = re.compile(r"(_s|_wall_s)$")


def parse(text: str):
    found = dict(re.findall(r"^# ([\w]+)=([0-9.eE+-]+)$", text, re.M))
    errors = dict(re.findall(r"^# ([\w]+)=ERROR (\S+)", text, re.M))
    platform = "tpu" if re.search(r"devices=\[(?!CpuDevice)", text) else "cpu"
    return {k: float(v) for k, v in found.items()}, errors, platform


def main(argv) -> int:
    path = argv[0]
    factor = 2.0
    require_all = "--require-all" in argv
    refresh = "--refresh" in argv
    if "--factor" in argv:
        factor = float(argv[argv.index("--factor") + 1])
    baseline_path = BASELINE_PATH
    if "--baseline" in argv:
        baseline_path = argv[argv.index("--baseline") + 1]
    with open(path) as f:
        text = f.read()
    values, errors, platform = parse(text)

    try:
        with open(baseline_path) as f:
            all_baselines = json.load(f)
    except FileNotFoundError:
        all_baselines = {}

    if refresh:
        all_baselines[platform] = values
        with open(baseline_path, "w") as f:
            json.dump(all_baselines, f, indent=1, sort_keys=True)
        print(
            f"bench_check: {platform} baseline refreshed with "
            f"{len(values)} metrics"
        )
        return 0

    baseline = all_baselines.get(platform)
    if not baseline:
        print(
            f"bench_check: no {platform} baseline recorded yet — nothing to "
            "compare (record one with --refresh)"
        )
        return 0

    failures, skipped, checked = [], [], 0
    for name, base in baseline.items():
        if name in values:
            v = values[name]
            if base == 0:
                skipped.append(f"{name}: zero baseline (re-refresh)")
                continue
            checked += 1
            if _IS_WALL.search(name):
                if v > base * factor:
                    failures.append(
                        f"{name}={v:g} above {base:g}×{factor:g} ceiling"
                    )
            elif v < base / factor:
                failures.append(
                    f"{name}={v:g} below {base:g}/{factor:g} floor"
                )
        elif name in errors and errors[name].startswith("ImportError"):
            # fixture deps (tensorflow) absent on this runner — a known
            # benign configuration, not a regression (ADVICE r2)
            if require_all:
                failures.append(f"MISSING {name} ({errors[name]})")
            else:
                skipped.append(f"{name}: {errors[name]}")
        else:
            failures.append(
                f"MISSING metric {name}"
                + (f" ({errors[name]})" if name in errors else "")
            )
    print(
        f"bench_check: {checked} {platform} metrics checked vs baseline "
        f"(factor {factor:g}), {len(skipped)} skipped, "
        f"{len(failures)} failures"
    )
    for s in skipped:
        print(f"  SKIP {s}")
    for f_ in failures:
        print(f"  FAIL {f_}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
