"""Bench regression gate for CI (CPU-fallback config).

Parses the ``# key=value`` lines bench.py prints and enforces loose
floors/ceilings (~20x headroom vs the recorded CPU-fallback table in
BASELINE.md) — the goal is to catch order-of-magnitude regressions
(accidental per-row dispatch, lost native marshalling, recompile storms),
not to benchmark CI runners.

Usage: python dev/bench_check.py bench_output.txt
"""

from __future__ import annotations

import re
import sys

# metric name → (kind, bound). kind 'min' = value must be >= bound,
# 'max' = value must be <= bound. Bounds are ~20x slack off the
# BASELINE.md CPU-fallback rows so runner variance never flakes.
BOUNDS = {
    "add3_map_blocks_rows_per_sec": ("min", 2e7),
    "logreg_map_blocks_rows_per_sec": ("min", 8e4),
    "inception_v3_map_blocks_rows_per_sec": ("min", 3.0),
    "convert_1M_int_rows_s": ("max", 1.0),
    "convertback_1M_int_cells_s": ("max", 6.0),
    "read_csv_1M_rows_s": ("max", 3.0),
    "aggregate_1M_512groups_wall_s": ("max", 3.0),
    "reduce_blocks_1M_wall_s": ("max", 0.5),
    "bert_tiny_map_rows_rows_per_sec": ("min", 500.0),
    "aggregate_strings_1M_512groups_wall_s": ("max", 30.0),
    "map_rows_ragged_rows_per_sec": ("min", 1000.0),
    "inception_v3_frozen_graphdef_rows_per_sec": ("min", 5.0),
    "inception_v3_frozen_int8_graphdef_rows_per_sec": ("min", 5.0),
}


def main(path: str) -> int:
    with open(path) as f:
        text = f.read()
    found = dict(re.findall(r"^# (\w+)=([0-9.eE+-]+)$", text, re.M))
    failures = []
    checked = 0
    for name, (kind, bound) in BOUNDS.items():
        if name not in found:
            failures.append(f"MISSING metric {name}")
            continue
        v = float(found[name])
        checked += 1
        if kind == "min" and v < bound:
            failures.append(f"{name}={v:g} below floor {bound:g}")
        elif kind == "max" and v > bound:
            failures.append(f"{name}={v:g} above ceiling {bound:g}")
    print(f"bench_check: {checked} metrics checked, {len(failures)} failures")
    for f_ in failures:
        print(f"  FAIL {f_}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
