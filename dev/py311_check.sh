#!/bin/bash
# Python-3.11 compatibility gate (VERDICT r4 #9): the CI matrix's 3.11
# leg has never executed because no jax-equipped 3.11 interpreter can be
# provisioned offline (zero egress, no pip). This is the static stand-in
# that CAN run anywhere a bare python3.11 exists:
#
#   1. py_compile every source file under 3.11 — rejects 3.12-only
#      SYNTAX (PEP 695 type parameters, f-string grammar extensions).
#   2. grep for 3.12-only stdlib API usage the syntax pass can't see.
#
# What it cannot prove: RUNTIME behavior differences (none known — the
# package uses no itertools.batched, no os.path.isjunction, no
# tomllib-3.12-only features; typing usage is 3.9-era). The real 3.11
# leg runs the moment CI reaches a real runner (ci.yml matrix).
set -e
cd "$(dirname "$0")/.."
PY311="${PY311:-python3.11}"
if ! command -v "$PY311" >/dev/null; then
  echo "py311_check: no python3.11 on PATH — skipping (documented risk)"
  exit 0
fi
# the axon sitecustomize needs jax; a bare 3.11 has none — silence it
export PALLAS_AXON_POOL_IPS=
FILES=$(find tensorframes_tpu tests examples dev -name "*.py"; echo bench.py __graft_entry__.py)
"$PY311" -m py_compile $FILES
# 3.12-only stdlib surface a syntax compile can't catch — same scope as
# the py_compile pass above (tests/dev scripts run on the 3.11 leg too)
if grep -rnE "itertools\.batched|os\.path\.isjunction|calendar\.(Month|Day)\b|\bsys\.monitoring" \
    tensorframes_tpu tests examples dev bench.py __graft_entry__.py --include="*.py"; then
  echo "py311_check: 3.12-only stdlib API found (lines above)"
  exit 1
fi
echo "py311_check: OK ($(echo "$FILES" | wc -w) files compile under $("$PY311" --version 2>&1); no 3.12-only stdlib use)"
