#!/usr/bin/env python
"""Thin shim: the repo self-lint moved into the package.

The rules live in :mod:`tensorframes_tpu.analysis.selfcheck` (one lint
entry point for CI: ``python -m tensorframes_tpu.analysis selfcheck``).
This script forwards so ``python dev/lint_rules.py`` keeps working.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tensorframes_tpu.analysis.selfcheck import (  # noqa: E402
    ALLOW_JAX_JIT,
    METRIC_FACTORIES,
    MUTATORS,
    REPO,
    lint_file,
    main,
)

__all__ = [
    "ALLOW_JAX_JIT", "METRIC_FACTORIES", "MUTATORS", "REPO",
    "lint_file", "main",
]

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
