#!/usr/bin/env bash
# Test runner (≙ the reference's python/run-tests.sh): full suite on the
# virtual 8-device CPU mesh, then the multi-chip dry-run.
# conftest.py pins the platform; no env needed for pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m pytest tests/ -x -q "$@"

JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python __graft_entry__.py 8

echo "run-tests: all green"
