#!/bin/bash
# Faithful LOCAL rehearsal of .github/workflows/ci.yml (VERDICT r3 #8).
#
# No GitHub runner is reachable from this environment (zero egress, no
# github.com), so this script executes the workflow's exact steps, in
# order, against a CLEAN CLONE of HEAD (the checkout step's semantics:
# CI must not see uncommitted files) inside a fresh venv. Documented
# deviations from the literal yml, each forced by the sandbox:
#
#   * matrix python-version: only the image's python (3.12) is
#     installed; the 3.11 leg cannot run here.
#   * `pip install -U pip` + `pip install -e ".[test]"`: the image has
#     no package index (zero egress). The venv is created with
#     --system-site-packages so the baked-in deps (jax, numpy, pytest,
#     …) satisfy the requirements, and the project itself installs with
#     --no-deps --no-build-isolation — the same "editable install then
#     run from the installed package" shape the workflow exercises.
#
# Usage: bash dev/ci_rehearsal.sh [logfile]
set -u -o pipefail

LOG=${1:-dev/ci_rehearsal.log}
REPO=$(cd "$(dirname "$0")/.." && pwd)
WORK=$(mktemp -d /tmp/ci_rehearsal.XXXXXX)
CLONE="$WORK/repo"
VENV="$WORK/venv"
export PALLAS_AXON_POOL_IPS=  # CPU CI: never touch the TPU relay
export JAX_PLATFORMS=cpu
export XLA_FLAGS=--xla_force_host_platform_device_count=8

run_step() {
  local name="$1"; shift
  echo "=== step: $name ===" | tee -a "$LOG"
  if ( "$@" ) >> "$LOG" 2>&1; then
    echo "--- step OK: $name" | tee -a "$LOG"
  else
    echo "--- step FAILED: $name (exit $?)" | tee -a "$LOG"
    echo "CI REHEARSAL: FAILED at '$name' — log: $LOG"
    exit 1
  fi
}

: > "$LOG"
{
  echo "ci.yml rehearsal — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "HEAD: $(git -C "$REPO" rev-parse HEAD)"
  echo "python: $(python --version 2>&1)"
  echo "workdir: $WORK"
} | tee -a "$LOG"

run_step "checkout (clean clone of HEAD)" \
  git clone --quiet --no-hardlinks "$REPO" "$CLONE"

run_step "setup-python (venv, system site-packages for baked-in deps)" \
  python -m venv --system-site-packages "$VENV"

cd "$CLONE"
PY="$VENV/bin/python"

run_step "Install (editable, --no-deps: zero-egress image carries deps)" \
  "$PY" -m pip install -e . --no-deps --no-build-isolation --quiet

run_step "Test (8-device virtual CPU mesh)" \
  "$PY" -m pytest tests/ -x -q

run_step "Bench smoke (CPU fallback)" bash -c \
  "\"$PY\" -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench.main()\" | tee bench_out.txt"

run_step "Bench regression gate (factor 10, alien-runner allowance)" \
  "$PY" dev/bench_check.py bench_out.txt --factor 10

run_step "Multi-chip dryrun (8 virtual devices)" \
  "$PY" -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "CI REHEARSAL: ALL STEPS GREEN — log: $LOG" | tee -a "$LOG"
rm -rf "$WORK"
