#!/bin/bash
# Faithful LOCAL rehearsal of .github/workflows/ci.yml (VERDICT r3 #8).
#
# No GitHub runner is reachable from this environment (zero egress, no
# github.com), so this script executes the workflow's exact steps, in
# order, against a CLEAN CLONE of HEAD (the checkout step's semantics:
# CI must not see uncommitted files). Documented deviations from the
# literal yml, each forced by the sandbox:
#
#   * matrix python-version: only the image's python (3.12) is
#     installed; the 3.11 leg cannot run here.
#   * the bench-smoke step usually runs CONTENDED (rehearsals share the
#     machine with a build session); its absolute numbers can print
#     10x+ slower than dedicated runs and must never be read as
#     regressions — the factor-10 gate exists exactly for that, and
#     rehearsal benches do not enter dev/bench_history.jsonl
#     (TFTPU_BENCH_NO_HISTORY).
#   * `pip install -U pip` + `pip install -e ".[test]"`: the image has
#     no package index (zero egress) and the interpreter is itself a
#     venv (a nested venv would lose its site-packages), so the project
#     installs from the clean clone with --no-deps --no-build-isolation
#     into a private --target directory — the same "build the package
#     metadata, then run the suite against the checkout" shape the
#     workflow exercises; the baked-in deps stand in for the [test]
#     extra.
#
# Usage: bash dev/ci_rehearsal.sh [logfile]
set -u -o pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
LOG=${1:-dev/ci_rehearsal.log}
case "$LOG" in
  /*) : ;;
  *) LOG="$REPO/$LOG" ;;  # absolute: the steps cd into the clone
esac
WORK=$(mktemp -d /tmp/ci_rehearsal.XXXXXX)
CLONE="$WORK/repo"
SITE="$WORK/site"
export PALLAS_AXON_POOL_IPS=  # CPU CI: never touch the TPU relay
export JAX_PLATFORMS=cpu
export XLA_FLAGS=--xla_force_host_platform_device_count=8
export TFTPU_BENCH_NO_HISTORY=1  # a contended smoke is not provenance

run_step() {
  local name="$1"; shift
  echo "=== step: $name ===" | tee -a "$LOG"
  if ( "$@" ) >> "$LOG" 2>&1; then
    echo "--- step OK: $name" | tee -a "$LOG"
  else
    local rc=$?
    echo "--- step FAILED: $name (exit $rc)" | tee -a "$LOG"
    echo "CI REHEARSAL: FAILED at '$name' — log: $LOG"
    exit 1
  fi
}

: > "$LOG"
{
  echo "ci.yml rehearsal — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "HEAD: $(git -C "$REPO" rev-parse HEAD)"
  echo "python: $(python --version 2>&1)"
  echo "workdir: $WORK"
} | tee -a "$LOG"

run_step "checkout (clean clone of HEAD)" \
  git clone --quiet --no-hardlinks "$REPO" "$CLONE"

run_step "setup-python (image interpreter; full 3.11 leg unavailable here)" \
  python -c "import sys; assert sys.version_info >= (3, 11); print(sys.version)"

cd "$CLONE"

# the CLONE's copy, like every other step: real CI checks out the
# commit, so an uncommitted working-tree file must not affect the gate
run_step "py311 static gate (the 3.11-leg stand-in that CAN run here)" \
  bash "$CLONE/dev/py311_check.sh"

# ci.yml's lint job. ruff is pip-installed on real runners; the
# zero-egress image may not carry it — the repo self-lint and the
# program analyzer (both stdlib + baked-in jax) always run.
if command -v ruff >/dev/null 2>&1; then
  run_step "Lint: ruff (correctness rules)" ruff check .
else
  echo "=== step: Lint: ruff — SKIPPED (ruff not in zero-egress image; runs on real CI)" | tee -a "$LOG"
fi
run_step "Lint: repo self-lint (analysis selfcheck, TFL conventions)" \
  python -m tensorframes_tpu.analysis selfcheck
run_step "Lint: static program diagnostics (examples, strict)" \
  python -m tensorframes_tpu.analysis --demo --strict --explain

run_step "Install (clean-clone package, --no-deps: zero-egress image carries deps)" \
  python -m pip install . --no-deps --no-build-isolation --quiet --target "$SITE"

run_step "Install check (package metadata + import from install target)" \
  env PYTHONPATH="$SITE" python -c "import tensorframes_tpu, importlib.metadata as md; print('installed', md.version('tensorframes-tpu'))"

run_step "Test (8-device virtual CPU mesh)" \
  env TFTPU_OBS_EXPORT="$WORK/obs" TFTPU_FLIGHT_DIR="$WORK/obs/flight" python -m pytest tests/ -x -q

# ci.yml's fusion-off smoke: TFTPU_FUSION=0 (the plan layer's escape
# hatch) must keep the verb/frame/sweep suites — and the whole-pipeline
# map→join→aggregate suite, which honors the ambient knob by design —
# green on the per-stage executor path (test_plan omitted: its fixture
# forces fusion ON; its equivalence sweep runs the fallback internally)
run_step "Fusion-off smoke (TFTPU_FUSION=0 fallback stays green)" \
  env TFTPU_FUSION=0 python -m pytest tests/test_verbs.py tests/test_frame.py tests/test_property_sweep.py tests/test_relational_pipeline.py tests/test_registered_query.py -q

# ci.yml's re-optimization-off smoke (ISSUE 14): TFTPU_REOPT=0 turns
# the adaptive optimizer (aggregate pushdown below joins, join
# reordering, stats-sidecar feedback) off — the relational suites and
# the adaptive equivalence sweeps (which honor the ambient knob;
# engagement-assertion tests skip themselves) must stay green on the
# PR 7 static cost model
run_step "Re-optimization-off smoke (TFTPU_REOPT=0 static cost model stays green)" \
  env TFTPU_REOPT=0 python -m pytest tests/test_relational_pipeline.py tests/test_plan_adaptive.py -q

# ci.yml's kernels-off smoke (ISSUE 12): TFTPU_PALLAS=0 removes the
# straggler pallas kernels from every cost-model decision — the
# XLA/host lowerings they replace must keep every selecting suite
# green (same contract as the fusion-off escape hatch above)
run_step "Kernels-off smoke (TFTPU_PALLAS=0 straggler kernels removed)" \
  env TFTPU_PALLAS=0 python -m pytest tests/test_kernels.py tests/test_segment.py tests/test_verbs.py tests/test_decode.py tests/test_generation.py -q

# ci.yml's lift-off smoke (ISSUE 18): TFTPU_LIFT=0 turns verified UDF
# lifting off — every numpy UDF replays the host-callback path (the
# bit-identity oracle lifts are verified against) as a counted barrier
# with reason `lifting-disabled`, and the UDF + relational suites must
# stay green on that path (test_lifting pins the knob per-test, the
# same shape as test_plan in the fusion-off leg)
run_step "Lift-off smoke (TFTPU_LIFT=0 callback path stays green)" bash -c "
  env TFTPU_LIFT=0 python -c \"
import numpy as np, jax
jax.config.update('jax_platforms', 'cpu')
import tensorframes_tpu as tfs
from tensorframes_tpu.plan import lift
assert tfs.configure().udf_lifting is False, 'TFTPU_LIFT=0 must disable lifting'
def score(x):
    return {'y': x * 2.0 + 1.0}
fr = tfs.frame_from_arrays({'x': np.arange(64, dtype=np.float32)}, num_blocks=4)
blocks = tfs.map_blocks(tfs.numpy_udf(score), fr).blocks()
got = np.concatenate([np.asarray(b['y']) for b in blocks])
assert got.tobytes() == (np.arange(64, dtype=np.float32) * 2.0 + 1.0).tobytes()
rec = lift.lift_log()[-1]
assert rec['lifted'] is False and rec['reason'] == 'lifting-disabled', rec
print('lift-off smoke: callback barrier replayed, reason=lifting-disabled')
\" &&
  env TFTPU_LIFT=0 python -m pytest tests/test_lifting.py tests/test_relational_pipeline.py -q
"

# ci.yml's compile-cache smoke: a tier-1 slice twice against one shared
# persistent store; the second run must report disk hits > 0 in its
# metrics JSONL (docs/compilecache.md cross-process contract)
# (pytest rc 1 — test failures — is tolerated: the Test step owns
# pass/fail; this step's gate is the disk-hit assertion)
run_step "Compile-cache round-trip smoke (second run hits the disk store)" bash -c "
  export TFTPU_COMPILE_CACHE='$WORK/cc-store' &&
  { env TFTPU_OBS_EXPORT='$WORK/cc-obs-1' python -m pytest tests/test_verbs.py -q || [ \$? -eq 1 ]; } &&
  { env TFTPU_OBS_EXPORT='$WORK/cc-obs-2' python -m pytest tests/test_verbs.py -q || [ \$? -eq 1 ]; } &&
  python -c \"
import json
hits = sum(d['value'] for d in map(json.loads, open('$WORK/cc-obs-2/tier1_metrics.jsonl'))
           if d['name'] == 'tftpu_compilecache_hits_total')
assert hits > 0, 'second run reported no persistent-store hits'
print('compilecache smoke: disk hits =', int(hits))
\"
"

# ci.yml's sharded compile-cache smoke (ISSUE 10): the
# tests/test_distributed.py cache worker runs twice in fresh
# subprocesses sharing one TFTPU_COMPILE_CACHE; run 2 must report
# tftpu_compilecache_hits_total > 0 and ZERO XLA compiles from its
# metrics JSONL, with bit-identical sharded results across the runs
run_step "Sharded compile-cache round-trip smoke (unified AOT dispatch)" \
  python -m pytest tests/test_distributed.py::test_sharded_cache_roundtrip_across_processes -q

# ci.yml's observability smoke: the telemetry example must produce all
# three artifacts (Chrome trace, metrics JSONL, step log) and the tier-1
# run above must have exported its own pair
run_step "Observability smoke (telemetry example + artifact check)" bash -c "
  env TFTPU_OBS_EXPORT='$WORK/obs' python -m examples.telemetry &&
  test -s '$WORK/obs/trace.json' &&
  test -s '$WORK/obs/metrics.jsonl' &&
  test -s '$WORK/obs/steps.jsonl' &&
  test -s '$WORK/obs/tier1_metrics.jsonl' &&
  test -s '$WORK/obs/tier1_trace.json' &&
  test -f '$WORK/obs/tier1_diagnostics.jsonl'
"

# ci.yml's serving smoke: a short open-loop load through the continuous
# batcher — hard-gated on steady_state_compiles=0 — whose metrics JSONL
# + trace land next to the other observability artifacts
run_step "Serving smoke (open-loop CPU load, zero steady-state compiles)" bash -c "
  env TFTPU_OBS_EXPORT='$WORK/obs' python -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench.serving_main()\" &&
  test -s '$WORK/obs/serving_metrics.jsonl' &&
  test -s '$WORK/obs/serving_trace.json'
"

# ci.yml's iterative-decode smoke (ISSUE 11): open-loop mixed-length
# prompts through the token-level decode engine + paged KV pool —
# exits nonzero on steady-state compiles, lost requests, or a
# batched-vs-solo bit-identity divergence; the tftpu_decode_* metrics
# JSONL rides the observability artifacts. The KV memory hierarchy leg
# (ISSUE 19) is gated inside the same smoke — prefix-hit TTFT p50
# below cold prefill, swap_resumes > 0 with zero corruption fallbacks,
# bit-identity vs the dense oracle — and the greps prove the
# tftpu_kvswap_* / tftpu_prefix_* families landed in the artifact
run_step "Serving decode smoke (iterative decode engine, paged KV pool)" bash -c "
  env TFTPU_OBS_EXPORT='$WORK/obs' python -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench.serving_decode_main()\" &&
  test -s '$WORK/obs/serving_decode_metrics.jsonl' &&
  test -s '$WORK/obs/serving_decode_trace.json' &&
  grep -q 'tftpu_kvswap_resume_total' '$WORK/obs/serving_decode_metrics.jsonl' &&
  grep -q 'tftpu_prefix_cache_hits_total' '$WORK/obs/serving_decode_metrics.jsonl'
"

# ci.yml's serving-fleet smoke (ISSUE 13): a supervised 2-replica
# serving fleet behind the router ingress, one replica SIGKILLed under
# open-loop load — exits nonzero on any lost request, an unbounded
# post-kill p99 window, or a restarted replica that compiled instead of
# warming from the shared store; tftpu_router_* metrics ride the
# observability artifacts
run_step "Serving fleet smoke (kill -9 a replica under open-loop load)" bash -c "
  env TFTPU_OBS_EXPORT='$WORK/obs' python -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench.serving_fleet_main()\" &&
  test -s '$WORK/obs/serving_fleet_metrics.jsonl' &&
  test -s '$WORK/obs/serving_fleet_trace.json'
"

# ci.yml's out-of-core smoke (ISSUE 15): a CSV dataset ~5x the enforced
# block budget streams a fused map→filter→aggregate chain through the
# blockstore partitioner — exits nonzero when peak RSS outgrows the
# 3.5x-budget cap, when nothing spilled, or when the streamed results
# diverge from the in-memory path; tftpu_blockstore_* metrics ride the
# observability artifacts
run_step "Out-of-core smoke (5x-budget CSV stream, bounded RSS)" bash -c "
  env TFTPU_OBS_EXPORT='$WORK/obs' python -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench.out_of_core_main()\" &&
  test -s '$WORK/obs/out_of_core_metrics.jsonl'
"

# ci.yml's registered-query step (ISSUE 20): the restart smoke (two
# fresh subprocesses, one compile cache — run 2 answers from the
# persistent result store with zero executions and zero compiles, bit-
# identical), then the bench leg's hard gates (warm repeat ≥10x,
# one-chunk refresh <10% of full recompute, FUSION=0 bit-identity)
run_step "Registered-query smoke (result cache survives a restart + bench gates)" bash -c "
  python '$CLONE/dev/registered_query_smoke.py' &&
  env TFTPU_OBS_EXPORT='$WORK/obs' python -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench.registered_query_main()\" &&
  test -s '$WORK/obs/registered_query_metrics.jsonl' &&
  grep -q tftpu_result_cache_hits_total '$WORK/obs/registered_query_metrics.jsonl'
"

# ci.yml's fleet chaos-drill step: kill-rank + hung-collective +
# drop-heartbeat on a 2-process CPU fleet, with the flight black box
# spooled next to the other observability artifacts
run_step "Fleet chaos drill (kill-rank + hung-collective + drop-heartbeat)" \
  env TFTPU_FLIGHT_DIR="$WORK/obs/flight" bash "$CLONE/dev/resilience_drill.sh" --only fleet-chaos

# ci.yml's plan-profile step (ISSUE 17): a tier-1 slice + the multijoin
# pipeline against a pinned compile cache; hard gates are the counted
# latency-driven decision flip (asserted inside _bench_multijoin) and
# at least one EXPLAIN ANALYZE profile sidecar, with the rendered
# report landing next to the other observability artifacts
run_step "Plan-profile sidecars + latency-driven decision-flip smoke (EXPLAIN ANALYZE)" bash -c "
  export TFTPU_COMPILE_CACHE='$WORK/cc-profile' &&
  python -m pytest tests/test_plan_adaptive.py tests/test_relational_pipeline.py -q &&
  python -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench._bench_multijoin(n_rows=200000, iters=1)\" &&
  ls '$WORK/cc-profile/planstats/'*.json >/dev/null &&
  mkdir -p '$WORK/obs/planstats' &&
  cp '$WORK/cc-profile/planstats/'*.json '$WORK/obs/planstats/' &&
  python -m tensorframes_tpu.observability report --profile '$WORK/cc-profile/planstats' | tee '$WORK/obs/plan_profile_report.txt'
"

run_step "Resilience drill (kill–resume, corrupted restore, fault injection)" \
  bash "$CLONE/dev/resilience_drill.sh" --skip fleet-chaos

run_step "Bench smoke (CPU fallback)" bash -c \
  "set -o pipefail; python -c \"import jax; jax.config.update('jax_platforms','cpu'); import bench; bench.main()\" | tee bench_out.txt"

run_step "Bench regression gate (factor 10, alien-runner allowance)" \
  python dev/bench_check.py bench_out.txt --factor 10

# ci.yml's bench-diff step: per-metric trajectory vs the latest
# committed BENCH_r*.json round via `observability diff` — warn-only,
# like CI: a contended rehearsal machine is even noisier than a runner
run_step "Bench diff vs committed round (observability diff, warn-only)" bash -c '
  LATEST=$(ls BENCH_r*.json 2>/dev/null | sort | tail -1)
  if [ -n "$LATEST" ]; then
    python -m tensorframes_tpu.observability diff "$LATEST" bench_out.txt --warn-only
  else
    echo "no committed BENCH_r*.json round; skipping diff"
  fi
'

run_step "Multi-chip dryrun (8 virtual devices)" \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "CI REHEARSAL: ALL STEPS GREEN — log: $LOG" | tee -a "$LOG"
rm -rf "$WORK"
