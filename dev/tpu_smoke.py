"""TPU smoke: validate the accelerator path end to end in one command.

Run this FIRST in any session with (possibly) working TPU hardware:

    python dev/tpu_smoke.py

It probes the backend from a throwaway subprocess (a wedged axon tunnel
hangs jax.devices() forever — bench.py's watchdog pattern), then checks
the pieces that only real-TPU compilation can validate:

1. basic matmul on the chip
2. the pallas segment-sum kernel NON-interpreted (its index maps were
   fixed blind for the x64 literal-typing Mosaic failure — see
   ops/segment.py)
3. the upstream pallas flash-attention kernel under x64-off tracing
4. a keyed aggregate through the fast path
5. a small Inception block scoring via map_blocks
6. int8 KV-cache decode (round 4: the HBM-bound config the cache
   quantization exists for)
7. device-resident sort_values + filter (round 4: lax.sort ordering and
   mask-only-crossing subset, both staying in HBM)

Exit code 0 = all green (prints per-check lines).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def probe(timeout_s: float = 60.0, attempts: int = 3) -> bool:
    """The axon tunnel intermittently hangs a NEW connection even when the
    chip is healthy (observed round 3: one probe hung >150s, the next
    connected in 0.09s) — so retry a few short attempts instead of one
    long one."""
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
            if r.returncode == 0 and "ok" in r.stdout:
                return True
        except subprocess.TimeoutExpired:
            pass
        print(f"probe attempt {i + 1}/{attempts} failed; retrying")
    return False


def main() -> int:
    if not probe():
        print("FAIL backend: accelerator unresponsive (wedged tunnel?)")
        return 1
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    print(f"devices: {jax.devices()}")
    allow_cpu = os.environ.get("TFTPU_SMOKE_ALLOW_CPU") == "1"
    if dev.platform == "cpu":
        if not allow_cpu:
            print("FAIL backend: only CPU visible")
            return 1
        # heal-pipeline rehearsal (dev/tpu_bench_on_heal.sh): run every
        # check the backend permits so the SHELL wiring is validated
        # before the one real window; pallas runs interpreted here
        print("NOTE rehearsal mode: CPU backend accepted, pallas interpreted")
    interp = dev.platform == "cpu"

    t0 = time.time()
    x = jnp.ones((1024, 1024), jnp.bfloat16)
    s = float((x @ x).sum())
    print(f"OK matmul ({s:.0f}) in {time.time() - t0:.1f}s")

    from tensorframes_tpu.ops import segment

    vals = jnp.asarray(np.random.default_rng(0).standard_normal((512, 4)), jnp.float32)
    sids = jnp.asarray(np.random.default_rng(1).integers(0, 16, 512), jnp.int32)
    try:
        t0 = time.time()
        out = segment.segment_sum_pallas(vals, sids, 16, interpret=interp)
        ref = np.zeros((16, 4), np.float32)
        np.add.at(ref, np.asarray(sids), np.asarray(vals))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)
        mode = "interpreted" if interp else "non-interpreted"
        print(f"OK pallas segment-sum ({mode}) in {time.time() - t0:.1f}s")
    except Exception as e:
        print(f"FAIL pallas segment-sum: {type(e).__name__}: {str(e)[:200]}")
        return 1

    from tensorframes_tpu.ops import attention as att

    q = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 4, 512, 128)), jnp.bfloat16
    )
    try:
        t0 = time.time()
        fast = jax.jit(lambda q: att.flash_attention(q, q, q, causal=True))(q)
        slow = att.blockwise_attention(q, q, q, causal=True)
        np.testing.assert_allclose(
            np.asarray(fast, np.float32),
            np.asarray(slow, np.float32),
            rtol=3e-2,
            atol=3e-2,
        )
        print(f"OK flash attention in {time.time() - t0:.1f}s")
    except Exception as e:
        print(f"WARN flash attention fell back/failed: {type(e).__name__}: {str(e)[:160]}")

    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    fr = tfs.frame_from_arrays(
        {"k": rng.integers(0, 32, 10_000), "v": rng.standard_normal(10_000).astype(np.float32)}
    )
    with tfs.with_graph():
        v_input = tfs.block(fr, "v", tf_name="v_input")
        agg = tfs.aggregate(
            tfs.reduce_sum(v_input, axis=0, name="v"), fr.group_by("k")
        )
    total = float(np.asarray(agg.column_values("v")).sum())
    assert abs(total - float(np.asarray(fr.column_values("v")).sum())) < 1e-2
    print(f"OK aggregate fast path (pallas={'on' if segment.pallas_enabled() else 'OFF'})")

    from tensorframes_tpu.models import inception as inc

    cfg = inc.inception_v3(channel_scale=0.25)
    params = inc.init_params(cfg, seed=0)
    images = inc.synthetic_images(cfg, 8, seed=0)
    df = tfs.frame_from_arrays({"images": images}).to_device()
    t0 = time.time()
    out = tfs.map_blocks(lambda images: inc.scoring_program(cfg, params)(images), df)
    lab = np.asarray(out.column_values("label"))
    print(f"OK inception quarter-width scoring ({lab.shape[0]} rows) in {time.time() - t0:.1f}s")

    # round-4 features on the chip: int8 KV-cache decode (the config the
    # quantization exists for) and device-resident sort/filter
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr

    gcfg = gen.gpt_tiny()
    gp = tr.quantize_params(tr.init_params(gcfg, seed=0))
    prompts = np.random.default_rng(3).integers(
        0, gcfg.vocab_size, (2, 4)
    ).astype(np.int32)
    t0 = time.time()
    toks = np.asarray(gen.generate(gcfg, gp, prompts, 6, kv_quant=True))
    assert toks.shape == (2, 6)
    print(f"OK int8-KV decode in {time.time() - t0:.1f}s")

    sf = tfs.frame_from_arrays(
        {"k": rng.standard_normal(4096).astype(np.float32),
         "t": np.arange(4096)}
    ).to_device()
    t0 = time.time()
    srt = sf.sort_values("k")
    [sb] = srt.blocks()
    assert hasattr(sb["k"], "addressable_shards")  # stayed on device
    kv = np.asarray(sb["k"])
    assert (np.diff(kv) >= 0).all()
    flt = sf.filter(lambda k: {"keep": k > 0.0})
    assert (np.asarray(flt.column_values("k")) > 0).all()
    print(f"OK device sort+filter in {time.time() - t0:.1f}s")

    # int8 matmul adjudication (VERDICT r4 #3): time the XLA structural
    # fusion vs the pallas in-kernel-dequant kernel at a gpt_small MLP
    # shape; the printed ratio decides whether config.pallas_int8_matmul
    # should default on. Correctness asserted either way.
    from tensorframes_tpu.ops import quantize as qz

    if dev.platform != "cpu":
        xq = jnp.asarray(
            np.random.default_rng(5).standard_normal((8, 768)), jnp.bfloat16
        )
        wq = qz.quantize(
            jnp.asarray(
                np.random.default_rng(6).standard_normal((768, 3072)),
                jnp.float32,
            )
        )
        from tensorframes_tpu.config import configure

        compiled_ok = False
        try:
            t0 = time.time()
            got_p = jax.block_until_ready(qz.matmul_pallas_int8(xq, wq))
            first_s = time.time() - t0
            compiled_ok = True
        except Exception as e:
            # a Mosaic compile failure is a WARN (the default path
            # stands); a WRONG RESULT below is a hard FAIL
            print(
                f"WARN int8mm pallas did not compile on chip: "
                f"{type(e).__name__}: {str(e)[:160]}"
            )
        if compiled_ok:
            # baseline must be the XLA structural fusion even if the
            # operator exported TFTPU_PALLAS_INT8_MM=1 (the flag this
            # benchmark adjudicates) — force it off around the timing
            from tensorframes_tpu.config import get_config

            prev_flag = get_config().pallas_int8_matmul
            configure(pallas_int8_matmul=False)
            try:

                def t_med(fn):
                    fn()
                    ts = []
                    for _ in range(5):
                        t1 = time.time()
                        jax.block_until_ready(fn())
                        ts.append(time.time() - t1)
                    return sorted(ts)[2]

                t_xla = t_med(lambda: qz.matmul(xq, wq))
                got_x = qz.matmul(xq, wq)
            finally:
                configure(pallas_int8_matmul=prev_flag)
            t_pal = t_med(lambda: qz.matmul_pallas_int8(xq, wq))
            err = np.abs(
                np.asarray(got_p, np.float32) - np.asarray(got_x, np.float32)
            ).max()
            tol = 3e-2 * max(1.0, float(np.abs(np.asarray(got_x)).max()))
            if err > tol:
                print(f"FAIL int8mm pallas WRONG RESULT: max|diff|={err}")
                return 1
            print(
                f"OK int8mm pallas={t_pal * 1e6:.0f}us "
                f"xla={t_xla * 1e6:.0f}us ratio={t_xla / t_pal:.2f}x "
                f"(compile {first_s:.1f}s; >1x → flip "
                "TFTPU_PALLAS_INT8_MM default)"
            )

    # ragged-vs-fixed done-check (VERDICT r4 #5): the wave design must
    # hold ragged map_rows within ~3x of fixed-shape on device backends
    # (the r3 chip run collapsed 23x on per-group round-trips). On CPU
    # the ratio is informational: dispatch dominates there by design.
    lens = np.random.default_rng(7).choice([8, 16, 24, 32], 4096)
    rrows = [{"v": np.arange(int(n), dtype=np.float32)} for n in lens]
    rf2 = tfs.frame_from_rows(rrows, num_blocks=2)
    rprog = tfs.compile_program(lambda v: {"s": v.sum()}, rf2, block=False)
    ff2 = tfs.frame_from_arrays(
        {"v": np.zeros((4096, 32), np.float32)}, num_blocks=2
    )
    fprog = tfs.compile_program(lambda v: {"s": v.sum()}, ff2, block=False)

    def timed(fn):
        """Median of 3 (after a compile-absorbing warm call): a single
        sample would let one scheduler/relay latency spike flip the
        smoke's exit code on a healthy chip."""
        fn()
        samples = []
        for _ in range(3):
            t1 = time.time()
            fn()
            samples.append(time.time() - t1)
        return sorted(samples)[1]

    rt = timed(lambda: np.asarray(tfs.map_rows(rprog, rf2).column_values("s")))
    ft = timed(lambda: np.asarray(tfs.map_rows(fprog, ff2).column_values("s")))
    ratio = rt / ft if ft > 0 else float("inf")
    if dev.platform == "cpu":
        print(f"NOTE ragged_vs_fixed ratio={ratio:.2f}x (CPU: informational)")
    elif ratio <= 3.0:
        print(f"OK ragged_vs_fixed ratio={ratio:.2f}x (target <= 3x)")
    else:
        print(f"FAIL ragged_vs_fixed ratio={ratio:.2f}x exceeds 3x target")
        return 1
    print("ALL GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
