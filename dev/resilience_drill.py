#!/usr/bin/env python
"""Robustness regression drill — one command, nonzero exit on any
unrecovered failure (wired for CI next to bench_check.py).

Exercises the acceptance surface of the resilience subsystem end-to-end:

1. **kill–resume**: a real training subprocess is SIGKILLed mid-loop
   after its first checkpoint, relaunched, and must reach a final state
   bit-identical to an uninterrupted run.
2. **corrupted-checkpoint restore**: the newest step's payload is
   truncated; ``restore`` must reject it (integrity failure) and fall
   back to the previous intact step, and ``verify()`` must flag it.
3. **transient-IO fault absorption**: ``checkpoint.save`` +
   ``io.prefetch.device_put`` faults injected every 2nd attempt must be
   fully absorbed by the retry policies (zero surviving failures).
4. **fleet chaos** (ISSUE 8): on a 2-process CPU subprocess fleet —
   kill-rank (SIGKILL a non-zero rank mid-``run_resumable``; the
   supervisor must restart and the resumed run converge bit-identically),
   hung-collective (delay-collective injection must trip the dispatch
   deadline watchdog with a postmortem naming the missing rank), and
   drop-heartbeat (the silent rank must be detected and the peer abort
   coordinated).

Run: ``python dev/resilience_drill.py`` (or ``dev/resilience_drill.sh``).
``--only NAME`` / ``--skip NAME`` select drills (CI runs the fleet leg
separately with ``TFTPU_FLIGHT_DIR`` armed so the black box ships in the
observability artifact).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
import traceback

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

def drill_kill_resume(root: str) -> str:
    """Delegate to tests/test_crash_resume.py — the single source of the
    SIGKILL/relaunch/compare logic (both the fast single-kill and the
    slow triple-kill variants), so the drill and the test suite can
    never drift apart."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_crash_resume.py",
         "-q", "-p", "no:cacheprovider"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise AssertionError(
            f"crash/resume tests failed:\n{proc.stdout}\n{proc.stderr}"
        )
    return ("kill -9 mid-loop (single + repeated), resumed runs match "
            "uninterrupted bit-for-bit")


def drill_corrupted_restore(root: str) -> str:
    import jax.numpy as jnp
    import numpy as np

    from tensorframes_tpu.checkpoint import Checkpointer

    ck = Checkpointer(os.path.join(root, "corrupt"), backend="npz")
    for s in (2, 4, 6):
        ck.save(s, {"w": jnp.full((4,), float(s), jnp.float32)})
    payload = os.path.join(ck.root, "step_6", "arrays.npz")
    data = open(payload, "rb").read()
    with open(payload, "wb") as f:
        f.write(data[: len(data) // 2])
    if ck.verify(6)[6]["ok"] is not False:
        raise AssertionError("verify() did not flag the truncated step")
    got = ck.restore(like={"w": jnp.zeros(4, jnp.float32)})
    if float(np.asarray(got["w"])[0]) != 4.0:
        raise AssertionError(f"restore did not fall back to step 4: {got}")
    return "truncated newest step rejected; restore fell back to previous intact step"


def drill_transient_faults(root: str) -> str:
    import jax.numpy as jnp
    import numpy as np

    import tensorframes_tpu as tfs
    from tensorframes_tpu import io as tfio
    from tensorframes_tpu.checkpoint import Checkpointer
    from tensorframes_tpu.resilience import RetryPolicy, inject
    from tensorframes_tpu.training import run_resumable

    policy = RetryPolicy(max_attempts=3, backoff=0.005)

    def step(state, batch):
        new = {"w": state["w"] + batch}
        return new, {"loss": new["w"].sum()}

    ck = Checkpointer(os.path.join(root, "flaky"), backend="npz", retry=policy)
    with inject("checkpoint.save", OSError, every_n=2) as save_inj:
        state, ran = run_resumable(
            step, {"w": jnp.zeros(2)}, ck,
            [jnp.full((2,), float(i)) for i in range(8)],
            num_steps=8, save_every=2,
        )
    if ran != 8 or save_inj.fired < 1:
        raise AssertionError(f"save drill: ran={ran}, fired={save_inj.fired}")

    frame = tfs.frame_from_arrays({"x": np.arange(16.0)})
    with inject("io.prefetch.device_put", OSError, every_n=2) as put_inj:
        batches = list(tfio.prefetch_to_device(
            tfio.iterate_batches(frame, batch_size=4), size=2, retry=policy,
        ))
    if len(batches) != 4 or put_inj.fired < 1:
        raise AssertionError(f"prefetch drill: n={len(batches)}, fired={put_inj.fired}")
    return (f"injected faults absorbed (save: {save_inj.fired} fired, "
            f"device_put: {put_inj.fired} fired), zero surviving failures")


_BLACKBOX_WORKER = """
import contextlib, os, sys, time
root = sys.argv[1]
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from tensorframes_tpu.checkpoint import Checkpointer
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.training import run_resumable

rank = int(os.environ["TFTPU_PROCESS_INDEX"])
attempt = int(os.environ.get("TFTPU_FLEET_ATTEMPT", "0"))
stack = contextlib.ExitStack()
if rank == 1 and attempt == 0:
    stack.enter_context(faults.inject(
        "fleet.rank.kill", faults.KillRank, after=2, max_times=1,
    ))

def step(state, batch):
    time.sleep(0.02)
    return {"w": state["w"] + batch}, {"loss": 0.0}

run_resumable(
    step, {"w": jnp.zeros((2,))},
    Checkpointer(os.path.join(root, "ck", f"r{rank}"), backend="npz"),
    [jnp.ones((2,))] * 10, num_steps=10, save_every=2,
)
"""


def drill_fleet_chaos(root: str) -> str:
    """Delegate to tests/test_fleet.py's chaos trio — kill-rank
    restart-resume, hung-collective watchdog, drop-heartbeat detection —
    the single source of the fleet acceptance logic, so the drill and
    the suite cannot drift. When the caller arms ``TFTPU_FLIGHT_DIR``
    (CI does), the drill additionally runs a supervised 2-rank
    kill-rank fleet whose flight spool points AT that directory — the
    pytest legs pin their black boxes to pytest temp dirs, so this is
    what actually ships a fleet black box in the artifact."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_fleet.py", "-q",
         "-p", "no:cacheprovider", "-m", "not slow",
         "-k", "kill9 or hung_collective or drop_heartbeat"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise AssertionError(
            f"fleet chaos tests failed:\n{proc.stdout}\n{proc.stderr}"
        )
    shipped = ""
    flight_dir = os.environ.get("TFTPU_FLIGHT_DIR")
    if flight_dir:
        from tensorframes_tpu.resilience import supervise

        result = supervise(
            [sys.executable, "-c", _BLACKBOX_WORKER, root], 2,
            rendezvous_dir=os.path.join(root, "fleet"),
            flight_dir=os.path.abspath(flight_dir),
            max_restarts=1, grace_s=5.0,
            env={"JAX_PLATFORMS": "cpu",
                 "TFTPU_HEARTBEAT_INTERVAL_S": "0.1",
                 "TFTPU_HEARTBEAT_TIMEOUT_S": "2.0"},
        )
        if not (result.ok and result.restarts == 1):
            raise AssertionError(
                f"black-box fleet exercise did not restart-recover: "
                f"{result}"
            )
        n = len([f for f in os.listdir(flight_dir)
                 if f.startswith(("flight_", "postmortem_"))])
        if n == 0:
            raise AssertionError(
                f"no flight black box landed in {flight_dir}"
            )
        shipped = f"; black box ({n} spool/postmortem files) → {flight_dir}"
    return ("kill-rank restarted+resumed bit-identically, hung collective "
            "tripped the deadline watchdog naming the missing rank, "
            "drop-heartbeat detected with coordinated abort" + shipped)


def drill_serving_fleet(root: str) -> str:
    """Serving-fleet chaos (ISSUE 13): a 2-replica supervised serving
    fleet where the ``serving.replica`` kill site SIGKILLs rank 1
    mid-run (armed via env in the victim — deterministic KillRank
    chaos, no code in the drill doing the killing), under a trickle of
    routed requests. The fleet must answer every request (redriving any
    caught in flight), restart the dead replica from the SHARED compile
    store with zero XLA compiles, and the ``router.dispatch`` Delay
    site must convert a stalled dispatch into a counted 504 — never a
    hang."""
    import json as _json
    import urllib.error
    import urllib.request

    from tensorframes_tpu.resilience import faults
    from tensorframes_tpu.serving import ServingFleet

    cmd = [
        sys.executable, "-m", "tensorframes_tpu.serving.replica_main",
        "--demo", "--max-batch-rows", "8",
    ]
    fleet = ServingFleet(
        cmd, 2,
        rendezvous_dir=os.path.join(root, "serving-fleet"),
        heartbeat_timeout_s=3.0,
        env={
            "JAX_PLATFORMS": "cpu",
            "TFTPU_HEARTBEAT_INTERVAL_S": "0.1",
            # the victim arms its own kill: rank 1, attempt 0, after
            # ~20 main-loop beats (~1s) — the registered
            # `serving.replica` kill_point fires, not an external kill
            "TFTPU_SERVING_CHAOS_KILL_AFTER": "20",
            "TFTPU_SERVING_CHAOS_KILL_RANK": "1",
        },
    )
    fleet.start()

    def post(body, timeout=90):
        req = urllib.request.Request(
            fleet.url + "/v1/score", data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, _json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read() or b"{}")

    try:
        n_ok = 0
        for i in range(60):  # ~3s of trickle load across the kill
            st, _ = post({"inputs": {"x": [[float(i % 5)] * 8]},
                          "deadline_s": 60.0})
            if st != 200:
                raise AssertionError(f"request {i} got {st}, not 200")
            n_ok += 1
            time.sleep(0.05)
        deadline = time.time() + 90.0
        while 1 not in fleet.restart_reports and time.time() < deadline:
            time.sleep(0.1)
        report = fleet.restart_reports.get(1)
        if not report:
            raise AssertionError(
                f"killed replica never restarted: {fleet.status()}"
            )
        if report.get("xla_compiles") != 0 or \
                (report.get("compile_cache_hits") or 0) < 1:
            raise AssertionError(
                f"restarted replica was not store-warm: {report}"
            )
        if fleet.restarts != 1:
            raise AssertionError(
                f"expected exactly 1 restart, got {fleet.restarts}"
            )
        # router.dispatch Delay chaos: the stalled dispatch must become
        # a counted 504 under the request deadline, never a hang
        with faults.inject("router.dispatch", faults.Delay(0.5)):
            st, body = post({"inputs": {"x": [[1.0] * 8]},
                             "deadline_s": 0.2})
        if st != 504:
            raise AssertionError(
                f"delayed dispatch returned {st}, expected 504: {body}"
            )
        return (
            f"{n_ok} routed requests all answered through a "
            f"kill_point SIGKILL of replica 1 (redrives="
            f"{fleet.status()['router']['redrives']}); restart was "
            f"store-warm (0 XLA compiles, "
            f"{report['compile_cache_hits']} store hits, "
            f"{report['recovery_s']}s recovery); delayed dispatch "
            "expired as a counted 504"
        )
    finally:
        fleet.stop()


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--only", action="append", default=[],
                        help="run only the named drill(s)")
    parser.add_argument("--skip", action="append", default=[],
                        help="skip the named drill(s)")
    args = parser.parse_args(argv)
    drills = [
        ("kill-resume", drill_kill_resume),
        ("corrupted-restore", drill_corrupted_restore),
        ("transient-faults", drill_transient_faults),
        ("fleet-chaos", drill_fleet_chaos),
        ("serving-fleet", drill_serving_fleet),
    ]
    names = [n for n, _ in drills]
    for sel in args.only + args.skip:
        if sel not in names:
            print(f"unknown drill {sel!r}; available: {', '.join(names)}")
            return 2
    if args.only:
        drills = [(n, f) for n, f in drills if n in args.only]
    if args.skip:
        drills = [(n, f) for n, f in drills if n not in args.skip]
    failures = 0
    with tempfile.TemporaryDirectory() as root:
        for name, fn in drills:
            t0 = time.time()
            try:
                msg = fn(root)
                print(f"PASS {name} ({time.time() - t0:.1f}s): {msg}")
            except Exception:
                failures += 1
                print(f"FAIL {name} ({time.time() - t0:.1f}s):")
                traceback.print_exc()
    if failures:
        print(f"resilience_drill: {failures}/{len(drills)} drills FAILED")
        return 1
    print(f"resilience_drill: all {len(drills)} drills recovered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
