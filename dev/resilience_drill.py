#!/usr/bin/env python
"""Robustness regression drill — one command, nonzero exit on any
unrecovered failure (wired for CI next to bench_check.py).

Exercises the acceptance surface of the resilience subsystem end-to-end:

1. **kill–resume**: a real training subprocess is SIGKILLed mid-loop
   after its first checkpoint, relaunched, and must reach a final state
   bit-identical to an uninterrupted run.
2. **corrupted-checkpoint restore**: the newest step's payload is
   truncated; ``restore`` must reject it (integrity failure) and fall
   back to the previous intact step, and ``verify()`` must flag it.
3. **transient-IO fault absorption**: ``checkpoint.save`` +
   ``io.prefetch.device_put`` faults injected every 2nd attempt must be
   fully absorbed by the retry policies (zero surviving failures).
4. **fleet chaos** (ISSUE 8): on a 2-process CPU subprocess fleet —
   kill-rank (SIGKILL a non-zero rank mid-``run_resumable``; the
   supervisor must restart and the resumed run converge bit-identically),
   hung-collective (delay-collective injection must trip the dispatch
   deadline watchdog with a postmortem naming the missing rank), and
   drop-heartbeat (the silent rank must be detected and the peer abort
   coordinated).

Run: ``python dev/resilience_drill.py`` (or ``dev/resilience_drill.sh``).
``--only NAME`` / ``--skip NAME`` select drills (CI runs the fleet leg
separately with ``TFTPU_FLIGHT_DIR`` armed so the black box ships in the
observability artifact).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
import traceback

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

def drill_kill_resume(root: str) -> str:
    """Delegate to tests/test_crash_resume.py — the single source of the
    SIGKILL/relaunch/compare logic (both the fast single-kill and the
    slow triple-kill variants), so the drill and the test suite can
    never drift apart."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_crash_resume.py",
         "-q", "-p", "no:cacheprovider"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise AssertionError(
            f"crash/resume tests failed:\n{proc.stdout}\n{proc.stderr}"
        )
    return ("kill -9 mid-loop (single + repeated), resumed runs match "
            "uninterrupted bit-for-bit")


def drill_corrupted_restore(root: str) -> str:
    import jax.numpy as jnp
    import numpy as np

    from tensorframes_tpu.checkpoint import Checkpointer

    ck = Checkpointer(os.path.join(root, "corrupt"), backend="npz")
    for s in (2, 4, 6):
        ck.save(s, {"w": jnp.full((4,), float(s), jnp.float32)})
    payload = os.path.join(ck.root, "step_6", "arrays.npz")
    data = open(payload, "rb").read()
    with open(payload, "wb") as f:
        f.write(data[: len(data) // 2])
    if ck.verify(6)[6]["ok"] is not False:
        raise AssertionError("verify() did not flag the truncated step")
    got = ck.restore(like={"w": jnp.zeros(4, jnp.float32)})
    if float(np.asarray(got["w"])[0]) != 4.0:
        raise AssertionError(f"restore did not fall back to step 4: {got}")
    return "truncated newest step rejected; restore fell back to previous intact step"


def drill_transient_faults(root: str) -> str:
    import jax.numpy as jnp
    import numpy as np

    import tensorframes_tpu as tfs
    from tensorframes_tpu import io as tfio
    from tensorframes_tpu.checkpoint import Checkpointer
    from tensorframes_tpu.resilience import RetryPolicy, inject
    from tensorframes_tpu.training import run_resumable

    policy = RetryPolicy(max_attempts=3, backoff=0.005)

    def step(state, batch):
        new = {"w": state["w"] + batch}
        return new, {"loss": new["w"].sum()}

    ck = Checkpointer(os.path.join(root, "flaky"), backend="npz", retry=policy)
    with inject("checkpoint.save", OSError, every_n=2) as save_inj:
        state, ran = run_resumable(
            step, {"w": jnp.zeros(2)}, ck,
            [jnp.full((2,), float(i)) for i in range(8)],
            num_steps=8, save_every=2,
        )
    if ran != 8 or save_inj.fired < 1:
        raise AssertionError(f"save drill: ran={ran}, fired={save_inj.fired}")

    frame = tfs.frame_from_arrays({"x": np.arange(16.0)})
    with inject("io.prefetch.device_put", OSError, every_n=2) as put_inj:
        batches = list(tfio.prefetch_to_device(
            tfio.iterate_batches(frame, batch_size=4), size=2, retry=policy,
        ))
    if len(batches) != 4 or put_inj.fired < 1:
        raise AssertionError(f"prefetch drill: n={len(batches)}, fired={put_inj.fired}")
    return (f"injected faults absorbed (save: {save_inj.fired} fired, "
            f"device_put: {put_inj.fired} fired), zero surviving failures")


_BLACKBOX_WORKER = """
import contextlib, os, sys, time
root = sys.argv[1]
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from tensorframes_tpu.checkpoint import Checkpointer
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.training import run_resumable

rank = int(os.environ["TFTPU_PROCESS_INDEX"])
attempt = int(os.environ.get("TFTPU_FLEET_ATTEMPT", "0"))
stack = contextlib.ExitStack()
if rank == 1 and attempt == 0:
    stack.enter_context(faults.inject(
        "fleet.rank.kill", faults.KillRank, after=2, max_times=1,
    ))

def step(state, batch):
    time.sleep(0.02)
    return {"w": state["w"] + batch}, {"loss": 0.0}

run_resumable(
    step, {"w": jnp.zeros((2,))},
    Checkpointer(os.path.join(root, "ck", f"r{rank}"), backend="npz"),
    [jnp.ones((2,))] * 10, num_steps=10, save_every=2,
)
"""


def drill_fleet_chaos(root: str) -> str:
    """Delegate to tests/test_fleet.py's chaos trio — kill-rank
    restart-resume, hung-collective watchdog, drop-heartbeat detection —
    the single source of the fleet acceptance logic, so the drill and
    the suite cannot drift. When the caller arms ``TFTPU_FLIGHT_DIR``
    (CI does), the drill additionally runs a supervised 2-rank
    kill-rank fleet whose flight spool points AT that directory — the
    pytest legs pin their black boxes to pytest temp dirs, so this is
    what actually ships a fleet black box in the artifact."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_fleet.py", "-q",
         "-p", "no:cacheprovider", "-m", "not slow",
         "-k", "kill9 or hung_collective or drop_heartbeat"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise AssertionError(
            f"fleet chaos tests failed:\n{proc.stdout}\n{proc.stderr}"
        )
    shipped = ""
    flight_dir = os.environ.get("TFTPU_FLIGHT_DIR")
    if flight_dir:
        from tensorframes_tpu.resilience import supervise

        result = supervise(
            [sys.executable, "-c", _BLACKBOX_WORKER, root], 2,
            rendezvous_dir=os.path.join(root, "fleet"),
            flight_dir=os.path.abspath(flight_dir),
            max_restarts=1, grace_s=5.0,
            env={"JAX_PLATFORMS": "cpu",
                 "TFTPU_HEARTBEAT_INTERVAL_S": "0.1",
                 "TFTPU_HEARTBEAT_TIMEOUT_S": "2.0"},
        )
        if not (result.ok and result.restarts == 1):
            raise AssertionError(
                f"black-box fleet exercise did not restart-recover: "
                f"{result}"
            )
        n = len([f for f in os.listdir(flight_dir)
                 if f.startswith(("flight_", "postmortem_"))])
        if n == 0:
            raise AssertionError(
                f"no flight black box landed in {flight_dir}"
            )
        shipped = f"; black box ({n} spool/postmortem files) → {flight_dir}"
    return ("kill-rank restarted+resumed bit-identically, hung collective "
            "tripped the deadline watchdog naming the missing rank, "
            "drop-heartbeat detected with coordinated abort" + shipped)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--only", action="append", default=[],
                        help="run only the named drill(s)")
    parser.add_argument("--skip", action="append", default=[],
                        help="skip the named drill(s)")
    args = parser.parse_args(argv)
    drills = [
        ("kill-resume", drill_kill_resume),
        ("corrupted-restore", drill_corrupted_restore),
        ("transient-faults", drill_transient_faults),
        ("fleet-chaos", drill_fleet_chaos),
    ]
    names = [n for n, _ in drills]
    for sel in args.only + args.skip:
        if sel not in names:
            print(f"unknown drill {sel!r}; available: {', '.join(names)}")
            return 2
    if args.only:
        drills = [(n, f) for n, f in drills if n in args.only]
    if args.skip:
        drills = [(n, f) for n, f in drills if n not in args.skip]
    failures = 0
    with tempfile.TemporaryDirectory() as root:
        for name, fn in drills:
            t0 = time.time()
            try:
                msg = fn(root)
                print(f"PASS {name} ({time.time() - t0:.1f}s): {msg}")
            except Exception:
                failures += 1
                print(f"FAIL {name} ({time.time() - t0:.1f}s):")
                traceback.print_exc()
    if failures:
        print(f"resilience_drill: {failures}/{len(drills)} drills FAILED")
        return 1
    print(f"resilience_drill: all {len(drills)} drills recovered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
