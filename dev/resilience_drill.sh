#!/usr/bin/env bash
# Robustness regression drill (CI entry point): kill–resume exercise,
# corrupted-checkpoint restore, and injected transient-IO faults under
# retry. Exits nonzero on any unrecovered failure.
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python dev/resilience_drill.py "$@"
