#!/bin/bash
# Probe the axon TPU backend on a 2-6 min cadence (120s sleep + up to
# 240s probe timeout when the backend hangs); write status to
# dev/tpu_probe.log and touch dev/TPU_ALIVE when a probe succeeds.
#
# SINGLETON: round 4 ended with two copies of this loop racing (a
# manual launch plus the heal script's re-arm). The flock below makes
# any second copy exit immediately, so re-arms can never stack.
exec 9>/root/repo/dev/.tpu_probe.lock
flock -n 9 || exit 0
while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 240 python -c "import jax; jax.devices(); print('ok')" >/dev/null 2>&1; then
    echo "$ts ALIVE" >> /root/repo/dev/tpu_probe.log
    touch /root/repo/dev/TPU_ALIVE
    exit 0
  else
    echo "$ts wedged" >> /root/repo/dev/tpu_probe.log
  fi
  sleep 120
done
