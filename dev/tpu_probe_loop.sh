#!/bin/bash
# Probe the axon TPU backend on a 2-6 min cadence (120s sleep + up to
# 240s probe timeout when the backend hangs); write status to
# dev/tpu_probe.log and touch dev/TPU_ALIVE when a probe succeeds.
#
# SINGLETON: round 4 ended with two copies of this loop racing (a
# manual launch plus the heal script's re-arm). The flock below makes
# any second copy exit immediately, so re-arms can never stack.
#
# SUPERVISION (round 5 lesson): this container has no init/cron, and
# background processes die with the shell session that launched them.
# Relaunching is IDEMPOTENT (second copies exit 0 on the flock), so the
# durable pattern is: relaunch this script at every opportunity — the
# first command of any session, before long waits, from any loop:
#     nohup bash dev/tpu_probe_loop.sh >/dev/null 2>&1 &
# On a healthy probe the process execs straight into the capture
# pipeline (dev/tpu_bench_on_heal.sh), so whichever copy is alive at
# heal time does the whole job. bench.py also self-probes, so a driver
# bench run during a healthy window captures TPU regardless.
exec 9>/root/repo/dev/.tpu_probe.lock
flock -n 9 || exit 0
while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 240 python -c "import jax; jax.devices(); print('ok')" >/dev/null 2>&1; then
    echo "$ts ALIVE" >> /root/repo/dev/tpu_probe.log
    touch /root/repo/dev/TPU_ALIVE
    # become the capture pipeline directly (round 5: separately-launched
    # watcher processes proved mortal across session shells, so the
    # probing process carries the capture itself; a supervisor relaunch
    # keeps A probe loop alive — second copies exit on the flock).
    # Closing fd 9 on the exec releases the probe lock in one stroke
    # (no leaked lock fd into the pipeline's children) so the heal
    # script's flapping-tunnel re-arm can take it again.
    exec bash /root/repo/dev/tpu_bench_on_heal.sh 9>&-
  else
    echo "$ts wedged" >> /root/repo/dev/tpu_probe.log
  fi
  sleep 120
done
