#!/usr/bin/env python
"""Release helper (≙ dev/release.py:1-115 in the reference): bump the
version in pyproject.toml and tensorframes_tpu/__init__.py, commit, and
tag. Non-interactive; prints the commands it would run with --dry-run.
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
FILES = {
    ROOT / "pyproject.toml": r'(version = ")([^"]+)(")',
    ROOT / "tensorframes_tpu" / "__init__.py": r'(__version__ = ")([^"]+)(")',
}


def current_version() -> str:
    text = (ROOT / "pyproject.toml").read_text()
    m = re.search(FILES[ROOT / "pyproject.toml"], text)
    if not m:
        sys.exit("could not find version in pyproject.toml")
    return m.group(2)


def bump(version: str, part: str) -> str:
    major, minor, patch = (int(x) for x in version.split("."))
    if part == "major":
        return f"{major + 1}.0.0"
    if part == "minor":
        return f"{major}.{minor + 1}.0"
    return f"{major}.{minor}.{patch + 1}"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("part", choices=["major", "minor", "patch"])
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument("--no-tag", action="store_true")
    args = ap.parse_args()

    old = current_version()
    new = bump(old, args.part)
    print(f"{old} -> {new}")
    for path, pattern in FILES.items():
        text = path.read_text()
        updated, n = re.subn(pattern, rf"\g<1>{new}\g<3>", text)
        if n != 1:
            sys.exit(f"expected exactly one version in {path}, found {n}")
        if args.dry_run:
            print(f"would update {path}")
        else:
            path.write_text(updated)
    cmds = [["git", "add"] + [str(p) for p in FILES]]
    cmds.append(["git", "commit", "-m", f"release: v{new}"])
    if not args.no_tag:
        cmds.append(["git", "tag", f"v{new}"])
    for cmd in cmds:
        if args.dry_run:
            print("would run:", " ".join(cmd))
        else:
            subprocess.run(cmd, check=True, cwd=ROOT)


if __name__ == "__main__":
    main()
