"""Registered-query restart smoke (ISSUE 20): the cross-process
contract of the persistent result cache.

Two FRESH subprocesses share one TFTPU_COMPILE_CACHE (and one CSV scan
directory). Run 1 registers a map→aggregate endpoint, executes (cold:
every chunk runs), and publishes per-chunk partials + the result table
into ``<cache>/results``. Run 2 registers the SAME pipeline and must
answer from the store alone: result-cache hits > 0, ZERO chunk
executions, ZERO XLA compiles (the probe only parses one chunk and
inspects the plan — nothing dispatches), and a bit-identical table.
Evidence rides each run's metrics JSONL (``tftpu_result_cache_*``,
``tftpu_executor_compile_seconds``) — the same artifact CI uploads.

Usage: ``python dev/registered_query_smoke.py`` (driver; exits nonzero
on any gate). The ``--worker`` form is the subprocess half.
"""

import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker(data_dir: str, cache_dir: str, out_npz: str,
            obs_dir: str) -> None:
    sys.path.insert(0, ROOT)
    import numpy as np

    import tensorframes_tpu as tfs
    from tensorframes_tpu.observability.metrics import REGISTRY
    from tensorframes_tpu.serving import QueryEndpoint, QuerySource

    tfs.configure(compilation_cache_dir=cache_dir)

    def build(f):
        f1 = tfs.map_blocks(lambda v: {"y": v * 5 - 2}, f)
        with tfs.with_graph():
            y_in = tfs.block(f1, "y", tf_name="y_input")
            return tfs.aggregate(
                [tfs.reduce_sum(y_in, axis=0, name="y")],
                f1.group_by("k"),
            )

    q = QueryEndpoint(
        "smoke", QuerySource(path=data_dir, kind="csv"), build
    )
    table = q.execute()
    os.makedirs(obs_dir, exist_ok=True)
    REGISTRY.write_jsonl(
        os.path.join(obs_dir, "registered_query_metrics.jsonl")
    )
    np.savez(out_npz, **{k: np.asarray(v) for k, v in table.items()})
    print(json.dumps({"cache_stats": q.cache_stats()}))


def _metric_total(obs_dir: str, name: str) -> float:
    path = os.path.join(obs_dir, "registered_query_metrics.jsonl")
    total = 0.0
    with open(path) as fh:
        for line in fh:
            d = json.loads(line)
            if d["name"] == name:
                total += d.get("value", d.get("count", 0.0)) or 0.0
    return total


def main() -> None:
    import numpy as np

    tmp = tempfile.mkdtemp(prefix="tftpu_regq_smoke_")
    data = os.path.join(tmp, "data")
    os.makedirs(data)
    rng = np.random.default_rng(7)
    for i in range(8):
        with open(os.path.join(data, f"part-{i:03d}.csv"), "w") as fh:
            fh.write("k,v\n")
            for k, v in zip(rng.integers(0, 16, 5000),
                            rng.integers(-99, 99, 5000)):
                fh.write(f"{k},{v}\n")
    cache = os.path.join(tmp, "cache")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("TFTPU_COMPILE_CACHE", None)  # the worker configures it
    stats = []
    for run in (1, 2):
        obs = os.path.join(tmp, f"obs-{run}")
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker",
             data, cache, os.path.join(tmp, f"run{run}.npz"), obs],
            env=env, cwd=ROOT, timeout=300, check=True,
            capture_output=True, text=True,
        )
        stats.append(json.loads(out.stdout.strip().splitlines()[-1]))
    cs1, cs2 = (s["cache_stats"] for s in stats)
    assert cs1["chunks_executed"] == 8, cs1
    assert cs2["hits"] > 0, f"run 2 never hit the result cache: {cs2}"
    assert cs2["misses"] == 0 and cs2["chunks_executed"] == 0, (
        f"run 2 executed instead of answering from the store: {cs2}"
    )
    obs2 = os.path.join(tmp, "obs-2")
    jl_hits = _metric_total(obs2, "tftpu_result_cache_hits_total")
    assert jl_hits > 0, "run 2 metrics JSONL reported no cache hits"
    compiles = _metric_total(obs2, "tftpu_executor_compile_seconds")
    assert compiles == 0, (
        f"run 2 compiled ({compiles} executor compile events) — the "
        "warm restart must be zero-compile"
    )
    with np.load(os.path.join(tmp, "run1.npz")) as a, \
            np.load(os.path.join(tmp, "run2.npz")) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            assert a[k].dtype == b[k].dtype, (k, a[k].dtype, b[k].dtype)
            assert np.array_equal(a[k], b[k]), f"column {k!r} diverged"
    print(
        "registered-query smoke: run2 hits={:.0f} chunks_executed=0 "
        "compiles=0 bit-identical".format(jl_hits)
    )


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(*sys.argv[2:6])
    else:
        main()
