"""Resumable training driver — failure detection / elastic recovery.

The reference has none of this (SURVEY.md §5: "Failure detection /
elastic recovery: none in-repo; entirely delegated to Spark task
retry/lineage"). On TPU the failure model is different: preemption kills
the whole single-controller program, and recovery means *restart from the
latest checkpoint* — so the recovery primitive is a checkpoint-integrated
training loop, not per-task retry.

``run_resumable`` wraps a jitted step function with periodic
checkpointing (Checkpointer) and resume-on-restart: a relaunched process
calls it with the same arguments and continues from the last saved step.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Tuple

from .checkpoint import Checkpointer
from .utils import get_logger

logger = get_logger(__name__)


def run_resumable(
    step_fn: Callable[[Any, Any], Tuple[Any, Any]],
    init_state: Any,
    checkpointer: Checkpointer,
    batches: Iterable,
    num_steps: int,
    save_every: int = 100,
    on_step: Optional[Callable[[int, Any], None]] = None,
) -> Tuple[Any, int]:
    """Run up to ``num_steps`` of ``state, metrics = step_fn(state, batch)``,
    checkpointing every ``save_every`` steps and resuming from the latest
    checkpoint if one exists.

    ``init_state`` doubles as the restore template (same pytree structure).
    ``batches`` is consumed from the beginning on every (re)start; steps
    already completed per the checkpoint are skipped so the data order
    stays deterministic across preemptions. Returns (final_state,
    steps_run_in_this_process).
    """
    start_step = 0
    state = init_state
    latest = checkpointer.latest_step()
    if latest is not None:
        state = checkpointer.restore(step=latest, like=init_state)
        start_step = latest
        logger.info("run_resumable: resuming from step %d", start_step)
    if start_step >= num_steps:
        return state, 0  # already complete: don't touch the iterator

    ran = 0
    step = start_step
    it = iter(batches)
    # skip batches consumed before the preemption (deterministic replay);
    # a dataset shorter than the checkpointed progress is a caller bug and
    # must not be silently absorbed
    for i in range(start_step):
        try:
            next(it)
        except StopIteration:
            raise ValueError(
                f"run_resumable: dataset exhausted at batch {i} while "
                f"skipping to checkpointed step {start_step} — the batches "
                "passed on resume are shorter than the original run's"
            ) from None
    try:
        while step < num_steps:
            try:
                batch = next(it)
            except StopIteration:
                break
            state, metrics = step_fn(state, batch)
            step += 1
            ran += 1
            if on_step is not None:
                on_step(step, metrics)
            if save_every and step % save_every == 0:
                checkpointer.save(step, state)
    except BaseException:
        # best-effort barrier checkpoint on the way down (preemption
        # SIGTERM arrives as an exception in most launchers)
        try:
            checkpointer.save(step, state)
            logger.warning("run_resumable: saved emergency checkpoint @ %d", step)
        except Exception:  # pragma: no cover
            logger.exception("run_resumable: emergency checkpoint failed")
        raise
    if save_every and step % save_every != 0 and ran:
        checkpointer.save(step, state)
    return state, ran
