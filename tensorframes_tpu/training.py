"""Resumable training driver — failure detection / elastic recovery.

The reference has none of this (SURVEY.md §5: "Failure detection /
elastic recovery: none in-repo; entirely delegated to Spark task
retry/lineage"). On TPU the failure model is different: preemption kills
the whole single-controller program, and recovery means *restart from the
latest checkpoint* — so the recovery primitive is a checkpoint-integrated
training loop, not per-task retry.

``run_resumable`` wraps a jitted step function with periodic
checkpointing (Checkpointer) and resume-on-restart: a relaunched process
calls it with the same arguments and continues from the last saved step.
A ``guard=`` policy (resilience subsystem) additionally detects
non-finite losses/states and skips, rolls back, or aborts — the NaN
tripwire the silent-divergence failure mode needs.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Tuple, Union

from .checkpoint import Checkpointer
from .observability.steps import StepTelemetry
from .resilience import fleet as _fleet
from .resilience.faults import kill_point
from .resilience.guards import StepGuard
from .utils import get_logger

logger = get_logger(__name__)


def run_resumable(
    step_fn: Callable[[Any, Any], Tuple[Any, Any]],
    init_state: Any,
    checkpointer: Checkpointer,
    batches: Iterable,
    num_steps: int,
    save_every: int = 100,
    on_step: Optional[Callable[[int, Any], None]] = None,
    skip_consumed: bool = True,
    guard: Optional[Union[StepGuard, str]] = None,
    resume_from: Optional[Tuple[int, Any]] = None,
    telemetry: Optional[StepTelemetry] = None,
) -> Tuple[Any, int]:
    """Run up to ``num_steps`` of ``state, metrics = step_fn(state, batch)``,
    checkpointing every ``save_every`` steps and resuming from the latest
    checkpoint if one exists.

    ``init_state`` doubles as the restore template (same pytree structure).
    ``batches`` is consumed from the beginning on every (re)start; steps
    already completed per the checkpoint are skipped so the data order
    stays deterministic across preemptions. Returns (final_state,
    steps_run_in_this_process).

    Failure handling: a best-effort checkpoint is written on normal loop
    exit AND before an uncaught exception propagates, so a crash between
    ``save_every`` boundaries loses at most the in-flight step
    (``save_every=0`` disables only the periodic saves). ``guard`` — a
    :class:`~tensorframes_tpu.resilience.StepGuard` or one of its policy
    strings (``"skip"`` / ``"rollback"`` / ``"raise"``) — inspects every
    update for non-finite losses/states and recovers per its policy; the
    restored checkpoint seeds its rollback baseline. ``telemetry`` — a
    :class:`~tensorframes_tpu.observability.StepTelemetry` — records
    per-step time/loss/rows-per-sec to the metrics registry, a JSONL
    step log, and (when tracing is enabled) the event timeline; it runs
    after ``on_step``, with the same (global step, metrics) arguments.
    """
    # fleet awareness: under a supervised fleet (TFTPU_FLEET_DIR — the
    # supervise() launcher arms it for its children) this loop
    # heartbeats and watches its peers; a plain single-process run pays
    # a single env read. This is what makes kill -9 of ANY rank
    # mid-run_resumable converge: survivors abort bounded, the
    # supervisor restarts, and this resume path replays
    # deterministically from the latest intact checkpoint.
    _fleet.enroll()
    if guard is not None:
        guard = StepGuard.coerce(guard)
    start_step = 0
    state = init_state
    if resume_from is not None:
        # the caller already restored (train_on_frame does, so it can
        # position its iterator to the step that actually loaded without
        # a second full checkpoint read)
        start_step, state = resume_from
        logger.info("run_resumable: resuming from step %d (caller-restored)",
                    start_step)
    elif checkpointer.latest_step() is not None:
        # restore_latest, not restore(step=latest): a step torn by the
        # previous crash must fall back to the prior intact one, and the
        # batch replay below must skip to the step that actually loaded
        start_step, state = checkpointer.restore_latest(like=init_state)
        logger.info("run_resumable: resuming from step %d", start_step)
    if guard is not None:
        guard.seed(start_step, state)
    if start_step >= num_steps:
        return state, 0  # already complete: don't touch the iterator

    ran = 0
    step = start_step
    it = iter(batches)
    # skip batches consumed before the preemption (deterministic replay);
    # a dataset shorter than the checkpointed progress is a caller bug and
    # must not be silently absorbed. Callers that pre-position the
    # iterator (train_on_frame skips host-side, before any device
    # transfer) pass skip_consumed=False.
    for i in range(start_step if skip_consumed else 0):
        try:
            next(it)
        except StopIteration:
            raise ValueError(
                f"run_resumable: dataset exhausted at batch {i} while "
                f"skipping to checkpointed step {start_step} — the batches "
                "passed on resume are shorter than the original run's"
            ) from None
    try:
        while step < num_steps:
            # kill-rank chaos site: a drill can SIGKILL this rank at an
            # exact step boundary (un-armed cost: one dict check)
            kill_point()
            try:
                batch = next(it)
            except StopIteration:
                break
            candidate, metrics = step_fn(state, batch)
            if guard is not None:
                # admit BEFORE committing to `state`: if the guard
                # raises, `state` still holds the last good pytree, so
                # the emergency checkpoint below cannot persist NaNs
                candidate, _admitted = guard.admit(
                    step + 1, candidate, metrics, prev_state=state
                )
            state = candidate
            step += 1
            ran += 1
            if on_step is not None:
                on_step(step, metrics)
            if telemetry is not None:
                telemetry(step, metrics)
            if save_every and step % save_every == 0:
                checkpointer.save(step, state)
    except BaseException:
        # best-effort barrier checkpoint on the way down (preemption
        # SIGTERM arrives as an exception in most launchers): save
        # BEFORE re-raising so the relaunch resumes at the crash point
        try:
            checkpointer.save(step, state)
            logger.warning("run_resumable: saved emergency checkpoint @ %d", step)
        except Exception:  # pragma: no cover
            logger.exception("run_resumable: emergency checkpoint failed")
        raise
    # best-effort final checkpoint on loop exit — also when periodic
    # saves are disabled (save_every=0), so a later relaunch never
    # replays completed work
    if ran and (not save_every or step % save_every != 0):
        checkpointer.save(step, state)
    return state, ran


def cast_float_leaves(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype`` (non-float
    leaves pass through) — the mixed-precision parameter cast shared by
    the grad-accum and sharded train steps."""
    import jax
    import jax.numpy as jnp

    dt_ = jnp.dtype(dtype)
    return jax.tree_util.tree_map(
        lambda x: x.astype(dt_)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


def make_grad_accum_step(
    loss_fn: Callable,
    tx,
    accum_steps: int,
    compute_dtype: Optional[str] = None,
) -> Callable:
    """Gradient accumulation: one optimizer update from ``accum_steps``
    microbatches, averaged — the standard lever when the global batch
    doesn't fit HBM (complements ``jax.checkpoint`` rematerialization).

    ``loss_fn(params, batch) -> scalar``; the returned
    ``step(params, opt_state, batch)`` expects ``batch`` pytree leaves
    with a leading dim divisible by ``accum_steps`` and scans over the
    microbatch splits — one compiled program, O(1) activation memory in
    the number of microbatches.

    ``compute_dtype`` (e.g. ``"bfloat16"``) enables MIXED-PRECISION
    training the TPU way: float params are cast to the compute dtype
    inside the differentiated function, so forward+backward run on the
    MXU at bf16 rate while the params the optimizer updates stay f32
    master weights (autodiff through the cast yields f32 gradients).
    bf16 shares f32's exponent range, so no loss scaling is needed —
    the GPU-era scaled-fp16 machinery has no TPU counterpart.
    """
    import jax
    import jax.numpy as jnp
    import optax

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    if compute_dtype is not None:
        def run_loss(p, mb):
            return loss_fn(cast_float_leaves(p, compute_dtype), mb)
    else:
        run_loss = loss_fn

    def step(params, opt_state, batch):
        def to_micro(x):
            n = x.shape[0]
            if n % accum_steps:
                raise ValueError(
                    f"batch dim {n} not divisible by accum_steps={accum_steps}"
                )
            return x.reshape((accum_steps, n // accum_steps) + x.shape[1:])

        micro = jax.tree_util.tree_map(to_micro, batch)
        zero = jax.tree_util.tree_map(jnp.zeros_like, params)

        def accum(carry, mb):
            g_sum, l_sum = carry
            loss, g = jax.value_and_grad(run_loss)(params, mb)
            g_sum = jax.tree_util.tree_map(jnp.add, g_sum, g)
            # cast into the f32 carry: under the package's default x64 a
            # float64 loss must not change the scan carry dtype
            return (g_sum, l_sum + loss.astype(jnp.float32)), None

        (g_sum, l_sum), _ = jax.lax.scan(
            accum, (zero, jnp.zeros((), jnp.float32)), micro
        )
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps, g_sum)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, l_sum / accum_steps

    return jax.jit(step)


def train_on_frame(
    step_fn: Callable[[Any, Any], Tuple[Any, Any]],
    init_state: Any,
    frame,
    columns,
    batch_size: int,
    num_steps: int,
    checkpointer: Optional[Checkpointer] = None,
    save_every: int = 100,
    shuffle: bool = True,
    seed: int = 0,
    prefetch: int = 2,
    on_step: Optional[Callable[[int, Any], None]] = None,
    guard: Optional[Union[StepGuard, str]] = None,
    telemetry: Optional[StepTelemetry] = None,
) -> Tuple[Any, int]:
    """Train straight off a frame: epoch-cycling minibatches from the
    frame's columns (reshuffled per epoch), background host→device
    prefetch, and — when a ``checkpointer`` is passed — preemption-safe
    resume through :func:`run_resumable`.

    This closes the loop the reference never had (inference-only): the
    same columnar frame that feeds the verbs feeds a training step.
    ``step_fn(state, batch)`` gets ``{column: device array[batch, ...]}``.
    Batches are uniform (the per-epoch remainder is dropped) so one XLA
    executable serves every step. ``on_step(i, metrics)`` receives the
    GLOBAL step index — after a resume it continues from the checkpoint
    (e.g. 701), matching ``run_resumable``. ``guard`` is forwarded to
    :func:`run_resumable` (non-finite-step detection; requires a
    ``checkpointer`` only for the resume leg — without one the guard
    still runs in the plain loop below). ``telemetry`` — a
    :class:`~tensorframes_tpu.observability.StepTelemetry` — records
    per-step time/loss/rows-per-sec; its ``rows_per_step`` is filled in
    from ``batch_size`` when unset, so rows/s works out of the box.
    """
    import itertools

    from .io import iterate_batches, prefetch_to_device

    if telemetry is not None and telemetry.rows_per_step is None:
        telemetry.rows_per_step = batch_size

    def batches():
        epoch = 0
        while True:
            yield from iterate_batches(
                frame,
                columns,
                batch_size=batch_size,
                shuffle=shuffle,
                seed=seed + epoch,
                drop_remainder=True,
            )
            epoch += 1

    raw = batches()
    try:
        if checkpointer is not None:
            # restore FIRST (restore_latest falls back past corrupted
            # steps and reports the step that actually loaded), then
            # fast-forward the replay HOST-SIDE to exactly that step —
            # before the prefetch wrapper exists, so resume never pays
            # device transfers for batches it only discards, and the
            # skip count can never desynchronize from the restored state
            resume = None
            latest = 0
            if checkpointer.latest_step() is not None:
                latest, restored = checkpointer.restore_latest(like=init_state)
                resume = (latest, restored)
            for _ in itertools.islice(raw, min(latest, num_steps)):
                pass
            stream = (
                prefetch_to_device(raw, size=prefetch) if prefetch else raw
            )
            return run_resumable(
                step_fn,
                init_state,
                checkpointer,
                stream,
                num_steps,
                save_every=save_every,
                on_step=on_step,
                skip_consumed=False,
                guard=guard,
                resume_from=resume,
                telemetry=telemetry,
            )
        if guard is not None:
            guard = StepGuard.coerce(guard)
            guard.seed(0, init_state)
        stream = prefetch_to_device(raw, size=prefetch) if prefetch else raw
        state = init_state
        ran = 0
        for batch in itertools.islice(stream, num_steps):
            prev_state = state
            state, metrics = step_fn(state, batch)
            ran += 1
            if guard is not None:
                state, _ = guard.admit(ran, state, metrics, prev_state=prev_state)
            if on_step is not None:
                on_step(ran, metrics)
            if telemetry is not None:
                telemetry(ran, metrics)
        return state, ran
    finally:
        # the epoch stream is infinite: close it (and the prefetch
        # generator wrapping it) so the worker thread and its staged HBM
        # buffers release now, not at GC time
        import time as _time

        try:
            stream.close()  # type: ignore[union-attr]
        except Exception:
            pass
        # the prefetch worker may still be mid-next(raw) for an instant
        # after its stop flag sets; retry briefly, then leave the
        # suspended generator to GC (the worker has already exited)
        for _ in range(100):
            try:
                raw.close()
                break
            except ValueError:
                _time.sleep(0.01)
