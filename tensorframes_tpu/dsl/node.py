"""The DSL expression graph: nodes, naming, and compilation to Programs.

This is the TPU-native analogue of the reference's two graph-building
surfaces at once:

* the Python placeholder style (``x = tfs.block(df, "x"); z = tf.add(x, 3,
  name='z')``, README.md:69-76) — here ``block``/``row`` return DSL nodes
  that support operators and named ops;
* the Scala DSL (``dsl/package.scala:17-134``: placeholder, constant,
  zeros, ones, fill, identity, add, div, reduce_sum, reduce_min; operator
  sugar and ``named``; ``dsl/Operation.scala``) with its scoped, counted
  naming context (``dsl/Paths.scala:17-55`` — ``scope/name``, dedup as
  ``name_1``, ``name_2``).

Instead of emitting ``NodeDef`` protos to feed a TF Session, a fetch list
compiles directly to a :class:`~tensorframes_tpu.program.Program` — a
jit-traceable function evaluated under XLA. Graph *state* differs from the
reference deliberately: naming counters live in an explicit context object
(with a default global instance) and ``with_graph`` scopes/resets it, which
doubles as the test-hygiene reset (≙ ``GraphScoping.testGraph``,
dsl/GraphScoping.scala:8-15). Unlike the reference's ``Paths`` the context
can be swapped thread-locally, removing the documented thread-unsafety
(dsl/Paths.scala:10-11).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .. import dtypes as dt
from ..program import Program, TensorSpec
from ..shape import Shape


class GraphContext:
    """Naming state: scope stack + per-name dedup counters."""

    def __init__(self):
        self.scopes: List[str] = []
        self.counters: Dict[str, int] = {}

    def reset(self):
        self.scopes.clear()
        self.counters.clear()

    def qualify(self, name: str) -> str:
        return "/".join(self.scopes + [name]) if self.scopes else name

    def unique(self, base: str) -> str:
        """TF-style dedup: first use keeps ``base``, later uses get
        ``base_1``, ``base_2``, … (≙ dsl/Paths.scala:40-55)."""
        qualified = self.qualify(base)
        n = self.counters.get(qualified, 0)
        self.counters[qualified] = n + 1
        return qualified if n == 0 else f"{qualified}_{n}"


_tls = threading.local()


def current_graph() -> GraphContext:
    g = getattr(_tls, "graph", None)
    if g is None:
        g = GraphContext()
        _tls.graph = g
    return g


@contextlib.contextmanager
def with_graph():
    """Fresh naming context for the duration of the block (recommended
    scoping practice, README.md:133-135; test hygiene ≙ GraphScoping)."""
    old = getattr(_tls, "graph", None)
    _tls.graph = GraphContext()
    try:
        yield _tls.graph
    finally:
        _tls.graph = old


@contextlib.contextmanager
def scope(name: str):
    """Name scope: nodes created inside get ``name/`` prefixed
    (≙ dsl/package.scala:32-33, Paths.withScope)."""
    g = current_graph()
    g.scopes.append(name)
    try:
        yield
    finally:
        g.scopes.pop()


ConstLike = Union[int, float, bool, list, tuple, np.ndarray]


class Node:
    """One DSL graph node.

    ``eval_fn`` consumes the evaluated parent arrays and produces this
    node's array; placeholders instead read from the feed dict at
    compile time.
    """

    def __init__(
        self,
        op: str,
        parents: Sequence["Node"],
        eval_fn: Optional[Callable],
        name: Optional[str] = None,
        dtype: Optional[dt.ScalarType] = None,
        shape: Optional[Shape] = None,
        reduce_axis: Optional[int] = None,
    ):
        g = current_graph()
        self.op = op
        self.parents = list(parents)
        self.eval_fn = eval_fn
        self.name = g.unique(name) if name else g.unique(op)
        self.dtype = dtype
        self.shape = shape
        # set for algebraic reducers (reduce_sum/min/max/mean over axis 0);
        # lets `aggregate` lower to vectorized segment ops.
        self.reduce_axis = reduce_axis
        self.is_placeholder = op == "placeholder"

    # -- naming -------------------------------------------------------------
    def named(self, name: str) -> "Node":
        """Rename (≙ the DSL's ``named``, dsl/Operation.scala:30-38)."""
        self.name = current_graph().qualify(name)
        return self

    def __repr__(self):
        return f"Node({self.op}:{self.name})"

    # -- operator sugar (≙ dsl/Implicits + Operation `+` `/`) ----------------
    def _lift(self, other) -> "Node":
        if isinstance(other, Node):
            return other
        return constant(other)

    def __add__(self, other):
        return add(self, self._lift(other))

    def __radd__(self, other):
        return add(self._lift(other), self)

    def __sub__(self, other):
        return sub(self, self._lift(other))

    def __rsub__(self, other):
        return sub(self._lift(other), self)

    def __mul__(self, other):
        return mul(self, self._lift(other))

    def __rmul__(self, other):
        return mul(self._lift(other), self)

    def __truediv__(self, other):
        return div(self, self._lift(other))

    def __rtruediv__(self, other):
        return div(self._lift(other), self)

    def __neg__(self):
        return unary("neg", jnp.negative, self)

    def __pow__(self, other):
        return binary("pow", jnp.power, self, self._lift(other))


def placeholder(
    dtype, shape, name: Optional[str] = None
) -> Node:
    """Explicit placeholder (≙ dsl/package.scala:45-50; tf.placeholder in
    the Python path). ``shape`` entries may be None/-1 for Unknown."""
    scalar = dtype if isinstance(dtype, dt.ScalarType) else dt.from_numpy(dtype)
    return Node(
        "placeholder",
        [],
        None,
        name=name or "placeholder",
        dtype=scalar,
        shape=Shape.from_any(shape),
    )


def constant(
    value: ConstLike, name: Optional[str] = None, dtype=None
) -> Node:
    """Embed a constant (≙ dsl/package.scala:53-58; DenseTensor constants).

    Plain Python scalars behave exactly like literals in jnp code
    (``x + 3.0``): weak-typed, adopting the other operand's dtype, and
    inlined by XLA. Typed values (numpy scalars/arrays, nested lists)
    keep their exact dtype — floats default to float64, ints to int64,
    matching frame inference. The node's declared dtype records the
    default; weak literals may narrow to the operand's dtype at trace
    time. Pass ``dtype=`` to pin the embedded dtype explicitly (e.g.
    ``dtypes.default_float().np_dtype`` to follow the framework policy
    — a float64 constant in a demoted program is a TFG102 leak)."""
    arr = np.asarray(value) if dtype is None else np.asarray(value, dtype=dtype)
    scalar = dt.from_numpy(arr.dtype)
    if arr.ndim == 0 and isinstance(value, (int, float)) and not isinstance(
        value, bool
    ):
        # plain Python scalars stay weak-typed literals, exactly as if the
        # user had written ``x + 3.0`` in jnp directly: XLA inlines them
        # (no hoisted constant buffer) and they adopt the operand's dtype
        val = value
    else:
        val = jnp.asarray(arr)
    return Node(
        "constant",
        [],
        lambda: val,
        name=name or "constant",
        dtype=scalar,
        shape=Shape(arr.shape),
    )


def _policy_dtype(dtype):
    """Resolve a constructor's ``dtype=None`` default to the framework
    float policy (:func:`tensorframes_tpu.dtypes.default_float`).

    .. deprecated:: 0.3
       These constructors previously hard-coded ``np.float64`` and
       silently relied on the x64 demotion pass to cast back down —
       exactly the pattern the TFG102 f64-leak rule flags. With x64 on
       and demotion off (the default CPU config) the policy still
       resolves to float64, so reference-parity programs are unchanged;
       pass ``dtype=np.float64`` explicitly to keep the old behavior
       under demotion."""
    if dtype is not None:
        return dtype
    return dt.default_float().np_dtype


def zeros(shape, dtype=None, name=None) -> Node:
    """≙ dsl/package.scala:60-64; dtype defaults to the framework float
    policy (see :func:`_policy_dtype` for the deprecation note)."""
    return constant(np.zeros(shape, dtype=_policy_dtype(dtype)),
                    name=name or "zeros")


def ones(shape, dtype=None, name=None) -> Node:
    """≙ dsl/package.scala:66-70; dtype defaults to the framework float
    policy (see :func:`_policy_dtype`)."""
    return constant(np.ones(shape, dtype=_policy_dtype(dtype)),
                    name=name or "ones")


def fill(shape, value, dtype=None, name=None) -> Node:
    """≙ dsl/package.scala:72-76. Float fills follow the framework float
    policy; int/bool fills keep numpy's inference (int64/bool), matching
    frame inference for those kinds."""
    if dtype is None and isinstance(value, float):
        dtype = dt.default_float().np_dtype
    return constant(np.full(shape, value, dtype=dtype), name=name or "fill")


def unary(op: str, fn: Callable, x: Node, name=None) -> Node:
    return Node(op, [x], fn, name=name)


def binary(op: str, fn: Callable, x: Node, y: Node, name=None) -> Node:
    return Node(op, [x, y], fn, name=name)


# -- op catalog (superset of dsl/package.scala:110-132) ----------------------

def identity(x: Node, name=None) -> Node:
    return unary("identity", lambda v: v, x, name=name)


def add(x: Node, y, name=None) -> Node:
    return binary("add", jnp.add, x, x._lift(y) if not isinstance(y, Node) else y, name=name)


def sub(x: Node, y, name=None) -> Node:
    return binary("sub", jnp.subtract, x, x._lift(y) if not isinstance(y, Node) else y, name=name)


def mul(x: Node, y, name=None) -> Node:
    return binary("mul", jnp.multiply, x, x._lift(y) if not isinstance(y, Node) else y, name=name)


def div(x: Node, y, name=None) -> Node:
    return binary("div", jnp.divide, x, x._lift(y) if not isinstance(y, Node) else y, name=name)


def matmul(x: Node, y: Node, name=None) -> Node:
    return binary("matmul", jnp.matmul, x, y, name=name)


def _reducer(op: str, fn: Callable, x: Node, axis, name) -> Node:
    ax = axis
    if isinstance(ax, (list, tuple)):
        ax = tuple(ax)
        single = ax[0] if len(ax) == 1 else None
    else:
        single = ax
        ax = (ax,) if ax is not None else None

    def eval_fn(v):
        # preserve the input dtype: the reduce contract requires fetch and
        # input dtypes to match (Operations.scala:98-108), but jnp.sum
        # would promote small ints to the default int under x64.
        return fn(v, axis=ax).astype(v.dtype)

    return Node(op, [x], eval_fn, name=name, reduce_axis=single)


def reduce_sum(x: Node, axis=0, name=None) -> Node:
    """≙ dsl/package.scala:122-127 (& build_reducer, DslImpl.scala:175-200)."""
    return _reducer("reduce_sum", jnp.sum, x, axis, name)


def reduce_min(x: Node, axis=0, name=None) -> Node:
    return _reducer("reduce_min", jnp.min, x, axis, name)


def reduce_max(x: Node, axis=0, name=None) -> Node:
    return _reducer("reduce_max", jnp.max, x, axis, name)


def reduce_mean(x: Node, axis=0, name=None) -> Node:
    return _reducer("reduce_mean", jnp.mean, x, axis, name)


def apply_fn(fn: Callable, *xs: Node, name=None) -> Node:
    """Escape hatch: apply an arbitrary jax function to DSL nodes. This is
    where the TPU build exceeds the reference's fixed op set — any traceable
    jnp program can join the graph."""
    return Node(getattr(fn, "__name__", "apply"), list(xs), fn, name=name)


def exp(x: Node, name=None) -> Node:
    return unary("exp", jnp.exp, x, name)


def log(x: Node, name=None) -> Node:
    return unary("log", jnp.log, x, name)


def tanh(x: Node, name=None) -> Node:
    return unary("tanh", jnp.tanh, x, name)


def sqrt(x: Node, name=None) -> Node:
    return unary("sqrt", jnp.sqrt, x, name)


def abs_(x: Node, name=None) -> Node:
    return unary("abs", jnp.abs, x, name)


def square(x: Node, name=None) -> Node:
    return unary("square", jnp.square, x, name)


def sigmoid(x: Node, name=None) -> Node:
    import jax.nn

    return unary("sigmoid", jax.nn.sigmoid, x, name)


def relu(x: Node, name=None) -> Node:
    import jax.nn

    return unary("relu", jax.nn.relu, x, name)


# ---------------------------------------------------------------------------
# Compilation: fetches → Program
# ---------------------------------------------------------------------------

def _closure(fetches: Sequence[Node]) -> List[Node]:
    """Transitive closure in topological order, deduped by node identity
    (≙ DslImpl.getClosure, dsl/DslImpl.scala:62-75)."""
    seen: Dict[int, Node] = {}
    order: List[Node] = []

    def visit(n: Node):
        if id(n) in seen:
            return
        seen[id(n)] = n
        for p in n.parents:
            visit(p)
        order.append(n)

    for f in fetches:
        visit(f)
    return order


def compile_fetches(fetches: Union[Node, Sequence[Node]]) -> Program:
    """Compile a fetch list into a Program (≙ DslImpl.buildGraph +
    analyzeGraphTF rolled into one, statically)."""
    if isinstance(fetches, Node):
        fetches = [fetches]
    fetches = list(fetches)
    names = [f.name for f in fetches]
    base = [n.split("/")[-1] for n in names]
    if len(set(base)) != len(base):
        # ≙ core.py:106-108 unique-column-name check
        raise ValueError(
            f"Could not infer a list of unique names for the columns: {names}"
        )
    nodes = _closure(fetches)
    placeholders = [n for n in nodes if n.is_placeholder]
    inputs = [TensorSpec(p.name, p.dtype, p.shape) for p in placeholders]

    def fn(feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        values: Dict[int, jnp.ndarray] = {}
        for n in nodes:
            if n.is_placeholder:
                values[id(n)] = feeds[n.name]
            else:
                args = [values[id(p)] for p in n.parents]
                values[id(n)] = n.eval_fn(*args)
        # column name = last path segment of the fetch name (feed-style
        # qualified names keep scopes; output columns use the base name,
        # ≙ core.py:106 stripping ":0")
        return {f.name.split("/")[-1]: values[id(f)] for f in fetches}

    prog = Program(fn, inputs, fetch_order=[n.split("/")[-1] for n in names])
    return prog


def segment_reduce_info(fetches: Sequence[Node]) -> Optional[List[Tuple[str, str, str]]]:
    """If every fetch is an algebraic reducer over axis 0 applied directly
    to a placeholder, return [(out_name, op, input_placeholder)] — enabling
    `aggregate`/`reduce_blocks` to lower to vectorized segment/psum ops
    instead of generic per-group execution. Otherwise None."""
    out = []
    for f in fetches:
        if f.reduce_axis != 0 or len(f.parents) != 1:
            return None
        p = f.parents[0]
        if not p.is_placeholder:
            return None
        out.append((f.name.split("/")[-1], f.op, p.name))
    return out
