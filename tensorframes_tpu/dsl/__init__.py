"""Public DSL surface (≙ dsl/package.scala:17-134 and the Python
``tfs.block``/``tfs.row`` auto-placeholders, core.py:421-474)."""

from __future__ import annotations

from typing import Optional

from ..shape import Unknown
from .node import (  # noqa: F401
    GraphContext,
    Node,
    abs_,
    add,
    apply_fn,
    binary,
    compile_fetches,
    constant,
    current_graph,
    div,
    exp,
    fill,
    identity,
    log,
    matmul,
    mul,
    ones,
    placeholder,
    reduce_max,
    reduce_mean,
    reduce_min,
    reduce_sum,
    relu,
    scope,
    segment_reduce_info,
    sigmoid,
    sqrt,
    square,
    sub,
    tanh,
    unary,
    with_graph,
    zeros,
)


def block(frame, col_name: str, tf_name: Optional[str] = None) -> Node:
    """Auto-placeholder for a column, block-shaped: leading row dim is
    always Unknown (empty/short blocks must not choke — ≙ core.py:470-473),
    tail = the column's cell shape.

    ≙ ``tfs.block`` (core.py:421-434) + ``extractPlaceholder``
    (dsl/DslImpl.scala:90-107).
    """
    info = frame.schema[col_name]
    if not info.is_device:
        raise TypeError(
            f"Column {col_name!r} has host-only type {info.dtype.name}; it "
            "cannot feed a device program (strings/binary ride along as "
            "pass-through columns)"
        )
    shape = info.cell_shape.prepend(Unknown)
    return placeholder(info.dtype, shape, name=tf_name or col_name)


def row(frame, col_name: str, tf_name: Optional[str] = None) -> Node:
    """Auto-placeholder shaped as one row's cell (≙ ``tfs.row``,
    core.py:436-449: the block shape minus the leading dim)."""
    info = frame.schema[col_name]
    if not info.is_device:
        raise TypeError(
            f"Column {col_name!r} has host-only type {info.dtype.name}; it "
            "cannot feed a device program"
        )
    return placeholder(info.dtype, info.cell_shape, name=tf_name or col_name)
