"""Pre-flight validation: program ⇄ frame schema matching.

This reproduces the reference's ``SchemaTransforms`` error contract
(impl/DebugRowOps.scala:53-273) — the largest behavioral surface of the
reference (SURVEY.md §7.4). Every check enumerates, in the error message,
both sides of the mismatch (columns available vs program nodes), as the
reference's messages do.

Contracts validated:

* **map verbs** (mapBlocks/mapRows, DebugRowOps.scala:318-363): every
  program input must name a frame column (after ``feed_dict`` renames);
  dtypes must match exactly (no implicit casting, datatypes.scala:155-161);
  the column's (cell/block) shape must be *at least as precise as* the
  placeholder's declared shape; output names must not collide with
  existing columns when appending.
* **reduce_blocks** (reduceBlocksSchema, DebugRowOps.scala:80-170): each
  fetch ``x`` must name an existing column; inputs must be exactly
  ``{x}_input`` for the fetches; ``x_input``'s shape must be one rank
  higher than ``x``'s with a widened (Unknown) lead dim
  (``widenLeadDim``, :265-272); dtypes equal.
* **reduce_rows** (reduceRowsSchema, DebugRowOps.scala:172-262): each
  fetch ``x`` pairs with placeholders ``x_1``/``x_2`` of identical dtype
  and shape (Operations.scala:83-95).
"""

from __future__ import annotations

from typing import Sequence

from . import dtypes as dt
from .program import Program, TensorSpec
from .schema import ColumnInfo, Schema


class ValidationError(ValueError):
    """A schema/program mismatch detected before execution."""


class StaticAnalysisError(ValidationError):
    """Error-severity static diagnostics under a verb's ``strict=True``
    (or ``DiagnosticReport.raise_on_errors()``). Like every
    ValidationError it fires *before* execution; ``diagnostics`` carries
    the structured findings (:mod:`tensorframes_tpu.analysis`)."""

    def __init__(self, message: str, diagnostics: Sequence = ()):
        super().__init__(message)
        self.diagnostics = list(diagnostics)


def _no_collisions(outputs: Sequence[TensorSpec], schema: Schema) -> None:
    cols = set(schema.names)
    clash = [o.name for o in outputs if o.name in cols]
    if clash:
        raise ValidationError(
            f"Output name(s) {clash} already exist as column(s) in the "
            f"frame (columns: {schema.names}). Output names must all differ "
            "from existing columns."
        )


def _check_dtype(col: ColumnInfo, spec: TensorSpec, role: str) -> None:
    if col.dtype is not spec.dtype:
        # the single sanctioned exception to no-casting: an f64/i64
        # column feeding a demoted 32-bit program input while x64
        # demotion is active (config.demote_x64_on_tpu)
        if dt.demotion_active() and dt.demote(col.dtype) is spec.dtype:
            return
        raise ValidationError(
            f"{role} {spec.name!r} has dtype {spec.dtype.name} but column "
            f"{col.name!r} has dtype {col.dtype.name}. No implicit casting "
            "is performed on inputs."
        )


def validate_map(
    program: Program,
    schema: Schema,
    block: bool,
    trim: bool = False,
) -> None:
    """Validate a map_blocks/map_rows program against a frame schema.

    ``program.inputs`` must already be renamed per feed_dict (so input
    names are column names).
    """
    for spec in program.inputs:
        col = schema.get(spec.name)
        if col is None:
            raise ValidationError(
                f"Program input {spec.name!r} does not match any column. "
                f"Graph inputs: {program.input_names}; frame columns: "
                f"{schema.names}. Use feed_dict to rename placeholders to "
                "columns."
            )
        _check_dtype(col, spec, "Placeholder")
        data_shape = col.block_shape if block else col.cell_shape
        if spec.shape.rank != data_shape.rank:
            kind = "block" if block else "row"
            raise ValidationError(
                f"Placeholder {spec.name!r} has rank {spec.shape.rank} "
                f"(shape {spec.shape}) but the column's {kind} shape is "
                f"{data_shape} (rank {data_shape.rank})."
            )
        if not data_shape.is_compatible_with(spec.shape):
            raise ValidationError(
                f"Placeholder {spec.name!r} declares shape {spec.shape} "
                f"which is incompatible with column shape {data_shape}. "
                "Run analyze() on the frame or append_shape() if the "
                "column's shape metadata is missing."
            )
    if not trim:
        _no_collisions(program.outputs, schema)
    if block and not trim:
        # appending requires outputs to keep the block's row count: lead
        # dim must be batch-covariant (Unknown) or the check happens at
        # runtime per block.
        for o in program.outputs:
            if o.shape.rank == 0:
                raise ValidationError(
                    f"map_blocks output {o.name!r} is a scalar; block "
                    "outputs must have a leading row dimension (use "
                    "map_blocks(trim=True) or reduce_blocks for "
                    "aggregations)."
                )


def validate_reduce_blocks(program: Program, schema: Schema) -> None:
    """≙ reduceBlocksSchema (DebugRowOps.scala:80-170)."""
    out_names = [o.name for o in program.outputs]
    for o in program.outputs:
        col = schema.get(o.name)
        if col is None:
            raise ValidationError(
                f"reduce_blocks output {o.name!r} must correspond to an "
                f"existing column. Outputs: {out_names}; columns: "
                f"{schema.names}."
            )
    expected_inputs = {f"{n}_input" for n in out_names}
    got_inputs = set(program.input_names)
    if got_inputs != expected_inputs:
        raise ValidationError(
            "reduce_blocks requires exactly one placeholder '<x>_input' per "
            f"fetch '<x>'. Expected inputs: {sorted(expected_inputs)}; got: "
            f"{sorted(got_inputs)}."
        )
    for o in program.outputs:
        col = schema[o.name]
        spec = program.input(f"{o.name}_input")
        _check_dtype(col, spec, "Placeholder")
        if o.dtype is not spec.dtype:
            raise ValidationError(
                f"Fetch {o.name!r} has dtype {o.dtype.name} but its input "
                f"{spec.name!r} has dtype {spec.dtype.name}; they must match."
            )
        if spec.shape.rank != o.shape.rank + 1:
            raise ValidationError(
                f"Placeholder {spec.name!r} (shape {spec.shape}) must have "
                f"exactly one more dimension than fetch {o.name!r} (shape "
                f"{o.shape})."
            )
        # the input block shape must be compatible with the column's
        if not col.block_shape.is_compatible_with(spec.shape):
            raise ValidationError(
                f"Placeholder {spec.name!r} declares shape {spec.shape}, "
                f"incompatible with column block shape {col.block_shape}."
            )


def validate_reduce_rows(program: Program, schema: Schema) -> None:
    """≙ reduceRowsSchema (DebugRowOps.scala:172-262)."""
    out_names = [o.name for o in program.outputs]
    for o in program.outputs:
        col = schema.get(o.name)
        if col is None:
            raise ValidationError(
                f"reduce_rows output {o.name!r} must correspond to an "
                f"existing column. Outputs: {out_names}; columns: "
                f"{schema.names}."
            )
    expected = set()
    for n in out_names:
        expected.add(f"{n}_1")
        expected.add(f"{n}_2")
    got = set(program.input_names)
    if got != expected:
        raise ValidationError(
            "reduce_rows requires exactly two placeholders '<x>_1' and "
            f"'<x>_2' per fetch '<x>'. Expected: {sorted(expected)}; got: "
            f"{sorted(got)}."
        )
    for o in program.outputs:
        col = schema[o.name]
        for suffix in ("_1", "_2"):
            spec = program.input(o.name + suffix)
            _check_dtype(col, spec, "Placeholder")
            if spec.shape.rank != o.shape.rank:
                raise ValidationError(
                    f"Placeholder {spec.name!r} (shape {spec.shape}) must "
                    f"have the same shape as fetch {o.name!r} (shape "
                    f"{o.shape})."
                )
            if not col.cell_shape.is_compatible_with(spec.shape):
                raise ValidationError(
                    f"Placeholder {spec.name!r} declares shape {spec.shape}, "
                    f"incompatible with column cell shape {col.cell_shape}."
                )
