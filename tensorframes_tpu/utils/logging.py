"""Logging setup (≙ the reference's ``Logging`` trait, Logging.scala:5-9,
and its log4j bootstrap, PythonInterface.scala:29-44 — here just stdlib
logging with a package-level logger and an opt-in debug env var)."""

from __future__ import annotations

import logging
import os

_ROOT = "tensorframes_tpu"


def get_logger(name: str = _ROOT) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logging.getLogger(_ROOT).handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        root = logging.getLogger(_ROOT)
        root.addHandler(handler)
        level = os.environ.get("TFTPU_LOG", "WARNING").upper()
        root.setLevel(getattr(logging, level, logging.WARNING))
    return logger
