"""Logging setup (≙ the reference's ``Logging`` trait, Logging.scala:5-9,
and its log4j bootstrap, PythonInterface.scala:29-44 — here just stdlib
logging with a package-level logger and an opt-in debug env var).

The ``TFTPU_LOG`` environment variable is re-read on every
:func:`get_logger` call, so a test (or an operator attaching to a live
process via a debugger) can flip verbosity without re-importing the
package. :func:`set_level` pins the level explicitly and stops the env
re-reads — an in-code decision outranks ambient environment."""

from __future__ import annotations

import logging
import os
from typing import Optional, Union

_ROOT = "tensorframes_tpu"

#: Explicitly-pinned level (via set_level); None → follow TFTPU_LOG.
_pinned_level: Optional[int] = None

#: Last TFTPU_LOG value applied (sentinel → never applied). The env is
#: re-applied only when its value CHANGES, so a user who configured the
#: root via plain ``logging.getLogger("tensorframes_tpu").setLevel(...)``
#: is not silently clobbered by the next get_logger call.
_UNSET = object()
_last_env_level = _UNSET


def _coerce_level(level: Union[int, str]) -> int:
    if isinstance(level, int):
        return level
    resolved = getattr(logging, str(level).upper(), None)
    if not isinstance(resolved, int):
        raise ValueError(f"unknown log level {level!r}")
    return resolved


def _ensure_handler() -> logging.Logger:
    root = logging.getLogger(_ROOT)
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        root.addHandler(handler)
    return root


def get_logger(name: str = _ROOT) -> logging.Logger:
    """Package logger factory. Unless :func:`set_level` has pinned a
    level, ``TFTPU_LOG`` is re-read at every call and applied whenever
    its value has changed — never frozen at whatever the env said the
    first time, and never clobbering a level set directly on the root
    logger in between env changes."""
    global _last_env_level
    root = _ensure_handler()
    if _pinned_level is None:
        level = os.environ.get("TFTPU_LOG", "WARNING").upper()
        if level != _last_env_level:
            _last_env_level = level
            root.setLevel(getattr(logging, level, logging.WARNING))
    return logging.getLogger(name)


def set_level(level: Union[int, str]) -> None:
    """Pin the package log level (``"DEBUG"``/``logging.DEBUG``/...).
    Overrides — and stops tracking — the ``TFTPU_LOG`` env var; call
    :func:`clear_level` to hand control back to the environment."""
    global _pinned_level
    _pinned_level = _coerce_level(level)
    _ensure_handler().setLevel(_pinned_level)


def clear_level() -> None:
    """Un-pin: the next :func:`get_logger` follows ``TFTPU_LOG`` again
    (and re-applies it, whatever its current value)."""
    global _pinned_level, _last_env_level
    _pinned_level = None
    _last_env_level = _UNSET
