from .logging import clear_level, get_logger, set_level

__all__ = ["clear_level", "get_logger", "set_level"]
