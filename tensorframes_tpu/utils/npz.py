"""Shared raw-bytes ndarray codec for npz storage.

numpy's npz loader cannot reconstruct ml_dtypes (bfloat16 loads as void
'|V2' arrays), so both the checkpoint backend (checkpoint.py) and frame
persistence (io.py) store arrays as flat uint8 bytes with the dtype and
shape recorded out-of-band in a JSON manifest. This module is the single
copy of that encode/decode pair.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def np_dtype_from_name(name: str) -> np.dtype:
    """Resolve a dtype string, falling back to ml_dtypes (bfloat16, float8…)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency; owns bfloat16/float8 dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_array(arr) -> Tuple[np.ndarray, Dict]:
    """array → (flat uint8 view, {"dtype", "shape"} manifest entry).

    The byte view is zero-copy when the input is already contiguous. The
    shape is recorded BEFORE ascontiguousarray, which promotes 0-d
    scalars to shape (1,) — that promotion must not leak into the
    manifest.
    """
    arr = np.asarray(arr)
    shape = list(arr.shape)
    arr = np.ascontiguousarray(arr)
    return arr.reshape(-1).view(np.uint8), {"dtype": str(arr.dtype), "shape": shape}


def decode_array(raw: np.ndarray, entry: Dict) -> np.ndarray:
    """Inverse of :func:`encode_array`. np.load returns fresh writable
    arrays, so the view+reshape stays copy-free and writable."""
    return raw.view(np_dtype_from_name(entry["dtype"])).reshape(entry["shape"])
