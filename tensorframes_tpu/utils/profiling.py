"""Tracing / profiling.

The reference has nothing beyond log4j levels (SURVEY.md §5: manual timing
only in ``ignore``-d perf suites); the survey's build note makes the
TPU-native equivalent first-class: per-verb wall-clock metrics plus
``jax.profiler`` device traces.

* ``span(name, rows=...)`` — context manager accumulating wall-clock,
  call count and row throughput per named operation. The five verbs wrap
  their execution in spans automatically; user code can add its own.
* ``metrics()`` / ``report()`` / ``reset_metrics()`` — inspect the
  accumulated stats (``report()`` is the profiling sibling of
  ``explain``).
* ``trace(logdir)`` — context manager around ``jax.profiler.trace``:
  captures a TensorBoard-viewable device trace (XLA ops, HBM transfers)
  when the runtime supports it; a no-op (with a log line) otherwise.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import warnings
from typing import Dict, Iterator, Optional

from .logging import get_logger

logger = get_logger(__name__)


_events_mod = None
_latency_mod = None


def _trace_events():
    """The structured event tracer (observability/events.py), imported
    lazily (then cached) to keep utils free of package-level import
    edges. Spans land on the Chrome-trace timeline whenever tracing is
    enabled — the aggregate table here and the timeline there come from
    the same instrumentation points."""
    global _events_mod
    if _events_mod is None:
        from ..observability import events

        _events_mod = events
    return _events_mod


def _latency(name: str, seconds: float) -> None:
    """Feed verb-named spans into the latency-quantile histograms
    (observability/latency.py) — same lazy-import shape as the tracer
    hook; non-verb names are ignored there with one dict lookup."""
    global _latency_mod
    if _latency_mod is None:
        from ..observability import latency

        _latency_mod = latency
    _latency_mod.observe_verb(name, seconds)


@dataclasses.dataclass
class SpanStats:
    calls: int = 0
    seconds: float = 0.0
    rows: int = 0
    flops: float = 0.0  # model FLOPs executed under this span (if known)
    bytes: float = 0.0  # XLA-cost-model bytes accessed (if known)

    @property
    def rows_per_sec(self) -> float:
        return self.rows / self.seconds if self.seconds > 0 else 0.0

    @property
    def flops_per_sec(self) -> float:
        return self.flops / self.seconds if self.seconds > 0 else 0.0

    @property
    def bytes_per_sec(self) -> float:
        return self.bytes / self.seconds if self.seconds > 0 else 0.0


_lock = threading.Lock()
_stats: Dict[str, SpanStats] = {}


@contextlib.contextmanager
def span(name: str, rows: int = 0) -> Iterator[None]:
    """Accumulate wall-clock (and optional row count) under ``name``.
    When structured tracing is enabled (``observability.events``), the
    span also lands on the Chrome-trace timeline as a complete event."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            s = _stats.setdefault(name, SpanStats())
            s.calls += 1
            s.seconds += dt
            s.rows += rows
        _latency(name, dt)
        ev = _trace_events()
        if ev.TRACER.enabled:
            ev.TRACER.emit_complete(
                name, t0, dt, args={"rows": rows} if rows else None,
                cat="profiling",
            )


def record(
    name: str,
    seconds: float,
    rows: int = 0,
    flops: float = 0.0,
    bytes_accessed: Optional[float] = None,
    **kwargs: float,
) -> None:
    """Directly accumulate one measurement (for code that times itself).
    ``flops``/``bytes_accessed`` let callers attach XLA cost-model
    counts (e.g. from ``Program.flops_per_row``/``bytes_per_row``) so
    :func:`report` can print achieved FLOP/s, HBM GB/s, and — when
    ``config.peak_flops`` is set — MFU.

    ``bytes=`` is the deprecated spelling of ``bytes_accessed`` (it
    shadowed the builtin); accepted for one release with a
    DeprecationWarning."""
    if "bytes" in kwargs:
        warnings.warn(
            "profiling.record(bytes=...) is deprecated; use "
            "bytes_accessed= (the old name shadowed the builtin)",
            DeprecationWarning,
            stacklevel=2,
        )
        if bytes_accessed is not None:
            raise TypeError(
                "record() got both bytes_accessed= and deprecated bytes="
            )
        bytes_accessed = kwargs.pop("bytes")
    if kwargs:
        raise TypeError(
            f"record() got unexpected keyword arguments {sorted(kwargs)}"
        )
    if bytes_accessed is None:
        bytes_accessed = 0.0
    with _lock:
        s = _stats.setdefault(name, SpanStats())
        s.calls += 1
        s.seconds += seconds
        s.rows += rows
        s.flops += flops
        s.bytes += bytes_accessed
    _latency(name, seconds)
    ev = _trace_events()
    if ev.TRACER.enabled:
        # callers record immediately after timing (the verbs do
        # ``record(name, perf_counter() - t0, ...)``), so "it just
        # ended" reconstructs the start closely enough for a timeline
        ev.TRACER.emit_complete(
            name, time.perf_counter() - seconds, seconds,
            args={"rows": rows} if rows else None, cat="profiling",
        )


def metrics() -> Dict[str, SpanStats]:
    """Snapshot of accumulated span stats."""
    with _lock:
        return {k: dataclasses.replace(v) for k, v in _stats.items()}


def reset_metrics() -> None:
    with _lock:
        _stats.clear()


def report() -> str:
    """Human-readable per-span table (the profiling ``explain``). Spans
    carrying FLOP counts get achieved GFLOP/s, plus model FLOP
    utilization (achieved / ``config.peak_flops``) when the chip's peak
    is configured — perf work becomes a number, not a vibe."""
    from ..config import get_config

    snap = metrics()
    if not snap:
        return "no spans recorded"
    peak = float(getattr(get_config(), "peak_flops", 0.0) or 0.0)
    any_flops = any(s.flops for s in snap.values())
    any_bytes = any(s.bytes for s in snap.values())
    name_w = max(len(k) for k in snap) + 2
    hdr = f"{'span':<{name_w}}{'calls':>7}{'seconds':>12}{'rows':>12}{'rows/s':>14}"
    if any_flops:
        hdr += f"{'GFLOP/s':>12}" + (f"{'MFU%':>8}" if peak else "")
    if any_bytes:
        hdr += f"{'GB/s':>10}"

    lines = [hdr]
    for name in sorted(snap):
        s = snap[name]
        rps = f"{s.rows_per_sec:,.0f}" if s.rows else "-"
        rows = f"{s.rows:,}" if s.rows else "-"
        line = f"{name:<{name_w}}{s.calls:>7}{s.seconds:>12.4f}{rows:>12}{rps:>14}"
        if any_flops:
            line += (
                f"{s.flops_per_sec / 1e9:>12,.1f}" if s.flops else f"{'-':>12}"
            )
            if peak:
                line += (
                    f"{100.0 * s.flops_per_sec / peak:>8.1f}"
                    if s.flops
                    else f"{'-':>8}"
                )
        if any_bytes:
            line += (
                f"{s.bytes_per_sec / 1e9:>10,.1f}" if s.bytes else f"{'-':>10}"
            )
        lines.append(line)
    return "\n".join(lines)


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace into ``logdir`` (TensorBoard
    format). Degrades to a no-op where the backend can't trace."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # pragma: no cover — backend-dependent
        logger.warning("jax.profiler trace unavailable: %s", e)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                logger.warning("jax.profiler stop_trace failed: %s", e)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a region in the device trace (shows up in TensorBoard); also
    accumulates a wall-clock span. Exceptions from the annotated body
    propagate untouched — only TraceAnnotation setup failures are
    swallowed."""
    import jax

    ann = None
    try:
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    except Exception:  # pragma: no cover — backend-dependent
        ann = None
    with span(name):
        try:
            yield
        finally:
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:  # pragma: no cover
                    pass
