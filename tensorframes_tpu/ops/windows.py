"""Trace-time numpy window arithmetic shared by the model zoo and the
GraphDef importer.

Why numpy and not ``reduce_window(ones)``: a reduce-window over a
constant makes XLA constant-fold a full-size pooling per compiled shape
— the 8-12s ``slow_operation_alarm`` stalls originally seen in the
Inception stem. Computing the divisor on the host embeds a ready
constant instead.
"""

from __future__ import annotations

import functools

import numpy as np


@functools.lru_cache(maxsize=64)
def same_pool_counts(
    h: int, w: int, kh: int, kw: int, sh: int = 1, sw: int = 1
) -> np.ndarray:
    """Per-pixel window population of a SAME-padded pool (TF's
    edge-clipped average divisor), shaped ``[1, out_h, out_w, 1]``."""
    out_h, out_w = -(-h // sh), -(-w // sw)
    pad_h = max((out_h - 1) * sh + kh - h, 0)
    pad_w = max((out_w - 1) * sw + kw - w, 0)
    top, left = pad_h // 2, pad_w // 2
    padded = np.zeros((h + pad_h, w + pad_w), np.float32)
    padded[top:top + h, left:left + w] = 1.0
    counts = np.zeros((out_h, out_w), np.float32)
    for i in range(out_h):
        for j in range(out_w):
            counts[i, j] = padded[i * sh:i * sh + kh, j * sw:j * sw + kw].sum()
    return counts.reshape(1, out_h, out_w, 1)
