from .verbs import (
    aggregate,
    compile_program,
    map_blocks,
    map_rows,
    reduce_blocks,
    reduce_rows,
)

__all__ = [
    "aggregate",
    "compile_program",
    "map_blocks",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
]
