from .verbs import aggregate, map_blocks, map_rows, reduce_blocks, reduce_rows

__all__ = ["aggregate", "map_blocks", "map_rows", "reduce_blocks", "reduce_rows"]
