"""Weight-only int8 quantization for inference.

Model scoring through the verbs is frozen-graph inference (params are
closure-captured constants ≙ variables-to-constants freezing,
core.py:42-56). On TPU those frozen weights live in HBM, and HBM
bandwidth — not MXU FLOPs — bounds small-batch serving. Symmetric
per-channel int8 storage cuts weight traffic 4× vs f32 (2× vs bf16);
XLA fuses the dequantize-convert into the consuming matmul/conv, so the
compute still runs in bf16/f32 on the MXU with full-precision scales.

``QuantizedTensor`` is a pytree, so quantized parameter trees flow
through ``jax.jit``, shardings, and checkpoints like any other params.
``quantize_tree`` converts a whole parameter tree (floating arrays with
rank >= min_rank); ``asarray`` is the read-side accessor models use so
one forward pass serves both plain and quantized trees.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Symmetric per-channel int8 weight: ``q * scale ≈ w``.

    ``scale`` broadcasts against ``q`` (kept with singleton dims), so
    dequantization is one fused multiply."""

    q: jnp.ndarray        # int8
    scale: jnp.ndarray    # f32, broadcastable to q's shape

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.q.shape)) + 4 * int(np.prod(self.scale.shape))

    def dequantize(self, dtype=jnp.float32) -> jnp.ndarray:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def quantize(w, channel_axis=-1) -> QuantizedTensor:
    """Symmetric per-channel int8: scales are per-slice max/127 along
    every axis EXCEPT ``channel_axis`` (the output-feature axis, whose
    per-channel dynamic range is what matters for matmul accuracy).
    ``channel_axis`` may be a tuple for weights whose channels span
    several axes (depthwise filters ``[H,W,C,M]`` keep ``(2, 3)``)."""
    w = jnp.asarray(w)
    if not jnp.issubdtype(w.dtype, jnp.floating):
        raise TypeError(f"quantize expects a floating array, got {w.dtype}")
    axes = (
        (channel_axis,) if isinstance(channel_axis, int) else tuple(channel_axis)
    )
    keep = {a % w.ndim for a in axes}
    reduce_axes = tuple(i for i in range(w.ndim) if i not in keep)
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale)


def asarray(w, dtype=jnp.float32) -> jnp.ndarray:
    """Read-side accessor: dequantize if quantized, else cast. Models use
    this so one forward serves plain and quantized parameter trees."""
    if isinstance(w, QuantizedTensor):
        return w.dequantize(dtype)
    return jnp.asarray(w).astype(dtype)


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` with STRUCTURAL dequantization fusion for quantized
    weights (VERDICT r3 #4: ``asarray`` relied on XLA *choosing* to fuse
    the dequantize into the dot; on a compute-bound config it instead
    materialized a full-precision weight copy, making int8 pure
    overhead).

    For a per-OUTPUT-channel quantized 2D weight the scale commutes out
    of the contraction::

        x @ (q * s)  ==  (x @ q.astype(x.dtype)) * s

    so the int8 weights stream from HBM and convert on-chip inside the
    dot fusion; the scale applies to the (much smaller) result. The
    product runs in f32 before casting back, preserving the scales'
    precision. Falls back to plain dequantize-then-matmul for scale
    layouts that span contracted axes."""
    if not isinstance(w, QuantizedTensor):
        return x @ jnp.asarray(w).astype(x.dtype)
    # scale commutes iff it is constant along every contracted axis of w
    # (all axes but the last): quantize(channel_axis=-1) keeps them as
    # singleton dims
    if w.q.ndim != 2 or w.scale.shape[:-1] != (1,) * (w.q.ndim - 1):
        return x @ w.dequantize(x.dtype)
    if _pallas_int8_eligible(x, w):
        # the probe in _pallas_int8_eligible already validated the
        # kernel family eagerly — no try/except here, because under an
        # outer jax.jit (how models call this) tracing cannot catch a
        # downstream Mosaic failure anyway
        return matmul_pallas_int8(x, w)
    out = x @ w.q.astype(x.dtype)
    scale = w.scale.reshape(-1)
    return (out.astype(jnp.float32) * scale).astype(x.dtype)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def matmul_pallas_int8(
    x: jnp.ndarray,
    w: QuantizedTensor,
    tile_n: int = 256,
    tile_k: int = 256,
    tile_m: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x @ w`` with the int8 weight dequantized INSIDE a pallas
    kernel: each weight tile streams HBM→VMEM as int8 (the whole point
    — 4× less weight traffic than f32, 2× less than bf16) and converts
    on-chip right before the MXU dot; the per-output-channel scale
    multiplies the accumulator on the last k step.

    Exists because :func:`matmul`'s structural fusion still leaves the
    convert placement to XLA, and the r3 chip run measured int8 ≈ f32
    there — consistent with a materialized wide copy. This kernel makes
    the int8 byte saving unconditional. Fully tiled over (m, n, k) with
    k innermost (sequential accumulation into the output block), so
    VMEM holds only one tile per operand regardless of activation size.
    Gated behind ``config.pallas_int8_matmul`` (off by default until a
    real-TPU window adjudicates it — ``dev/tpu_smoke.py`` prints the
    comparison); shapes: x [*, k], w.q [k, n], per-output-channel
    scales. Same index-map x64 discipline as ops/segment.py (``i - i``
    is an i32 zero under jax x64)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    assert w.q.ndim == 2 and w.scale.shape[:-1] == (1,)
    lead = x.shape[:-1]
    k = x.shape[-1]
    n = w.q.shape[1]
    m = int(np.prod(lead)) if lead else 1
    x2 = x.reshape(m, k)

    t_m = tile_m if m > tile_m else _round_up(max(m, 1), 8)
    m_pad = _round_up(max(m, 1), t_m)
    k_pad = _round_up(k, tile_k)
    n_pad = _round_up(n, tile_n)
    xp = jnp.zeros((m_pad, k_pad), x.dtype).at[:m, :k].set(x2)
    qp = jnp.zeros((k_pad, n_pad), jnp.int8).at[:k, :n].set(w.q)
    sp = (
        jnp.ones((8, n_pad), jnp.float32)
        .at[:, :n]
        .set(jnp.broadcast_to(w.scale.reshape(1, n), (8, n)))
    )
    k_steps = k_pad // tile_k

    def kernel(x_ref, q_ref, s_ref, o_ref):
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        q_wide = q_ref[:].astype(x_ref.dtype)  # int8→wide IN VMEM
        o_ref[:] += jnp.dot(
            x_ref[:], q_wide, preferred_element_type=jnp.float32
        )

        @pl.when(ki == k_steps - 1)
        def _scale():
            o_ref[:] = o_ref[:] * s_ref[0, :][None, :]

    grid = (m_pad // t_m, n_pad // tile_n, k_steps)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (t_m, tile_k), lambda i, j, kk: (i, kk),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (tile_k, tile_n), lambda i, j, kk: (kk, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (8, tile_n), lambda i, j, kk: (i - i, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (t_m, tile_n), lambda i, j, kk: (i, j),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((m_pad, n_pad), jnp.float32),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:m, :n].reshape(*lead, n).astype(x.dtype)


# Probe-once gate: under jax.jit (how models call matmul) a Mosaic
# compile failure surfaces at the OUTER jit's compile, where matmul's
# try/except can no longer catch it. So eligibility runs a tiny
# CONCRETE kernel once per process; if the kernel family doesn't
# compile on this toolchain, the flag disables before any traced use.
# The (m,n,k) tiling bounds every block to tile-sized VMEM, so probe
# success is shape-representative. Resettable via reset_pallas_int8().
_pallas_int8_state = {"probed": False, "ok": False}  # lint: guarded (benign race: a duplicate concurrent probe reaches the same verdict)


def reset_pallas_int8() -> None:
    """Forget the probe result (e.g. after switching backends)."""
    _pallas_int8_state["probed"] = False
    _pallas_int8_state["ok"] = False


def _pallas_int8_probe_ok() -> bool:
    if not _pallas_int8_state["probed"]:
        _pallas_int8_state["probed"] = True
        try:
            xs = jnp.ones((8, 128), jnp.bfloat16)
            ws = quantize(jnp.ones((128, 128), jnp.float32))
            jax.block_until_ready(matmul_pallas_int8(xs, ws))
            _pallas_int8_state["ok"] = True
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "pallas int8 matmul probe failed — using the XLA "
                "structural fusion: %s", e,
            )
            _pallas_int8_state["ok"] = False
    return _pallas_int8_state["ok"]


#: The activation dtypes the once-per-process probe validates (ADVICE
#: r5): the probe compiles a bf16 kernel, and f32 shares its Mosaic
#: lowering family. Anything else (f64 under x64, f16, integers) was
#: never probed and could fail Mosaic INSIDE the outer jit — exactly
#: the failure the probe-once gate exists to prevent — so it takes the
#: XLA structural-fusion path instead.
_PROBED_DTYPES = (jnp.bfloat16, jnp.float32)


def _pallas_dtype_ok(dtype) -> bool:
    """True when ``dtype`` belongs to the probe-validated family."""
    return any(dtype == jnp.dtype(d) for d in _PROBED_DTYPES)


def _pallas_int8_eligible(x, w) -> bool:
    from ..config import get_config

    return (
        get_config().pallas_int8_matmul
        and isinstance(w, QuantizedTensor)
        and w.q.ndim == 2
        and w.scale.shape[:-1] == (1,)
        and _pallas_dtype_ok(jnp.asarray(x).dtype)
        and jax.default_backend() == "tpu"
        and _pallas_int8_probe_ok()
    )


def quantize_tree(
    params: Any,
    min_rank: int = 2,
    predicate: Optional[Callable[[tuple, jnp.ndarray], bool]] = None,
    channel_axis: int = -1,
) -> Any:
    """Quantize every floating leaf with rank >= ``min_rank`` (weights;
    biases/norms stay full precision). ``predicate(path, leaf)`` can veto
    individual leaves (e.g. keep embeddings full precision)."""

    def maybe_q(path, leaf):
        if isinstance(leaf, QuantizedTensor):
            return leaf  # idempotent on already-quantized trees
        arr = jnp.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.floating) or arr.ndim < min_rank:
            return leaf
        if predicate is not None and not predicate(path, arr):
            return leaf
        return quantize(arr, channel_axis)

    # is_leaf stops tree_map from descending INTO QuantizedTensor (a
    # registered pytree) and re-quantizing its scale array
    return jax.tree_util.tree_map_with_path(
        maybe_q, params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def tree_nbytes(params: Any) -> int:
    """Total parameter bytes (QuantizedTensor-aware) — the HBM footprint
    the quantization exists to shrink."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.nbytes
        else:
            arr = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
            total += int(np.prod(arr.shape)) * arr.dtype.itemsize
    return total
