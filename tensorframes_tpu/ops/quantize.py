"""Weight-only int8 quantization for inference.

Model scoring through the verbs is frozen-graph inference (params are
closure-captured constants ≙ variables-to-constants freezing,
core.py:42-56). On TPU those frozen weights live in HBM, and HBM
bandwidth — not MXU FLOPs — bounds small-batch serving. Symmetric
per-channel int8 storage cuts weight traffic 4× vs f32 (2× vs bf16);
XLA fuses the dequantize-convert into the consuming matmul/conv, so the
compute still runs in bf16/f32 on the MXU with full-precision scales.

``QuantizedTensor`` is a pytree, so quantized parameter trees flow
through ``jax.jit``, shardings, and checkpoints like any other params.
``quantize_tree`` converts a whole parameter tree (floating arrays with
rank >= min_rank); ``asarray`` is the read-side accessor models use so
one forward pass serves both plain and quantized trees.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Symmetric per-channel int8 weight: ``q * scale ≈ w``.

    ``scale`` broadcasts against ``q`` (kept with singleton dims), so
    dequantization is one fused multiply."""

    q: jnp.ndarray        # int8
    scale: jnp.ndarray    # f32, broadcastable to q's shape

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.q.shape)) + 4 * int(np.prod(self.scale.shape))

    def dequantize(self, dtype=jnp.float32) -> jnp.ndarray:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def quantize(w, channel_axis=-1) -> QuantizedTensor:
    """Symmetric per-channel int8: scales are per-slice max/127 along
    every axis EXCEPT ``channel_axis`` (the output-feature axis, whose
    per-channel dynamic range is what matters for matmul accuracy).
    ``channel_axis`` may be a tuple for weights whose channels span
    several axes (depthwise filters ``[H,W,C,M]`` keep ``(2, 3)``)."""
    w = jnp.asarray(w)
    if not jnp.issubdtype(w.dtype, jnp.floating):
        raise TypeError(f"quantize expects a floating array, got {w.dtype}")
    axes = (
        (channel_axis,) if isinstance(channel_axis, int) else tuple(channel_axis)
    )
    keep = {a % w.ndim for a in axes}
    reduce_axes = tuple(i for i in range(w.ndim) if i not in keep)
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale)


def asarray(w, dtype=jnp.float32) -> jnp.ndarray:
    """Read-side accessor: dequantize if quantized, else cast. Models use
    this so one forward serves plain and quantized parameter trees."""
    if isinstance(w, QuantizedTensor):
        return w.dequantize(dtype)
    return jnp.asarray(w).astype(dtype)


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` with STRUCTURAL dequantization fusion for quantized
    weights (VERDICT r3 #4: ``asarray`` relied on XLA *choosing* to fuse
    the dequantize into the dot; on a compute-bound config it instead
    materialized a full-precision weight copy, making int8 pure
    overhead).

    For a per-OUTPUT-channel quantized 2D weight the scale commutes out
    of the contraction::

        x @ (q * s)  ==  (x @ q.astype(x.dtype)) * s

    so the int8 weights stream from HBM and convert on-chip inside the
    dot fusion; the scale applies to the (much smaller) result. The
    product runs in f32 before casting back, preserving the scales'
    precision. Falls back to plain dequantize-then-matmul for scale
    layouts that span contracted axes."""
    if not isinstance(w, QuantizedTensor):
        return x @ jnp.asarray(w).astype(x.dtype)
    # scale commutes iff it is constant along every contracted axis of w
    # (all axes but the last): quantize(channel_axis=-1) keeps them as
    # singleton dims
    if w.q.ndim != 2 or w.scale.shape[:-1] != (1,) * (w.q.ndim - 1):
        return x @ w.dequantize(x.dtype)
    out = x @ w.q.astype(x.dtype)
    scale = w.scale.reshape(-1)
    return (out.astype(jnp.float32) * scale).astype(x.dtype)


def quantize_tree(
    params: Any,
    min_rank: int = 2,
    predicate: Optional[Callable[[tuple, jnp.ndarray], bool]] = None,
    channel_axis: int = -1,
) -> Any:
    """Quantize every floating leaf with rank >= ``min_rank`` (weights;
    biases/norms stay full precision). ``predicate(path, leaf)`` can veto
    individual leaves (e.g. keep embeddings full precision)."""

    def maybe_q(path, leaf):
        if isinstance(leaf, QuantizedTensor):
            return leaf  # idempotent on already-quantized trees
        arr = jnp.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.floating) or arr.ndim < min_rank:
            return leaf
        if predicate is not None and not predicate(path, arr):
            return leaf
        return quantize(arr, channel_axis)

    # is_leaf stops tree_map from descending INTO QuantizedTensor (a
    # registered pytree) and re-quantizing its scale array
    return jax.tree_util.tree_map_with_path(
        maybe_q, params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def tree_nbytes(params: Any) -> int:
    """Total parameter bytes (QuantizedTensor-aware) — the HBM footprint
    the quantization exists to shrink."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.nbytes
        else:
            arr = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
            total += int(np.prod(arr.shape)) * arr.dtype.itemsize
    return total
