"""Long-context attention kernels: blockwise, pallas-flash, and ring.

The reference has **no** sequence/long-context support at all (SURVEY.md
§5: max tensor order 2 per cell; scaling is by rows only). For the TPU
framework long-context is first-class: these kernels power the
transformer model family and are public ops in their own right.

Three implementations, one contract (``[batch, heads, seq, head_dim]``):

* :func:`blockwise_attention` — pure-jax online-softmax scan over key/value
  chunks (memory O(seq·block) instead of O(seq²)); runs on any backend and
  is the reference implementation for the other two.
* :func:`flash_attention` — dispatches to the TPU pallas flash kernel
  (VMEM-tiled MXU kernel) on TPU backends, else falls back to blockwise.
* :func:`ring_attention` — sequence parallelism over a mesh axis: q/k/v
  are sharded on the sequence dim; each device scans the full sequence by
  rotating its k/v shard around the ring with ``lax.ppermute`` (ICI
  neighbor exchange) while accumulating the online softmax. Communication
  overlaps compute, memory per device is O(seq/sp), and the math is
  exactly dense attention.

Serving decode adds a fourth: :func:`paged_decode_attention` — the
fused paged int8-KV kernel (``kernels/decode_attention.py``) behind
the same public surface, selected per engine by the plan cost model.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils import get_logger

logger = get_logger(__name__)

NEG_INF = -1e30


def _enable_x64_ctx():
    """The x64 context manager moved from ``jax.experimental.enable_x64``
    to ``jax.enable_x64`` (jax >= 0.9); support both spellings."""
    try:
        from jax.experimental import enable_x64  # jax < 0.9
    except ImportError:
        enable_x64 = jax.enable_x64
    return enable_x64


def _online_block(
    q: jnp.ndarray,  # [b, h, sq, d] (pre-scaled)
    k: jnp.ndarray,  # [b, h, sk, d]
    v: jnp.ndarray,  # [b, h, sk, d]
    o: jnp.ndarray,  # [b, h, sq, d] f32 accumulator
    m: jnp.ndarray,  # [b, h, sq] f32 running max
    l: jnp.ndarray,  # [b, h, sq] f32 running denominator
    mask: Optional[jnp.ndarray],  # [sq, sk] bool or None
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-softmax accumulation step (flash-attention recurrence)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # rows with nothing attended yet keep m at NEG_INF; exp underflows to 0
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
    )
    return o_new, m_new, l_new


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_size: int = 512,
) -> jnp.ndarray:
    """Memory-efficient attention: lax.scan over k/v chunks with an online
    softmax. Exact (not an approximation); peak memory O(sq · block_size)
    per head instead of O(sq · sk)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_size = min(block_size, sk)
    num_blocks = -(-sk // block_size)
    pad = num_blocks * block_size - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    scale = float(1.0 / np.sqrt(d))  # python float: weak-typed, no f64 promotion under x64
    qs = (q * scale).astype(q.dtype)

    kb = k.reshape(b, h, num_blocks, block_size, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, num_blocks, block_size, d).transpose(2, 0, 1, 3, 4)

    q_pos = jnp.arange(sq)
    k_pos_base = jnp.arange(block_size)

    def step(carry, inp):
        o, m, l = carry
        blk_idx, k_blk, v_blk = inp
        if causal or pad:
            k_pos = blk_idx * block_size + k_pos_base
            mask = k_pos[None, :] < sk  # mask padding
            if causal:
                mask = mask & (q_pos[:, None] >= k_pos[None, :])
        else:
            mask = None
        o, m, l = _online_block(qs, k_blk, v_blk, o, m, l, mask)
        return (o, m, l), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (o, m, l), _ = jax.lax.scan(
        step, (o0, m0, l0), (jnp.arange(num_blocks), kb, vb)
    )
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_size: int = 512,
) -> jnp.ndarray:
    """TPU pallas flash kernel when available, else blockwise fallback."""
    if jax.default_backend() in ("tpu", "axon") and _pallas_flash_usable():
        try:
            enable_x64 = _enable_x64_ctx()
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as pallas_flash,
            )

            d = q.shape[-1]
            # trace the kernel with x64 OFF: this package enables x64
            # globally, under which integer literals in the upstream
            # kernel's index maps trace as i64 beside i32 grid indices —
            # the same Mosaic func.return legalization failure the
            # segment kernel hit (see ops/segment.py)
            with enable_x64(False):
                return pallas_flash(
                    q, k, v, causal=causal, sm_scale=float(1.0 / np.sqrt(d))
                )
        except Exception:
            # per-call trace-time rejections (seq not divisible by the
            # kernel's 128 block, unsupported dtype/head_dim) — the
            # canary only rules out process-wide Mosaic failures
            pass
    return blockwise_attention(q, k, v, causal=causal, block_size=block_size)


@functools.lru_cache(maxsize=1)
def _pallas_flash_usable() -> bool:
    """Compile-probe the upstream pallas flash kernel ONCE per process on
    a canary shape. A trace-time try/except alone cannot protect callers:
    a Mosaic legalization failure surfaces at the OUTER jit's compile,
    long after this helper returned — so compile a tiny standalone jit
    here and fall back to blockwise attention for the whole process if
    it fails (the same self-healing contract as the segment kernel's
    kill-switch, ops/segment.py)."""
    try:
        enable_x64 = _enable_x64_ctx()
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as pallas_flash,
        )

        with enable_x64(False):
            q = jnp.zeros((1, 2, 256, 128), jnp.float32)
            jax.jit(
                lambda a, b, c: pallas_flash(a, b, c, causal=False)
            ).lower(q, q, q).compile()
        return True
    except Exception as e:  # pragma: no cover - backend-dependent
        logger.warning(
            "pallas flash attention unusable on this backend (%s: %s); "
            "using blockwise attention",
            type(e).__name__, e,
        )
        return False


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism)
# ---------------------------------------------------------------------------

def _axis_size(axis_name: str) -> int:
    """Static size of a named mapped axis inside a shard_map body —
    ``jax.lax.axis_size`` where it exists (jax >= 0.6), the axis-env
    lookup on older releases. Always a Python int (the ring's permute
    schedule and scan length are build-time constants)."""
    lax_size = getattr(jax.lax, "axis_size", None)
    if lax_size is not None:
        return int(lax_size(axis_name))
    from jax._src import core as _core

    return int(_core.get_axis_env().axis_size(axis_name))


def _ring_attention_local(
    q: jnp.ndarray,  # [b, h, s_loc, d] — local sequence shard
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool,
) -> jnp.ndarray:
    """shard_map body: rotate k/v shards around the ring while accumulating
    the online softmax for the local queries."""
    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = float(1.0 / np.sqrt(d))  # weak-typed: no f64 promotion under x64
    qs = (q * scale).astype(q.dtype)
    q_pos = my * s_loc + jnp.arange(s_loc)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        o, m, l, k_cur, v_cur = carry
        # the shard we currently hold originated on device (my - t) mod n
        src = (my - t) % n
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        o, m, l = _online_block(qs, k_cur, v_cur, o, m, l, mask)
        # rotate k/v to the next device; overlaps with next iteration's
        # compute under XLA's async collective scheduling
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n)
    )
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def _maybe_axis(mesh: Mesh, name: Optional[str], dim_size: int) -> Optional[str]:
    """Use mesh axis ``name`` for a dim only when it exists and divides the
    dim evenly; otherwise keep the dim replicated (shard_map would reject
    an uneven split)."""
    if not name or name not in mesh.shape:
        return None
    return name if dim_size % mesh.shape[name] == 0 else None


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = "tp",
) -> jnp.ndarray:
    """Sequence-parallel exact attention over ``mesh[axis]``.

    Inputs are global arrays [b, heads, seq, head_dim] with ``seq``
    (logically) sharded over ``axis``; ``seq`` must divide evenly by the
    axis size. Batch / heads may additionally be sharded over
    ``batch_axis`` / ``head_axis`` (heads stay tp-sharded end-to-end in
    the Megatron layout instead of being all-gathered at the shard_map
    boundary).
    """
    from ..parallel._shard_map import shard_map

    seq = q.shape[2]
    sp = mesh.shape[axis]
    if seq % sp != 0:
        raise ValueError(
            f"ring_attention: seq {seq} not divisible by mesh axis "
            f"{axis!r} of size {sp}"
        )
    db = _maybe_axis(mesh, batch_axis, q.shape[0])
    ha = _maybe_axis(mesh, head_axis, q.shape[1])
    spec = P(db, ha, axis, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    batch_axis: Optional[str] = "dp",
) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style) — the
    complement of :func:`ring_attention`.

    Inputs are [b, heads, seq, head_dim] with ``seq`` sharded over
    ``axis``. Two ``all_to_all`` collectives re-shard: heads scatter
    across the sp group while sequence gathers (each device then holds the
    FULL sequence for heads/sp heads), standard blockwise attention runs
    locally with no per-step communication, and the reverse exchange
    restores sequence sharding. Versus the ring: 2 bulk a2a transfers
    instead of sp ppermute rounds — better when ICI latency dominates and
    heads divide evenly; the ring wins when heads < sp or memory for the
    full sequence per head is tight.
    """
    from ..parallel._shard_map import shard_map

    seq, heads = q.shape[2], q.shape[1]
    sp = mesh.shape[axis]
    if seq % sp != 0:
        raise ValueError(
            f"ulysses_attention: seq {seq} not divisible by mesh axis "
            f"{axis!r} of size {sp}"
        )
    if heads % sp != 0:
        raise ValueError(
            f"ulysses_attention: heads {heads} not divisible by mesh axis "
            f"{axis!r} of size {sp} (use ring_attention for heads < sp)"
        )
    db = _maybe_axis(mesh, batch_axis, q.shape[0])

    def local(qs, ks, vs):
        # one fused exchange for q/k/v (stacked on a lead axis): heads
        # scatter (split dim 2), sequence gathers (concat dim 3)
        # [3, b, h, s/sp, d] → [3, b, h/sp, s, d]
        qkv = jnp.stack([qs, ks, vs])
        qkv = lax.all_to_all(qkv, axis, split_axis=2, concat_axis=3, tiled=True)
        ctx = blockwise_attention(qkv[0], qkv[1], qkv[2], causal=causal)
        # reverse: sequence scatters, heads gather → [b, h, s/sp, d]
        return lax.all_to_all(ctx, axis, split_axis=2, concat_axis=1, tiled=True)

    spec = P(db, None, axis, None)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check=False,
    )
    return fn(q, k, v)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_scale: jnp.ndarray,
    layer: int,
    tables: jnp.ndarray,
    pos: jnp.ndarray,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused paged int8-KV decode attention (ISSUE 12): one kernel
    gathers each slot's pages through its page table (scalar-prefetch
    index maps, pages stream HBM→VMEM as int8), dequantizes
    in-register, and computes the masked softmax attention — the
    public face of ``kernels/decode_attention.paged_decode_attention``.
    ``q`` [slots, heads, head_dim]; the pool arrays are the
    ``models/generation.init_paged_kv`` layout. Bit-identical to the
    XLA gather→dequant→attend chain on the CPU interpreter (asserted
    in tests). The serving decode engine selects it per engine via the
    cost model (``plan/rules.decide_decode_attention``)."""
    from ..kernels.decode_attention import (
        paged_decode_attention as _kernel,
    )

    return _kernel(
        q, k_pages, v_pages, k_scale, v_scale, layer, tables, pos,
        interpret=interpret,
    )


def dense_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    padding_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Plain O(s²) attention — the correctness oracle for the kernels.

    ``padding_mask``: bool [batch, seq_k]; False positions are masked out.
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q / float(np.sqrt(d)), k,
        preferred_element_type=jnp.float32,
    )
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    if padding_mask is not None:
        s = jnp.where(padding_mask[:, None, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)
