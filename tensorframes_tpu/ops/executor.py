"""Block execution engine: marshalling + compiled-program caching.

This layer replaces the reference's per-partition worker kernels
(``DebugRowOpsImpl``, impl/DebugRowOps.scala:704-980) and its Row⇄Tensor
marshalling stack (``TFDataOps``/``DataOps``/``datatypes``). Where the
reference opens a fresh TF ``Graph``+``Session`` per partition
(TensorFlowOps.scala:76-95) and hand-rolls buffer fill loops
(DataOps.scala:63-81), here each program is ``jax.jit``-compiled **once per
distinct block shape** and cached by XLA; marshalling is a zero-copy
``numpy → jax.Array`` device transfer.

Block row counts produced by the frame partitioner take at most two
distinct values (n//k and n//k+1), so map_blocks' jit cache stays tiny
without padding. map_rows additionally buckets its vmapped lead dim to
powers of two (:func:`bucket_rows`) so externally-built frames with
arbitrary block sizes — and ragged blocks grouped by cell shape — keep
the compile count O(log n); ``cache_sizes`` gives the honest recompile
accounting SURVEY.md §7 hard-part 1 calls for.

Dispatch is ONE pipeline (ISSUE 10): every feed — host blocks,
multi-device sharded columns, multi-process SPMD frames, callback
programs — keys by (entry kind, feed shapes/dtypes, input placements)
and builds a per-key executable by explicit ``lower().compile()``,
consulting the persistent store (:mod:`tensorframes_tpu.compilecache`)
first. That is the Julia-to-TPU thesis (arXiv 1810.09868) applied at
the executor: whole programs compiled ahead-of-time for the actual
target topology, never per-process lazy jit. The old jax.jit path
survives only as :meth:`CompiledProgram._fallback_call` — an
explicitly-counted last resort for programs whose AOT build raises —
and :func:`aot_jit` offers the same pipeline for arbitrary pytree
functions (the model train steps the MULTICHIP dryruns compile).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes as dt
from ..config import get_config
from ..observability import events as _events
from ..observability import flight as _flight
from ..observability import latency as _latency
from ..observability.metrics import counter as _counter
from ..observability.metrics import histogram as _histogram
from ..program import Program
from ..resilience import fleet as _fleet
from ..resilience.faults import delay_point, fault_point, register_site
from ..utils import get_logger

logger = get_logger(__name__)

register_site(
    "executor.dispatch",
    "CompiledProgram._run dispatch body, inside the deadline-watchdog "
    "scope — an injected Delay simulates a hung collective (the "
    "dispatch stalls instead of failing) so the watchdog is drillable",
)

# Registered at import so the exposition always carries the executor
# family (a cold cache reads hits=0, it does not vanish). "Hit" means
# this CompiledProgram has already dispatched this exact feed-shape key;
# A miss's cost is split honestly (ISSUE 5 satellite, completed by the
# ISSUE 10 unification): trace + XLA compile lands in compile-seconds
# (skipped entirely when the persistent store serves the executable —
# compare against tftpu_compilecache_load_seconds), the first execution
# in first-run-seconds — on EVERY dispatch path, sharded and
# multi-process included. The old "legacy fallback lumps compile+run"
# caveat is gone with the legacy path: the last-resort jit fallback is
# separately counted and observes neither histogram. This is the honest
# recompile accounting SURVEY §7 hard-part 1 asks for.
_JIT_HITS = _counter(
    "tftpu_executor_jit_cache_hits_total",
    "Dispatches whose feed-shape/placement key was already compiled",
)
_JIT_MISSES = _counter(
    "tftpu_executor_jit_cache_misses_total",
    "Dispatches that required a fresh executable (compiled or loaded "
    "from the persistent store)",
)
_COMPILE_SECONDS = _histogram(
    "tftpu_executor_compile_seconds",
    "Trace + XLA-compile wall-clock per feed-shape key (persistent-"
    "store hits skip it; run time is never included)",
)
_FIRST_RUN_SECONDS = _histogram(
    "tftpu_executor_first_run_seconds",
    "Wall-clock of the first execution per feed-shape key, compile "
    "excluded",
)
_FALLBACK_DISPATCHES = _counter(
    "tftpu_executor_fallback_dispatch_total",
    "Dispatches that could not build an AOT executable and fell back "
    "to lazy jax.jit (last resort; the failure reason is logged once "
    "per key)",
)
_PADDING_WASTE = _counter(
    "tftpu_executor_padding_waste_rows_total",
    "Rows added by bucket padding of the vmapped lead dim",
)
_GATHER_BYTES = _counter(
    "tftpu_executor_gather_bytes_total",
    "Bytes of feed columns gathered for program dispatch — the plan "
    "layer's select pushdown shows up as this counter NOT growing for "
    "pruned columns",
)


def donation_supported() -> bool:
    """True when the active backend implements input-buffer donation.
    XLA:CPU ignores donation with a per-call warning, so the donate
    paths gate on this instead of spamming host-only runs."""
    return jax.default_backend() not in ("cpu",)


def bucket_rows(n: int) -> int:
    """Round a row count up to the next power-of-two bucket:
    ``min_bucket * 2**k`` for the smallest k that fits, bounded by
    ``max_bucket_doublings`` (config). Beyond the largest bucket the
    exact count is returned — an honest exact-shape compile instead of
    unbounded padding.

    This is the static-shape answer to the reference's per-shape
    recompiles (DataOps.scala:103-144 dynamic-shape handling; SURVEY §7
    hard-part 1): padding the *vmapped lead dim* keeps the jit cache
    O(log n) over arbitrary block sizes. Only row-independent (map_rows)
    semantics may use it — padded rows are sliced off after execution.
    """
    cfg = get_config()
    b = max(1, int(cfg.min_bucket))
    if n <= b:
        return b
    for _ in range(max(0, int(cfg.max_bucket_doublings))):
        b *= 2
        if b >= n:
            return b
    return n


def bucket_table() -> List[int]:
    """The lead-dim bucket ladder :func:`bucket_rows` rounds into under
    the current config: ``[min_bucket, min_bucket*2, …]``, one entry per
    allowed doubling. The static analyzer's recompile-storm rule
    (TFG101) cross-checks program shapes against this table — an
    Unknown dim the ladder cannot bound compiles per distinct extent."""
    cfg = get_config()
    b = max(1, int(cfg.min_bucket))
    out = [b]
    for _ in range(max(0, int(cfg.max_bucket_doublings))):
        b *= 2
        out.append(b)
    return out


def pad_lead_dim(
    feeds: Dict[str, np.ndarray], n: int, target: int
) -> Dict[str, np.ndarray]:
    """Pad every feed's leading dim from ``n`` to ``target`` rows by
    replicating the last row (replication keeps padded rows numerically
    tame — no 0-divides or log(0) from zero fill; results are sliced back
    to ``n`` rows by the caller)."""
    if target == n:
        return feeds
    _PADDING_WASTE.inc(target - n)
    out = {}
    for k, v in feeds.items():
        v = np.asarray(v)
        pad = np.broadcast_to(v[-1:], (target - n,) + v.shape[1:])
        out[k] = np.concatenate([v, pad])
    return out


def _sharding_token(sh) -> Optional[str]:
    """Canonical JSON of a sharding's descriptor, memoized per
    (sharding, current default device) — jax shardings are hashable and
    reused across dispatches, and rebuilding the descriptor walks
    mesh.devices per feed per call, per-step overhead the replaced raw
    jax.jit dispatch never paid. The default device is part of the memo
    key because the descriptor normalizes the default placement to the
    trivial token: a mid-process ``jax_default_device`` change must not
    serve stale Nones. None for the trivial placement."""
    from ..parallel.mesh import default_device

    return _sharding_token_cached(sh, default_device())


@functools.lru_cache(maxsize=256)
def _sharding_token_cached(sh, _default_dev) -> Optional[str]:
    import json as _json

    from ..parallel.mesh import sharding_descriptor

    desc = sharding_descriptor(sh)
    return None if desc is None else _json.dumps(desc, sort_keys=True)


def _feed_sharding(v):
    """The feed's sharding when it is a NON-TRIVIAL placement (sharded
    over a mesh, or committed to a non-default device), else None —
    host arrays and default-device feeds keep a placement-free identity
    so warmed shapes match them regardless of how the data arrives."""
    try:
        sh = getattr(v, "sharding", None)
        if sh is None:
            return None
        return sh if _sharding_token(sh) is not None else None
    except Exception:  # pragma: no cover - defensive: never block dispatch
        return None


def _placement_token(v) -> Optional[str]:
    """Hashable dispatch-key component for a feed's placement (the
    canonical JSON of its sharding descriptor; None for the trivial
    placement). An AOT executable is layout-specialized — calling it
    with differently-sharded arguments raises — so the placement is
    part of the dispatch identity exactly like shape and dtype."""
    try:
        sh = getattr(v, "sharding", None)
        return None if sh is None else _sharding_token(sh)
    except Exception:  # pragma: no cover - defensive: never block dispatch
        return None


class _KeyedBuildCache:
    """Double-checked per-key build memoization shared by the two AOT
    builders (CompiledProgram executables and _AotJit entries): an
    outer lock guards the maps, builds serialize on a PER-KEY lock so
    distinct keys compile concurrently, and a key whose build raised is
    memoized as failed — callers fall back to lazy jit. ONE copy of the
    protocol, so a lock-ordering or accounting fix cannot silently skip
    one builder."""

    def __init__(self):
        self.built: Dict[Tuple, object] = {}
        self.failed: set = set()
        self._lock = threading.Lock()
        self._key_locks: Dict[Tuple, threading.Lock] = {}

    def peek(self, key):
        """Lock-free read for the dispatch fast path (dict.get is
        GIL-atomic); None when unbuilt or failed."""
        return self.built.get(key)

    def get_or_build(self, key: Tuple, build: Callable,
                     describe: str) -> Tuple[object, str]:
        """Return ``(value, how)`` — ``('cached')`` when already built,
        the builder's own ``(value, how)`` on a fresh build, or
        ``(None, 'failed')`` when this (or an earlier) build of ``key``
        raised."""
        with self._lock:
            if key in self.built:
                return self.built[key], "cached"
            if key in self.failed:
                return None, "failed"
            key_lock = self._key_locks.setdefault(key, threading.Lock())
        with key_lock:
            with self._lock:  # lost the race: another thread built it
                if key in self.built:
                    return self.built[key], "cached"
                if key in self.failed:
                    return None, "failed"
            try:
                value, how = build()
            except Exception as e:
                logger.debug("AOT path unavailable for %s (%s); using "
                             "jit dispatch", describe, e)
                with self._lock:
                    self.failed.add(key)
                return None, "failed"
            with self._lock:
                self.built[key] = value
            return value, how


def _store_meta(kind: str, form: str, donate: bool, inputs,
                shardings: Dict, multiprocess: bool,
                rank: Optional[int], label: Optional[str] = None) -> Dict:
    """The ONE store-entry meta schema, shared by both AOT builders
    (CompiledProgram and _AotJit) so an accounting or schema change
    cannot silently diverge between the two dispatch entries."""
    meta = {
        "kind": kind,
        "form": form,
        "donate": donate,
        "backend": jax.default_backend(),
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "jax": jax.__version__,
        "inputs": inputs,
    }
    if label is not None:
        meta["label"] = label
    if shardings:
        from ..parallel.mesh import sharding_descriptor

        meta["shardings"] = {
            k: sharding_descriptor(sh)
            for k, sh in sorted(shardings.items())
        }
    if multiprocess:
        meta["n_processes"] = jax.process_count()
        meta["published_by_rank"] = rank
    return meta


def _hoisted_for(fn, feeds: Dict[str, jnp.ndarray]):
    """Build a :class:`HoistedProgram` (program.py — weights as runtime
    arguments, device-committed once) at these feeds' shapes — and
    placements: sharded feeds trace (and later lower) with their
    shardings attached, so the hoisted executable is specialized to the
    same layout the dispatch will call it with."""
    from ..program import HoistedProgram

    abstract = {}
    for k, v in feeds.items():
        sh = _feed_sharding(v)
        abstract[k] = (
            jax.ShapeDtypeStruct(np.shape(v), v.dtype, sharding=sh)
            if sh is not None
            else jax.ShapeDtypeStruct(np.shape(v), v.dtype)
        )
    return HoistedProgram(fn, abstract)


class CompiledProgram:
    """A Program plus its jitted entrypoints (block and per-row)."""

    def __init__(self, program: Program, hoist_consts: Optional[bool] = None):
        self.program = program
        self.hoist = (
            get_config().hoist_constants if hoist_consts is None else hoist_consts
        )
        self.jit_block = jax.jit(program.fn)
        # vmapped form: maps the program over the leading axis of every
        # input — the TPU-native replacement for the reference's row loop
        # (performMapRows, DebugRowOps.scala:826-864).
        self.jit_vmap = jax.jit(jax.vmap(program.fn))
        # input-donating variants, built lazily: the caller passes
        # donate=True only for freshly-transferred host feeds, letting
        # XLA reuse input HBM for outputs (peak-footprint halving on
        # big blocks)
        self._jit_block_donate = None
        self._jit_vmap_donate = None
        self._hoisted: Dict[Tuple, object] = {}
        # feed-shape keys already dispatched at least once, per entry
        # kind — the basis of the exported jit-cache hit/miss counters
        # (mirrors what XLA's own cache will decide, without reaching
        # into jax internals on the hot path)
        self._dispatched: set = set()
        # per-feed-shape AOT executables (the primary dispatch path):
        # built by explicit lower().compile() — or deserialized from
        # the persistent store (compilecache) — so compile time and
        # run time are separately measurable, and a warm store can
        # skip XLA entirely. Keys include the donate variant; a failed
        # key permanently uses the legacy jit path instead.
        self._aot = _KeyedBuildCache()

    @staticmethod
    def _feeds_key(kind: str, feeds) -> Tuple:
        return (kind,) + tuple(
            sorted(
                (k, tuple(int(d) for d in np.shape(v)), str(v.dtype),
                 _placement_token(v))
                for k, v in feeds.items()
            )
        )

    def _note_dispatch(self, key: Tuple, donate: bool) -> bool:
        """Count a cache hit or miss for this dispatch; True on miss.
        ``donate`` is part of the dispatch identity — the donating
        variants compile through separate jitted callables, so a first
        donate=True call at a known shape is still a fresh compile."""
        if donate:
            key = key + ("donate",)
        if key in self._dispatched:
            _JIT_HITS.inc()
            return False
        self._dispatched.add(key)
        _JIT_MISSES.inc()
        return True

    def _entry(self, key: Tuple, fn, feeds):
        entry = self._hoisted.get(key)
        if entry is None:
            try:
                entry = _hoisted_for(fn, feeds)
            except Exception as e:
                # exotic programs (host callbacks, non-array consts) keep
                # the plain closure-capture path
                logger.debug("constant hoisting unavailable: %s", e)
                entry = False
            self._hoisted[key] = entry
        return entry

    def _kind_fn(self, kind: str) -> Callable:
        return self.program.fn if kind == "block" else jax.vmap(
            self.program.fn
        )

    def _fingerprint(self, kind: str, abstract: Dict, donate: bool,
                     entry) -> Optional[str]:
        """Persistent-store key for this (program, feed-shape, variant,
        placement). None when the program cannot be fingerprinted (no
        store use)."""
        from ..compilecache.fingerprint import fingerprint_from_closed

        avals = sorted(
            (k, tuple(int(d) for d in v.shape), str(v.dtype))
            for k, v in abstract.items()
        )
        shardings = {
            k: sh for k, v in abstract.items()
            if (sh := _feed_sharding(v)) is not None
        }
        outs = list(
            self.program.fetch_order
            or [o.name for o in self.program.outputs]
        )
        try:
            if entry:
                closed = entry.closed
                hoisted = True
            else:
                closed = jax.make_jaxpr(self._kind_fn(kind))(abstract)
                hoisted = False
            return fingerprint_from_closed(
                closed, avals, outs, kind=kind, donate=donate,
                hoisted=hoisted, shardings=shardings,
            )
        except Exception as e:
            from ..compilecache.store import note_unfingerprintable

            logger.debug("program not fingerprintable: %s", e)
            note_unfingerprintable()
            return None

    def _build_aot(self, kind: str, akey: Tuple, feeds: Dict,
                   donate: bool) -> Optional[Tuple[Callable, str]]:
        """Build the per-shape executable for ``akey``: trace (hoisted
        when possible), consult the persistent store, else AOT
        lower+compile (timed into compile-seconds) and publish to the
        store. Returns (callable, 'disk'|'compiled'), or None when this
        key must use the legacy jit path. ``feeds`` may be concrete
        arrays or ShapeDtypeStructs (warmup compiles without data)."""
        call, how = self._aot.get_or_build(
            akey,
            lambda: self._build_aot_impl(kind, akey, feeds, donate),
            describe=str(akey[0]),
        )
        return None if call is None else (call, how)

    def _build_aot_impl(self, kind, akey, feeds, donate):
        from ..compilecache import store as cc_store

        base = akey[:-1] if akey and akey[-1] == "donate" else akey
        abstract = {}
        shardings = {}
        for k, v in feeds.items():
            sh = _feed_sharding(v)
            if sh is not None:
                shardings[k] = sh
                abstract[k] = jax.ShapeDtypeStruct(
                    np.shape(v), v.dtype, sharding=sh
                )
            else:
                abstract[k] = jax.ShapeDtypeStruct(np.shape(v), v.dtype)
        multiprocess = jax.process_count() > 1
        t0 = time.perf_counter()
        # multi-process fleets keep the plain (closure-capture) form:
        # hoisted consts are committed to THIS rank's local device, so
        # a hoisted executable bakes a per-rank device assignment into
        # its input layout and could never be shared across the fleet's
        # store — baked consts compile identically on every rank
        entry = (
            self._entry(base, self._kind_fn(kind), feeds)
            if self.hoist and not multiprocess else None
        )
        trace_s = time.perf_counter() - t0

        store = None
        fp = None
        rank = jax.process_index() if multiprocess else None
        from ..plan.ir import program_has_callback

        if not program_has_callback(self.program):
            # callback programs bind process-local host functions — an
            # executable serialized from one process cannot call back
            # into another's registry, so they never touch the store
            # (in-process AOT still applies, through this same pipeline,
            # so the hit/compile/first-run accounting stays uniform)
            store = cc_store.active_store()
        if store is not None:
            fp = self._fingerprint(kind, abstract, donate, entry)
        meta_inputs = sorted(
            (k, list(v.shape), str(v.dtype)) for k, v in abstract.items()
        )
        if fp is not None:
            loaded = store.get(fp, rank=rank)
            if loaded is not None:
                return self._wrap_executable(entry, loaded), "disk"
            store.record_miss(
                kind,
                [(n, tuple(s), d) for (n, s, d) in meta_inputs],
                donate,
                sharded=bool(shardings),
            )

        t1 = time.perf_counter()
        if entry:
            jitted = (
                jax.jit(entry._run, donate_argnums=(1,))
                if donate else entry.jitted
            )
            compiled = jitted.lower(
                entry.consts, entry._flat_abstract
            ).compile()
        else:
            jitted = (
                jax.jit(self._kind_fn(kind), donate_argnums=(0,))
                if donate else jax.jit(self._kind_fn(kind))
            )
            compiled = jitted.lower(abstract).compile()
        _COMPILE_SECONDS.observe(trace_s + (time.perf_counter() - t1))
        if fp is not None:
            meta = _store_meta(
                kind, "hoisted" if entry else "plain", donate,
                meta_inputs, shardings, multiprocess, rank,
            )
            store.put(fp, compiled, meta=meta, rank=rank)
        return self._wrap_executable(entry, compiled), "compiled"

    @staticmethod
    def _wrap_executable(entry, executable) -> Callable:
        """Close the executable over its call convention: hoisted form
        takes (consts, flat_inputs), plain form the feeds dict."""
        if entry:
            in_tree = entry.in_tree
            consts = entry.consts

            def call(feeds):
                flat, tree = jax.tree_util.tree_flatten(feeds)
                if tree != in_tree:
                    raise ValueError(
                        "input structure changed since tracing"
                    )
                return executable(consts, flat)

            return call
        return lambda feeds: executable(feeds)

    def warm(self, kind: str, abstract: Dict[str, object],
             donate: bool = False) -> str:
        """Precompile (or disk-load) the executable for one feed-shape
        key WITHOUT executing it — ``abstract`` maps input names to
        ShapeDtypeStructs (attach a ``sharding`` to warm a sharded
        placement's key). The key is marked dispatched, so the first
        real dispatch at this shape counts as a jit-cache hit (no
        compile happens there). Multi-process fleets warm like anything
        else — every dispatch rides the unified AOT path, so the old
        refusal (warming keys the legacy jit path would bypass) has
        nothing left to refuse. Returns 'cached' | 'disk' | 'compiled'
        | 'failed'."""
        donate = donate and donation_supported()
        key = self._feeds_key(kind, abstract)
        akey = key + ("donate",) if donate else key
        built = self._build_aot(kind, akey, abstract, donate)
        if built is None:
            return "failed"
        self._dispatched.add(akey)
        return built[1]

    def _run(self, kind: str, feeds, to_numpy: bool, donate: bool):
        # flight-record identity of this dispatch BEFORE anything can
        # fail (fault injection fires at the fault_point below): a crash
        # postmortem must carry the dispatch that was in flight
        def _shape_of(v):
            s = getattr(v, "shape", None)
            if s is not None:
                return list(s)
            try:
                return [len(v)]  # ragged list feed: lead dim only
            except TypeError:
                return []

        summary = {
            "entry": kind,
            "outputs": ",".join(self.program.fetch_order[:6]),
            "shapes": {
                k: _shape_of(v) for k, v in list(feeds.items())[:6]
            },
        }
        try:
            fault_point(
                f"executor.run_{'block' if kind == 'block' else 'rows'}"
            )
            donate = donate and donation_supported()
            feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
            key = self._feeds_key(kind, feeds)
            # NOTE: the hoisted entry is keyed WITHOUT donate (one
            # HoistedProgram serves both; donation is a call-time
            # argument), while the hit/miss identity includes it
            # (donate variants are separate executables)
            akey = key + ("donate",) if donate else key
            fresh = self._note_dispatch(key, donate)
            call = self._aot.peek(akey)
            if call is None:
                built = self._build_aot(kind, akey, feeds, donate)
                if built is not None:
                    call = built[0]
            deadline = _fleet.dispatch_deadline_s()
            if deadline and call is None and fresh:
                # last-resort jit fallback, first dispatch at this
                # shape: the XLA compile happens lazily INSIDE the call
                # (the unified AOT path compiles outside the watchdog,
                # above — so a store-hit or freshly-AOT-compiled first
                # dispatch stays bounded). A 20-40s TPU compile is not
                # a hung collective — and under supervise() a
                # deterministic compile > deadline would burn the whole
                # restart budget without any rank ever being hung.
                # Genuine cache-miss lazy compiles are therefore the
                # ONLY exempt dispatches (counted, so an exemption in
                # steady state is visible); everything else stays
                # bounded.
                _fleet.note_deadline_exemption(
                    f"executor.run_{'block' if kind == 'block' else 'rows'}"
                )
                deadline = 0.0

            def _invoke():
                delay_point("executor.dispatch")
                r = (
                    call(feeds) if call is not None
                    else self._fallback_call(kind, key, feeds, donate)
                )
                if deadline:
                    # deadline mode synchronizes: a collective wedged on
                    # a dead peer must hang INSIDE the watchdog scope,
                    # not at a later np.asarray outside it
                    r = jax.block_until_ready(r)
                return r

            t0 = time.perf_counter()
            if deadline:
                out = _fleet.run_with_deadline(
                    _invoke,
                    describe=(
                        f"executor.run_"
                        f"{'block' if kind == 'block' else 'rows'}"
                        f"[{','.join(self.program.fetch_order[:4])}]"
                    ),
                    deadline=deadline,
                )
            else:
                out = _invoke()
            dt = time.perf_counter() - t0
        except BaseException as e:
            _flight.record(
                "dispatch.error", error=type(e).__name__,
                message=str(e), **summary,
            )
            raise
        _latency.dispatch_histogram(kind).observe(dt)
        _flight.record(
            "dispatch", seconds=round(dt, 6), compiled=fresh, **summary
        )
        if fresh:
            if call is not None:
                _FIRST_RUN_SECONDS.observe(dt)
            # the jit fallback's lazy compile+run is deliberately NOT
            # observed into compile-seconds: that histogram times pure
            # trace+XLA-compile on every path now, and the fallback has
            # its own counter (lumping would resurrect the pre-unification
            # accounting caveat)
        if _events.TRACER.enabled:
            _events.TRACER.emit_complete(
                f"executor.run_{'block' if kind == 'block' else 'rows'}",
                t0, dt, args={"compiled": fresh}, cat="executor",
            )
        if not to_numpy:
            return out  # stay in HBM: sharded frames chain without transfers
        return {k: np.asarray(v) for k, v in out.items()}

    def _fallback_call(self, kind: str, key: Tuple, feeds, donate: bool):
        """Last-resort lazy jax.jit dispatch, reachable ONLY when the
        unified AOT build raised (``_aot.failed``) — every normal feed
        class (host, sharded, multi-process, callback) rides the AOT
        pipeline. Explicitly counted so a fleet quietly living on this
        path is visible in the exposition; the build failure itself is
        logged by :meth:`_build_aot`."""
        _FALLBACK_DISPATCHES.inc()
        entry = (
            self._entry(key, self._kind_fn(kind), feeds)
            if self.hoist else None
        )
        if entry:
            return entry(feeds, donate=donate)
        if kind == "block":
            if donate:
                if self._jit_block_donate is None:
                    self._jit_block_donate = jax.jit(
                        self.program.fn, donate_argnums=(0,)
                    )
                return self._jit_block_donate(feeds)
            return self.jit_block(feeds)
        if donate:
            if self._jit_vmap_donate is None:
                self._jit_vmap_donate = jax.jit(
                    jax.vmap(self.program.fn), donate_argnums=(0,)
                )
            return self._jit_vmap_donate(feeds)
        return self.jit_vmap(feeds)

    def run_block(
        self,
        feeds: Dict[str, np.ndarray],
        to_numpy: bool = True,
        donate: bool = False,
    ) -> Dict[str, np.ndarray]:
        return self._run("block", feeds, to_numpy, donate)

    def run_rows(
        self,
        feeds: Dict[str, np.ndarray],
        to_numpy: bool = True,
        donate: bool = False,
    ) -> Dict[str, np.ndarray]:
        return self._run("vmap", feeds, to_numpy, donate)

    def run_rows_bucketed(
        self,
        feeds: Dict[str, np.ndarray],
        to_numpy: bool = True,
        donate: bool = False,
    ) -> Dict[str, np.ndarray]:
        """The serving layer's batched dispatch entry (ISSUE 9): pad
        the shared lead dim up the power-of-two ladder
        (:func:`bucket_rows` — the same policy ``compilecache.warmup``
        precompiles), run the vmapped program, slice back to the true
        row count. Unlike ``map_rows``' adaptive bucketing this ALWAYS
        buckets, so a server warmed over the ladder dispatches any
        admissible row count with zero steady-state compiles — and a
        row's result is bit-identical however it was coalesced (vmap is
        row-independent; padding replicates the last row and is sliced
        off here)."""
        sizes = {k: int(np.shape(v)[0]) for k, v in feeds.items()}
        ns = set(sizes.values())
        if len(ns) != 1:
            raise ValueError(
                f"run_rows_bucketed: feeds disagree on the lead dim: "
                f"{sizes}"
            )
        n = ns.pop()
        if n == 0:
            raise ValueError("run_rows_bucketed: zero-row dispatch")
        feeds = pad_lead_dim(feeds, n, bucket_rows(n))
        outs = self._run("vmap", feeds, to_numpy=False, donate=donate)
        outs = {k: v[:n] for k, v in outs.items()}
        if not to_numpy:
            return outs
        return {k: np.asarray(v) for k, v in outs.items()}

    def cache_sizes(self) -> Dict[str, int]:
        """Honest recompile accounting (SURVEY §7 hard-part 1): how many
        distinct shapes each entrypoint holds an executable for (AOT
        entries — compiled or store-loaded — plus legacy jit/hoisted
        compiles; donate variants of one shape count once, as before).
        Ragged map_rows grows the vmap cache by one per distinct
        (cell shape, lead-dim bucket) group."""
        def size(fn) -> int:
            try:
                return int(fn._cache_size())
            except Exception:  # pragma: no cover - jax internals moved
                return -1

        aot_bases = {
            (k[:-1] if k and k[-1] == "donate" else k)
            for k in self._aot.built
        }

        def count(kind: str) -> int:
            aot = sum(1 for b in aot_bases if b[0] == kind)
            hoisted = sum(
                1 for k, v in self._hoisted.items()
                if v and k[0] == kind and k not in aot_bases
            )
            return aot + hoisted

        return {
            "block": size(self.jit_block) + count("block"),
            "vmap": size(self.jit_vmap) + count("vmap"),
        }


# ---------------------------------------------------------------------------
# aot_jit — the unified pipeline for arbitrary pytree functions
# ---------------------------------------------------------------------------

def _shardings_tree_token(tree) -> object:
    """JSON-able identity of a declared in/out_shardings pytree (None
    passes through; sharding leaves become their descriptors). Folded
    into the fingerprint's ``extra`` slot: two aot_jit entries tracing
    to the same jaxpr but declaring different output layouts compile
    different collective schedules and must key apart."""
    if tree is None:
        return None
    Sharding = jax.sharding.Sharding
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, Sharding)
    )
    from ..parallel.mesh import sharding_descriptor

    return {
        "tree": str(treedef),
        "leaves": [
            sharding_descriptor(leaf) if isinstance(leaf, Sharding)
            else (None if leaf is None else str(leaf))
            for leaf in leaves
        ],
    }


class _AotJit:
    """``jax.jit``-shaped callable whose dispatch rides the executor's
    unified AOT pipeline: per-argument-shape/placement keys, explicit
    ``lower().compile()`` timed into ``tftpu_executor_compile_seconds``,
    the persistent store consulted first (topology-fingerprinted, so a
    fleet restart loads instead of recompiling), and the lazy-jit
    fallback explicitly counted. See :func:`aot_jit`."""

    def __init__(self, fn, in_shardings=None, out_shardings=None,
                 label: Optional[str] = None):
        kw = {}
        if in_shardings is not None:
            kw["in_shardings"] = in_shardings
        if out_shardings is not None:
            kw["out_shardings"] = out_shardings
        self._fn = fn
        self._jitted = jax.jit(fn, **kw)
        self._label = label or getattr(fn, "__qualname__", None) \
            or type(fn).__name__
        self._decl = {
            "in_shardings": _shardings_tree_token(in_shardings),
            "out_shardings": _shardings_tree_token(out_shardings),
        }
        self._builds = _KeyedBuildCache()
        self._dispatched: set = set()

    def _key(self, leaves, treedef) -> Optional[Tuple]:
        if any(
            not hasattr(v, "dtype") or not hasattr(v, "shape")
            for v in leaves
        ):
            # a Python-scalar leaf traces weakly-typed under jit; an AOT
            # executable is strongly typed — this entry stays lazy-jit
            return None
        # weak_type is part of the identity: a weak leaf promotes
        # differently (int8 + weak int stays int8), so a weak and a
        # strong feed of the same dtype must not share an executable.
        # The treedef enters as the OBJECT (hashable, eq-comparable) —
        # stringifying a transformer's param tree repr per step is
        # dispatch overhead the jax.jit C++ fast path never paid.
        return (treedef,) + tuple(
            (tuple(int(d) for d in v.shape), str(v.dtype),
             bool(getattr(v, "weak_type", False)), _placement_token(v))
            for v in leaves
        )

    def _build(self, key: Tuple, args) -> Optional[Callable]:
        call, _ = self._builds.get_or_build(
            key,
            lambda: (self._build_impl(args), "built"),
            describe=f"aot_jit({self._label})",
        )
        return call

    def _build_impl(self, args) -> Callable:
        from ..compilecache import store as cc_store
        from ..compilecache.fingerprint import fingerprint_from_closed

        def abstract_of(v):
            # weak_type must survive into the trace: dropping it would
            # promote int8 + weak-int to the weak leaf's dtype, a result
            # the jax.jit this wraps never produces
            weak = bool(getattr(v, "weak_type", False))
            sh = _feed_sharding(v)
            if sh is not None:
                return jax.ShapeDtypeStruct(np.shape(v), v.dtype,
                                            sharding=sh, weak_type=weak)
            return jax.ShapeDtypeStruct(np.shape(v), v.dtype,
                                        weak_type=weak)

        abstract = jax.tree_util.tree_map(abstract_of, args)
        multiprocess = jax.process_count() > 1
        rank = jax.process_index() if multiprocess else None

        t0 = time.perf_counter()
        closed = jax.make_jaxpr(self._fn)(*abstract)
        trace_s = time.perf_counter() - t0

        from ..analysis.rules import _iter_eqns

        has_callback = any(
            "callback" in eqn.primitive.name
            for eqn in _iter_eqns(closed.jaxpr)
        )
        leaves = jax.tree_util.tree_leaves(abstract)
        avals = [
            (f"a{i}", tuple(int(d) for d in v.shape), str(v.dtype))
            for i, v in enumerate(leaves)
        ]
        shardings = {
            f"a{i}": sh for i, v in enumerate(leaves)
            if (sh := getattr(v, "sharding", None)) is not None
        }
        store = None if has_callback else cc_store.active_store()
        fp = None
        if store is not None:
            # weak_type must reach the PERSISTENT key too: the jaxpr
            # text renders weak and strong avals identically, so without
            # this a strong-compiled store entry would be served to a
            # weak-typed feed of the same shape/dtype (the in-process
            # key already splits them)
            extra = dict(self._decl)
            weak = [
                bool(getattr(v, "weak_type", False)) for v in leaves
            ]
            if any(weak):
                extra["weak"] = weak
            try:
                fp = fingerprint_from_closed(
                    closed, avals, [self._label], kind="fn",
                    shardings=shardings, extra=extra,
                )
            except Exception as e:
                logger.debug("aot_jit(%s) not fingerprintable: %s",
                             self._label, e)
                cc_store.note_unfingerprintable()
        if fp is not None:
            loaded = store.get(fp, rank=rank)
            if loaded is not None:
                return lambda *a: loaded(*a)
            store.record_miss(
                "fn", [(n, tuple(s), d) for (n, s, d) in avals],
                False, sharded=bool(shardings),
            )
        t1 = time.perf_counter()
        compiled = self._jitted.lower(*abstract).compile()
        _COMPILE_SECONDS.observe(trace_s + (time.perf_counter() - t1))
        if fp is not None:
            meta = _store_meta(
                "fn", "plain", False,
                sorted((n, list(s), d) for (n, s, d) in avals),
                shardings, multiprocess, rank, label=self._label,
            )
            store.put(fp, compiled, meta=meta, rank=rank)
        return lambda *a: compiled(*a)

    def __call__(self, *args):
        leaves, treedef = jax.tree_util.tree_flatten(args)
        key = self._key(leaves, treedef)
        call = None
        if key is not None:
            fresh = key not in self._dispatched
            if fresh:
                self._dispatched.add(key)
                _JIT_MISSES.inc()
            else:
                _JIT_HITS.inc()
            call = self._build(key, args)
        else:
            # keyless (lazy-jit-only) entries still scope the deadline
            # exemption to the FIRST dispatch of each signature jax's
            # own trace cache would compile for — weak-typed Python
            # scalars key by type, not value. Without this, `fresh`
            # would hold on every call and permanently blind the fleet
            # watchdog to steady-state hangs of this entry.
            lazy_key = ("lazy", treedef) + tuple(
                (tuple(int(d) for d in v.shape), str(v.dtype),
                 _placement_token(v))
                if hasattr(v, "shape") and hasattr(v, "dtype")
                else (type(v).__name__,)
                for v in leaves
            )
            fresh = lazy_key not in self._dispatched
            if fresh:
                self._dispatched.add(lazy_key)
        deadline = _fleet.dispatch_deadline_s()
        if deadline and call is None and fresh:
            # same scoping as CompiledProgram._run: only a genuine
            # cache-miss lazy compile (the counted fallback) is exempt
            # from the dispatch deadline — AOT/store-served first
            # dispatches compiled above, outside the watchdog scope
            _fleet.note_deadline_exemption(f"aot_jit[{self._label}]")
            deadline = 0.0
        if call is None:
            _FALLBACK_DISPATCHES.inc()

        def _invoke():
            r = call(*args) if call is not None else self._jitted(*args)
            if deadline:
                r = jax.block_until_ready(r)
            return r

        t0 = time.perf_counter()
        if deadline:
            out = _fleet.run_with_deadline(
                _invoke, describe=f"aot_jit[{self._label}]",
                deadline=deadline,
            )
        else:
            out = _invoke()
        if fresh and call is not None:
            _FIRST_RUN_SECONDS.observe(time.perf_counter() - t0)
        return out


def aot_jit(fn, *, in_shardings=None, out_shardings=None,
            label: Optional[str] = None) -> Callable:
    """Drop-in replacement for ``jax.jit(fn, in_shardings=...,
    out_shardings=...)`` that dispatches through the executor's unified
    AOT pipeline (ISSUE 10): explicit ``lower().compile()`` per
    argument-shape/placement key with the compile timed into
    ``tftpu_executor_compile_seconds``, the persistent store
    (``TFTPU_COMPILE_CACHE``) consulted before XLA — keyed by the
    topology-fingerprinted content hash, so sharded and multi-process
    programs restart warm — and lazy jit surviving only as the counted
    last-resort fallback. The model train-step factories (transformer
    dp/tp/sp, MoE ep, pipeline pp) build their steps through this, which
    is what lets the MULTICHIP dryruns hit the store on a second run.

    Positional array arguments only (pytrees fine); a call with a
    Python-scalar leaf stays on the lazy-jit path for that key (an AOT
    executable is strongly typed; jit traces scalars weakly)."""
    return _AotJit(fn, in_shardings=in_shardings,
                   out_shardings=out_shardings, label=label)


def gather_feeds(
    block: Dict[str, object],
    input_names: Sequence[str],
    program: Program,
) -> Dict[str, np.ndarray]:
    """Materialize the program's input columns from a block as dense arrays.

    Ragged (list-stored) columns raise here with the analyze hint — the
    reference's equivalent failure happens in ``TFDataOps.convert``'s
    lead-dim check (TFDataOps.scala:28-59).
    """
    demote = dt.demotion_active()
    feeds = {}
    for name in input_names:
        v = block[name]
        if isinstance(v, list):
            spec = program.input(name)
            try:
                v = np.asarray(v, dtype=spec.dtype.np_dtype)
            except (ValueError, TypeError):
                raise ValueError(
                    f"Column {name!r} holds ragged cells and cannot form a "
                    "dense block. Use map_rows for ragged data, or run "
                    "analyze()/append_shape() if the cells are uniform."
                ) from None
        elif demote:
            # x64 demotion boundary: cast 64-bit columns down to the
            # program's 32-bit input spec (works for numpy and sharded
            # jax arrays alike — on device it is a cheap elementwise op)
            spec = program.input(name)
            if getattr(v, "dtype", None) != spec.dtype.np_dtype:
                v = v.astype(spec.dtype.np_dtype)
        feeds[name] = v
        nbytes = getattr(v, "nbytes", 0)
        if nbytes:
            _GATHER_BYTES.inc(int(nbytes))
    return feeds


def block_is_ragged(block: Dict[str, object], input_names: Sequence[str]) -> bool:
    for name in input_names:
        v = block[name]
        if isinstance(v, list):
            shapes = set()
            for c in v:
                shapes.add(np.shape(c))
                if len(shapes) > 1:
                    return True
    return False


# ---------------------------------------------------------------------------
# reduce_rows folds (sequential pairwise, ≙ performReducePairwise,
# DebugRowOps.scala:939-979 — but as a single lax.scan under one jit per
# block shape instead of one Session.run per row pair)
# ---------------------------------------------------------------------------

def pair_fold_body(program: Program, out_names: Sequence[str]) -> Callable:
    """The (unjitted) pairwise fold over the leading axis of per-output
    arrays: dict x -> [n, ...cell] (n >= 1) → dict x -> cell. Shared by
    the host fold (below) and the sharded reduce_rows program
    (verbs._sharded_reduce_rows_fn), so fold semantics cannot diverge."""

    def fold(cols: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        init = {x: cols[x][0] for x in out_names}
        rest = {x: cols[x][1:] for x in out_names}

        def step(carry, xs):
            feeds = {}
            for x in out_names:
                feeds[f"{x}_1"] = carry[x]
                feeds[f"{x}_2"] = xs[x]
            out = program.fn(feeds)
            return {x: out[x] for x in out_names}, None

        carry, _ = jax.lax.scan(step, init, rest)
        return carry

    return fold


def make_pair_fold(program: Program, out_names: Sequence[str]) -> Callable:
    """Jitted form of :func:`pair_fold_body`."""
    return jax.jit(pair_fold_body(program, out_names))
