"""Group-key encoding shared by the host aggregate path and the sharded
device plans: key columns → dense group ids in lexicographic group order
(the ordering Catalyst's groupBy output sort matched,
DebugRowOps.scala:583).

Two strategies:

* **dense span** — all-integer keys with a small mixed-radix span use
  pure O(n) arithmetic + bincount; no sort of any kind;
* **dictionary** — anything else encodes via ``np.unique`` per column,
  then a composite code. NaN float keys collapse into ONE group — the
  Catalyst/Spark groupBy convention (NaNs compare equal for grouping).

All arithmetic is performed in int64 regardless of the key column dtype
(an int8 key spanning -128..127 must not wrap its 255-wide offset).
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence, Tuple

import numpy as np

# max dense bucket count for the arithmetic strategy
DENSE_SPAN_LIMIT = 1 << 20


def group_ids(
    arrs: Sequence[np.ndarray],
) -> Tuple[np.ndarray, List[np.ndarray], int]:
    """Encode parallel key columns into dense group ids.

    Returns ``(seg_ids, group_key_cols, num_groups)`` where ``seg_ids``
    is int64 of row length, ``group_key_cols`` holds one array per input
    column with the key values of each group (lexicographic order), and
    ``num_groups`` is the distinct-group count.
    """
    arrs = [np.asarray(a) for a in arrs]
    if all(np.issubdtype(a.dtype, np.integer) for a in arrs):
        mins = [int(a.min()) for a in arrs]
        ranges = [int(a.max()) - m + 1 for a, m in zip(arrs, mins)]
        K = 1
        for r in ranges:  # python ints: no overflow past the gate
            K *= r
        if K <= DENSE_SPAN_LIMIT:
            comb = arrs[0].astype(np.int64) - mins[0]
            for a, m, r in zip(arrs[1:], mins[1:], ranges[1:]):
                comb = comb * np.int64(r) + (a.astype(np.int64) - m)
            counts = np.bincount(comb, minlength=K)
            present = np.flatnonzero(counts)
            remap = np.empty(K, np.int64)
            remap[present] = np.arange(len(present))
            seg_ids = remap[comb]
            strides = mixed_radix_strides(ranges)
            group_key_cols = [
                ((present // strides[i]) % ranges[i] + mins[i]).astype(
                    arrs[i].dtype
                )
                for i in range(len(arrs))
            ]
            return seg_ids, group_key_cols, len(present)
    if len(arrs) == 1:
        # single key column (the overwhelmingly common group_by shape):
        # the per-column encode IS the final answer — its codes are
        # already dense and lexicographically ordered, so the composite
        # re-unique below would be a redundant O(n log n) sort
        uniq, c = _unique_inverse(arrs[0])
        return c.astype(np.int64), [uniq], len(uniq)
    comb = None
    for a in arrs:
        _, c = _unique_inverse(a)
        c = c.astype(np.int64)
        if comb is None:
            comb = c
        else:
            # comb is densified each step (< n), so comb*radix+c stays
            # within int64 up to ~3e9 rows — no mixed-radix overflow
            _, comb = np.unique(comb, return_inverse=True)
            comb = comb.astype(np.int64) * np.int64(int(c.max()) + 1) + c
    _, first_idx, seg_ids = np.unique(
        comb, return_index=True, return_inverse=True
    )
    # each group's key values = the key tuple at its first occurrence
    group_key_cols = [a[first_idx] for a in arrs]
    return seg_ids.astype(np.int64), group_key_cols, len(first_idx)


def _unique_inverse(a: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """``np.unique(a, return_inverse=True)`` with an O(n) native hash
    pass for string/object columns (the 1M-row string-key aggregate spent
    ~0.8s in numpy's sort-based unique — the dominant cost the round-2
    verdict flagged). First-appearance codes remap through an argsort of
    the K uniques (tiny) so the lexicographic-order contract holds.
    Float columns keep numpy for its NaN-collapse convention."""
    if a.dtype == object or a.dtype.kind in ("U", "S"):
        if a.dtype == object:
            # Catalyst's grouping convention: NaN keys compare EQUAL
            # (one group). Canonicalize float-NaN cells to one singleton
            # so every downstream encode (native hash or python dict —
            # both resolve the singleton by identity) sees one key;
            # grouping semantics must not depend on whether the optional
            # native build succeeded (and could diverge across hosts)
            mask = a != a  # elementwise: only NaN cells are != themselves
            if np.any(mask):
                a = a.copy()
                a[mask] = math.nan
        from .. import native

        cells = a.tolist()
        enc = native.dict_encode(cells)
        if enc is not None:
            codes, uniques = enc
        elif a.dtype != object:
            # U/S fixed-width strings have no NaN/mixed-type hazards —
            # numpy's sort-based unique is semantically identical and
            # far faster than a python loop
            return np.unique(a, return_inverse=True)
        else:
            # pure-python first-appearance encode with IDENTICAL
            # semantics to the native hash pass (np.unique is no
            # substitute here: object-dtype unique compares by == so
            # NaNs never collapse, and mixed-type keys raise on '<')
            table: Dict[object, int] = {}
            codes = np.empty(len(cells), np.int64)
            uniques = []
            for i, v in enumerate(cells):
                code = table.get(v)
                if code is None:
                    code = len(uniques)
                    table[v] = code
                    uniques.append(v)
                codes[i] = code
        k = len(uniques)
        uniq_arr = np.empty(k, dtype=object)
        uniq_arr[:] = uniques
        try:
            order = np.argsort(uniq_arr, kind="stable")
        except TypeError:
            # mixed-type keys (e.g. NaN float among strings) have no
            # '<' order; fall back to a deterministic total order by
            # (type name, repr) — np.unique would just raise here
            order = np.asarray(
                sorted(
                    range(k),
                    key=lambda i: (
                        type(uniques[i]).__name__, repr(uniques[i])
                    ),
                ),
                np.int64,
            )
        rank = np.empty(k, np.int64)
        rank[order] = np.arange(k)
        if a.dtype != object:  # keep U/S dtype for callers
            uniq_arr = uniq_arr.astype(a.dtype)
        return uniq_arr[order], rank[codes]
    return np.unique(a, return_inverse=True)


def frame_group_ids(
    frame, keys: Sequence[str]
) -> Tuple[np.ndarray, List[np.ndarray], int]:
    """:func:`group_ids` over ``frame``'s key columns, with a per-frame
    **dictionary cache**: the encode (for string keys, a full hash pass
    over 1M python objects — the measured 6-10x gap between string and
    numeric aggregation) runs ONCE per (frame, key set) and every later
    aggregate/join epilogue on the same materialized frame reuses the
    codes. Frames are immutable once materialized, so the cache can
    never go stale; lazy frames are forced by ``column_values`` first
    and only cached when they ended up materialized. Callers must have
    ruled out the zero-row case (group_ids cannot encode it)."""
    ck = tuple(keys)
    hit = frame_cache_get(frame, ck)
    if hit is not None:
        return hit
    res = group_ids([frame.column_values(k) for k in keys])
    frame_cache_put(frame, ck, res)
    return res


def frame_cache_get(frame, key):
    """Read one entry of a frame's group-ids dictionary cache (None on
    miss / cache absent)."""
    cache = getattr(frame, "_group_ids_cache", None)
    return cache.get(key) if cache is not None else None


def frame_cache_put(frame, key, value) -> None:
    """Store one entry in a frame's group-ids dictionary cache — the
    ONE create/bound/evict policy for every writer (the host encode
    here and the device dictionary plan in ops/device_agg.py), so the
    staleness rule cannot diverge between them: only materialized
    frames cache (their blocks are immutable), and retained encodings
    per frame are bounded."""
    if not getattr(frame, "is_materialized", False):
        return
    cache = getattr(frame, "_group_ids_cache", None)
    if cache is None:
        try:
            cache = frame._group_ids_cache = {}
        except AttributeError:  # pragma: no cover - exotic frames
            return
    if len(cache) >= 8:  # bound retained encodings per frame
        cache.clear()
    cache[key] = value


def mixed_radix_strides(ranges: Sequence[int]) -> List[int]:
    """Strides with the FIRST key most significant, so composite codes
    order lexicographically by key tuple."""
    strides = [1] * len(ranges)
    for i in range(len(ranges) - 2, -1, -1):
        strides[i] = strides[i + 1] * ranges[i + 1]
    return strides
