"""Group-key encoding shared by the host aggregate path and the sharded
device plans: key columns → dense group ids in lexicographic group order
(the ordering Catalyst's groupBy output sort matched,
DebugRowOps.scala:583).

Two strategies:

* **dense span** — all-integer keys with a small mixed-radix span use
  pure O(n) arithmetic + bincount; no sort of any kind;
* **dictionary** — anything else encodes via ``np.unique`` per column,
  then a composite code. NaN float keys collapse into ONE group — the
  Catalyst/Spark groupBy convention (NaNs compare equal for grouping).

All arithmetic is performed in int64 regardless of the key column dtype
(an int8 key spanning -128..127 must not wrap its 255-wide offset).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

# max dense bucket count for the arithmetic strategy
DENSE_SPAN_LIMIT = 1 << 20


def group_ids(
    arrs: Sequence[np.ndarray],
) -> Tuple[np.ndarray, List[np.ndarray], int]:
    """Encode parallel key columns into dense group ids.

    Returns ``(seg_ids, group_key_cols, num_groups)`` where ``seg_ids``
    is int64 of row length, ``group_key_cols`` holds one array per input
    column with the key values of each group (lexicographic order), and
    ``num_groups`` is the distinct-group count.
    """
    arrs = [np.asarray(a) for a in arrs]
    if all(np.issubdtype(a.dtype, np.integer) for a in arrs):
        mins = [int(a.min()) for a in arrs]
        ranges = [int(a.max()) - m + 1 for a, m in zip(arrs, mins)]
        K = 1
        for r in ranges:  # python ints: no overflow past the gate
            K *= r
        if K <= DENSE_SPAN_LIMIT:
            comb = arrs[0].astype(np.int64) - mins[0]
            for a, m, r in zip(arrs[1:], mins[1:], ranges[1:]):
                comb = comb * np.int64(r) + (a.astype(np.int64) - m)
            counts = np.bincount(comb, minlength=K)
            present = np.flatnonzero(counts)
            remap = np.empty(K, np.int64)
            remap[present] = np.arange(len(present))
            seg_ids = remap[comb]
            strides = mixed_radix_strides(ranges)
            group_key_cols = [
                ((present // strides[i]) % ranges[i] + mins[i]).astype(
                    arrs[i].dtype
                )
                for i in range(len(arrs))
            ]
            return seg_ids, group_key_cols, len(present)
    comb = None
    for a in arrs:
        _, c = np.unique(a, return_inverse=True)
        c = c.astype(np.int64)
        if comb is None:
            comb = c
        else:
            # comb is densified each step (< n), so comb*radix+c stays
            # within int64 up to ~3e9 rows — no mixed-radix overflow
            _, comb = np.unique(comb, return_inverse=True)
            comb = comb.astype(np.int64) * np.int64(int(c.max()) + 1) + c
    _, first_idx, seg_ids = np.unique(
        comb, return_index=True, return_inverse=True
    )
    # each group's key values = the key tuple at its first occurrence
    group_key_cols = [a[first_idx] for a in arrs]
    return seg_ids.astype(np.int64), group_key_cols, len(first_idx)


def mixed_radix_strides(ranges: Sequence[int]) -> List[int]:
    """Strides with the FIRST key most significant, so composite codes
    order lexicographically by key tuple."""
    strides = [1] * len(ranges)
    for i in range(len(ranges) - 2, -1, -1):
        strides[i] = strides[i + 1] * ranges[i + 1]
    return strides
