"""On-device keyed aggregation for sharded frames.

The host `aggregate` path (verbs.py) gathers rows to the host and
lexsorts by key — fine single-host, but it is still the reference's
driver-shaped plan (Catalyst shuffle ≙ host sort,
DebugRowOps.scala:583). For sharded frames with integer keys this module
replaces the shuffle entirely with the TPU-native plan:

    per-shard dense segment reduction  →  one ICI collective

Each shard scatter-reduces its local rows into a dense ``[K, ...]``
bucket table (K = the mixed-radix span of the key ranges), then a single
``psum``/``pmin``/``pmax`` over the batch axis merges the tables — a
log-depth hardware collective instead of a host round-trip. Empty
buckets are dropped afterwards using the (psum-merged) per-bucket
counts. Multi-host works by construction: the collective crosses
process boundaries through ICI/DCN, and only the tiny dense table is
ever host-materialized.

Two plans, tried in order:

* **dense span** — integer keys whose mixed-radix span is small
  (``K <= 1<<20`` buckets, ``K × feature-elems <= 1<<24``): bucket ids
  come from pure device arithmetic; the keys never touch the host.
* **dictionary encoding** — arbitrary keys (strings, huge-span ints,
  composites): one host pass over the *key columns only* builds dense
  group ids via ``np.unique`` (values stay on device), then the same
  segment-reduce + collective runs with ``K = #distinct groups``. This
  removes the reference's Catalyst shuffle for any key type
  (DebugRowOps.scala:583) at the cost of one key-column transfer.

Anything else (non-algebraic fetches, ragged values, trimmed row counts
the mesh no longer divides) falls back to the host path. The dense-table
trick is the same reformulation the pallas segment kernel uses
(scatter → dense compute): on TPU, bounded dense work beats
data-dependent shuffles.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel._shard_map import shard_map
from ..utils import get_logger
from .keys import group_ids, mixed_radix_strides

logger = get_logger(__name__)

_KEY_LIMIT = 1 << 20          # max dense bucket count
_TABLE_ELEM_LIMIT = 1 << 24   # max K × per-row feature elements


@lru_cache(maxsize=32)
def _agg_fn(mesh, axis: str, ops_key, K: int, strides: Tuple[int, ...]):
    """Jitted shard_map program: local dense segment-reduce + one
    collective per output. ``ops_key`` is a tuple of (name, op, ndim);
    inputs are the offset key columns (min already subtracted) and the
    value columns, all sharded over ``axis``."""

    def local(keys, vals):
        ids = keys[0] * strides[0]
        for k, s in zip(keys[1:], strides[1:]):
            ids = ids + k * s
        out = {}
        count = jax.ops.segment_sum(
            jnp.ones(ids.shape, jnp.int32), ids, num_segments=K
        )
        out["__count__"] = lax.psum(count, axis)
        for name, op, _ in ops_key:
            v = vals[name]
            if op in ("reduce_sum", "reduce_mean"):
                t = jax.ops.segment_sum(v, ids, num_segments=K)
                out[name] = lax.psum(t, axis)
            elif op == "reduce_min":
                t = jax.ops.segment_min(v, ids, num_segments=K)
                out[name] = lax.pmin(t, axis)
            elif op == "reduce_max":
                t = jax.ops.segment_max(v, ids, num_segments=K)
                out[name] = lax.pmax(t, axis)
            else:  # pragma: no cover - guarded by caller
                raise ValueError(f"unsupported op {op}")
        return out

    n_keys = len(strides)
    in_specs = (
        tuple(P(axis) for _ in range(n_keys)),
        {name: P(axis, *([None] * (ndim - 1))) for name, _, ndim in ops_key},
    )
    out_specs = {name: P() for name, _, _ in ops_key}
    out_specs["__count__"] = P()
    return jax.jit(
        shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


@jax.jit
def _stacked_minmax(*cols):
    """Per-column (min, max) pairs in one device computation / one
    transfer. Each pair keeps its column's own dtype — casting to a
    common int64 here would silently truncate to int32 when x64 is
    disabled and corrupt the range guard."""
    return tuple((c.min(), c.max()) for c in cols)


# Per-array (min, max) memo for the dense plan's span probe: device
# frame columns are immutable, but the probe's device_get is a full
# relay round trip PER aggregate CALL on tunnel-attached chips (the r4
# follow-up: "aggregate's device plan pays per-call relay transfers").
# id()-keyed with a weakref finalizer so entries die with their array
# (ids recycle only after the finalizer has already evicted the entry).
_minmax_memo: Dict[int, tuple] = {}  # lint: guarded (benign race: concurrent writers memoize the same immutable probe; worst case one redundant device_get)

# Same lifetime discipline for the dictionary plan's encode: keyed by
# the tuple of key-column array ids; holds (staged dense ids on device,
# group key columns, K). Evicted when any key array is collected.
_dict_encode_memo: Dict[tuple, tuple] = {}  # lint: guarded (benign race: same-key writers store identical staged values)


def _placement_token() -> tuple:
    """The topology a staged upload targeted: a cached relay placement
    is only valid while the backend and visible device set are
    unchanged — keying the staged ids by this token re-stages after a
    backend/device flip instead of serving a mis-placed array."""
    return (
        jax.default_backend(),
        tuple(d.id for d in jax.local_devices()),
    )


def _cached_minmax(cols):
    import weakref

    missing = [c for c in cols if id(c) not in _minmax_memo]
    if missing:
        got = jax.device_get(_stacked_minmax(*missing))
        for c, mm in zip(missing, got):
            key = id(c)
            _minmax_memo[key] = mm
            weakref.finalize(c, _minmax_memo.pop, key, None)
    return [_minmax_memo[id(c)] for c in cols]


def _run_tables(
    frame, axis, ops, out_names, K, strides, key_feeds, main, tail, ids_tail
):
    """Shared tail of both plans: device segment-reduce + collective,
    host fold of the tiny tail block, empty-bucket drop, mean divide.
    Returns ``(sel, out_cols)`` — the surviving bucket ids (ascending,
    i.e. lexicographic key order) and the finished output columns."""
    ops_key = tuple((x, ops[x], int(main[x].ndim)) for x in out_names)
    fn = _agg_fn(frame.mesh, axis, ops_key, K, tuple(strides))
    res = fn(key_feeds, {x: main[x] for x in out_names})
    count = np.asarray(res["__count__"])
    tables = {x: np.asarray(res[x]) for x in out_names}

    # -- fold the host tail block in (≤ dp-1 rows) --------------------------
    if tail is not None and ids_tail is not None and len(ids_tail):
        np.add.at(count, ids_tail, 1)
        for x in out_names:
            v = np.asarray(tail[x], dtype=tables[x].dtype)
            if ops[x] in ("reduce_sum", "reduce_mean"):
                np.add.at(tables[x], ids_tail, v)
            elif ops[x] == "reduce_min":
                np.minimum.at(tables[x], ids_tail, v)
            else:
                np.maximum.at(tables[x], ids_tail, v)

    sel = np.flatnonzero(count > 0)
    out_cols: Dict[str, np.ndarray] = {}
    for x in out_names:
        t = tables[x][sel]
        if ops[x] == "reduce_mean":
            c = count[sel].reshape((-1,) + (1,) * (t.ndim - 1))
            t = (t / c).astype(tables[x].dtype)
        out_cols[x] = t
    return sel, out_cols


def _allgather_dicts(local_cols: List[np.ndarray]) -> Tuple[List[np.ndarray], int]:
    """Union every process's group-key dictionary columns.

    Serializes this process's dictionary (one array per key column, one
    row per LOCAL distinct group), allgathers fixed-width byte buffers in
    two phases (sizes, then padded payloads — ``process_allgather``
    requires equal shapes), and returns ``(union_cols, offset)`` where
    ``union_cols`` concatenates all processes' dictionaries in process
    order and ``offset`` is where this process's entries start."""
    import pickle

    from jax.experimental import multihost_utils as mh

    payload = np.frombuffer(
        pickle.dumps(local_cols, protocol=pickle.HIGHEST_PROTOCOL), np.uint8
    )
    sizes = np.asarray(
        mh.process_allgather(np.asarray([payload.size], np.int64))
    ).reshape(-1)
    width = int(sizes.max())
    padded = np.zeros(width, np.uint8)
    padded[: payload.size] = payload
    bufs = np.asarray(mh.process_allgather(padded)).reshape(len(sizes), width)
    # every rank received every rank's dictionary — the host-gather
    # volume the file shuffle exists to eliminate (asserted zero in the
    # shuffled-aggregate tests)
    from ..blockstore.store import HOSTGATHER_BYTES

    HOSTGATHER_BYTES.inc(float(bufs.nbytes))
    dicts = [
        pickle.loads(bufs[p, : int(sizes[p])].tobytes())
        for p in range(len(sizes))
    ]
    me = jax.process_index()
    offset = int(sum(len(d[0]) for d in dicts[:me]))
    union = [
        np.concatenate([np.asarray(d[i]) for d in dicts])
        for i in range(len(local_cols))
    ]
    return union, offset


def extract_local_rows(v):
    """This process's rows of one frame column: host lists are already
    process-local; sharded device arrays concatenate their addressable
    shards in global-index order. Returns None when no shard is
    addressable (caller must treat as ineligible). Shared by the
    dictionary plan and the generic multiprocess aggregate (verbs.py)."""
    if isinstance(v, list):
        return np.asarray(v, dtype=object)
    if isinstance(v, np.ndarray):
        return v
    shards = sorted(
        v.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    if not shards:
        return None
    return np.concatenate([np.asarray(s.data) for s in shards])


def gather_local_columns(frame, names) -> Optional[Dict[str, np.ndarray]]:
    """This process's rows of every named column, concatenated across
    blocks — the local half of the distributed relational verbs (join's
    broadcast build side, sort's allgather input, VERDICT r3 #7).
    Returns None when any column has no addressable shard here; callers
    MUST vote on that with :func:`uniform_ok` before entering any
    collective, so an ineligible fleet raises everywhere instead of one
    process bailing out of an allgather its peers already entered."""
    cols: Dict[str, np.ndarray] = {}
    for name in names:
        parts = []
        for b in frame.blocks():
            lr = extract_local_rows(b[name])
            if lr is None:
                return None
            parts.append(lr)
        cols[name] = parts[0] if len(parts) == 1 else np.concatenate(parts)
    return cols


def assemble_key_cols(frame, keys, group_key_cols, sel=None):
    """Result key columns from per-key group arrays: optional group
    selection, cast device keys back to their schema dtype (host keys —
    strings — pass through). Shared result epilogue of the dictionary
    plan and the generic multiprocess aggregate (verbs.py)."""
    key_cols = {}
    for i, k in enumerate(keys):
        vals = group_key_cols[i] if sel is None else group_key_cols[i][sel]
        info = frame.schema[k]
        key_cols[k] = (
            vals.astype(info.dtype.np_dtype) if info.is_device else vals
        )
    return key_cols


def uniform_ok(ok: bool) -> bool:
    """Collective eligibility vote: every process must take the same
    branch BEFORE any further collective — one process falling back to a
    host path while the rest allgather would deadlock both groups."""
    from jax.experimental import multihost_utils as mh

    all_ok = np.asarray(
        mh.process_allgather(np.asarray([1 if ok else 0], np.int32))
    )
    return bool(int(all_ok.min()))


def _aggregate_multiprocess_dict(
    frame, keys, ops, out_names, main, feat, axis
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]]:
    """Dictionary plan across processes: local encode → dictionary
    allgather/merge → global dense ids → shared segment plan. Key columns
    may be process-local host lists (strings) or sharded device arrays;
    value columns stay sharded throughout."""
    from jax.sharding import NamedSharding

    key_local: List[np.ndarray] = []
    ok = True
    for k in keys:
        v = extract_local_rows(main[k])
        if v is None:
            ok = False
            break
        key_local.append(v)
    n_local = len(key_local[0]) if key_local else 0
    if ok and any(len(a) != n_local for a in key_local):
        # a host key column whose local rows disagree with this process's
        # device shard rows cannot be aligned
        ok = False
    if not uniform_ok(ok):
        return None
    if n_local:
        ids_local, local_dict, k_local = group_ids(key_local)
    else:
        ids_local = np.zeros(0, np.int64)
        local_dict, k_local = [a[:0] for a in key_local], 0
    union_cols, offset = _allgather_dicts(local_dict)
    union_ids, group_key_cols, K = group_ids(union_cols)
    if K * feat > _TABLE_ELEM_LIMIT:
        logger.debug(
            "device aggregate: %d groups ×%d feat exceeds the table limit "
            "(multi-process)", K, feat,
        )
        return None
    gids_local = union_ids[offset:offset + k_local][ids_local].astype(np.int32)
    ids_global = jax.make_array_from_process_local_data(
        NamedSharding(frame.mesh, P(axis)), gids_local
    )
    sel, out_cols = _run_tables(
        frame, axis, ops, out_names, K, (1,), (ids_global,), main, None, None
    )
    return assemble_key_cols(frame, keys, group_key_cols, sel), out_cols


def try_aggregate_device(
    frame,
    keys: Sequence[str],
    seg_info,
    out_names: Sequence[str],
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]]:
    """Attempt the sharded device plans (dense span, then dictionary
    encoding). Returns ``(key_cols, out_cols)`` with groups in
    lexicographic key order (the host path's ordering), or None when
    ineligible."""
    if not frame.is_sharded or frame.num_rows == 0:
        return None
    ops = {name: op for name, op, _ in seg_info}
    if any(ops[x] not in ("reduce_sum", "reduce_min", "reduce_max", "reduce_mean")
           for x in out_names):
        return None
    blocks = frame.blocks()
    main, tail = blocks[0], (blocks[1] if len(blocks) > 1 else None)
    for x in out_names:
        if isinstance(main[x], list):
            return None
    for k in keys:
        # ragged device key columns can't form ids; host-resident key
        # columns (strings, …) are fine — the dictionary plan handles them
        if isinstance(main[k], list) and frame.schema[k].is_device:
            return None
    # global row count reads a VALUE column: value columns are always
    # dense device arrays here, whereas a key column may be a
    # process-local host list whose length is only this process's rows
    main_rows = int(
        main[out_names[0]].shape[0]
        if out_names
        else (
            len(main[keys[0]])
            if isinstance(main[keys[0]], list)
            else main[keys[0]].shape[0]
        )
    )
    if main_rows == 0:
        return None  # everything in the tail → host path is already optimal
    axis = getattr(frame, "_axis", None) or "dp"
    dp = frame.mesh.shape.get(axis, 1)
    if main_rows % dp:
        # a trimmed map can leave a sharded frame with a row count the
        # mesh no longer divides; shard_map would reject it — host path
        # (mirrors the reduce_rows guard, verbs.py)
        return None
    feat = 0
    for x in out_names:
        cell = main[x].shape[1:]
        feat = max(feat, int(np.prod(cell)) if cell else 1)

    dense_eligible = all(
        frame.schema[k].is_device
        and np.issubdtype(frame.schema[k].dtype.np_dtype, np.integer)
        for k in keys
    )
    if dense_eligible:
        # -- plan A: dense mixed-radix span (keys never leave the device) ---
        mm = _cached_minmax([main[k] for k in keys])
        mins, ranges = [], []
        for i, k in enumerate(keys):
            lo, hi = int(mm[i][0]), int(mm[i][1])
            if tail is not None and len(tail[k]):
                t = np.asarray(tail[k])
                lo, hi = min(lo, int(t.min())), max(hi, int(t.max()))
            mins.append(lo)
            ranges.append(int(hi - lo + 1))
        # python ints: key spans near the int32/int64 limits must not wrap
        # the product and sneak past the eligibility gate
        K = math.prod(ranges)
        if K <= _KEY_LIMIT and K * feat <= _TABLE_ELEM_LIMIT:
            # keys[0] most significant → bucket order == lexicographic order
            strides = mixed_radix_strides(ranges)
            # widen BEFORE the offset subtraction: an int8 key spanning
            # -128..127 must not wrap its 255-wide offset (the negative
            # id would be silently dropped by the XLA scatter)
            keys_off = tuple(
                (main[k].astype(jnp.int32) - np.int32(mins[i]))
                if main[k].dtype.itemsize < 8
                else (main[k] - mins[i]).astype(jnp.int32)
                for i, k in enumerate(keys)
            )
            ids_tail = None
            if tail is not None:
                ids_tail = np.zeros(len(tail[keys[0]]), np.int64)
                for i, k in enumerate(keys):
                    ids_tail += (
                        np.asarray(tail[k]).astype(np.int64) - mins[i]
                    ) * strides[i]
            sel, out_cols = _run_tables(
                frame, axis, ops, out_names, K, strides, keys_off,
                main, tail, ids_tail,
            )
            key_cols: Dict[str, np.ndarray] = {}
            for i, k in enumerate(keys):
                comp = (sel // strides[i]) % ranges[i] + mins[i]
                key_cols[k] = comp.astype(frame.schema[k].dtype.np_dtype)
            return key_cols, out_cols
        logger.debug(
            "device aggregate: key span %d (×%d feat) too large for the "
            "dense plan; trying dictionary encoding", K, feat,
        )

    # -- plan B: dictionary encoding — one host pass over the KEY columns
    # only (values stay sharded on device). Arbitrary key types; K becomes
    # the number of distinct groups, not the key span. -----------------------
    if jax.process_count() > 1:
        # multi-process: each process dictionary-encodes its LOCAL key
        # rows, the per-process dictionaries union through one allgather
        # (tiny: one entry per distinct group), and the merged dense ids
        # feed the same segment plan — no process ever sees another's
        # raw key column (≙ replacing the Catalyst shuffle at
        # DebugRowOps.scala:583 with a dictionary exchange)
        if tail is not None and len(tail[out_names[0] if out_names else keys[0]]):
            # the multi-process plan has no tail fold; declining here is
            # SPMD-uniform (block structure derives from global shapes)
            return None
        return _aggregate_multiprocess_dict(
            frame, keys, ops, out_names, main, feat, axis
        )
    # repeated aggregates over the same IMMUTABLE device key columns
    # skip the per-call device_get + host encode + ids re-upload (each a
    # relay round trip on tunnel-attached chips); host-list keys stay
    # uncached (lists are mutable)
    memo_key = None
    if tail is None and all(
        not isinstance(main[k], list) for k in keys
    ):
        memo_key = tuple(id(main[k]) for k in keys)
        hit = _dict_encode_memo.get(memo_key)
        if hit is not None:
            ids_dev, group_key_cols, K = hit
            if K * feat > _TABLE_ELEM_LIMIT:
                return None
            sel, out_cols = _run_tables(
                frame, axis, ops, out_names, K, (1,), (ids_dev,),
                main, None, None,
            )
            return (
                assemble_key_cols(frame, keys, group_key_cols, sel),
                out_cols,
            )
    # host-list (e.g. STRING) keys have no stable array identity for
    # the id memo above, but the FRAME is immutable once materialized:
    # cache their dictionary encode on it (the same convention as
    # keys.frame_group_ids), so repeated string-keyed aggregates skip
    # the full hash pass over every key cell
    from .keys import frame_cache_get, frame_cache_put

    frame_ck = ("__device_dict__",) + tuple(keys)
    hit = None
    staged_ck = None
    ids_dev = None
    if memo_key is None and tail is None:
        hit = frame_cache_get(frame, frame_ck)
        # relay-placement cache (the r4 follow-up): the encode cache
        # above still paid a host->device ids upload — a full relay
        # round trip on tunnel-attached chips — on EVERY call; the
        # staged array is as immutable as the frame, scoped to the
        # placement it was uploaded for
        staged_ck = frame_ck + ("__staged__", _placement_token())
    if hit is not None:
        ids_all, group_key_cols, K = hit
        ids_dev = frame_cache_get(frame, staged_ck)
    else:
        key_host: List[np.ndarray] = []
        for k in keys:
            v = main[k]
            if isinstance(v, list):
                arr = np.asarray(v, dtype=object)
            else:
                arr = np.asarray(jax.device_get(v))
            if tail is not None and len(tail[k]):
                tv = tail[k]
                tarr = (
                    np.asarray(tv, dtype=object)
                    if isinstance(tv, list)
                    else np.asarray(tv)
                )
                arr = np.concatenate([arr, tarr])
            key_host.append(arr)
        # shared encoder (ops/keys.py): dense group ids, lexicographic
        # order
        ids_all, group_key_cols, K = group_ids(key_host)
        if memo_key is None and tail is None:
            frame_cache_put(frame, frame_ck, (ids_all, group_key_cols, K))
    if K * feat > _TABLE_ELEM_LIMIT:
        logger.debug(
            "device aggregate: %d groups ×%d feat exceeds the table limit; "
            "host path", K, feat,
        )
        return None
    ids_tail = ids_all[main_rows:] if tail is not None else None
    if ids_dev is None:
        ids_dev = jnp.asarray(ids_all[:main_rows].astype(np.int32))
        if staged_ck is not None:
            frame_cache_put(frame, staged_ck, ids_dev)
    if memo_key is not None:
        import weakref

        _dict_encode_memo[memo_key] = (ids_dev, group_key_cols, K)
        for k in keys:  # evict when ANY key column dies
            weakref.finalize(
                main[k], _dict_encode_memo.pop, memo_key, None
            )
    sel, out_cols = _run_tables(
        frame, axis, ops, out_names, K, (1,), (ids_dev,),
        main, tail, ids_tail,
    )
    key_cols = {}
    for i, k in enumerate(keys):
        vals = group_key_cols[i][sel]
        info = frame.schema[k]
        key_cols[k] = (
            vals.astype(info.dtype.np_dtype) if info.is_device else vals
        )
    return key_cols, out_cols
