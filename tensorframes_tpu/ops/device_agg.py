"""On-device keyed aggregation for sharded frames.

The host `aggregate` path (verbs.py) gathers rows to the host and
lexsorts by key — fine single-host, but it is still the reference's
driver-shaped plan (Catalyst shuffle ≙ host sort,
DebugRowOps.scala:583). For sharded frames with integer keys this module
replaces the shuffle entirely with the TPU-native plan:

    per-shard dense segment reduction  →  one ICI collective

Each shard scatter-reduces its local rows into a dense ``[K, ...]``
bucket table (K = the mixed-radix span of the key ranges), then a single
``psum``/``pmin``/``pmax`` over the batch axis merges the tables — a
log-depth hardware collective instead of a host round-trip. Empty
buckets are dropped afterwards using the (psum-merged) per-bucket
counts. Multi-host works by construction: the collective crosses
process boundaries through ICI/DCN, and only the tiny dense table is
ever host-materialized.

Eligibility: algebraic fetches (sum/min/max/mean), integer key columns,
and a key span small enough that the dense table is cheap
(``K <= 1<<20`` buckets and ``K × feature-elems <= 1<<24``). Anything
else falls back to the host path. The dense-table trick is the same
reformulation the pallas segment kernel uses (scatter → dense compute):
on TPU, bounded dense work beats data-dependent shuffles.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel._shard_map import shard_map
from ..utils import get_logger

logger = get_logger(__name__)

_KEY_LIMIT = 1 << 20          # max dense bucket count
_TABLE_ELEM_LIMIT = 1 << 24   # max K × per-row feature elements


@lru_cache(maxsize=32)
def _agg_fn(mesh, axis: str, ops_key, K: int, strides: Tuple[int, ...]):
    """Jitted shard_map program: local dense segment-reduce + one
    collective per output. ``ops_key`` is a tuple of (name, op, ndim);
    inputs are the offset key columns (min already subtracted) and the
    value columns, all sharded over ``axis``."""

    def local(keys, vals):
        ids = keys[0] * strides[0]
        for k, s in zip(keys[1:], strides[1:]):
            ids = ids + k * s
        out = {}
        count = jax.ops.segment_sum(
            jnp.ones(ids.shape, jnp.int32), ids, num_segments=K
        )
        out["__count__"] = lax.psum(count, axis)
        for name, op, _ in ops_key:
            v = vals[name]
            if op in ("reduce_sum", "reduce_mean"):
                t = jax.ops.segment_sum(v, ids, num_segments=K)
                out[name] = lax.psum(t, axis)
            elif op == "reduce_min":
                t = jax.ops.segment_min(v, ids, num_segments=K)
                out[name] = lax.pmin(t, axis)
            elif op == "reduce_max":
                t = jax.ops.segment_max(v, ids, num_segments=K)
                out[name] = lax.pmax(t, axis)
            else:  # pragma: no cover - guarded by caller
                raise ValueError(f"unsupported op {op}")
        return out

    n_keys = len(strides)
    in_specs = (
        tuple(P(axis) for _ in range(n_keys)),
        {name: P(axis, *([None] * (ndim - 1))) for name, _, ndim in ops_key},
    )
    out_specs = {name: P() for name, _, _ in ops_key}
    out_specs["__count__"] = P()
    return jax.jit(
        shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


@jax.jit
def _stacked_minmax(*cols):
    """[n_cols, 2] (min, max) in one device computation / one transfer."""
    return jnp.stack(
        [
            jnp.stack([c.min().astype(jnp.int64), c.max().astype(jnp.int64)])
            for c in cols
        ]
    )


def try_aggregate_device(
    frame,
    keys: Sequence[str],
    seg_info,
    out_names: Sequence[str],
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]]:
    """Attempt the sharded dense-bucket plan. Returns
    ``(key_cols, out_cols)`` with groups in lexicographic key order (the
    host path's ordering), or None when ineligible."""
    if not frame.is_sharded or frame.num_rows == 0:
        return None
    ops = {name: op for name, op, _ in seg_info}
    if any(ops[x] not in ("reduce_sum", "reduce_min", "reduce_max", "reduce_mean")
           for x in out_names):
        return None
    for k in keys:
        info = frame.schema[k]
        if not info.is_device or not np.issubdtype(info.dtype.np_dtype, np.integer):
            return None
    blocks = frame.blocks()
    main, tail = blocks[0], (blocks[1] if len(blocks) > 1 else None)
    for x in out_names:
        if isinstance(main[x], list):
            return None
    for k in keys:
        if isinstance(main[k], list):
            return None
    main_rows = int(main[keys[0]].shape[0])
    if main_rows == 0:
        return None  # everything in the tail → host path is already optimal

    # -- key ranges → mixed-radix bucket ids --------------------------------
    mm = np.asarray(jax.device_get(_stacked_minmax(*(main[k] for k in keys))))
    mins, ranges = [], []
    for i, k in enumerate(keys):
        lo, hi = int(mm[i, 0]), int(mm[i, 1])
        if tail is not None and len(tail[k]):
            t = np.asarray(tail[k])
            lo, hi = min(lo, int(t.min())), max(hi, int(t.max()))
        mins.append(lo)
        ranges.append(int(hi - lo + 1))
    # python ints: key spans near the int32/int64 limits must not wrap the
    # product and sneak past the eligibility gate
    K = math.prod(ranges)
    feat = 0
    for x in out_names:
        cell = main[x].shape[1:]
        feat = max(feat, int(np.prod(cell)) if cell else 1)
    if K > _KEY_LIMIT or K * feat > _TABLE_ELEM_LIMIT:
        logger.debug(
            "device aggregate: key span %d (×%d feat) too large; host path",
            K, feat,
        )
        return None
    # keys[0] most significant → bucket order == lexicographic key order
    strides = [1] * len(keys)
    for i in range(len(keys) - 2, -1, -1):
        strides[i] = strides[i + 1] * ranges[i + 1]

    mesh = frame.mesh
    axis = getattr(frame, "_axis", None) or "dp"
    ops_key = tuple((x, ops[x], int(main[x].ndim)) for x in out_names)
    fn = _agg_fn(mesh, axis, ops_key, K, tuple(strides))
    keys_off = tuple(
        (main[k] - mins[i]).astype(jnp.int32) for i, k in enumerate(keys)
    )
    res = fn(keys_off, {x: main[x] for x in out_names})
    count = np.asarray(res["__count__"])
    tables = {x: np.asarray(res[x]) for x in out_names}

    # -- fold the host tail block in (≤ dp-1 rows) --------------------------
    if tail is not None:
        ids_t = np.zeros(len(tail[keys[0]]), np.int64)
        for i, k in enumerate(keys):
            ids_t += (np.asarray(tail[k]) - mins[i]) * strides[i]
        np.add.at(count, ids_t, 1)
        for x in out_names:
            v = np.asarray(tail[x], dtype=tables[x].dtype)
            if ops[x] in ("reduce_sum", "reduce_mean"):
                np.add.at(tables[x], ids_t, v)
            elif ops[x] == "reduce_min":
                np.minimum.at(tables[x], ids_t, v)
            else:
                np.maximum.at(tables[x], ids_t, v)

    sel = np.flatnonzero(count > 0)
    out_cols: Dict[str, np.ndarray] = {}
    for x in out_names:
        t = tables[x][sel]
        if ops[x] == "reduce_mean":
            c = count[sel].reshape((-1,) + (1,) * (t.ndim - 1))
            t = (t / c).astype(tables[x].dtype)
        out_cols[x] = t
    key_cols: Dict[str, np.ndarray] = {}
    for i, k in enumerate(keys):
        comp = (sel // strides[i]) % ranges[i] + mins[i]
        key_cols[k] = comp.astype(frame.schema[k].dtype.np_dtype)
    return key_cols, out_cols
