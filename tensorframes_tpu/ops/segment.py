"""Segment reduction kernels for keyed ``aggregate``.

The aggregate fast path (verbs.py) lowers algebraic fetches to segment
reductions over key-sorted rows. On TPU, XLA implements
``jax.ops.segment_sum`` as a scatter-add — a serialized, VPU-bound op.
This module adds a **custom pallas kernel** that reformulates the sorted
segment-sum as a one-hot contraction: for each row tile, build the
``[tile, segments]`` membership one-hot and contract it against the value
tile on the **MXU** (a dense matmul), accumulating into the output block
across the grid. Dense MXU work replaces the scatter — the standard TPU
trick for small-to-moderate segment counts.

``segment_sum`` dispatches: pallas on TPU for f32/bf16 2-D values with a
bounded segment count, XLA's segment_sum otherwise. The pallas path is
also exercised on CPU in interpreter mode by the tests.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

# rows per grid step (sublane-aligned); lanes carry the feature dim
_TILE_ROWS = 256
# above this many segments the one-hot matmul wastes more FLOPs than the
# scatter costs; fall back to XLA
_MAX_PALLAS_SEGMENTS = 4096


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _seg_kernel(seg_ref, val_ref, out_ref):
    """One grid step: out[s, d] += Σ_{rows r in tile with seg(r)=s} val[r, d].

    seg_ref: [tile, 1] int32 (padded rows carry num_segments → no match);
    val_ref: [tile, d]; out_ref: [segments_padded, d] (same block every
    step — accumulates across the sequential TPU grid).
    """
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    seg = seg_ref[:, 0]  # [tile]
    tile = seg.shape[0]
    s_pad = out_ref.shape[0]
    # [tile, segments] membership one-hot; 2-D iota (TPU requires ≥2D)
    seg_iota = lax.broadcasted_iota(jnp.int32, (tile, s_pad), 1)
    onehot = (seg[:, None] == seg_iota).astype(jnp.float32)
    vals = val_ref[:].astype(jnp.float32)
    # [segments, tile] @ [tile, d] on the MXU. precision=HIGHEST: the TPU
    # MXU's default single-pass f32 matmul truncates inputs to bf16 —
    # measured on v5e (round 3 smoke), that costs ~2e-1 relative error on
    # cancelling sums vs the exact scatter. The one-hot operand is exact
    # either way; HIGHEST makes the value operand f32-faithful.
    out_ref[:] += lax.dot_general(
        onehot,
        vals,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )


def segment_sum_pallas(
    values: jnp.ndarray,
    seg_ids: jnp.ndarray,
    num_segments: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sorted-or-not segment sum via the one-hot MXU kernel.

    values [n, d] (f32/bf16), seg_ids [n] int32 in [0, num_segments).
    Returns [num_segments, d] float32.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = values.shape
    n_pad = _round_up(max(n, 1), _TILE_ROWS)
    d_pad = _round_up(max(d, 1), 128)
    s_pad = _round_up(num_segments, 8)

    vals = jnp.zeros((n_pad, d_pad), values.dtype).at[:n, :d].set(values)
    # padded rows point at segment id == num_segments → match nothing
    segs = jnp.full((n_pad, 1), num_segments, jnp.int32).at[:n, 0].set(
        seg_ids.astype(jnp.int32)
    )

    grid = (n_pad // _TILE_ROWS,)
    # index maps derive EVERY component from the grid index: this package
    # enables jax x64 at import, under which a literal ``0`` traces as an
    # i64 constant next to the i32 grid index — Mosaic then fails to
    # legalize the index map's mixed-type func.return
    # ("(i32, i64) -> ()", observed on v5e). ``i - i`` is an i32 zero.
    out = pl.pallas_call(
        _seg_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (_TILE_ROWS, 1), lambda i: (i, i - i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (_TILE_ROWS, d_pad), lambda i: (i, i - i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (s_pad, d_pad), lambda i: (i - i, i - i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((s_pad, d_pad), jnp.float32),
        interpret=interpret,
    )(segs, vals)
    return out[:num_segments, :d]


# Mosaic kill-switch: a TPU-toolchain kernel-compile failure at runtime
# must degrade to XLA's scatter path, never take down `aggregate`
# (verbs.py catches the failure, calls disable_pallas(), and retries).
_pallas_disabled = False


def disable_pallas(reason: str = "") -> None:
    global _pallas_disabled
    if not _pallas_disabled:
        import logging

        logging.getLogger(__name__).warning(
            "disabling pallas segment kernel (falling back to XLA "
            "segment_sum)%s", f": {reason}" if reason else ""
        )
        try:
            # fused plan epilogues traced with pallas enabled are stale
            # the moment the kill-switch trips — drop them so the next
            # force re-traces onto the XLA scatter instead of replaying
            # the failing kernel from the cache forever
            from ..plan.lower import clear_fused_cache

            clear_fused_cache()
        except Exception:  # pragma: no cover - never block the switch
            pass
    _pallas_disabled = True


def pallas_enabled() -> bool:
    return not _pallas_disabled


def _pallas_eligible(values: jnp.ndarray, num_segments: int) -> bool:
    return (
        not _pallas_disabled
        and values.ndim == 2
        and values.dtype in (jnp.float32, jnp.bfloat16)
        and 0 < num_segments <= _MAX_PALLAS_SEGMENTS
        and jax.default_backend() == "tpu"
    )


def host_segment_eligible(ops_key, val_cols) -> bool:
    """True when the keyed reduction should run as HOST ``np.bincount``
    instead of the jitted segment program: CPU backend only (XLA:CPU
    lowers ``segment_sum`` to a serialized scatter — measured ~45ms per
    1M-row f32 column vs ~4ms for bincount's weighted histogram), and
    only for 1-D float sum/mean (int sums must not ride bincount's
    float64 weights — >2^53 would silently lose bits; min/max have no
    bincount form). Works on numpy AND jax-array values so the fused
    plan epilogue and the eager path take the SAME branch — that
    sameness is what keeps fused and unfused outputs bit-identical."""
    if jax.default_backend() != "cpu":
        return False
    for x, op in ops_key:
        v = val_cols[x]
        if op not in ("reduce_sum", "reduce_mean"):
            return False
        if getattr(v, "ndim", None) != 1:
            return False
        if not jnp.issubdtype(v.dtype, jnp.floating):
            return False
    return True


def segment_reduce_host(ops_key, num_segments, val_cols, seg_ids):
    """CPU segment sums/means via ``np.bincount``: one fused weighted-
    histogram pass per column, accumulating in float64 (a strictly
    tighter error bound than the f32 sequential scatter) and cast back
    to the value dtype — the fetch-dtype contract the jitted path
    keeps. Both the plan's fused epilogue and the ``TFTPU_FUSION=0``
    path dispatch through THIS function on CPU, so the bit-identical
    contract holds by construction."""
    import numpy as np

    seg_ids = np.asarray(seg_ids)
    if seg_ids.size == 0:
        # zero-row feed (ISSUE 12 bugfix sweep): ``np.asarray([])`` is
        # float64 and ``np.bincount`` rejects float ids with a
        # TypeError. Every segment is empty, so the answer is closed-
        # form: zeros for sums, 0/0 → NaN for means — exactly the bits
        # the jitted segment program produces for empty segments.
        out = {}
        for x, op in ops_key:
            v = np.asarray(val_cols[x])
            s = np.zeros(num_segments, np.float64)
            if op == "reduce_mean":
                with np.errstate(invalid="ignore", divide="ignore"):
                    s = s / np.zeros(num_segments, np.float64)
            out[x] = s.astype(v.dtype)
        return out
    seg_ids = seg_ids.astype(np.intp, copy=False)
    out = {}
    counts = None
    for x, op in ops_key:
        v = np.asarray(val_cols[x])  # syncs a device value in one copy
        s = np.bincount(seg_ids, weights=v, minlength=num_segments)
        if op == "reduce_mean":
            if counts is None:
                counts = np.bincount(seg_ids, minlength=num_segments)
            # segment-count bucketing pads num_segments past the real
            # group count; the padded slots divide 0/0 and are sliced
            # away by the caller — suppress numpy's warning so a
            # warnings-as-errors consumer sees no fused-only noise
            with np.errstate(invalid="ignore", divide="ignore"):
                s = s / counts
        out[x] = s.astype(v.dtype)
    return out


def segment_sum(
    values: jnp.ndarray,
    seg_ids: jnp.ndarray,
    num_segments: int,
) -> jnp.ndarray:
    """Segment sum with automatic kernel dispatch: the pallas one-hot MXU
    kernel on TPU (1-D/2-D f32/bf16 values, bounded segment count), XLA's
    scatter-based ``jax.ops.segment_sum`` otherwise. Result dtype matches
    ``values``."""
    v2 = values[:, None] if values.ndim == 1 else values
    if _pallas_eligible(v2, num_segments):
        out = segment_sum_pallas(v2, seg_ids, num_segments)
        if values.ndim == 1:
            out = out[:, 0]
        return out.astype(values.dtype)
    return jax.ops.segment_sum(values, seg_ids, num_segments=num_segments)
