"""Hash/range-partitioned exchange between processes — the TPU-native
equivalent of Catalyst's shuffle exchange (``DebugRowOps.scala:583``:
Spark hash-partitions both sides of a join/sort across executors so no
executor holds the global frame).

Round-4 verdict item #2: the multi-process relational verbs replicated
their inputs (allgather sort, broadcast join) — correct, but O(global)
memory per process. This module gives them a real shuffle:

* :func:`partition_by_hash` — content-stable row hashes (identical on
  every process for the same values, unlike ``ops.keys.group_ids``
  codes, which depend on local data order) → ``hash % P``.
* :func:`partition_by_range` — sampled splitters (identical on every
  process: the sample is allgathered, tiny) → partition p holds the
  p-th key range, so concatenating per-process results in process
  order IS the global sort order.
* :func:`exchange_rows` — the data plane: per-destination pickled
  payloads ride ONE ``lax.all_to_all`` over a one-device-per-process
  mesh axis (XLA collectives over ICI/DCN — Gloo on the multi-process
  CPU backend), so each process receives only its partition.

Memory per process: O(global/P) for balanced keys (max payload over
(src, dst) pairs × P), vs O(global) for the replicating plans. The
replicating plans remain the small-frame fast path behind
``config.relational_broadcast_bytes``.
"""

from __future__ import annotations

import pickle
import zlib
from functools import lru_cache
from typing import Dict, List, Optional, Sequence

import numpy as np

# splitmix64 constants — a well-mixed 64-bit finalizer (public domain
# constant set; avalanches every input bit across the output)
_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SM_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_M2 = np.uint64(0x94D049BB133111EB)

# observability for tests and debugging: per-call accounting of the
# last exchange on THIS process
# ({"sent": [P], "received": [P], "rounds": n, "chunk": bytes})
last_exchange_stats: Optional[Dict[str, object]] = None


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over a uint64 array (vectorized)."""
    x = x + _SM_GAMMA
    x = (x ^ (x >> np.uint64(30))) * _SM_M1
    x = (x ^ (x >> np.uint64(27))) * _SM_M2
    return x ^ (x >> np.uint64(31))


def _cell_bytes(v) -> bytes:
    if isinstance(v, str):
        return v.encode("utf-8", "surrogatepass")
    if isinstance(v, bytes):
        return v
    if isinstance(v, np.ndarray):
        return v.tobytes()
    # None and anything else with a stable repr; repr is deterministic
    # across processes for the primitive cell types host columns hold
    # (PYTHONHASHSEED salts hash(), so hash() is NOT usable)
    return repr(v).encode("utf-8")


def _f64_bits(f: np.ndarray) -> np.ndarray:
    """Canonical float64 bit patterns: one NaN, -0.0 == +0.0."""
    f = f.astype(np.float64, copy=True)
    f[np.isnan(f)] = np.nan
    f[f == 0.0] = 0.0
    return f.view(np.uint64)


_NUMERIC_CELL = (bool, int, float, np.integer, np.floating, np.bool_)


def content_hash64(arrs: Sequence) -> np.ndarray:
    """Per-row uint64 hashes that are IDENTICAL on every process for
    identical key values — the property partition assignment needs and
    dictionary codes don't have.

    EVERY numeric value (bool/int/uint/float, array or object cell)
    hashes through its canonical float64 bit pattern: the join's
    broadcast path compares key unions after numpy promotion
    (``np.concatenate([int_col, float_col])`` → f64), so 5 must hash
    like 5.0 or a size-triggered switch to the hash exchange would
    silently drop cross-dtype matches. Distinct huge ints that collide
    in f64 merely COLOCATE (a harmless partition collision — they
    compare equal in the promoted join too). String/bytes/other cells
    hash their bytes (crc32 + length, mixed to 64 bits)."""
    np_err = np.seterr(over="ignore")  # uint64 mixing wraps by design
    try:
        combined = None
        for a in arrs:
            if isinstance(a, list):
                a = np.asarray(a, dtype=object)
            a = np.asarray(a)
            if a.dtype == object or a.dtype.kind in ("U", "S"):
                cells = a.tolist()
                h = np.empty(len(cells), np.uint64)
                for i, v in enumerate(cells):
                    if isinstance(v, _NUMERIC_CELL):
                        h[i] = _f64_bits(np.asarray([v]))[0]
                    else:
                        b = _cell_bytes(v)
                        h[i] = np.uint64(
                            zlib.crc32(b) ^ (len(b) << 32)
                        )
            else:  # every numeric family → canonical f64 bits
                h = _f64_bits(a)
            h = _mix64(h)
            combined = h if combined is None else _mix64(combined ^ h)
        return combined
    finally:
        np.seterr(**np_err)


def partition_by_hash(key_cols: Sequence, num_parts: int) -> np.ndarray:
    """Destination partition per local row: ``content_hash64 % P``."""
    return (content_hash64(key_cols) % np.uint64(num_parts)).astype(np.int64)


def _lex_geq(row_cols, split_tuple, asc) -> np.ndarray:
    """Vectorized ``row >= splitter`` under lexicographic multi-key
    order with per-key ascending flags. ``row_cols`` holds per-key
    int64 code arrays, ``split_tuple`` the splitter's codes. Rows fully
    equal to the splitter compare >= (ties land in the higher
    partition, matching the splitter-count assignment)."""
    n = len(row_cols[0])
    geq = np.ones(n, bool)  # fully-equal default
    decided = np.zeros(n, bool)
    for col, sv, a in zip(row_cols, split_tuple, asc):
        gt = (col > sv) if a else (col < sv)
        lt = (col < sv) if a else (col > sv)
        geq = np.where(~decided & gt, True, geq)
        geq = np.where(~decided & lt, False, geq)
        decided = decided | gt | lt
    return geq


def partition_by_range(
    key_cols: Sequence,
    num_parts: int,
    ascending: Sequence[bool],
    sample_per_process: int = 2048,
) -> np.ndarray:
    """Range partitioning for the distributed sort: every process
    allgathers a small deterministic SAMPLE of its key rows, computes
    identical splitters from the union, and assigns each local row to
    ``#{splitters lexicographically <= row}``. Concatenating partitions
    0..P-1 in order then yields the global sort order (each partition is
    sorted locally afterwards). The sample is the only replicated data —
    O(P * sample) rows, independent of frame size."""
    from .device_agg import _allgather_dicts
    from .keys import _unique_inverse

    local = [
        np.asarray(a, dtype=object) if isinstance(a, list) else np.asarray(a)
        for a in key_cols
    ]
    n = len(local[0])
    # deterministic evenly-spaced sample (no RNG: every process must be
    # reproducible, and order bias is broken by the global union)
    take = min(n, sample_per_process)
    idx = (
        np.linspace(0, n - 1, take).astype(np.int64)
        if take
        else np.zeros(0, np.int64)
    )
    sample = [a[idx] for a in local]
    union, _ = _allgather_dicts(sample)

    # codes must be computed over sample∪local TOGETHER: _unique_inverse
    # codes are only comparable within one encode pass. The comparison
    # RESULTS are value-determined, hence identical across processes
    # even though the codes differ.
    m = len(union[0])
    codes = []
    for u_col, l_col in zip(union, local):
        if u_col.dtype == object or l_col.dtype == object:
            both = np.empty(m + n, dtype=object)
            both[:m] = list(u_col)
            both[m:] = list(l_col)
        else:
            both = np.concatenate([u_col, l_col])
        codes.append(_unique_inverse(both)[1].astype(np.int64))
    samp_codes = [c[:m] for c in codes]
    row_codes = [c[m:] for c in codes]

    # identical splitters everywhere: lexsort the union sample (which is
    # identical on every process) and read P-1 quantile rows
    order = np.lexsort(
        [
            c if a else -c
            for c, a in zip(reversed(samp_codes), reversed(ascending))
        ]
    )
    if m == 0 or num_parts == 1:
        return np.zeros(n, np.int64)
    q = [
        order[min(m - 1, (m * (i + 1)) // num_parts)]
        for i in range(num_parts - 1)
    ]
    part = np.zeros(n, np.int64)
    for s_idx in q:
        split = tuple(c[s_idx] for c in samp_codes)
        part += _lex_geq(row_codes, split, ascending).astype(np.int64)
    return part


def _one_device_per_process():
    import jax

    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, d)
    return [by_proc[p] for p in sorted(by_proc)]


@lru_cache(maxsize=4)
def _swap_fn(procs: int):
    """The exchange's (mesh, jitted all_to_all) pair, built once per
    process count: rebuilding the jit wrapper per call would miss jax's
    jit cache and recompile the collective on every exchange (a single
    over-budget join exchanges twice). Shapes vary per call (chunk), so
    the jit still specializes per chunk width under ONE stable wrapper."""
    import jax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel._shard_map import shard_map

    mesh = Mesh(np.asarray(_one_device_per_process()), ("px",))
    swap = jax.jit(
        shard_map(
            lambda s: lax.all_to_all(
                s, "px", split_axis=1, concat_axis=0, tiled=True
            ),
            mesh=mesh,
            in_specs=P("px", None, None),
            out_specs=P(None, "px", None),
        )
    )
    return mesh, swap


# per-round budget for the padded all_to_all buffers (send and receive
# shards are each [P, round_width] — bounded by this regardless of skew)
_EXCHANGE_ROUND_BYTES = 64 << 20


def _exchange_bytes(parts: List[bytes]) -> List[bytes]:
    """All-to-all of arbitrary byte payloads between processes: entry
    ``parts[dst]`` is sent from this process to ``dst``; returns
    ``recv[src]`` = the payload ``src`` addressed to this process.

    One size allgather (tiny) + CHUNKED padded uint8 ``lax.all_to_all``
    rounds: padding every slot to the global max payload would cost
    P × max bytes per process — O(global) again under a hot-key skew,
    the exact blow-up the exchange exists to avoid. Chunking bounds the
    in-flight buffers to ``_EXCHANGE_ROUND_BYTES`` per direction per
    round; only the hot partition's OWNER accumulates its (genuinely
    large) partition, which no partitioning scheme can avoid."""
    global last_exchange_stats
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import multihost_utils as mh
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    procs = jax.process_count()
    me = jax.process_index()
    assert len(parts) == procs
    sizes = np.asarray([len(b) for b in parts], np.int64)
    all_sizes = np.asarray(mh.process_allgather(sizes)).reshape(procs, procs)
    max_size = int(all_sizes.max())
    # cap at the actual max payload: a small exchange must not pad every
    # slot to the full round budget (P x budget of wire traffic for KB
    # of data); identical on every process (allgathered sizes), so the
    # chunk and round count cannot diverge across the fleet
    chunk = max(1 << 16, _EXCHANGE_ROUND_BYTES // max(procs, 1))
    chunk = min(chunk, max(1, max_size))
    rounds = max(1, -(-max_size // chunk))

    mesh, swap = _swap_fn(procs)
    recv = [bytearray() for _ in range(procs)]
    for r in range(rounds):
        lo = r * chunk
        local = np.zeros((1, procs, chunk), np.uint8)
        for dst, b in enumerate(parts):
            seg = b[lo: lo + chunk]
            if seg:
                local[0, dst, : len(seg)] = np.frombuffer(seg, np.uint8)
        arr = jax.make_array_from_callback(
            (procs, procs, chunk),
            NamedSharding(mesh, P("px")),
            lambda _idx: jnp.asarray(local),
        )
        out = swap(arr)
        [shard] = [s for s in out.addressable_shards]
        got = np.asarray(shard.data)[:, 0, :]  # [P(src), chunk]
        for src in range(procs):
            take = min(chunk, int(all_sizes[src, me]) - lo)
            if take > 0:
                recv[src] += got[src, :take].tobytes()
    last_exchange_stats = {
        "sent": [int(s) for s in sizes],
        "received": [int(all_sizes[src, me]) for src in range(procs)],
        "rounds": rounds,
        "chunk": chunk,
    }
    return [bytes(b) for b in recv]


def _file_shuffle_ctx():
    """The file-transport shuffle context (blockstore.shuffle), or None
    when no shuffle dir is armed — the exchange then rides the XLA
    collective. A context whose world disagrees with an initialized
    multi-process jax fleet is IGNORED (a stale/foreign shuffle env
    must not hijack the fleet: callers partition rows by
    ``jax.process_count()``, and a smaller file world would silently
    drop the excess partitions). Lazy import: the exchange must not
    pull the blockstore package into processes that never shuffle."""
    import jax

    from ..blockstore import shuffle as _fs

    ctx = _fs.context() if _fs.enabled() else None
    if (
        ctx is not None
        and jax.process_count() > 1
        and ctx.nprocs != jax.process_count()
    ):
        return None
    return ctx


def _exchange_bytes_files(parts: List[bytes], ctx) -> List[bytes]:
    """File-transport twin of :func:`_exchange_bytes`: per-rank spill
    files in the shared shuffle dir (blockstore.shuffle.exchange) —
    CRC-framed, deadline-bounded, no collective involved, so it works
    on backends without multi-process collectives and between plain OS
    processes. Keeps ``last_exchange_stats`` populated for the same
    observability."""
    global last_exchange_stats
    from ..blockstore import shuffle as _fs

    recv = _fs.exchange(parts, name="exchange_rows", ctx=ctx)
    last_exchange_stats = {
        "sent": [len(p) for p in parts],
        "received": [len(b) for b in recv],
        "rounds": 1,
        "chunk": max((len(p) for p in parts), default=0),
        "transport": "files",
    }
    return recv


def exchange_rows(
    cols: Dict[str, object], part: np.ndarray
) -> Dict[str, object]:
    """Shuffle this process's rows to their partition owners and return
    the rows every process sent HERE (source-process order, then local
    row order — deterministic). ``cols`` maps names to process-local
    numpy arrays or cell lists; ``part`` holds each row's destination
    process. Everything serializes through pickle so string/object and
    multi-dim columns exchange the same way.

    Transport: the chunked ``lax.all_to_all`` collective by default;
    per-rank spill files (:mod:`tensorframes_tpu.blockstore.shuffle`)
    when a shuffle dir is armed (``TFTPU_SHUFFLE_DIR``, or
    ``TFTPU_SHUFFLE_TRANSPORT=files`` on a rendezvous-dir fleet) —
    rank/world then come from the shuffle context, so file-fleet
    processes without ``jax.distributed`` exchange the same way."""
    import jax

    fctx = _file_shuffle_ctx()
    procs = fctx.nprocs if fctx is not None else jax.process_count()
    names = list(cols)
    as_arr = {
        n: (
            np.asarray(v, dtype=object)
            if isinstance(v, list)
            else np.asarray(v)
        )
        for n, v in cols.items()
    }
    payloads = []
    for dst in range(procs):
        sel = np.flatnonzero(part == dst)
        sub = [as_arr[n][sel] for n in names]
        payloads.append(
            pickle.dumps(sub, protocol=pickle.HIGHEST_PROTOCOL)
        )
    received = (
        _exchange_bytes_files(payloads, fctx)
        if fctx is not None
        else _exchange_bytes(payloads)
    )
    chunks = [pickle.loads(b) for b in received]
    out: Dict[str, object] = {}
    for i, n in enumerate(names):
        pieces = [c[i] for c in chunks]
        if as_arr[n].dtype == object:
            merged: List[object] = []
            for p in pieces:
                merged.extend(list(p))
            out[n] = merged
        else:
            out[n] = np.concatenate(pieces) if pieces else as_arr[n][:0]
    return out


def global_frame_bytes(local_cols: Dict[str, object]) -> int:
    """Total bytes of the GLOBAL frame (sum over processes of this
    process-local estimate) — the quantity the broadcast-vs-exchange
    budget gates on. One tiny allgather."""
    import jax
    from jax.experimental import multihost_utils as mh

    local = 0
    for v in local_cols.values():
        if isinstance(v, np.ndarray) and v.dtype != object:
            local += int(v.nbytes)
        else:
            cells = v if isinstance(v, list) else list(v)
            for c in cells:
                local += (
                    int(np.asarray(c).nbytes)
                    if isinstance(c, np.ndarray)
                    else len(_cell_bytes(c))
                )
    if jax.process_count() == 1:
        return local
    totals = np.asarray(
        mh.process_allgather(np.asarray([local], np.int64))
    )
    return int(totals.sum())
