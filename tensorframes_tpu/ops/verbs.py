"""The five verbs: map_blocks, map_rows, reduce_blocks, reduce_rows,
aggregate.

Public surface parity with the reference
(``OperationsInterface``, Operations.scala:20-135; Python client
core.py:144-419). Execution is TPU-native:

* ``map_blocks`` — one jitted XLA program per block (per distinct block
  shape), replacing Session-per-partition (DebugRowOps.scala:305-400).
* ``map_rows`` — ``jax.vmap`` over the block's rows (one compiled program,
  rows batched onto the MXU), replacing the per-row Session loop
  (DebugRowOps.scala:826-864); ragged rows fall back to per-shape
  compilation (≙ per-row dynamic lead dims, TFDataOps.scala:90-103).
* ``reduce_rows`` — a ``lax.scan`` pairwise fold inside one jit per block,
  then across block partials (≙ sequential performReducePairwise,
  DebugRowOps.scala:939-979, minus the per-pair Session.run overhead).
* ``reduce_blocks`` — per-block program run, partials stacked and reduced
  once more (≙ performReduceBlock + driver pairwise RDD.reduce,
  DebugRowOps.scala:510-533 — the stack-and-rerun replaces O(blocks)
  driver round-trips).
* ``aggregate`` — keyed aggregation: a vectorized ``jax.ops.segment_*``
  fast path when the fetches are algebraic reducers, else chunked
  compaction with a bounded buffer (≙ TensorFlowUDAF's compact-every-10,
  DebugRowOps.scala:608-702).

Programs may be DSL nodes, plain Python functions over jnp, or loaded
StableHLO artifacts (see program.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes as dt
from ..config import get_config
from ..dsl.node import Node, compile_fetches, segment_reduce_info
from ..frame import Block, GroupedData, TensorFrame, _block_num_rows
from ..program import Program, TensorSpec, analyze_program, program_from_function
from ..schema import ColumnInfo, Schema
from ..shape import Shape, Unknown
from ..utils import get_logger
from ..utils import profiling
from ..validation import (
    ValidationError,
    validate_map,
    validate_reduce_blocks,
    validate_reduce_rows,
)
from .executor import (
    block_is_ragged,
    bucket_rows,
    gather_feeds,
    make_pair_fold,
    pad_lead_dim,
    pair_fold_body,
)

logger = get_logger(__name__)

Fetches = Union[Node, Sequence[Node], Program, Callable]


def _plan_map_result(
    frame, program: Program, schema: Schema, rows: bool
) -> Optional["TensorFrame"]:
    """Record this map stage on the frame's logical plan instead of
    nesting another compute thunk (tensorframes_tpu/plan): at force
    time the whole chain lowers to one composed XLA dispatch per block.
    Returns None when planning is off (TFTPU_FUSION=0) or re-entrant
    (the lowering pass executes through these same verbs)."""
    from ..plan import ir as plan_ir

    if not plan_ir.fusion_enabled():
        return None
    node = plan_ir.PlanNode(
        "map",
        parent=plan_ir.node_for_parent(frame),
        program=program,
        rows=rows,
        out_names=[o.name for o in program.outputs],
        schema=schema,
    )

    def pending():
        from ..plan.lower import execute_plan

        return execute_plan(node)

    result = TensorFrame(None, schema, pending=pending)
    node.bind(result)
    result._plan = node
    result._produced_by_map = True
    if frame.is_sharded:
        result._mesh = frame.mesh
        result._axis = getattr(frame, "_axis", None)
    return result


def _is_pandas(obj) -> bool:
    try:
        import pandas as pd

        return isinstance(obj, pd.DataFrame)
    except ImportError:  # pragma: no cover
        return False


def _input_specs_from_schema(schema: Schema, block: bool) -> Dict[str, TensorSpec]:
    specs = {}
    for c in schema.device_columns:
        shape = c.block_shape if block else c.cell_shape
        specs[c.name] = TensorSpec(c.name, c.dtype, shape)
    return specs


class NumpyUDF:
    """A numpy UDF captured for verified lifting (``tfs.numpy_udf``).

    The wrapped function receives one *numpy* array per parameter
    (parameter name = column name, block-level) and returns arrays /
    a dict / a tuple of arrays. Capture goes one of two ways:

    * the static lifter (analysis/lifting + plan/lift) synthesizes an
      equivalent pure plan-IR Program and verifies it bit-exactly on a
      boundary-value corpus — the lifted stage fuses like any other
      (no TFG107 barrier), or
    * anything that does not verify runs as a ``jax.pure_callback``
      host stage — exactly what the user wrote, with the decline
      reason counted and surfaced via TFG112 / ``lint --lift-report``.

    Results are bit-identical either way by construction; the lift
    exists purely for speed. Block-level only: ``map_rows`` raises.
    Capture warns (TFG112) when the UDF closes over mutable state —
    the callback re-reads such state per block, so later mutations
    silently rebind its behavior (stale-closure hazard).
    """

    def __init__(self, fn: Callable):
        if not callable(fn) or isinstance(fn, (Node, Program)):
            raise TypeError(
                "numpy_udf wraps a plain Python function over numpy "
                f"arrays; got {type(fn).__name__}")
        self.fn = fn
        self._programs: Dict[tuple, Program] = {}
        self._prog_lock = threading.Lock()
        self._warn_mutable_closures()

    def _warn_mutable_closures(self) -> None:
        from ..analysis.lifting import detect_mutable_closures

        names = detect_mutable_closures(self.fn)
        if not names:
            return
        from ..analysis.diagnostics import Diagnostic, DiagnosticReport

        udf = getattr(self.fn, "__name__", "<udf>")
        DiagnosticReport([
            Diagnostic(
                code="TFG112",
                severity="warn",
                message=(
                    f"numpy_udf {udf!r} closes over mutable state "
                    f"({', '.join(sorted(names))}): the callback re-reads "
                    "it on every block, so mutating it after capture "
                    "silently rebinds the UDF's behavior (stale-closure "
                    "hazard); lifting declines it"
                ),
                subject=udf,
                fix=(
                    "snapshot the captured value into an immutable "
                    "scalar, pass it as a column, or freeze it "
                    "(tuple / float) before capture"
                ),
            )
        ])

    def _materialize(
        self,
        schema: Schema,
        block: bool,
        reduce_mode: Optional[str],
        feed_dict: Optional[Dict[str, str]],
    ) -> Program:
        if not block:
            raise ValidationError(
                "numpy_udf programs are block-level (the host callback "
                "runs once per block, and lifting targets block "
                "expressions); use map_blocks / aggregate, not map_rows"
            )
        specs = _input_specs_from_schema(schema, block)
        for ph, col in (feed_dict or {}).items():
            if col in specs and ph not in specs:
                specs[ph] = TensorSpec(ph, specs[col].dtype, specs[col].shape)
        if reduce_mode == "blocks":
            for c in schema.device_columns:
                specs[f"{c.name}_input"] = TensorSpec(
                    f"{c.name}_input", c.dtype, c.block_shape
                )
        # cache the analyzed Program per capture context so steady-state
        # calls reuse one object (and hence one memoized executable)
        key = (
            tuple(sorted(
                (n, str(s.dtype), tuple(repr(d) for d in s.shape.dims))
                for n, s in specs.items()
            )),
            reduce_mode,
            dt.demotion_active(),
            bool(get_config().udf_lifting),
        )
        with self._prog_lock:
            cached = self._programs.get(key)
        if cached is not None:
            return cached
        from ..plan import lift as plan_lift

        program = plan_lift.build_udf_program(self.fn, specs)
        with self._prog_lock:
            self._programs.setdefault(key, program)
            return self._programs[key]


def numpy_udf(fn: Callable) -> NumpyUDF:
    """Capture a numpy host function for verified lifting — see
    :class:`NumpyUDF`. Usable anywhere block-level fetches are:
    ``map_blocks(numpy_udf(f), frame)``, ``aggregate``,
    ``reduce_blocks``."""
    return NumpyUDF(fn)


def _normalize_program(
    fetches: Fetches,
    schema: Schema,
    block: bool,
    reduce_mode: Optional[str] = None,
    feed_dict: Optional[Dict[str, str]] = None,
    shape_hints: Optional[Dict[str, object]] = None,
) -> Tuple[Program, Optional[List[Tuple[str, str, str]]]]:
    """Accept DSL nodes / a python function / a Program; return an analyzed
    Program plus (for DSL reducer fetches) segment-lowering info.

    ``reduce_mode`` ('rows' | 'blocks') extends the input-spec namespace for
    plain-function fetches so parameters may follow the reduce naming
    contracts (``x_1``/``x_2``, ``x_input``) in addition to column names.
    ``feed_dict`` (placeholder → column) extends it with the renamed
    placeholders, so a function parameter may name a placeholder that a
    feed_dict maps onto a differently-named column (core.py:128-142).
    """
    seg_info = None
    if isinstance(fetches, Program):
        # already-analyzed Programs pass through untouched so their memoized
        # XLA executables (Program.compiled) survive across verb calls;
        # seg_info recorded at compile time keeps the aggregate fast path.
        if fetches.outputs:
            return fetches, getattr(fetches, "seg_info", None)
        program = fetches
    elif isinstance(fetches, Node) or (
        isinstance(fetches, (list, tuple))
        and fetches
        and all(isinstance(f, Node) for f in fetches)
    ):
        nodes = [fetches] if isinstance(fetches, Node) else list(fetches)
        program = compile_fetches(nodes)
        seg_info = segment_reduce_info(nodes)
    elif isinstance(fetches, NumpyUDF):
        # capture → lifted-or-callback Program, fully analyzed and
        # cached on the UDF (like the Program passthrough above, so the
        # memoized executable survives across verb calls — demotion is
        # applied inside the capture)
        program = fetches._materialize(schema, block, reduce_mode, feed_dict)
        return program, getattr(program, "seg_info", None)
    elif callable(fetches):
        specs = _input_specs_from_schema(schema, block)
        for ph, col in (feed_dict or {}).items():
            if col in specs and ph not in specs:
                specs[ph] = TensorSpec(ph, specs[col].dtype, specs[col].shape)
        if reduce_mode == "rows":
            for c in schema.device_columns:
                specs[f"{c.name}_1"] = TensorSpec(f"{c.name}_1", c.dtype, c.cell_shape)
                specs[f"{c.name}_2"] = TensorSpec(f"{c.name}_2", c.dtype, c.cell_shape)
        elif reduce_mode == "blocks":
            for c in schema.device_columns:
                specs[f"{c.name}_input"] = TensorSpec(
                    f"{c.name}_input", c.dtype, c.block_shape
                )
        program = program_from_function(fetches, specs)
    else:
        raise TypeError(
            "fetches must be a DSL Node, a list of Nodes, a Program, or a "
            f"callable; got {type(fetches).__name__}"
        )
    hints = (
        {k: Shape.from_any(v) for k, v in shape_hints.items()}
        if shape_hints
        else None
    )
    if dt.demotion_active():
        # x64 demotion: analyze (and hence trace/execute) the program
        # against 32-bit input specs; gather_feeds casts at the boundary
        demoted = [
            TensorSpec(s.name, dt.demote(s.dtype), s.shape)
            for s in program.inputs
        ]
        program = Program(program.fn, demoted, fetch_order=program.fetch_order)
    program = analyze_program(program, hints=hints)
    program.seg_info = seg_info  # survives Program reuse via compile_program
    return program, seg_info


def _apply_feed_dict(program: Program, feed_dict: Optional[Dict[str, str]]) -> Program:
    """feed_dict: placeholder name → column name (≙ core.py:128-142).
    Placeholders not mentioned keep their own name as the column name."""
    if not feed_dict:
        return program
    unknown = [k for k in feed_dict if k not in program.input_names]
    if unknown:
        raise ValidationError(
            f"feed_dict key(s) {unknown} do not match any program input; "
            f"inputs: {program.input_names}"
        )
    return program.rename_inputs(dict(feed_dict))


def _demote_cast(v, spec: TensorSpec):
    """The x64-demotion boundary for verb paths that build feeds by hand
    (gather_feeds applies the same rule): cast a 64-bit column down to
    the program's demoted 32-bit input spec. Identity when demotion is
    inactive or dtypes already agree; works on numpy and jax arrays."""
    if (
        dt.demotion_active()
        and getattr(v, "dtype", None) != spec.dtype.np_dtype
    ):
        return v.astype(spec.dtype.np_dtype)
    return v


def _strict_lint(program: Program, frame, block_mode: Optional[bool]) -> None:
    """The verbs' ``strict=True`` hook: run the static analyzer
    (:mod:`tensorframes_tpu.analysis`) on the normalized program and
    raise :class:`~tensorframes_tpu.validation.StaticAnalysisError` on
    any error-severity diagnostic — before the first dispatch. Block
    shapes feed the recompile-storm rule only when the frame is already
    materialized (lint never forces a pending computation)."""
    from ..analysis import lint_program

    counts = None
    if getattr(frame, "is_materialized", False):
        counts = tuple(_block_num_rows(b) for b in frame.blocks())
    lint_program(
        program, block_mode=block_mode, block_row_counts=counts,
    ).raise_on_errors()


def _sorted_output_infos(program: Program, block_mode: bool) -> List[ColumnInfo]:
    """Output columns first, sorted by name (≙ DebugRowOps.scala:353-379)."""
    infos = []
    for o in sorted(program.outputs, key=lambda s: s.name):
        if block_mode:
            block_shape = o.shape if o.shape.rank > 0 else Shape((Unknown,))
            block_shape = block_shape.with_leading_unknown()
        else:
            block_shape = o.shape.prepend(Unknown)
        infos.append(ColumnInfo(o.name, o.dtype, block_shape))
    return infos


def compile_program(
    fetches: Fetches,
    frame,
    block: bool = True,
    reduce_mode: Optional[str] = None,
    feed_dict: Optional[Dict[str, str]] = None,
    shape_hints: Optional[Dict[str, object]] = None,
) -> Program:
    """Pre-compile fetches against a frame's schema into a reusable Program.

    Passing the returned Program to a verb repeatedly reuses one XLA
    executable across calls (the jit cache lives on the Program), instead
    of re-tracing per invocation — the steady-state serving path.

    ``shape_hints`` ({output name → shape}) override discovered output
    shapes wherever the hint dim is known — the per-call shape side
    channel (≙ ShapeDescription + the hint-override rule,
    TensorFlowOps.scala:126-133).
    """
    program, _ = _normalize_program(
        fetches,
        frame.schema,
        block=block,
        reduce_mode=reduce_mode,
        shape_hints=shape_hints,
    )
    return _apply_feed_dict(program, feed_dict)


# ---------------------------------------------------------------------------
# map_blocks
# ---------------------------------------------------------------------------

def _rebalance_trimmed(out_blocks, names, mesh, axis):
    """Re-split a trimmed sharded result so the mesh divides the main
    block again (SURVEY §7 hard-part 3: row-count-changing outputs across
    shards need a size exchange before reassembly — here the exchange is
    a ``device_put`` resharding, which XLA lowers to ICI collectives,
    ≙ TrimmingOperationsSuite.scala:17-47 semantics). The result obeys
    the same invariants as ``to_device``: divisible device main block +
    small host tail, so every downstream verb fast path composes."""
    import jax

    from ..parallel.mesh import batch_sharding

    if jax.process_count() > 1:
        # boundary rows can't be host-shuffled across non-addressable
        # shards; leave the blocks as produced — the verb guards decline
        # the fast paths for non-divisible shapes, so results stay correct
        return out_blocks

    dp = mesh.shape[axis]
    dev_cols = dict(out_blocks[0])
    # any further blocks are the mapped host-tail results — tiny
    tail_cols = {
        nm: np.concatenate([np.asarray(ob[nm]) for ob in out_blocks[1:]])
        for nm in names
    } if len(out_blocks) > 1 else {}
    n_dev = int(next(iter(dev_cols.values())).shape[0])
    n_tail = int(next(iter(tail_cols.values())).shape[0]) if tail_cols else 0
    n_main = ((n_dev + n_tail) // dp) * dp
    main, tailb = {}, {}
    for nm in names:
        arr = dev_cols[nm]
        if n_main <= n_dev:
            # only the <= dp-1 overflow rows leave the device; the big
            # array reshards in place via device_put (ICI on real chips)
            extra = np.asarray(arr[n_main:]) if n_main < n_dev else None
        else:
            # promote tail rows to fill the last full shard row-group
            fill = jnp.asarray(tail_cols[nm][: n_main - n_dev])
            arr = jnp.concatenate([arr, fill], axis=0)
            extra = None
        main[nm] = jax.device_put(
            arr[:n_main], batch_sharding(mesh, arr.ndim, axis)
        )
        rest = tail_cols.get(nm)
        if rest is not None:
            rest = rest[max(0, n_main - n_dev):]
        parts = [p for p in (extra, rest) if p is not None and len(p)]
        if parts:
            tailb[nm] = np.concatenate(parts)
    return [main] + ([tailb] if tailb else [])

def map_blocks(
    fetches: Fetches,
    frame,
    feed_dict: Optional[Dict[str, str]] = None,
    trim: bool = False,
    strict: bool = False,
) -> "TensorFrame":
    """Transform a frame block by block, appending one column per output
    (or replacing all columns when ``trim=True``, in which case the output
    row count may differ from the input's).

    ≙ ``tfs.map_blocks`` (core.py:267-313) → DebugRowOps.mapBlocks
    (DebugRowOps.scala:305-400); trimmed variant ≙ mapBlocksTrimmed.
    Lazy: returns a frame with a pending computation (core.py:278-279).
    ``strict=True`` additionally runs the static analyzer and raises on
    error-severity diagnostics before any dispatch.
    """
    if _is_pandas(frame):
        return _map_pandas(fetches, frame, feed_dict, block=True,
                           strict=strict)
    program, _ = _normalize_program(
        fetches, frame.schema, block=True, feed_dict=feed_dict
    )
    program = _apply_feed_dict(program, feed_dict)
    validate_map(program, frame.schema, block=True, trim=trim)
    if strict:
        _strict_lint(program, frame, block_mode=True)
    out_infos = _sorted_output_infos(program, block_mode=True)
    if trim:
        schema = Schema(out_infos)
    else:
        schema = Schema(out_infos + frame.schema.columns)
        planned = _plan_map_result(frame, program, schema, rows=False)
        if planned is not None:
            return planned
    compiled = program.compiled()
    parent = frame
    input_names = program.input_names
    sharded = frame.is_sharded

    def compute() -> List[Block]:
        from collections import deque

        out_blocks: List[Block] = []
        t0 = time.perf_counter()
        n_total = 0
        # pipelined execution: keep up to `depth` blocks in flight so block
        # k+1's host→HBM transfer and compute overlap block k's device→host
        # readback (jax dispatch is async; only np.asarray synchronizes).
        # Sharded frames skip the window — their outputs stay in HBM.
        depth = 0 if sharded else max(0, get_config().map_pipeline_depth)
        in_flight: deque = deque()

        def finish(b: Block, n: int, outs) -> None:
            if not sharded:
                outs = {k: np.asarray(v) for k, v in outs.items()}
            if trim:
                out_blocks.append({i.name: outs[i.name] for i in out_infos})
                return
            for o in program.outputs:
                got = outs[o.name].shape[0] if outs[o.name].ndim > 0 else None
                if got != n:
                    raise ValidationError(
                        f"map_blocks output {o.name!r} produced {got} rows "
                        f"for a block of {n} rows. Appending requires "
                        "matching row counts; use trim=True for "
                        "row-count-changing programs."
                    )
            nb: Block = {i.name: outs[i.name] for i in out_infos}
            nb.update(b)
            out_blocks.append(nb)

        blocks = parent.blocks()
        # host-frame path: stage upcoming blocks' feeds in HBM from a
        # background thread so block k+1's host→device transfer overlaps
        # block k's compute — on transfer-taxed links (the relay tunnel;
        # any DCN-attached host) the copy is the dominant cost, exactly
        # the layer the reference called "very simple and very
        # inefficient" (TFDataOps.scala:32-33). Sharded frames skip it:
        # their columns already live in HBM.
        prefetch_depth = (
            0 if sharded else max(0, get_config().map_prefetch_depth)
        )
        feeds_seq = (
            gather_feeds(b, input_names, program) for b in blocks
        )
        if prefetch_depth > 0 and len(blocks) > 1:
            from .. import io as _io

            feeds_seq = _io.prefetch_to_device(feeds_seq, size=prefetch_depth)
        donate_cfg = get_config().donate_inputs
        for b, feeds in zip(blocks, feeds_seq):
            n = _block_num_rows(b)
            n_total += n
            # donate only provably-fresh buffers: every input column came
            # from host memory (the transfer above made a private device
            # copy). A device-resident frame column is the frame's own
            # storage — donating it would corrupt later reads.
            donate = donate_cfg and not any(
                isinstance(b[name], jax.Array) for name in input_names
            )
            outs = compiled.run_block(feeds, to_numpy=False, donate=donate)
            in_flight.append((b, n, outs))
            if len(in_flight) > depth:
                finish(*in_flight.popleft())
        while in_flight:
            finish(*in_flight.popleft())
        if trim and sharded and out_blocks:
            out_blocks = _rebalance_trimmed(
                out_blocks,
                [i.name for i in out_infos],
                parent.mesh,
                getattr(parent, "_axis", None) or get_config().batch_axis,
            )
        # device-resident outputs return before the TPU finishes (async
        # dispatch); label those spans distinctly so report() rows/s is
        # honest — only the host path measures completed execution
        name = "map_blocks.dispatch" if sharded else "map_blocks"
        profiling.record(name, time.perf_counter() - t0, n_total)
        return out_blocks

    result = TensorFrame(None, schema, pending=compute)
    result._produced_by_map = True
    if trim:
        # a row-count-changing map is a fusion barrier: downstream
        # chains re-root here (TFG107 names it when maps sit both sides)
        from ..plan import ir as plan_ir

        plan_ir.mark_barrier(
            result, "trim map_blocks (row-count-changing output)", frame
        )
    if sharded:
        result._mesh = frame.mesh
        result._axis = getattr(frame, "_axis", None)
    return result


# ---------------------------------------------------------------------------
# map_rows
# ---------------------------------------------------------------------------

# ragged staging byte cap: below it, every shape-group's feeds move in
# ONE device_put and dispatch before the first sync (transfer-latency
# win); above it, groups run one at a time so staged inputs + in-flight
# outputs can't OOM HBM on many-GB ragged blocks
_RAGGED_STAGE_BYTES = 1 << 28  # 256 MB


def _group_rows_by_shape(
    b: Dict[str, object], input_names: Sequence[str], n: int
) -> List[np.ndarray]:
    """Row indices grouped by input cell shape — the ragged dispatch
    unit. The common case (ONE 1-D ragged column) grouped VECTORIZED:
    lengths via a single fromiter, then unique/argsort, no 20k-iteration
    python dict loop; multi-input / higher-rank cells keep the general
    tuple-key path."""
    if n == 0:
        # zero rows → zero groups: np.split over an empty order array
        # would fabricate one EMPTY group whose downstream staging
        # (np.stack of nothing, est_bytes reading idx[0]) crashes
        return []
    if len(input_names) == 1:
        col = b[input_names[0]]
        cells = col if isinstance(col, list) else list(col)
        if cells and all(
            isinstance(c, np.ndarray) and c.ndim == 1 for c in cells
        ):
            lens = np.fromiter(
                (c.shape[0] for c in cells), np.int64, count=n
            )
            uniq, inv = np.unique(lens, return_inverse=True)
            order = np.argsort(inv, kind="stable")
            bounds = np.searchsorted(inv[order], np.arange(1, len(uniq)))
            return [g for g in np.split(order, bounds)]
    groups: Dict[tuple, List[int]] = {}
    for i in range(n):
        key = tuple(np.shape(b[name][i]) for name in input_names)
        groups.setdefault(key, []).append(i)
    return [np.asarray(v) for v in groups.values()]


def _stack_group(col, idx) -> np.ndarray:
    """Stack the cells ``col[i] for i in idx`` (same shape by grouping)
    into ``[len(idx), *cell]``: one native memcpy pass when available
    (np.stack pays per-element dispatch — it dominated the ragged host
    path), np.stack otherwise."""
    from .. import native

    cells = [col[i] for i in idx]
    try:
        # native.stack_cells returns None itself for unavailable /
        # non-ndarray / object-dtype / non-contiguous first cells;
        # BufferError covers a non-contiguous LATER cell (a sliced-view
        # ndarray) whose PyObject_GetBuffer fails inside rowpack.cpp —
        # np.stack handles such views fine (ADVICE r4)
        stacked = native.stack_cells(cells)
    except (ValueError, TypeError, BufferError):
        stacked = None
    if stacked is not None:
        return stacked
    return np.stack([np.asarray(c) for c in cells])


def _ragged_gather_plan(cols, input_names, n, program, group_list):
    """Device-side ragged staging (ISSUE 12): when the cost model
    selects the pallas ragged-gather kernel
    (``plan/rules.decide_ragged_gather`` — single 1-D ragged column,
    kernel-capable backend), the column's cells move ONCE as a flat
    device buffer and each shape group's padded batch is gathered
    on-device by ``kernels/ragged_gather.py`` — the per-group host
    ``np.stack`` + transfer disappears. Returns a
    ``gather(idx) -> feeds`` closure, or None to keep host staging
    (the ordinary path — not a counted decision)."""
    if len(input_names) != 1:
        return None
    name = input_names[0]
    cells = cols[name]
    if not cells or not all(
        isinstance(c, np.ndarray) and c.ndim == 1 and c.shape[0] > 0
        for c in cells
    ):
        return None
    if len({c.dtype for c in cells}) != 1:
        return None
    from ..plan import rules as _prules
    from ..plan import stats as _pstats

    decision = _prules.decide_ragged_gather(
        n, len(group_list), cells[0].dtype,
        observed_walls=_pstats.strategy_walls("ragged_gather"),
    )
    if decision is None:
        return None
    from ..kernels import ragged_gather as _krg
    from ..plan.lower import _note_decision

    lens = np.fromiter((c.shape[0] for c in cells), np.int64, count=n)
    if int(lens.sum()) > np.iinfo(np.int32).max:
        # start offsets ride int32 scalar prefetch; a flat buffer past
        # 2^31 elements would wrap them — host staging handles it
        return None
    starts = np.zeros(n, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    flat = np.concatenate(cells)
    spec = program.input(name)
    if dt.demotion_active() and flat.dtype != spec.dtype.np_dtype:
        # the x64 demotion boundary, applied once to the flat buffer
        # instead of per stacked group (mirrors group_feeds)
        flat = flat.astype(spec.dtype.np_dtype)
    flat_dev = jax.device_put(flat)
    _note_decision(decision)

    def gather(idx):
        g = len(idx)
        gb = bucket_rows(g)
        st = np.zeros(gb, np.int32)  # padding rows re-read offset 0;
        st[:g] = starts[np.asarray(idx)]  # their outputs are sliced off
        L = int(lens[int(idx[0])])
        return {name: _krg.ragged_gather_rows(flat_dev, st, L)}

    return gather


def _ragged_rows_outs(
    cols: Dict[str, list],
    input_names: Sequence[str],
    n: int,
    program: Program,
    compiled,
) -> Dict[str, object]:
    """Run a row-wise program over ``n`` ragged rows (``cols`` maps each
    input to its per-row cells): group rows by input cell shape, stage
    every group's padded feeds, move them with ONE device_put call, and
    dispatch every group before the first result sync — per-group
    transfer+sync round-trips multiply per-call link latency by the
    shape count (the r3 TPU run collapsed 23x on exactly this; VERDICT
    r3 #5; ≙ TFDataOps.scala:90-103). Returns one value per output:
    a dense ``[n, *cell]`` array (uniform cell shapes) or a per-row
    cell list (ragged outputs)."""
    if n == 0:
        # zero ragged rows: dtype/rank-correct empties (Unknown inner
        # dims degrade to 0), mirroring map_rows' empty-block branch —
        # the staging below assumes at least one row per group
        out0: Dict[str, object] = {}
        for o in program.outputs:
            dims = tuple(0 if d == Unknown else d for d in o.shape.dims)
            out0[o.name] = np.empty((0,) + dims, dtype=o.dtype.np_dtype)
        return out0
    group_list = [g for g in _group_rows_by_shape(cols, input_names, n)
                  if len(g)]
    donate_r = get_config().donate_inputs
    window = max(1, get_config().map_pipeline_depth)
    gather = _ragged_gather_plan(cols, input_names, n, program,
                                 group_list)

    def group_feeds(idx):
        g = len(idx)
        feeds = {}
        for name in input_names:
            stacked = _stack_group(cols[name], idx)
            spec = program.input(name)
            if (
                dt.demotion_active()
                and stacked.dtype != spec.dtype.np_dtype
            ):
                # x64 demotion boundary (mirrors gather_feeds)
                stacked = stacked.astype(spec.dtype.np_dtype)
            feeds[name] = stacked
        return pad_lead_dim(feeds, g, bucket_rows(g))

    def est_bytes(idx):
        # staged size WITHOUT staging: bucket-padded rows x cell bytes
        # (post-demotion dtype) — so wave planning never materializes
        # copies it may not use
        g = bucket_rows(len(idx))
        total = 0
        for name in input_names:
            c = np.asarray(cols[name][int(idx[0])])
            item = (
                np.dtype(program.input(name).dtype.np_dtype).itemsize
                if dt.demotion_active()
                else c.dtype.itemsize
            )
            total += g * int(np.prod(c.shape)) * item
        return total

    # WAVES: consecutive groups whose staged bytes fit the cap move
    # with one device_put and dispatch before the first sync (the
    # transfer-latency win VERDICT r3 #5 demands); the next wave stages
    # only after the previous drains, so peak host memory is one wave's
    # padded copies and peak HBM is one wave's inputs plus a
    # map_pipeline_depth window of outputs. A wave always holds >= 1
    # group, so a single over-cap group still runs (the old
    # group-at-a-time over-cap behavior is the 1-group-wave case).
    waves: List[List] = [[]]
    wave_bytes = 0
    for idx in group_list:
        bts = est_bytes(idx)
        if waves[-1] and wave_bytes + bts > _RAGGED_STAGE_BYTES:
            waves.append([])
            wave_bytes = 0
        waves[-1].append(idx)
        wave_bytes += bts

    from collections import deque as _deque

    outs_list: List[Dict[str, np.ndarray]] = []
    from ..plan.lower import observe_strategy_wall as _obs_wall

    for wave in waves:
        if gather is not None:
            t_stage = time.perf_counter()
            try:
                # padded batches materialize ON DEVICE (one flat
                # buffer moved once, above); rows already bucket-padded
                staged = [gather(idx) for idx in wave]
            except Exception as e:
                from . import segment as _segment

                # same triage as _segment_reduce_best: only a Mosaic
                # kernel-compile failure justifies the process-wide
                # fallback (kill-switch + fused-cache invalidation,
                # then the exact host staging below); a genuine bug in
                # the gather stays loud — swallowing it would silently
                # double-stage every ragged column forever
                if not _segment.pallas_enabled() or "Mosaic" not in str(e):
                    raise
                _segment.disable_pallas(
                    f"{type(e).__name__} in ragged-gather kernel"
                )
                gather = None
                staged = jax.device_put(
                    [group_feeds(idx) for idx in wave]
                )
            else:
                _obs_wall(
                    "ragged_gather", "pallas_ragged_gather",
                    time.perf_counter() - t_stage,
                )
        else:
            t_stage = time.perf_counter()
            staged = jax.device_put([group_feeds(idx) for idx in wave])
            if len(input_names) == 1:
                # only the single-ragged-column case competes with the
                # pallas gather — keep the wall table apples-to-apples
                _obs_wall(
                    "ragged_gather", "host_stack",
                    time.perf_counter() - t_stage,
                )
        in_flight_r: _deque = _deque()
        for f in staged:
            # freshly-transferred private copies: donation-safe
            # (honoring the kill switch)
            in_flight_r.append(
                compiled.run_rows(f, to_numpy=False, donate=donate_r)
            )
            if len(in_flight_r) > window:
                o = in_flight_r.popleft()
                outs_list.append(
                    {k: np.asarray(v) for k, v in o.items()}
                )
        while in_flight_r:
            o = in_flight_r.popleft()
            outs_list.append({k: np.asarray(v) for k, v in o.items()})
        del staged
    # VECTORIZED scatter: a uniform output column writes whole groups
    # via index assignment — no per-row python loop, no per-row dict,
    # no final re-stack (the r1-r3 assembly spent most of the ragged
    # path's host time there). Ragged outputs (cell shapes differ
    # across groups) keep the per-row list form.
    outs: Dict[str, object] = {}
    for o in program.outputs:
        cell_shapes = {outs_g[o.name].shape[1:] for outs_g in outs_list}
        if len(cell_shapes) == 1:
            first = outs_list[0][o.name]
            dest = np.empty((n,) + first.shape[1:], dtype=first.dtype)
            for idx, outs_g in zip(group_list, outs_list):
                dest[np.asarray(idx)] = (
                    np.asarray(outs_g[o.name])[: len(idx)]
                )
            outs[o.name] = dest
        else:
            cells: List = [None] * n
            for idx, outs_g in zip(group_list, outs_list):
                og = np.asarray(outs_g[o.name])
                for j, i in enumerate(idx):
                    cells[i] = og[j]
            outs[o.name] = cells  # ragged output column
    return outs


def map_rows(
    fetches: Fetches,
    frame,
    feed_dict: Optional[Dict[str, str]] = None,
    strict: bool = False,
) -> "TensorFrame":
    """Transform a frame row by row (placeholders are cell-shaped).

    ≙ ``tfs.map_rows`` (core.py:224-265) → DebugRowOps.mapRows
    (DebugRowOps.scala:403-484). Uniform blocks run as one vmapped XLA
    program; ragged blocks fall back to per-row execution with a
    per-cell-shape compile cache.
    """
    if _is_pandas(frame):
        return _map_pandas(fetches, frame, feed_dict, block=False,
                           strict=strict)
    program, _ = _normalize_program(
        fetches, frame.schema, block=False, feed_dict=feed_dict
    )
    program = _apply_feed_dict(program, feed_dict)
    validate_map(program, frame.schema, block=False)
    if strict:
        _strict_lint(program, frame, block_mode=False)
    out_infos = _sorted_output_infos(program, block_mode=False)
    schema = Schema(out_infos + frame.schema.columns)
    planned = _plan_map_result(frame, program, schema, rows=True)
    if planned is not None:
        return planned
    compiled = program.compiled()
    parent = frame
    input_names = program.input_names

    def compute() -> List[Block]:
        t0 = time.perf_counter()
        blocks = parent.blocks()
        results: List[Optional[Block]] = [None] * len(blocks)
        ragged_entries: List[Tuple[int, Block, int]] = []
        n_total = 0
        for bi, b in enumerate(blocks):
            n = _block_num_rows(b)
            n_total += n
            if n == 0:
                nb: Block = {}
                for i in out_infos:
                    # preserve the cell rank so cross-block concatenation
                    # works; Unknown inner dims degrade to 0
                    dims = tuple(
                        0 if d == Unknown else d for d in i.cell_shape.dims
                    )
                    nb[i.name] = np.empty((0,) + dims, dtype=i.dtype.np_dtype)
                nb.update(b)
                results[bi] = nb
                continue
            if block_is_ragged(b, input_names):
                ragged_entries.append((bi, b, n))
                continue
            feeds = gather_feeds(b, input_names, program)
            if not parent.is_sharded:
                # adaptive lead-dim bucketing: the partitioner yields
                # at most two block sizes, so the first few distinct
                # shapes compile exactly (zero padded work); once the
                # vmap cache shows shape proliferation (>= 3 distinct
                # sizes — an externally-built frame), pad to
                # power-of-two buckets so compiles stay O(log n).
                # (Sharded main blocks have one stable size — and
                # padding would disturb their device layout.)
                target = n
                if compiled.cache_sizes()["vmap"] >= 3:
                    target = bucket_rows(n)
                feeds = pad_lead_dim(feeds, n, target)
                outs = compiled.run_rows(feeds, to_numpy=False)
                outs = {k: np.asarray(v[:n]) for k, v in outs.items()}
            else:
                outs = compiled.run_rows(feeds, to_numpy=False)
            nb = {i.name: outs[i.name] for i in out_infos}
            nb.update(b)
            results[bi] = nb
        if ragged_entries:
            # GLOBAL ragged pass (≙ per-row dynamic lead dim,
            # TFDataOps.scala:90-103): group rows by input cell shape
            # across EVERY ragged block at once — #dispatches (and, on
            # device backends, #transfers) is the number of DISTINCT
            # shapes, not shapes x blocks, and each group's vmap runs
            # at the largest possible batch
            merged: Dict[str, list] = {name: [] for name in input_names}
            for _, b, _ in ragged_entries:
                for name in input_names:
                    col = b[name]
                    merged[name].extend(
                        col if isinstance(col, list) else list(col)
                    )
            big_n = sum(nr for _, _, nr in ragged_entries)
            outs_global = _ragged_rows_outs(
                merged, input_names, big_n, program, compiled
            )
            off = 0
            for bi, b, nr in ragged_entries:
                nb = {
                    i.name: outs_global[i.name][off:off + nr]
                    for i in out_infos
                }
                nb.update(b)
                results[bi] = nb
                off += nr
        name = "map_rows.dispatch" if parent.is_sharded else "map_rows"
        profiling.record(name, time.perf_counter() - t0, n_total)
        return results

    result = TensorFrame(None, schema, pending=compute)
    result._produced_by_map = True
    if frame.is_sharded:
        result._mesh = frame.mesh
        result._axis = getattr(frame, "_axis", None)
    return result


def _map_pandas(fetches, pdf, feed_dict, block: bool, strict: bool = False):
    """Local pandas path (≙ ``_map_pd``, core.py:171-183): run the program
    on the pandas columns and append the outputs to a copy of the frame.
    ``strict`` rides through to the converted-frame map_blocks so the
    pandas interop honors the same pre-dispatch analysis gate."""
    from ..frame import frame_from_pandas

    tf_frame = frame_from_pandas(pdf, num_blocks=1)
    # the reference's _map_pd always feeds whole columns (block semantics)
    result = map_blocks(fetches, tf_frame, feed_dict=feed_dict, strict=strict)
    out = pdf.copy()
    for name in result.schema.names:
        if name not in pdf.columns:
            out[name] = list(result.column_values(name))
    return out


# ---------------------------------------------------------------------------
# reduce_rows
# ---------------------------------------------------------------------------

def _unpack_results(program: Program, finals: Dict[str, np.ndarray]):
    """Return numpy results in fetch order; single fetch unwraps
    (≙ _unpack_row, core.py:111-125)."""
    out = []
    for name in program.fetch_order or program.output_names:
        v = finals[name]
        arr = np.asarray(v)
        out.append(arr if arr.ndim > 0 else arr.item())
    return out[0] if len(out) == 1 else out


def _sharded_reduce_rows_fn(program: Program, out_names, mesh, axis):
    """One XLA program for reduce_rows over a sharded frame: each shard
    folds its local rows with ``lax.scan``, the per-shard partials
    ``all_gather`` over the batch axis, and a second scan folds them —
    no host round-trip (≙ replacing performReducePairwise + driver fold,
    DebugRowOps.scala:939-979, with on-device collectives)."""
    from ..parallel._shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    pair_scan = pair_fold_body(program, out_names)

    def local(vals):
        carry = pair_scan(vals)
        gathered = {
            x: jax.lax.all_gather(carry[x], axis) for x in out_names
        }
        return pair_scan(gathered)

    in_specs = (
        {
            x: P(axis, *([None] * (program.input(f"{x}_1").shape.rank)))
            for x in out_names
        },
    )
    out_specs = {x: P() for x in out_names}
    return jax.jit(
        shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def reduce_rows(
    fetches: Fetches, frame, strict: bool = False
) -> Union[np.ndarray, list]:
    """Pairwise-reduce all rows to a single row. Each fetch ``x`` consumes
    placeholders ``x_1``/``x_2`` (Operations.scala:83-96). Eager
    (core.py:197 "not lazy").

    Execution: within each block, a sequential ``lax.scan`` fold under one
    jit; block partials are folded the same way. On sharded frames the
    fold runs per shard with an ``all_gather`` merge — one XLA program,
    no host gather. Reduction order is unspecified by contract
    (core.py:186-187), so the split does not change the result class the
    reference supports (associative programs).
    """
    program, _ = _normalize_program(
        fetches, frame.schema, block=False, reduce_mode="rows"
    )
    validate_reduce_rows(program, frame.schema)
    if strict:
        _strict_lint(program, frame, block_mode=False)
    out_names = [o.name for o in program.outputs]
    fold = make_pair_fold(program, out_names)
    t0 = time.perf_counter()

    # whole-pipeline route: a lazy plan-carrying frame fuses its map
    # chain WITH the pairwise-fold epilogue into one program per block
    # (plan/lower.lower_reduce) — the mapped columns never materialize;
    # the per-block partials below then combine exactly as always.
    from ..plan.lower import lower_reduce

    planned = lower_reduce(frame, program, out_names, "rows")
    partials: List[Dict[str, np.ndarray]] = (
        list(planned[0]) if planned is not None else []
    )
    blocks = [] if planned is not None else frame.blocks()
    if frame.is_sharded and blocks:
        main = blocks[0]
        axis = getattr(frame, "_axis", None) or get_config().batch_axis
        dp = frame.mesh.shape.get(axis, 1)
        main_ok = all(
            not isinstance(main.get(x), list)
            and getattr(main.get(x), "ndim", 0) >= 1
            and main[x].shape[0] >= 1
            # a trimmed map can leave a sharded frame with a row count the
            # mesh no longer divides; shard_map would reject it — host path
            and main[x].shape[0] % dp == 0
            for x in out_names
        )
        if main_ok:
            cache = getattr(program, "_sharded_rr", None)
            if cache is None or cache[0] != (frame.mesh, axis):
                fn = _sharded_reduce_rows_fn(
                    program, out_names, frame.mesh, axis
                )
                program._sharded_rr = ((frame.mesh, axis), fn)
            fn = program._sharded_rr[1]
            res = fn(
                {
                    x: _demote_cast(main[x], program.input(f"{x}_1"))
                    for x in out_names
                }
            )
            partials.append({x: np.asarray(res[x]) for x in out_names})
            blocks = blocks[1:]  # tail (if any) folds in below

    for b in blocks:
        n = _block_num_rows(b)
        if n == 0:
            continue
        feeds = {}
        for x in out_names:
            v = b[x]
            if isinstance(v, list):
                spec = program.input(f"{x}_1")
                try:
                    v = np.asarray(v, dtype=spec.dtype.np_dtype)
                except (ValueError, TypeError):
                    raise ValueError(
                        f"Column {x!r} holds ragged cells; reduce_rows "
                        "needs dense blocks (run analyze() first)."
                    ) from None
            elif not isinstance(v, np.ndarray):
                # sharded columns: the pairwise fold is sequential by
                # contract, so pull the shard-split array to host rather
                # than scan over a dp-sharded lead dim (unsupported slice)
                v = np.asarray(v)
            feeds[x] = _demote_cast(v, program.input(f"{x}_1"))
        if n == 1:
            partials.append({x: np.asarray(feeds[x][0]) for x in out_names})
        else:
            res = fold({x: jnp.asarray(feeds[x]) for x in out_names})
            partials.append({x: np.asarray(res[x]) for x in out_names})
    if not partials:
        raise ValueError("reduce_rows on an empty frame")
    if len(partials) == 1:
        finals = partials[0]
    else:
        stacked = {
            x: jnp.asarray(np.stack([p[x] for p in partials])) for x in out_names
        }
        res = fold(stacked)
        finals = {x: np.asarray(res[x]) for x in out_names}
    profiling.record(
        "reduce_rows", time.perf_counter() - t0,
        planned[1] if planned is not None else frame.num_rows,
    )
    return _unpack_results(program, finals)


# ---------------------------------------------------------------------------
# reduce_blocks
# ---------------------------------------------------------------------------

def reduce_blocks(
    fetches: Fetches, frame, strict: bool = False
) -> Union[np.ndarray, list]:
    """Block-reduce all rows to a single row. Each fetch ``x`` consumes a
    placeholder ``x_input`` with one extra (Unknown) leading dim
    (Operations.scala:98-108). Eager.

    Execution ≙ performReduceBlock per partition + pairwise merge
    (DebugRowOps.scala:510-533), except partials are stacked and reduced in
    one final program run instead of driver-coordinated pairwise merging.
    """
    program, _ = _normalize_program(
        fetches, frame.schema, block=True, reduce_mode="blocks"
    )
    validate_reduce_blocks(program, frame.schema)
    if strict:
        _strict_lint(program, frame, block_mode=True)
    out_names = [o.name for o in program.outputs]
    compiled = program.compiled()
    t0 = time.perf_counter()

    # whole-pipeline route: fuse the recorded map chain with the reduce
    # program into one dispatch per block (plan/lower.lower_reduce) —
    # the mapped columns never materialize; partials combine as always.
    from ..plan.lower import lower_reduce

    planned = lower_reduce(frame, program, out_names, "blocks")
    partials: List[Dict[str, np.ndarray]] = (
        list(planned[0]) if planned is not None else []
    )
    for b in ([] if planned is not None else frame.blocks()):
        if _block_num_rows(b) == 0:
            continue
        feeds = {}
        for x in out_names:
            v = b[x]
            spec = program.input(f"{x}_input")
            if isinstance(v, list):
                try:
                    v = np.asarray(v, dtype=spec.dtype.np_dtype)
                except (ValueError, TypeError):
                    raise ValueError(
                        f"Column {x!r} holds ragged cells; reduce_blocks "
                        "needs dense blocks (run analyze() first)."
                    ) from None
            else:
                v = _demote_cast(v, spec)
            feeds[f"{x}_input"] = v
        partials.append(compiled.run_block(feeds))
    if not partials:
        raise ValueError("reduce_blocks on an empty frame")
    if len(partials) == 1:
        finals = partials[0]
    else:
        feeds = {
            f"{x}_input": np.stack([p[x] for p in partials]) for x in out_names
        }
        finals = compiled.run_block(feeds)
    profiling.record(
        "reduce_blocks", time.perf_counter() - t0,
        planned[1] if planned is not None else frame.num_rows,
    )
    return _unpack_results(program, finals)


# ---------------------------------------------------------------------------
# aggregate (keyed)
# ---------------------------------------------------------------------------

from functools import lru_cache

from .segment import segment_sum as _segment_sum


def _agg_schema_infos(schema, keys, program) -> List[ColumnInfo]:
    """Result schema of a keyed aggregate: key columns (Unknown lead)
    then the program outputs sorted by name — shared by the eager
    assemble and the plan route's lazy result frame."""
    infos: List[ColumnInfo] = []
    for k in keys:
        infos.append(schema[k].with_block_shape(
            schema[k].cell_shape.prepend(Unknown)
        ))
    for o in sorted(program.outputs, key=lambda s: s.name):
        infos.append(ColumnInfo(o.name, o.dtype, o.shape.prepend(Unknown)))
    return infos


def _empty_agg_blocks(schema) -> List[Block]:
    """The zero-row aggregate result for ``schema`` — ONE definition
    shared by the eager empty-frame branch and the plan lowering, so
    the fused and unfused empty-aggregate schemas cannot drift."""
    empty: Block = {}
    for i in schema:
        dims = tuple(0 if d == Unknown else d for d in i.cell_shape.dims)
        if i.is_device:
            empty[i.name] = np.empty((0,) + dims, dtype=i.dtype.np_dtype)
        else:
            empty[i.name] = []
    return [empty]


def _segment_reduce_best(ops_key, num_groups, val_cols, seg_ids):
    """Keyed-reduction backend dispatch, recorded as a cost-model
    decision (``plan/rules.decide_segment_reduce``): host
    ``np.bincount`` on the CPU backend for 1-D float sums/means
    (XLA:CPU's serialized scatter is ~20x slower), the fused pallas
    segment-reduce kernel on kernel-capable backends
    (``kernels/segment_reduce.py`` — ONE dispatch for every fetch),
    the jitted segment program otherwise. Values may be numpy or jax
    arrays; returns numpy columns. EVERY host-frame keyed reduction —
    the eager fast path and the plan's fused epilogues — dispatches
    here, so fused and unfused outputs stay bit-identical whichever
    backend wins (the strategy choice is deterministic per feed). A
    Mosaic failure in the kernel trips the process-wide kill-switch
    (fused-cache invalidation included) and falls through to the
    jitted scatter — the PR 7 recovery contract."""
    from . import segment as _segment
    from ..plan import stats as _pstats
    from ..plan.lower import _note_decision, _note_flip, observe_strategy_wall
    from ..plan.rules import decide_segment_reduce

    decision = decide_segment_reduce(
        ops_key, val_cols, num_groups,
        observed_walls=_pstats.strategy_walls("segment_reduce"),
    )
    _note_decision(decision)
    _note_flip(decision)
    if decision.kind == "host_segment_reduce":
        t0 = time.perf_counter()
        out = _segment.segment_reduce_host(
            ops_key, num_groups, val_cols, seg_ids
        )
        observe_strategy_wall(
            "segment_reduce", "host_segment_reduce",
            time.perf_counter() - t0,
        )
        return out
    if decision.kind == "pallas_segment_reduce":
        from ..kernels import segment_reduce as _ksr

        t0 = time.perf_counter()
        try:
            out = _ksr.segment_reduce_pallas(
                ops_key, num_groups, val_cols, seg_ids
            )
        except Exception as e:
            # same triage as run_segment_fast: only a Mosaic kernel-
            # compile failure justifies the process-wide fallback
            if not _segment.pallas_enabled() or "Mosaic" not in str(e):
                raise
            _segment.disable_pallas(
                f"{type(e).__name__} in segment-reduce kernel"
            )
            _ksr._pallas_fn_for.cache_clear()
        else:
            observe_strategy_wall(
                "segment_reduce", "pallas_segment_reduce",
                time.perf_counter() - t0,
            )
            return out
    t0 = time.perf_counter()
    seg_vals = {x: jnp.asarray(val_cols[x]) for x, _ in ops_key}
    # int32 ids: halves the host→HBM id-column transfer (the hot cost
    # on relay-attached chips); group counts can't exceed int32 — the
    # id space is bounded by row count long before 2^31
    sids = jnp.asarray(np.asarray(seg_ids).astype(np.int32))
    res = run_segment_fast(ops_key, num_groups, seg_vals, sids)
    out = {x: np.asarray(res[x]) for x, _ in ops_key}
    observe_strategy_wall(
        "segment_reduce", "jit_segment_reduce", time.perf_counter() - t0
    )
    return out


def run_segment_fast(ops_key, num_groups, seg_vals, sids):
    """One jitted segment-reduce dispatch with the pallas kill-switch:
    a Mosaic kernel-compile failure disables the pallas path process-
    wide and retries on XLA's scatter — shared by the eager aggregate
    and the plan lowering's fused epilogues so retry semantics cannot
    diverge. ``_seg_fast_for`` is looked up by name so tests may
    monkeypatch it."""
    try:
        return _seg_fast_for(ops_key, num_groups)(seg_vals, sids)
    except Exception as e:
        from . import segment as _segment

        # only a pallas kernel-compile failure (Mosaic) justifies the
        # process-wide fallback; transient TPU errors (OOM etc.) and
        # genuine program bugs re-raise untouched
        if not _segment.pallas_enabled() or "Mosaic" not in str(e):
            raise
        _segment.disable_pallas(f"{type(e).__name__} in aggregate")
        _seg_fast_for.cache_clear()  # drop executables traced w/ pallas
        return _seg_fast_for(ops_key, num_groups)(seg_vals, sids)


def _host_fast_aggregate(program, frame, keys, seg_info, out_names):
    """The host segment fast path over a (forced) frame: gather value
    columns, encode group keys through the per-frame dictionary cache
    (:func:`tensorframes_tpu.ops.keys.frame_group_ids` — string keys
    encode once, not per aggregate), one vectorized segment reduction
    (:func:`_segment_reduce_best` picks the backend). Returns
    ``(out_key_cols, out_cols, n_rows)``. Shared by the eager
    aggregate and the plan lowering's fallback path."""
    from .keys import frame_group_ids

    val_cols = {}
    for x in out_names:
        vals = frame.column_values(x)
        if vals.dtype == object:
            raise ValueError(
                f"Column {x!r} is ragged; aggregate requires uniform "
                "cells (run analyze() first)."
            )
        val_cols[x] = _demote_cast(vals, program.input(f"{x}_input"))
    seg_ids, group_key_cols, num_groups = frame_group_ids(frame, keys)
    ops_key = tuple((out_name, op) for out_name, op, _ in seg_info)
    out_cols = _segment_reduce_best(ops_key, num_groups, val_cols, seg_ids)
    return dict(zip(keys, group_key_cols)), out_cols, len(seg_ids)


@lru_cache(maxsize=32)
def _seg_fast_for(ops, num_groups):
    """Jitted keyed reduction: one XLA program for all fetches. ``sids``
    may arrive in ANY order — segment scatters (and the pallas one-hot
    kernel) are sortedness-agnostic, so do not add ``indices_are_sorted``
    here. ``ops`` is a tuple of (output_name, reducer_op). The LRU keeps
    repeated aggregates on one executable while bounding retained
    programs when group counts vary per batch (evicted entries free
    their XLA executables)."""

    @jax.jit
    def fn(vals, sids):
        outs = {}
        for out_name, op in ops:
            v = vals[out_name]
            if op == "reduce_mean":
                s = _segment_sum(v, sids, num_segments=num_groups)
                c = jax.ops.segment_sum(
                    jnp.ones(v.shape[:1], v.dtype), sids, num_segments=num_groups
                )
                c = c.reshape((-1,) + (1,) * (v.ndim - 1))
                # cast back: fetch dtype == input dtype by contract
                # (the generic path does this via _reducer's astype)
                outs[out_name] = (s / c).astype(v.dtype)
            else:
                outs[out_name] = _SEGMENT_OPS[op](
                    v, sids, num_segments=num_groups
                )
        return outs

    return fn


_SEGMENT_OPS = {
    # sum rides the custom pallas one-hot MXU kernel on TPU (segment.py);
    # min/max stay on XLA's segment scatter
    "reduce_sum": _segment_sum,
    "reduce_min": jax.ops.segment_min,
    "reduce_max": jax.ops.segment_max,
}


def _batched_compaction(program, val_cols, seg_ids, num_groups, out_names):
    """Arbitrary-combiner aggregation as LEVEL-BATCHED device compaction.

    ≙ TensorFlowUDAF's compact-every-bufferSize fold (DebugRowOps.scala:
    608-702): the user program is applied to row buffers of <= buf rows,
    partials stack and re-compact — the same algebraic contract. But
    instead of one program call per chunk per GROUP from a python loop
    (the round-2 shape of this path: ~100k dispatches for 1M rows / 512
    groups), every level dispatches all same-sized chunks across ALL
    groups as one vmapped XLA call: <= buf dispatches per level,
    O(buf · log_buf(max group size)) total, data device-resident between
    levels (VERDICT r2 missing #5 — the UDAF-equivalent now runs on
    device). Chunk-count lead dims are padded to power-of-two buckets so
    the vmap cache stays O(log) per chunk size; padded chunks compute
    garbage that is simply never scattered back.
    """
    if num_groups == 0:
        out = {}
        for o in program.outputs:
            dims = tuple(0 if d == Unknown else d for d in o.shape.dims)
            out[o.name] = np.empty((0,) + dims, o.dtype.np_dtype)
        return out
    buf = max(2, get_config().aggregate_buffer_size)
    compiled = program.compiled()

    order = np.argsort(seg_ids, kind="stable")
    counts = np.bincount(seg_ids, minlength=num_groups).astype(np.int64)
    cur = {
        x: jnp.asarray(np.asarray(val_cols[x])[order]) for x in out_names
    }

    def run_chunks(mat):
        """One vmapped dispatch over a [n_chunks, size] row-index matrix.
        The lead dim is bucketed by padding the HOST index matrix (repeat
        the last row) before the device gather — feeds never round-trip
        to host for padding, so levels stay device-resident."""
        n_chunks = mat.shape[0]
        target = bucket_rows(n_chunks)
        if target > n_chunks:
            mat = np.concatenate(
                [mat, np.repeat(mat[-1:], target - n_chunks, axis=0)]
            )
        idx = jnp.asarray(mat.astype(np.int32))  # halve the index upload
        feeds = {
            f"{x}_input": jnp.take(cur[x], idx, axis=0)
            for x in out_names
        }
        res = compiled.run_rows(feeds, to_numpy=False)
        return {x: res[x][:n_chunks] for x in out_names}

    while int(counts.max(initial=0)) > buf:
        starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        k, r = counts // buf, counts % buf
        new_counts = k + (r > 0)
        new_starts = np.concatenate(([0], np.cumsum(new_counts)[:-1]))
        total_new = int(new_counts.sum())
        parts = []  # (positions in the next level's flat state, results)
        if int(k.sum()):
            # all FULL buf-chunks across all groups: one dispatch
            g_of = np.repeat(np.arange(num_groups), k)
            rank = np.arange(len(g_of)) - np.repeat(np.cumsum(k) - k, k)
            base = starts[g_of] + rank * buf
            mat = base[:, None] + np.arange(buf)[None, :]
            parts.append((new_starts[g_of] + rank, run_chunks(mat)))
        for rv in np.unique(r[r > 0]):
            # remainder chunks batched by size: <= buf-1 dispatches
            sel = np.flatnonzero(r == rv)
            base = starts[sel] + k[sel] * buf
            mat = base[:, None] + np.arange(int(rv))[None, :]
            parts.append((new_starts[sel] + k[sel], run_chunks(mat)))
        nxt = {}
        for x in out_names:
            first = parts[0][1][x]
            acc = jnp.zeros((total_new,) + first.shape[1:], first.dtype)
            for pos, res in parts:
                acc = acc.at[jnp.asarray(pos)].set(res[x])
            nxt[x] = acc
        cur, counts = nxt, new_counts

    # final application — the program runs at least once per group even
    # for single-row groups (matches the UDAF's final evaluate)
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    finals = {x: None for x in out_names}
    for cv in np.unique(counts):
        sel = np.flatnonzero(counts == cv)
        mat = starts[sel][:, None] + np.arange(int(cv))[None, :]
        res = run_chunks(mat)
        for x in out_names:
            if finals[x] is None:
                finals[x] = jnp.zeros(
                    (num_groups,) + res[x].shape[1:], res[x].dtype
                )
            finals[x] = finals[x].at[jnp.asarray(sel)].set(res[x])
    return {x: np.asarray(finals[x]) for x in out_names}


def _allgather_rows(arr: np.ndarray, ks: Optional[np.ndarray] = None) -> np.ndarray:
    """Allgather variable-row-count per-process arrays: the local
    ``[k_p, *cell]`` partials concatenate over processes in process-index
    order (matching ``_allgather_dicts``' union ordering). Two phases —
    row counts (pass precomputed ``ks`` to skip this collective when
    gathering several same-length columns), then payloads padded to the
    max count."""
    from jax.experimental import multihost_utils as mh

    if ks is None:
        ks = np.asarray(
            mh.process_allgather(np.asarray([arr.shape[0]], np.int64))
        ).ravel()
    kmax = int(ks.max())
    padded = np.zeros((kmax,) + arr.shape[1:], arr.dtype)
    padded[: arr.shape[0]] = arr
    gathered = np.asarray(mh.process_allgather(padded))
    gathered = gathered.reshape((len(ks), kmax) + arr.shape[1:])
    from ..blockstore.store import HOSTGATHER_BYTES

    HOSTGATHER_BYTES.inc(float(gathered.nbytes))
    return np.concatenate([gathered[p, : int(ks[p])] for p in range(len(ks))])


def _aggregate_multiprocess_generic(program, frame, keys, out_names):
    """Arbitrary-combiner aggregation across processes (the UDAF merge at
    multi-host scale — closes VERDICT r2 missing #5's second half: the
    generic path previously had NO multi-process story, it raised from
    ``column_values``).

    Per process: local group-id encode + local level-batched compaction
    to ONE partial row per local group (the program's algebraic contract
    — re-applying it to stacked partials is valid, exactly the
    reference's UDAF merge assumption, DebugRowOps.scala:668-683). Then
    one small allgather of (keys, partial rows) and a final combine of
    the union — every process computes the identical replicated result.
    Returns None when ineligible (non-uniform or ragged columns, host
    tail, outputs with Unknown dims — an empty-shard process could not
    then shape its padded allgather buffer)."""
    from .device_agg import (
        _allgather_dicts,
        assemble_key_cols,
        extract_local_rows,
        uniform_ok,
    )
    from .keys import group_ids

    blocks = frame.blocks()
    main = blocks[0]
    tail = blocks[1] if len(blocks) > 1 else None

    if frame.num_rows == 0:
        # group_ids cannot encode zero rows; aggregate()'s empty-frame
        # branch (checked BEFORE its host gather) owns the layout —
        # num_rows is global, so every process returns together and no
        # collective is left dangling
        return None

    ok = True
    if tail is not None and any(
        _block_num_rows({c: tail[c]}) for c in tail
    ):
        ok = False  # host-tail rows are process-ambiguous here
    if any(d == Unknown for o in program.outputs for d in o.shape.dims):
        ok = False
    cols = {}
    if ok:
        for c in list(keys) + list(out_names):
            v = extract_local_rows(main[c])
            if v is None or (c in out_names and v.dtype == object):
                ok = False  # ragged value cells can't batch
                break
            cols[c] = v
        if ok:
            n_local = len(cols[keys[0]])
            ok = all(len(cols[c]) == n_local for c in cols)
    from .exchange import _file_shuffle_ctx

    fctx = _file_shuffle_ctx()
    if fctx is not None and fctx.nprocs != jax.process_count():
        fctx = None  # a stale/foreign shuffle dir must not hijack a fleet
    if fctx is not None and fctx.nprocs > 1:
        # the eligibility vote goes through spill files too: with the
        # file transport armed, XLA collectives may be unavailable
        # entirely (that is the transport's reason to exist)
        from ..blockstore import shuffle as _fs

        agree = _fs.vote_all(ok, name="agg.ok")
    else:
        agree = uniform_ok(ok)
    if not agree:
        return None

    if len(cols[keys[0]]):
        ids_local, local_dict, k_local = group_ids(
            [cols[k] for k in keys]
        )
    else:
        ids_local = np.zeros(0, np.int64)
        local_dict = [np.asarray(cols[k])[:0] for k in keys]
        k_local = 0
    val_local = {
        x: _demote_cast(cols[x], program.input(f"{x}_input"))
        for x in out_names
    }
    partials = _batched_compaction(
        program, val_local, ids_local, k_local, out_names,
    )
    if fctx is not None and fctx.nprocs > 1:
        # file-shuffle merge (ROADMAP #3): ZERO host-gathered partial
        # tables — partials hash-partition by group key through per-rank
        # spill files, each rank combines only its key partition, and
        # only the small finals are shared back
        return _merge_partials_shuffled(
            program, frame, keys, out_names, list(local_dict), partials,
        )
    from jax.experimental import multihost_utils as mh

    union_key_cols, _ = _allgather_dicts(list(local_dict))
    ks = np.asarray(
        mh.process_allgather(np.asarray([k_local], np.int64))
    ).ravel()  # one counts collective shared by every value column
    union_vals = {
        x: _allgather_rows(np.asarray(partials[x]), ks) for x in out_names
    }
    union_ids, group_key_cols, K = group_ids(union_key_cols)
    out_cols = _batched_compaction(
        program, union_vals, union_ids, K, out_names
    )
    return assemble_key_cols(frame, keys, group_key_cols), out_cols


def _merge_partials_shuffled(
    program, frame, keys, out_names, local_dict, partials
):
    """Merge per-rank partial aggregation tables through the file
    shuffle (blockstore.shuffle) instead of allgathering them: the
    combine work distributes over ranks, no rank ever holds every
    rank's partials, and the exchange needs no XLA collective. Returns
    the same replicated ``(key_cols, out_cols)`` as the allgather
    path, groups in lexicographic key order."""
    from ..blockstore import shuffle as _fs
    from .device_agg import assemble_key_cols
    from .exchange import partition_by_hash
    from .keys import group_ids

    key_names = [f"__k{i}" for i in range(len(local_dict))]
    table = {n: np.asarray(a) for n, a in zip(key_names, local_dict)}
    for x in out_names:
        table[x] = np.asarray(partials[x])
    nprocs = _fs.context().nprocs
    part = partition_by_hash([table[n] for n in key_names], nprocs)
    mine = _fs.shuffle_rows(table, part, name="agg.partials")
    kcols = [np.asarray(mine[n]) for n in key_names]
    if len(kcols[0]):
        ids, gk, K = group_ids(kcols)
        combined = _batched_compaction(
            program, {x: np.asarray(mine[x]) for x in out_names},
            ids.astype(np.int64), K, out_names,
        )
    else:
        gk = [a[:0] for a in kcols]
        combined = {x: np.asarray(mine[x])[:0] for x in out_names}
    final = {n: np.asarray(g) for n, g in zip(key_names, gk)}
    for x in out_names:
        final[x] = np.asarray(combined[x])
    union = _fs.allshare_table(final, name="agg.finals")
    union_key_cols = [
        np.asarray(union[n], dtype=object)
        if isinstance(union[n], list) else np.asarray(union[n])
        for n in key_names
    ]
    union_ids, group_key_cols, K = group_ids(union_key_cols)
    out_cols = _batched_compaction(
        program, {x: np.asarray(union[x]) for x in out_names},
        union_ids.astype(np.int64), K, out_names,
    )
    return assemble_key_cols(frame, keys, group_key_cols), out_cols


def aggregate(
    fetches: Fetches, grouped: GroupedData, strict: bool = False
) -> "TensorFrame":
    """Algebraic aggregation over grouped data: one output row per key.

    ≙ ``tfs.aggregate`` (core.py:401-419) → DebugRowOps.aggregate via
    ``TensorFlowUDAF`` (DebugRowOps.scala:554-599, 608-702). Fetches follow
    the ``x`` / ``x_input`` naming contract, like reduce_blocks.

    Execution order, no sorting of rows anywhere: sharded frames first
    try the on-device plans (ops/device_agg.py — per-shard segment
    reduce + one collective). Otherwise keys encode to dense group ids
    on the host (ops/keys.py; value columns are never reordered), then
    either
    (a) *segment fast path* — the fetches are recognized algebraic
    reducers and lower to one vectorized ``jax.ops.segment_*`` program
    over the whole frame fed UNSORTED ids (replacing the Catalyst
    shuffle + UDAF with a single XLA program), or
    (b) *generic path* — groups made contiguous by a stable argsort of
    the int ids, then per group chunked compaction through the user
    program with a bounded buffer (compact-every-N,
    ≙ DebugRowOps.scala:646-657), keeping the jit cache ≤ N shapes.
    """
    frame = grouped.frame
    keys = grouped.keys
    t0 = time.perf_counter()
    program, seg_info = _normalize_program(
        fetches, frame.schema, block=True, reduce_mode="blocks"
    )
    validate_reduce_blocks(program, frame.schema)
    if strict:
        _strict_lint(program, frame, block_mode=True)
    out_names = [o.name for o in program.outputs]
    unfused_reason: Optional[str] = None

    def _assemble(out_key_cols, out_cols, n_rows):
        infos = _agg_schema_infos(frame.schema, keys, program)
        block: Block = {}
        block.update(out_key_cols)
        for o in program.outputs:
            block[o.name] = out_cols[o.name]
        profiling.record("aggregate", time.perf_counter() - t0, n_rows)
        tf = TensorFrame([block], Schema(infos))
        if unfused_reason is not None:
            from ..plan import ir as plan_ir

            plan_ir.mark_unfused(tf, "aggregate", unfused_reason)
        return tf

    # -- whole-pipeline route: a lazy plan-carrying frame records an
    # `aggregate` node instead of forcing its chain — the lowering
    # composes the fused upstream maps with a segment-reduce epilogue
    # into ONE program per block (plan/lower.execute_aggregate), so
    # the mapped value columns never materialize. Sharded and
    # multi-process frames keep their explicit device/collective plans
    # below; non-algebraic fetches keep the UDAF path (and get TFG109
    # evidence recorded for lint_plan). --------------------------------
    algebraic = seg_info is not None and all(
        op in _SEGMENT_OPS or op == "reduce_mean" for _, op, _ in seg_info
    )
    from ..plan import ir as plan_ir

    if (
        getattr(frame, "_plan", None) is not None
        and not frame.is_sharded
        and plan_ir.fusion_enabled()
        and jax.process_count() == 1
    ):
        if algebraic:
            node = plan_ir.PlanNode(
                "aggregate",
                parent=plan_ir.node_for_parent(frame),
                program=program,
                out_names=out_names,
                keys=keys,
                spec=tuple(seg_info),
                schema=Schema(_agg_schema_infos(frame.schema, keys, program)),
            )
            node._extended = True  # terminal: consumers re-source on it

            def agg_pending():
                from ..plan.lower import execute_aggregate

                return execute_aggregate(node)

            result = TensorFrame(None, node.schema, pending=agg_pending)
            node.bind(result)
            result._plan = node
            return result
        unfused_reason = (
            "non-algebraic fetches (no segment lowering): the chain "
            "materializes before the generic UDAF path runs — use "
            "reduce_sum/min/max/mean DSL fetches to fuse the epilogue"
        )

    # -- sharded fast path: per-shard dense segment reduce + one ICI
    # collective (no host gather, no sort — see ops/device_agg.py) ----------
    if seg_info is not None and frame.is_sharded:
        from .device_agg import try_aggregate_device

        dev = try_aggregate_device(frame, keys, seg_info, out_names)
        if dev is not None:
            key_cols_d, out_cols_d = dev
            return _assemble(key_cols_d, out_cols_d, frame.num_rows)

    # -- multi-process generic path: local compaction + partial exchange.
    # Gate: the fetches must be safely re-appliable to stacked partials —
    # true for arbitrary non-reducer programs (the UDAF contract the user
    # opted into) and for sum/min/max reducers whose device plan
    # declined, but NOT for reduce_mean (mean of partial means is not
    # the group mean; its segment plan handles it or the host path
    # raises loudly) -----------------------------------------------------
    mean_free = seg_info is None or all(
        op != "reduce_mean" for _, op, _ in seg_info
    )
    if frame.is_sharded and jax.process_count() > 1 and mean_free:
        mp = _aggregate_multiprocess_generic(program, frame, keys, out_names)
        if mp is not None:
            key_cols_mp, out_cols_mp = mp
            return _assemble(key_cols_mp, out_cols_mp, frame.num_rows)

    # -- empty frame: build the zero-row result BEFORE any host gather —
    # column_values on a multi-process sharded frame raises for
    # non-addressable columns even when there is nothing to gather
    if frame.num_rows == 0:
        schema_e = Schema(_agg_schema_infos(frame.schema, keys, program))
        profiling.record("aggregate", time.perf_counter() - t0, 0)
        return TensorFrame(_empty_agg_blocks(schema_e), schema_e)

    # -- host paths ---------------------------------------------------------
    if algebraic:
        # -- segment fast path: gather + cached key encode + ONE
        # vectorized segment dispatch (shared with the plan lowering's
        # fallback — see _host_fast_aggregate) ------------------------------
        out_key_cols, out_cols, n = _host_fast_aggregate(
            program, frame, keys, seg_info, out_names
        )
        return _assemble(out_key_cols, out_cols, n)

    # -- generic (UDAF-equivalent) path: level-batched device
    # compaction — see _batched_compaction ----------------------------------
    from .keys import frame_group_ids

    val_cols = {}
    for x in out_names:
        vals = frame.column_values(x)
        if vals.dtype == object:
            raise ValueError(
                f"Column {x!r} is ragged; aggregate requires uniform cells "
                "(run analyze() first)."
            )
        val_cols[x] = _demote_cast(vals, program.input(f"{x}_input"))
    seg_ids, group_key_cols, num_groups = frame_group_ids(frame, keys)
    out_cols = _batched_compaction(
        program, val_cols, seg_ids, num_groups, out_names
    )
    return _assemble(dict(zip(keys, group_key_cols)), out_cols, len(seg_ids))
