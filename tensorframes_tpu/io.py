"""Data loading: batch iteration + host→device prefetch.

The reference has no loader of its own — Spark's scan pipeline feeds
partitions to executors while TF runs (implicit overlap). The TPU-native
equivalent must be explicit: ``iterate_batches`` walks a frame's columns
in minibatches on the host, and ``prefetch_to_device`` runs
``jax.device_put`` on a background thread into a bounded buffer so the
next batch's host→HBM transfer overlaps the current batch's compute —
double buffering, the standard input-pipeline recipe.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Dict, Iterator, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .observability.metrics import counter as _counter
from .observability.metrics import gauge as _gauge
from .observability.metrics import histogram as _histogram
from .resilience.faults import fault_point
from .resilience.retry import RetryPolicy, retry_call
from .utils import get_logger
from .utils.npz import decode_array, encode_array

logger = get_logger(__name__)

# Prefetch pipeline telemetry (registered at import; see
# observability/metrics.py). The two wait histograms are the overlap
# diagnostic: a consumer that never waits is compute-bound (prefetch is
# doing its job); a producer that never waits means the buffer is too
# small or the loader too slow.
_PREFETCH_DEPTH = _gauge(
    "tftpu_prefetch_queue_depth",
    "Batches currently staged in the prefetch buffer",
)
_PREFETCH_BATCHES = _counter(
    "tftpu_prefetch_batches_total",
    "Batches delivered to the consumer by prefetch_to_device",
)
_PRODUCER_WAIT = _histogram(
    "tftpu_prefetch_producer_wait_seconds",
    "Time the prefetch worker blocked waiting for buffer space",
)
_CONSUMER_WAIT = _histogram(
    "tftpu_prefetch_consumer_wait_seconds",
    "Time the consumer blocked waiting for a staged batch",
)


def iterate_batches(
    frame,
    columns: Optional[Sequence[str]] = None,
    batch_size: int = 256,
    shuffle: bool = False,
    seed: int = 0,
    drop_remainder: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Yield ``{col: array[batch, ...]}`` minibatches from a frame's dense
    columns (host-side)."""
    if columns is None:
        columns = [c.name for c in frame.schema.device_columns]
    else:
        columns = list(columns)
    if not columns:
        raise ValueError(
            "iterate_batches: no columns to batch (frame has no dense "
            "device columns, or an empty selection was passed)"
        )
    cols = {c: np.asarray(frame.column_values(c)) for c in columns}
    n = len(next(iter(cols.values())))
    order = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(order)
    stop = n - (n % batch_size) if drop_remainder else n
    for lo in range(0, stop, batch_size):
        idx = order[lo : lo + batch_size]
        yield {c: v[idx] for c, v in cols.items()}


_SENTINEL = object()


def prefetch_to_device(
    batches: Iterable,
    size: int = 2,
    sharding=None,
    retry: Optional[RetryPolicy] = None,
    join_timeout: float = 5.0,
) -> Iterator:
    """Wrap a batch iterator with background ``jax.device_put``.

    A worker thread stages up to ``size`` batches in HBM ahead of the
    consumer (``sharding`` optionally places them on a mesh), so transfer
    overlaps compute.

    Failure semantics (the input-pipeline leg of the resilience
    subsystem): a worker exception is parked in a side slot — never
    inside the data queue where a full buffer or a consumer drain could
    delay or drop it — and re-raised by the consumer's very next
    ``__next__`` once the already-staged good batches are exhausted. The
    consumer never blocks indefinitely: it polls worker liveness, so
    even a worker killed by a non-``Exception`` (``KeyboardInterrupt``,
    interpreter teardown) surfaces instead of hanging the training loop.
    Shutdown joins the worker with ``join_timeout`` and logs if it is
    still wedged (e.g. a stuck transfer) rather than blocking teardown
    forever. ``retry`` applies a
    :class:`~tensorframes_tpu.resilience.RetryPolicy` to each
    host→device transfer, absorbing transient device-put faults.
    """
    def put(batch):
        def xfer():
            fault_point("io.prefetch.device_put")
            if sharding is not None:
                return jax.device_put(batch, sharding)
            return jax.device_put(batch)

        return retry_call(xfer, policy=retry, describe="prefetch.device_put")

    return pipeline_iter(
        batches, stage=put, size=size, join_timeout=join_timeout,
        observe=True, thread_name="tfs-prefetch",
    )


def pipeline_iter(
    items: Iterable,
    stage=None,
    size: int = 2,
    join_timeout: float = 5.0,
    observe: bool = False,
    thread_name: str = "tfs-pipeline",
) -> Iterator:
    """The generalized double-buffered pipeline under
    :func:`prefetch_to_device`: a worker thread pulls ``items``, applies
    ``stage`` (identity by default — pure read-ahead), and stages up to
    ``size`` results for the consumer. The streaming partitioner
    (``blockstore.stream_chain``) uses it to overlap the next chunk's
    disk read/parse with the current chunk's compute; failure and
    shutdown semantics are exactly prefetch_to_device's (parked worker
    exceptions, liveness polling, bounded join). ``observe=True`` wires
    the prefetch telemetry instruments (only prefetch_to_device should
    — the histograms describe the host→device pipeline).
    """
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()
    done = threading.Event()
    err: List[Optional[BaseException]] = [None]
    if stage is None:
        stage = lambda item: item  # noqa: E731 - identity read-ahead

    def enqueue(item) -> bool:
        # bounded put that aborts when the consumer is gone, so an
        # abandoned iterator can't pin the worker (and its staged HBM
        # buffers) forever
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                if item is not _SENTINEL and observe:
                    _PRODUCER_WAIT.observe(time.perf_counter() - t0)
                    _PREFETCH_DEPTH.set(q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in items:
                if stop.is_set() or not enqueue(stage(item)):
                    return
        except BaseException as e:  # parked for the consumer thread —
            # BaseException too: a KeyboardInterrupt/SystemExit dying in
            # the worker must surface as an error, not truncate the
            # stream into a clean-looking end-of-data
            err[0] = e
        finally:
            done.set()
            enqueue(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True, name=thread_name)
    t.start()

    try:
        # wait_t0 spans every empty poll until the next item lands (and
        # is re-armed after each yield resumes), so the histogram records
        # true per-batch consumer stall, not just the last 0.2s slice
        wait_t0 = time.perf_counter()
        while True:
            try:
                item = q.get(timeout=0.2)
            except queue.Empty:
                # nothing staged: if the worker is gone the stream is
                # over (error or not) — without this check a worker that
                # died before enqueueing its sentinel would hang us
                if done.is_set() or not t.is_alive():
                    try:
                        item = q.get_nowait()  # racing final enqueue
                    except queue.Empty:
                        if err[0] is not None:
                            raise err[0]
                        return
                else:
                    continue
            if item is _SENTINEL:
                if err[0] is not None:
                    raise err[0]
                return
            if observe:
                _CONSUMER_WAIT.observe(time.perf_counter() - wait_t0)
                _PREFETCH_DEPTH.set(q.qsize())
                _PREFETCH_BATCHES.inc()
            yield item
            wait_t0 = time.perf_counter()
    finally:
        # consumer finished or bailed early: release the worker, drop
        # any staged batches, and bound the shutdown wait. The depth
        # gauge goes to 0 here — a finished stream must not export
        # phantom staged batches (the sentinel, or batches a bailing
        # consumer abandoned) in an end-of-run snapshot
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        if observe:
            _PREFETCH_DEPTH.set(0)
        t.join(timeout=join_timeout)
        if t.is_alive():  # pragma: no cover - requires a wedged transfer
            logger.warning(
                "%s: worker still running %.1fs after shutdown (stuck "
                "stage?); leaving daemon thread behind",
                thread_name, join_timeout,
            )


# ---------------------------------------------------------------------------
# Frame persistence
# ---------------------------------------------------------------------------
#
# The reference never persists frames itself — Spark's data sources own
# storage. A standalone framework needs its own: a directory with a JSON
# schema manifest, one compressed npz for the dense columns, and (only when
# present) a pickle for host columns (strings / binaries / ragged cells).
# Dense arrays are stored as raw bytes keyed c0, c1, … with the numpy
# dtype/shape in the manifest: npz cannot reconstruct ml_dtypes (bfloat16
# loads as void '|V2'), and npz keys must not collide with savez's own
# parameter names (a column called "file" would) — same scheme as
# checkpoint.py's npz backend.

_MANIFEST = "frame.json"
_DENSE = "columns.npz"
_HOST = "host_columns.pkl"
_FORMAT_VERSION = 1


def save_frame(frame, path: str) -> None:
    """Write a frame to ``path`` (a directory, created if needed).

    Device columns are materialized to host numpy first; block structure
    is not preserved (reload with any ``num_blocks``).
    """
    import json
    import os
    import pickle
    import shutil

    fault_point("io.save_frame")
    # fail BEFORE touching the filesystem: a multi-host global array
    # cannot be materialized by one process (and a partial directory
    # would be worse than an error)
    for b in frame.blocks():
        for name, v in b.items():
            if not getattr(v, "is_fully_addressable", True):
                raise ValueError(
                    f"save_frame: column {name!r} spans non-addressable "
                    "devices (multi-host global array); use "
                    "save_frame_sharded/load_frame_sharded instead"
                )

    dense: Dict[str, np.ndarray] = {}
    host: Dict[str, list] = {}
    cols = []
    for i, info in enumerate(frame.schema):
        vals = [b[info.name] for b in frame.blocks()]
        is_list = any(isinstance(v, list) for v in vals)
        col = {
            "name": info.name,
            "dtype": info.dtype.name,
            "block_shape": list(info.block_shape.dims),
        }
        if info.is_device and not is_list:
            arr = np.concatenate([np.asarray(v) for v in vals], axis=0)
            dense[f"c{i}"], entry = encode_array(arr)
            col["np_dtype"] = entry["dtype"]
            col["np_shape"] = entry["shape"]
        else:
            flat: list = []
            for v in vals:
                flat.extend(list(v))
            host[info.name] = flat
        cols.append(col)
    manifest = {
        "format_version": _FORMAT_VERSION,
        "num_rows": frame.num_rows,
        "columns": cols,
    }
    # atomic save: build the whole directory aside, then swap it in — a
    # crash mid-write must never pair a new manifest with stale columns.
    # normpath first: with a trailing slash the tmp dir would land INSIDE
    # the target and be destroyed by the pre-swap rmtree.
    path = os.path.normpath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    try:
        os.makedirs(tmp)
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
        np.savez_compressed(os.path.join(tmp, _DENSE), **dense)
        if host:
            with open(os.path.join(tmp, _HOST), "wb") as f:
                pickle.dump(host, f)
        # keep a recoverable frame on disk at every instant: rename the
        # old directory aside, swap the new one in, only then delete the
        # old (rmtree-then-rename would lose the previous frame outright
        # on a crash between the two calls). The aside name is FIXED so a
        # later save — any process — can self-heal a crash that happened
        # inside the two-rename window instead of leaking the only copy.
        old = f"{path}.old"
        if os.path.isdir(old) and not os.path.isdir(path):
            os.rename(old, path)  # heal a previous crashed swap
        shutil.rmtree(old, ignore_errors=True)
        if os.path.isdir(path):
            os.rename(path, old)
        os.rename(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    logger.info(
        "save_frame: %d rows, %d dense + %d host columns -> %s",
        manifest["num_rows"], len(dense), len(host), path,
    )




def load_frame(path: str, num_blocks: Optional[int] = None):
    """Load a frame written by :func:`save_frame`."""
    import json
    import os
    import pickle

    from . import dtypes as dt
    from .frame import TensorFrame, _partition_bounds
    from .schema import ColumnInfo, Schema
    from .shape import Shape

    fault_point("io.load_frame")
    path = os.path.normpath(path)
    if not os.path.isdir(path) and os.path.isdir(f"{path}.old"):
        # a save crashed inside its two-rename swap window; the previous
        # frame is intact under the fixed aside name — read it
        path = f"{path}.old"
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format_version", 0) > _FORMAT_VERSION:
        raise ValueError(
            f"frame at {path} has format_version "
            f"{manifest['format_version']}; this build reads <= {_FORMAT_VERSION}"
        )
    raw = {}
    npz = os.path.join(path, _DENSE)
    if os.path.exists(npz):
        with np.load(npz, allow_pickle=False) as z:
            raw = {k: z[k] for k in z.files}
    host = {}
    pkl = os.path.join(path, _HOST)
    if os.path.exists(pkl):
        with open(pkl, "rb") as f:
            host = pickle.load(f)

    infos = []
    data: Dict[str, object] = {}
    for i, c in enumerate(manifest["columns"]):
        infos.append(
            ColumnInfo(c["name"], dt.by_name(c["dtype"]), Shape(c["block_shape"]))
        )
        if f"c{i}" in raw:  # dense: bytes → manifest dtype/shape
            data[c["name"]] = decode_array(
                raw[f"c{i}"], {"dtype": c["np_dtype"], "shape": c["np_shape"]}
            )
        else:
            data[c["name"]] = host[c["name"]]

    n = manifest["num_rows"]
    from .config import get_config

    k = num_blocks or min(get_config().default_num_blocks, max(1, n))
    blocks = []
    for lo, hi in _partition_bounds(n, k):
        blocks.append({name: v[lo:hi] for name, v in data.items()})
    return TensorFrame(blocks, Schema(infos))


def save_frame_sharded(frame, path: str) -> str:
    """Multi-host frame persistence: every process writes ITS OWN rows.

    A global sharded frame spans processes, so no single process can
    materialize it (``save_frame`` refuses). Instead each process writes
    the rows of its addressable shards to ``path/part-<process_index>``
    (atomic per part, via save_frame) and the set of parts reassembles
    with :func:`load_frame_sharded`. Single-process frames degrade to
    one part. Returns this process's part directory.

    All processes must call this in lockstep (standard SPMD contract);
    ``path`` is usually shared storage (NFS/GCS-fuse) in a real fleet.
    """
    import os

    import jax

    from .frame import TensorFrame
    from .schema import Schema

    pid = jax.process_index()
    local_block: Dict[str, object] = {}
    infos = []
    for info in frame.schema:
        parts = []
        for b in frame.blocks():
            v = b[info.name]
            if isinstance(v, (list, np.ndarray)):
                parts.append(v)
            elif getattr(v, "is_fully_addressable", True):
                parts.append(np.asarray(v))
            else:
                # concat this process's shards in row order, keeping ONE
                # replica per row-range: meshes with non-batch axes
                # replicate each row-shard across them (same index,
                # replica_id > 0) and must not duplicate rows
                shards = sorted(
                    (s for s in v.addressable_shards if s.replica_id == 0),
                    key=lambda s: s.index[0].start or 0,
                )
                parts.append(
                    np.concatenate([np.asarray(s.data) for s in shards], axis=0)
                )
        if isinstance(parts[0], list):
            flat: list = []
            for p in parts:
                flat.extend(list(p))
            local_block[info.name] = flat
        else:
            local_block[info.name] = np.concatenate(
                [np.asarray(p) for p in parts], axis=0
            )
        infos.append(info)
    part = os.path.join(path, f"part-{pid}")
    os.makedirs(path, exist_ok=True)
    save_frame(TensorFrame([local_block], Schema(infos)), part)
    # every process writes the identical meta (benign race) so a reload
    # under a different process count fails loudly instead of dropping parts
    import json

    with open(os.path.join(path, "parts.json"), "w") as f:
        json.dump({"num_parts": jax.process_count()}, f)
    return part


def load_frame_sharded(path: str, mesh=None, axis: Optional[str] = None):
    """Load this process's ``part-<process_index>`` written by
    :func:`save_frame_sharded` and reassemble the GLOBAL sharded frame
    (``parallel.frame_from_process_local``). Host-only columns are not
    supported across processes (same rule as frame_from_process_local)."""
    import os

    import jax

    from .parallel.distributed import frame_from_process_local

    import json

    meta_path = os.path.join(path, "parts.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            num_parts = json.load(f)["num_parts"]
        if num_parts != jax.process_count():
            raise ValueError(
                f"load_frame_sharded: saved with {num_parts} process(es) "
                f"but loading with {jax.process_count()}; part counts must "
                "match (repartition via a single-process load_frame of "
                "each part instead)"
            )
    part = os.path.join(path, f"part-{jax.process_index()}")
    local = load_frame(part, num_blocks=1)
    [block] = local.blocks()
    data = {}
    for info in local.schema:
        v = block[info.name]
        if isinstance(v, list):
            raise TypeError(
                f"Column {info.name!r}: host-only columns cannot span "
                "processes; drop them before save_frame_sharded or load "
                "the part directly with load_frame"
            )
        data[info.name] = v
    return frame_from_process_local(data, mesh=mesh, axis=axis)


# ---------------------------------------------------------------------------
# CSV ingestion
# ---------------------------------------------------------------------------

def _infer_csv_types(sample_rows, ncols):
    """Per-column type lattice over sample fields: int ⊂ float ⊂ str;
    empty fields promote numeric columns to float (missing → NaN)."""
    kinds = ["int"] * ncols
    for fields in sample_rows:
        for j in range(ncols):
            f = fields[j] if j < len(fields) else ""
            k = kinds[j]
            if k == "str":
                continue
            if f == "":
                if k == "int":
                    kinds[j] = "float"
                continue
            try:
                int(f)
                continue
            except ValueError:
                pass
            try:
                float(f)
                kinds[j] = "float"
            except ValueError:
                kinds[j] = "str"
    return kinds


def read_csv(
    path,
    delimiter: str = ",",
    dtypes: Optional[Dict[str, str]] = None,
    num_blocks: Optional[int] = None,
    rows_per_chunk: int = 262_144,
):
    """Read a header-ed CSV into a frame: int64/float64 columns for
    numeric data (types inferred from a sample; empty numeric fields →
    NaN via float promotion), string columns host-resident.

    Unquoted files parse in ONE native C++ pass (rowpack.parse_csv — the
    data-ingestion edge of the marshalling layer); quoted files and
    builds without the native module take the csv-module path with the
    same semantics. ``dtypes`` ({column: "int64"|"float64"|"string"})
    overrides inference per column.

    ``path`` may also be a **directory or a list of part files** (each
    with its own header). Parts then ingest chunk by chunk through a
    spillable :class:`~tensorframes_tpu.blockstore.BlockStore` instead
    of materializing the whole table: peak ingest RSS is bounded by the
    largest single part plus the ``TFTPU_BLOCK_BUDGET_MB`` budget, and
    the returned frame's dense blocks are zero-read ``np.memmap`` views
    over the spilled segments (the OS page cache owns residency; host
    string columns still load eagerly). Column types are inferred from
    the FIRST part and applied to the rest — pass ``dtypes`` when parts
    could infer differently. ``num_blocks`` is honored via an explicit
    ``repartition`` (which materializes — leave it None to stay
    out-of-core; block structure then mirrors the ingest chunks).
    For frames that must never materialize at all, walk
    :func:`scan_csv` with ``blockstore.stream_chain`` instead.
    """
    if isinstance(path, (list, tuple)) or os.path.isdir(path):
        frame = _frame_via_store(
            scan_csv(
                path, delimiter=delimiter, dtypes=dtypes,
                rows_per_chunk=rows_per_chunk,
            ),
            what=f"read_csv({path!r})",
        )
        if frame is None:
            # every part was header-only: the single-file empty path
            # builds the correctly-typed zero-row frame (scan_csv
            # yields only non-empty blocks, and empty string columns
            # cannot round-trip through frame_from_arrays)
            [first, *_] = _part_files(path, (".csv", ".tsv", ".txt"))
            return _read_csv_single(
                first, delimiter=delimiter, dtypes=dtypes,
                num_blocks=num_blocks,
            )
        return frame.repartition(num_blocks) if num_blocks else frame
    return _read_csv_single(
        path, delimiter=delimiter, dtypes=dtypes, num_blocks=num_blocks
    )


def _read_csv_single(
    path: str,
    delimiter: str = ",",
    dtypes: Optional[Dict[str, str]] = None,
    num_blocks: Optional[int] = None,
):
    """One CSV file → frame (the pre-dataplane ``read_csv`` body)."""
    import csv as _csv
    import re

    from . import native
    from .frame import frame_from_arrays

    with open(path, "rb") as f:
        data = f.read()
    head, _, body = data.partition(b"\n")
    quoted = b'"' in data
    head_text = head.decode("utf-8").rstrip("\r")
    if quoted:
        # quoted files get real csv parsing everywhere, header included
        names = next(_csv.reader([head_text], delimiter=delimiter))
        names = [h.strip() for h in names]
    else:
        names = [h.strip() for h in head_text.split(delimiter)]
    ncols = len(names)

    _KIND_FOR = {"int64": "int", "float64": "float", "string": "str"}

    def apply_overrides(kinds):
        for j, n in enumerate(names):
            want = (dtypes or {}).get(n)
            if want is not None:
                if want not in _KIND_FOR:
                    raise ValueError(
                        f"read_csv: unsupported dtype {want!r} for column "
                        f"{n!r}; supported: {sorted(_KIND_FOR)}"
                    )
                kinds[j] = _KIND_FOR[want]
        return kinds

    if re.search(rb"\S", body) is None:
        # empty lists can't infer a schema; build explicit column infos
        from . import dtypes as dt
        from .frame import TensorFrame
        from .schema import ColumnInfo, Schema
        from .shape import Shape, Unknown

        kinds = apply_overrides(["float"] * ncols)
        kind_dt = {"int": "int64", "float": "float64", "str": "string"}
        infos, block = [], {}
        for n, k in zip(names, kinds):
            scalar = dt.by_name(kind_dt[k])
            infos.append(ColumnInfo(n, scalar, Shape((Unknown,))))
            block[n] = (
                [] if k == "str" else np.empty((0,), scalar.np_dtype)
            )
        return TensorFrame([block], Schema(infos))

    # sample-based inference over a bounded prefix (first 100 lines of the
    # first MiB — never materializes the whole file line-by-line), then
    # per-column override
    prefix = body[: 1 << 20]
    lines = prefix.split(b"\n")
    if len(body) > len(prefix):
        lines = lines[:-1]  # last line may be truncated mid-field
    sample_text = [
        line.decode("utf-8", "replace").rstrip("\r")
        for line in lines[:100]
        if line.strip()
    ]
    if quoted:
        sample = list(_csv.reader(sample_text, delimiter=delimiter))
    else:
        sample = [t.split(delimiter) for t in sample_text]
    kinds = apply_overrides(_infer_csv_types(sample, ncols))

    mod_ok = native.available() and not quoted and len(delimiter) == 1
    cols: Dict[str, object] = {}
    if mod_ok:
        codes = [{"int": 3, "float": 0, "str": 4}[k] for k in kinds]
        out = native._load().parse_csv(body, ord(delimiter), codes)
        nrow = out[-1]
        for j, n in enumerate(names):
            if kinds[j] == "str":
                cols[n] = out[j]
            else:
                npdt = np.int64 if kinds[j] == "int" else np.float64
                cols[n] = np.frombuffer(out[j], dtype=npdt)
        logger.debug("read_csv: native parse of %d rows", nrow)
    else:
        text = body.decode("utf-8", "replace").splitlines()
        reader = _csv.reader(text, delimiter=delimiter)
        raw: List[List[str]] = [r for r in reader if r]
        for j, n in enumerate(names):
            vals = [r[j] if j < len(r) else "" for r in raw]
            if kinds[j] == "int":
                cols[n] = np.asarray([int(v) for v in vals], np.int64)
            elif kinds[j] == "float":
                cols[n] = np.asarray(
                    [float(v) if v != "" else np.nan for v in vals], np.float64
                )
            else:
                cols[n] = vals
    return frame_from_arrays(cols, num_blocks=num_blocks)


# ---------------------------------------------------------------------------
# Chunked multi-part ingest through the block store (ROADMAP #3)
# ---------------------------------------------------------------------------

def _part_files(paths, exts) -> List[str]:
    """Resolve a directory (sorted, extension-filtered) or an explicit
    list (caller order preserved — it IS the row order) to part files."""
    if isinstance(paths, (list, tuple)):
        out = [os.fspath(p) for p in paths]
        missing = [p for p in out if not os.path.isfile(p)]
        if missing:
            raise FileNotFoundError(f"part file(s) not found: {missing}")
        if not out:
            raise ValueError("empty part-file list")
        return out
    out = []
    for name in sorted(os.listdir(paths)):
        full = os.path.join(paths, name)
        if name.startswith((".", "_")) or not os.path.isfile(full):
            continue
        if os.path.splitext(name)[1].lower() in exts:
            out.append(full)
    if not out:
        raise ValueError(
            f"no part files matching {sorted(exts)} under {paths!r}"
        )
    return out


#: Part-file extensions per scan kind (the same sets scan_csv /
#: scan_parquet filter by).
PART_EXTS: Dict[str, tuple] = {
    "csv": (".csv", ".tsv", ".txt"),
    "parquet": (".parquet", ".pq"),
}


def part_manifest(paths, kind: str = "csv") -> List[Tuple[str, str]]:
    """Chunk-arrival manifest of a growing directory (or explicit part
    list): ``[(path, signature), ...]`` in scan order. The signature is
    :func:`compilecache.fingerprint.part_signature` (basename + size +
    mtime_ns — O(#files) stat calls, no content read), so a registered
    query can decide per request whether anything arrived, changed, or
    disappeared since its cached partials were computed: appended parts
    show up as new (path, sig) rows, a rewritten part keeps its path
    but moves its signature, a removed part drops its row."""
    from .compilecache.fingerprint import part_signature

    try:
        exts = PART_EXTS[kind]
    except KeyError:
        raise ValueError(
            f"part_manifest kind must be one of {sorted(PART_EXTS)}, "
            f"got {kind!r}"
        ) from None
    return [(p, part_signature(p)) for p in _part_files(paths, exts)]


def part_frame(path: str, kind: str = "csv", delimiter: str = ",",
               dtypes: Optional[Dict[str, str]] = None):
    """ONE part file → one frame (possibly zero-row for a header-only
    CSV part). The per-chunk read of the registered-query incremental
    path: an appended part is re-read alone, never the directory.
    ``dtypes`` pins CSV column types exactly like :func:`scan_csv`'s
    first-part pinning — callers that read parts independently must pin
    from one authoritative part themselves or two chunks of one table
    could parse under different types."""
    if kind == "csv":
        return _read_csv_single(
            path, delimiter=delimiter, dtypes=(dtypes or None),
            num_blocks=1,
        )
    if kind == "parquet":
        _require_pyarrow()
        import pyarrow.parquet as pq

        return frame_from_arrow(pq.read_table(path), num_blocks=1)
    raise ValueError(
        f"part_frame kind must be one of {sorted(PART_EXTS)}, got {kind!r}"
    )


def _iter_row_chunks(block: Dict[str, object], rows_per_chunk: int):
    n = 0
    for v in block.values():
        n = len(v)
        break
    for lo in range(0, n, max(1, rows_per_chunk)):
        hi = min(n, lo + rows_per_chunk)
        yield {k: v[lo:hi] for k, v in block.items()}


def scan_csv(
    paths,
    delimiter: str = ",",
    dtypes: Optional[Dict[str, str]] = None,
    rows_per_chunk: int = 262_144,
) -> Iterator[Dict[str, object]]:
    """Chunked CSV scan: yield ``{column: array|list}`` blocks of at
    most ``rows_per_chunk`` rows from a directory / list of part files,
    one part in memory at a time — the block source for
    ``blockstore.stream_chain`` (multi-TB scans never materialize).
    Types are inferred from the first part WITH rows and pinned as
    overrides for the rest (pass ``dtypes`` to pin them yourself); a
    part whose values cannot parse under the pinned types raises —
    parts must be type-consistent. Only non-empty blocks are yielded
    (header-only parts contribute nothing)."""
    overrides: Dict[str, str] = dict(dtypes or {})
    pinned = False
    for part in _part_files(paths, (".csv", ".tsv", ".txt")):
        f = _read_csv_single(
            part, delimiter=delimiter,
            dtypes=(overrides or None), num_blocks=1,
        )
        if not pinned and f.num_rows > 0:
            # pin from the first part WITH rows: a header-only part
            # infers float64 everywhere and would poison the overrides
            for info in f.schema:
                overrides.setdefault(info.name, info.dtype.name)
            pinned = True
        if f.num_rows == 0:
            continue  # header-only part: nothing to yield (and see the
            # pinning guard above — its float defaults must not stick)
        [block] = f.blocks()
        yield from _iter_row_chunks(block, rows_per_chunk)


def scan_parquet(
    paths, rows_per_chunk: int = 262_144
) -> Iterator[Dict[str, object]]:
    """Chunked Parquet scan (via pyarrow's batch reader): yield blocks
    of at most ``rows_per_chunk`` rows from a directory / list of part
    files without materializing any full table — the block source for
    ``blockstore.stream_chain``."""
    pa = _require_pyarrow()
    import pyarrow.parquet as pq

    for part in _part_files(paths, (".parquet", ".pq")):
        pf = pq.ParquetFile(part)
        for batch in pf.iter_batches(batch_size=max(1, rows_per_chunk)):
            if batch.num_rows == 0:
                continue
            f = frame_from_arrow(
                pa.Table.from_batches([batch]), num_blocks=1
            )
            [block] = f.blocks()
            yield block


def _frame_via_store(blocks_iter, what: str):
    """Ingest a block stream through a spillable BlockStore and rebuild
    a TensorFrame over memmap views of the spilled segments. The store
    is pinned to the frame (dropped with it); ingest RSS is bounded by
    the resident budget, not the table."""
    import weakref

    from .blockstore import BlockStore
    from .blockstore.partitioner import SpilledFrame
    from .frame import frame_from_arrays

    store = BlockStore()
    refs, schema, sig = [], None, None
    try:
        for block in blocks_iter:
            f = frame_from_arrays(block, num_blocks=1)
            fsig = [(i.name, i.dtype.name) for i in f.schema]
            if schema is None:
                schema, sig = f.schema, fsig
            elif fsig != sig:
                raise ValueError(
                    f"{what}: part schema drifted — first part "
                    f"{sig}, this chunk {fsig}; pass dtypes= to pin "
                    "column types across parts"
                )
            [b] = f.blocks()
            refs.append(store.put(b))
    except BaseException:
        store.close()
        raise
    if schema is None:
        # zero non-empty chunks: the caller owns the typed empty-frame
        # fallback (scan_* yield only non-empty blocks)
        store.close()
        return None
    spilled = SpilledFrame(store, refs, schema, owns_store=True)
    frame = spilled.to_frame(mmap=True)
    # pin the spill segments to the frame's lifetime (deleted with it;
    # on Linux open memmaps stay valid over the unlink)
    frame._data_plane = spilled
    weakref.finalize(frame, spilled.drop)
    logger.info(
        "%s: ingested %d chunk(s), %d rows via block store "
        "(resident=%d spilled=%d)",
        what, len(refs), spilled.num_rows, store.resident_bytes,
        store.spilled_bytes,
    )
    return frame


def write_csv(frame, path: str, delimiter: str = ",") -> None:
    """Write a frame to a header-ed CSV (the inverse of :func:`read_csv`).

    Dense numeric columns format via numpy; string/host columns via str().
    Vector cells are rejected — CSV is a scalar-column format (same rule
    as the reference's string support: scalars only, datatypes.scala:577-581).
    """
    import csv as _csv

    cols = {}
    for info in frame.schema:
        if info.cell_shape.rank > 0:
            raise ValueError(
                f"write_csv: column {info.name!r} has cell shape "
                f"{info.cell_shape}; CSV holds scalar columns only"
            )
        v = frame.column_values(info.name)
        cols[info.name] = v
    names = list(cols)
    n = len(next(iter(cols.values()))) if names else 0
    with open(path, "w", newline="") as f:
        w = _csv.writer(f, delimiter=delimiter)
        w.writerow(names)
        for i in range(n):
            w.writerow([cols[c][i] for c in names])


# ---------------------------------------------------------------------------
# Arrow / Parquet interop (optional: gated on pyarrow)
# ---------------------------------------------------------------------------
#
# Arrow IS the columnar interchange format the reference's Row-marshalling
# layer never had: an arrow Table's numeric columns view as numpy without
# copying, so table → frame → HBM is two zero-copy hops + one DMA
# (jax.device_put). Everything here degrades with a clear ImportError if
# pyarrow is absent — it is an optional dependency.

def _require_pyarrow():
    try:
        import pyarrow  # noqa: F401

        return pyarrow
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "pyarrow is required for arrow/parquet interop "
            "(pip install pyarrow)"
        ) from e


def frame_from_arrow(table, num_blocks: Optional[int] = None):
    """Build a frame from a pyarrow Table (zero-copy for non-null numeric
    columns). Strings become host columns; list-typed columns become
    per-row cells (dense if uniform, ragged otherwise)."""
    pa = _require_pyarrow()
    from .frame import frame_from_arrays

    data: Dict[str, object] = {}
    for name in table.column_names:
        col = table.column(name)
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        t = col.type
        if pa.types.is_integer(t) or pa.types.is_floating(t):
            if col.null_count:
                if pa.types.is_integer(t):
                    raise ValueError(
                        f"Column {name!r} has nulls; integer columns cannot "
                        "represent missing values (cast to float upstream)"
                    )
                data[name] = col.to_numpy(zero_copy_only=False)
            else:
                data[name] = col.to_numpy(zero_copy_only=True)
        elif pa.types.is_boolean(t):
            data[name] = col.to_numpy(zero_copy_only=False)
        elif pa.types.is_string(t) or pa.types.is_large_string(t):
            data[name] = col.to_pylist()
        elif pa.types.is_binary(t) or pa.types.is_large_binary(t):
            data[name] = col.to_pylist()
        elif pa.types.is_list(t) or pa.types.is_large_list(t) or (
            pa.types.is_fixed_size_list(t)
        ):
            data[name] = [
                np.asarray(cell) if cell is not None else None
                for cell in col.to_pylist()
            ]
        else:
            raise TypeError(f"Column {name!r}: unsupported arrow type {t}")
    return frame_from_arrays(data, num_blocks=num_blocks)


def frame_to_arrow(frame):
    """Frame → pyarrow Table. Scalar numeric columns are zero-copy;
    vector cells become arrow lists; host columns pass through."""
    pa = _require_pyarrow()

    arrays = {}
    for info in frame.schema:
        v = frame.column_values(info.name)
        if isinstance(v, np.ndarray) and v.dtype != object and v.ndim == 1:
            arrays[info.name] = pa.array(v)
        elif isinstance(v, np.ndarray) and v.dtype != object:
            arrays[info.name] = pa.array([row.tolist() for row in v])
        else:
            arrays[info.name] = pa.array(list(v))
    return pa.table(arrays)


def read_parquet(
    path, num_blocks: Optional[int] = None, rows_per_chunk: int = 262_144
):
    """Read a parquet file into a frame (via pyarrow).

    ``path`` may also be a directory or a list of part files: parts
    then ingest batch by batch through a spillable block store (same
    contract as the multi-part ``read_csv`` — bounded ingest RSS,
    memmap-backed dense blocks, ``num_blocks`` honored only via an
    explicit materializing repartition). For never-materialize scans,
    walk :func:`scan_parquet` with ``blockstore.stream_chain``."""
    _require_pyarrow()
    import pyarrow.parquet as pq

    if isinstance(path, (list, tuple)) or os.path.isdir(path):
        frame = _frame_via_store(
            scan_parquet(path, rows_per_chunk=rows_per_chunk),
            what=f"read_parquet({path!r})",
        )
        if frame is None:  # all parts empty: the single-file path owns
            # the typed zero-row frame (see read_csv)
            [first, *_] = _part_files(path, (".parquet", ".pq"))
            return frame_from_arrow(
                pq.read_table(first), num_blocks=num_blocks
            )
        return frame.repartition(num_blocks) if num_blocks else frame
    return frame_from_arrow(pq.read_table(path), num_blocks=num_blocks)


def write_parquet(frame, path: str) -> None:
    """Write a frame to a parquet file (via pyarrow)."""
    _require_pyarrow()
    import pyarrow.parquet as pq

    pq.write_table(frame_to_arrow(frame), path)
