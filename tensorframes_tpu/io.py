"""Data loading: batch iteration + host→device prefetch.

The reference has no loader of its own — Spark's scan pipeline feeds
partitions to executors while TF runs (implicit overlap). The TPU-native
equivalent must be explicit: ``iterate_batches`` walks a frame's columns
in minibatches on the host, and ``prefetch_to_device`` runs
``jax.device_put`` on a background thread into a bounded buffer so the
next batch's host→HBM transfer overlaps the current batch's compute —
double buffering, the standard input-pipeline recipe.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, Iterable, Optional, Sequence

import jax
import numpy as np

from .utils import get_logger

logger = get_logger(__name__)


def iterate_batches(
    frame,
    columns: Optional[Sequence[str]] = None,
    batch_size: int = 256,
    shuffle: bool = False,
    seed: int = 0,
    drop_remainder: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Yield ``{col: array[batch, ...]}`` minibatches from a frame's dense
    columns (host-side)."""
    if columns is None:
        columns = [c.name for c in frame.schema.device_columns]
    else:
        columns = list(columns)
    if not columns:
        raise ValueError(
            "iterate_batches: no columns to batch (frame has no dense "
            "device columns, or an empty selection was passed)"
        )
    cols = {c: np.asarray(frame.column_values(c)) for c in columns}
    n = len(next(iter(cols.values())))
    order = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(order)
    stop = n - (n % batch_size) if drop_remainder else n
    for lo in range(0, stop, batch_size):
        idx = order[lo : lo + batch_size]
        yield {c: v[idx] for c, v in cols.items()}


_SENTINEL = object()


def prefetch_to_device(
    batches: Iterable,
    size: int = 2,
    sharding=None,
) -> Iterator:
    """Wrap a batch iterator with background ``jax.device_put``.

    A worker thread stages up to ``size`` batches in HBM ahead of the
    consumer (``sharding`` optionally places them on a mesh), so transfer
    overlaps compute. Exceptions from the source iterator propagate to the
    consumer at the point of ``next()``.
    """
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()

    def put(batch):
        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.device_put(batch)

    def enqueue(item) -> bool:
        # bounded put that aborts when the consumer is gone, so an
        # abandoned iterator can't pin the worker (and its staged HBM
        # buffers) forever
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in batches:
                if stop.is_set() or not enqueue(put(batch)):
                    return
        except Exception as e:  # propagate into the consumer thread
            enqueue(e)
            return
        enqueue(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True, name="tfs-prefetch")
    t.start()

    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        # consumer finished or bailed early: release the worker and drop
        # any staged batches
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
