"""Foreign TF ``GraphDef`` ingestion: frozen-graph files → :class:`Program`.

The reference executed ``GraphDef`` protos produced by *any* TF program —
``PythonOpBuilder.graphFromFile`` reads the serialized bytes straight off
disk (PythonInterface.scala:115-118; fixtures
``src/test/resources/graph.pb`` / ``graph2.pb``, loaded by
test/dsl.scala:109-112). This module closes that capability for the TPU
build without importing TensorFlow: a minimal clean-room protobuf
wire-format reader decodes the ``GraphDef``/``NodeDef``/``AttrValue``/
``TensorProto`` subset frozen inference graphs actually use, and each node
lowers to a ``jax.numpy`` expression evaluated in topological order.

Supported ops cover the surface the reference's own DSL emits
(Placeholder/Const/Identity/Add/Div/Sum/Min — dsl/DslImpl.scala:77-200),
the obvious neighbours (Sub/Mul/Neg/Max/Mean/Prod/Maximum/Minimum/
MatMul/Relu/Exp/Log/Sqrt/Rsqrt/Cast/Reshape/Squeeze/Pad/Softmax), and
the convolutional family frozen image models need (Conv2D/
DepthwiseConv2dNative/MaxPool/AvgPool/BiasAdd/Concat[V2]/
FusedBatchNorm[V2/V3] over NHWC), and the transformer family
(GatherV2 embeddings, Einsum/BatchMatMulV2 attention, SelectV2
masking, LayerNorm moments, Erf/Erfc gelu) — enough that a full frozen
keras Inception-v3 (~2200 nodes, batchnorm decomposed to
Mul/Sub/Rsqrt/AddV2 by the freezer), TF1-era graphs with un-decomposed
FusedBatchNorm, and a frozen keras MultiHeadAttention encoder block
execute bit-close to TF (tests/test_graphdef_frozen.py).
Multi-output ops (Split/SplitV/Unpack/TopKV2/IdentityN) evaluate to
tuples with ``:k`` ref selection. Un-frozen ``tf.function`` exports
import too: ``PartitionedCall``/``StatefulPartitionedCall`` bodies come
from the graph's ``FunctionDefLibrary`` (clean-room FunctionDef decode;
nested and multi-output calls included). ``quantize_weights=True``
stores filters as per-channel int8. Anything else raises with the op
name — the honest bounded-op-subset contract.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt
from .ops.windows import same_pool_counts
from .program import Program, TensorSpec, analyze_program
from .shape import Shape, Unknown
from .utils import get_logger

logger = get_logger(__name__)


class UnresolvedVariableError(ValueError):
    """A reachable VarHandleOp has no bound value (the checkpoint bundle
    restored fine but the graph references a variable absent from it).
    ``load_saved_model`` falls back to TensorFlow freezing on exactly
    this failure; other lowering ``ValueError``s are genuine import
    errors and stay chained into any final failure (ADVICE r4)."""

# ---------------------------------------------------------------------------
# protobuf wire-format primitives (clean-room; spec: protobuf.dev/encoding)
# ---------------------------------------------------------------------------


class _WireError(ValueError):
    """Byte-level decoding failure (malformed wire format) — distinct
    from semantic ValueErrors (unsupported dtype, string Const, …) so
    :func:`parse_graphdef` can re-label only true corruption."""


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise _WireError("malformed varint")


def _signed(v: int) -> int:
    """Interpret a decoded varint as two's-complement int64 (TF dim sizes
    encode -1 this way, not zigzag)."""
    return v - (1 << 64) if v >= 1 << 63 else v


def _iter_fields(data: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes.
    LEN fields yield their raw bytes; varints yield ints; fixed32/64 yield
    raw 4/8 bytes. Unknown fields pass through for callers to skip."""
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 0x7
        if wire == 0:
            v, pos = _read_varint(data, pos)
            yield field, wire, v
        elif wire == 1:
            yield field, wire, data[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(data, pos)
            yield field, wire, data[pos:pos + ln]
            pos += ln
        elif wire == 5:
            yield field, wire, data[pos:pos + 4]
            pos += 4
        else:
            raise _WireError(f"unsupported wire type {wire}")


# ---------------------------------------------------------------------------
# TF proto subset: TensorShapeProto / TensorProto / AttrValue / NodeDef
# ---------------------------------------------------------------------------

# tensorflow/core/framework/types.proto DataType enum → dtype registry
# (bfloat16 may be absent when ml_dtypes is unavailable — skip None)
_TF_DTYPES = {
    k: v
    for k, v in {
        1: dt.float32,
        2: dt.float64,
        3: dt.int32,
        4: dt.uint8,
        6: dt.int8,
        7: dt.string,
        9: dt.int64,
        10: dt.bool_,
        14: dt.bfloat16,
        19: dt.float16,
    }.items()
    if v is not None
}


def _parse_shape(data: bytes) -> Optional[List[int]]:
    """TensorShapeProto: dims (field 2, Dim.size field 1, -1 = unknown);
    unknown_rank (field 3). Returns None for unknown rank."""
    dims: List[int] = []
    unknown_rank = False
    for field, _, v in _iter_fields(data):
        if field == 2:
            size = 0
            for f2, _, v2 in _iter_fields(v):
                if f2 == 1:
                    size = _signed(v2)
            dims.append(size)
        elif field == 3 and v:
            unknown_rank = True
    return None if unknown_rank else dims


class _StringTensor:
    """A parsed DT_STRING TensorProto: inert unless consumed. Dead
    string Consts (SavedModel saver cruft) must not break the import of
    an otherwise-numeric graph."""

    __slots__ = ("values",)

    def __init__(self, values):
        self.values = values

    def __repr__(self):
        return f"_StringTensor({len(self.values)} values)"


def _parse_tensor(data: bytes) -> np.ndarray:
    """TensorProto → numpy. Handles tensor_content (field 4) and the typed
    ``*_val`` repeated fields (packed or not); a single value fills the
    whole declared shape (TF's scalar-broadcast convention)."""
    dtype = dt.float32
    shape: List[int] = []
    content = b""
    vals: List = []
    for field, wire, v in _iter_fields(data):
        if field == 1:
            dtype = _TF_DTYPES.get(v)
            if dtype is None:
                raise ValueError(f"TensorProto: unsupported dtype enum {v}")
        elif field == 2:
            shape = _parse_shape(v) or []
        elif field == 4:
            content = v
        elif field == 5:  # float_val
            if wire == 5:
                vals.append(struct.unpack("<f", v)[0])
            else:
                vals.extend(
                    struct.unpack(f"<{len(v) // 4}f", v)
                )
        elif field == 6:  # double_val
            if wire == 1:
                vals.append(struct.unpack("<d", v)[0])
            else:
                vals.extend(struct.unpack(f"<{len(v) // 8}d", v))
        elif field in (7, 10):  # int_val / int64_val
            if wire == 0:
                vals.append(_signed(v))
            else:
                pos = 0
                while pos < len(v):
                    x, pos = _read_varint(v, pos)
                    vals.append(_signed(x))
        elif field == 11:  # bool_val
            if wire == 0:
                vals.append(bool(v))
            else:
                pos = 0
                while pos < len(v):
                    x, pos = _read_varint(v, pos)
                    vals.append(bool(x))
        elif field == 13:  # half_val: fp16/bf16 bit patterns as int32s
            raw: List[int] = []
            if wire == 0:
                raw.append(v)
            else:
                pos = 0
                while pos < len(v):
                    x, pos = _read_varint(v, pos)
                    raw.append(x)
            vals.extend(("half_bits", x) for x in raw)
        elif field == 8:  # string_val — host-only; see _StringTensor
            vals.append(("string_val", v))
    if dtype is dt.string or any(
        isinstance(x, tuple) and x and x[0] == "string_val" for x in vals
    ):
        # String Consts PARSE (SavedModel graphs carry dead saver/config
        # strings) but are rejected the moment a device program actually
        # CONSUMES one (strings are host-only; ≙ datatypes.scala:577-581)
        return _StringTensor(
            [x[1] for x in vals if isinstance(x, tuple)
             and x and x[0] == "string_val"]
        )
    np_dtype = dtype.np_dtype
    size = int(np.prod(shape)) if shape else 1
    if content:
        arr = np.frombuffer(content, dtype=np_dtype.newbyteorder("<"))
        arr = arr.astype(np_dtype)
    elif vals:
        if vals and isinstance(vals[0], tuple):  # half_val bit patterns
            bits = np.asarray([x for _, x in vals], dtype=np.uint16)
            arr = bits.view(np_dtype)
        else:
            arr = np.asarray(vals, dtype=np_dtype)
        if arr.size == 1 and size > 1:
            arr = np.full(size, arr.reshape(())[()], dtype=np_dtype)
        elif 1 < arr.size < size:
            # TF's partial-fill convention: remaining elements repeat the
            # LAST listed value
            arr = np.concatenate(
                [arr, np.full(size - arr.size, arr.flat[-1], dtype=np_dtype)]
            )
    else:
        arr = np.zeros(size, dtype=np_dtype)
    return arr.reshape(shape)


class _Attr:
    """One decoded AttrValue (attr_value.proto): whichever oneof member
    was present. ``ints``/``floats``/``bools`` carry ListValue members
    (Conv2D strides, pool ksize, Squeeze dims, …)."""

    __slots__ = ("s", "i", "f", "b", "type", "shape", "tensor",
                 "ints", "floats", "bools", "func")

    def __init__(self):
        self.s = self.i = self.f = self.b = None
        self.type = self.shape = self.tensor = None
        self.ints = self.floats = self.bools = None
        self.func = None  # NameAttrList name (PartitionedCall's 'f')


def _parse_list_value(a: _Attr, data: bytes) -> None:
    """AttrValue.ListValue: repeated i (field 3) / f (4) / b (5), packed
    per proto3 (attr_value.proto declares [packed = true]); handle the
    unpacked encoding too."""
    ints: List[int] = []
    floats: List[float] = []
    bools: List[bool] = []
    for field, wire, v in _iter_fields(data):
        if field == 3:
            if wire == 0:
                ints.append(_signed(v))
            else:
                pos = 0
                while pos < len(v):
                    x, pos = _read_varint(v, pos)
                    ints.append(_signed(x))
        elif field == 4:
            if wire == 5:
                floats.append(struct.unpack("<f", v)[0])
            else:
                floats.extend(struct.unpack(f"<{len(v) // 4}f", v))
        elif field == 5:
            if wire == 0:
                bools.append(bool(v))
            else:
                bools.extend(bool(b) for b in v)
    if ints:
        a.ints = ints
    if floats:
        a.floats = floats
    if bools:
        a.bools = bools


def _parse_attr(data: bytes) -> _Attr:
    a = _Attr()
    for field, _, v in _iter_fields(data):
        if field == 1:
            _parse_list_value(a, v)
        elif field == 2:
            a.s = v
        elif field == 3:
            a.i = _signed(v)
        elif field == 4:
            a.f = struct.unpack("<f", v)[0]
        elif field == 5:
            a.b = bool(v)
        elif field == 6:
            a.type = v
        elif field == 7:
            a.shape = _parse_shape(v)
        elif field == 8:
            a.tensor = _parse_tensor(v)
        elif field == 10:  # func: NameAttrList (field 1 = name)
            for f2, _, v2 in _iter_fields(v):
                if f2 == 1:
                    a.func = v2.decode("utf-8")
    return a


class GraphNode:
    """One decoded NodeDef (node_def.proto)."""

    __slots__ = ("name", "op", "inputs", "attrs")

    def __init__(self, name: str, op: str, inputs: List[str], attrs: Dict[str, _Attr]):
        self.name = name
        self.op = op
        self.inputs = inputs
        self.attrs = attrs

    def __repr__(self):
        return f"GraphNode({self.name!r}, op={self.op!r}, inputs={self.inputs})"


class FunctionDef:
    """One decoded library function (function.proto): signature arg
    names, body nodes (same :class:`GraphNode` records as the main
    graph), and the ``ret`` map from output-arg name to a body ref in
    the function convention (``node:port:index``)."""

    __slots__ = ("name", "input_args", "output_args", "nodes", "ret")

    def __init__(self, name, input_args, output_args, nodes, ret):
        self.name = name
        self.input_args = input_args
        self.output_args = output_args
        self.nodes = nodes
        self.ret = ret


class GraphNodes(list):
    """The parsed main-graph nodes, plus the function library (name →
    :class:`FunctionDef`) for graphs that keep ``PartitionedCall``
    wrappers (un-frozen ``tf.function`` exports)."""

    def __init__(self, nodes, library=None):
        super().__init__(nodes)
        self.library: Dict[str, FunctionDef] = library or {}


def parse_graphdef(data: bytes) -> "GraphNodes":
    """Decode a serialized ``GraphDef`` (graph.proto: field 1 = repeated
    NodeDef, field 2 = FunctionDefLibrary) into :class:`GraphNode`
    records plus the function library (``.library`` on the returned
    list — PartitionedCall bodies). Unknown fields are skipped — version
    stamps and device placements don't affect the inference subset.
    Malformed bytes raise ``ValueError`` ("not a valid GraphDef"), never
    a bare index/struct error."""
    try:
        return _parse_graphdef_inner(data)
    except (IndexError, struct.error, UnicodeDecodeError, _WireError) as e:
        # only true wire-level corruption re-labels; semantic errors
        # (unsupported dtype enum, string Const) keep their own message
        raise ValueError(
            f"not a valid serialized GraphDef ({type(e).__name__} while "
            f"decoding: {e})"
        ) from e


def _parse_node_def(v: bytes) -> GraphNode:
    name = op = ""
    inputs: List[str] = []
    attrs: Dict[str, _Attr] = {}
    for f2, _, v2 in _iter_fields(v):
        if f2 == 1:
            name = v2.decode("utf-8")
        elif f2 == 2:
            op = v2.decode("utf-8")
        elif f2 == 3:
            inputs.append(v2.decode("utf-8"))
        elif f2 == 5:
            k = av = None
            for f3, _, v3 in _iter_fields(v2):
                if f3 == 1:
                    k = v3.decode("utf-8")
                elif f3 == 2:
                    av = _parse_attr(v3)
            if k is not None and av is not None:
                attrs[k] = av
    return GraphNode(name, op, inputs, attrs)


def _parse_function_def(data: bytes) -> FunctionDef:
    """function.proto FunctionDef: field 1 = OpDef signature (name=1,
    input_arg=2, output_arg=3; ArgDef name=1), field 3 = repeated
    NodeDef, field 4 = ret map (key=1, value=2)."""
    name = ""
    input_args: List[str] = []
    output_args: List[str] = []
    nodes: List[GraphNode] = []
    ret: Dict[str, str] = {}
    for field, _, v in _iter_fields(data):
        if field == 1:  # OpDef
            for f2, _, v2 in _iter_fields(v):
                if f2 == 1:
                    name = v2.decode("utf-8")
                elif f2 in (2, 3):  # ArgDef
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            (input_args if f2 == 2 else output_args).append(
                                v3.decode("utf-8")
                            )
        elif field == 3:
            nodes.append(_parse_node_def(v))
        elif field == 4:  # map<string, string> entry
            k = val = None
            for f2, _, v2 in _iter_fields(v):
                if f2 == 1:
                    k = v2.decode("utf-8")
                elif f2 == 2:
                    val = v2.decode("utf-8")
            if k is not None and val is not None:
                ret[k] = val
    return FunctionDef(name, input_args, output_args, nodes, ret)


def _parse_graphdef_inner(data: bytes) -> "GraphNodes":
    nodes: List[GraphNode] = []
    library: Dict[str, FunctionDef] = {}
    for field, _, v in _iter_fields(data):
        if field == 1:
            nodes.append(_parse_node_def(v))
        elif field == 2:  # FunctionDefLibrary: field 1 = FunctionDef
            for f2, _, v2 in _iter_fields(v):
                if f2 == 1:
                    fd = _parse_function_def(v2)
                    library[fd.name] = fd
    return GraphNodes(nodes, library)


# ---------------------------------------------------------------------------
# lowering: GraphNode list → Program
# ---------------------------------------------------------------------------

def _axes(idx_arr: np.ndarray) -> Tuple[int, ...]:
    return tuple(int(i) for i in np.atleast_1d(np.asarray(idx_arr)))


# elementwise / binary ops: name → lambda over jnp arrays
_BINARY = {
    "Add": jnp.add,
    "AddV2": jnp.add,
    "Sub": jnp.subtract,
    "Mul": jnp.multiply,
    "Div": jnp.divide,
    "RealDiv": jnp.divide,
    "Maximum": jnp.maximum,
    "Minimum": jnp.minimum,
    "FloorDiv": jnp.floor_divide,
    "FloorMod": jnp.mod,
    "Pow": jnp.power,
    "SquaredDifference": lambda a, b: jnp.square(a - b),
    "Greater": jnp.greater,
    "GreaterEqual": jnp.greater_equal,
    "Less": jnp.less,
    "LessEqual": jnp.less_equal,
    "Equal": jnp.equal,
    "NotEqual": jnp.not_equal,
    "LogicalAnd": jnp.logical_and,
    "LogicalOr": jnp.logical_or,
    "Atan2": jnp.arctan2,
    # the 0-input short-circuits TF defines: Xdivy/Xlogy return 0 where
    # x==0 (whatever y), DivNoNan returns 0 where y==0
    "Xdivy": lambda x, y: jnp.where(
        x == 0, jnp.zeros_like(jnp.divide(x, y)), jnp.divide(x, y)
    ),
    "Xlogy": lambda x, y: jnp.where(
        x == 0,
        jnp.zeros_like(jnp.multiply(x, jnp.log(y))),
        jnp.multiply(x, jnp.log(y)),
    ),
    "DivNoNan": lambda x, y: jnp.where(
        y == 0, jnp.zeros_like(jnp.divide(x, y)), jnp.divide(x, y)
    ),
    # TF's Mod is C-style TRUNCATED modulo (sign of the dividend);
    # jnp.mod is floor-modulo — lax.rem / np.fmod have the right
    # semantics
    "Mod": jax.lax.rem,
    "TruncateDiv": lambda a, b: jnp.trunc(a / b).astype(a.dtype)
    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
    else jax.lax.div(a, b),
}
_UNARY = {
    "Identity": lambda x: x,
    # a VarHandleOp resolves to the variable's VALUE at import (clean-room
    # bundle restore, bundle.py), so the read is an identity
    "ReadVariableOp": lambda x: x,
    # graph-plumbing no-ops under pure inference
    "Snapshot": lambda x: x,
    "PreventGradient": lambda x: x,
    "CheckNumerics": lambda x: x,
    "LogSoftmax": jax.nn.log_softmax,
    "L2Loss": lambda x: jnp.sum(jnp.square(x)) / 2,
    "Neg": jnp.negative,
    "Square": jnp.square,
    "Abs": jnp.abs,
    "Relu": lambda x: jnp.maximum(x, 0),
    "Relu6": lambda x: jnp.clip(x, 0, 6),
    "Exp": jnp.exp,
    "Log": jnp.log,
    "Sqrt": jnp.sqrt,
    "Rsqrt": lambda x: 1.0 / jnp.sqrt(x),
    "Tanh": jnp.tanh,
    "Sigmoid": lambda x: 1.0 / (1.0 + jnp.exp(-x)),
    "Softmax": lambda x: jnp.exp(x - x.max(-1, keepdims=True))
    / jnp.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True),
    "Erf": lambda x: jax.lax.erf(x),
    "Erfc": lambda x: jax.lax.erfc(x),  # keras gelu lowers through erfc
    "Floor": jnp.floor,
    "Ceil": jnp.ceil,
    "Round": jnp.round,
    "LogicalNot": jnp.logical_not,
    "StopGradient": lambda x: x,  # inference import: gradient-free
    "Elu": jax.nn.elu,
    "Selu": jax.nn.selu,
    "Softplus": jax.nn.softplus,
    "Softsign": jax.nn.soft_sign,
    "Sin": jnp.sin,
    "Cos": jnp.cos,
    "Tan": jnp.tan,
    "Atan": jnp.arctan,
    "Asin": jnp.arcsin,
    "Acos": jnp.arccos,
    "Sinh": jnp.sinh,
    "Cosh": jnp.cosh,
    "Asinh": jnp.arcsinh,
    "Acosh": jnp.arccosh,
    "Atanh": jnp.arctanh,
    "Log1p": jnp.log1p,
    "Expm1": jnp.expm1,
    "Reciprocal": lambda x: 1.0 / x,
    "Sign": jnp.sign,
    "IsNan": jnp.isnan,
    "IsInf": jnp.isinf,
    "IsFinite": jnp.isfinite,
}
# reducers: name → jnp reduction
_REDUCERS = {
    "Sum": jnp.sum,
    "Min": jnp.min,
    "Max": jnp.max,
    "Mean": jnp.mean,
    "Prod": jnp.prod,
    "All": jnp.all,
    "Any": jnp.any,
}

# numpy twins for the shape-arithmetic subgraphs (Shape → Pack → Tile …):
# when EVERY operand of one of these ops is trace-time concrete (a numpy
# value — Const, Shape output, or arithmetic thereof), evaluate in numpy
# so concreteness propagates. That is what makes the reference's TF1
# dynamic-shape idiom (`tile(x, pack([tf.shape(p)[0], 1]))`,
# tensorframes_snippets/kmeans.py:28-45) executable under XLA's static
# shapes: `tf.shape` of a traced array is static at trace time, so the
# whole multiples chain folds to host integers before jnp.tile sees it.
_BINARY_NP = {
    "Atan2": np.arctan2,
    "Mod": np.fmod,  # truncated, like lax.rem
    "TruncateDiv": lambda a, b: np.trunc(np.true_divide(a, b)).astype(
        np.asarray(a).dtype
    )
    if np.issubdtype(np.asarray(a).dtype, np.floating)
    else (np.sign(a) * np.sign(b) * (np.abs(a) // np.abs(b))).astype(
        np.asarray(a).dtype
    ),
    "SquaredDifference": lambda a, b: np.square(a - b),
    "Greater": np.greater,
    "GreaterEqual": np.greater_equal,
    "Less": np.less,
    "LessEqual": np.less_equal,
    "Equal": np.equal,
    "NotEqual": np.not_equal,
    "LogicalAnd": np.logical_and,
    "LogicalOr": np.logical_or,
    "Add": np.add,
    "AddV2": np.add,
    "Sub": np.subtract,
    "Mul": np.multiply,
    "Div": np.true_divide,
    "RealDiv": np.true_divide,
    "Maximum": np.maximum,
    "Minimum": np.minimum,
    "FloorDiv": np.floor_divide,
    "FloorMod": np.mod,
    "Pow": np.power,
}
_UNARY_NP = {
    "Identity": lambda x: x,
    "ReadVariableOp": lambda x: x,
    "Neg": np.negative,
    "Square": np.square,
    "Abs": np.abs,
}


def _is_concrete(*vs) -> bool:
    """True when every value is host-resident (numpy / python scalar) —
    i.e. known at trace time, usable for shapes, axes, and multiples."""
    return all(
        isinstance(v, (np.ndarray, np.generic, int, float, bool)) for v in vs
    )


def _concrete_operand(n: "GraphNode", what: str, v) -> np.ndarray:
    if not _is_concrete(v):
        raise ValueError(
            f"{n.op} node {n.name!r}: {what} must be trace-time constant "
            "(a Const, or derived from Shape of a placeholder); got a "
            "traced value"
        )
    return np.asarray(v)


# ops whose evaluation yields a TUPLE of outputs; data refs ``name:k``
# select the k-th element (everything else is single-output)
_MULTI_OUTPUT = (
    "Split", "SplitV", "Unpack", "TopKV2", "IdentityN",
    "PartitionedCall", "StatefulPartitionedCall",
)


def _num_outputs(node, library=None) -> int:
    """Static output arity of a multi-output node (from its attrs —
    or, for function calls, the library signature), so out-of-range
    ``:k`` refs fail at IMPORT time, not first call."""
    if node.op in ("Split", "SplitV"):
        return int(node.attrs["num_split"].i)
    if node.op == "Unpack":
        return int(node.attrs["num"].i)
    if node.op == "TopKV2":
        return 2
    if node.op == "IdentityN":
        return len([r for r in node.inputs if not r.startswith("^")])
    if node.op in ("PartitionedCall", "StatefulPartitionedCall"):
        f = node.attrs.get("f")
        fd = (library or {}).get(f.func if f else None)
        return len(fd.output_args) if fd else 1
    return 1


# list-output ports: the numeric index in a function-body ref
# ``node:port:idx`` selects directly into the tuple; named scalar ports
# map by name
_PORT_MAPS = {"TopKV2": {"values": 0, "indices": 1}}


def _resolve_fn_ref(ref: str, value, op: str):
    """Resolve a FunctionDef-convention data ref (``node:port:index``)
    against an evaluated body-node value."""
    if not isinstance(value, tuple):
        return value
    parts = ref.split(":")
    port = parts[1] if len(parts) >= 2 else ""
    idx = int(parts[2]) if len(parts) >= 3 and parts[2].isdigit() else 0
    pm = _PORT_MAPS.get(op)
    if pm is not None:
        if port not in pm:
            raise ValueError(
                f"function ref {ref!r}: unknown output port {port!r} of "
                f"{op}"
            )
        idx = pm[port]
    if idx >= len(value):
        raise ValueError(
            f"function ref {ref!r} selects output {idx} but the node has "
            f"{len(value)} outputs"
        )
    return value[idx]


def _eval_function(fdef, call_args, library, compute_dtype):
    """Evaluate one library function body (PartitionedCall target):
    bind ``call_args`` to the signature's input args, run the body nodes
    with the same work-stack discipline as the main graph (refs use the
    FunctionDef ``node:port:index`` convention), and return the outputs
    in ``output_args`` order via the ``ret`` map. Nested calls recurse —
    call DEPTH is bounded by the program's nesting, unlike the node-chain
    depth the iterative main evaluator protects against."""
    env = dict(zip(fdef.input_args, call_args))
    by_name = {n.name: n for n in fdef.nodes}
    values: Dict[str, object] = {}

    def resolve(ref):
        if ref.startswith("^"):
            return None
        base = ref.split(":")[0]
        if base in env and base not in by_name:
            return env[base]
        return _resolve_fn_ref(ref, values[base], by_name[base].op)

    def materialize(target: str):
        # NOTE: mirrors the main evaluator's DFS work stack in
        # program_from_graphdef.fn (same push/expanded cycle discipline,
        # Const/NoOp cases) with the FUNCTION ref convention — a change
        # to either traversal must be applied to both
        stack = [target]
        expanded = set()
        while stack:
            nm = stack[-1]
            if nm in values or (nm in env and nm not in by_name):
                stack.pop()
                continue
            node = by_name.get(nm)
            if node is None:
                raise ValueError(
                    f"function {fdef.name!r}: ref to unknown node {nm!r}"
                )
            if node.op == "Const":
                values[nm] = node.attrs["value"].tensor
            elif node.op == "NoOp":
                values[nm] = None
            else:
                refs = [r for r in node.inputs if not r.startswith("^")]
                deps = [
                    r.split(":")[0] for r in refs
                    if not (r.split(":")[0] in env
                            and r.split(":")[0] not in by_name)
                ]
                pending = [d for d in deps if d not in values]
                if pending:
                    if nm in expanded:
                        raise ValueError(
                            f"function {fdef.name!r} contains a cycle "
                            f"through {nm!r}"
                        )
                    expanded.add(nm)
                    stack.extend(pending)
                    continue
                if node.op in ("PartitionedCall", "StatefulPartitionedCall"):
                    values[nm] = _eval_call(
                        node, [resolve(r) for r in refs], library,
                        compute_dtype,
                    )
                else:
                    values[nm] = _eval_node(
                        node, [resolve(r) for r in refs],
                        compute_dtype=compute_dtype,
                    )
            stack.pop()
        return None

    outs = []
    for out_name in fdef.output_args:
        ref = fdef.ret.get(out_name)
        if ref is None:
            raise ValueError(
                f"function {fdef.name!r}: output {out_name!r} missing "
                "from the ret map"
            )
        base = ref.split(":")[0]
        if not (base in env and base not in by_name):
            materialize(base)
        outs.append(resolve(ref))
    return outs[0] if len(outs) == 1 else tuple(outs)


def _eval_call(node, args, library, compute_dtype):
    """Dispatch a PartitionedCall/StatefulPartitionedCall node to its
    library function."""
    f = node.attrs.get("f")
    fd = library.get(f.func) if f and f.func else None
    if fd is None:
        raise ValueError(
            f"call node {node.name!r}: function "
            f"{(f.func if f else None)!r} not in the graph library"
        )
    if len(args) != len(fd.input_args):
        raise ValueError(
            f"call node {node.name!r}: {len(args)} args for function "
            f"{fd.name!r} expecting {len(fd.input_args)}"
        )
    return _eval_function(fd, args, library, compute_dtype)


def _select_output(v, ref: str):
    """Resolve a data ref against an evaluated node value: multi-output
    tuples select by the ref's ``:k`` suffix (default 0)."""
    if isinstance(v, tuple):
        idx = 0
        if ":" in ref:
            suffix = ref.rsplit(":", 1)[1]
            if suffix.isdigit():
                idx = int(suffix)
        if idx >= len(v):
            raise ValueError(
                f"ref {ref!r} selects output {idx} but the node has "
                f"{len(v)} outputs"
            )
        return v[idx]
    return v


def _base(ref: str) -> str:
    """Strip the ':output-index' suffix and control '^' prefix from a
    NodeDef input reference."""
    ref = ref[1:] if ref.startswith("^") else ref
    return ref.split(":")[0]


def _nhwc(n: "GraphNode") -> None:
    fmt = n.attrs.get("data_format")
    if fmt is not None and fmt.s not in (None, b"NHWC"):
        raise ValueError(
            f"{n.op} node {n.name!r}: only NHWC data_format is supported "
            f"(got {fmt.s!r}) — TPU-native layouts are NHWC"
        )


def _pad_str(n: "GraphNode") -> str:
    p = n.attrs.get("padding")
    pad = (p.s or b"VALID").decode() if p else "VALID"
    if pad not in ("SAME", "VALID"):
        raise ValueError(
            f"{n.op} node {n.name!r}: padding {pad!r} unsupported "
            "(SAME/VALID only)"
        )
    return pad


def _conv2d(n: "GraphNode", x, w, preferred=None):
    """Conv2D (NHWC, HWIO weights — TF's native layouts, which are also
    the TPU-friendly ones). ``preferred`` sets the accumulation dtype
    (f32 under a reduced-precision compute policy); None keeps the
    operands' own dtype — f64/bf16 graphs stay faithful."""
    _nhwc(n)
    strides = (n.attrs["strides"].ints or [1, 1, 1, 1])[1:3]
    dil = n.attrs.get("dilations")
    rhs_dilation = tuple((dil.ints or [1, 1, 1, 1])[1:3]) if dil else (1, 1)
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(strides),
        padding=_pad_str(n),
        rhs_dilation=rhs_dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=preferred,
    )


def _depthwise_conv2d(n: "GraphNode", x, w, preferred=None):
    """DepthwiseConv2dNative: [H,W,C,M] filter → grouped conv with
    feature_group_count=C and an [H,W,1,C*M] kernel."""
    _nhwc(n)
    strides = (n.attrs["strides"].ints or [1, 1, 1, 1])[1:3]
    dil = n.attrs.get("dilations")
    rhs_dilation = tuple((dil.ints or [1, 1, 1, 1])[1:3]) if dil else (1, 1)
    h, wd, c, m = w.shape
    return jax.lax.conv_general_dilated(
        x,
        w.reshape(h, wd, 1, c * m),
        window_strides=tuple(strides),
        padding=_pad_str(n),
        rhs_dilation=rhs_dilation,
        feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=preferred,
    )


def _strided_slice(n: "GraphNode", x, begin, end, strides):
    """StridedSlice with concrete begin/end/strides, honoring the five
    bit masks. Covers the dominant real-graph shape idiom
    ``tf.shape(x)[0]`` (begin=[0], end=[1], shrink_axis_mask=1) and
    general python-slicing-expressible forms."""
    begin = _concrete_operand(n, "begin", begin).tolist()
    end = _concrete_operand(n, "end", end).tolist()
    strides = _concrete_operand(n, "strides", strides).tolist()

    def mask(key: str) -> int:
        a = n.attrs.get(key)
        return int(a.i) if a and a.i is not None else 0

    bm, em = mask("begin_mask"), mask("end_mask")
    elm, nam, sam = (
        mask("ellipsis_mask"), mask("new_axis_mask"), mask("shrink_axis_mask")
    )
    idx: list = []
    for i in range(len(begin)):
        if (elm >> i) & 1:
            idx.append(Ellipsis)
        elif (nam >> i) & 1:
            idx.append(None)  # np.newaxis
        elif (sam >> i) & 1:
            idx.append(int(begin[i]))
        else:
            b = None if (bm >> i) & 1 else int(begin[i])
            e = None if (em >> i) & 1 else int(end[i])
            idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


def _pool(n: "GraphNode", x):
    """MaxPool / AvgPool over NHWC. AvgPool with SAME padding divides by
    the true (edge-clipped) window population, matching TF."""
    _nhwc(n)
    ksize = tuple(n.attrs["ksize"].ints)
    strides = tuple(n.attrs["strides"].ints)
    pad = _pad_str(n)
    if n.op == "MaxPool":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else (
            jnp.iinfo(x.dtype).min
        )
        return jax.lax.reduce_window(
            x, init, jax.lax.max, ksize, strides, pad
        )
    # accumulate at >= f32 precision without truncating f64 graphs
    acc = jnp.promote_types(x.dtype, jnp.float32)
    s = jax.lax.reduce_window(
        x.astype(acc), 0.0, jax.lax.add, ksize, strides, pad
    )
    if pad == "VALID":
        cnt = float(np.prod(ksize))
    else:
        # trace-time numpy window counts: reduce_window over a constant
        # would make XLA constant-fold a full-size pool per shape (the
        # inception-stem slow_operation_alarm stalls; ops/windows.py)
        cnt = same_pool_counts(
            int(x.shape[1]), int(x.shape[2]),
            ksize[1], ksize[2], strides[1], strides[2],
        )
    return (s / cnt).astype(x.dtype)


def _resolve_compute_dtype(compute_dtype):
    """Resolve the ``"auto"`` serving-precision default: bfloat16 on
    accelerator backends (the idiomatic TPU inference mode — the r3 TPU
    run showed the f32-only import path trailing the native bf16 model
    ~5×), f32-faithful (``None``) on CPU, where golden tests compare
    bit-for-bit against TF running the same bytes. Pass ``None``
    explicitly for f32-faithful serving on any backend."""
    if compute_dtype != "auto":
        return compute_dtype
    import jax

    resolved = "bfloat16" if jax.default_backend() != "cpu" else None
    if resolved == "bfloat16":
        # precision drift must be traceable: "auto" silently changing
        # imported-graph numerics vs TF is worth one log line per
        # process (ADVICE r4)
        global _auto_bf16_logged
        if not _auto_bf16_logged:
            _auto_bf16_logged = True
            logger.info(
                "compute_dtype='auto' resolved to bfloat16 on the %s "
                "backend: imported MatMul/Conv ops serve in bf16 with "
                "f32 accumulation and will not bit-match TF; pass "
                "compute_dtype=None for f32-faithful serving",
                jax.default_backend(),
            )
    return resolved


_auto_bf16_logged = False


def program_from_graphdef(
    nodes: Sequence[GraphNode],
    fetches: Optional[Sequence[str]] = None,
    relax_lead_dim: bool = False,
    quantize_weights: bool = False,
    compute_dtype: Optional[str] = "auto",
    variables: Optional[Dict[str, np.ndarray]] = None,
) -> Program:
    """Lower decoded GraphDef nodes to a :class:`Program`.

    ``fetches`` defaults to the graph's sinks (non-Placeholder nodes no
    other node consumes — the reference instead required explicit fetches
    via ShapeDescription). ``relax_lead_dim=True`` widens each
    placeholder's leading dim to Unknown so fixed-shape frozen graphs run
    over arbitrary block row counts (≙ extractPlaceholder's block-shape
    widening, dsl/DslImpl.scala:90-107). ``quantize_weights=True``
    stores float Const filters feeding Conv2D/depthwise/MatMul as
    symmetric per-channel int8 (ops/quantize.py — 4× less weight HBM
    traffic; XLA fuses the dequantize into the consuming conv/matmul).

    ``compute_dtype`` (e.g. ``"bfloat16"``) is a serving-precision
    policy for the MXU ops only: MatMul/Conv2D/depthwise contract in
    that dtype with float32 accumulation (``preferred_element_type``),
    all other ops stay exact. The default ``"auto"`` serves bfloat16 on
    accelerator backends and f32-faithful on CPU; pass ``None`` for
    f32-faithful everywhere (:func:`_resolve_compute_dtype`).

    ``variables`` binds VarHandleOp nodes to concrete values (keyed by
    the op's ``shared_name``, falling back to the node name): the handle
    evaluates to the value and ``ReadVariableOp`` is an identity —
    un-frozen variable-bearing graphs run as pure programs.
    ``load_saved_model`` fills this from the checkpoint bundle
    (clean-room, ``bundle.py``) so no TensorFlow is needed even at
    conversion time.
    """
    compute_dtype = _resolve_compute_dtype(compute_dtype)
    by_name = {n.name: n for n in nodes}
    library = getattr(nodes, "library", {}) or {}
    consumed = set()
    for n in nodes:
        for ref in n.inputs:
            consumed.add(_base(ref))
    if fetches is None:
        fetches = [
            n.name
            for n in nodes
            if n.name not in consumed and n.op not in ("Placeholder", "NoOp")
        ]
        if not fetches:
            raise ValueError("GraphDef has no sink nodes; pass fetches=")
    missing = [f for f in fetches if _base(f) not in by_name]
    if missing:
        raise ValueError(
            f"fetch(es) {missing} not in graph; nodes: {sorted(by_name)}"
        )
    for f in fetches:
        fnode = by_name[_base(f)]
        if fnode.op == "Const" and isinstance(
            (fnode.attrs.get("value").tensor
             if fnode.attrs.get("value") is not None else None),
            _StringTensor,
        ):
            raise ValueError(
                f"fetch {f!r} is a string Const — string values are not "
                "executable on device (host-only; "
                "≙ datatypes.scala:577-581)"
            )
        # same producer rule as consumer refs: a ':k>0' fetch of a
        # single-output node would silently receive output :0
        if ":" in f:
            suffix = f.rsplit(":", 1)[1]
            if not suffix.isdigit():
                raise ValueError(
                    f"fetch {f!r}: malformed output suffix {suffix!r} "
                    "(expected an integer, e.g. 'split:1')"
                )
            if int(suffix) > 0:
                producer = by_name[_base(f)]
                if producer.op not in _MULTI_OUTPUT:
                    raise ValueError(
                        f"fetch {f!r} selects output {suffix} of "
                        f"single-output op {producer.op!r}; only "
                        f"multi-output ops ({sorted(_MULTI_OUTPUT)}) "
                        "expose outputs past :0"
                    )
                if int(suffix) >= _num_outputs(producer, library):
                    raise ValueError(
                        f"fetch {f!r} selects output {suffix} but "
                        f"{producer.op} node {producer.name!r} has "
                        f"{_num_outputs(producer, library)} outputs"
                    )

    # restrict validation + program inputs to the nodes the evaluator
    # can actually reach from the fetches through DATA refs (the
    # evaluator never follows control deps) — a SavedModel main graph
    # carries a dead saver subgraph (SaveV2/RestoreV2/StringJoin + a
    # string filename Placeholder) that must not poison the import
    reachable = set()
    _stack = [_base(f) for f in fetches]
    while _stack:
        _nm = _stack.pop()
        if _nm in reachable or _nm not in by_name:
            continue
        reachable.add(_nm)
        _stack.extend(
            _base(r) for r in by_name[_nm].inputs if not r.startswith("^")
        )

    # output :k>0 is legal only for registered MULTI-OUTPUT ops; for any
    # other producer (FusedBatchNorm's batch stats, …) it would silently
    # receive output :0 — reject it up front. Only REACHABLE consumers
    # matter: dead saver subgraphs consume :1 outputs of ops the
    # evaluator never touches
    for n in nodes:
        if n.name not in reachable:
            continue
        for ref in n.inputs:
            if not ref.startswith("^") and ":" in ref:
                idx = ref.rsplit(":", 1)[1]
                if idx.isdigit() and int(idx) > 0:
                    producer = by_name.get(_base(ref))
                    if producer is None or producer.op not in _MULTI_OUTPUT:
                        raise ValueError(
                            f"node {n.name!r} consumes output {ref!r}; "
                            "only multi-output ops "
                            f"({sorted(_MULTI_OUTPUT)}) expose outputs "
                            "past :0"
                        )
                    if int(idx) >= _num_outputs(producer, library):
                        raise ValueError(
                            f"node {n.name!r} consumes output {ref!r} but "
                            f"{producer.op} node {producer.name!r} has "
                            f"{_num_outputs(producer, library)} outputs"
                        )

    # placeholders → program inputs (reachable only: a SavedModel's
    # saver filename placeholder must not become a program input)
    inputs: List[TensorSpec] = []
    consts: Dict[str, np.ndarray] = {}
    for n in nodes:
        if n.name not in reachable:
            continue
        if n.op == "Placeholder":
            a = n.attrs.get("dtype")
            dtype = _TF_DTYPES.get(a.type if a else 1, dt.float32)
            sh = n.attrs["shape"].shape if "shape" in n.attrs else None
            if sh is None:
                dims: Tuple = (Unknown,)
            else:
                dims = tuple(Unknown if d < 0 else d for d in sh)
            if relax_lead_dim and dims:
                dims = (Unknown,) + tuple(dims[1:])
            inputs.append(TensorSpec(n.name, dtype, Shape(dims)))
        elif n.op == "Const":
            consts[n.name] = n.attrs["value"].tensor
        elif n.op == "VarHandleOp":
            sn = n.attrs.get("shared_name")
            key = (
                sn.s.decode("utf-8") if sn is not None and sn.s else n.name
            )
            if variables is not None and key in variables:
                consts[n.name] = np.asarray(variables[key])
            elif variables is not None and n.name in variables:
                consts[n.name] = np.asarray(variables[n.name])
            else:
                raise UnresolvedVariableError(
                    f"graph contains variable {key!r} (VarHandleOp node "
                    f"{n.name!r}) with no bound value; pass "
                    "variables={name: array} — load_saved_model restores "
                    "them from the checkpoint bundle automatically "
                    "(tensorframes_tpu.bundle)"
                )

    structural = (
        "Placeholder", "Const", "Cast", "Reshape", "MatMul", "NoOp",
        "VarHandleOp",
        "Conv2D", "DepthwiseConv2dNative", "MaxPool", "AvgPool",
        "BiasAdd", "ConcatV2", "Concat", "Squeeze", "Pad", "PadV2",
        "FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3",
        # dynamic-shape tier (VERDICT r2 #3): the TF1 idioms the
        # reference's own snippet graphs use (kmeans.py:28-45). Shape
        # folds to trace-time constants under XLA's static shapes.
        "Shape", "Pack", "Tile", "ExpandDims", "StridedSlice",
        "Fill", "Range", "ArgMin", "ArgMax",
        # transformer tier (round 3): the op family frozen keras/TF2
        # attention models emit (Embedding gather, einsum attention,
        # layernorm moments, gelu's Erf, masking selects)
        "GatherV2", "Einsum", "Transpose", "Select", "SelectV2",
        "BatchMatMulV2", "BatchMatMul",
        # multi-output tier: evaluate to tuples; consumers select via :k
        "LeakyRelu",
        "Slice", "ZerosLike", "OnesLike", "BroadcastTo", "OneHot",
        "Cumsum", "Cumprod", "Rank", "Size",
        # image-serving tier (round 4): the ops frozen detection /
        # segmentation / preprocessing graphs lean on
        "AddN", "ReverseV2", "GatherNd", "MirrorPad", "MatrixBandPart",
        "DepthToSpace", "SpaceToDepth",
        "ResizeBilinear", "ResizeNearestNeighbor",
        "Split", "SplitV", "Unpack", "TopKV2", "IdentityN",
        # function calls (un-frozen tf.function exports): bodies come
        # from the graph's FunctionDefLibrary and are validated below
        "PartitionedCall", "StatefulPartitionedCall",
    )
    def _walk_function_nodes(seen_fns):
        """Yield every node of every library function reachable from
        the main graph's call nodes (nested calls included) so the
        unsupported-op gate covers function bodies too."""
        pending = []
        for n in nodes:
            if n.name not in reachable:
                continue
            if n.op in ("PartitionedCall", "StatefulPartitionedCall"):
                fattr = n.attrs.get("f")
                if fattr is None or not fattr.func:
                    raise ValueError(
                        f"call node {n.name!r} has no function attr 'f' — "
                        "malformed call structure fails at import, not "
                        "first execution"
                    )
                pending.append(fattr.func)
        while pending:
            fname = pending.pop()
            if fname in seen_fns:
                continue
            seen_fns.add(fname)
            fd = library.get(fname)
            if fd is None:
                raise ValueError(
                    f"call to function {fname!r} but the GraphDef library "
                    f"only defines {sorted(library)}"
                )
            for bn in fd.nodes:
                if bn.op in ("PartitionedCall", "StatefulPartitionedCall"):
                    f2 = bn.attrs.get("f")
                    if f2 is None or not f2.func:
                        raise ValueError(
                            f"call node {bn.name!r} (in function "
                            f"{fname!r}) has no function attr 'f'"
                        )
                    pending.append(f2.func)
                yield bn

    unsupported = sorted(
        {
            n.op
            for n in [x for x in nodes if x.name in reachable]
            + list(_walk_function_nodes(set()))
            if n.op not in structural
            and n.op not in _BINARY
            and n.op not in _UNARY
            and n.op not in _REDUCERS
        }
    )
    if unsupported:
        raise ValueError(
            f"GraphDef contains unsupported op(s) {unsupported}; supported: "
            f"{sorted(structural)}, "
            f"{sorted(_BINARY)}, {sorted(_UNARY)}, {sorted(_REDUCERS)}"
        )

    if library:
        # A (malformed) recursive or mutually-recursive library passes
        # the seen-set dedup walk above but would recurse unboundedly at
        # the first _eval_function call — surface the module's clean
        # ValueError at import time instead of a RecursionError at run
        # time.  DFS with an ACTIVE-CHAIN stack (not just a visited
        # set), rooted at the main graph's call nodes.
        def _called(fd):
            return [
                bn.attrs["f"].func
                for bn in fd.nodes
                if bn.op in ("PartitionedCall", "StatefulPartitionedCall")
                and bn.attrs.get("f") is not None
                and bn.attrs["f"].func
            ]

        roots = [
            n.attrs["f"].func
            for n in nodes
            if n.name in reachable
            and n.op in ("PartitionedCall", "StatefulPartitionedCall")
        ]
        state: Dict[str, int] = {}  # 0 = on the active chain, 1 = done
        for root in roots:
            if state.get(root) == 1:
                continue
            chain = [root]
            stack = [(root, iter(_called(library[root])))]
            state[root] = 0
            while stack:
                fname, it = stack[-1]
                for callee in it:
                    if callee not in library:
                        continue  # missing fns already raised in the walk
                    st = state.get(callee)
                    if st == 0:
                        cycle = chain[chain.index(callee):] + [callee]
                        raise ValueError(
                            "GraphDef function library has a call cycle: "
                            + " -> ".join(cycle)
                            + "; recursive tf.functions cannot lower to "
                            "a static XLA graph"
                        )
                    if st is None:
                        state[callee] = 0
                        chain.append(callee)
                        stack.append((callee, iter(_called(library[callee]))))
                        break
                else:
                    state[fname] = 1
                    stack.pop()
                    chain.pop()

    if quantize_weights:
        if library:
            raise ValueError(
                "quantize_weights=True is not supported for graphs with a "
                "function library (PartitionedCall bodies): the weight "
                "planner only sees main-graph consumers, so quantization "
                "would silently no-op. Freeze/inline the graph first "
                "(convert_variables_to_constants_v2)."
            )
        from .ops.quantize import quantize

        def resolve_const(name: str) -> Optional[str]:
            """Follow Identity chains (the freezer leaves
            ReadVariableOp→Identity wrappers over each folded Const)."""
            seen = set()
            while name in by_name and name not in seen:
                seen.add(name)
                node = by_name[name]
                if node.op != "Identity":
                    break
                refs = [r for r in node.inputs if not r.startswith("^")]
                if not refs:
                    break
                name = _base(refs[0])
            return name if name in consts else None

        # per-consumer channel spec: Conv2D filters [H,W,I,O] keep the
        # output axis; depthwise [H,W,C,M] channels span BOTH trailing
        # axes (one scale per (channel, multiplier) — axis -1 alone
        # would collapse to per-tensor when M==1, the classic MobileNet
        # int8 accuracy failure); MatMul honors transpose_b. Conflicting
        # specs for a shared weight skip quantization.
        weight_plan: Dict[str, object] = {}
        conflicted = set()
        for n in nodes:
            if n.op in ("Conv2D", "DepthwiseConv2dNative", "MatMul"):
                data_refs = [r for r in n.inputs if not r.startswith("^")]
                if len(data_refs) < 2:
                    continue
                wn = resolve_const(_base(data_refs[1]))
                if wn is None:
                    continue
                w = consts[wn]
                if w.ndim < 2 or not np.issubdtype(w.dtype, np.floating):
                    continue
                if n.op == "DepthwiseConv2dNative":
                    spec: object = (2, 3)
                elif n.op == "MatMul":
                    tb = n.attrs.get("transpose_b")
                    spec = 0 if (tb and tb.b) else -1
                else:
                    spec = -1
                if wn in weight_plan and weight_plan[wn] != spec:
                    conflicted.add(wn)
                weight_plan[wn] = spec
        for wn, spec in weight_plan.items():
            if wn not in conflicted:
                consts[wn] = quantize(consts[wn], channel_axis=spec)

    fetch_list = list(fetches)

    def fn(feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        from .ops.quantize import QuantizedTensor

        values: Dict[str, object] = {}

        def materialize(target: str):
            # explicit DFS work stack, not recursion: a frozen graph's
            # longest op chain can exceed Python's ~1000-frame recursion
            # limit (ResNet-152-class sequential models; VERDICT r2 #6)
            stack = [target]
            expanded = set()
            while stack:
                nm = stack[-1]
                if nm in values:
                    stack.pop()
                    continue
                node = by_name.get(nm)
                if node is None:
                    raise ValueError(
                        f"graph references node {nm!r} which does not exist"
                    )
                if node.op == "Placeholder":
                    values[nm] = feeds[nm]
                elif node.op in ("Const", "VarHandleOp"):
                    # raw numpy stays trace-time concrete (shape
                    # arithmetic consumes it on the host); a
                    # QuantizedTensor flows INTACT to its consumer so
                    # MatMul/Conv can contract int8 directly and scale
                    # the output — dequantizing here would materialize a
                    # full f32 weight copy every call. A VarHandleOp's
                    # "handle" IS its restored value (bundle.py), so
                    # downstream ReadVariableOps are identities.
                    values[nm] = consts[nm]
                elif node.op == "NoOp":
                    values[nm] = None  # control-only; never consumed as data
                else:
                    refs = [
                        r for r in node.inputs if not r.startswith("^")
                    ]
                    deps = [_base(r) for r in refs]
                    pending = [d for d in deps if d not in values]
                    if pending:
                        if nm in expanded:
                            # we already pushed nm's deps once; being back
                            # here with deps still missing means a dep
                            # chain loops back through nm
                            raise ValueError(
                                f"GraphDef contains a cycle through {nm!r}"
                            )
                        expanded.add(nm)
                        stack.extend(pending)
                        continue
                    call_args = [
                        _select_output(values[_base(r)], r) for r in refs
                    ]
                    if node.op in (
                        "PartitionedCall", "StatefulPartitionedCall"
                    ):
                        values[nm] = _eval_call(
                            node, call_args, library, compute_dtype
                        )
                    else:
                        values[nm] = _eval_node(
                            node, call_args, compute_dtype=compute_dtype
                        )
                stack.pop()
            return values[target]

        out = {}
        for f in fetch_list:
            v = _select_output(materialize(_base(f)), f)
            if isinstance(v, _StringTensor):
                raise ValueError(
                    f"fetch {f!r} is a string Const — string values are "
                    "not executable on device (host-only; "
                    "≙ datatypes.scala:577-581)"
                )
            if isinstance(v, QuantizedTensor):  # directly-fetched weight
                v = v.dequantize(jnp.float32)
            # shape-arith fetches come back as host numpy; normalize to
            # device arrays (matches the pre-r3 Const behavior incl. the
            # x64-off f64→f32 demotion)
            out[f] = jnp.asarray(v) if _is_concrete(v) else v
        return out

    return Program(fn, inputs, fetch_order=fetch_list)


def _eval_node(n: GraphNode, args: List, compute_dtype: Optional[str] = None):
    """Evaluate one non-structural node given its already-evaluated data
    inputs. Operands that shape the *program* (reduction axes, reshape
    targets, Tile multiples, pad widths, …) must be trace-time concrete —
    satisfied both by Const nodes (≙ build_reducer's const child,
    DslImpl.scala:175-200) and by values derived from ``Shape`` of a
    traced array, which is static under XLA.

    Quantized weights (``QuantizedTensor``) are consumed natively by
    MatMul/Conv2D/DepthwiseConv2dNative — int8 enters the contraction
    and the per-channel scale multiplies the OUTPUT, so no dequantized
    f32 weight is ever materialized; every other consumer dequantizes."""
    from .ops.quantize import QuantizedTensor

    name = n.name
    op = n.op
    for a in args:
        if isinstance(a, _StringTensor):
            raise ValueError(
                f"node {name!r} ({op}) consumes a string Const — string "
                "values are not executable on device (host-only; "
                "≙ datatypes.scala:577-581)"
            )

    def mxu(x):
        """Serving-precision cast for MXU operands: f32 → compute_dtype
        (accumulation stays f32 via preferred_element_type below).

        For CONCRETE operands (weight Consts — numpy at trace time)
        this astype is EAGER, so the jaxpr embeds a bf16 constant and
        constant hoisting passes bf16 weights as runtime arguments —
        half the per-call weight HBM traffic of hoisted-f32-plus-
        convert. Pinned by test_bf16_serving_halves_hoisted_weight_
        bytes; tracers (activations) convert inside the program."""
        if compute_dtype is not None and getattr(x, "dtype", None) == jnp.float32:
            return x.astype(compute_dtype)
        return x

    def pet_for(*ops_):
        """f32 accumulation ONLY when the policy is on AND every
        operand is a <=32-bit float (the ones mxu() may have reduced);
        f64/int contractions keep their exact dtype — preferred_element_
        type must never narrow, and 'all other ops stay exact'."""
        if compute_dtype is None:
            return None
        ok = (jnp.bfloat16, jnp.float16, jnp.float32)
        if all(jnp.asarray(o).dtype in ok for o in ops_):
            return jnp.float32
        return None

    if op == "MatMul":
        a, b = args
        ta = n.attrs.get("transpose_a")
        tb = n.attrs.get("transpose_b")
        if isinstance(a, QuantizedTensor):
            a = a.dequantize(jnp.float32)
        a = mxu(a)
        if ta and ta.b:
            a = a.T
        if isinstance(b, QuantizedTensor):
            q = b.q.T if (tb and tb.b) else b.q
            scale = b.scale.T if (tb and tb.b) else b.scale
            p = pet_for(a)
            out = jax.lax.dot_general(
                a,
                q,
                dimension_numbers=(((a.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=p if p is not None else a.dtype,
            )
            return out * jnp.asarray(scale, out.dtype)
        if tb and tb.b:
            b = b.T
        b = mxu(b)
        p = pet_for(a, b)
        if p is not None:
            return jnp.matmul(a, b, preferred_element_type=p)
        return a @ b
    if op == "Conv2D" and isinstance(args[1], QuantizedTensor):
        x_, w_ = args
        x_ = mxu(x_)
        out = _conv2d(n, x_, w_.q.astype(x_.dtype), preferred=pet_for(x_))
        return out * jnp.asarray(w_.scale.reshape(1, 1, 1, -1), out.dtype)
    if op == "DepthwiseConv2dNative" and isinstance(args[1], QuantizedTensor):
        x_, w_ = args
        x_ = mxu(x_)
        out = _depthwise_conv2d(
            n, x_, w_.q.astype(x_.dtype), preferred=pet_for(x_)
        )
        return out * jnp.asarray(w_.scale.reshape(1, 1, 1, -1), out.dtype)
    args = [
        a.dequantize(jnp.float32) if isinstance(a, QuantizedTensor) else a
        for a in args
    ]
    if op in _BINARY:
        if op in _BINARY_NP and _is_concrete(*args):
            return _BINARY_NP[op](*args)
        return _BINARY[op](*args)
    if op in _UNARY:
        if op in _UNARY_NP and _is_concrete(args[0]):
            return _UNARY_NP[op](args[0])
        return _UNARY[op](args[0])
    if op in _REDUCERS:
        axes = _axes(_concrete_operand(n, "reduction_indices", args[1]))
        keep = n.attrs.get("keep_dims")
        return _REDUCERS[op](
            args[0], axis=axes, keepdims=bool(keep.b) if keep else False
        )
    if op == "Cast":
        to = _TF_DTYPES.get(n.attrs["DstT"].type)
        if to is None:
            raise ValueError(
                f"Cast node {name!r}: unsupported DstT dtype enum "
                f"{n.attrs['DstT'].type}"
            )
        if _is_concrete(args[0]):
            return np.asarray(args[0]).astype(to.np_dtype)
        return args[0].astype(to.np_dtype)
    if op == "Reshape":
        shp = tuple(
            int(d) for d in _concrete_operand(n, "shape", args[1])
        )
        return args[0].reshape(shp)
    if op == "IdentityN":
        return tuple(args)
    if op == "Split":
        # inputs: (split_dim, value); attr num_split
        ax = int(np.asarray(_concrete_operand(n, "split_dim", args[0])))
        num = int(n.attrs["num_split"].i)
        return tuple(jnp.split(args[1], num, axis=ax))
    if op == "SplitV":
        # inputs: (value, size_splits, split_dim); attr num_split
        sizes = [
            int(s) for s in np.asarray(
                _concrete_operand(n, "size_splits", args[1])
            )
        ]
        ax = int(np.asarray(_concrete_operand(n, "split_dim", args[2])))
        if any(s < 0 for s in sizes):  # one -1 infers its size
            total = args[0].shape[ax]
            known = sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else total - known for s in sizes]
        bounds = list(np.cumsum(sizes)[:-1])
        return tuple(jnp.split(args[0], bounds, axis=ax))
    if op == "Unpack":
        ax_attr = n.attrs.get("axis")
        ax = int(ax_attr.i) if ax_attr and ax_attr.i is not None else 0
        num = int(n.attrs["num"].i)
        return tuple(
            jnp.squeeze(s, axis=ax)
            for s in jnp.split(args[0], num, axis=ax)
        )
    if op == "TopKV2":
        kk = int(np.asarray(_concrete_operand(n, "k", args[1])))
        vals_tk, idx_tk = jax.lax.top_k(args[0], kk)
        return (vals_tk, idx_tk.astype(jnp.int32))
    if op == "Slice":
        begin = [int(d) for d in np.asarray(
            _concrete_operand(n, "begin", args[1])
        )]
        size = [int(d) for d in np.asarray(
            _concrete_operand(n, "size", args[2])
        )]
        x_ = args[0]
        lims = []
        for i, (b, s) in enumerate(zip(begin, size)):
            e = b + (s if s >= 0 else x_.shape[i] - b)
            if b < 0 or e > x_.shape[i]:
                raise ValueError(
                    f"Slice node {name!r}: begin+size {b}+{s} out of "
                    f"range for dim {i} of size {x_.shape[i]} (TF "
                    "rejects this; no silent clipping)"
                )
            lims.append(e)
        sl = tuple(slice(b, e) for b, e in zip(begin, lims))
        return x_[sl]
    if op == "ZerosLike":
        if _is_concrete(args[0]):
            return np.zeros_like(args[0])
        return jnp.zeros_like(args[0])
    if op == "OnesLike":
        if _is_concrete(args[0]):
            return np.ones_like(args[0])
        return jnp.ones_like(args[0])
    if op == "BroadcastTo":
        shp = tuple(
            int(d) for d in np.asarray(
                _concrete_operand(n, "shape", args[1])
            )
        )
        if _is_concrete(args[0]):
            return np.broadcast_to(args[0], shp)
        return jnp.broadcast_to(args[0], shp)
    if op == "OneHot":
        depth = int(np.asarray(_concrete_operand(n, "depth", args[1])))
        on_v, off_v = args[2], args[3]
        ax_attr = n.attrs.get("axis")
        ax = int(ax_attr.i) if ax_attr is not None and ax_attr.i is not None else -1
        oh = jax.nn.one_hot(jnp.asarray(args[0]), depth, axis=ax)
        return (oh * on_v + (1 - oh) * off_v).astype(
            jnp.result_type(on_v, off_v)
        )
    if op in ("Cumsum", "Cumprod"):
        ax = int(np.asarray(_concrete_operand(n, "axis", args[1])))
        exclusive = n.attrs.get("exclusive")
        reverse = n.attrs.get("reverse")
        if (exclusive and exclusive.b) or (reverse and reverse.b):
            raise ValueError(
                f"{op} node {name!r}: exclusive/reverse modes unsupported"
            )
        if _is_concrete(args[0]):
            # shape-arithmetic chains (cumprod of a Shape = strides)
            # must stay host-concrete
            fn_np = np.cumsum if op == "Cumsum" else np.cumprod
            return fn_np(np.asarray(args[0]), axis=ax)
        fn_ = jnp.cumsum if op == "Cumsum" else jnp.cumprod
        return fn_(args[0], axis=ax)
    if op == "Rank":
        return np.asarray(np.ndim(args[0]), np.int32)
    if op == "Size":
        ot = n.attrs.get("out_type")
        out_dt_ = _TF_DTYPES.get(ot.type, dt.int32) if ot is not None else dt.int32
        return np.asarray(int(np.prod(np.shape(args[0]))), out_dt_.np_dtype)
    if op == "LeakyRelu":
        al = n.attrs.get("alpha")
        if al is None:
            alpha = 0.2  # attr absent entirely: TF's op-def default
        else:
            # proto3 omits 0.0 from the wire, so a PRESENT attr with no
            # f field means an explicit alpha=0.0, not the default
            alpha = float(al.f) if al.f is not None else 0.0
        return jnp.where(args[0] > 0, args[0], args[0] * alpha)
    if op == "GatherV2":
        params_, indices, axis = args
        bd = n.attrs.get("batch_dims")
        if bd and bd.i:
            raise ValueError(
                f"GatherV2 node {name!r}: batch_dims != 0 is unsupported"
            )
        ax = int(np.asarray(_concrete_operand(n, "axis", axis)))
        if _is_concrete(params_, indices):
            return np.take(params_, np.asarray(indices), axis=ax)
        return jnp.take(params_, jnp.asarray(indices), axis=ax)
    if op == "Einsum":
        eq = n.attrs["equation"].s.decode()
        ops_ = [mxu(a) for a in args]
        p = pet_for(*ops_)
        if p is not None:
            return jnp.einsum(eq, *ops_, preferred_element_type=p)
        return jnp.einsum(eq, *ops_)
    if op == "Transpose":
        perm = tuple(
            int(d) for d in np.asarray(_concrete_operand(n, "perm", args[1]))
        )
        return jnp.transpose(args[0], perm)
    if op in ("Select", "SelectV2"):
        c, xv, yv = args
        if op == "Select" and getattr(c, "ndim", 0) == 1 and (
            getattr(xv, "ndim", 0) > 1
        ):
            # v1 Select: a vector condition picks whole ROWS of x/y
            c = c.reshape((-1,) + (1,) * (xv.ndim - 1))
        return jnp.where(c, xv, yv)
    if op in ("BatchMatMulV2", "BatchMatMul"):
        a, b = (mxu(v) for v in args)
        adj_x, adj_y = n.attrs.get("adj_x"), n.attrs.get("adj_y")
        if adj_x and adj_x.b:
            a = jnp.swapaxes(a, -1, -2)
        if adj_y and adj_y.b:
            b = jnp.swapaxes(b, -1, -2)
        p = pet_for(a, b)
        if p is not None:
            return jnp.matmul(a, b, preferred_element_type=p)
        return a @ b
    if op == "Conv2D":
        x_, w_ = mxu(args[0]), mxu(args[1])
        return _conv2d(n, x_, w_, preferred=pet_for(x_, w_))
    if op == "DepthwiseConv2dNative":
        x_, w_ = mxu(args[0]), mxu(args[1])
        return _depthwise_conv2d(n, x_, w_, preferred=pet_for(x_, w_))
    if op in ("MaxPool", "AvgPool"):
        return _pool(n, args[0])
    if op == "BiasAdd":
        _nhwc(n)
        return args[0] + args[1]
    if op in ("ConcatV2", "Concat"):
        # axis is a DATA input: LAST for ConcatV2, FIRST for the v1 form
        ax_val = args[-1] if op == "ConcatV2" else args[0]
        ax = int(_concrete_operand(n, "axis", ax_val))
        vals_cat = args[:-1] if op == "ConcatV2" else args[1:]
        return jnp.concatenate(vals_cat, axis=ax)
    if op == "Squeeze":
        dims_a = n.attrs.get("squeeze_dims") or n.attrs.get("axis")
        dims = tuple(dims_a.ints) if dims_a and dims_a.ints else None
        if _is_concrete(args[0]):
            return np.squeeze(args[0], axis=dims)
        return jnp.squeeze(args[0], axis=dims)
    if op in ("Pad", "PadV2"):
        pads = [
            tuple(int(x) for x in row)
            for row in _concrete_operand(n, "paddings", args[1])
        ]
        cval = 0.0
        if op == "PadV2":
            cval = float(_concrete_operand(n, "pad value", args[2]))
        return jnp.pad(args[0], pads, constant_values=cval)
    if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
        # inference form (TF1-era frozen graphs keep the op
        # un-decomposed): y = (x - mean) * rsqrt(var + eps) * scale
        # + offset over NHWC channels. Output :0 only — consumers of
        # :1/:2 are rejected at import. The op's is_training DEFAULT is
        # true, so a missing attr (strip_default_attrs) means training.
        tr = n.attrs.get("is_training")
        if tr is None or tr.b:
            raise ValueError(
                f"{op} node {name!r}: is_training=true (explicit or by "
                "TF default) is not executable in a frozen graph"
            )
        _nhwc(n)
        eps_a = n.attrs.get("epsilon")
        eps = eps_a.f if eps_a and eps_a.f is not None else 1e-4
        xb, scale, offset, mean, var = args[:5]
        inv = scale * (1.0 / jnp.sqrt(var + eps))
        return (xb - mean) * inv + offset
    # ---- dynamic-shape tier (TF1 idioms; kmeans.py:28-45) ----
    if op == "Shape":
        out_a = n.attrs.get("out_type")
        out_dt = _TF_DTYPES.get(out_a.type if out_a else 3, dt.int32)
        # static under XLA: a traced array's .shape is host integers at
        # trace time — this is what folds the reference's dynamic-Tile
        # idiom into a static program
        return np.asarray(args[0].shape, out_dt.np_dtype)
    if op == "Pack":
        ax_a = n.attrs.get("axis")
        ax = int(ax_a.i) if ax_a and ax_a.i is not None else 0
        if _is_concrete(*args):
            return np.stack([np.asarray(a) for a in args], axis=ax)
        return jnp.stack(args, axis=ax)
    if op == "ExpandDims":
        ax = int(_concrete_operand(n, "dim", args[1]))
        if _is_concrete(args[0]):
            return np.expand_dims(args[0], ax)
        return jnp.expand_dims(args[0], ax)
    if op == "Tile":
        mult = tuple(
            int(m) for m in _concrete_operand(n, "multiples", args[1])
        )
        if _is_concrete(args[0]):
            return np.tile(args[0], mult)
        return jnp.tile(args[0], mult)
    if op == "StridedSlice":
        return _strided_slice(n, *args[:4])
    if op == "Fill":
        dims = tuple(int(d) for d in _concrete_operand(n, "dims", args[0]))
        if _is_concrete(args[1]):
            return np.full(dims, np.asarray(args[1]))
        return jnp.full(dims, args[1])
    if op == "Range":
        start = _concrete_operand(n, "start", args[0])
        limit = _concrete_operand(n, "limit", args[1])
        delta = _concrete_operand(n, "delta", args[2])
        return np.arange(
            start[()] if start.ndim == 0 else start,
            limit[()] if limit.ndim == 0 else limit,
            delta[()] if delta.ndim == 0 else delta,
        )
    if op in ("ArgMin", "ArgMax"):
        ax = int(_concrete_operand(n, "dimension", args[1])) if len(args) > 1 else 0
        out_a = n.attrs.get("output_type")
        out_dt = _TF_DTYPES.get(out_a.type if out_a else 9, dt.int64)
        red = jnp.argmin if op == "ArgMin" else jnp.argmax
        if _is_concrete(args[0]):
            red = np.argmin if op == "ArgMin" else np.argmax
        return red(args[0], axis=ax).astype(out_dt.np_dtype)
    if op == "AddN":
        total = args[0]
        for a in args[1:]:
            total = total + a
        return total
    if op == "ReverseV2":
        axes = _axes(_concrete_operand(n, "axis", args[1]))
        return jnp.flip(args[0], axis=axes)
    if op == "GatherNd":
        x, idx = args
        # index tuples along the last dim select slices of x; jnp-wrap
        # the table so a concrete Const indexed by traced indices works
        return jnp.asarray(x)[tuple(jnp.moveaxis(jnp.asarray(idx), -1, 0))]
    if op == "MirrorPad":
        pads = np.asarray(_concrete_operand(n, "paddings", args[1]))
        mode_a = n.attrs.get("mode")
        mode = (mode_a.s or b"REFLECT").decode("utf-8") if mode_a else "REFLECT"
        return jnp.pad(
            args[0],
            [tuple(int(p) for p in row) for row in pads],
            mode="reflect" if mode == "REFLECT" else "symmetric",
        )
    if op == "MatrixBandPart":
        x = args[0]
        lower = int(_concrete_operand(n, "num_lower", args[1]))
        upper = int(_concrete_operand(n, "num_upper", args[2]))
        m, k = x.shape[-2], x.shape[-1]
        i = jnp.arange(m)[:, None]
        j = jnp.arange(k)[None, :]
        keep = jnp.ones((m, k), bool)
        if lower >= 0:
            keep = keep & (i - j <= lower)
        if upper >= 0:
            keep = keep & (j - i <= upper)
        return jnp.where(keep, x, jnp.zeros((), x.dtype))
    if op in ("DepthToSpace", "SpaceToDepth"):
        bs = int(n.attrs["block_size"].i)
        fmt_a = n.attrs.get("data_format")
        if fmt_a and fmt_a.s and fmt_a.s != b"NHWC":
            raise ValueError(
                f"{op} node {name!r}: only NHWC is supported "
                f"(got {fmt_a.s.decode('utf-8')})"
            )
        x = args[0]
        b, h, w, c = x.shape
        if op == "DepthToSpace":
            x = x.reshape(b, h, w, bs, bs, c // (bs * bs))
            x = x.transpose(0, 1, 3, 2, 4, 5)
            return x.reshape(b, h * bs, w * bs, c // (bs * bs))
        x = x.reshape(b, h // bs, bs, w // bs, bs, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(b, h // bs, w // bs, c * bs * bs)
    if op in ("ResizeBilinear", "ResizeNearestNeighbor"):
        size = np.asarray(_concrete_operand(n, "size", args[1]))
        ac_a = n.attrs.get("align_corners")
        hp_a = n.attrs.get("half_pixel_centers")
        return _tf_resize(
            args[0], int(size[0]), int(size[1]),
            bilinear=(op == "ResizeBilinear"),
            align=bool(ac_a.b) if ac_a else False,
            half_pixel=bool(hp_a.b) if hp_a else False,
        )
    raise ValueError(f"unsupported op {op}")  # pragma: no cover — gated


def _tf_resize(x, nh: int, nw: int, bilinear: bool, align: bool,
               half_pixel: bool):
    """TF's legacy image resize, exactly (resize_bilinear_op.cc /
    resize_nearest_neighbor_op.cc semantics for every align_corners /
    half_pixel_centers combination). NHWC; source coordinates are
    STATIC numpy (the size operand is trace-time concrete), so only
    gathers and lerps reach XLA. ResizeBilinear always outputs f32,
    matching TF's kernel signature."""
    b, h, w, c = x.shape

    def scale_for(out_n, in_n):
        if align and out_n > 1:
            return (in_n - 1) / (out_n - 1)
        return in_n / out_n

    def src_coords(out_n, in_n):
        i = np.arange(out_n, dtype=np.float64)
        sc = scale_for(out_n, in_n)
        if half_pixel and not align:
            return (i + 0.5) * sc - 0.5
        return i * sc

    if bilinear:
        def interp_axis(out_n, in_n):
            src = src_coords(out_n, in_n)
            lower = np.maximum(np.floor(src), 0).astype(np.int32)
            upper = np.minimum(np.ceil(src), in_n - 1).astype(np.int32)
            lerp = (src - np.floor(src)).astype(np.float32)
            return lower, upper, lerp

        ly, uy, ty = interp_axis(nh, h)
        lx, ux, tx = interp_axis(nw, w)
        xf = x.astype(jnp.float32)
        top = jnp.take(xf, ly, axis=1)
        bot = jnp.take(xf, uy, axis=1)

        def horiz(img):
            left = jnp.take(img, lx, axis=2)
            right = jnp.take(img, ux, axis=2)
            return left + (right - left) * tx[None, None, :, None]

        t = horiz(top)
        bm = horiz(bot)
        return t + (bm - t) * ty[None, :, None, None]

    def nn_index(out_n, in_n):
        i = np.arange(out_n, dtype=np.float64)
        sc = scale_for(out_n, in_n)
        if half_pixel and not align:
            # NN's half-pixel scaler is (i + 0.5) * scale with NO -0.5
            # (TF's HalfPixelScalerForNN), then floor
            idx = np.floor((i + 0.5) * sc).astype(np.int64)
        elif align:
            # TF rounds half AWAY from zero (roundf), not half-to-even
            idx = np.floor(i * sc + 0.5).astype(np.int64)
        else:
            idx = np.floor(i * sc).astype(np.int64)
        return np.clip(idx, 0, in_n - 1).astype(np.int32)

    iy = nn_index(nh, h)
    ix = nn_index(nw, w)
    return jnp.take(jnp.take(x, iy, axis=1), ix, axis=2)


def load_graphdef(
    path: str,
    fetches: Optional[Sequence[str]] = None,
    relax_lead_dim: bool = False,
    quantize_weights: bool = False,
    compute_dtype: Optional[str] = "auto",
) -> Program:
    """Load a frozen TF ``GraphDef`` file as an analyzed Program
    (≙ ``graphFromFile``, PythonInterface.scala:115-118 — but static:
    shapes come from probing the lowered jax program, not from importing
    into a live TF runtime)."""
    with open(path, "rb") as f:
        data = f.read()
    program = program_from_graphdef(
        parse_graphdef(data),
        fetches=fetches,
        relax_lead_dim=relax_lead_dim,
        quantize_weights=quantize_weights,
        compute_dtype=compute_dtype,
    )
    return analyze_program(program)


def _parse_meta_graphs_raw(data: bytes):
    """Decode every MetaGraphDef's envelope — ``(graphdef_bytes,
    signatures, tags)`` per meta graph, in file order — WITHOUT parsing
    the graphs themselves.  Selection (which meta graph serves the
    requested signature) needs only signatures and tags; a train+serve
    SavedModel's train graph (optimizer ops, gradient subgraphs) can
    dwarf the serve graph, so the full node decode waits until one meta
    graph is picked. Wire path: SavedModel.meta_graphs (field 2) →
    MetaGraphDef.meta_info_def.tags (fields 1.4) + graph_def (field 2)
    + signature_def map (field 5)."""
    metas = []
    try:
        for field, _, v in _iter_fields(data):
            if field != 2:
                continue
            graph_bytes = None
            signatures: Dict[str, Dict[str, Dict[str, str]]] = {}
            tags: List[str] = []
            for f2, _, v2 in _iter_fields(v):
                if f2 == 1:  # MetaInfoDef
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 4 and isinstance(v3, bytes):
                            tags.append(v3.decode("utf-8"))
                elif f2 == 2:
                    graph_bytes = v2
                elif f2 == 5:  # map<string, SignatureDef> entry
                    key = None
                    sig = {"inputs": {}, "outputs": {}}
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            key = v3.decode("utf-8")
                        elif f3 == 2:  # SignatureDef
                            for f4, _, v4 in _iter_fields(v3):
                                if f4 in (1, 2):  # inputs/outputs map
                                    io_name = ref = None
                                    for f5, _, v5 in _iter_fields(v4):
                                        if f5 == 1:
                                            io_name = v5.decode("utf-8")
                                        elif f5 == 2:  # TensorInfo
                                            for f6, _, v6 in _iter_fields(v5):
                                                if f6 == 1:
                                                    ref = v6.decode("utf-8")
                                    if io_name is not None and ref:
                                        side = (
                                            "inputs" if f4 == 1 else "outputs"
                                        )
                                        sig[side][io_name] = ref
                    if key is not None:
                        signatures[key] = sig
            if graph_bytes is not None:
                metas.append((graph_bytes, signatures, tags))
    except (
        IndexError, TypeError, AttributeError, struct.error,
        UnicodeDecodeError, _WireError,
    ) as e:
        raise ValueError(
            f"not a valid serialized SavedModel ({type(e).__name__} while "
            f"decoding: {e})"
        ) from e
    if not metas:
        raise ValueError("SavedModel contains no MetaGraphDef graph")
    return metas


def parse_saved_model_meta_graphs(data: bytes):
    """Decode EVERY MetaGraphDef in ``saved_model.pb`` (saved_model.proto)
    without TensorFlow: returns a list of ``(GraphNodes, signatures,
    tags)`` triples, one per meta graph, in file order. ``signatures``
    maps each signature key to ``{"inputs": {arg: tensor_ref},
    "outputs": {...}}`` (TensorInfo names like
    ``"StatefulPartitionedCall:0"``); ``tags`` is the meta graph's
    tag-set (e.g. ``["serve"]``, ``["train"]``).

    A SavedModel may carry several meta graphs (e.g. train+serve);
    ``load_saved_model`` picks the one holding the requested signature
    rather than assuming it lives in the first.
    """
    return [
        (parse_graphdef(gb), signatures, tags)
        for gb, signatures, tags in _parse_meta_graphs_raw(data)
    ]


def parse_saved_model(data: bytes):
    """Decode ``saved_model.pb`` and return ``(GraphNodes, signatures)``
    for the SERVING meta graph: the one tagged ``serve`` when several
    meta graphs are present (train+serve exports), else the first. Only
    the selected meta graph's nodes are decoded. See
    :func:`parse_saved_model_meta_graphs` for the full list."""
    metas = _parse_meta_graphs_raw(data)
    for gb, signatures, tags in metas:
        if "serve" in tags:
            return parse_graphdef(gb), signatures
    return parse_graphdef(metas[0][0]), metas[0][1]


def load_saved_model(
    path: str,
    signature: str = "serving_default",
    fetches: Optional[Sequence[str]] = None,
    relax_lead_dim: bool = False,
    quantize_weights: bool = False,
    compute_dtype: Optional[str] = "auto",
) -> Program:
    """Import a TF SavedModel signature — with NO TensorFlow at all.

    The clean-room parser reads ``saved_model.pb`` directly (MetaGraph
    selection, signature map, function library for PartitionedCall
    bodies), and VARIABLE-BEARING models restore their weights straight
    from the checkpoint bundle (``bundle.py`` reads
    ``variables/variables.index`` + data shards; VarHandleOp binds to
    the value, ReadVariableOp is an identity). TensorFlow is used only
    as a FALLBACK for models the clean-room path cannot resolve (legacy
    ``VariableV2`` graphs, unresolvable handles, or
    ``quantize_weights=True``, whose weight planner needs an inlined
    graph) — those freeze via ``convert_variables_to_constants_v2``.

    Migration affordance beyond the reference (which took raw GraphDefs
    only): modern TF users hold SavedModels, and they import here with
    an empty environment — no tensorflow at conversion OR scoring time.
    """
    import os as _os

    pb = _os.path.join(path, "saved_model.pb")
    tf_free_error = None
    if _os.path.exists(pb):
        with open(pb, "rb") as fh:
            metas = _parse_meta_graphs_raw(fh.read())
        # Pick the meta graph HOLDING the requested signature (prefer a
        # serve-tagged one on ties): multi-meta-graph SavedModels
        # (e.g. train+serve tag-sets) may keep the serving signature in
        # a later entry, where first-only decoding would miss it. Only
        # the picked graph's nodes decode — the others stay raw bytes.
        holders = [m for m in metas if signature in m[1]]
        pool = holders or metas
        tagged = [m for m in pool if "serve" in m[2]]
        graph_bytes, signatures, _tags = (tagged or pool)[0]
        nodes = parse_graphdef(graph_bytes)
        has_vars = any(
            n.op in ("VarHandleOp", "VariableV2", "ReadVariableOp")
            for n in nodes
        )
        variables = None
        if has_vars and signatures and not quantize_weights:
            # clean-room variable restore (VERDICT r3 #9): read the
            # checkpoint bundle directly so variable-bearing SavedModels
            # import with NO TensorFlow even at conversion time. Any
            # malformed/unsupported bundle falls back to TF freezing.
            # quantize_weights still routes through TF freezing: the
            # weight planner needs an inlined (library-free) graph.
            try:
                from .bundle import restore_variables

                variables = restore_variables(
                    _os.path.join(path, "variables")
                )
            except Exception as e:
                logger.warning(
                    "clean-room variable restore failed (%s); falling "
                    "back to TensorFlow freezing", e,
                )
                variables = None
        if signatures and (not has_vars or variables is not None):
            if signature not in signatures:
                every = sorted({s for _, sigs, _ in metas for s in sigs})
                raise KeyError(
                    f"SavedModel has no signature {signature!r} in any "
                    f"of its {len(metas)} meta graph(s); available: "
                    f"{every}"
                )

            def _tf_free_import():
                sig = signatures[signature]
                sig_fetches = fetches
                rename = None
                if sig_fetches is None:
                    # fetch the signature's output tensors, then rename the
                    # result columns to the signature's output-arg names —
                    # several output names may ALIAS one tensor, so the map
                    # is fetch → [names]
                    sig_fetches = []
                    rename = {}
                    for out_name, ref in sorted(sig["outputs"].items()):
                        f = ref[:-2] if ref.endswith(":0") else ref
                        if f not in rename:
                            sig_fetches.append(f)
                            rename[f] = []
                        rename[f].append(out_name)
                program = program_from_graphdef(
                    nodes,
                    fetches=sig_fetches,
                    relax_lead_dim=relax_lead_dim,
                    quantize_weights=quantize_weights,
                    compute_dtype=compute_dtype,
                    variables=variables,
                )
                if rename:
                    inner = program.fn
                    rmap = dict(rename)

                    def renamed(feeds, _inner=inner, _rmap=rmap):
                        out = {}
                        for k, v in _inner(feeds).items():
                            for nm2 in _rmap.get(k, [k]):
                                out[nm2] = v
                        return out

                    program = Program(
                        renamed,
                        program.inputs,
                        fetch_order=[
                            nm2
                            for f in program.fetch_order
                            for nm2 in rmap.get(f, [f])
                        ],
                    )
                # inputs follow the signature's declared arg names too (the
                # TF-freeze path exposes these; graph placeholders carry
                # mangled 'serving_default_*' names)
                in_rename = {}
                for arg_name, ref in sig["inputs"].items():
                    ph = ref[:-2] if ref.endswith(":0") else ref
                    if ph != arg_name and ph in [
                        i.name for i in program.inputs
                    ]:
                        in_rename[ph] = arg_name
                if in_rename:
                    program = program.rename_inputs(in_rename)
                return analyze_program(program)

            if not has_vars:
                return _tf_free_import()
            try:
                return _tf_free_import()
            except UnresolvedVariableError as e:
                # a resolvable BUNDLE does not guarantee a
                # resolvable GRAPH: a reachable VarHandleOp whose
                # shared_name is absent from the restored map keeps
                # the old TF-freezing behavior below
                tf_free_error = e
                logger.warning(
                    "TF-free variable import failed (%s); falling "
                    "back to TensorFlow freezing", e,
                )
            except ValueError as e:
                # a GENUINE lowering failure (e.g. unsupported op —
                # legacy VariableV2 lands here). TF re-tracing during
                # freezing can still produce a lowerable graph, so
                # fall back — but keep the root cause chained so a
                # missing-tensorflow environment surfaces it instead
                # of only the generic 'tensorflow required' (ADVICE r4)
                tf_free_error = e
                logger.warning(
                    "TF-free import hit a lowering error (%s); "
                    "retrying via TensorFlow freezing", e,
                )
    try:
        import tensorflow as tf
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )
    except ImportError as e:
        msg = (
            "this SavedModel holds variables, and freezing them needs "
            "tensorflow; freeze offline (convert_variables_to_constants_v2) "
            "and use load_graphdef on the result instead (variable-FREE "
            "SavedModels load without tensorflow)"
        )
        if tf_free_error is not None:
            msg += (
                f"; note the TF-free import path failed first with: "
                f"{tf_free_error}"
            )
        # chain `e`, not tf_free_error: a BROKEN tensorflow install
        # (numpy ABI mismatch etc.) must stay visible — tf_free_error
        # is already embedded in the message above
        raise ImportError(msg) from e
    m = tf.saved_model.load(path)
    if signature not in m.signatures:
        raise KeyError(
            f"SavedModel has no signature {signature!r}; available: "
            f"{sorted(m.signatures)}"
        )
    frozen = convert_variables_to_constants_v2(m.signatures[signature])
    data = frozen.graph.as_graph_def().SerializeToString()
    program = program_from_graphdef(
        parse_graphdef(data),
        fetches=fetches,
        relax_lead_dim=relax_lead_dim,
        quantize_weights=quantize_weights,
        compute_dtype=compute_dtype,
    )
    return analyze_program(program)
