"""tensorframes_tpu — a TPU-native columnar-frame compute framework.

A brand-new framework with the capabilities of TensorFrames (the reference,
databricks/tensorframes): attach numeric programs to the columns of a
distributed dataframe through five verbs — ``map_rows``, ``map_blocks``
(± trimmed), ``reduce_rows``, ``reduce_blocks``, keyed ``aggregate`` — plus
schema tooling (``analyze``, ``append_shape``, ``print_schema``).

Architecture (TPU-first, not a port — see SURVEY.md §7):

* a frame is a block-partitioned columnar container of arrays
  (host numpy and/or device ``jax.Array`` shards over a mesh), not a Spark
  DataFrame;
* a user program is a traced JAX function / expression graph
  (jaxpr / StableHLO), not a protobuf ``GraphDef`` fed to a TF Session;
* distribution is ``jax.sharding`` + ``shard_map`` with ICI collectives,
  not driver-coordinated ``RDD.reduce`` / Catalyst shuffles.
"""

from __future__ import annotations

import jax as _jax

from .config import get_config as _get_config, configure  # noqa: F401

if _get_config().enable_x64:
    # The reference's core column types are Double/Long
    # (datatypes.scala:265-267); x64 makes those exact end-to-end.
    _jax.config.update("jax_enable_x64", True)

if _get_config().compilation_cache_dir:
    # persistent executable cache: a fresh process deserializes compiled
    # XLA programs instead of paying the 20-40s TPU compile again
    _jax.config.update(
        "jax_compilation_cache_dir", _get_config().compilation_cache_dir
    )
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from . import dtypes  # noqa: E402,F401
from .shape import Shape, Unknown  # noqa: E402,F401
from .schema import ColumnInfo, Schema  # noqa: E402,F401
from .frame import TensorFrame, describe, frame_from_arrays, frame_from_pandas, frame_from_rows  # noqa: E402,F401
from .frame import analyze, append_shape, print_schema, explain  # noqa: E402,F401
from .dsl import (  # noqa: E402,F401
    Node,
    abs_,
    add,
    apply_fn,
    block,
    constant,
    div,
    exp,
    fill,
    identity,
    log,
    matmul,
    mul,
    ones,
    placeholder,
    reduce_max,
    reduce_mean,
    reduce_min,
    reduce_sum,
    relu,
    row,
    scope,
    sigmoid,
    sqrt,
    square,
    sub,
    tanh,
    with_graph,
    zeros,
)
from .program import (  # noqa: E402,F401
    Program,
    TensorSpec,
    load_program,
    program_from_function,
    save_program,
)
from .graphdef import (  # noqa: E402,F401
    load_graphdef,
    load_saved_model,
    parse_graphdef,
    parse_saved_model,
    parse_saved_model_meta_graphs,
    program_from_graphdef,
)
from .bundle import restore_variables  # noqa: E402,F401
from .validation import StaticAnalysisError, ValidationError  # noqa: E402,F401
from . import analysis  # noqa: E402,F401
from .analysis import analyze_frame, lint_plan, lint_program  # noqa: E402,F401
from . import plan  # noqa: E402,F401  (registers tftpu_plan_* metrics)
from . import kernels  # noqa: E402,F401  (registers tftpu_kernels_* metrics)
from .plan import explain_plan  # noqa: E402,F401
from .ops.verbs import (  # noqa: E402,F401
    NumpyUDF,
    aggregate,
    compile_program,
    map_blocks,
    map_rows,
    numpy_udf,
    reduce_blocks,
    reduce_rows,
)
from .checkpoint import Checkpointer, CheckpointCorruptionError  # noqa: E402,F401
from .training import run_resumable  # noqa: E402,F401
from . import resilience  # noqa: E402,F401  (registers tftpu_fleet_* metrics)
from .resilience import RetryPolicy, StepGuard, supervise  # noqa: E402,F401
from . import io  # noqa: E402,F401
from .io import (  # noqa: E402,F401
    frame_from_arrow,
    frame_to_arrow,
    load_frame,
    read_csv,
    read_parquet,
    save_frame,
    write_csv,
    write_parquet,
)
from .utils import profiling  # noqa: E402,F401
from . import observability  # noqa: E402,F401
from .observability import StepTelemetry  # noqa: E402,F401
from . import compilecache  # noqa: E402,F401  (registers tftpu_compilecache_* metrics)
from .compilecache import WarmupReport, warmup  # noqa: E402,F401
from . import blockstore  # noqa: E402,F401  (registers tftpu_blockstore_* metrics)
from .blockstore import (  # noqa: E402,F401
    BlockStore,
    SpilledFrame,
    stream_chain,
)
from .io import scan_csv, scan_parquet  # noqa: E402,F401
from . import serving  # noqa: E402,F401  (registers tftpu_serving_* metrics)
from .serving import (  # noqa: E402,F401
    DecodeConfig,
    DecodeEngine,
    Server,
    ServingConfig,
    serve_http,
)

__version__ = "0.3.0"

__all__ = [
    "TensorFrame",
    "frame_from_arrays",
    "frame_from_pandas",
    "frame_from_rows",
    "Shape",
    "Unknown",
    "ColumnInfo",
    "Schema",
    "dtypes",
    "configure",
    # verbs (≙ reference __init__.py:15-21 public surface)
    "map_rows",
    "map_blocks",
    "reduce_rows",
    "reduce_blocks",
    "aggregate",
    "compile_program",
    "numpy_udf",
    "NumpyUDF",
    "analyze",
    "append_shape",
    "print_schema",
    "explain",
    "describe",
    "plan",
    "explain_plan",
    "lint_plan",
    # aux subsystems
    "serving",
    "Server",
    "ServingConfig",
    "DecodeConfig",
    "DecodeEngine",
    "serve_http",
    "Checkpointer",
    "CheckpointCorruptionError",
    "resilience",
    "RetryPolicy",
    "StepGuard",
    "supervise",
    "run_resumable",
    "profiling",
    "observability",
    "StepTelemetry",
    "io",
    "save_frame",
    "load_frame",
    "read_csv",
    "write_csv",
    "frame_from_arrow",
    "frame_to_arrow",
    "read_parquet",
    "write_parquet",
    "scan_csv",
    "scan_parquet",
    # out-of-core data plane
    "blockstore",
    "BlockStore",
    "SpilledFrame",
    "stream_chain",
    # dsl / placeholder helpers
    "Node",
    "block",
    "row",
    "placeholder",
    "constant",
    "zeros",
    "ones",
    "fill",
    "with_graph",
    "scope",
    # op catalog
    "identity",
    "add",
    "sub",
    "mul",
    "div",
    "matmul",
    "reduce_sum",
    "reduce_min",
    "reduce_max",
    "reduce_mean",
    "exp",
    "log",
    "tanh",
    "sqrt",
    "abs_",
    "square",
    "sigmoid",
    "relu",
    "apply_fn",
    # programs
    "Program",
    "TensorSpec",
    "program_from_function",
    "save_program",
    "load_program",
    "ValidationError",
    # static analysis (tfguard)
    "analysis",
    "analyze_frame",
    "lint_program",
    "StaticAnalysisError",
]
