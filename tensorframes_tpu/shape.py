"""Tensor shapes with unknown dimensions.

The frame layer tracks a (possibly partial) shape for every column cell and
every block. A dimension may be *unknown* (``Unknown == -1``), which arises
when rows in a column carry ragged vectors, or when the user has not yet run
``analyze`` on the frame.

Capability parity with the reference's ``Shape`` abstraction
(reference: src/main/scala/org/tensorframes/Shape.scala:16-109):

* unknown dims encoded as -1 (Shape.scala:88-89)
* ``prepend`` / ``tail`` / ``drop_inner`` structural ops
* a *precision lattice*: ``is_more_precise_than`` (Shape.scala:54-59)
* ``merge`` to Unknown on disagreement
  (reference: ExperimentalOperations.scala:168-178)
* physical-shape inference from element counts
  (reference: impl/DataOps.scala:103-144)

Unlike the reference this is a pure-Python value type with no protobuf
round-tripping — the XLA-side shape is derived from ``jax.ShapeDtypeStruct``
at trace time instead of ``TensorShapeProto``.
"""

from __future__ import annotations

import math
from typing import Iterable, Optional, Tuple

Unknown: int = -1


class Shape:
    """An immutable N-dimensional shape; dims may be ``Unknown`` (-1).

    ``Shape.empty()`` (rank 0) denotes a scalar.
    """

    __slots__ = ("_dims",)

    def __init__(self, dims: Iterable[int]):
        dims = tuple(int(d) for d in dims)
        for d in dims:
            if d < -1:
                raise ValueError(f"Invalid dimension {d} in shape {dims}")
        object.__setattr__(self, "_dims", dims)

    def __setattr__(self, *a):  # immutability
        raise AttributeError("Shape is immutable")

    # -- constructors -------------------------------------------------------
    @staticmethod
    def empty() -> "Shape":
        return Shape(())

    @staticmethod
    def scalar() -> "Shape":
        return Shape(())

    @staticmethod
    def of(*dims: int) -> "Shape":
        return Shape(dims)

    @staticmethod
    def unknown(rank: int) -> "Shape":
        """A shape of given rank with every dim unknown."""
        return Shape((Unknown,) * rank)

    @staticmethod
    def from_any(x) -> "Shape":
        """Coerce sequences / Shape / None-style dims into a Shape.

        ``None`` entries map to Unknown, mirroring the reference's Python
        client convention (core.py:38-40: ``-1 if x is None else x``).
        """
        if isinstance(x, Shape):
            return x
        return Shape(tuple(Unknown if d is None else int(d) for d in x))

    # -- accessors ----------------------------------------------------------
    @property
    def dims(self) -> Tuple[int, ...]:
        return self._dims

    @property
    def rank(self) -> int:
        return len(self._dims)

    @property
    def is_scalar(self) -> bool:
        return not self._dims

    @property
    def has_unknown(self) -> bool:
        return Unknown in self._dims

    def __len__(self) -> int:
        return len(self._dims)

    def __iter__(self):
        return iter(self._dims)

    def __getitem__(self, i):
        return self._dims[i]

    def __eq__(self, other) -> bool:
        return isinstance(other, Shape) and self._dims == other._dims

    def __hash__(self) -> int:
        return hash(("Shape", self._dims))

    def __repr__(self) -> str:
        return f"Shape{list(self._dims)}"

    def __str__(self) -> str:
        return "[" + ",".join("?" if d == Unknown else str(d) for d in self._dims) + "]"

    # -- structural ops (≙ Shape.scala prepend/tail/dropInner) --------------
    def prepend(self, dim: Optional[int]) -> "Shape":
        """Add a leading (block/row-count) dimension; None → Unknown."""
        d = Unknown if dim is None else int(dim)
        return Shape((d,) + self._dims)

    @property
    def tail(self) -> "Shape":
        """Drop the leading dimension (block shape → cell shape)."""
        if not self._dims:
            raise ValueError("Cannot take tail of a scalar shape")
        return Shape(self._dims[1:])

    def drop_inner(self) -> "Shape":
        """Drop the innermost (last) dimension."""
        if not self._dims:
            raise ValueError("Cannot drop inner dim of a scalar shape")
        return Shape(self._dims[:-1])

    def with_leading_unknown(self) -> "Shape":
        """Replace the leading dim by Unknown (block shapes never pin the
        row count — empty partitions would otherwise fail; core.py:470-473)."""
        if not self._dims:
            raise ValueError("Scalar shape has no leading dim")
        return Shape((Unknown,) + self._dims[1:])

    # -- element counting ---------------------------------------------------
    @property
    def num_elements(self) -> Optional[int]:
        """Number of elements, or None if any dim is unknown."""
        if self.has_unknown:
            return None
        return math.prod(self._dims) if self._dims else 1

    # -- the precision lattice ----------------------------------------------
    def is_more_precise_than(self, other: "Shape") -> bool:
        """True iff this shape refines ``other``: same rank and every dim
        either matches or ``other``'s dim is Unknown.

        ≙ ``Shape.checkMorePreciseThan`` (reference: Shape.scala:54-59).
        """
        if self.rank != other.rank:
            return False
        return all(o == Unknown or s == o for s, o in zip(self._dims, other._dims))

    def is_compatible_with(self, other: "Shape") -> bool:
        """Dims compatible pointwise (equal, or either Unknown); same rank."""
        if self.rank != other.rank:
            return False
        return all(
            s == o or s == Unknown or o == Unknown
            for s, o in zip(self._dims, other._dims)
        )

    def merge(self, other: "Shape") -> Optional["Shape"]:
        """Pointwise merge for the analyze scan: dims that disagree become
        Unknown; rank mismatch yields None (incompatible columns).

        ≙ ``ExtraOperations.merge`` (reference: ExperimentalOperations.scala:168-178).
        """
        if self.rank != other.rank:
            return None
        return Shape(
            tuple(s if s == o else Unknown for s, o in zip(self._dims, other._dims))
        )

    def refine(self, hint: "Shape") -> "Shape":
        """Overlay a hint shape: hint dims win wherever they are known.

        This is the *hint-override* rule: user/DSL-provided shape hints take
        precedence over statically derived shapes
        (reference: TensorFlowOps.scala:126-133).
        """
        if hint.rank != self.rank:
            return hint  # a hint of different rank replaces outright
        return Shape(
            tuple(h if h != Unknown else s for s, h in zip(self._dims, hint._dims))
        )


def infer_physical_shape(num_elements: int, shape: Shape) -> Shape:
    """Resolve at most one Unknown dim of ``shape`` from a known element count.

    ≙ ``DataOps.inferPhysicalShape`` (reference: impl/DataOps.scala:103-144):
    given the flat element count of a materialised tensor and a partial
    shape, solve for the single unknown dimension. Errors mirror the
    reference's contract: more than one unknown dim, non-divisible counts,
    and zero-sized known dims with nonzero counts are all rejected.
    """
    dims = shape.dims
    unknown_idx = [i for i, d in enumerate(dims) if d == Unknown]
    if len(unknown_idx) > 1:
        raise ValueError(
            f"Shape {shape} has more than one unknown dimension; cannot infer "
            f"physical shape from {num_elements} elements"
        )
    known = math.prod([d for d in dims if d != Unknown]) if dims else 1
    if not unknown_idx:
        if known != num_elements:
            raise ValueError(
                f"Shape {shape} implies {known} elements but buffer has "
                f"{num_elements}"
            )
        return shape
    if known == 0:
        if num_elements != 0:
            raise ValueError(
                f"Shape {shape} has a zero dim but buffer has {num_elements} elements"
            )
        resolved = 0
    else:
        if num_elements % known != 0:
            raise ValueError(
                f"Buffer of {num_elements} elements does not divide into shape {shape}"
            )
        resolved = num_elements // known
    out = list(dims)
    out[unknown_idx[0]] = resolved
    return Shape(out)


def shape_of_nested(cell) -> Shape:
    """Recursive shape of a (possibly nested) Python list / numpy cell.

    ≙ the analyze pass's per-cell recursion
    (reference: ExperimentalOperations.scala:140-152). Numpy arrays report
    their ndarray shape directly; nested lists recurse on the first element
    (ragged inner lists are detected by the caller via merge()).
    """
    import numpy as np

    if isinstance(cell, np.ndarray):
        return Shape(cell.shape)
    if isinstance(cell, (list, tuple)):
        if len(cell) == 0:
            return Shape((0,))
        inner = shape_of_nested(cell[0])
        return inner.prepend(len(cell))
    return Shape.empty()
