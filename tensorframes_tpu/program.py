"""Program capture and static analysis.

The reference ingests user programs in three forms
(project/Build.scala:102-107): the TF Python API, serialized protobuf
``GraphDef``\\ s, and a small Scala DSL — all funnelled into a byte blob that
is later *re-imported into the TF runtime* to discover inputs/outputs/
dtypes/shapes (``analyzeGraphTF``, TensorFlowOps.scala:101-141).

The TPU-native equivalents here:

* **traced Python functions** over ``jax.numpy`` (primary; ≙ the TF Python
  path — closure-captured values play the role of frozen ``tf.Variable``
  constants, core.py:42-56);
* **DSL expression graphs** (:mod:`tensorframes_tpu.dsl`), compiled to the
  same ``Program`` form;
* **serialized StableHLO** via ``jax.export`` (≙ ``GraphDef`` file loading,
  PythonInterface.scala:115-118).

Analysis is *static*: instead of loading a graph into a live runtime, we
``jax.eval_shape`` the program against abstract inputs. Unknown (batch)
dimensions are discovered by probing two distinct batch sizes and marking
every output dim that co-varies with the probe — this replaces the
reference's shape-hints workaround for dims the graph pruned
(ShapeDescription.scala:12-19). Explicit user hints still override
(the hint-override rule, TensorFlowOps.scala:126-133).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt
from .shape import Shape, Unknown

# Probe batch sizes used to discover batch-covariant output dims. Coprime and
# unequal so a dim matching both probes by accident is effectively impossible.
_PROBE_A = 3
_PROBE_B = 7


class HoistedProgram:
    """A function traced to a jaxpr with its closure CONSTANTS lifted to
    runtime arguments (single shared implementation — the executor's
    per-shape cache, ``Program.cost_analysis``, and tests all use this).

    Why hoist: ``jax.jit(fn)`` embeds closure-captured weights as HLO
    literals and XLA constant-folds through them — measured round 3,
    that re-materialized int8-quantized weights as full f32 constants
    (zero byte saving) and re-embedded every model's weights into every
    per-shape HLO. Passing ``closed.consts`` as arguments keeps weights
    as runtime parameters: int8 stays ``s8`` in the executable and the
    compiler never sees a literal to fold.

    Constants are ``jax.device_put`` once at construction so repeated
    calls reuse the committed device buffers instead of re-uploading
    weights per call."""

    __slots__ = (
        "jitted", "consts", "in_tree", "_flat_abstract", "_run",
        "_jitted_donate", "closed", "out_tree",
    )

    def __init__(self, fn: Callable, abstract_inputs):
        from jax.core import eval_jaxpr

        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(
            abstract_inputs
        )
        out_tree = jax.tree_util.tree_structure(out_shape)
        self._flat_abstract, self.in_tree = jax.tree_util.tree_flatten(
            abstract_inputs
        )
        jaxpr = closed.jaxpr
        # kept for the persistent compile cache: the fingerprint hashes
        # the jaxpr text + const avals (values stay out of the key — in
        # this hoisted form the executable is weight-independent), and
        # the store's serialized entries reconstruct call treedefs from
        # (n_consts, input count, out_tree)
        self.closed = closed
        self.out_tree = out_tree
        self.consts = jax.device_put(closed.consts)

        def run(consts, flat_ins):
            outs = eval_jaxpr(jaxpr, consts, *flat_ins)
            return jax.tree_util.tree_unflatten(out_tree, outs)

        self._run = run
        self.jitted = jax.jit(run)
        self._jitted_donate = None

    def __call__(self, inputs, donate: bool = False):
        flat, tree = jax.tree_util.tree_flatten(inputs)
        if tree != self.in_tree:
            raise ValueError("input structure changed since tracing")
        if donate:
            # donate the flat INPUTS only — the hoisted consts (model
            # weights) are reused across calls and must never be donated
            if self._jitted_donate is None:
                self._jitted_donate = jax.jit(
                    self._run, donate_argnums=(1,)
                )
            return self._jitted_donate(self.consts, flat)
        return self.jitted(self.consts, flat)

    def aot_compile(self):
        """AOT-compile at the traced shapes (cost analysis, HLO text)."""
        return self.jitted.lower(self.consts, self._flat_abstract).compile()

    def const_bytes(self) -> int:
        """Total bytes of the hoisted constants — the program's true
        weight-residency footprint (QuantizedTensor-aware by summing the
        flattened leaves)."""
        return sum(
            int(np.prod(c.shape)) * c.dtype.itemsize
            for c in jax.tree_util.tree_leaves(self.consts)
        )


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Name + dtype + (partial) shape of one program input or output.

    ≙ ``GraphNodeSummary`` (TensorFlowOps.scala:163-169).
    """

    name: str
    dtype: dt.ScalarType
    shape: Shape  # may contain Unknown dims

    def pretty(self) -> str:
        return f"{self.name}: {self.dtype.name}{self.shape}"


class Program:
    """A compiled-form user program: named inputs → named outputs.

    ``fn`` maps a dict of arrays (keyed by input name) to a dict of arrays
    (keyed by output name). It must be jit-traceable. ``inputs`` carries the
    declared dtype/shape of each input (shapes may have Unknown dims);
    ``outputs`` is filled in by :func:`analyze_program`.
    """

    def __init__(
        self,
        fn: Callable[[Dict[str, jnp.ndarray]], Dict[str, jnp.ndarray]],
        inputs: Sequence[TensorSpec],
        outputs: Optional[Sequence[TensorSpec]] = None,
        fetch_order: Optional[Sequence[str]] = None,
    ):
        self.fn = fn
        self.inputs: List[TensorSpec] = list(inputs)
        self.outputs: List[TensorSpec] = list(outputs) if outputs else []
        # order in which the user listed fetches (defines result ordering for
        # reduce verbs returning numpy arrays)
        self.fetch_order: List[str] = (
            list(fetch_order) if fetch_order else [o.name for o in self.outputs]
        )
        self._compiled = None  # memoized CompiledProgram (ops/executor.py)

    def compiled(self):
        """Memoized jitted entrypoints. Reusing a Program across verb calls
        reuses the XLA executable — the analogue of the reference keeping
        one Session across a pairwise fold (DebugRowOps.scala:939-979), but
        across whole verb invocations."""
        if self._compiled is None:
            from .ops.executor import CompiledProgram

            self._compiled = CompiledProgram(self)
        return self._compiled

    @property
    def input_names(self) -> List[str]:
        return [s.name for s in self.inputs]

    @property
    def output_names(self) -> List[str]:
        return [s.name for s in self.outputs]

    def input(self, name: str) -> TensorSpec:
        for s in self.inputs:
            if s.name == name:
                return s
        raise KeyError(
            f"Program has no input {name!r}; inputs: {self.input_names}"
        )

    def output(self, name: str) -> TensorSpec:
        for s in self.outputs:
            if s.name == name:
                return s
        raise KeyError(
            f"Program has no output {name!r}; outputs: {self.output_names}"
        )

    def rename_inputs(self, mapping: Dict[str, str]) -> "Program":
        """Rename inputs (placeholder → column feed_dict remapping,
        ≙ core.py:128-142). ``mapping`` maps old input name → new name."""
        new_inputs = [
            TensorSpec(mapping.get(s.name, s.name), s.dtype, s.shape)
            for s in self.inputs
        ]
        inner = self.fn
        inv = {mapping.get(s.name, s.name): s.name for s in self.inputs}

        def fn(feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
            return inner({inv.get(k, k): v for k, v in feeds.items()})

        renamed = Program(fn, new_inputs, self.outputs, self.fetch_order)
        # carry the segment-lowering info (input names remapped) so the
        # aggregate fast path survives feed_dict renames
        seg = getattr(self, "seg_info", None)
        if seg is not None:
            renamed.seg_info = [
                (out, op, mapping.get(inp, inp)) for (out, op, inp) in seg
            ]
        return renamed

    def explain(self) -> str:
        ins = ", ".join(s.pretty() for s in self.inputs)
        outs = ", ".join(s.pretty() for s in self.outputs)
        extra = ""
        if self._compiled is not None:
            sizes = self._compiled.cache_sizes()
            extra = (
                f", compiled_shapes={{block: {sizes['block']}, "
                f"vmap: {sizes['vmap']}}}"
            )
        return f"Program(inputs=[{ins}], outputs=[{outs}]{extra})"

    def lint(
        self,
        probe: int = 8,
        rules: Optional[Sequence[str]] = None,
        hbm_budget_bytes: Optional[int] = None,
    ):
        """Pre-execution static diagnostics over this program's jaxpr +
        specs (:mod:`tensorframes_tpu.analysis`): recompile storms, f64
        leaks, dead inputs, donation aliasing, NaN hazards, HBM budget.
        Purely static — tracing only, zero XLA compiles, zero transfers.
        Returns a :class:`~tensorframes_tpu.analysis.DiagnosticReport`;
        chain ``.raise_on_errors()`` for strict behavior."""
        from .analysis import lint_program

        return lint_program(
            self, probe=probe, rules=rules,
            hbm_budget_bytes=hbm_budget_bytes,
        )

    def cost_analysis(self, probe: int = 8) -> Dict[str, float]:
        """XLA's compiled cost model for this program: flops, bytes
        accessed, peak memory (keys as XLA reports them). Unknown dims are
        probed at ``probe`` rows. Observability upgrade over the
        reference's log4j-only tracing (SURVEY §5): the reference could
        not ask its runtime what a graph costs without running it."""
        cache = getattr(self, "_cost_cache", None)
        if cache is None:
            cache = self._cost_cache = {}
        if probe in cache:
            return dict(cache[probe])
        abstract = _abstract_inputs(self.inputs, probe)
        compiled = None
        from .config import get_config

        if get_config().hoist_constants:
            # cost the program in the same form the executor runs it:
            # closure constants (weights) lifted to runtime parameters —
            # otherwise XLA folds through them and the model (a) misses
            # their HBM traffic and (b) un-does int8 quantization
            try:
                compiled = HoistedProgram(self.fn, abstract).aot_compile()
            except Exception:  # exotic programs: closure-capture costing
                compiled = None
        if compiled is None:
            compiled = jax.jit(self.fn).lower(abstract).compile()
        costs = compiled.cost_analysis()
        if isinstance(costs, (list, tuple)):  # older jax returns [dict]
            costs = costs[0] if costs else {}
        cache[probe] = dict(costs or {})
        return dict(cache[probe])

    def flops_per_row(self, probe: int = 8) -> float:
        """Marginal model FLOPs per input row, estimated from XLA's cost
        model at two probe batch sizes (the difference removes any
        batch-independent constant work). Memoized — feeds the MFU
        column in ``profiling.report()``."""
        cached = getattr(self, "_flops_per_row", None)
        if cached is not None:
            return cached
        f1 = float(self.cost_analysis(probe).get("flops", 0.0))
        f2 = float(self.cost_analysis(2 * probe).get("flops", 0.0))
        val = max(0.0, (f2 - f1) / probe)
        self._flops_per_row = val
        return val

    def bytes_per_row(self, probe: int = 8) -> float:
        """Marginal XLA-cost-model bytes accessed per input row (same
        two-probe scheme as :meth:`flops_per_row`). Feeds the HBM GB/s
        column in ``profiling.report()`` — and makes weight-traffic
        claims (int8 quantization's 4×) checkable without hardware
        counters."""
        cached = getattr(self, "_bytes_per_row", None)
        if cached is not None:
            return cached
        b1 = float(self.cost_analysis(probe).get("bytes accessed", 0.0))
        b2 = float(self.cost_analysis(2 * probe).get("bytes accessed", 0.0))
        val = max(0.0, (b2 - b1) / probe)
        self._bytes_per_row = val
        return val

    def total_bytes_accessed(self, probe: int = 8) -> float:
        """Absolute ``bytes accessed`` at ``probe`` rows — includes the
        batch-independent weight traffic ``bytes_per_row`` differences
        away (exactly the part int8 quantization shrinks)."""
        return float(self.cost_analysis(probe).get("bytes accessed", 0.0))


def _abstract_inputs(
    inputs: Sequence[TensorSpec], probe: int
) -> Dict[str, jax.ShapeDtypeStruct]:
    out = {}
    for s in inputs:
        dims = tuple(probe if d == Unknown else d for d in s.shape.dims)
        out[s.name] = jax.ShapeDtypeStruct(dims, s.dtype.np_dtype)
    return out


def analyze_program(
    program: Program,
    hints: Optional[Dict[str, Shape]] = None,
) -> Program:
    """Static shape/dtype analysis of a Program (≙ ``analyzeGraphTF``).

    Runs ``jax.eval_shape`` with two different probe values substituted for
    Unknown input dims; output dims equal to a probe in both runs (and
    scaling with it) are marked Unknown (batch-covariant). ``hints``
    (output name → Shape) override discovered shapes wherever the hint dim
    is known — the reference's hint-override rule
    (TensorFlowOps.scala:126-133).
    """
    hints = hints or {}

    def run(probe: int):
        abstract = _abstract_inputs(program.inputs, probe)
        return jax.eval_shape(program.fn, abstract)

    res_a = run(_PROBE_A)
    if any(s.shape.has_unknown for s in program.inputs):
        res_b = run(_PROBE_B)
    else:
        res_b = res_a

    if not isinstance(res_a, dict):
        raise TypeError(
            "Program function must return a dict of named outputs; got "
            f"{type(res_a).__name__}"
        )

    outputs: List[TensorSpec] = []
    order = program.fetch_order or list(res_a.keys())
    for name in res_a:
        sa, sb = res_a[name], res_b[name]
        dims = []
        for da, db in zip(sa.shape, sb.shape):
            if da == db:
                dims.append(da)
            else:
                # dim co-varied with the probe → batch-dependent → Unknown
                dims.append(Unknown)
        shape = Shape(dims)
        if name in hints:
            shape = shape.refine(Shape.from_any(hints[name]))
        outputs.append(TensorSpec(name, dt.from_numpy(sa.dtype), shape))
    # keep fetch order where given
    by_name = {o.name: o for o in outputs}
    ordered = [by_name[n] for n in order if n in by_name] + [
        o for o in outputs if o.name not in order
    ]
    return Program(program.fn, program.inputs, ordered, order)


# ---------------------------------------------------------------------------
# Ingestion form (a): plain Python functions
# ---------------------------------------------------------------------------

def program_from_function(
    fn: Callable,
    input_specs: Dict[str, TensorSpec],
    output_names: Optional[Sequence[str]] = None,
) -> Program:
    """Wrap a Python function whose positional args are column names.

    The function receives one array per parameter (parameter name = input
    name) and returns either a dict name→array or a single array / tuple —
    singles are named after ``output_names`` (or the function's name).
    Closure-captured arrays are compile-time constants, playing the role of
    the reference's frozen variables (core.py:42-56).
    """
    import inspect

    sig = inspect.signature(fn)
    params = [p.name for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)]
    missing = [p for p in params if p not in input_specs]
    if missing:
        raise ValueError(
            f"Function parameter(s) {missing} do not match any known input; "
            f"available: {sorted(input_specs)}"
        )
    inputs = [input_specs[p] for p in params]

    def wrapped(feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        res = fn(*[feeds[p] for p in params])
        if isinstance(res, dict):
            return res
        if isinstance(res, (tuple, list)):
            names = output_names or [f"{fn.__name__}_{i}" for i in range(len(res))]
            if len(names) != len(res):
                raise ValueError(
                    f"Function returned {len(res)} outputs but "
                    f"{len(names)} output names were given"
                )
            return dict(zip(names, res))
        name = (output_names or [fn.__name__])[0]
        return {name: res}

    return Program(wrapped, inputs, fetch_order=list(output_names or []))


# ---------------------------------------------------------------------------
# Ingestion form (c): serialized StableHLO artifacts (jax.export)
# ---------------------------------------------------------------------------

def save_program(program: Program, path: str, batch: int = 8) -> None:
    """Serialize a Program to a StableHLO artifact on disk
    (≙ writing ``proto.pb``, core.py:58-69). Unknown dims are exported as
    symbolic dimensions so the artifact stays batch-polymorphic."""
    from jax import export as jax_export

    names = [s.name for s in program.inputs]
    scopes = jax_export.SymbolicScope()
    args = []
    for s in program.inputs:
        dims = tuple(
            jax_export.symbolic_shape(f"b{i}", scope=scopes)[0]
            if d == Unknown
            else d
            for i, d in enumerate(s.shape.dims)
        )
        args.append(jax.ShapeDtypeStruct(dims, s.dtype.np_dtype))

    def positional(*xs):
        return program.fn(dict(zip(names, xs)))

    exported = jax_export.export(jax.jit(positional))(*args)
    blob = exported.serialize()
    meta = {
        "inputs": [(s.name, s.dtype.name, list(s.shape.dims)) for s in program.inputs],
        "fetch_order": program.fetch_order,
    }
    import json

    with open(path, "wb") as f:
        header = json.dumps(meta).encode("utf-8")
        f.write(len(header).to_bytes(8, "little"))
        f.write(header)
        f.write(blob)


def load_program(path: str) -> Program:
    """Load a serialized Program (≙ ``graphFromFile``,
    PythonInterface.scala:115-118)."""
    import json

    from jax import export as jax_export

    with open(path, "rb") as f:
        hlen = int.from_bytes(f.read(8), "little")
        meta = json.loads(f.read(hlen).decode("utf-8"))
        blob = f.read()
    exported = jax_export.deserialize(bytearray(blob))
    names = [n for (n, _, _) in meta["inputs"]]
    inputs = [
        TensorSpec(n, dt.by_name(t), Shape(dims)) for (n, t, dims) in meta["inputs"]
    ]

    def fn(feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        return exported.call(*[feeds[n] for n in names])

    return Program(fn, inputs, fetch_order=meta.get("fetch_order"))
