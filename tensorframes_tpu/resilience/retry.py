"""Retry policies for host-side IO and device-put paths.

The reference rode on Spark's task retry; here the host driver owns the
policy. :class:`RetryPolicy` is a small value object — attempt budget,
exponential backoff with deterministic jitter, optional per-attempt
watchdog timeout, and a retryable-exception classification — and
:func:`retry_call` / :func:`retryable` apply it to any callable.

The watchdog timeout runs the attempt on a daemon worker thread and
abandons it when the deadline passes (Python cannot safely interrupt an
arbitrary blocked call); the abandoned attempt may still complete in the
background, so callers must only guard **idempotent** operations with a
timeout — exactly the checkpoint-write / device-transfer / filesystem
calls this package wires it into.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from functools import wraps
from typing import Callable, Optional, Tuple, Type

from ..observability import flight as _flight
from ..observability.metrics import counter as _counter
from ..utils import get_logger

logger = get_logger(__name__)

# Retry telemetry (registered at import so an exposition always carries
# the family): attempts counts each failed-then-rescheduled attempt,
# exhaustions each RetryError, backoff-seconds the total sleep the
# policy injected — retries that silently absorb a flaky disk are now
# a graph, not a debug log.
_RETRY_ATTEMPTS = _counter(
    "tftpu_retry_attempts_total",
    "Failed attempts that were backed off and rescheduled",
)
_RETRY_EXHAUSTIONS = _counter(
    "tftpu_retry_exhaustions_total",
    "retry_call budgets exhausted (RetryError raised)",
)
_RETRY_BACKOFF_SECONDS = _counter(
    "tftpu_retry_backoff_seconds_total",
    "Total backoff sleep injected between retry attempts",
)


class AttemptTimeout(TimeoutError):
    """A single attempt exceeded the policy's per-attempt timeout."""


class RetryError(RuntimeError):
    """All attempts exhausted; ``__cause__`` is the last failure."""


#: Exceptions that are transient by default: filesystem/network wobble
#: and watchdog timeouts. Everything else (ValueError, corruption
#: errors, …) is a real bug and must propagate on the first attempt.
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    OSError,
    ConnectionError,
    TimeoutError,
)


@dataclass(frozen=True)
class RetryPolicy:
    """How to retry a transient failure.

    ``backoff`` is the first sleep; attempt *k* sleeps
    ``min(backoff * 2**(k-1), backoff_max) * (1 + U[0, jitter))``. With
    the default ``seed=None`` the jitter PRNG seeds from OS entropy per
    call, so a fleet of workers sharing one policy gets **decorrelated**
    backoff (no thundering herd on the coordinator redial). Pass an
    explicit ``seed`` for deterministic drill schedules. ``timeout``
    (seconds) arms the per-attempt watchdog; ``None`` disables it.
    ``deadline_s`` caps the **total elapsed** wall-clock of the whole
    ``retry_call`` — attempts, backoff sleeps and watchdog waits all
    included: a per-attempt watchdog alone lets a flaky coordinator
    stretch ``init_distributed`` to attempts × (timeout + backoff),
    while a deadline makes the budget a wall-clock promise. The running
    attempt's watchdog window and every backoff sleep are clipped to
    the remaining budget; exhaustion raises :class:`RetryError` naming
    the deadline.
    """

    max_attempts: int = 3
    backoff: float = 0.05
    backoff_max: float = 2.0
    jitter: float = 0.25
    timeout: Optional[float] = None
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE
    seed: Optional[int] = None
    deadline_s: Optional[float] = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.backoff < 0 or self.backoff_max < 0 or self.jitter < 0:
            raise ValueError("backoff, backoff_max and jitter must be >= 0")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {self.deadline_s}")

    def delay(self, attempt: int, rng) -> float:
        """Sleep before retry number ``attempt`` (1-based)."""
        base = min(self.backoff * (2.0 ** (attempt - 1)), self.backoff_max)
        return base * (1.0 + rng.random() * self.jitter)

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable)


class WatchdogExpired(Exception):
    """Internal sentinel from :func:`run_abandonable`: the call outlived
    its window. Deliberately NOT a ``TimeoutError`` — callers translate
    it into their own surface (:class:`AttemptTimeout` here,
    ``HungDispatchError`` in the fleet watchdog) and must never confuse
    it with a timeout the wrapped function itself raised."""


def run_abandonable(fn: Callable, args, kwargs, timeout: float,
                    thread_name: str = "tfs-watchdog-attempt"):
    """Run ``fn(*args, **kwargs)`` on a daemon thread, waiting at most
    ``timeout`` seconds: the ONE abandon-path primitive shared by the
    per-attempt retry watchdog and the fleet dispatch-deadline watchdog.
    On expiry raises :class:`WatchdogExpired`; the attempt keeps running
    on its thread (Python cannot safely interrupt an arbitrary blocked
    call), so only idempotent operations belong under it. The wrapped
    function's own exceptions re-raise on the caller thread unchanged."""
    outcome: dict = {}
    done = threading.Event()

    def attempt():
        try:
            outcome["value"] = fn(*args, **kwargs)
        except BaseException as e:  # re-raised on the caller thread
            outcome["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=attempt, daemon=True, name=thread_name)
    t.start()
    if not done.wait(timeout):
        raise WatchdogExpired(timeout)
    if "error" in outcome:
        raise outcome["error"]
    return outcome["value"]


def _run_with_watchdog(fn: Callable, args, kwargs, timeout: float):
    """Run ``fn`` on a worker thread; raise :class:`AttemptTimeout` if it
    outlives ``timeout`` seconds (the attempt is abandoned, not killed)."""
    try:
        return run_abandonable(
            fn, args, kwargs, timeout, thread_name="tfs-retry-attempt"
        )
    except WatchdogExpired:
        raise AttemptTimeout(
            f"attempt still running after {timeout:.3g}s (abandoned)"
        ) from None


def retry_call(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    describe: Optional[str] = None,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)`` under ``policy``.

    ``policy=None`` means **no retries** — a plain call — so call sites
    can thread an optional policy straight through without branching
    (and a user who never opted in can never get surprise re-execution).
    Retryable failures (per ``policy.retryable``) are logged, backed off
    and re-attempted; non-retryable ones propagate immediately. When the
    attempt budget runs out, :class:`RetryError` raises ``from`` the last
    failure. ``on_retry(attempt, exc)`` observes each scheduled retry
    (drill hooks / metrics).
    """
    import random

    if policy is None:
        return fn(*args, **kwargs)
    rng = random.Random(policy.seed)
    name = describe or getattr(fn, "__qualname__", repr(fn))
    t_start = time.monotonic()

    def remaining() -> Optional[float]:
        if policy.deadline_s is None:
            return None
        return policy.deadline_s - (time.monotonic() - t_start)

    last: Optional[BaseException] = None
    deadline_hit = False
    for attempt in range(1, policy.max_attempts + 1):
        try:
            rem = remaining()
            if rem is not None and rem <= 0:
                deadline_hit = True
                break
            # the attempt's watchdog window never outlives the total
            # deadline: a blocked attempt is abandoned the instant the
            # budget runs out, not at its own (later) timeout
            window = policy.timeout
            if rem is not None:
                window = rem if window is None else min(window, rem)
            if window is not None:
                return _run_with_watchdog(fn, args, kwargs, window)
            return fn(*args, **kwargs)
        except BaseException as e:
            if not policy.is_retryable(e):
                raise
            last = e
            if attempt == policy.max_attempts:
                break
            rem = remaining()
            if rem is not None and rem <= 0:
                deadline_hit = True
                break
            delay = policy.delay(attempt, rng)
            if rem is not None:
                delay = min(delay, rem)
            _RETRY_ATTEMPTS.inc()
            _RETRY_BACKOFF_SECONDS.inc(delay)
            _flight.record(
                "retry", site=name, attempt=attempt,
                max_attempts=policy.max_attempts,
                error=type(e).__name__, message=str(e),
                backoff_s=round(delay, 4),
            )
            logger.warning(
                "retry %s: attempt %d/%d failed (%s: %s); retrying in %.3fs",
                name, attempt, policy.max_attempts, type(e).__name__, e, delay,
            )
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                time.sleep(delay)
    deadline_hit = deadline_hit or (
        policy.deadline_s is not None
        and time.monotonic() - t_start >= policy.deadline_s
    )
    _RETRY_EXHAUSTIONS.inc()
    _flight.record(
        "retry.exhausted", site=name, max_attempts=policy.max_attempts,
        deadline_s=policy.deadline_s if deadline_hit else None,
        error=type(last).__name__ if last else None,
        message=str(last) if last else None,
    )
    if deadline_hit:
        raise RetryError(
            f"{name}: deadline_s={policy.deadline_s:g} exceeded after "
            f"{time.monotonic() - t_start:.2f}s (gave up at attempt "
            f"{attempt}/{policy.max_attempts})"
        ) from last
    raise RetryError(
        f"{name}: all {policy.max_attempts} attempts failed"
    ) from last


def retryable(policy: Optional[RetryPolicy] = None, **policy_kwargs):
    """Decorator form: ``@retryable(max_attempts=5)`` or
    ``@retryable(policy)``. The wrapped function keeps its signature."""
    if policy is not None and policy_kwargs:
        raise ValueError("pass either a RetryPolicy or keyword fields, not both")
    pol = policy or RetryPolicy(**policy_kwargs)

    def deco(fn: Callable) -> Callable:
        @wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, policy=pol, **kwargs)

        wrapped.retry_policy = pol  # type: ignore[attr-defined]
        return wrapped

    return deco
