"""Fleet launcher: spawn ranks, reap crashes, coordinated teardown,
restart-and-resume.

``supervise()`` owns a multi-process run end-to-end the way the
reference's cluster manager owned Spark executors — except the recovery
unit here is the **whole fleet**: a single-controller SPMD program
cannot lose one rank and continue, so any rank's death (crash, SIGKILL,
wedged-heartbeat) triggers a coordinated abort of the survivors and a
full restart. Convergence is delegated to the checkpoint subsystem:
workers that drive :func:`~tensorframes_tpu.training.run_resumable`
resume from the latest intact CRC-verified step (``restore_latest``,
PR 1) with deterministic batch replay, so a ``kill -9`` of any rank
mid-run converges to the same final state as an uninterrupted run —
the property tests/test_fleet.py asserts bit-for-bit.

Lifecycle per incarnation:

1. **clear** stale heartbeats/abort/barrier files from the rendezvous
   dir (a leftover abort signal must not kill the new attempt at birth);
2. **spawn** ``num_processes`` ranks with
   :func:`~tensorframes_tpu.observability.context.child_env` identity
   (shared ``TFTPU_RUN_ID``, per-rank ``TFTPU_PROCESS_INDEX``) plus
   ``TFTPU_FLEET_DIR`` / ``TFTPU_NUM_PROCESSES`` /
   ``TFTPU_FLEET_ATTEMPT`` / ``TFTPU_FLIGHT_DIR`` — so every child
   heartbeats, monitors, and spools its black box without bespoke code;
3. **watch**: reap exits, and declare a still-running rank dead when
   its published heartbeat goes stale past the timeout. Stale-beat
   detection catches **whole-process** stalls (SIGSTOP, swap death, a
   wedged interpreter) — a rank blocked inside an XLA collective keeps
   beating from its daemon thread, so hung-*collective* recovery comes
   from the dispatch-deadline watchdog (``configure(
   dispatch_deadline_s=)``), which converts the hang into an abort exit
   this loop reaps; arm it whenever hung-rank coverage matters;
4. on failure: **signal the coordinated abort**, give survivors a grace
   window to die cleanly (their monitors see the signal and exit
   :data:`~tensorframes_tpu.resilience.fleet.ABORT_EXIT_CODE`), then
   escalate SIGTERM → SIGKILL; **restart** up to ``max_restarts`` times,
   recording ``tftpu_fleet_restarts_total`` and the detection→respawn
   wall-clock in ``tftpu_fleet_recovery_seconds``.

Exceeding the restart budget raises :class:`SuperviseError` carrying the
full per-attempt exit-code history.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Union

from ..config import get_config
from ..observability import context as _context
from ..observability import flight as _flight
from ..utils import get_logger
from . import fleet as _fleet

# The supervisor's view rides the same tftpu_fleet_* instruments
# fleet.py registers at import — ONE definition each (help text and
# buckets cannot drift between the two halves of the subsystem).
from .fleet import (
    ALIVE_RANKS as _ALIVE_RANKS,
    DEAD_RANKS as _DEAD_RANKS,
    MISSED_BEATS as _MISSED_BEATS,
    RECOVERY_SECONDS as _RECOVERY_SECONDS,
    RESTARTS as _RESTARTS,
)

logger = get_logger(__name__)

__all__ = ["RankFailure", "SuperviseResult", "SuperviseError", "supervise"]

Cmd = Union[Sequence[str], Callable[[int], Sequence[str]]]


@dataclass
class RankFailure:
    """What took an incarnation down."""

    rank: int
    reason: str
    #: "exit" (nonzero rc), "signal" (killed), "heartbeat" (wedged),
    #: "abort" (a rank signalled the coordinated abort first)
    kind: str


@dataclass
class SuperviseResult:
    """Outcome of one :func:`supervise` call."""

    ok: bool
    #: fleet incarnations launched (1 = no restart was needed)
    attempts: int
    restarts: int
    #: per-incarnation ``{rank: returncode}`` (negative = -signal)
    exit_codes: List[Dict[int, int]]
    failures: List[RankFailure]
    #: total failure-detection → fleet-respawned seconds across restarts
    recovery_seconds: float
    rendezvous_dir: str
    run_id: str


class SuperviseError(_fleet.FleetError):
    """The restart budget ran out; ``result`` holds the full history."""

    def __init__(self, message: str, result: SuperviseResult):
        super().__init__(message)
        self.result = result


def _spawn_fleet(
    cmd: Cmd,
    num_processes: int,
    *,
    run_id: str,
    rendezvous_dir: str,
    flight_dir: str,
    flight_explicit: bool,
    attempt: int,
    env: Optional[Dict[str, str]],
    inherit_env: bool,
) -> Dict[int, subprocess.Popen]:
    procs: Dict[int, subprocess.Popen] = {}
    try:
        for i in range(num_processes):
            e = dict(os.environ) if inherit_env else {}
            if env:
                e.update(env)
            e.update(_context.child_env(i))
            e["TFTPU_RUN_ID"] = run_id
            e["TFTPU_FLEET_DIR"] = rendezvous_dir
            e["TFTPU_NUM_PROCESSES"] = str(num_processes)
            e["TFTPU_FLEET_ATTEMPT"] = str(attempt)
            if flight_explicit:
                # the caller named a black-box destination: it wins over
                # an inherited TFTPU_FLIGHT_DIR (e.g. CI arming the
                # pytest session's own spool)
                e["TFTPU_FLIGHT_DIR"] = flight_dir
            else:
                e.setdefault("TFTPU_FLIGHT_DIR", flight_dir)
            argv = list(cmd(i)) if callable(cmd) else list(cmd)
            procs[i] = subprocess.Popen(argv, env=e)
    except BaseException:
        # a later rank failed to spawn (cmd(i) raised, ENOMEM, …): the
        # already-running ranks must not be orphaned to train
        # unsupervised — kill and reap them before propagating
        for p in procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # pragma: no cover - best-effort reap
                pass
        raise
    return procs


def _teardown(
    procs: Dict[int, subprocess.Popen], grace_s: float
) -> Dict[int, int]:
    """Reap every rank: wait out the grace window (monitors that saw the
    abort signal exit on their own, with their final heartbeat and
    postmortem intact), then SIGTERM, then SIGKILL."""
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline and any(
        p.poll() is None for p in procs.values()
    ):
        time.sleep(0.02)
    for p in procs.values():
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and any(
        p.poll() is None for p in procs.values()
    ):
        time.sleep(0.02)
    for p in procs.values():
        if p.poll() is None:  # pragma: no cover - stuck in uninterruptible IO
            p.kill()
    return {i: p.wait() for i, p in procs.items()}


def supervise(
    cmd: Cmd,
    num_processes: int,
    *,
    rendezvous_dir: Optional[str] = None,
    max_restarts: int = 2,
    heartbeat_timeout_s: Optional[float] = None,
    poll_s: float = 0.05,
    grace_s: float = 3.0,
    env: Optional[Dict[str, str]] = None,
    inherit_env: bool = True,
    run_id: Optional[str] = None,
    flight_dir: Optional[str] = None,
) -> SuperviseResult:
    """Launch and supervise a ``num_processes``-rank fleet running
    ``cmd`` (one argv for every rank, or ``cmd(rank) -> argv``).

    Blocks until the fleet finishes clean (every rank exits 0) —
    returning the :class:`SuperviseResult` — or the restart budget is
    exhausted (:class:`SuperviseError`). Any rank exiting nonzero, dying
    to a signal, or letting its heartbeat go stale past
    ``heartbeat_timeout_s`` fails the incarnation: survivors are torn
    down via the coordinated abort + grace + SIGTERM/SIGKILL ladder and
    the whole fleet restarts (resume-from-checkpoint is the workers'
    side of the contract, via ``run_resumable``). Heartbeat staleness
    detects whole-process stalls; a rank wedged *inside a collective*
    still beats — pair supervision with
    ``configure(dispatch_deadline_s=)`` so hangs become abort exits
    this loop can see. ``rendezvous_dir``
    defaults to a fresh temp dir; children's flight-recorder black
    boxes spool under ``flight_dir`` (default ``<rendezvous>/flight``)
    for ``read_blackbox()`` after the dust settles."""
    if num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    rendezvous_dir = rendezvous_dir or tempfile.mkdtemp(prefix="tftpu-fleet-")
    os.makedirs(rendezvous_dir, exist_ok=True)
    run = run_id or _context.run_id()
    flight_explicit = flight_dir is not None
    flight_dir = flight_dir or os.path.join(rendezvous_dir, "flight")
    timeout = (
        get_config().heartbeat_timeout_s
        if heartbeat_timeout_s is None else float(heartbeat_timeout_s)
    )
    restarts = 0
    attempts = 0
    recovery_total = 0.0
    t_detect: Optional[float] = None
    exit_codes: List[Dict[int, int]] = []
    failures: List[RankFailure] = []
    while True:
        attempts += 1
        _fleet.clear_fleet(rendezvous_dir, run)
        procs = _spawn_fleet(
            cmd, num_processes, run_id=run, rendezvous_dir=rendezvous_dir,
            flight_dir=flight_dir, flight_explicit=flight_explicit,
            attempt=attempts - 1, env=env, inherit_env=inherit_env,
        )
        if t_detect is not None:
            # recovery = failure detection → fleet RESPAWNED (teardown
            # + clear + spawn), measured here so the histogram matches
            # its help string — the respawn cost is the dominant term
            recovery = time.monotonic() - t_detect
            t_detect = None
            recovery_total += recovery
            _RECOVERY_SECONDS.observe(recovery)
            logger.warning(
                "supervise: fleet respawned %.2fs after failure "
                "detection", recovery,
            )
        logger.info(
            "supervise: attempt %d — %d rank(s) up in %s",
            attempts, num_processes, rendezvous_dir,
        )
        failure: Optional[RankFailure] = None
        exited: Dict[int, int] = {}
        while failure is None and len(exited) < num_processes:
            time.sleep(poll_s)
            _ALIVE_RANKS.set(
                sum(1 for p in procs.values() if p.poll() is None)
            )
            for i, p in procs.items():
                if i in exited:
                    continue
                rc = p.poll()
                if rc is None:
                    continue
                exited[i] = rc
                if rc == 0:
                    continue
                if rc == _fleet.ABORT_EXIT_CODE:
                    # a deliberate coordinated abort: the CAUSE is in
                    # the abort record (usually another rank's death),
                    # not this messenger
                    ab = _fleet.abort_requested(rendezvous_dir, run) or {}
                    blamed = (ab.get("ranks") or [i])
                    failure = RankFailure(
                        rank=int(blamed[0]) if blamed else i,
                        reason=(
                            f"coordinated abort (signalled by rank "
                            f"{ab.get('by', i)}): "
                            f"{ab.get('reason', 'no record')}"
                        ),
                        kind="abort",
                    )
                elif rc < 0:
                    failure = RankFailure(
                        rank=i, reason=f"rank {i} killed by signal {-rc}",
                        kind="signal",
                    )
                else:
                    failure = RankFailure(
                        rank=i, reason=f"rank {i} exited rc={rc}",
                        kind="exit",
                    )
                break
            if failure is not None:
                break
            # heartbeat watch: a rank can be alive as a process and dead
            # as a participant (wedged in a collective, spinning in C).
            # Only ranks that have PUBLISHED at least one beat are
            # judged — a worker that never enrolls is supervised by
            # exit code alone.
            try:
                beats = _fleet.read_heartbeats(rendezvous_dir, run)
            except OSError:  # pragma: no cover - transient fs wobble
                beats = {}
            now = time.time()
            for i, rec in beats.items():
                if i in exited or i not in procs or rec.get("stopped"):
                    continue
                age = now - float(rec.get("ts", now))
                if age > timeout:
                    _flight.record(
                        "fleet.heartbeat_lost", rank=i,
                        age_s=round(age, 3), timeout_s=timeout,
                    )
                    _MISSED_BEATS.inc()
                    failure = RankFailure(
                        rank=i,
                        reason=(
                            f"rank {i} heartbeat stale for {age:.2f}s "
                            f"(timeout {timeout:g}s)"
                        ),
                        kind="heartbeat",
                    )
                    break
        if failure is None:
            exit_codes.append(exited)
            _ALIVE_RANKS.set(0)
            logger.info(
                "supervise: fleet finished clean after %d attempt(s) "
                "(%d restart(s))", attempts, restarts,
            )
            return SuperviseResult(
                ok=True, attempts=attempts, restarts=restarts,
                exit_codes=exit_codes, failures=failures,
                recovery_seconds=recovery_total,
                rendezvous_dir=rendezvous_dir, run_id=run,
            )
        t_detect = time.monotonic()
        failures.append(failure)
        _DEAD_RANKS.inc()
        _flight.record(
            "fleet.rank_dead", rank=failure.rank, reason=failure.reason,
            failure_kind=failure.kind, attempt=attempts,
        )
        logger.error("supervise: %s", failure.reason)
        _fleet.signal_abort(
            rendezvous_dir, failure.reason, dead_ranks=[failure.rank],
            run_id=run,
        )
        final = _teardown(procs, grace_s)
        final.update(exited)
        exit_codes.append(final)
        _ALIVE_RANKS.set(0)
        if restarts >= max_restarts:
            result = SuperviseResult(
                ok=False, attempts=attempts, restarts=restarts,
                exit_codes=exit_codes, failures=failures,
                recovery_seconds=recovery_total,
                rendezvous_dir=rendezvous_dir, run_id=run,
            )
            raise SuperviseError(
                f"fleet failed {attempts} time(s) (restart budget "
                f"{max_restarts} exhausted); last failure: "
                f"{failure.reason}",
                result,
            )
        restarts += 1
        _RESTARTS.inc()
        _flight.record(
            "fleet.restart", attempt=attempts + 1, after=failure.reason,
        )
        logger.warning(
            "supervise: restarting fleet (attempt %d/%d)",
            attempts + 1, max_restarts + 1,
        )
