"""Training-step guards: detect non-finite losses/states and recover.

A diverged step on TPU does not crash — it silently poisons every
parameter with NaN and the run burns accelerator-hours producing
garbage. :class:`StepGuard` is the host-side tripwire: after each
``step_fn`` the driver hands it the candidate state and metrics, and it
either admits the update, **skips** it (keep the pre-step state),
**rolls back** to the last known-good snapshot, or **raises**
:class:`NonFiniteError`. ``training.run_resumable(guard=...)`` wires it
into the loop; pass a policy string (``"skip"`` / ``"rollback"`` /
``"raise"``) or a configured instance.

The finiteness check materializes float leaves to host, which
synchronizes the device stream — that is the price of detection. Use
``check="metrics"`` to inspect only the (small) metrics pytree when the
loss alone is a good enough canary, or ``every_n`` to amortize.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from ..observability import flight as _flight
from ..observability.metrics import counter as _counter
from ..utils import get_logger

logger = get_logger(__name__)

_POLICIES = ("raise", "skip", "rollback")
_CHECKS = ("metrics", "state", "both")

# One trip counter per policy, pre-registered so the exposition always
# carries all three series (a run that never tripped reads 0 everywhere
# instead of omitting the family a dashboard alerts on).
_TRIP_COUNTERS = {
    p: _counter(
        "tftpu_guard_trips_total",
        "Non-finite training steps caught by StepGuard, by policy",
        labels={"policy": p},
    )
    for p in _POLICIES
}


class NonFiniteError(FloatingPointError):
    """A training step produced NaN/Inf and the guard policy is to stop."""


def _array_finite(arr: np.ndarray) -> bool:
    if arr.dtype == object:
        return True
    if np.issubdtype(arr.dtype, np.floating) or np.issubdtype(
        arr.dtype, np.complexfloating
    ):
        return bool(np.isfinite(arr).all())
    if arr.dtype.kind == "V":  # ml_dtypes (bfloat16, float8…)
        return bool(np.isfinite(arr.astype(np.float32)).all())
    return True  # ints/bools vacuously finite


def tree_all_finite(tree: Any) -> bool:
    """True when every floating/complex leaf of ``tree`` is finite.

    Integer, bool and non-array leaves pass vacuously. Device arrays are
    pulled to host (synchronizing) — call this off the step's critical
    path or accept the sync. Multi-host global arrays are checked over
    THIS process's addressable shards (no single process can materialize
    the global array; NaN spreads through the collectives, so a local
    check still trips). Materialization failures propagate — a guard
    that silently treats an uncheckable leaf as finite is no guard.
    """
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            for shard in leaf.addressable_shards:
                if not _array_finite(np.asarray(shard.data)):
                    return False
            continue
        try:
            arr = np.asarray(leaf)
        except (TypeError, ValueError):
            continue  # genuinely non-array leaf (e.g. a string metric)
        if not _array_finite(arr):
            return False
    return True


class StepGuard:
    """Admission control for training-step updates.

    ``policy``:

    * ``"raise"`` — any non-finite step raises :class:`NonFiniteError`.
    * ``"skip"`` — discard the bad update, keep the pre-step state, and
      keep consuming batches (a poison batch costs one step, not a run).
    * ``"rollback"`` — revert to the last admitted-good snapshot (jax
      arrays are immutable, so snapshots are reference-kept, not
      copied). With ``snapshot_every > 1`` the snapshot may trail by up
      to that many steps — cheaper bookkeeping, coarser recovery.

    ``max_consecutive`` bad steps escalate to :class:`NonFiniteError`
    under every policy: a persistently-diverged run must stop, not spin.
    ``check`` selects what is inspected (``"metrics"``, ``"state"``, or
    ``"both"``); ``every_n`` inspects only every n-th step.

    Counters (``admitted``, ``skipped``, ``rollbacks``) are public for
    drills and ``on_step`` telemetry.
    """

    def __init__(
        self,
        policy: str = "rollback",
        check: str = "both",
        max_consecutive: int = 10,
        snapshot_every: int = 1,
        every_n: int = 1,
    ):
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, got {policy!r}")
        if check not in _CHECKS:
            raise ValueError(f"check must be one of {_CHECKS}, got {check!r}")
        if max_consecutive < 1:
            raise ValueError("max_consecutive must be >= 1")
        if snapshot_every < 1 or every_n < 1:
            raise ValueError("snapshot_every and every_n must be >= 1")
        self.policy = policy
        self.check = check
        self.max_consecutive = max_consecutive
        self.snapshot_every = snapshot_every
        self.every_n = every_n
        self.admitted = 0
        self.skipped = 0
        self.rollbacks = 0
        self._bad_streak = 0
        self._good_state: Any = None
        self._good_step: Optional[int] = None

    @classmethod
    def coerce(cls, guard) -> "StepGuard":
        """``"skip"`` → ``StepGuard(policy="skip")``; instances pass through."""
        if isinstance(guard, cls):
            return guard
        if isinstance(guard, str):
            return cls(policy=guard)
        raise TypeError(
            f"guard must be a StepGuard or policy string {_POLICIES}, "
            f"got {type(guard).__name__}"
        )

    def seed(self, step: int, state: Any) -> None:
        """Register a known-good baseline (the restored checkpoint), so a
        rollback before the first admitted step has somewhere to land."""
        self._good_state = state
        self._good_step = step

    def _is_bad(self, state: Any, metrics: Any) -> bool:
        if self.check in ("metrics", "both") and not tree_all_finite(metrics):
            return True
        if self.check in ("state", "both") and not tree_all_finite(state):
            return True
        return False

    def admit(
        self, step: int, new_state: Any, metrics: Any, prev_state: Any
    ) -> Tuple[Any, bool]:
        """Inspect the candidate update for step ``step``.

        Returns ``(state_to_continue_with, admitted)``. Raises
        :class:`NonFiniteError` under the ``"raise"`` policy or after
        ``max_consecutive`` bad steps.
        """
        if self.every_n > 1 and step % self.every_n != 0:
            self.admitted += 1
            return new_state, True
        if not self._is_bad(new_state, metrics):
            self.admitted += 1
            self._bad_streak = 0
            if self.policy == "rollback" and step % self.snapshot_every == 0:
                self._good_state = new_state
                self._good_step = step
            return new_state, True

        self._bad_streak += 1
        _TRIP_COUNTERS[self.policy].inc()
        _flight.record(
            "guard.trip", policy=self.policy, step=step,
            streak=self._bad_streak, max_consecutive=self.max_consecutive,
        )
        if self.policy == "raise" or self._bad_streak >= self.max_consecutive:
            err = NonFiniteError(
                f"non-finite loss/state at step {step} "
                f"({self._bad_streak} consecutive; policy={self.policy!r})"
            )
            # guard-raise is one of the flight recorder's dump triggers:
            # the black box written here carries the dispatches/steps
            # that led into divergence, even if the caller catches the
            # error and the process never "crashes"
            _flight.dump(reason="guard-raise", exc=err)
            raise err
        if self.policy == "skip":
            self.skipped += 1
            logger.warning(
                "StepGuard: non-finite step %d skipped (streak %d/%d)",
                step, self._bad_streak, self.max_consecutive,
            )
            return prev_state, False
        # rollback
        self.rollbacks += 1
        target = self._good_state if self._good_state is not None else prev_state
        logger.warning(
            "StepGuard: non-finite step %d rolled back to step %s "
            "(streak %d/%d)",
            step, self._good_step, self._bad_streak, self.max_consecutive,
        )
        return target, False
