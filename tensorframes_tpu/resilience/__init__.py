"""Resilience subsystem: fault injection, retry policies, step guards.

The reference delegated every failure mode to Spark task retry/lineage
(SURVEY.md §5: "Failure detection / elastic recovery: none in-repo"). A
TPU-native runtime has no JVM scheduler underneath it, so this package
re-owns those guarantees explicitly, DrJAX-style — failure semantics
live in the host driver, not the compiled program:

* :mod:`~tensorframes_tpu.resilience.faults` — a deterministic, seedable
  fault-injection registry. Production code is instrumented with named
  ``fault_point(site)`` hooks (executor block execution, prefetch
  device_put, frame save/load, checkpoint save/restore, distributed
  init); tests and drills turn faults on with the ``inject()`` context
  manager. Zero overhead when no injection is active.
* :mod:`~tensorframes_tpu.resilience.retry` — configurable retry
  policies (max attempts, exponential backoff + deterministic jitter,
  per-attempt watchdog timeout, retryable-exception classification)
  for host-side IO and device-put paths.
* :mod:`~tensorframes_tpu.resilience.guards` — training-step guards
  that detect non-finite losses / states and skip the step, roll back
  to the last good state, or raise; plugged into
  ``training.run_resumable(guard=...)``.
* :mod:`~tensorframes_tpu.resilience.fleet` — fleet supervision for
  multi-process runs: heartbeat publishing into a shared rendezvous dir,
  dead-rank/straggler detection, a hung-collective dispatch-deadline
  watchdog (``configure(dispatch_deadline_s=)``), a bounded rendezvous
  ``barrier``, and the coordinated-abort protocol — a wedged or killed
  rank produces a flight-recorder postmortem naming it, not an
  indefinite collective hang.
* :mod:`~tensorframes_tpu.resilience.supervisor` — ``supervise()``: the
  fleet launcher that spawns ranks with the shared telemetry identity,
  reaps crashes and wedged heartbeats, tears survivors down via the
  coordinated abort, and restarts the run resuming from the latest
  intact checkpoint.

Checkpoint integrity (per-array CRC32 manifests, fsync-before-rename,
corrupted-step fallback) lives in :mod:`tensorframes_tpu.checkpoint`
and is exercised through the fault sites defined here.
"""

from __future__ import annotations

from .faults import (  # noqa: F401
    SITES,
    Delay,
    KillRank,
    active_sites,
    delay_point,
    fault_point,
    inject,
    kill_point,
    list_sites,
    register_site,
    reset,
)
from .guards import NonFiniteError, StepGuard, tree_all_finite  # noqa: F401
from .retry import (  # noqa: F401
    AttemptTimeout,
    RetryError,
    RetryPolicy,
    retry_call,
    retryable,
)
from .fleet import (  # noqa: F401
    ABORT_EXIT_CODE,
    CoordinatedAbortError,
    DeadRankError,
    FleetError,
    FleetMonitor,
    FleetStatus,
    Heartbeater,
    HungDispatchError,
    barrier,
    enroll,
    run_with_deadline,
)
from .supervisor import (  # noqa: F401
    RankFailure,
    SuperviseError,
    SuperviseResult,
    supervise,
)

__all__ = [
    "SITES",
    "active_sites",
    "fault_point",
    "delay_point",
    "kill_point",
    "inject",
    "reset",
    "Delay",
    "KillRank",
    "list_sites",
    "register_site",
    "AttemptTimeout",
    "RetryError",
    "RetryPolicy",
    "retry_call",
    "retryable",
    "NonFiniteError",
    "StepGuard",
    "tree_all_finite",
    "ABORT_EXIT_CODE",
    "FleetError",
    "DeadRankError",
    "HungDispatchError",
    "CoordinatedAbortError",
    "FleetStatus",
    "Heartbeater",
    "FleetMonitor",
    "barrier",
    "enroll",
    "run_with_deadline",
    "supervise",
    "SuperviseResult",
    "SuperviseError",
    "RankFailure",
]
