"""Resilience subsystem: fault injection, retry policies, step guards.

The reference delegated every failure mode to Spark task retry/lineage
(SURVEY.md §5: "Failure detection / elastic recovery: none in-repo"). A
TPU-native runtime has no JVM scheduler underneath it, so this package
re-owns those guarantees explicitly, DrJAX-style — failure semantics
live in the host driver, not the compiled program:

* :mod:`~tensorframes_tpu.resilience.faults` — a deterministic, seedable
  fault-injection registry. Production code is instrumented with named
  ``fault_point(site)`` hooks (executor block execution, prefetch
  device_put, frame save/load, checkpoint save/restore, distributed
  init); tests and drills turn faults on with the ``inject()`` context
  manager. Zero overhead when no injection is active.
* :mod:`~tensorframes_tpu.resilience.retry` — configurable retry
  policies (max attempts, exponential backoff + deterministic jitter,
  per-attempt watchdog timeout, retryable-exception classification)
  for host-side IO and device-put paths.
* :mod:`~tensorframes_tpu.resilience.guards` — training-step guards
  that detect non-finite losses / states and skip the step, roll back
  to the last good state, or raise; plugged into
  ``training.run_resumable(guard=...)``.

Checkpoint integrity (per-array CRC32 manifests, fsync-before-rename,
corrupted-step fallback) lives in :mod:`tensorframes_tpu.checkpoint`
and is exercised through the fault sites defined here.
"""

from __future__ import annotations

from .faults import (  # noqa: F401
    SITES,
    active_sites,
    fault_point,
    inject,
    reset,
)
from .guards import NonFiniteError, StepGuard, tree_all_finite  # noqa: F401
from .retry import (  # noqa: F401
    AttemptTimeout,
    RetryError,
    RetryPolicy,
    retry_call,
    retryable,
)

__all__ = [
    "SITES",
    "active_sites",
    "fault_point",
    "inject",
    "reset",
    "AttemptTimeout",
    "RetryError",
    "RetryPolicy",
    "retry_call",
    "retryable",
    "NonFiniteError",
    "StepGuard",
    "tree_all_finite",
]
