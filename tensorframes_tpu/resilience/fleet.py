"""Fleet supervision: heartbeats, dead-rank detection, hung-collective
watchdogs, and coordinated abort for multi-process runs.

The reference inherited Spark's executor-failure recovery for free — a
lost partition was recomputed from lineage. This stack traded that away
for whole-pipeline native compilation (the Flare trade, arxiv
1703.08219): once ``init_distributed`` finishes its handshake, a
SIGKILLed or wedged rank stalls every collective in ``parallel/``
forever, because XLA's collectives have no peer-death story. This module
is the missing fleet half of the resilience subsystem:

* **Heartbeats** — every enrolled process publishes a small JSON beat
  (stamped with the observability ``run_id``/``process_index`` context)
  into a shared **rendezvous dir** (``TFTPU_FLEET_DIR``;
  :func:`~tensorframes_tpu.resilience.supervisor.supervise` arms it for
  its children) every ``heartbeat_interval_s``. A clean exit leaves a
  final ``stopped`` beat so finished ranks are never mistaken for dead.
* **Monitoring** — :class:`FleetMonitor` (a daemon thread) reads the
  beats and classifies peers: *dead* past ``heartbeat_timeout_s``,
  *straggler* past half of it. :func:`enroll` wires the default policy:
  a detected dead peer (or a peer's abort signal) dumps a
  flight-recorder postmortem naming the missing rank, signals a
  **coordinated abort**, and exits with :data:`ABORT_EXIT_CODE` — a
  bounded, diagnosable death instead of an indefinite collective hang.
* **Hung-dispatch watchdog** — :func:`run_with_deadline` bounds any
  dispatch by ``config.dispatch_deadline_s``
  (``TFTPU_DISPATCH_DEADLINE_S`` / ``configure(dispatch_deadline_s=)``);
  on expiry it records + dumps a ``fleet.hung_dispatch`` postmortem
  naming the stalled dispatch and the unresponsive ranks, signals the
  abort, and raises :class:`HungDispatchError`. ``ops/executor.py``
  wraps every program dispatch with it; ``parallel/distributed.py``
  wraps the coordinator handshake and cross-process frame assembly.
* **Rendezvous barrier** — :func:`barrier` is a file-based fleet
  barrier with the same deadline semantics, for host-side lockstep
  points (run start, checkpoint epochs) where a missing rank must be
  *named*, not waited on.

Everything here is deterministically drillable on CPU subprocess fleets
via the fault sites ``fleet.heartbeat`` (drop-heartbeat),
``fleet.barrier`` (delay-collective), ``executor.dispatch``
(delay-collective at the dispatch itself), and ``fleet.rank.kill``
(kill-rank) — see tests/test_fleet.py and ``dev/resilience_drill.sh``.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set

from ..config import get_config
from ..observability import context as _context
from ..observability import flight as _flight
from ..observability.metrics import counter as _counter
from ..observability.metrics import gauge as _gauge
from ..observability.metrics import histogram as _histogram
from ..utils import get_logger
from . import faults as _faults
from . import retry as _retry

logger = get_logger(__name__)

__all__ = [
    "ABORT_EXIT_CODE",
    "FleetError",
    "DeadRankError",
    "HungDispatchError",
    "CoordinatedAbortError",
    "FleetStatus",
    "Heartbeater",
    "FleetMonitor",
    "FleetMember",
    "rendezvous_dir",
    "write_beat",
    "read_heartbeats",
    "write_json_atomic",
    "read_latest_records",
    "fleet_status",
    "signal_abort",
    "abort_requested",
    "clear_fleet",
    "enroll",
    "current_member",
    "barrier",
    "dispatch_deadline_s",
    "run_with_deadline",
]

#: Exit code of a coordinated abort: a rank that detected a dead peer
#: (or saw the abort signal) and exited deliberately — the supervisor
#: distinguishes it from the crash that caused it.
ABORT_EXIT_CODE = 43

# Fleet telemetry, registered at import (tensorframes_tpu/__init__
# imports the resilience package) so expositions always carry the
# family: a run that never lost a rank reads 0, it does not vanish.
_HEARTBEATS = _counter(
    "tftpu_fleet_heartbeats_total",
    "Heartbeats this process published into the rendezvous dir",
)
_HEARTBEATS_SKIPPED = _counter(
    "tftpu_fleet_heartbeats_skipped_total",
    "Heartbeats dropped (fleet.heartbeat fault injection or beat-write "
    "IO failure)",
)
MISSED_BEATS = _counter(
    "tftpu_fleet_missed_beats_total",
    "Monitor scans that found a peer's newest beat stale (straggler or "
    "dead threshold)",
)
_STRAGGLERS = _counter(
    "tftpu_fleet_stragglers_total",
    "Peer ranks newly flagged as stragglers (beat older than the "
    "straggler threshold, younger than the dead timeout)",
)
DEAD_RANKS = _counter(
    "tftpu_fleet_dead_ranks_total",
    "Peer ranks declared dead (heartbeat older than the timeout, or "
    "process reaped by the supervisor)",
)
_ABORTS = _counter(
    "tftpu_fleet_aborts_total",
    "Coordinated aborts signalled into the rendezvous dir",
)
_HUNG_DISPATCHES = _counter(
    "tftpu_fleet_hung_dispatches_total",
    "Dispatches/barriers that exceeded the dispatch deadline and were "
    "aborted by the watchdog",
)
_DEADLINE_EXEMPTIONS = _counter(
    "tftpu_fleet_deadline_exemptions_total",
    "First dispatches that ran unbounded because their XLA compile "
    "happens lazily inside the call (the executor's counted lazy-jit "
    "fallback — the ONLY exempt class since the unified AOT dispatch; "
    "a nonzero rate in steady state means programs are living on the "
    "fallback path)",
)
RESTARTS = _counter(
    "tftpu_fleet_restarts_total",
    "Full-fleet restarts performed by supervise() after a rank failure",
)
RECOVERY_SECONDS = _histogram(
    "tftpu_fleet_recovery_seconds",
    "Failure-detection → fleet-respawned wall-clock per supervise() "
    "restart",
)
ALIVE_RANKS = _gauge(
    "tftpu_fleet_alive_ranks",
    "Ranks of the supervised fleet currently running (supervisor's view)",
)

_faults.register_site(
    "fleet.heartbeat",
    "Heartbeater beat loop — an injected error drops the beat "
    "(drop-heartbeat chaos: peers must detect the silence)",
)
_faults.register_site(
    "fleet.barrier",
    "fleet.barrier arrival — an injected Delay stalls this rank's "
    "arrival (hung-collective chaos at a rendezvous point)",
)


class FleetError(RuntimeError):
    """Base of the fleet-supervision failure family."""


class DeadRankError(FleetError):
    """One or more peer ranks stopped heartbeating (or were reaped)."""

    def __init__(self, ranks: Sequence[int], message: str):
        super().__init__(message)
        self.ranks = tuple(sorted(int(r) for r in ranks))


class HungDispatchError(FleetError, TimeoutError):
    """A dispatch/barrier exceeded the dispatch deadline. Subclasses
    ``TimeoutError`` so the default retry classification treats it as
    transient (a redial after a fleet restart may succeed)."""


class CoordinatedAbortError(FleetError):
    """A peer signalled the coordinated abort; this rank stops too."""


# ---------------------------------------------------------------------------
# rendezvous dir + heartbeat files
# ---------------------------------------------------------------------------

def rendezvous_dir() -> Optional[str]:
    """The fleet rendezvous directory (``TFTPU_FLEET_DIR``), or None
    when this process is not part of a supervised fleet."""
    return os.environ.get("TFTPU_FLEET_DIR") or None


def _hb_path(directory: str, run_id: str, rank: int) -> str:
    return os.path.join(directory, f"hb_{run_id}_p{rank}.json")


def write_json_atomic(path: str, rec: dict) -> str:
    """Publish one JSON record atomically (tmp-write + rename) — a
    reader never sees a torn record. Shared by heartbeats and the
    serving fleet's replica cards."""
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)
    return path


def read_latest_records(
    directory: str,
    pattern: str,
    run_id: Optional[str] = None,
    *,
    rank_field: str = "process_index",
) -> Dict[int, dict]:
    """The newest record per rank (``{rank: record}``) matching
    ``pattern``, filtered to ``run_id`` when given. Tolerates
    unreadable/foreign files — a monitor must never crash on a
    half-provisioned dir. The ONE tolerant-read used by heartbeats and
    replica cards."""
    out: Dict[int, dict] = {}
    for path in _glob.glob(os.path.join(directory, pattern)):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        try:
            rank = int(rec[rank_field])
        except (KeyError, TypeError, ValueError):
            continue
        if run_id is not None and rec.get("run_id") != run_id:
            continue
        prev = out.get(rank)
        if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
            out[rank] = rec
    return out


def write_beat(
    directory: str,
    *,
    seq: int = 0,
    interval_s: Optional[float] = None,
    stopped: bool = False,
    rank: Optional[int] = None,
) -> str:
    """Atomically publish one heartbeat record (tmp-write + rename, so a
    reader never sees a torn beat). ``stopped=True`` is the clean-exit
    marker: a finished rank must read as departed, not dead."""
    ctx = _context.snapshot()
    rank = ctx["process_index"] if rank is None else int(rank)
    rec = {
        "run_id": ctx["run_id"],
        "process_index": rank,
        "pid": os.getpid(),
        "seq": int(seq),
        "ts": time.time(),
        "interval_s": float(
            get_config().heartbeat_interval_s if interval_s is None
            else interval_s
        ),
        "stopped": bool(stopped),
    }
    os.makedirs(directory, exist_ok=True)
    return write_json_atomic(
        _hb_path(directory, rec["run_id"], rank), rec
    )


def read_heartbeats(
    directory: str, run_id: Optional[str] = None
) -> Dict[int, dict]:
    """The newest published beat per rank (``{rank: record}``), filtered
    to ``run_id`` when given (see :func:`read_latest_records`)."""
    pattern = f"hb_{run_id}_p*.json" if run_id else "hb_*_p*.json"
    return read_latest_records(directory, pattern, run_id)


@dataclass
class FleetStatus:
    """One monitor scan's verdict over the fleet's heartbeats."""

    alive: List[int] = field(default_factory=list)
    stopped: List[int] = field(default_factory=list)
    stragglers: List[int] = field(default_factory=list)
    dead: List[int] = field(default_factory=list)
    #: expected (per ``num_processes``) but never published a beat
    missing: List[int] = field(default_factory=list)
    #: newest-beat age per seen rank, seconds
    ages: Dict[int, float] = field(default_factory=dict)

    def unresponsive(self) -> List[int]:
        """Ranks a hung dispatch should name: dead + missing + stragglers."""
        return sorted(set(self.dead) | set(self.missing) | set(self.stragglers))


def fleet_status(
    directory: str,
    *,
    run_id: Optional[str] = None,
    num_processes: Optional[int] = None,
    timeout_s: Optional[float] = None,
    straggler_s: Optional[float] = None,
    now: Optional[float] = None,
) -> FleetStatus:
    """Classify every rank from its newest beat: ``stopped`` (clean
    final beat), ``dead`` (age > ``timeout_s``), ``straggler``
    (age > ``straggler_s``, default half the timeout), else ``alive``;
    ranks below ``num_processes`` that never published are ``missing``."""
    cfg = get_config()
    timeout_s = cfg.heartbeat_timeout_s if timeout_s is None else timeout_s
    straggler_s = timeout_s / 2.0 if straggler_s is None else straggler_s
    now = time.time() if now is None else now
    beats = read_heartbeats(directory, run_id)
    st = FleetStatus()
    for rank in sorted(beats):
        rec = beats[rank]
        age = max(0.0, now - float(rec.get("ts", 0)))
        st.ages[rank] = age
        if rec.get("stopped"):
            st.stopped.append(rank)
        elif age > timeout_s:
            st.dead.append(rank)
        elif age > straggler_s:
            st.stragglers.append(rank)
        else:
            st.alive.append(rank)
    if num_processes:
        st.missing = sorted(set(range(int(num_processes))) - set(beats))
    return st


# ---------------------------------------------------------------------------
# coordinated abort
# ---------------------------------------------------------------------------

def _abort_path(directory: str, run_id: str) -> str:
    return os.path.join(directory, f"abort_{run_id}.json")


def signal_abort(
    directory: str,
    reason: str,
    *,
    dead_ranks: Sequence[int] = (),
    run_id: Optional[str] = None,
) -> str:
    """Publish the coordinated-abort signal into the rendezvous dir
    (first writer wins — the original cause must not be overwritten by
    the cascade it triggers). Every enrolled rank's monitor, barrier
    wait, and the supervisor react to it."""
    run_id = run_id or _context.run_id()
    os.makedirs(directory, exist_ok=True)
    path = _abort_path(directory, run_id)
    rec = {
        "run_id": run_id,
        "reason": str(reason)[:500],
        "ranks": sorted(int(r) for r in dead_ranks),
        "by": _context.process_index(),
        "pid": os.getpid(),
        "ts": time.time(),
    }
    try:
        with open(path, "x") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        _ABORTS.inc()
        _flight.record(
            "fleet.abort", reason=rec["reason"], ranks=rec["ranks"],
        )
        logger.error("fleet: coordinated abort signalled: %s", reason)
    except FileExistsError:
        pass  # a peer already signalled; theirs is the cause of record
    except OSError as e:  # pragma: no cover - dying filesystem
        logger.warning("fleet: abort signal write failed: %s", e)
    return path


def abort_requested(
    directory: str, run_id: Optional[str] = None
) -> Optional[dict]:
    """The coordinated-abort record, if one has been signalled."""
    run_id = run_id or _context.run_id()
    try:
        with open(_abort_path(directory, run_id)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_fleet(directory: str, run_id: Optional[str] = None) -> int:
    """Remove heartbeat/abort/barrier state for ``run_id`` (every run
    when None) — the supervisor calls it between fleet incarnations so a
    stale abort signal cannot kill the restarted attempt at birth."""
    run_id = run_id or "*"
    removed = 0
    for pattern in (
        f"hb_{run_id}_p*.json",
        f"abort_{run_id}.json",
        f"barrier_{run_id}_*",
    ):
        for path in _glob.glob(os.path.join(directory, pattern)):
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    return removed


# ---------------------------------------------------------------------------
# heartbeat publisher
# ---------------------------------------------------------------------------

class Heartbeater:
    """Daemon thread publishing this process's beat every
    ``interval_s``. The ``fleet.heartbeat`` fault site sits in the loop:
    an injected error drops beats (drop-heartbeat chaos) without harming
    the host process."""

    def __init__(
        self, directory: str, interval_s: Optional[float] = None,
        rank: Optional[int] = None,
    ):
        self.directory = directory
        self.interval_s = float(
            get_config().heartbeat_interval_s if interval_s is None
            else interval_s
        )
        self.rank = (
            _context.process_index() if rank is None else int(rank)
        )
        self.seq = 0
        self.skipped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Heartbeater":
        if self._thread is not None:
            return self
        # first beat synchronously: monitors (and the supervisor) must
        # see this rank the instant enroll() returns, not an interval
        # later
        self.beat_once()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"tfs-heartbeat-p{self.rank}",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat_once()

    def beat_once(self) -> bool:
        """Publish one beat; False when it was dropped (injected fault
        or IO failure — either way the silence is the signal peers see)."""
        try:
            _faults.fault_point("fleet.heartbeat")
            self.seq += 1
            write_beat(
                self.directory, seq=self.seq, interval_s=self.interval_s,
                rank=self.rank,
            )
        except Exception as e:
            self.skipped += 1
            _HEARTBEATS_SKIPPED.inc()
            logger.debug("heartbeat dropped: %s", e)
            return False
        _HEARTBEATS.inc()
        return True

    def stop(self, graceful: bool = True) -> None:
        """Stop beating; ``graceful`` publishes the final ``stopped``
        beat so peers read this rank as departed-clean, not dead."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s * 4 + 1.0)
            self._thread = None
        if graceful:
            try:
                self.seq += 1
                write_beat(
                    self.directory, seq=self.seq,
                    interval_s=self.interval_s, rank=self.rank,
                    stopped=True,
                )
            except OSError as e:  # pragma: no cover - dying filesystem
                logger.debug("final heartbeat failed: %s", e)


# ---------------------------------------------------------------------------
# monitor
# ---------------------------------------------------------------------------

class FleetMonitor:
    """Daemon thread classifying peers from their beats. Callbacks fire
    once per newly-detected condition: ``on_dead(ranks, status)``,
    ``on_straggler(ranks, status)``, ``on_abort(record)``. The monitor
    never judges its own rank (a wedged self cannot usefully self-report;
    peers and the supervisor own that verdict). When ``num_processes``
    is known, a rank that NEVER publishes a beat within
    ``startup_grace_s`` of the monitor's start (default
    ``max(4 × timeout_s, 20s)`` — generous, because peers may still be
    importing jax or loading a model before they enroll) is declared
    dead too: a rank that crashed before its first beat must not be
    invisible just because it never said hello."""

    def __init__(
        self,
        directory: str,
        *,
        run_id: Optional[str] = None,
        num_processes: Optional[int] = None,
        timeout_s: Optional[float] = None,
        straggler_s: Optional[float] = None,
        poll_s: Optional[float] = None,
        self_rank: Optional[int] = None,
        startup_grace_s: Optional[float] = None,
        on_dead: Optional[Callable[[List[int], FleetStatus], None]] = None,
        on_straggler: Optional[Callable[[List[int], FleetStatus], None]] = None,
        on_abort: Optional[Callable[[dict], None]] = None,
    ):
        cfg = get_config()
        self.directory = directory
        self.run_id = run_id or _context.run_id()
        self.num_processes = num_processes
        self.timeout_s = (
            cfg.heartbeat_timeout_s if timeout_s is None else timeout_s
        )
        self.straggler_s = (
            self.timeout_s / 2.0 if straggler_s is None else straggler_s
        )
        self.poll_s = (
            cfg.heartbeat_interval_s if poll_s is None else poll_s
        )
        self.self_rank = (
            _context.process_index() if self_rank is None else self_rank
        )
        self.startup_grace_s = (
            max(4.0 * self.timeout_s, 20.0)
            if startup_grace_s is None else startup_grace_s
        )
        self._t0 = time.monotonic()
        self.on_dead = on_dead
        self.on_straggler = on_straggler
        self.on_abort = on_abort
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reported_dead: Set[int] = set()
        self._reported_straggler: Set[int] = set()
        self._abort_seen = False

    def start(self) -> "FleetMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"tfs-fleet-monitor-p{self.self_rank}",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s * 4 + 1.0)
            self._thread = None

    def status(self) -> FleetStatus:
        return fleet_status(
            self.directory, run_id=self.run_id,
            num_processes=self.num_processes, timeout_s=self.timeout_s,
            straggler_s=self.straggler_s,
        )

    def check_once(self) -> FleetStatus:
        """One scan (the loop body; callable directly from tests)."""
        ab = abort_requested(self.directory, self.run_id)
        if ab is not None and not self._abort_seen:
            self._abort_seen = True
            _flight.record(
                "fleet.abort_seen", reason=ab.get("reason"),
                ranks=ab.get("ranks"), by=ab.get("by"),
            )
            if self.on_abort is not None:
                self.on_abort(ab)
        st = self.status()
        new_stragglers = [
            r for r in st.stragglers
            if r != self.self_rank and r not in self._reported_straggler
        ]
        if new_stragglers:
            self._reported_straggler.update(new_stragglers)
            _STRAGGLERS.inc(len(new_stragglers))
            MISSED_BEATS.inc(len(new_stragglers))
            for r in new_stragglers:
                _flight.record(
                    "fleet.straggler", rank=r,
                    age_s=round(st.ages.get(r, -1.0), 3),
                    straggler_s=self.straggler_s,
                )
            logger.warning(
                "fleet: straggler rank(s) %s (beat age > %.3gs)",
                new_stragglers, self.straggler_s,
            )
            if self.on_straggler is not None:
                self.on_straggler(new_stragglers, st)
        dead_now = list(st.dead)
        if st.missing and time.monotonic() - self._t0 > self.startup_grace_s:
            # expected ranks that never published a single beat: after
            # the startup grace they are dead, not "not yet here"
            dead_now.extend(st.missing)
        new_dead = [
            r for r in dead_now
            if r != self.self_rank and r not in self._reported_dead
        ]
        if new_dead:
            self._reported_dead.update(new_dead)
            DEAD_RANKS.inc(len(new_dead))
            MISSED_BEATS.inc(len(new_dead))
            for r in new_dead:
                _flight.record(
                    "fleet.heartbeat_lost", rank=r,
                    age_s=round(st.ages.get(r, -1.0), 3),
                    timeout_s=self.timeout_s,
                    never_started=r in st.missing,
                )
            logger.error(
                "fleet: dead rank(s) %s (no heartbeat for > %.3gs)",
                new_dead, self.timeout_s,
            )
            if self.on_dead is not None:
                self.on_dead(new_dead, st)
        return st

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception as e:  # pragma: no cover - must keep watching
                logger.debug("fleet monitor scan failed: %s", e)


# ---------------------------------------------------------------------------
# enrollment (the worker-side default policy)
# ---------------------------------------------------------------------------

@dataclass
class FleetMember:
    """This process's fleet membership: its heartbeater + monitor."""

    directory: str
    heartbeater: Heartbeater
    monitor: Optional[FleetMonitor]

    def leave(self, graceful: bool = True) -> None:
        if self.monitor is not None:
            self.monitor.stop()
        self.heartbeater.stop(graceful=graceful)


_member_lock = threading.Lock()
_member: Optional[FleetMember] = None


def current_member() -> Optional[FleetMember]:
    with _member_lock:
        return _member


def _abort_self(reason: str, ranks: Sequence[int], directory: str,
                signal_peers: bool) -> None:
    """The coordinated-abort exit: postmortem first (the black box must
    name the missing rank), signal peers, then ``os._exit`` — a wedged
    main thread blocked inside a collective cannot be unwound politely,
    and a bounded diagnosable death is the contract."""
    _flight.record("fleet.self_abort", reason=reason, ranks=list(ranks))
    _flight.dump(reason="fleet_abort")
    if signal_peers:
        signal_abort(directory, reason, dead_ranks=ranks)
    member = current_member()
    if member is not None:
        member.heartbeater.stop(graceful=True)
    logger.error("fleet: aborting (exit %d): %s", ABORT_EXIT_CODE, reason)
    os._exit(ABORT_EXIT_CODE)


def enroll(
    directory: Optional[str] = None,
    *,
    monitor: bool = True,
    abort_on_dead: bool = True,
    num_processes: Optional[int] = None,
    timeout_s: Optional[float] = None,
    interval_s: Optional[float] = None,
) -> Optional[FleetMember]:
    """Join the fleet rooted at ``directory`` (default
    ``TFTPU_FLEET_DIR``; **no-op returning None when unset** — a plain
    single-process run pays nothing). Starts the heartbeat publisher
    and, with ``monitor=True``, the peer monitor under the default
    policy: a dead peer or a peer's abort signal → flight postmortem
    naming the rank → coordinated abort → ``os._exit(ABORT_EXIT_CODE)``
    (``abort_on_dead=False`` records without exiting). Idempotent per
    process; ``training.run_resumable`` calls this automatically, so any
    training loop launched under ``supervise()`` is fleet-aware."""
    global _member
    directory = directory or rendezvous_dir()
    if not directory:
        return None
    # creation happens UNDER the lock: a check-then-act gap would let
    # two concurrent first enrollments (e.g. two threads entering
    # run_resumable) each start a Heartbeater, and the loser's orphan
    # would keep publishing fresh beats for this rank forever — masking
    # stale-heartbeat detection after the real member leaves
    with _member_lock:
        if _member is not None:
            return _member
        member = _enroll_locked(
            directory, monitor=monitor, abort_on_dead=abort_on_dead,
            num_processes=num_processes, timeout_s=timeout_s,
            interval_s=interval_s,
        )
        _member = member
    import atexit

    atexit.register(member.leave)
    logger.info(
        "fleet: enrolled rank %d in %s (interval %.3gs)",
        member.heartbeater.rank, directory, member.heartbeater.interval_s,
    )
    return member


def _enroll_locked(
    directory: str,
    *,
    monitor: bool,
    abort_on_dead: bool,
    num_processes: Optional[int],
    timeout_s: Optional[float],
    interval_s: Optional[float],
) -> FleetMember:
    num_processes = (
        _context.num_processes() if num_processes is None else num_processes
    )
    hb = Heartbeater(directory, interval_s=interval_s).start()
    mon = None
    if monitor:
        def _on_dead(ranks: List[int], st: FleetStatus) -> None:
            reason = (
                f"rank(s) {ranks} stopped heartbeating "
                f"(timeout {mon.timeout_s:g}s)"
            )
            if abort_on_dead:
                _abort_self(reason, ranks, directory, signal_peers=True)

        def _on_abort(rec: dict) -> None:
            reason = (
                f"coordinated abort from rank {rec.get('by')}: "
                f"{rec.get('reason')}"
            )
            if abort_on_dead:
                _abort_self(
                    reason, rec.get("ranks") or [], directory,
                    signal_peers=False,
                )

        mon = FleetMonitor(
            directory, num_processes=num_processes, timeout_s=timeout_s,
            on_dead=_on_dead, on_abort=_on_abort,
        )
        mon.start()
    return FleetMember(directory, hb, mon)


def _reset_member_for_tests() -> None:
    """Forget the enrollment singleton (test hygiene only)."""
    global _member
    with _member_lock:
        m, _member = _member, None
    if m is not None:
        m.leave(graceful=False)


def _after_fork_in_child() -> None:
    # a forked child inherits the parent's membership object but NOT its
    # threads: drop it so the child can enroll under its own rank. No
    # lock — the child is single-threaded here.
    global _member
    _member = None


if hasattr(os, "register_at_fork"):  # pragma: no branch - posix
    os.register_at_fork(after_in_child=_after_fork_in_child)


# ---------------------------------------------------------------------------
# hung-dispatch watchdog
# ---------------------------------------------------------------------------

def dispatch_deadline_s() -> float:
    """The active dispatch deadline (seconds; 0 = watchdog disabled)."""
    try:
        return float(get_config().dispatch_deadline_s or 0.0)
    except (TypeError, ValueError):
        return 0.0


def note_deadline_exemption(describe: str) -> None:
    """Record that one dispatch ran UNBOUNDED by the watchdog because
    its XLA compile happens lazily inside the call (a deterministic
    20-40s TPU compile is not a hung collective, and under supervise()
    it would burn the restart budget with no rank hung). Since the
    unified AOT dispatch (ISSUE 10) the only such dispatches are the
    executor's counted lazy-jit fallback on a genuine cache miss —
    store hits and fresh AOT builds compile OUTSIDE the watchdog scope
    and stay bounded — so the exemption is counted and flight-recorded:
    a fleet quietly exempting dispatches in steady state is a fleet
    living on the fallback path."""
    _DEADLINE_EXEMPTIONS.inc()
    _flight.record("fleet.deadline_exemption", entry=describe)


def _hung(
    describe: str,
    deadline: float,
    directory: Optional[str],
    *,
    missing: Optional[List[int]] = None,
    extra: Optional[dict] = None,
    signal: bool = True,
    message: Optional[str] = None,
) -> HungDispatchError:
    """Build the hung-dispatch verdict — the ONE protocol both the
    dispatch watchdog and the barrier share: count, flight-record +
    postmortem naming the stalled dispatch and the unresponsive ranks,
    and (unless ``signal=False``) the coordinated abort. ``missing``
    overrides the heartbeat-inferred unresponsive set when the caller
    knows it exactly (the barrier does, from arrivals)."""
    directory = directory or rendezvous_dir()
    if missing is None:
        missing = []
        if directory:
            try:
                missing = fleet_status(
                    directory, run_id=_context.run_id(),
                    num_processes=_context.num_processes(),
                ).unresponsive()
            except Exception:  # pragma: no cover - status is best-effort
                pass
    _HUNG_DISPATCHES.inc()
    _flight.record(
        "fleet.hung_dispatch", entry=describe, deadline_s=deadline,
        missing_ranks=missing, **(extra or {}),
    )
    _flight.dump(reason="hung_dispatch")
    if signal and directory:
        signal_abort(
            directory,
            f"hung dispatch {describe!r} (deadline {deadline:g}s, "
            f"unresponsive ranks {missing})",
            dead_ranks=missing,
        )
    if message is None:
        message = f"dispatch {describe!r} exceeded the {deadline:g}s deadline"
        if missing:
            message += f"; unresponsive rank(s): {missing}"
        message += (
            " — aborted by the hung-collective watchdog (see the "
            "flight-recorder postmortem; the in-flight attempt is "
            "abandoned, not interrupted)"
        )
    return HungDispatchError(message)


def run_with_deadline(
    fn: Callable[[], object],
    *,
    describe: str = "dispatch",
    deadline: Optional[float] = None,
    directory: Optional[str] = None,
    signal: bool = True,
):
    """Run ``fn()`` bounded by the dispatch deadline (default
    ``config.dispatch_deadline_s``; disabled → a plain call, zero
    overhead). On expiry the attempt is abandoned on its daemon thread
    (Python cannot interrupt a call blocked inside XLA) and
    :class:`HungDispatchError` raises after the postmortem/abort
    protocol — the bounded answer to a collective wedged on a dead
    peer. ``signal=False`` skips the coordinated-abort write for
    operations that are RETRIED on timeout (the ``init_distributed``
    handshake): an abort record outliving a successful redial would
    kill every rank the moment it enrolled."""
    d = dispatch_deadline_s() if deadline is None else float(deadline or 0)
    if d <= 0:
        return fn()
    try:
        return _retry.run_abandonable(
            fn, (), {}, d, thread_name="tfs-dispatch-deadline"
        )
    except _retry.WatchdogExpired:
        raise _hung(describe, d, directory, signal=signal) from None


# ---------------------------------------------------------------------------
# rendezvous barrier
# ---------------------------------------------------------------------------

# per-(run-incarnation, name) call counter: every use of a barrier name
# gets its own generation, so calling fleet_barrier("sync") at run start
# AND at every checkpoint epoch synchronizes each time instead of the
# later calls silently matching the first use's stale arrival files.
# SPMD lockstep (every rank calls every barrier, in order) makes the
# per-process counters agree across the fleet. Guarded by _gen_lock.
_gen_lock = threading.Lock()
_barrier_gen: Dict[str, int] = {}


def barrier(
    name: str,
    *,
    directory: Optional[str] = None,
    num_processes: Optional[int] = None,
    rank: Optional[int] = None,
    deadline: Optional[float] = None,
    poll_s: float = 0.01,
) -> None:
    """File-based fleet barrier: every rank marks its arrival at
    ``name`` and waits for all ``num_processes`` peers — bounded by
    ``deadline`` (``None`` or ``<= 0`` falls back to the dispatch
    deadline when armed, else a startup-skew-tolerant
    ``max(4 × heartbeat_timeout_s, 20s)`` — the same allowance the
    monitor's startup grace budgets, because a run-start barrier must
    tolerate a peer that is still importing jax; a barrier is **never**
    unbounded, and ``0`` means "default", matching the module's
    0-disables convention rather than an instant trip). A missing peer
    raises :class:`HungDispatchError` *naming the missing ranks* after
    the postmortem/abort protocol; a peer's abort signal raises
    :class:`CoordinatedAbortError`. Single-process (or un-enrolled)
    callers return immediately — every entry point stays safe to call
    unconditionally. Reusing a name is fine: each use is a distinct
    generation (per-process counters, agreeing under the SPMD lockstep
    contract), and the supervisor's ``TFTPU_FLEET_ATTEMPT`` is folded
    in so restarted fleets start their counts fresh. Generations two or
    more behind the current one are pruned on entry (reaching
    generation *g* proves every rank observed all of *g−2*'s arrivals),
    so per-epoch barriers don't grow the rendezvous dir unboundedly."""
    directory = directory or rendezvous_dir()
    if not directory:
        return
    n = num_processes if num_processes is not None else _context.num_processes()
    if not n or int(n) <= 1:
        return
    n = int(n)
    rank = _context.process_index() if rank is None else int(rank)
    run = _context.run_id()
    _faults.delay_point("fleet.barrier")
    attempt = os.environ.get("TFTPU_FLEET_ATTEMPT", "0")
    with _gen_lock:
        # keyed by DIRECTORY too: barriers against different rendezvous
        # dirs are independent fleets — a shared counter would leave
        # this rank polling generation g while dirB's peers write g0
        gen_key = f"{os.path.abspath(directory)}|{run}_a{attempt}_{name}"
        gen = _barrier_gen.get(gen_key, 0)
        _barrier_gen[gen_key] = gen + 1
    base = f"barrier_{run}_a{attempt}_{name}"
    tag = f"{base}.g{gen}"
    os.makedirs(directory, exist_ok=True)
    # prune spent generations (<= g-2): being AT g means every rank
    # completed g-1, which required observing ALL of g-2's arrivals —
    # nobody can still be polling those files. (g-1's files must stay:
    # a slower peer may not have observed them yet.)
    for path in _glob.glob(os.path.join(directory, f"{base}.g*")):
        try:
            old_gen = int(
                os.path.basename(path)[len(base) + 2:].split("_p", 1)[0]
            )
        except (IndexError, ValueError):
            continue
        if old_gen <= gen - 2:
            try:
                os.remove(path)
            except OSError:
                pass  # a peer pruned it first
    with open(os.path.join(directory, f"{tag}_p{rank}"), "w") as f:
        f.write(str(time.time()))
    d = deadline
    if d is None or d <= 0:
        d = dispatch_deadline_s() or max(
            4.0 * get_config().heartbeat_timeout_s, 20.0
        )
    t0 = time.monotonic()
    while True:
        arrived = set()
        for path in _glob.glob(os.path.join(directory, f"{tag}_p*")):
            try:
                arrived.add(int(path.rsplit("_p", 1)[1]))
            except (IndexError, ValueError):
                continue
        if len(arrived) >= n:
            return
        ab = abort_requested(directory, run)
        if ab is not None:
            raise CoordinatedAbortError(
                f"barrier {name!r}: coordinated abort from rank "
                f"{ab.get('by')}: {ab.get('reason')}"
            )
        if time.monotonic() - t0 > d:
            # the missing set is known EXACTLY from arrivals here — no
            # heartbeat inference needed
            missing = sorted(set(range(n)) - arrived)
            raise _hung(
                f"fleet.barrier[{name}]", d, directory,
                missing=missing, extra={"arrived": sorted(arrived)},
                message=(
                    f"barrier {name!r}: rank(s) {missing} missing after "
                    f"the {d:g}s deadline (arrived: {sorted(arrived)}) — "
                    "aborted by the hung-collective watchdog (see the "
                    "flight-recorder postmortem)"
                ),
            )
        time.sleep(poll_s)
