"""Deterministic, seedable fault injection.

Production code paths declare **named sites** by calling
:func:`fault_point` — a no-op (one falsy dict check, no lock) unless a
test, drill, or chaos exercise has armed an injection for that site with
:func:`inject`:

    from tensorframes_tpu.resilience import faults

    with faults.inject("checkpoint.save", OSError("disk wobble"), every_n=2):
        ckpt.save(10, state)   # every 2nd save attempt raises OSError

Injections fire **deterministically** (``every_n`` / ``after`` /
``max_times`` counters) or **probabilistically but reproducibly**
(``p=`` with a seeded PRNG), so a drill that exposed a bug replays
bit-for-bit. The registry is process-global and thread-safe: prefetch
workers, retry watchdogs, and the driver thread all hit the same
counters, which is exactly what a transient-IO drill wants.

Beyond plain raises, two site flavors support **fleet chaos** (ISSUE 8):
:func:`delay_point` sites catch an injected :class:`Delay` and sleep —
simulating a stalled-but-alive operation (a hung collective waiting on a
dead peer) that only a deadline watchdog can unblock; :func:`kill_point`
sites catch an injected :class:`KillRank` and ``SIGKILL`` their own
process — the deterministic stand-in for a preempted/OOM-killed rank.

Sites are **registered** (:func:`register_site` / :func:`list_sites`) by
the module that instruments them, so tests can assert the instrumented
set and the documentation (docs/resilience.md) never drift: any literal
site name appearing at a ``fault_point``/``delay_point``/``kill_point``
call in the package must be registered, and every registered site must
be named in the docs (tests/test_resilience.py drift guard).
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple, Union

from ..observability import flight as _flight
from ..observability.metrics import counter as _counter
from ..utils import get_logger

logger = get_logger(__name__)

_INJECTIONS_FIRED = _counter(
    "tftpu_fault_injections_fired_total",
    "Armed fault injections that actually raised at a fault_point",
)

#: The core site names instrumented across the package (documentation +
#: typo guard for tests; fault_point accepts arbitrary names). The full
#: live catalog — including sites other modules register at import —
#: is :func:`list_sites`.
SITES: Tuple[str, ...] = (
    "executor.run_block",
    "executor.run_rows",
    "io.prefetch.device_put",
    "io.save_frame",
    "io.load_frame",
    "checkpoint.save",
    "checkpoint.restore",
    "distributed.init",
)

ErrorSpec = Union[BaseException, type]


class Delay(Exception):
    """Injectable stall: a :func:`delay_point` site catches it and sleeps
    ``seconds`` instead of raising — the deterministic simulation of an
    operation that hangs (a collective waiting on a dead peer) rather
    than fails. At a plain :func:`fault_point` it propagates like any
    other injected error."""

    def __init__(self, seconds: float):
        super().__init__(f"injected delay of {seconds:g}s")
        self.seconds = float(seconds)


class KillRank(BaseException):
    """Injectable preemption: a :func:`kill_point` site catches it and
    ``SIGKILL``s its own process — no exception path, no atexit, exactly
    the blast shape of a preempted or OOM-killed rank. ``BaseException``
    so stray ``except Exception`` handlers between the site and the test
    cannot accidentally absorb a scheduled kill."""

    def __init__(self, message: str = "injected kill-rank fault"):
        super().__init__(message)


class Injection:
    """One armed fault: bookkeeping for when it fires.

    ``hits`` counts every time the site was reached while this injection
    was armed; ``fired`` counts the times it actually raised — both are
    readable after the ``with`` block for assertions.
    """

    def __init__(
        self,
        site: str,
        error: ErrorSpec,
        every_n: int = 1,
        after: int = 0,
        max_times: Optional[int] = None,
        p: Optional[float] = None,
        seed: int = 0,
    ):
        if every_n < 1:
            raise ValueError(f"every_n must be >= 1, got {every_n}")
        if p is not None and not (0.0 <= p <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {p}")
        self.site = site
        self.error = error
        self.every_n = every_n
        self.after = after
        self.max_times = max_times
        self.p = p
        self._rng = random.Random(seed)
        self.hits = 0
        self.fired = 0

    def _should_fire(self) -> bool:
        # caller holds the registry lock
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.max_times is not None and self.fired >= self.max_times:
            return False
        if self.p is not None:
            fire = self._rng.random() < self.p
        else:
            fire = (self.hits - self.after) % self.every_n == 0
        if fire:
            self.fired += 1
        return fire

    def make_error(self) -> BaseException:
        err = self.error
        if isinstance(err, BaseException):
            return err
        return err(f"injected fault at {self.site!r}")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Injection({self.site!r}, {self.error!r}, every_n={self.every_n}, "
            f"hits={self.hits}, fired={self.fired})"
        )


_lock = threading.Lock()
_registry: Dict[str, List[Injection]] = {}

# site catalog: name -> where/how it is instrumented. Seeded with the
# core SITES; modules that add sites (resilience/fleet.py, the executor
# dispatch watchdog) register theirs at import, and the drift-guard test
# holds every instrumented literal + every registered name to the docs.
_sites: Dict[str, str] = {}


def register_site(name: str, where: str) -> None:
    """Declare a fault site in the catalog (idempotent; re-registering
    with a different description updates it)."""
    if not name:
        raise ValueError("site name must be non-empty")
    with _lock:
        _sites[name] = where


def list_sites() -> Dict[str, str]:
    """The registered site catalog: ``{site name: where it is
    instrumented}``, sorted by name. This is the anti-drift surface —
    tests assert every ``fault_point``/``delay_point``/``kill_point``
    literal in the package is registered here and documented in
    docs/resilience.md."""
    with _lock:
        return dict(sorted(_sites.items()))


_CORE_SITE_DOCS: Dict[str, str] = {
    "executor.run_block": "CompiledProgram.run_block (block execution)",
    "executor.run_rows": "CompiledProgram.run_rows (vmapped execution)",
    "io.prefetch.device_put": "prefetch_to_device worker (host→HBM transfer)",
    "io.save_frame": "io.save_frame (frame persistence write)",
    "io.load_frame": "io.load_frame (frame persistence read)",
    "checkpoint.save": "Checkpointer.save (inside the retry scope)",
    "checkpoint.restore": "Checkpointer restore of one step directory",
    "distributed.init": "parallel.distributed.init_distributed handshake",
}
for _name, _where in _CORE_SITE_DOCS.items():
    register_site(_name, _where)


def fault_point(site: str) -> None:
    """Instrumentation hook: raise if an armed injection elects to fire.

    The un-armed fast path is a single truthiness check on a module
    dict — cheap enough for per-block call sites.
    """
    if not _registry:
        return
    with _lock:
        injections = _registry.get(site)
        if not injections:
            return
        err = None
        for inj in injections:
            if inj._should_fire():
                err = inj.make_error()
                break
    if err is not None:
        _INJECTIONS_FIRED.inc()
        _flight.record(
            "fault.injected", site=site, error=type(err).__name__,
            message=str(err),
        )
        logger.debug("fault_point(%s): raising injected %r", site, err)
        raise err


def delay_point(site: str) -> None:
    """A fault site with stall semantics: an injected :class:`Delay`
    makes this call sleep in place (the operation hangs, it does not
    fail), so hung-collective watchdogs are drillable deterministically.
    Any other injected error propagates exactly like :func:`fault_point`.
    """
    try:
        fault_point(site)
    except Delay as d:
        _flight.record("fault.delayed", site=site, seconds=d.seconds)
        logger.debug("delay_point(%s): sleeping injected %.3gs", site,
                     d.seconds)
        time.sleep(d.seconds)


def kill_point(site: str = "fleet.rank.kill") -> None:
    """A fault site with preemption semantics: an injected
    :class:`KillRank` makes this process ``SIGKILL`` itself — the
    deterministic kill-rank chaos primitive for subprocess-fleet drills
    (the flight-recorder disk spool, being line-flushed, survives as the
    black box). Any other injected error propagates like
    :func:`fault_point`."""
    try:
        fault_point(site)
    except KillRank:
        _flight.record("fault.kill_rank", site=site, pid=os.getpid())
        logger.warning("kill_point(%s): SIGKILLing own process (pid %d)",
                       site, os.getpid())
        os.kill(os.getpid(), signal.SIGKILL)


register_site(
    "fleet.rank.kill",
    "kill_point default site: training.run_resumable loop edge (any "
    "enrolled rank can be deterministically preempted mid-run)",
)

# Serving-fleet chaos sites (ISSUE 13). Registered here — not at the
# instrumenting modules — because the drills that arm them (tests,
# dev/resilience_drill.py serving-fleet leg) must see them in the
# catalog even in processes that never import the serving package.
register_site(
    "router.dispatch",
    "serving/router.py Router.dispatch, before each router→replica "
    "attempt — an injected Delay stalls the proxied dispatch (deadline-"
    "expiry chaos at the ingress); any other injected error fails the "
    "attempt exactly like a dead replica socket, driving the redrive "
    "path deterministically",
)
register_site(
    "serving.replica",
    "serving/replica.py serve_replica main loop — an injected KillRank "
    "SIGKILLs the replica process (the serving-fleet kill-replica "
    "chaos: the fleet must reroute, redrive, and restart it); other "
    "injected errors crash the loop into the nonzero-exit path",
)

# Out-of-core data-plane chaos sites (ISSUE 15). Registered centrally
# for the same reason as the serving sites: drills must see them even
# before the blockstore package loads.
register_site(
    "blockstore.spill",
    "blockstore/store.py _spill_entry, before the segment publish — an "
    "injected error fails that block's spill (the put raises; resident "
    "accounting is untouched); an injected Delay stalls the spill, "
    "back-pressuring the streaming partitioner deterministically",
)
register_site(
    "shuffle.exchange",
    "blockstore/shuffle.py exchange/allshare entry and every framed "
    "payload read — an injected Delay stalls this rank's exchange so "
    "peers' deadline waits (and the hung-shuffle postmortem naming this "
    "rank) are drillable; an injected transient OSError exercises the "
    "CRC-framed read's retry policy; a persistent one quarantines the "
    "payload and raises ShuffleCorruptionError",
)


@contextmanager
def inject(
    site: str,
    error: ErrorSpec = RuntimeError,
    every_n: int = 1,
    after: int = 0,
    max_times: Optional[int] = None,
    p: Optional[float] = None,
    seed: int = 0,
) -> Iterator[Injection]:
    """Arm a fault at ``site`` for the duration of the ``with`` block.

    ``error`` is an exception instance (raised as-is, same object every
    firing) or class (instantiated per firing). Deterministic schedule:
    skip the first ``after`` hits, then fire every ``every_n``-th hit,
    at most ``max_times`` times. Alternatively ``p=``/``seed=`` fires
    with probability ``p`` from a dedicated seeded PRNG — reproducible
    chaos. Yields the :class:`Injection` for hit/fire assertions.
    """
    inj = Injection(
        site, error, every_n=every_n, after=after, max_times=max_times,
        p=p, seed=seed,
    )
    with _lock:
        _registry.setdefault(site, []).append(inj)
    try:
        yield inj
    finally:
        with _lock:
            lst = _registry.get(site, [])
            if inj in lst:
                lst.remove(inj)
            if not lst:
                _registry.pop(site, None)


def active_sites() -> Tuple[str, ...]:
    """Site names with at least one armed injection (drill introspection)."""
    with _lock:
        return tuple(sorted(_registry))


def reset() -> None:
    """Disarm everything (test hygiene after a failed drill)."""
    with _lock:
        _registry.clear()
