"""Deterministic, seedable fault injection.

Production code paths declare **named sites** by calling
:func:`fault_point` — a no-op (one falsy dict check, no lock) unless a
test, drill, or chaos exercise has armed an injection for that site with
:func:`inject`:

    from tensorframes_tpu.resilience import faults

    with faults.inject("checkpoint.save", OSError("disk wobble"), every_n=2):
        ckpt.save(10, state)   # every 2nd save attempt raises OSError

Injections fire **deterministically** (``every_n`` / ``after`` /
``max_times`` counters) or **probabilistically but reproducibly**
(``p=`` with a seeded PRNG), so a drill that exposed a bug replays
bit-for-bit. The registry is process-global and thread-safe: prefetch
workers, retry watchdogs, and the driver thread all hit the same
counters, which is exactly what a transient-IO drill wants.

Instrumented sites (the stable names; any string is accepted so layers
can add sites without touching this module):

==============================  =============================================
site                            raised from
==============================  =============================================
``executor.run_block``          CompiledProgram.run_block (block execution)
``executor.run_rows``           CompiledProgram.run_rows (vmapped execution)
``io.prefetch.device_put``      prefetch_to_device worker (host→HBM transfer)
``io.save_frame``               io.save_frame (frame persistence write)
``io.load_frame``               io.load_frame (frame persistence read)
``checkpoint.save``             Checkpointer.save (inside the retry scope)
``checkpoint.restore``          Checkpointer restore of one step directory
``distributed.init``            parallel.distributed.init_distributed
==============================  =============================================
"""

from __future__ import annotations

import random
import threading
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple, Union

from ..observability import flight as _flight
from ..observability.metrics import counter as _counter
from ..utils import get_logger

logger = get_logger(__name__)

_INJECTIONS_FIRED = _counter(
    "tftpu_fault_injections_fired_total",
    "Armed fault injections that actually raised at a fault_point",
)

#: The site names instrumented across the package (documentation +
#: typo guard for tests; fault_point accepts arbitrary names).
SITES: Tuple[str, ...] = (
    "executor.run_block",
    "executor.run_rows",
    "io.prefetch.device_put",
    "io.save_frame",
    "io.load_frame",
    "checkpoint.save",
    "checkpoint.restore",
    "distributed.init",
)

ErrorSpec = Union[BaseException, type]


class Injection:
    """One armed fault: bookkeeping for when it fires.

    ``hits`` counts every time the site was reached while this injection
    was armed; ``fired`` counts the times it actually raised — both are
    readable after the ``with`` block for assertions.
    """

    def __init__(
        self,
        site: str,
        error: ErrorSpec,
        every_n: int = 1,
        after: int = 0,
        max_times: Optional[int] = None,
        p: Optional[float] = None,
        seed: int = 0,
    ):
        if every_n < 1:
            raise ValueError(f"every_n must be >= 1, got {every_n}")
        if p is not None and not (0.0 <= p <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {p}")
        self.site = site
        self.error = error
        self.every_n = every_n
        self.after = after
        self.max_times = max_times
        self.p = p
        self._rng = random.Random(seed)
        self.hits = 0
        self.fired = 0

    def _should_fire(self) -> bool:
        # caller holds the registry lock
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.max_times is not None and self.fired >= self.max_times:
            return False
        if self.p is not None:
            fire = self._rng.random() < self.p
        else:
            fire = (self.hits - self.after) % self.every_n == 0
        if fire:
            self.fired += 1
        return fire

    def make_error(self) -> BaseException:
        err = self.error
        if isinstance(err, BaseException):
            return err
        return err(f"injected fault at {self.site!r}")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Injection({self.site!r}, {self.error!r}, every_n={self.every_n}, "
            f"hits={self.hits}, fired={self.fired})"
        )


_lock = threading.Lock()
_registry: Dict[str, List[Injection]] = {}


def fault_point(site: str) -> None:
    """Instrumentation hook: raise if an armed injection elects to fire.

    The un-armed fast path is a single truthiness check on a module
    dict — cheap enough for per-block call sites.
    """
    if not _registry:
        return
    with _lock:
        injections = _registry.get(site)
        if not injections:
            return
        err = None
        for inj in injections:
            if inj._should_fire():
                err = inj.make_error()
                break
    if err is not None:
        _INJECTIONS_FIRED.inc()
        _flight.record(
            "fault.injected", site=site, error=type(err).__name__,
            message=str(err),
        )
        logger.debug("fault_point(%s): raising injected %r", site, err)
        raise err


@contextmanager
def inject(
    site: str,
    error: ErrorSpec = RuntimeError,
    every_n: int = 1,
    after: int = 0,
    max_times: Optional[int] = None,
    p: Optional[float] = None,
    seed: int = 0,
) -> Iterator[Injection]:
    """Arm a fault at ``site`` for the duration of the ``with`` block.

    ``error`` is an exception instance (raised as-is, same object every
    firing) or class (instantiated per firing). Deterministic schedule:
    skip the first ``after`` hits, then fire every ``every_n``-th hit,
    at most ``max_times`` times. Alternatively ``p=``/``seed=`` fires
    with probability ``p`` from a dedicated seeded PRNG — reproducible
    chaos. Yields the :class:`Injection` for hit/fire assertions.
    """
    inj = Injection(
        site, error, every_n=every_n, after=after, max_times=max_times,
        p=p, seed=seed,
    )
    with _lock:
        _registry.setdefault(site, []).append(inj)
    try:
        yield inj
    finally:
        with _lock:
            lst = _registry.get(site, [])
            if inj in lst:
                lst.remove(inj)
            if not lst:
                _registry.pop(site, None)


def active_sites() -> Tuple[str, ...]:
    """Site names with at least one armed injection (drill introspection)."""
    with _lock:
        return tuple(sorted(_registry))


def reset() -> None:
    """Disarm everything (test hygiene after a failed drill)."""
    with _lock:
        _registry.clear()
