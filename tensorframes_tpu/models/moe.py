"""Mixture-of-Experts FFN with expert parallelism (EP).

The reference has no MoE (SURVEY.md §2.7: expert parallelism — absent);
this extends the framework's parallelism inventory beyond parity, the way
ring attention did for sequence parallelism. Design is TPU-native
(GShard/Switch style), not a port:

* **Routing** is switch (top-1) with a per-shard expert capacity; dispatch
  and combine are one-hot einsums — dense MXU work with static shapes,
  no gather/scatter, no data-dependent control flow.
* **Expert parallelism** shards the expert dim of the weight stacks over
  the mesh's ``ep`` axis under ``shard_map``; tokens travel to their
  expert's device and back via two ``lax.all_to_all`` collectives over
  ICI (the EP analogue of the ring's ``ppermute``).
* Dropped tokens (over capacity) pass through on the residual path, as in
  Switch Transformers.

``moe_ffn`` (single-device einsum math) and ``moe_ffn_ep`` (shard_map +
all_to_all) compute the same function when capacity is not exceeded —
that equivalence is the correctness test.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel._shard_map import shard_map as _shard_map


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden: int = 64
    mlp_hidden: int = 256
    num_experts: int = 8
    # per-expert slots as a multiple of (tokens / experts); tokens over
    # capacity fall through to the residual connection
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32

    def capacity(self, tokens_per_shard: int) -> int:
        c = int(np.ceil(self.capacity_factor * tokens_per_shard / self.num_experts))
        return max(c, 1)


def init_moe_params(cfg: MoEConfig, seed: int = 0) -> Dict:
    k0, k1, k2 = jax.random.split(jax.random.PRNGKey(seed), 3)
    h, m, e = cfg.hidden, cfg.mlp_hidden, cfg.num_experts
    # python floats (weak-typed): numpy f64 scalars would promote the
    # f32 weights to f64 under the package's global x64 mode
    s_in, s_out = float(1.0 / np.sqrt(h)), float(1.0 / np.sqrt(m))
    return {
        "router": jax.random.normal(k0, (h, e), jnp.float32) * s_in,
        "w_in": jax.random.normal(k1, (e, h, m), jnp.float32) * s_in,
        "b_in": jnp.zeros((e, m), jnp.float32),
        "w_out": jax.random.normal(k2, (e, m, h), jnp.float32) * s_out,
        "b_out": jnp.zeros((e, h), jnp.float32),
    }


def moe_param_shardings(mesh: Mesh, axis: str = "ep") -> Dict:
    """Expert dim sharded over ``axis``; the router is replicated."""
    ep = axis if axis in mesh.shape else None

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "router": ns(),
        "w_in": ns(ep, None, None),
        "b_in": ns(ep, None),
        "w_out": ns(ep, None, None),
        "b_out": ns(ep, None),
    }


# ---------------------------------------------------------------------------
# Routing (shared by both impls)
# ---------------------------------------------------------------------------

def _route(cfg: MoEConfig, router_w, x, capacity: int):
    """Switch top-1 routing with capacity.

    Returns (dispatch [t, e, c] one-hot, combine [t, e, c] gate-weighted,
    aux load-balancing stats).
    """
    logits = x.astype(jnp.float32) @ router_w  # [t, e]
    gates = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(gates, axis=-1)  # [t]
    gate = jnp.take_along_axis(gates, idx[:, None], axis=-1)[:, 0]  # [t]
    expert_1h = jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.float32)  # [t, e]
    # position of each token within its expert's queue (first-come)
    pos = jnp.cumsum(expert_1h, axis=0) * expert_1h  # [t, e]; 1-based
    pos = (pos.sum(axis=-1) - 1.0).astype(jnp.int32)  # [t]; -1 if unrouted
    keep = (pos < capacity) & (pos >= 0)
    pos_1h = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [t, c]
    dispatch = expert_1h[:, :, None] * pos_1h[:, None, :]  # [t, e, c]
    dispatch = dispatch * keep[:, None, None]
    combine = dispatch * gate[:, None, None]
    # Switch aux loss stats: fraction routed + mean gate prob per expert
    frac = expert_1h.mean(axis=0)
    prob = gates.mean(axis=0)
    return dispatch, combine, (frac, prob)


def load_balancing_loss(frac: jnp.ndarray, prob: jnp.ndarray) -> jnp.ndarray:
    """Switch Transformers aux loss: E · Σ_e frac_e · prob_e."""
    e = frac.shape[-1]
    return e * jnp.sum(frac * prob, axis=-1)


def _expert_ffn(w_in, b_in, w_out, b_out, tokens, dtype):
    """tokens [e, c, h] through each expert's 2-layer MLP (batched einsum —
    one MXU matmul per projection across all local experts)."""
    y = jnp.einsum("ech,ehm->ecm", tokens.astype(dtype), w_in.astype(dtype))
    y = jax.nn.gelu(y + b_in[:, None, :].astype(dtype))
    y = jnp.einsum("ecm,emh->ech", y, w_out.astype(dtype))
    return y + b_out[:, None, :].astype(dtype)


# ---------------------------------------------------------------------------
# Single-device reference impl
# ---------------------------------------------------------------------------

def moe_ffn(
    cfg: MoEConfig, params: Dict, x: jnp.ndarray, return_stats: bool = False
):
    """x [t, h] → [t, h]. Pure einsum dispatch/combine on one device.
    With ``return_stats`` also returns the (frac, prob) load-balancing
    stats from the routing pass (so losses don't route twice)."""
    capacity = cfg.capacity(x.shape[0])
    dispatch, combine, stats = _route(cfg, params["router"], x, capacity)
    dispatched = jnp.einsum("tec,th->ech", dispatch, x.astype(jnp.float32))
    outs = _expert_ffn(
        params["w_in"], params["b_in"], params["w_out"], params["b_out"],
        dispatched, cfg.dtype,
    )
    y = jnp.einsum("tec,ech->th", combine, outs.astype(jnp.float32))
    y = y.astype(x.dtype)
    return (y, stats) if return_stats else y


# ---------------------------------------------------------------------------
# Expert-parallel impl (shard_map + all_to_all over 'ep')
# ---------------------------------------------------------------------------

def moe_ffn_ep(
    cfg: MoEConfig,
    params: Dict,
    x: jnp.ndarray,
    mesh: Mesh,
    axis: str = "ep",
    batch_axis: Optional[str] = "dp",
    return_stats: bool = False,
):
    """x [t, h] (sharded over ``axis``×``batch_axis`` on dim 0) → [t, h],
    with experts sharded over ``axis``: each shard routes its local tokens,
    ships them to the owning expert's device (all_to_all), runs the local
    experts, and ships results back (reverse all_to_all). A ``batch_axis``
    present on the mesh additionally splits tokens data-parallel (each dp
    replica runs its own independent a2a over its ep group).
    """
    n_ep = mesh.shape[axis]
    if cfg.num_experts % n_ep != 0:
        raise ValueError(
            f"num_experts={cfg.num_experts} not divisible by mesh axis "
            f"{axis!r}={n_ep}"
        )
    e_local = cfg.num_experts // n_ep
    db = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    token_dim0 = (axis, db) if db else axis
    stat_axes = (axis, db) if db else (axis,)

    def shard_fn(router, w_in, b_in, w_out, b_out, xs):
        # xs: local tokens [t_local, h]; w_in: local experts [e_local, h, m]
        t_local = xs.shape[0]
        capacity = cfg.capacity(t_local)
        dispatch, combine, (frac, prob) = _route(cfg, router, xs, capacity)
        # global load-balance stats = mean of per-shard stats (equal sizes)
        frac = lax.pmean(frac, stat_axes)
        prob = lax.pmean(prob, stat_axes)
        # [t, e, c] → [e, c, h], expert-major so the a2a split is contiguous
        dispatched = jnp.einsum("tec,th->ech", dispatch, xs.astype(jnp.float32))
        # exchange: split experts over the ep group, concat source shards.
        # [e, c, h] → [ep, e_local, c, h]; after a2a, dim 0 indexes the
        # SOURCE shard and e_local are OUR experts.
        dispatched = dispatched.reshape(n_ep, e_local, capacity, -1)
        recv = lax.all_to_all(dispatched, axis, split_axis=0, concat_axis=0)
        # [ep(source), e_local, c, h] → [e_local, ep·c, h]
        tokens = recv.transpose(1, 0, 2, 3).reshape(e_local, n_ep * capacity, -1)
        outs = _expert_ffn(w_in, b_in, w_out, b_out, tokens, cfg.dtype)
        # reverse the exchange
        outs = outs.reshape(e_local, n_ep, capacity, -1).transpose(1, 0, 2, 3)
        back = lax.all_to_all(
            outs.astype(jnp.float32), axis, split_axis=0, concat_axis=0
        )
        # [ep(expert-group), e_local, c, h] → [e, c, h] at the source shard
        back = back.reshape(cfg.num_experts, capacity, -1)
        y = jnp.einsum("tec,ech->th", combine, back)
        return y.astype(xs.dtype), frac, prob

    y, frac, prob = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(),                    # router replicated
            P(axis, None, None),    # w_in
            P(axis, None),          # b_in
            P(axis, None, None),    # w_out
            P(axis, None),          # b_out
            P(token_dim0, None),    # tokens sharded over ep (× dp)
        ),
        out_specs=(P(token_dim0, None), P(), P()),
        check=False,
    )(
        params["router"], params["w_in"], params["b_in"],
        params["w_out"], params["b_out"], x,
    )
    return (y, (frac, prob)) if return_stats else y


# ---------------------------------------------------------------------------
# Training helpers
# ---------------------------------------------------------------------------

def loss_fn(
    cfg: MoEConfig,
    params: Dict,
    x: jnp.ndarray,
    y: jnp.ndarray,
    mesh: Optional[Mesh] = None,
    axis: str = "ep",
    batch_axis: Optional[str] = "dp",
    aux_weight: float = 0.01,
) -> jnp.ndarray:
    """Regression loss through the MoE layer (+ Switch aux loss), runnable
    dense or expert-parallel. The aux stats come from the forward pass's
    own routing — no second routing pass."""
    if mesh is not None and axis in mesh.shape:
        out, (frac, prob) = moe_ffn_ep(
            cfg, params, x, mesh, axis=axis, batch_axis=batch_axis,
            return_stats=True,
        )
    else:
        out, (frac, prob) = moe_ffn(cfg, params, x, return_stats=True)
    mse = jnp.mean((out.astype(jnp.float32) - y.astype(jnp.float32)) ** 2)
    return mse + aux_weight * load_balancing_loss(frac, prob)


def make_ep_train_step(
    cfg: MoEConfig,
    mesh: Mesh,
    tx,
    axis: str = "ep",
    batch_axis: Optional[str] = "dp",
):
    """Jitted expert-parallel train step over ``mesh``: tokens sharded over
    ep × dp (each dp replica owns a distinct batch slice — no redundant
    compute), expert weights sharded over ep, optimizer state mirroring
    the params."""
    db = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    shardings = moe_param_shardings(mesh, axis=axis)
    data_sharding = NamedSharding(mesh, P((axis, db) if db else axis, None))

    def step(params, opt_state, x, y):
        import optax

        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(
                cfg, p, x, y, mesh=mesh, axis=axis, batch_axis=db
            )
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    init_opt = jax.jit(tx.init, in_shardings=(shardings,))
    # unified AOT dispatch (ISSUE 10): the ep train step keys by its
    # mesh/sharding topology and restarts warm from the persistent store
    from ..ops.executor import aot_jit

    jitted = aot_jit(
        step,
        in_shardings=(shardings, None, data_sharding, data_sharding),
        out_shardings=(shardings, None, NamedSharding(mesh, P())),
        label="moe.ep_train_step",
    )
    return jitted, data_sharding, shardings, init_opt


def scoring_program(cfg: MoEConfig, params: Dict):
    """map_blocks program: token-feature block [n, hidden] →
    {"moe_out": [n, hidden]} — MoE inference through the same verb as
    every other model family (params closure-captured ≙ frozen-graph)."""

    def program(features):
        return {"moe_out": moe_ffn(cfg, params, features)}

    return program
