"""A compact, real transformer encoder — the framework's flagship model.

Written in pure JAX (explicit params pytree, no flax dependency) so every
sharding decision is visible. This powers:

* BERT-style embedding extraction through ``map_rows``/``map_blocks``
  (BASELINE config 5);
* the multi-chip training-step dry-run (``__graft_entry__.dryrun_multichip``)
  with genuine dp/tp/sp shardings over a ``jax.sharding.Mesh``.

Sharding layout (the "How to Scale Your Model" recipe: pick a mesh,
annotate, let XLA insert the ICI collectives):

* batch dim → ``dp``; sequence dim of activations → ``sp``
  (attention gathers k/v over ``sp`` via XLA-inserted all-gathers; the
  manual ring-attention kernel in ops/attention.py is the alternative
  path for long sequences);
* attention head dim and MLP hidden dim → ``tp`` (Megatron-style:
  column-parallel in, row-parallel out, one psum per block);
* everything is bfloat16 on the MXU with float32 params/optimizer state.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    hidden: int = 768
    num_heads: int = 12
    num_layers: int = 12
    mlp_ratio: int = 4
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16  # activations/compute; params stay f32
    # attention implementation: 'dense' | 'blockwise' | 'flash' | 'ring' |
    # 'ulysses' (ring/ulysses = sequence parallelism over the mesh 'sp'
    # axis — ppermute ring vs all-to-all head exchange; see ops/attention.py)
    attention_impl: str = "dense"
    causal: bool = False
    # rematerialize each layer in the backward pass (jax.checkpoint):
    # trades recompute FLOPs for activation HBM — the standard lever for
    # long sequences / deep stacks
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden // self.num_heads

    @property
    def mlp_hidden(self) -> int:
        return self.hidden * self.mlp_ratio


def bert_base(**kw) -> TransformerConfig:
    """BERT-base geometry (12L/768H/12 heads)."""
    return TransformerConfig(vocab_size=30_522, **kw)


def tiny(**kw) -> TransformerConfig:
    """A tiny config for tests and CPU dry-runs."""
    return TransformerConfig(
        vocab_size=128, hidden=32, num_heads=4, num_layers=2, max_seq_len=16, **kw
    )


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def init_params(cfg: TransformerConfig, seed: int = 0) -> Dict:
    """Initialize the parameter pytree (float32)."""
    k = jax.random.PRNGKey(seed)
    keys = jax.random.split(k, 4 + 4 * cfg.num_layers)
    h, m = cfg.hidden, cfg.mlp_hidden

    def dense(key, shape, scale=None):
        # float(): a numpy f64 scalar would promote the f32 weights
        # to f64 under the package's global x64 mode — f64 transformers
        # crash/stall the TPU compiler (no native f64)
        scale = float(scale if scale is not None else 1.0 / np.sqrt(shape[0]))
        return jax.random.normal(key, shape, jnp.float32) * scale

    params = {
        "embed": {
            "tok": dense(keys[0], (cfg.vocab_size, h), 0.02),
            "pos": dense(keys[1], (cfg.max_seq_len, h), 0.02),
        },
        "final_ln": {"scale": jnp.ones((h,), jnp.float32),
                     "bias": jnp.zeros((h,), jnp.float32)},
        "layers": [],
    }
    for i in range(cfg.num_layers):
        ka, kb, kc, kd = keys[4 + 4 * i : 8 + 4 * i]
        params["layers"].append(
            {
                "ln1": {"scale": jnp.ones((h,), jnp.float32),
                        "bias": jnp.zeros((h,), jnp.float32)},
                "ln2": {"scale": jnp.ones((h,), jnp.float32),
                        "bias": jnp.zeros((h,), jnp.float32)},
                "attn": {
                    "qkv": dense(ka, (h, 3 * h)),
                    "out": dense(kb, (h, h)),
                },
                "mlp": {
                    "in": dense(kc, (h, m)),
                    "in_bias": jnp.zeros((m,), jnp.float32),
                    "out": dense(kd, (m, h)),
                    "out_bias": jnp.zeros((h,), jnp.float32),
                },
            }
        )
    return params


def param_shardings(cfg: TransformerConfig, mesh: Mesh) -> Dict:
    """PartitionSpec pytree: Megatron-style tensor parallelism over ``tp``.

    qkv / mlp-in are column-parallel (output dim sharded); out / mlp-out
    are row-parallel (input dim sharded) → XLA inserts one psum per block.
    """
    tp = "tp" if "tp" in mesh.shape else None  # degrade on tp-less meshes

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    layer = {
        "ln1": {"scale": ns(), "bias": ns()},
        "ln2": {"scale": ns(), "bias": ns()},
        "attn": {"qkv": ns(None, tp), "out": ns(tp, None)},
        "mlp": {
            "in": ns(None, tp),
            "in_bias": ns(tp),
            "out": ns(tp, None),
            "out_bias": ns(),
        },
    }
    return {
        "embed": {"tok": ns(), "pos": ns()},
        "final_ln": {"scale": ns(), "bias": ns()},
        "layers": [layer for _ in range(cfg.num_layers)],
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layer_norm(x, scale, bias, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _attention(cfg: TransformerConfig, p, x, mask, mesh=None):
    from ..ops import attention as att
    from ..ops.quantize import matmul as _mm

    b, s, h = x.shape
    qkv = _mm(x, p["qkv"]).reshape(b, s, 3, cfg.num_heads, cfg.head_dim)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    # [b, heads, s, d]
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    impl = cfg.attention_impl
    if mask is not None and impl != "dense":
        raise NotImplementedError(
            f"attention_impl={impl!r} does not support a padding mask yet; "
            "use attention_impl='dense' for padded batches"
        )
    if impl in ("ring", "ulysses"):
        if mesh is None or "sp" not in mesh.shape:
            raise ValueError(
                f"attention_impl={impl!r} requires a mesh with an 'sp' axis "
                "passed to forward(...); got "
                f"{None if mesh is None else dict(mesh.shape)}"
            )
        if impl == "ring":
            ctx = att.ring_attention(q, k, v, mesh, axis="sp", causal=cfg.causal)
        else:
            ctx = att.ulysses_attention(
                q, k, v, mesh, axis="sp", causal=cfg.causal
            )
    elif impl == "blockwise":
        ctx = att.blockwise_attention(q, k, v, causal=cfg.causal)
    elif impl == "flash":
        ctx = att.flash_attention(q, k, v, causal=cfg.causal)
    elif impl == "dense":
        ctx = att.dense_attention(
            q, k, v, causal=cfg.causal, padding_mask=mask
        )
    else:
        raise ValueError(f"Unknown attention_impl {impl!r}")
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h)
    return _mm(ctx, p["out"])


def _mlp(p, x):
    from ..ops.quantize import matmul as _mm

    y = _mm(x, p["in"]) + p["in_bias"].astype(x.dtype)
    y = jax.nn.gelu(y)
    return _mm(y, p["out"]) + p["out_bias"].astype(x.dtype)


def forward(
    cfg: TransformerConfig,
    params: Dict,
    tokens: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    mesh: Optional[Mesh] = None,
) -> jnp.ndarray:
    """Encoder forward: int tokens [b, s] → hidden states [b, s, h].

    ``mask`` (padding mask) is honoured by the dense impl; the blockwise /
    flash / ring kernels currently assume unpadded sequences. ``mesh`` is
    required for ``attention_impl='ring'`` (sequence parallelism over its
    'sp' axis).
    """
    x = params["embed"]["tok"][tokens].astype(cfg.dtype)
    s = tokens.shape[1]
    x = x + params["embed"]["pos"][:s].astype(cfg.dtype)

    def layer(x, p):
        x = x + _attention(cfg, p["attn"], _layer_norm(x, **p["ln1"]), mask, mesh)
        return x + _mlp(p["mlp"], _layer_norm(x, **p["ln2"]))

    if cfg.remat:
        # recompute each layer's activations in the backward pass instead
        # of keeping them resident: O(1) layers of activation HBM
        layer = jax.checkpoint(layer)
    for p in params["layers"]:
        x = layer(x, p)
    return _layer_norm(x, **params["final_ln"])


def embed_program(cfg: TransformerConfig, params: Dict):
    """map_blocks program: token block [n, s] → {"embedding": [n, h]}.

    Mean-pooled final hidden states — BERT-style sentence embeddings
    (BASELINE config 5)."""

    def program(tokens):
        hs = forward(cfg, params, tokens)
        return {"embedding": hs.mean(axis=1).astype(jnp.float32)}

    return program


def embed_row_program(cfg: TransformerConfig, params: Dict):
    """map_rows program: one token cell [s] → {"embedding": [h]}.

    The per-row formulation of BASELINE config 5 ("BERT-base embedding
    extraction: mapRows over a tokenized text column"); map_rows vmaps it
    over the block, so the whole block still runs as one batched XLA
    program on the MXU."""

    def program(tokens):
        hs = forward(cfg, params, tokens[None, :])
        return {"embedding": hs[0].mean(axis=0).astype(jnp.float32)}

    return program


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------

def loss_fn(cfg: TransformerConfig, params, tokens, targets, mesh=None):
    """Causal-LM-style cross entropy against the token embedding matrix."""
    hs = forward(cfg, params, tokens, mesh=mesh)
    logits = hs.astype(jnp.float32) @ params["embed"]["tok"].T
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return nll.mean()


def make_train_step(cfg: TransformerConfig, tx):
    """Plain (unsharded) jittable train step."""

    def step(params, opt_state, tokens, targets):
        import optax

        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens, targets)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_sharded_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    tx,
    seq_axis: Optional[str] = "sp",
    mixed_precision: bool = False,
):
    """Jit the train step over a mesh with dp/tp(/sp) shardings.

    Data: tokens/targets [b, s] → P('dp', 'sp'). Params: Megatron tp
    layout. Optimizer state mirrors param shardings. XLA's SPMD partitioner
    inserts the all-gathers/psums over ICI. ``mixed_precision=True`` casts
    the LAYER params to ``cfg.dtype`` inside the differentiated function
    — the tp all-gathers and the backward then move bf16 instead of f32
    (forward already computes in ``cfg.dtype`` via per-use casts; the
    flag shrinks the collective/grad traffic). The embedding table stays
    f32: ``loss_fn`` deliberately keeps the large-vocab logits
    contraction in f32, and the master weights the optimizer updates are
    f32 either way (no loss scaling: bf16 keeps f32's exponent range).
    """
    if seq_axis is not None and seq_axis not in mesh.shape:
        seq_axis = None  # e.g. a pure-dp mesh: sequence stays unsharded
    data_spec = P("dp", seq_axis) if seq_axis else P("dp", None)
    data_sharding = NamedSharding(mesh, data_spec)
    shardings = param_shardings(cfg, mesh)

    def run_loss(p, tokens, targets):
        if mixed_precision:
            from ..training import cast_float_leaves

            # embed stays f32 — see docstring (f32 logits head)
            p = {
                **p,
                "layers": cast_float_leaves(p["layers"], cfg.dtype),
                "final_ln": cast_float_leaves(p["final_ln"], cfg.dtype),
            }
        return loss_fn(cfg, p, tokens, targets, mesh=mesh)

    def step(params, opt_state, tokens, targets):
        import optax

        loss, grads = jax.value_and_grad(
            lambda p: run_loss(p, tokens, targets)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # Optimizer state mirrors param shardings: init under jit with sharded
    # params — XLA propagates the tp layout into adam's mu/nu, so optimizer
    # memory scales down with tp exactly like the params.
    init_opt_state = jax.jit(tx.init, in_shardings=(shardings,))

    # opt_state in/out shardings are inferred from the (already sharded)
    # state arrays produced by init_opt_state. The step dispatches through
    # the executor's unified AOT pipeline (aot_jit): its executable is
    # compiled explicitly, keyed by the mesh/sharding/process topology,
    # and served from the persistent store on restart — the MULTICHIP
    # dryrun's second run must not pay XLA again.
    from ..ops.executor import aot_jit

    jitted = aot_jit(
        step,
        in_shardings=(shardings, None, data_sharding, data_sharding),
        out_shardings=(shardings, None, NamedSharding(mesh, P())),
        label="transformer.sharded_train_step",
    )
    return jitted, data_sharding, shardings, init_opt_state


def synthetic_batch(cfg: TransformerConfig, batch: int, seq: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    targets = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    return tokens, targets


def quantize_params(params: Dict) -> Dict:
    """Weight-only int8 quantization of the layer weights (attn qkv/out,
    mlp in/out). Embeddings, norms, and biases stay full precision —
    they are gathered/broadcast, not matmul'd, so quantizing them saves
    little and costs accuracy. The returned tree runs through the same
    ``forward`` (ops/quantize.asarray dequantizes at the matmul, which
    XLA fuses into the MXU op), at ~4x less weight HBM traffic."""
    from ..ops.quantize import quantize_tree

    return quantize_tree(
        params,
        predicate=lambda path, _: "embed" not in jax.tree_util.keystr(path),
    )
