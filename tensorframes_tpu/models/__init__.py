"""Model-level workloads served through the frame verbs.

The reference ships no model *framework* — its models are demo workloads
driven through the verbs: k-means via map_blocks+aggregate
(tensorframes_snippets/kmeans.py:85-162), harmonic/geometric means via
aggregate (geom_mean.py:26-49), and a VGG-16 inference sketch
(read_image.py). The BASELINE configs add MNIST logistic-regression
scoring, Inception-v3 batch inference, and BERT-base embedding extraction.

Here each model family is a first-class module producing *programs* (pure
jax functions + params) that plug into ``map_blocks``/``map_rows`` like any
user program, plus sharded training steps for the multi-chip path.
"""

from . import generation, inception, logreg, vgg  # noqa: F401
