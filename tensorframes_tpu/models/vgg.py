"""VGG-16 image classification (batch-inference workload).

The reference's image-inference snippet is literally a VGG-16 sketch
(tensorframes_snippets/read_image.py: slim ``vgg.vgg_16`` + central-crop
preprocessing + softmax + top-5), run through map_blocks-style scoring.
This is that workload re-designed TPU-first:

* NHWC layout end-to-end; channel widths are already 64..512 — native
  MXU lane sizes.
* bfloat16 weights/activations with float32 accumulation
  (``preferred_element_type``), the standard TPU inference recipe.
* the two 4096-wide FC layers are expressed as matmuls over the flattened
  7×7×512 feature map — pure MXU work (slim expresses them as 7×7 VALID
  convs; same arithmetic, but the matmul form lets XLA pick the tiling).
* preprocessing (resize-shorter-side + central crop + mean subtraction,
  ≙ ``vgg_preprocessing.preprocess_image``) is a jittable device-side
  function over a batch, not a per-image host loop.
* scoring returns softmax scores plus top-k indices/values
  (≙ read_image.py's ``top_predictions`` fetches), plugged into
  ``map_blocks`` as a plain function program over an image column.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_DN = ("NHWC", "HWIO", "NHWC")

# channels-last ImageNet RGB means (vgg_preprocessing's _R_MEAN/_G_MEAN/_B_MEAN)
_RGB_MEAN = (123.68, 116.779, 103.939)

# the 13 conv layers of configuration "D" (Simonyan & Zisserman 2014):
# (#convs in the block, out_channels) per pooling stage
_VGG16_PLAN = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))


@dataclasses.dataclass(frozen=True)
class VGGConfig:
    num_classes: int = 1000
    image_size: int = 224
    channel_scale: float = 1.0
    fc_width: int = 4096
    compute_dtype: str = "bfloat16"  # activations/weights; accum is f32

    def ch(self, c: int) -> int:
        """Scaled channel count, lane-aligned to a multiple of 8."""
        return max(8, int(round(c * self.channel_scale / 8.0)) * 8)

    @property
    def fc(self) -> int:
        return max(8, int(round(self.fc_width * self.channel_scale / 8.0)) * 8)


def vgg_16(**kw) -> VGGConfig:
    return VGGConfig(**kw)


def tiny(**kw) -> VGGConfig:
    kw.setdefault("num_classes", 10)
    kw.setdefault("image_size", 32)
    kw.setdefault("channel_scale", 0.125)
    kw.setdefault("compute_dtype", "float32")
    return VGGConfig(**kw)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

class _KeyGen:
    def __init__(self, seed: int):
        self._key = jax.random.PRNGKey(seed)

    def __call__(self):
        self._key, sub = jax.random.split(self._key)
        return sub


def _conv_init(key, cin: int, cout: int, dtype) -> Dict:
    w = jax.random.normal(key, (3, 3, cin, cout), jnp.float32)
    w = (w * np.sqrt(2.0 / (9 * cin))).astype(dtype)
    return {"w": w, "b": jnp.zeros((cout,), dtype)}


def _dense_init(key, cin: int, cout: int, dtype) -> Dict:
    w = jax.random.normal(key, (cin, cout), jnp.float32)
    w = (w * np.sqrt(2.0 / cin)).astype(dtype)
    return {"w": w, "b": jnp.zeros((cout,), dtype)}


def init_params(cfg: VGGConfig, seed: int = 0) -> Dict:
    """Parameter tree keyed ``conv{stage}_{i}`` / ``fc6|fc7|fc8`` — the
    slim checkpoint naming, so pretrained-weight import is a rename."""
    kg = _KeyGen(seed)
    dt_ = jnp.dtype(cfg.compute_dtype)
    p: Dict = {}
    cin = 3
    for stage, (reps, width) in enumerate(_VGG16_PLAN, start=1):
        cout = cfg.ch(width)
        for i in range(1, reps + 1):
            p[f"conv{stage}_{i}"] = _conv_init(kg(), cin, cout, dt_)
            cin = cout
    # feature map after 5 pools: (size/32)² × ch(512)
    feat = (cfg.image_size // 32) ** 2 * cin
    p["fc6"] = _dense_init(kg(), feat, cfg.fc, dt_)
    p["fc7"] = _dense_init(kg(), cfg.fc, cfg.fc, dt_)
    p["fc8"] = _dense_init(kg(), cfg.fc, cfg.num_classes, dt_)
    return p


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _conv_relu(p, x):
    from ..ops.quantize import asarray as _qw

    y = lax.conv_general_dilated(
        x, _qw(p["w"], x.dtype), (1, 1), "SAME", dimension_numbers=_DN,
        preferred_element_type=jnp.float32,
    )
    return jax.nn.relu(y + p["b"].astype(jnp.float32)).astype(x.dtype)


def _maxpool2(x):
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def forward(cfg: VGGConfig, params: Dict, images: jnp.ndarray) -> jnp.ndarray:
    """images [n, S, S, 3] float → logits [n, num_classes] float32."""
    x = images.astype(jnp.dtype(cfg.compute_dtype))
    for stage, (reps, _) in enumerate(_VGG16_PLAN, start=1):
        for i in range(1, reps + 1):
            x = _conv_relu(params[f"conv{stage}_{i}"], x)
        x = _maxpool2(x)
    x = x.reshape(x.shape[0], -1)  # [n, (S/32)²·512]
    from ..ops.quantize import asarray as _qw

    for name in ("fc6", "fc7"):
        p = params[name]
        x = jax.nn.relu(
            jnp.dot(x, _qw(p["w"], x.dtype), preferred_element_type=jnp.float32)
            + p["b"].astype(jnp.float32)
        ).astype(x.dtype)
    p = params["fc8"]
    return (
        jnp.dot(x, _qw(p["w"], x.dtype), preferred_element_type=jnp.float32)
        + p["b"].astype(jnp.float32)
    )


# ---------------------------------------------------------------------------
# Preprocessing (≙ vgg_preprocessing.preprocess_image, inference branch)
# ---------------------------------------------------------------------------

def preprocess(images: jnp.ndarray, out_size: int) -> jnp.ndarray:
    """Central-crop a [n, H, W, 3] batch to ``out_size`` and subtract the
    ImageNet channel means. Jittable; runs on device as part of the same
    XLA program as the network when composed in a scoring function."""
    n, h, w, _ = images.shape
    if h < out_size or w < out_size:
        raise ValueError(
            f"preprocess: input {h}x{w} smaller than crop {out_size}"
        )
    top = (h - out_size) // 2
    left = (w - out_size) // 2
    x = lax.slice(
        images, (0, top, left, 0), (n, top + out_size, left + out_size, 3)
    )
    mean = jnp.asarray(_RGB_MEAN, images.dtype)
    return x - mean


# ---------------------------------------------------------------------------
# map_blocks scoring program (≙ read_image.py's output_nodes:
# probabilities + top-k indices + top-k values)
# ---------------------------------------------------------------------------

def scoring_program(cfg: VGGConfig, params: Dict, top_k: int = 5):
    """Image block [n, S, S, 3] → {"scores", "top_idx", "top_val"}."""
    k = min(top_k, cfg.num_classes)

    def program(images):
        logits = forward(cfg, params, images)
        scores = jax.nn.softmax(logits, axis=-1).astype(jnp.float32)
        top_val, top_idx = lax.top_k(scores, k)
        return {
            "scores": scores,
            "top_idx": top_idx.astype(jnp.int32),
            "top_val": top_val,
        }

    return program


def synthetic_images(cfg: VGGConfig, n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    s = cfg.image_size
    return rng.standard_normal((n, s, s, 3), dtype=np.float32)


def param_count(params) -> int:
    from ..ops.quantize import QuantizedTensor

    total = 0
    for v in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        shape = v.q.shape if isinstance(v, QuantizedTensor) else v.shape
        total += int(np.prod(shape))
    return total


def quantize_params(params: Dict) -> Dict:
    """Weight-only int8 for every conv/dense weight (per output channel);
    biases stay full precision (min_rank=2 excludes them)."""
    from ..ops.quantize import quantize_tree

    return quantize_tree(params)
