"""Inception-v3 image classification (batch-inference workload).

BASELINE config 4: "Inception-v3 frozen GraphDef batch inference over
image-bytes DataFrame" — the reference's VGG sketch
(tensorframes_snippets/read_image.py) generalized to the BASELINE's named
model. Re-designed TPU-first rather than ported:

* NHWC layout end-to-end (the TPU-native conv layout; XLA tiles the
  channel dim onto the MXU lanes).
* bfloat16 activations/weights with float32 accumulation
  (``preferred_element_type``) — the standard TPU inference recipe.
* batch-norm folded into conv scale/bias at init (this is *frozen-graph*
  inference ≙ variables-to-constants freezing, core.py:42-56, so BN is a
  constant affine).
* scoring plugs into ``map_blocks`` as a plain function program over an
  image column, like every other workload.

Architecture follows the Inception-v3 paper (Szegedy et al. 2015): stem,
3×block-A (35×35), grid-reduction-B, 4×block-C (17×17, factorized 7×1/1×7),
grid-reduction-D, 2×block-E (8×8), global average pool, dense classifier.
A ``channel_scale`` knob shrinks widths for tests; ``tiny()`` runs on
75×75 inputs in seconds on CPU.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_DN = ("NHWC", "HWIO", "NHWC")


@dataclasses.dataclass(frozen=True)
class InceptionConfig:
    num_classes: int = 1000
    image_size: int = 299
    channel_scale: float = 1.0
    compute_dtype: str = "bfloat16"  # activations/weights; accum is f32

    def ch(self, c: int) -> int:
        """Scaled channel count, rounded up to a multiple of 8 (keeps the
        last dim MXU/VPU lane-aligned even for tiny test configs)."""
        return max(8, int(round(c * self.channel_scale / 8.0)) * 8)


def inception_v3(**kw) -> InceptionConfig:
    return InceptionConfig(**kw)


def tiny(**kw) -> InceptionConfig:
    kw.setdefault("num_classes", 10)
    kw.setdefault("image_size", 75)
    kw.setdefault("channel_scale", 0.125)
    kw.setdefault("compute_dtype", "float32")
    return InceptionConfig(**kw)


# ---------------------------------------------------------------------------
# Parameter init
# ---------------------------------------------------------------------------

def _conv_init(key, kh: int, kw: int, cin: int, cout: int, dtype) -> Dict:
    """He-normal conv weight + the folded-BN affine (scale, bias)."""
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
    w = (w * np.sqrt(2.0 / fan_in)).astype(dtype)
    # frozen BN folds to an affine; identity-initialized here (random
    # weights — the bench measures compute, not accuracy)
    return {"w": w, "scale": jnp.ones((cout,), dtype), "bias": jnp.zeros((cout,), dtype)}


class _KeyGen:
    def __init__(self, seed: int):
        self._key = jax.random.PRNGKey(seed)

    def __call__(self):
        self._key, sub = jax.random.split(self._key)
        return sub


def init_params(cfg: InceptionConfig, seed: int = 0) -> Dict:
    """Build the full parameter tree. Layer names mirror the paper's
    mixed-block structure so shardings/checkpoints address them stably."""
    kg = _KeyGen(seed)
    dt = jnp.dtype(cfg.compute_dtype)
    c = cfg.ch

    def conv(kh, kw, cin, cout):
        return _conv_init(kg(), kh, kw, cin, cout, dt)

    p: Dict = {}
    # -- stem ---------------------------------------------------------------
    p["stem"] = {
        "c1": conv(3, 3, 3, c(32)),        # /2
        "c2": conv(3, 3, c(32), c(32)),
        "c3": conv(3, 3, c(32), c(64)),    # SAME
        # maxpool /2
        "c4": conv(1, 1, c(64), c(80)),
        "c5": conv(3, 3, c(80), c(192)),
        # maxpool /2
    }
    cur = c(192)

    # -- 3 × block A (pool_proj 32, 64, 64) ---------------------------------
    for i, pool_ch in enumerate([32, 64, 64]):
        p[f"mixed_a{i}"] = {
            "b1": conv(1, 1, cur, c(64)),
            "b5_1": conv(1, 1, cur, c(48)),
            "b5_2": conv(5, 5, c(48), c(64)),
            "b3_1": conv(1, 1, cur, c(64)),
            "b3_2": conv(3, 3, c(64), c(96)),
            "b3_3": conv(3, 3, c(96), c(96)),
            "bp": conv(1, 1, cur, c(pool_ch)),
        }
        cur = c(64) + c(64) + c(96) + c(pool_ch)

    # -- grid reduction B ---------------------------------------------------
    p["mixed_b"] = {
        "b3": conv(3, 3, cur, c(384)),          # /2 VALID
        "bd_1": conv(1, 1, cur, c(64)),
        "bd_2": conv(3, 3, c(64), c(96)),
        "bd_3": conv(3, 3, c(96), c(96)),       # /2 VALID
        # maxpool /2
    }
    cur = c(384) + c(96) + cur

    # -- 4 × block C (7×1/1×7 factorized; c7 = 128,160,160,192) -------------
    for i, c7 in enumerate([128, 160, 160, 192]):
        p[f"mixed_c{i}"] = {
            "b1": conv(1, 1, cur, c(192)),
            "b7_1": conv(1, 1, cur, c(c7)),
            "b7_2": conv(1, 7, c(c7), c(c7)),
            "b7_3": conv(7, 1, c(c7), c(192)),
            "bd_1": conv(1, 1, cur, c(c7)),
            "bd_2": conv(7, 1, c(c7), c(c7)),
            "bd_3": conv(1, 7, c(c7), c(c7)),
            "bd_4": conv(7, 1, c(c7), c(c7)),
            "bd_5": conv(1, 7, c(c7), c(192)),
            "bp": conv(1, 1, cur, c(192)),
        }
        cur = 4 * c(192)

    # -- grid reduction D ---------------------------------------------------
    p["mixed_d"] = {
        "b3_1": conv(1, 1, cur, c(192)),
        "b3_2": conv(3, 3, c(192), c(320)),     # /2 VALID
        "b7_1": conv(1, 1, cur, c(192)),
        "b7_2": conv(1, 7, c(192), c(192)),
        "b7_3": conv(7, 1, c(192), c(192)),
        "b7_4": conv(3, 3, c(192), c(192)),     # /2 VALID
        # maxpool /2
    }
    cur = c(320) + c(192) + cur

    # -- 2 × block E --------------------------------------------------------
    for i in range(2):
        p[f"mixed_e{i}"] = {
            "b1": conv(1, 1, cur, c(320)),
            "b3_1": conv(1, 1, cur, c(384)),
            "b3_2a": conv(1, 3, c(384), c(384)),
            "b3_2b": conv(3, 1, c(384), c(384)),
            "bd_1": conv(1, 1, cur, c(448)),
            "bd_2": conv(3, 3, c(448), c(384)),
            "bd_3a": conv(1, 3, c(384), c(384)),
            "bd_3b": conv(3, 1, c(384), c(384)),
            "bp": conv(1, 1, cur, c(192)),
        }
        cur = c(320) + 2 * c(384) + 2 * c(384) + c(192)

    # -- classifier ---------------------------------------------------------
    wk = kg()
    p["fc"] = {
        "w": (jax.random.normal(wk, (cur, cfg.num_classes), jnp.float32) * 0.01).astype(dt),
        "b": jnp.zeros((cfg.num_classes,), dt),
    }
    return p


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _conv2d(p, x, stride: int = 1, padding="SAME"):
    """conv + folded-BN affine + relu; f32 accumulation on the MXU."""
    from ..ops.quantize import asarray as _qw

    y = lax.conv_general_dilated(
        x,
        _qw(p["w"], x.dtype),
        (stride, stride),
        padding,
        dimension_numbers=_DN,
        preferred_element_type=jnp.float32,
    )
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return jax.nn.relu(y).astype(x.dtype)


def _maxpool(x, window: int = 3, stride: int = 2, padding="VALID"):
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        (1, window, window, 1),
        (1, stride, stride, 1),
        padding,
    )


def _avgpool3(x):
    """3x3 SAME average pool with a trace-time numpy divisor — feeding
    ``reduce_window(ones)`` to XLA instead makes the compiler
    constant-fold a full-size reduce-window per shape (the 8-12s
    slow_operation_alarm stalls in the inception stem; ops/windows.py)."""
    from ..ops.windows import same_pool_counts

    s = lax.reduce_window(
        x.astype(jnp.float32), 0.0, lax.add, (1, 3, 3, 1), (1, 1, 1, 1), "SAME"
    )
    n = same_pool_counts(int(x.shape[1]), int(x.shape[2]), 3, 3)
    return (s / n).astype(x.dtype)


def _block_a(p, x):
    b1 = _conv2d(p["b1"], x)
    b5 = _conv2d(p["b5_2"], _conv2d(p["b5_1"], x))
    bd = _conv2d(p["b3_3"], _conv2d(p["b3_2"], _conv2d(p["b3_1"], x)))
    bp = _conv2d(p["bp"], _avgpool3(x))
    return jnp.concatenate([b1, b5, bd, bp], axis=-1)


def _block_b(p, x):
    b3 = _conv2d(p["b3"], x, stride=2, padding="VALID")
    bd = _conv2d(
        p["bd_3"],
        _conv2d(p["bd_2"], _conv2d(p["bd_1"], x)),
        stride=2,
        padding="VALID",
    )
    bp = _maxpool(x)
    return jnp.concatenate([b3, bd, bp], axis=-1)


def _block_c(p, x):
    b1 = _conv2d(p["b1"], x)
    b7 = _conv2d(p["b7_3"], _conv2d(p["b7_2"], _conv2d(p["b7_1"], x)))
    bd = x
    for k in ("bd_1", "bd_2", "bd_3", "bd_4", "bd_5"):
        bd = _conv2d(p[k], bd)
    bp = _conv2d(p["bp"], _avgpool3(x))
    return jnp.concatenate([b1, b7, bd, bp], axis=-1)


def _block_d(p, x):
    b3 = _conv2d(p["b3_2"], _conv2d(p["b3_1"], x), stride=2, padding="VALID")
    b7 = x
    for k in ("b7_1", "b7_2", "b7_3"):
        b7 = _conv2d(p[k], b7)
    b7 = _conv2d(p["b7_4"], b7, stride=2, padding="VALID")
    bp = _maxpool(x)
    return jnp.concatenate([b3, b7, bp], axis=-1)


def _block_e(p, x):
    b1 = _conv2d(p["b1"], x)
    b3 = _conv2d(p["b3_1"], x)
    b3 = jnp.concatenate(
        [_conv2d(p["b3_2a"], b3), _conv2d(p["b3_2b"], b3)], axis=-1
    )
    bd = _conv2d(p["bd_2"], _conv2d(p["bd_1"], x))
    bd = jnp.concatenate(
        [_conv2d(p["bd_3a"], bd), _conv2d(p["bd_3b"], bd)], axis=-1
    )
    bp = _conv2d(p["bp"], _avgpool3(x))
    return jnp.concatenate([b1, b3, bd, bp], axis=-1)


def forward(cfg: InceptionConfig, params: Dict, images: jnp.ndarray) -> jnp.ndarray:
    """images [n, H, W, 3] float → logits [n, num_classes] (float32)."""
    x = images.astype(jnp.dtype(cfg.compute_dtype))
    s = params["stem"]
    x = _conv2d(s["c1"], x, stride=2, padding="VALID")
    x = _conv2d(s["c2"], x, padding="VALID")
    x = _conv2d(s["c3"], x)
    x = _maxpool(x)
    x = _conv2d(s["c4"], x)
    x = _conv2d(s["c5"], x, padding="VALID")
    x = _maxpool(x)
    for i in range(3):
        x = _block_a(params[f"mixed_a{i}"], x)
    x = _block_b(params["mixed_b"], x)
    for i in range(4):
        x = _block_c(params[f"mixed_c{i}"], x)
    x = _block_d(params["mixed_d"], x)
    for i in range(2):
        x = _block_e(params[f"mixed_e{i}"], x)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global average pool
    from ..ops.quantize import asarray as _qw

    fc = params["fc"]
    return x @ _qw(fc["w"], jnp.float32) + fc["b"].astype(jnp.float32)


# ---------------------------------------------------------------------------
# map_blocks program + synthetic data
# ---------------------------------------------------------------------------

def scoring_program(cfg: InceptionConfig, params: Dict):
    """A map_blocks program: image block [n, H, W, 3] → {"scores", "label"}.

    Params are closure-captured constants (≙ frozen-graph inference,
    core.py:42-56); the whole network compiles into one XLA program per
    block shape.
    """

    def program(images):
        logits = forward(cfg, params, images)
        return {
            "scores": jax.nn.softmax(logits, axis=-1).astype(jnp.float32),
            "label": jnp.argmax(logits, axis=-1).astype(jnp.int32),
        }

    return program


def synthetic_images(
    cfg: InceptionConfig, n: int, seed: int = 0
) -> np.ndarray:
    rng = np.random.default_rng(seed)
    side = cfg.image_size
    return rng.standard_normal((n, side, side, 3), dtype=np.float32)


def param_count(params) -> int:
    from ..ops.quantize import QuantizedTensor

    total = 0
    for v in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        shape = v.q.shape if isinstance(v, QuantizedTensor) else v.shape
        total += int(np.prod(shape))
    return total


def quantize_params(params: Dict) -> Dict:
    """Weight-only int8 for conv/dense weights; the folded-BN scale/bias
    and fc bias stay full precision (rank < 2)."""
    from ..ops.quantize import quantize_tree

    return quantize_tree(params)
