"""Multinomial logistic regression (MNIST-class scoring workload).

BASELINE config 3: "MNIST logistic-regression scoring: map_blocks over a
784-dim feature column". The model is a single dense layer + softmax —
one MXU matmul per block; scoring plugs into ``map_blocks`` as a plain
function program, and a data-parallel training step is provided for
completeness.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def init_params(
    num_features: int = 784,
    num_classes: int = 10,
    seed: int = 0,
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    k = jax.random.PRNGKey(seed)
    w = jax.random.normal(k, (num_features, num_classes), dtype) * 0.01
    b = jnp.zeros((num_classes,), dtype)
    return {"w": w, "b": b}


def scoring_program(params: Dict[str, jnp.ndarray]):
    """A map_blocks program: features block [n, d] → {"scores", "label"}.

    Params are closure-captured constants (≙ frozen tf.Variables,
    core.py:42-56).
    """

    def program(features):
        logits = features @ params["w"] + params["b"]
        probs = jax.nn.softmax(logits, axis=-1)
        return {
            "scores": probs.astype(features.dtype),
            "label": jnp.argmax(logits, axis=-1).astype(jnp.int32),
        }

    return program


def loss_fn(params, features, labels):
    logits = features @ params["w"] + params["b"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    return nll


def train_step(params, opt_state, features, labels, tx):
    import optax

    loss, grads = jax.value_and_grad(loss_fn)(params, features, labels)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def make_synthetic_mnist(
    n: int = 10_000, num_features: int = 784, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, num_features), dtype=np.float32)
    y = rng.integers(0, 10, size=(n,), dtype=np.int64)
    return x, y
