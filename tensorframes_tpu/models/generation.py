"""Autoregressive decoding for the transformer family (causal LM).

The reference is inference-only over frozen graphs; its model ceiling is
one Session.run per block. A causal decoder is the workload that shows
why the TPU formulation matters: generation is a ``lax.scan`` over
single-token steps against a **static-shape KV cache**, so the whole
decode loop is ONE compiled XLA program — no per-token dispatch, no
dynamic shapes, cache updates as ``dynamic_update_slice`` in HBM.

Reuses the transformer parameter tree (transformer.init_params) with
``causal=True``; logits tie to the token embedding (no separate LM head).
``generate_program`` plugs batched generation into ``map_blocks`` like
any other program: a frame of prompt rows in, a column of continuations
out.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .transformer import TransformerConfig, _layer_norm, _mlp


def gpt_tiny(**kw) -> TransformerConfig:
    """A small causal config for tests/demos."""
    kw.setdefault("vocab_size", 97)
    kw.setdefault("hidden", 32)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_layers", 2)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("causal", True)
    return TransformerConfig(**kw)


def gpt_small(**kw) -> TransformerConfig:
    """GPT-2-small-shaped causal config (bench workload)."""
    kw.setdefault("vocab_size", 32_000)
    kw.setdefault("hidden", 768)
    kw.setdefault("num_heads", 12)
    kw.setdefault("num_layers", 12)
    kw.setdefault("max_seq_len", 1024)
    kw.setdefault("causal", True)
    return TransformerConfig(**kw)


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------

def init_kv_cache(cfg: TransformerConfig, batch: int, length: Optional[int] = None) -> Dict:
    """Static-shape cache: k/v per layer, [b, heads, length, head_dim].

    ``length`` defaults to ``cfg.max_seq_len`` but callers that know the
    exact decode horizon (prompt + new tokens — ``generate`` does) should
    pass it: cache HBM and per-step attention FLOPs scale with it."""
    S = length or cfg.max_seq_len
    shape = (cfg.num_layers, batch, cfg.num_heads, S, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _forward_cached(
    cfg: TransformerConfig,
    params: Dict,
    tokens: jnp.ndarray,   # [b, t] chunk (prompt prefill or one decode step)
    cache: Dict,
    offset,                # scalar: positions [offset, offset+t) being written
) -> Tuple[jnp.ndarray, Dict]:
    """Run a chunk through the decoder, reading/writing the KV cache.

    Returns (hidden states [b, t, h], updated cache). Attention is dense
    over the cache's static horizon S = cache["k"].shape[3] (the decode
    horizon ``generate`` sizes it to, ≤ cfg.max_seq_len) with a validity
    mask (j <= offset + local position) — the standard static-shape
    decode formulation.
    """
    b, t = tokens.shape
    h, nh, hd = cfg.hidden, cfg.num_heads, cfg.head_dim
    S = cache["k"].shape[3]  # cache horizon (≤ cfg.max_seq_len)
    x = params["embed"]["tok"][tokens].astype(cfg.dtype)
    pos = offset + jnp.arange(t)
    x = x + params["embed"]["pos"][pos].astype(cfg.dtype)

    # mask [t, S]: chunk position i may attend cache slot j iff j <= offset+i
    valid = jnp.arange(S)[None, :] <= (offset + jnp.arange(t))[:, None]
    neg = jnp.asarray(-1e30, jnp.float32)

    from ..ops.quantize import asarray as _w

    new_cache = {"k": cache["k"], "v": cache["v"]}
    for li, p in enumerate(params["layers"]):
        y = _layer_norm(x, **p["ln1"])
        qkv = (y @ _w(p["attn"]["qkv"], y.dtype)).reshape(b, t, 3, nh, hd)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)           # [b, nh, t, hd]
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        ck = lax.dynamic_update_slice(
            new_cache["k"][li], k, (0, 0, offset, 0)
        )
        cv = lax.dynamic_update_slice(
            new_cache["v"][li], v, (0, 0, offset, 0)
        )
        new_cache["k"] = new_cache["k"].at[li].set(ck)
        new_cache["v"] = new_cache["v"].at[li].set(cv)
        # attend q against the whole (static) cache, masked to valid slots
        scores = jnp.einsum(
            "bntd,bnsd->bnts", q, ck, preferred_element_type=jnp.float32
        ) / float(np.sqrt(hd))
        scores = jnp.where(valid[None, None], scores, neg)
        w = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        ctx = jnp.einsum("bnts,bnsd->bntd", w, cv)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, h)
        x = x + ctx @ _w(p["attn"]["out"], x.dtype)
        x = x + _mlp(p["mlp"], _layer_norm(x, **p["ln2"]))
    return _layer_norm(x, **params["final_ln"]), new_cache


def _logits(cfg: TransformerConfig, params: Dict, hs: jnp.ndarray) -> jnp.ndarray:
    """Weight-tied LM head: hidden [.., h] → logits [.., vocab] (f32)."""
    emb = params["embed"]["tok"].astype(jnp.float32)
    return hs.astype(jnp.float32) @ emb.T


# ---------------------------------------------------------------------------
# Generation
# ---------------------------------------------------------------------------

def generate(
    cfg: TransformerConfig,
    params: Dict,
    prompts: jnp.ndarray,   # [b, prompt_len] int tokens
    max_new_tokens: int,
    temperature: float = 0.0,
    seed: int = 0,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations. Greedy when
    ``temperature == 0``, else categorical sampling.

    Prefill runs the prompt as one chunk; the decode loop is a
    ``lax.scan`` of single-token cached steps — one XLA program end to
    end. Returns [b, max_new_tokens] int32.
    """
    prompts = jnp.asarray(prompts)
    b, plen = prompts.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if plen + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len({plen}) + max_new_tokens({max_new_tokens}) exceeds "
            f"max_seq_len({cfg.max_seq_len})"
        )
    # size the cache to the actual decode horizon: HBM and per-step
    # attention FLOPs scale with it, and both lengths are static here
    cache = init_kv_cache(cfg, b, length=plen + max_new_tokens)
    hs, cache = _forward_cached(cfg, params, prompts, cache, 0)
    first = _pick(cfg, params, hs[:, -1], temperature, jax.random.PRNGKey(seed))

    def step(carry, rng):
        tok, pos, cache = carry
        hs, cache = _forward_cached(cfg, params, tok[:, None], cache, pos)
        nxt = _pick(cfg, params, hs[:, -1], temperature, rng)
        return (nxt, pos + 1, cache), nxt

    rngs = jax.random.split(jax.random.PRNGKey(seed + 1), max_new_tokens - 1)
    (_, _, _), rest = lax.scan(step, (first, plen, cache), rngs)
    return jnp.concatenate([first[:, None], rest.T], axis=1).astype(jnp.int32)


def _pick(cfg, params, h_last, temperature, rng):
    logits = _logits(cfg, params, h_last)
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits / temperature, axis=-1).astype(
        jnp.int32
    )


def generate_naive(
    cfg: TransformerConfig,
    params: Dict,
    prompts: jnp.ndarray,
    max_new_tokens: int,
) -> jnp.ndarray:
    """Cache-free greedy reference: re-run the full forward per token.

    O(n²) per token — exists as the correctness oracle for the cached
    path (tests assert identical outputs), mirroring the reference's
    slow-but-obviously-correct execution stance (DebugRowOps.scala:277-280).
    """
    from . import transformer as tr

    toks = jnp.asarray(prompts)
    for _ in range(max_new_tokens):
        hs = tr.forward(cfg, params, toks)
        nxt = jnp.argmax(_logits(cfg, params, hs[:, -1]), axis=-1)
        toks = jnp.concatenate([toks, nxt[:, None].astype(toks.dtype)], axis=1)
    return toks[:, prompts.shape[1]:].astype(jnp.int32)


def generate_program(
    cfg: TransformerConfig,
    params: Dict,
    max_new_tokens: int,
    temperature: float = 0.0,
    seed: int = 0,
):
    """map_blocks program: prompt block [n, plen] → {"generated": [n, new]}.

    When sampling (``temperature > 0``), a content-derived salt folds
    into the seed so different blocks of a multi-block frame draw
    different noise (a pure program cannot see its block index — identical
    blocks still sample identically, which is at least deterministic)."""

    def program(prompts):
        salt = (
            prompts.astype(jnp.uint32).sum() if temperature > 0.0 else 0
        )
        return {
            "generated": generate(
                cfg, params, prompts, max_new_tokens, temperature, seed + salt
            )
        }

    return program
