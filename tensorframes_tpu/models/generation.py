"""Autoregressive decoding for the transformer family (causal LM).

The reference is inference-only over frozen graphs; its model ceiling is
one Session.run per block. A causal decoder is the workload that shows
why the TPU formulation matters: generation is a ``lax.scan`` over
single-token steps against a **static-shape KV cache**, so the whole
decode loop is ONE compiled XLA program — no per-token dispatch, no
dynamic shapes, cache updates as ``dynamic_update_slice`` in HBM.

Reuses the transformer parameter tree (transformer.init_params) with
``causal=True``; logits tie to the token embedding (no separate LM head).
``generate_program`` plugs batched generation into ``map_blocks`` like
any other program: a frame of prompt rows in, a column of continuations
out.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .transformer import TransformerConfig, _layer_norm, _mlp


def gpt_tiny(**kw) -> TransformerConfig:
    """A small causal config for tests/demos."""
    kw.setdefault("vocab_size", 97)
    kw.setdefault("hidden", 32)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_layers", 2)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("causal", True)
    return TransformerConfig(**kw)


def gpt_small(**kw) -> TransformerConfig:
    """GPT-2-small-shaped causal config (bench workload)."""
    kw.setdefault("vocab_size", 32_000)
    kw.setdefault("hidden", 768)
    kw.setdefault("num_heads", 12)
    kw.setdefault("num_layers", 12)
    kw.setdefault("max_seq_len", 1024)
    kw.setdefault("causal", True)
    return TransformerConfig(**kw)


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------

def init_kv_cache(
    cfg: TransformerConfig,
    batch: int,
    length: Optional[int] = None,
    quant: bool = False,
) -> Dict:
    """Static-shape cache: k/v per layer, [b, heads, length, head_dim].

    ``length`` defaults to ``cfg.max_seq_len`` but callers that know the
    exact decode horizon (prompt + new tokens — ``generate`` does) should
    pass it: cache HBM and per-step attention FLOPs scale with it.

    ``quant=True`` stores k/v as int8 with one f32 scale per cache slot
    (per layer/batch/head/position — absmax over head_dim): decode is
    HBM-bandwidth-bound and the cache is the per-step traffic that GROWS
    with sequence length, so int8 halves it vs a bf16 cache (4× vs f32)
    at a ~1.6% scale overhead (4 bytes per head_dim=64 slot). Reads
    dequantize inside the attention contractions — the scale commutes
    out of the score contraction and folds into the softmax weights for
    the context one (see ``_forward_cached``); no dequantized copy is
    materialized (VERDICT r3 #4)."""
    S = length or cfg.max_seq_len
    shape = (cfg.num_layers, batch, cfg.num_heads, S, cfg.head_dim)
    if quant:
        sshape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.ones(sshape, jnp.float32),
            "v_scale": jnp.ones(sshape, jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def kv_cache_nbytes(cache: Dict) -> int:
    """Total cache HBM footprint in bytes — the number int8 KV
    quantization exists to shrink."""
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in cache.values())


def _quantize_slots(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 per-slot quantization over the trailing head_dim:
    [b, nh, t, hd] → (int8 values, f32 scales [b, nh, t, 1]). One-line
    wrapper over the shared ``ops/quantize.quantize`` scheme (keeping
    every axis but head_dim as channel axes) so the zero-guard/rounding
    conventions cannot diverge from the weight path."""
    from ..ops.quantize import quantize

    qt = quantize(x.astype(jnp.float32), channel_axis=(0, 1, 2))
    return qt.q, qt.scale


def _forward_cached(
    cfg: TransformerConfig,
    params: Dict,
    tokens: jnp.ndarray,   # [b, t] chunk (prompt prefill or one decode step)
    cache: Dict,
    offset,                # scalar: positions [offset, offset+t) being written
) -> Tuple[jnp.ndarray, Dict]:
    """Run a chunk through the decoder, reading/writing the KV cache.

    Returns (hidden states [b, t, h], updated cache). Attention is dense
    over the cache's static horizon S = cache["k"].shape[3] (the decode
    horizon ``generate`` sizes it to, ≤ cfg.max_seq_len) with a validity
    mask (j <= offset + local position) — the standard static-shape
    decode formulation.
    """
    b, t = tokens.shape
    h, nh, hd = cfg.hidden, cfg.num_heads, cfg.head_dim
    S = cache["k"].shape[3]  # cache horizon (≤ cfg.max_seq_len)
    x = params["embed"]["tok"][tokens].astype(cfg.dtype)
    pos = offset + jnp.arange(t)
    x = x + params["embed"]["pos"][pos].astype(cfg.dtype)

    # mask [t, S]: chunk position i may attend cache slot j iff j <= offset+i
    valid = jnp.arange(S)[None, :] <= (offset + jnp.arange(t))[:, None]
    neg = jnp.asarray(-1e30, jnp.float32)

    from ..ops.quantize import matmul as _mm

    quant = "k_scale" in cache  # int8 cache (init_kv_cache(quant=True))
    new_cache = dict(cache)
    for li, p in enumerate(params["layers"]):
        y = _layer_norm(x, **p["ln1"])
        qkv = _mm(y, p["attn"]["qkv"]).reshape(b, t, 3, nh, hd)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)           # [b, nh, t, hd]
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        if quant:
            k, k_s = _quantize_slots(k)
            v, v_s = _quantize_slots(v)
            for key, chunk in (("k_scale", k_s), ("v_scale", v_s)):
                new_cache[key] = lax.dynamic_update_slice(
                    new_cache[key], chunk[None], (li, 0, 0, offset, 0)
                )
        # ONE 5-D dynamic_update_slice per tensor (li is a static index
        # here, the loop is python): the previous slice-out → update →
        # .at[li].set chain rematerialized the whole [L,b,nh,S,hd]
        # cache per layer when XLA failed to prove in-place — the CPU
        # cost model charged ~24x the analytic step traffic for
        # gpt_small (dev/int8_breakeven.py); a single DUS on the full
        # array aliases reliably
        new_cache["k"] = lax.dynamic_update_slice(
            new_cache["k"], k[None], (li, 0, 0, offset, 0)
        )
        new_cache["v"] = lax.dynamic_update_slice(
            new_cache["v"], v[None], (li, 0, 0, offset, 0)
        )
        ck = new_cache["k"][li]  # li is static: a plain slice, no scatter
        cv = new_cache["v"][li]
        if quant:
            # int8 k/v stream from HBM and convert on-chip; each scale
            # is per cache SLOT (constant along the contracted head_dim
            # for scores, so it commutes out; for the context
            # contraction over s it folds into the softmax weights)
            ck_s = new_cache["k_scale"][li][..., 0]       # [b, nh, S]
            cv_s = new_cache["v_scale"][li][..., 0]
            ck = ck.astype(cfg.dtype)
            cv = cv.astype(cfg.dtype)
        # attend q against the whole (static) cache, masked to valid slots
        scores = jnp.einsum(
            "bntd,bnsd->bnts", q, ck, preferred_element_type=jnp.float32
        ) / float(np.sqrt(hd))
        if quant:
            scores = scores * ck_s[:, :, None, :]
        scores = jnp.where(valid[None, None], scores, neg)
        w = jax.nn.softmax(scores, axis=-1)
        if quant:
            w = (w * cv_s[:, :, None, :]).astype(cfg.dtype)
        else:
            w = w.astype(cfg.dtype)
        ctx = jnp.einsum("bnts,bnsd->bntd", w, cv)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, h)
        x = x + _mm(ctx, p["attn"]["out"])
        x = x + _mlp(p["mlp"], _layer_norm(x, **p["ln2"]))
    return _layer_norm(x, **params["final_ln"]), new_cache


def _logits(cfg: TransformerConfig, params: Dict, hs: jnp.ndarray) -> jnp.ndarray:
    """Weight-tied LM head: hidden [.., h] → logits [.., vocab] (f32)."""
    emb = params["embed"]["tok"].astype(jnp.float32)
    return hs.astype(jnp.float32) @ emb.T


# ---------------------------------------------------------------------------
# Generation
# ---------------------------------------------------------------------------

def generate(
    cfg: TransformerConfig,
    params: Dict,
    prompts: jnp.ndarray,   # [b, prompt_len] int tokens
    max_new_tokens: int,
    temperature: float = 0.0,
    seed: int = 0,
    kv_quant: bool = False,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations. Greedy when
    ``temperature == 0``, else categorical sampling.

    Prefill runs the prompt as one chunk; the decode loop is a
    ``lax.scan`` of single-token cached steps — one XLA program end to
    end. Returns [b, max_new_tokens] int32. ``kv_quant=True`` keeps the
    KV cache int8 in HBM (see :func:`init_kv_cache`).
    """
    prompts = jnp.asarray(prompts)
    b, plen = prompts.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if plen + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len({plen}) + max_new_tokens({max_new_tokens}) exceeds "
            f"max_seq_len({cfg.max_seq_len})"
        )
    # size the cache to the actual decode horizon: HBM and per-step
    # attention FLOPs scale with it, and both lengths are static here
    cache = init_kv_cache(cfg, b, length=plen + max_new_tokens, quant=kv_quant)
    hs, cache = _forward_cached(cfg, params, prompts, cache, 0)
    first = _pick(cfg, params, hs[:, -1], temperature, jax.random.PRNGKey(seed))

    def step(carry, rng):
        tok, pos, cache = carry
        hs, cache = _forward_cached(cfg, params, tok[:, None], cache, pos)
        nxt = _pick(cfg, params, hs[:, -1], temperature, rng)
        return (nxt, pos + 1, cache), nxt

    rngs = jax.random.split(jax.random.PRNGKey(seed + 1), max_new_tokens - 1)
    (_, _, _), rest = lax.scan(step, (first, plen, cache), rngs)
    return jnp.concatenate([first[:, None], rest.T], axis=1).astype(jnp.int32)


def _pick(cfg, params, h_last, temperature, rng):
    logits = _logits(cfg, params, h_last)
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits / temperature, axis=-1).astype(
        jnp.int32
    )


def generate_naive(
    cfg: TransformerConfig,
    params: Dict,
    prompts: jnp.ndarray,
    max_new_tokens: int,
) -> jnp.ndarray:
    """Cache-free greedy reference: re-run the full forward per token.

    O(n²) per token — exists as the correctness oracle for the cached
    path (tests assert identical outputs), mirroring the reference's
    slow-but-obviously-correct execution stance (DebugRowOps.scala:277-280).
    """
    from . import transformer as tr

    toks = jnp.asarray(prompts)
    for _ in range(max_new_tokens):
        hs = tr.forward(cfg, params, toks)
        nxt = jnp.argmax(_logits(cfg, params, hs[:, -1]), axis=-1)
        toks = jnp.concatenate([toks, nxt[:, None].astype(toks.dtype)], axis=1)
    return toks[:, prompts.shape[1]:].astype(jnp.int32)


def generate_program(
    cfg: TransformerConfig,
    params: Dict,
    max_new_tokens: int,
    temperature: float = 0.0,
    seed: int = 0,
    kv_quant: bool = False,
):
    """map_blocks program: prompt block [n, plen] → {"generated": [n, new]}.

    When sampling (``temperature > 0``), a content-derived salt folds
    into the seed so different blocks of a multi-block frame draw
    different noise (a pure program cannot see its block index — identical
    blocks still sample identically, which is at least deterministic)."""

    def program(prompts):
        salt = (
            prompts.astype(jnp.uint32).sum() if temperature > 0.0 else 0
        )
        return {
            "generated": generate(
                cfg, params, prompts, max_new_tokens, temperature,
                seed + salt, kv_quant=kv_quant,
            )
        }

    return program
