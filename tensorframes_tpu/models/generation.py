"""Autoregressive decoding for the transformer family (causal LM).

The reference is inference-only over frozen graphs; its model ceiling is
one Session.run per block. A causal decoder is the workload that shows
why the TPU formulation matters: generation is a ``lax.scan`` over
single-token steps against a **static-shape KV cache**, so the whole
decode loop is ONE compiled XLA program — no per-token dispatch, no
dynamic shapes, cache updates as ``dynamic_update_slice`` in HBM.

Reuses the transformer parameter tree (transformer.init_params) with
``causal=True``; logits tie to the token embedding (no separate LM head).
``generate_program`` plugs batched generation into ``map_blocks`` like
any other program: a frame of prompt rows in, a column of continuations
out.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .transformer import TransformerConfig, _layer_norm, _mlp


def gpt_tiny(**kw) -> TransformerConfig:
    """A small causal config for tests/demos."""
    kw.setdefault("vocab_size", 97)
    kw.setdefault("hidden", 32)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_layers", 2)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("causal", True)
    return TransformerConfig(**kw)


def gpt_small(**kw) -> TransformerConfig:
    """GPT-2-small-shaped causal config (bench workload)."""
    kw.setdefault("vocab_size", 32_000)
    kw.setdefault("hidden", 768)
    kw.setdefault("num_heads", 12)
    kw.setdefault("num_layers", 12)
    kw.setdefault("max_seq_len", 1024)
    kw.setdefault("causal", True)
    return TransformerConfig(**kw)


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------

def init_kv_cache(
    cfg: TransformerConfig,
    batch: int,
    length: Optional[int] = None,
    quant: bool = False,
) -> Dict:
    """Static-shape cache: k/v per layer, [b, heads, length, head_dim].

    ``length`` defaults to ``cfg.max_seq_len`` but callers that know the
    exact decode horizon (prompt + new tokens — ``generate`` does) should
    pass it: cache HBM and per-step attention FLOPs scale with it.

    ``quant=True`` stores k/v as int8 with one f32 scale per cache slot
    (per layer/batch/head/position — absmax over head_dim): decode is
    HBM-bandwidth-bound and the cache is the per-step traffic that GROWS
    with sequence length, so int8 halves it vs a bf16 cache (4× vs f32)
    at a ~1.6% scale overhead (4 bytes per head_dim=64 slot). Reads
    dequantize inside the attention contractions — the scale commutes
    out of the score contraction and folds into the softmax weights for
    the context one (see ``_forward_cached``); no dequantized copy is
    materialized (VERDICT r3 #4)."""
    S = length or cfg.max_seq_len
    shape = (cfg.num_layers, batch, cfg.num_heads, S, cfg.head_dim)
    if quant:
        sshape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.ones(sshape, jnp.float32),
            "v_scale": jnp.ones(sshape, jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def kv_cache_nbytes(cache: Dict) -> int:
    """Total cache HBM footprint in bytes — the number int8 KV
    quantization exists to shrink."""
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in cache.values())


def _quantize_slots(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 per-slot quantization over the trailing head_dim:
    [b, nh, t, hd] → (int8 values, f32 scales [b, nh, t, 1]). One-line
    wrapper over the shared ``ops/quantize.quantize`` scheme (keeping
    every axis but head_dim as channel axes) so the zero-guard/rounding
    conventions cannot diverge from the weight path."""
    from ..ops.quantize import quantize

    qt = quantize(x.astype(jnp.float32), channel_axis=(0, 1, 2))
    return qt.q, qt.scale


def _forward_cached(
    cfg: TransformerConfig,
    params: Dict,
    tokens: jnp.ndarray,   # [b, t] chunk (prompt prefill or one decode step)
    cache: Dict,
    offset,                # scalar: positions [offset, offset+t) being written
) -> Tuple[jnp.ndarray, Dict]:
    """Run a chunk through the decoder, reading/writing the KV cache.

    Returns (hidden states [b, t, h], updated cache). Attention is dense
    over the cache's static horizon S = cache["k"].shape[3] (the decode
    horizon ``generate`` sizes it to, ≤ cfg.max_seq_len) with a validity
    mask (j <= offset + local position) — the standard static-shape
    decode formulation.
    """
    b, t = tokens.shape
    h, nh, hd = cfg.hidden, cfg.num_heads, cfg.head_dim
    S = cache["k"].shape[3]  # cache horizon (≤ cfg.max_seq_len)
    x = params["embed"]["tok"][tokens].astype(cfg.dtype)
    pos = offset + jnp.arange(t)
    x = x + params["embed"]["pos"][pos].astype(cfg.dtype)

    # mask [t, S]: chunk position i may attend cache slot j iff j <= offset+i
    valid = jnp.arange(S)[None, :] <= (offset + jnp.arange(t))[:, None]
    neg = jnp.asarray(-1e30, jnp.float32)

    from ..ops.quantize import matmul as _mm

    quant = "k_scale" in cache  # int8 cache (init_kv_cache(quant=True))
    new_cache = dict(cache)
    for li, p in enumerate(params["layers"]):
        y = _layer_norm(x, **p["ln1"])
        qkv = _mm(y, p["attn"]["qkv"]).reshape(b, t, 3, nh, hd)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)           # [b, nh, t, hd]
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        if quant:
            k, k_s = _quantize_slots(k)
            v, v_s = _quantize_slots(v)
            for key, chunk in (("k_scale", k_s), ("v_scale", v_s)):
                new_cache[key] = lax.dynamic_update_slice(
                    new_cache[key], chunk[None], (li, 0, 0, offset, 0)
                )
        # ONE 5-D dynamic_update_slice per tensor (li is a static index
        # here, the loop is python): the previous slice-out → update →
        # .at[li].set chain rematerialized the whole [L,b,nh,S,hd]
        # cache per layer when XLA failed to prove in-place — the CPU
        # cost model charged ~24x the analytic step traffic for
        # gpt_small (dev/int8_breakeven.py); a single DUS on the full
        # array aliases reliably
        new_cache["k"] = lax.dynamic_update_slice(
            new_cache["k"], k[None], (li, 0, 0, offset, 0)
        )
        new_cache["v"] = lax.dynamic_update_slice(
            new_cache["v"], v[None], (li, 0, 0, offset, 0)
        )
        ck = new_cache["k"][li]  # li is static: a plain slice, no scatter
        cv = new_cache["v"][li]
        if quant:
            # int8 k/v stream from HBM and convert on-chip; each scale
            # is per cache SLOT (constant along the contracted head_dim
            # for scores, so it commutes out; for the context
            # contraction over s it folds into the softmax weights)
            ck_s = new_cache["k_scale"][li][..., 0]       # [b, nh, S]
            cv_s = new_cache["v_scale"][li][..., 0]
            ck = ck.astype(cfg.dtype)
            cv = cv.astype(cfg.dtype)
        # attend q against the whole (static) cache, masked to valid slots
        scores = jnp.einsum(
            "bntd,bnsd->bnts", q, ck, preferred_element_type=jnp.float32
        ) / float(np.sqrt(hd))
        if quant:
            scores = scores * ck_s[:, :, None, :]
        scores = jnp.where(valid[None, None], scores, neg)
        w = jax.nn.softmax(scores, axis=-1)
        if quant:
            w = (w * cv_s[:, :, None, :]).astype(cfg.dtype)
        else:
            w = w.astype(cfg.dtype)
        ctx = jnp.einsum("bnts,bnsd->bntd", w, cv)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, h)
        x = x + _mm(ctx, p["attn"]["out"])
        x = x + _mlp(p["mlp"], _layer_norm(x, **p["ln2"]))
    return _layer_norm(x, **params["final_ln"]), new_cache


def _logits(cfg: TransformerConfig, params: Dict, hs: jnp.ndarray) -> jnp.ndarray:
    """Weight-tied LM head: hidden [.., h] → logits [.., vocab] (f32)."""
    emb = params["embed"]["tok"].astype(jnp.float32)
    return hs.astype(jnp.float32) @ emb.T


# ---------------------------------------------------------------------------
# Generation
# ---------------------------------------------------------------------------

def generate(
    cfg: TransformerConfig,
    params: Dict,
    prompts: jnp.ndarray,   # [b, prompt_len] int tokens
    max_new_tokens: int,
    temperature: float = 0.0,
    seed: int = 0,
    kv_quant: bool = False,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations. Greedy when
    ``temperature == 0``, else categorical sampling.

    Prefill runs the prompt as one chunk; the decode loop is a
    ``lax.scan`` of single-token cached steps — one XLA program end to
    end. Returns [b, max_new_tokens] int32. ``kv_quant=True`` keeps the
    KV cache int8 in HBM (see :func:`init_kv_cache`).
    """
    prompts = jnp.asarray(prompts)
    b, plen = prompts.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if plen + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len({plen}) + max_new_tokens({max_new_tokens}) exceeds "
            f"max_seq_len({cfg.max_seq_len})"
        )
    # size the cache to the actual decode horizon: HBM and per-step
    # attention FLOPs scale with it, and both lengths are static here
    cache = init_kv_cache(cfg, b, length=plen + max_new_tokens, quant=kv_quant)
    hs, cache = _forward_cached(cfg, params, prompts, cache, 0)
    first = _pick(cfg, params, hs[:, -1], temperature, jax.random.PRNGKey(seed))

    def step(carry, rng):
        tok, pos, cache = carry
        hs, cache = _forward_cached(cfg, params, tok[:, None], cache, pos)
        nxt = _pick(cfg, params, hs[:, -1], temperature, rng)
        return (nxt, pos + 1, cache), nxt

    rngs = jax.random.split(jax.random.PRNGKey(seed + 1), max_new_tokens - 1)
    (_, _, _), rest = lax.scan(step, (first, plen, cache), rngs)
    return jnp.concatenate([first[:, None], rest.T], axis=1).astype(jnp.int32)


def _pick(cfg, params, h_last, temperature, rng):
    logits = _logits(cfg, params, h_last)
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits / temperature, axis=-1).astype(
        jnp.int32
    )


def generate_naive(
    cfg: TransformerConfig,
    params: Dict,
    prompts: jnp.ndarray,
    max_new_tokens: int,
) -> jnp.ndarray:
    """Cache-free greedy reference: re-run the full forward per token.

    O(n²) per token — exists as the correctness oracle for the cached
    path (tests assert identical outputs), mirroring the reference's
    slow-but-obviously-correct execution stance (DebugRowOps.scala:277-280).
    """
    from . import transformer as tr

    toks = jnp.asarray(prompts)
    for _ in range(max_new_tokens):
        hs = tr.forward(cfg, params, toks)
        nxt = jnp.argmax(_logits(cfg, params, hs[:, -1]), axis=-1)
        toks = jnp.concatenate([toks, nxt[:, None].astype(toks.dtype)], axis=1)
    return toks[:, prompts.shape[1]:].astype(jnp.int32)


# ---------------------------------------------------------------------------
# Paged KV: the pool layout + step functions the serving decode engine
# (serving/decode.py) runs through aot_jit. Same int8-KV scheme as
# init_kv_cache(quant=True) — int8 k/v with one f32 scale per cache slot
# — but laid out page-major so a pool of fixed-size pages can be shared
# by many sequences through per-sequence page tables (vLLM-style paged
# attention, ISSUE 11).
# ---------------------------------------------------------------------------

def init_paged_kv(
    cfg: TransformerConfig, num_pages: int, page_size: int
) -> Dict[str, jnp.ndarray]:
    """The paged int8 KV pool as columnar state: page-major arrays
    ``[num_pages, layers, heads, page_size, head_dim]`` (int8 k/v, f32
    per-slot scales) — each array is one frame column with pages as
    rows (``serving.kvpool.PagedKVPool.as_frame``). Page 0 is the
    reserved NULL page: padding slots and masked prefill positions
    write there, and attention masks guarantee it is never read
    unmasked, so its garbage contents cannot reach any output."""
    if num_pages < 2:
        raise ValueError(
            f"num_pages must be >= 2 (page 0 is the reserved null "
            f"page), got {num_pages}"
        )
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    shape = (num_pages, cfg.num_layers, cfg.num_heads, page_size,
             cfg.head_dim)
    sshape = shape[:-1] + (1,)
    return {
        "k": jnp.zeros(shape, jnp.int8),
        "v": jnp.zeros(shape, jnp.int8),
        "k_scale": jnp.ones(sshape, jnp.float32),
        "v_scale": jnp.ones(sshape, jnp.float32),
    }


def paged_kv_nbytes(pool: Dict[str, jnp.ndarray]) -> int:
    """Pool HBM footprint in bytes (the budget eviction exists to honor)."""
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a in pool.values())


def paged_prefill_fn(cfg: TransformerConfig, page_size: int,
                     max_pages: int):
    """Build the prefill step for one sequence: ``fn(params, pool,
    tokens[T], length, table[max_pages]) -> (pool, first_token)``.

    ``tokens`` is the prompt padded to a ladder bucket T; ``length`` is
    the true prompt length (a traced int32 scalar — one executable per
    T bucket serves every prompt length in it). Writes positions
    ``[0, length)`` into the sequence's pages through ``table`` (padding
    positions route to the null page), attends causally within the
    chunk over the QUANTIZED k/v — exactly the values decode steps will
    read back from the pool — and returns the first generated token
    (greedy argmax at position ``length - 1``).
    """

    def prefill(params, pool, tokens, length, table):
        from ..ops.quantize import matmul as _mm

        (T,) = tokens.shape
        h, nh, hd = cfg.hidden, cfg.num_heads, cfg.head_dim
        tpos = jnp.arange(T)
        x = params["embed"]["tok"][tokens].astype(cfg.dtype)
        x = x + params["embed"]["pos"][tpos].astype(cfg.dtype)
        valid = tpos < length                       # real prompt slots
        # per-position pool coordinates; masked positions → null page 0
        pg = jnp.where(valid, table[jnp.minimum(tpos // page_size,
                                                max_pages - 1)], 0)
        off = tpos % page_size
        causal = tpos[None, :] <= tpos[:, None]     # [T, T]
        neg = jnp.asarray(-1e30, jnp.float32)
        pool = dict(pool)
        for li, p in enumerate(params["layers"]):
            y = _layer_norm(x, **p["ln1"])
            qkv = _mm(y, p["attn"]["qkv"]).reshape(T, 3, nh, hd)
            q = qkv[:, 0].transpose(1, 0, 2)        # [nh, T, hd]
            k = qkv[:, 1].transpose(1, 0, 2)
            v = qkv[:, 2].transpose(1, 0, 2)
            kq, ks = _quantize_slots(k[None])       # [1, nh, T, hd]
            vq, vs = _quantize_slots(v[None])
            kq, ks, vq, vs = kq[0], ks[0], vq[0], vs[0]
            # ONE scatter per tensor per layer: advanced indices at the
            # page and offset axes broadcast to [T, nh, ...] views
            pool["k"] = pool["k"].at[pg, li, :, off].set(
                kq.transpose(1, 0, 2)
            )
            pool["v"] = pool["v"].at[pg, li, :, off].set(
                vq.transpose(1, 0, 2)
            )
            pool["k_scale"] = pool["k_scale"].at[pg, li, :, off].set(
                ks.transpose(1, 0, 2)
            )
            pool["v_scale"] = pool["v_scale"].at[pg, li, :, off].set(
                vs.transpose(1, 0, 2)
            )
            # attend within the chunk over the quantized k/v — the same
            # dequantize-commutes formulation as _forward_cached, so
            # prefill sees exactly what the pool now holds
            kd = kq.astype(cfg.dtype)
            scores = jnp.einsum(
                "ntd,nsd->nts", q, kd,
                preferred_element_type=jnp.float32,
            ) / float(np.sqrt(hd))
            scores = scores * ks[..., 0][:, None, :]
            scores = jnp.where(causal[None], scores, neg)
            w = jax.nn.softmax(scores, axis=-1)
            w = (w * vs[..., 0][:, None, :]).astype(cfg.dtype)
            ctx = jnp.einsum("nts,nsd->ntd", w, vq.astype(cfg.dtype))
            ctx = ctx.transpose(1, 0, 2).reshape(T, h)
            x = x + _mm(ctx, p["attn"]["out"])
            x = x + _mlp(p["mlp"], _layer_norm(x, **p["ln2"]))
        hs = _layer_norm(x, **params["final_ln"])
        last = jnp.take(hs, length - 1, axis=0)
        first = jnp.argmax(
            _logits(cfg, params, last), axis=-1
        ).astype(jnp.int32)
        return pool, first

    return prefill


def paged_suffix_prefill_fn(cfg: TransformerConfig, page_size: int,
                            max_pages: int):
    """Build the prefix-cache suffix prefill: ``fn(params, pool,
    tokens[T], start, length, table[max_pages]) -> (pool, first)``.

    The prefix-cache join path (serving/decode.py): positions
    ``[0, start)`` are already resident in the sequence's pages (shared
    pages matched by content hash), so only the suffix ``tokens[:length]``
    is processed — written at positions ``[start, start + length)``
    through ``table`` and attended against the WHOLE sequence via the
    paged gather (`paged_attention_reference`, the same dequantize-
    commutes chain decode steps read through, so a cache-hit join emits
    exactly the tokens full prefill + decode would). ``start`` and
    ``length`` are traced int32 scalars — one executable per suffix
    bucket T serves every (start, length) in it; ``start=0`` degrades
    to a full prefill through the gather chain.
    """

    def suffix_prefill(params, pool, tokens, start, length, table):
        from ..kernels.decode_attention import paged_attention_reference
        from ..ops.quantize import matmul as _mm

        (T,) = tokens.shape
        h, nh, hd = cfg.hidden, cfg.num_heads, cfg.head_dim
        tpos = jnp.arange(T)
        valid = tpos < length
        seqpos = start + tpos                   # absolute KV positions
        emb_pos = jnp.minimum(
            seqpos, params["embed"]["pos"].shape[0] - 1
        )
        x = params["embed"]["tok"][tokens].astype(cfg.dtype)
        x = x + params["embed"]["pos"][emb_pos].astype(cfg.dtype)
        pg = jnp.where(
            valid,
            table[jnp.minimum(seqpos // page_size, max_pages - 1)], 0,
        )
        off = seqpos % page_size
        # per-row gather coordinates: each suffix position attends the
        # sequence's own pages masked to j <= its absolute position;
        # padding rows carry null tables and position 0
        tables_r = jnp.where(valid[:, None], table[None, :], 0)
        pos_r = jnp.where(valid, seqpos, 0)
        pool = dict(pool)
        for li, p in enumerate(params["layers"]):
            y = _layer_norm(x, **p["ln1"])
            qkv = _mm(y, p["attn"]["qkv"]).reshape(T, 3, nh, hd)
            q = qkv[:, 0]                       # [T, nh, hd]
            k = qkv[:, 1]
            v = qkv[:, 2]
            kq, ks = _quantize_slots(k[:, :, None, :])
            vq, vs = _quantize_slots(v[:, :, None, :])
            kq, ks = kq[:, :, 0], ks[:, :, 0]
            vq, vs = vq[:, :, 0], vs[:, :, 0]
            # write first, then gather-attend — row i sees positions
            # 0..start+i including its own token, the decode-step order
            pool["k"] = pool["k"].at[pg, li, :, off].set(kq)
            pool["v"] = pool["v"].at[pg, li, :, off].set(vq)
            pool["k_scale"] = pool["k_scale"].at[pg, li, :, off].set(ks)
            pool["v_scale"] = pool["v_scale"].at[pg, li, :, off].set(vs)
            ctx = paged_attention_reference(
                q, pool["k"], pool["v"],
                pool["k_scale"], pool["v_scale"],
                li, tables_r, pos_r,
            ).reshape(T, h)
            x = x + _mm(ctx, p["attn"]["out"])
            x = x + _mlp(p["mlp"], _layer_norm(x, **p["ln2"]))
        hs = _layer_norm(x, **params["final_ln"])
        last = jnp.take(hs, length - 1, axis=0)
        first = jnp.argmax(
            _logits(cfg, params, last), axis=-1
        ).astype(jnp.int32)
        return pool, first

    return suffix_prefill


def paged_page_ops_fns(max_pages: int):
    """Build the page-granular pool maintenance steps the KV memory
    hierarchy dispatches (serving/decode.py, ISSUE 19) — all shapes
    fixed, so each is ONE warmable executable:

    * ``extract(pool, idx[max_pages]) -> {col: [max_pages, ...]}`` —
      gather a sequence's pages out of the pool (host-swap-out reads
      this, then trims to the real page count; padding entries gather
      the null page and are discarded).
    * ``restore(pool, idx[max_pages], k, v, k_scale, v_scale) -> pool``
      — scatter swapped-in page payloads back (padding entries target
      the null page, whose contents are garbage by contract).
    * ``copy_page(pool, src, dst) -> pool`` — duplicate one page
      (copy-on-extend: a ragged-tail prefix-cache hit copies the shared
      page before writing into it).
    """

    def extract(pool, idx):
        return {name: col[idx] for name, col in pool.items()}

    def restore(pool, idx, k, v, k_scale, v_scale):
        pool = dict(pool)
        pool["k"] = pool["k"].at[idx].set(k)
        pool["v"] = pool["v"].at[idx].set(v)
        pool["k_scale"] = pool["k_scale"].at[idx].set(k_scale)
        pool["v_scale"] = pool["v_scale"].at[idx].set(v_scale)
        return pool

    def copy_page(pool, src, dst):
        pool = dict(pool)
        for name in ("k", "v", "k_scale", "v_scale"):
            pool[name] = pool[name].at[dst].set(pool[name][src])
        return pool

    return extract, restore, copy_page


def paged_decode_step_fn(cfg: TransformerConfig, page_size: int,
                         max_pages: int,
                         attn_kernel: Optional[str] = None):
    """Build the batched decode step: ``fn(params, pool, tokens[S],
    pos[S], tables[S, max_pages]) -> (pool, next_tokens[S])``.

    One token per running slot: writes each slot's new k/v into its
    current page (padding slots carry all-null tables and write into
    the null page), gathers each slot's pages back as a contiguous
    ``[S, heads, max_pages*page_size, head_dim]`` context (the paged KV
    gather), and attends masked to ``j <= pos``. Every slot's row is
    computed independently (the map_rows/vmap convention), which is
    what makes a batched step bit-identical per slot to a solo step —
    the serving bench hard-gates it.

    ``attn_kernel="pallas"`` replaces the gather→dequant→attend chain
    with the fused paged int8-KV pallas kernel
    (:func:`tensorframes_tpu.kernels.decode_attention.paged_decode_attention`
    — pages stream HBM→VMEM through the page table and dequantize
    in-register; no materialized gather copy). The choice is a counted
    cost-model decision made ONCE per engine
    (``plan/rules.decide_decode_attention``), so batched and solo
    steps always trace the same lowering and the bit-identity gates
    hold either way.
    """

    def step(params, pool, tokens, pos, tables):
        from ..ops.quantize import matmul as _mm

        (S,) = tokens.shape
        h, nh, hd = cfg.hidden, cfg.num_heads, cfg.head_dim
        x = params["embed"]["tok"][tokens].astype(cfg.dtype)
        x = x + params["embed"]["pos"][pos].astype(cfg.dtype)
        wpg = jnp.take_along_axis(
            tables, jnp.minimum(pos // page_size, max_pages - 1)[:, None],
            axis=1,
        )[:, 0]                                     # [S] write page
        woff = pos % page_size
        pool = dict(pool)
        for li, p in enumerate(params["layers"]):
            y = _layer_norm(x, **p["ln1"])
            qkv = _mm(y, p["attn"]["qkv"]).reshape(S, 3, nh, hd)
            q = qkv[:, 0]                           # [S, nh, hd]
            k = qkv[:, 1]
            v = qkv[:, 2]
            kq, ks = _quantize_slots(k[:, :, None, :])  # [S, nh, 1, hd]
            vq, vs = _quantize_slots(v[:, :, None, :])
            kq, ks = kq[:, :, 0], ks[:, :, 0]       # [S, nh, hd/1]
            vq, vs = vq[:, :, 0], vs[:, :, 0]
            pool["k"] = pool["k"].at[wpg, li, :, woff].set(kq)
            pool["v"] = pool["v"].at[wpg, li, :, woff].set(vq)
            pool["k_scale"] = pool["k_scale"].at[wpg, li, :, woff].set(ks)
            pool["v_scale"] = pool["v_scale"].at[wpg, li, :, woff].set(vs)
            if attn_kernel == "pallas":
                # fused paged-attention kernel: the page gather, int8
                # dequant, and masked softmax-attend run in ONE pallas
                # dispatch (write above first, so slot j still attends
                # its own current token)
                from ..kernels.decode_attention import (
                    paged_decode_attention,
                )

                ctx = paged_decode_attention(
                    q, pool["k"], pool["v"],
                    pool["k_scale"], pool["v_scale"],
                    li, tables, pos,
                ).reshape(S, h)
            else:
                # paged KV gather: each slot pulls its own pages (write
                # above first, so slot j attends its own current token).
                # ONE implementation serves both the production XLA
                # lowering and the kernel's bit-identity oracle — they
                # cannot drift apart
                from ..kernels.decode_attention import (
                    paged_attention_reference,
                )

                ctx = paged_attention_reference(
                    q, pool["k"], pool["v"],
                    pool["k_scale"], pool["v_scale"],
                    li, tables, pos,
                ).reshape(S, h)
            x = x + _mm(ctx, p["attn"]["out"])
            x = x + _mlp(p["mlp"], _layer_norm(x, **p["ln2"]))
        hs = _layer_norm(x, **params["final_ln"])
        nxt = jnp.argmax(
            _logits(cfg, params, hs), axis=-1
        ).astype(jnp.int32)
        return pool, nxt

    return step


def generate_program(
    cfg: TransformerConfig,
    params: Dict,
    max_new_tokens: int,
    temperature: float = 0.0,
    seed: int = 0,
    kv_quant: bool = False,
):
    """map_blocks program: prompt block [n, plen] → {"generated": [n, new]}.

    When sampling (``temperature > 0``), a content-derived salt folds
    into the seed so different blocks of a multi-block frame draw
    different noise (a pure program cannot see its block index — identical
    blocks still sample identically, which is at least deterministic)."""

    def program(prompts):
        salt = (
            prompts.astype(jnp.uint32).sum() if temperature > 0.0 else 0
        )
        return {
            "generated": generate(
                cfg, params, prompts, max_new_tokens, temperature,
                seed + salt, kv_quant=kv_quant,
            )
        }

    return program
