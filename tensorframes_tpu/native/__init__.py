"""Native marshalling layer: C++ kernels for the row⇄columnar hot loops.

The runtime half of the host⇄device marshalling layer (the compute half is
XLA). Plays the role of the reference's hand-unrolled Scala loops + JNI
buffer hand-off (DataOps.scala:18-167, datatypes.scala:328-565): one native
pass gathers scalar cells out of row dicts into contiguous buffers (viewed
as numpy arrays zero-copy, then `jax.device_put` to HBM), and one native
pass materializes result rows from column buffers.

The extension is compiled on demand from the bundled source with g++ (no
pybind11 — plain CPython C API) and cached next to this file; anything that
fails — no compiler, unsupported platform, exotic cell types — falls back
to the pure-Python path transparently. ``TFS_TPU_DISABLE_NATIVE=1``
disables it outright.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import get_logger

logger = get_logger(__name__)

_DTYPE_CODES = {
    np.dtype(np.float64): 0,
    np.dtype(np.float32): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
}

_lock = threading.Lock()
_mod = None
_load_attempted = False


def _source_path() -> str:
    return os.path.join(os.path.dirname(__file__), "rowpack.cpp")


def _so_path() -> str:
    return os.path.join(os.path.dirname(__file__), "_rowpack.so")


def _build() -> bool:
    """Compile rowpack.cpp → _rowpack.so with g++. Returns success."""
    include = sysconfig.get_paths()["include"]
    # build to a temp path and os.replace so an interrupted g++ can never
    # leave a truncated .so at the final path (which would otherwise look
    # newer than the source and permanently disable the native path)
    tmp = _so_path() + f".tmp{os.getpid()}"
    cmd = [
        "g++",
        "-O3",
        "-shared",
        "-fPIC",
        "-std=c++17",
        f"-I{include}",
        _source_path(),
        "-o",
        tmp,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
        if proc.returncode != 0:  # pragma: no cover
            logger.warning("native build failed:\n%s", proc.stderr[-2000:])
            return False
        os.replace(tmp, _so_path())
    except (OSError, subprocess.TimeoutExpired) as e:  # pragma: no cover
        logger.warning("native build failed: %s", e)
        return False
    finally:
        if os.path.exists(tmp):  # pragma: no cover
            try:
                os.remove(tmp)
            except OSError:
                pass
    return True


def _load():
    global _mod, _load_attempted
    with _lock:
        if _load_attempted:
            return _mod
        _load_attempted = True
        if os.environ.get("TFS_TPU_DISABLE_NATIVE", "") == "1":
            return None
        if not os.path.exists(_so_path()) or (
            os.path.getmtime(_so_path()) < os.path.getmtime(_source_path())
        ):
            if not _build():
                return None
        try:
            from . import _rowpack  # type: ignore[attr-defined]

            _mod = _rowpack
        except ImportError as e:  # pragma: no cover
            # a stale/corrupt artifact: rebuild once from scratch
            logger.warning("native module failed to import (%s); rebuilding", e)
            try:
                os.remove(_so_path())
            except OSError:
                pass
            _mod = None
            if _build():
                try:
                    import importlib

                    _mod = importlib.import_module(f"{__name__}._rowpack")
                except ImportError:
                    _mod = None
        return _mod


def available() -> bool:
    return _load() is not None


def supported_dtype(np_dtype) -> bool:
    return np.dtype(np_dtype) in _DTYPE_CODES


def gather_column(
    rows: Sequence[Dict[str, object]], name: str, np_dtype
) -> Optional[np.ndarray]:
    """Pack ``rows[i][name]`` scalars into a 1-D array in one native pass.

    Returns None when the native module is unavailable; raises on missing
    keys / non-convertible cells (callers catch and fall back).
    """
    mod = _load()
    if mod is None:
        return None
    dtype = np.dtype(np_dtype)
    buf = mod.gather_column(rows, name, _DTYPE_CODES[dtype])
    # bytearray → writable ndarray view, zero-copy
    return np.frombuffer(buf, dtype=dtype)


def dict_encode(values) -> Optional[tuple]:
    """One native hash pass over arbitrary hashable cells: returns
    ``(codes int32 ndarray, uniques list)`` with codes in FIRST-APPEARANCE
    order (caller remaps to lexicographic). None when unavailable."""
    mod = _load()
    if mod is None:
        return None
    buf, uniques = mod.dict_encode(
        values if isinstance(values, (list, tuple)) else list(values)
    )
    return np.frombuffer(buf, dtype=np.int32), uniques


def stack_cells(cells: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Stack equal-shape contiguous ndarray cells into ``[len(cells),
    *cell_shape]`` with ONE native memcpy pass — np.stack pays
    per-element numpy dispatch, which dominates the ragged map_rows
    host path at thousands of small cells per shape group. Returns
    None when unavailable or the first cell is not a supported dense
    array (callers fall back to np.stack). Mismatched cells raise
    ValueError — for shape mismatch np.stack does too, but for DTYPE
    mismatch np.stack would silently promote; a caller wanting
    promotion must catch and fall back."""
    mod = _load()
    if mod is None or len(cells) == 0:
        return None
    c0 = cells[0]
    if not isinstance(c0, np.ndarray) or c0.dtype.hasobject:
        return None
    if not c0.flags.c_contiguous:
        return None
    buf = mod.stack_cells(cells)
    return np.frombuffer(buf, dtype=c0.dtype).reshape(
        (len(cells),) + c0.shape
    )


def columns_to_rows(
    names: Sequence[str], arrays: Sequence[np.ndarray]
) -> Optional[List[Dict[str, object]]]:
    """Materialize a list of row dicts from scalar column arrays in one
    native pass. Returns None when unavailable or any column is not a
    supported 1-D numeric array.
    """
    mod = _load()
    if mod is None or not names:
        # zero-column frames keep the Python path's semantics
        return None
    bufs = []
    codes = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        if a.ndim != 1 or a.dtype not in _DTYPE_CODES:
            return None
        bufs.append(a)
        codes.append(_DTYPE_CODES[a.dtype])
    return mod.scatter_rows(tuple(names), tuple(bufs), tuple(codes))
