// Native row <-> columnar marshalling kernels.
//
// The TPU-native framework's equivalent of the reference's hand-unrolled
// hot loops (DataOps.scala:63-81 convertFast0 — rows -> tensor buffers;
// DataOps.scala:20-61 convertBackFast0 — tensors -> rows). There the loops
// ran in Scala against java.nio buffers feeding JNI tf.Tensor.create; here
// they run in C++ against CPython objects feeding numpy (and from numpy,
// jax.device_put to HBM) — the host-side half of the host<->device
// marshalling layer SURVEY.md §7 ranks as hard part #6.
//
// Scope mirrors the reference's fast path: scalar numeric columns
// (Double/Float/Int/Long, datatypes.scala:265-267). Vector cells and
// host-only (string/binary) columns take the Python slow path, as the
// reference's reshapeIter slow path did (DataOps.scala:85-101).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

enum DtypeCode { F64 = 0, F32 = 1, I32 = 2, I64 = 3 };

size_t itemsize_for(int code) { return (code == F32 || code == I32) ? 4 : 8; }

// Convert one cell to int64 honouring __index__ (covers numpy integers).
bool cell_to_i64(PyObject* v, int64_t* out) {
  if (PyLong_Check(v)) {
    long long x = PyLong_AsLongLong(v);
    if (x == -1 && PyErr_Occurred()) return false;
    *out = static_cast<int64_t>(x);
    return true;
  }
  PyObject* idx = PyNumber_Index(v);
  if (idx == nullptr) return false;
  long long x = PyLong_AsLongLong(idx);
  Py_DECREF(idx);
  if (x == -1 && PyErr_Occurred()) return false;
  *out = static_cast<int64_t>(x);
  return true;
}

// gather_column(rows, name, code) -> bytearray of len(rows) packed cells.
//
// One pass over a sequence of row dicts: borrow rows[i][name], convert,
// write into a contiguous buffer the wrapper views as a numpy array
// without copying.
PyObject* gather_column(PyObject*, PyObject* args) {
  PyObject* rows;
  const char* name;
  int code;
  if (!PyArg_ParseTuple(args, "Osi", &rows, &name, &code)) return nullptr;
  if (code < F64 || code > I64) {
    PyErr_Format(PyExc_ValueError, "bad dtype code %d", code);
    return nullptr;
  }
  PyObject* fast = PySequence_Fast(rows, "rows must be a sequence");
  if (fast == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  const size_t isz = itemsize_for(code);
  PyObject* out = PyByteArray_FromStringAndSize(nullptr, n * isz);
  PyObject* key = PyUnicode_FromString(name);
  if (out == nullptr || key == nullptr) goto fail;
  {
    char* buf = PyByteArray_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* row = PySequence_Fast_GET_ITEM(fast, i);  // borrowed
      if (!PyDict_Check(row)) {
        PyErr_Format(PyExc_TypeError, "row %zd is not a dict", (ssize_t)i);
        goto fail;
      }
      PyObject* v = PyDict_GetItemWithError(row, key);  // borrowed
      if (v == nullptr) {
        if (!PyErr_Occurred())
          PyErr_Format(PyExc_KeyError, "row %zd has no column '%s'",
                       (ssize_t)i, name);
        goto fail;
      }
      switch (code) {
        case F64: {
          double d = PyFloat_AsDouble(v);
          if (d == -1.0 && PyErr_Occurred()) goto fail;
          reinterpret_cast<double*>(buf)[i] = d;
          break;
        }
        case F32: {
          double d = PyFloat_AsDouble(v);
          if (d == -1.0 && PyErr_Occurred()) goto fail;
          reinterpret_cast<float*>(buf)[i] = static_cast<float>(d);
          break;
        }
        case I32: {
          int64_t x;
          if (!cell_to_i64(v, &x)) goto fail;
          if (x < INT32_MIN || x > INT32_MAX) {
            PyErr_Format(PyExc_OverflowError,
                         "row %zd column '%s': %lld out of int32 range",
                         (ssize_t)i, name, (long long)x);
            goto fail;
          }
          reinterpret_cast<int32_t*>(buf)[i] = static_cast<int32_t>(x);
          break;
        }
        case I64: {
          int64_t x;
          if (!cell_to_i64(v, &x)) goto fail;
          reinterpret_cast<int64_t*>(buf)[i] = x;
          break;
        }
      }
    }
  }
  Py_DECREF(key);
  Py_DECREF(fast);
  return out;
fail:
  Py_XDECREF(key);
  Py_XDECREF(out);
  Py_DECREF(fast);
  return nullptr;
}

// scatter_rows(names, buffers, codes) -> list of row dicts.
//
// names: tuple of str; buffers: tuple of C-contiguous 1-D buffers (one per
// column, equal lengths); codes: tuple of dtype codes. Builds the whole
// list-of-dicts result in one C pass (the collect() hot loop).
PyObject* scatter_rows(PyObject*, PyObject* args) {
  PyObject *names, *buffers, *codes;
  if (!PyArg_ParseTuple(args, "OOO", &names, &buffers, &codes)) return nullptr;
  if (!PyTuple_Check(names) || !PyTuple_Check(buffers) || !PyTuple_Check(codes)) {
    PyErr_SetString(PyExc_TypeError, "names/buffers/codes must be tuples");
    return nullptr;
  }
  const Py_ssize_t k = PyTuple_GET_SIZE(names);
  if (PyTuple_GET_SIZE(buffers) != k || PyTuple_GET_SIZE(codes) != k) {
    PyErr_SetString(PyExc_ValueError, "names/buffers/codes length mismatch");
    return nullptr;
  }
  if (k == 0) return PyList_New(0);  // zero-column frame: no rows to infer
  Py_buffer* views = new Py_buffer[k];
  int* col_codes = new int[k];
  Py_ssize_t acquired = 0;
  PyObject* result = nullptr;
  Py_ssize_t n = -1;

  for (; acquired < k; ++acquired) {
    PyObject* b = PyTuple_GET_ITEM(buffers, acquired);
    if (PyObject_GetBuffer(b, &views[acquired], PyBUF_C_CONTIGUOUS) != 0)
      goto done;
    long code = PyLong_AsLong(PyTuple_GET_ITEM(codes, acquired));
    if ((code == -1 && PyErr_Occurred()) || code < F64 || code > I64) {
      if (!PyErr_Occurred())
        PyErr_Format(PyExc_ValueError, "bad dtype code %ld", code);
      ++acquired;  // this view was acquired; release it in cleanup
      goto done;
    }
    col_codes[acquired] = static_cast<int>(code);
    const Py_ssize_t rows_here =
        views[acquired].len / (Py_ssize_t)itemsize_for(col_codes[acquired]);
    if (n == -1) {
      n = rows_here;
    } else if (rows_here != n) {
      PyErr_Format(PyExc_ValueError,
                   "column %zd has %zd rows, expected %zd",
                   (ssize_t)acquired, (ssize_t)rows_here, (ssize_t)n);
      ++acquired;
      goto done;
    }
  }

  result = PyList_New(n);
  if (result == nullptr) goto done;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* row = PyDict_New();
    if (row == nullptr) goto fail_rows;
    PyList_SET_ITEM(result, i, row);  // steals
    for (Py_ssize_t j = 0; j < k; ++j) {
      const char* buf = static_cast<const char*>(views[j].buf);
      PyObject* cell = nullptr;
      switch (col_codes[j]) {
        case F64:
          cell = PyFloat_FromDouble(reinterpret_cast<const double*>(buf)[i]);
          break;
        case F32:
          cell = PyFloat_FromDouble(
              (double)reinterpret_cast<const float*>(buf)[i]);
          break;
        case I32:
          cell = PyLong_FromLong(reinterpret_cast<const int32_t*>(buf)[i]);
          break;
        case I64:
          cell = PyLong_FromLongLong(reinterpret_cast<const int64_t*>(buf)[i]);
          break;
      }
      if (cell == nullptr) goto fail_rows;
      if (PyDict_SetItem(row, PyTuple_GET_ITEM(names, j), cell) != 0) {
        Py_DECREF(cell);
        goto fail_rows;
      }
      Py_DECREF(cell);
    }
  }
  goto done;

fail_rows:
  Py_CLEAR(result);
done:
  for (Py_ssize_t j = 0; j < acquired; ++j) PyBuffer_Release(&views[j]);
  delete[] views;
  delete[] col_codes;
  return result;  // nullptr on error (exception set)
}

// parse_csv(data: bytes, delim: byte, codes: tuple[int]) -> tuple
//
// One native pass over unquoted CSV bytes (the Python wrapper detects
// quoting and takes the csv-module path instead). Per column code:
// F64 -> bytearray of packed doubles (empty field = NaN), I64 ->
// bytearray of packed int64, STR(4) -> list[str]. Rows end at '\n'
// (optional '\r' stripped); a missing trailing newline is fine.
constexpr int STR_CODE = 4;

PyObject* parse_csv(PyObject*, PyObject* args) {
  Py_buffer data;
  int delim_i;
  PyObject* codes_obj;
  if (!PyArg_ParseTuple(args, "y*iO", &data, &delim_i, &codes_obj)) {
    return nullptr;
  }
  const char delim = static_cast<char>(delim_i);
  PyObject* codes_fast =
      PySequence_Fast(codes_obj, "codes must be a sequence");
  if (codes_fast == nullptr) {
    PyBuffer_Release(&data);
    return nullptr;
  }
  const Py_ssize_t ncols = PySequence_Fast_GET_SIZE(codes_fast);
  int* codes = new int[ncols];
  for (Py_ssize_t j = 0; j < ncols; ++j) {
    const long code = PyLong_AsLong(PySequence_Fast_GET_ITEM(codes_fast, j));
    if (code == -1 && PyErr_Occurred()) {
      Py_DECREF(codes_fast);
      delete[] codes;
      PyBuffer_Release(&data);
      return nullptr;
    }
    codes[j] = static_cast<int>(code);
  }
  Py_DECREF(codes_fast);

  // estimate rows (newline count + a possible unterminated last line) so
  // numeric buffers allocate once instead of O(rows) reallocs
  const char* scan = static_cast<const char*>(data.buf);
  const char* scan_end = scan + data.len;
  Py_ssize_t est = (data.len > 0 && scan_end[-1] != '\n') ? 1 : 0;
  for (const char* q = scan; q < scan_end; ++q) {
    if (*q == '\n') ++est;
  }

  // column outputs
  PyObject** outs = new PyObject*[ncols]();
  bool ok = true;
  for (Py_ssize_t j = 0; j < ncols && ok; ++j) {
    outs[j] = (codes[j] == STR_CODE)
                  ? PyList_New(0)
                  : PyByteArray_FromStringAndSize(nullptr, est * 8);
    if (outs[j] == nullptr) ok = false;
  }

  const char* p = static_cast<const char*>(data.buf);
  const char* end = p + data.len;
  char numbuf[64];
  long long nrow = 0;
  while (ok && p < end) {
    // skip blank lines, LF or CRLF (the csv-module fallback drops them)
    if (*p == '\n') { ++p; continue; }
    if (*p == '\r' && p + 1 < end && p[1] == '\n') { p += 2; continue; }
    for (Py_ssize_t j = 0; j < ncols && ok; ++j) {
      const char* f = p;
      while (p < end && *p != delim && *p != '\n') ++p;
      const char* fe = p;
      if (fe > f && fe[-1] == '\r') --fe;
      const size_t flen = static_cast<size_t>(fe - f);
      if (codes[j] == STR_CODE) {
        PyObject* s = PyUnicode_DecodeUTF8(f, static_cast<Py_ssize_t>(flen),
                                           "replace");
        if (s == nullptr || PyList_Append(outs[j], s) != 0) {
          Py_XDECREF(s);
          ok = false;
          break;
        }
        Py_DECREF(s);
      } else {
        if (flen >= sizeof(numbuf)) {
          PyErr_Format(PyExc_ValueError,
                       "csv row %lld col %zd: field too long", nrow, j);
          ok = false;
          break;
        }
        std::memcpy(numbuf, f, flen);
        numbuf[flen] = '\0';
        if (codes[j] == F64) {
          double v;
          if (flen == 0) {
            v = __builtin_nan("");
          } else {
            char* ep = nullptr;
            // PyOS_string_to_double is locale-independent (strtod honours
            // LC_NUMERIC and would reject '0.5' under comma-decimal locales)
            v = PyOS_string_to_double(numbuf, &ep, nullptr);
            if (v == -1.0 && PyErr_Occurred()) PyErr_Clear();
            if (ep != numbuf + flen) {
              PyErr_Format(PyExc_ValueError,
                           "csv row %lld col %zd: bad float %.60s", nrow, j,
                           numbuf);
              ok = false;
              break;
            }
          }
          std::memcpy(PyByteArray_AS_STRING(outs[j]) + nrow * 8, &v, 8);
        } else {  // I64
          char* ep = nullptr;
          errno = 0;
          long long v = strtoll(numbuf, &ep, 10);
          if (errno == ERANGE) {
            PyErr_Format(PyExc_OverflowError,
                         "csv row %lld col %zd: %.60s out of int64 range",
                         nrow, j, numbuf);
            ok = false;
            break;
          }
          if (flen == 0 || ep != numbuf + flen) {
            PyErr_Format(PyExc_ValueError,
                         "csv row %lld col %zd: bad int %.60s", nrow, j,
                         numbuf);
            ok = false;
            break;
          }
          int64_t v64 = static_cast<int64_t>(v);
          std::memcpy(PyByteArray_AS_STRING(outs[j]) + nrow * 8, &v64, 8);
        }
      }
      // advance past the delimiter (not past the newline)
      if (p < end && *p == delim && j + 1 < ncols) ++p;
    }
    if (!ok) break;
    // drop any extra fields beyond the header's columns (the csv-module
    // fallback ignores them too) — without this the leftover text would
    // be re-parsed as phantom rows PAST the preallocated buffers
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;  // consume the newline
    ++nrow;
  }

  // shrink numeric buffers to the actual row count (empty lines skipped)
  for (Py_ssize_t j = 0; j < ncols && ok; ++j) {
    if (codes[j] != STR_CODE && PyByteArray_GET_SIZE(outs[j]) != nrow * 8) {
      if (PyByteArray_Resize(outs[j], nrow * 8) != 0) ok = false;
    }
  }

  PyObject* result = nullptr;
  if (ok) {
    result = PyTuple_New(ncols + 1);
    if (result != nullptr) {
      for (Py_ssize_t j = 0; j < ncols; ++j) {
        PyTuple_SET_ITEM(result, j, outs[j]);  // steals
        outs[j] = nullptr;
      }
      PyTuple_SET_ITEM(result, ncols, PyLong_FromLongLong(nrow));
    }
  }
  for (Py_ssize_t j = 0; j < ncols; ++j) Py_XDECREF(outs[j]);
  delete[] outs;
  delete[] codes;
  PyBuffer_Release(&data);
  return result;
}

// dict_encode(seq) -> (bytearray of int32 first-appearance codes, uniques
// list in first-appearance order).
//
// One hash pass over arbitrary hashable cells (strings are the target:
// aggregate()'s dictionary key encoding). Replaces numpy's sort-based
// np.unique(return_inverse=True) — O(n) dict probes vs O(n log n) string
// comparisons; the caller lexicographically argsorts the K uniques
// (K = distinct groups, tiny) and remaps vectorized.
PyObject* dict_encode(PyObject*, PyObject* args) {
  PyObject* seq;
  if (!PyArg_ParseTuple(args, "O", &seq)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "dict_encode expects a sequence");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject* out = PyByteArray_FromStringAndSize(nullptr, n * 4);
  PyObject* table = PyDict_New();
  PyObject* uniques = PyList_New(0);
  bool ok = out != nullptr && table != nullptr && uniques != nullptr;
  if (ok) {
    int32_t* codes = reinterpret_cast<int32_t*>(PyByteArray_AS_STRING(out));
    for (Py_ssize_t i = 0; i < n && ok; ++i) {
      PyObject* v = PySequence_Fast_GET_ITEM(fast, i);  // borrowed
      PyObject* idx = PyDict_GetItemWithError(table, v);  // borrowed
      if (idx != nullptr) {
        codes[i] = static_cast<int32_t>(PyLong_AsLong(idx));
      } else if (PyErr_Occurred()) {
        ok = false;  // unhashable cell — error already set
      } else {
        Py_ssize_t k = PyList_GET_SIZE(uniques);
        if (k >= INT32_MAX) {
          PyErr_SetString(PyExc_OverflowError, "too many distinct keys");
          ok = false;
          break;
        }
        PyObject* kobj = PyLong_FromSsize_t(k);
        if (kobj == nullptr || PyDict_SetItem(table, v, kobj) != 0 ||
            PyList_Append(uniques, v) != 0) {
          Py_XDECREF(kobj);
          ok = false;
          break;
        }
        Py_DECREF(kobj);
        codes[i] = static_cast<int32_t>(k);
      }
    }
  }
  PyObject* result = nullptr;
  if (ok) {
    result = PyTuple_New(2);
    if (result != nullptr) {
      PyTuple_SET_ITEM(result, 0, out);      // steals
      PyTuple_SET_ITEM(result, 1, uniques);  // steals
      out = nullptr;
      uniques = nullptr;
    }
  }
  Py_XDECREF(out);
  Py_XDECREF(uniques);
  Py_XDECREF(table);
  Py_DECREF(fast);
  return result;
}

// stack_cells(cells) -> bytearray of the cells' bytes concatenated.
//
// The ragged map_rows path stacks thousands of small same-shape ndarray
// cells per shape group (np.stack pays per-element numpy dispatch); one
// native pass over the buffer protocol memcpys them. Every cell must be
// C-contiguous with identical itemsize/format/shape — any mismatch
// raises and the wrapper falls back to np.stack.
PyObject* stack_cells(PyObject*, PyObject* args) {
  PyObject* cells;
  if (!PyArg_ParseTuple(args, "O", &cells)) return nullptr;
  PyObject* fast = PySequence_Fast(cells, "cells must be a sequence");
  if (fast == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  if (n == 0) {
    Py_DECREF(fast);
    PyErr_SetString(PyExc_ValueError, "stack_cells needs >= 1 cell");
    return nullptr;
  }
  PyObject* out = nullptr;
  Py_buffer first;
  first.obj = nullptr;
  {
    PyObject* c0 = PySequence_Fast_GET_ITEM(fast, 0);  // borrowed
    if (PyObject_GetBuffer(c0, &first, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) !=
        0)
      goto fail;
    const Py_ssize_t cell_len = first.len;
    out = PyByteArray_FromStringAndSize(nullptr, n * cell_len);
    if (out == nullptr) goto fail;
    char* buf = PyByteArray_AS_STRING(out);
    std::memcpy(buf, first.buf, cell_len);
    for (Py_ssize_t i = 1; i < n; ++i) {
      PyObject* c = PySequence_Fast_GET_ITEM(fast, i);  // borrowed
      Py_buffer view;
      if (PyObject_GetBuffer(c, &view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) !=
          0)
        goto fail;
      bool ok =
          view.len == cell_len && view.itemsize == first.itemsize &&
          view.ndim == first.ndim &&
          ((view.format == nullptr && first.format == nullptr) ||
           (view.format != nullptr && first.format != nullptr &&
            std::strcmp(view.format, first.format) == 0));
      // same byte length is NOT same shape ([2,6] vs [3,4] f32):
      // PyBUF_C_CONTIGUOUS implies ND, so shape arrays are present
      if (ok && view.shape != nullptr && first.shape != nullptr) {
        for (int d = 0; d < view.ndim; ++d)
          if (view.shape[d] != first.shape[d]) {
            ok = false;
            break;
          }
      }
      if (!ok) {
        PyBuffer_Release(&view);
        PyErr_Format(PyExc_ValueError,
                     "cell %zd does not match cell 0's shape/dtype",
                     (ssize_t)i);
        goto fail;
      }
      std::memcpy(buf + i * cell_len, view.buf, cell_len);
      PyBuffer_Release(&view);
    }
  }
  PyBuffer_Release(&first);
  Py_DECREF(fast);
  return out;
fail:
  if (first.obj != nullptr) PyBuffer_Release(&first);
  Py_XDECREF(out);
  Py_DECREF(fast);
  return nullptr;
}

PyMethodDef methods[] = {
    {"dict_encode", dict_encode, METH_VARARGS,
     "dict_encode(seq) -> (bytearray int32 codes, uniques list)"},
    {"stack_cells", stack_cells, METH_VARARGS,
     "stack_cells(cells) -> bytearray of concatenated equal-shape cells"},
    {"gather_column", gather_column, METH_VARARGS,
     "gather_column(rows, name, dtype_code) -> bytearray of packed cells"},
    {"scatter_rows", scatter_rows, METH_VARARGS,
     "scatter_rows(names, buffers, dtype_codes) -> list of row dicts"},
    {"parse_csv", parse_csv, METH_VARARGS,
     "parse_csv(data, delim_byte, codes) -> (*columns, nrows)"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_rowpack",
                         "Native row<->columnar marshalling kernels.", -1,
                         methods};

}  // namespace

PyMODINIT_FUNC PyInit__rowpack(void) { return PyModule_Create(&moduledef); }
