"""Observability subsystem: structured tracing, metrics, exporters.

PR 1's resilience layer (retries, NaN guards, verified checkpoints) ran
blind — no counter recorded a retry attempt, a guard trip, or a cache
miss. This package gives every layer first-class telemetry, the way
DrJAX instruments its MapReduce primitives for scale debugging: a perf
or reliability claim without exported numbers is a vibe.

* :mod:`~tensorframes_tpu.observability.events` — structured event
  tracer (nested spans, instants, monotonic µs timestamps, thread ids)
  exporting Chrome ``trace_event`` JSON for Perfetto /
  ``chrome://tracing``; layered on top of the ``utils/profiling.py``
  span aggregates (every profiling span lands on the timeline when
  tracing is enabled).
* :mod:`~tensorframes_tpu.observability.metrics` — process-wide
  registry of named counters / gauges / fixed-bucket histograms with
  JSONL snapshot export, Prometheus text exposition
  (``to_prometheus()``), and a ``metrics_server(port)`` scrape
  endpoint.
* :mod:`~tensorframes_tpu.observability.steps` — ``StepTelemetry``, the
  per-step training callback (step time, loss, rows/s → registry +
  JSONL step log + trace), wired into
  ``training.run_resumable(telemetry=...)`` / ``train_on_frame``.

Instrumented out of the box: ``ops/executor.py`` (jit-cache hits /
misses, first-compile seconds, bucket-padding waste rows), ``io.py``
prefetch (queue depth, producer/consumer waits), ``checkpoint.py``
(save/restore seconds + bytes, CRC failures), ``resilience/`` (retry
attempts / exhaustions / backoff seconds, guard trips by policy, fault
injections fired), and the training loops. All instruments register at
import time, so an exposition always carries the full catalog — an
idle counter reads 0 instead of vanishing.
"""

from __future__ import annotations

from . import context  # noqa: F401
from . import events  # noqa: F401
from . import flight  # noqa: F401
from . import latency  # noqa: F401
from . import merge  # noqa: F401
from . import metrics  # noqa: F401
from . import profile  # noqa: F401
from .events import TRACER, Tracer  # noqa: F401
from .flight import FlightRecorder, RECORDER  # noqa: F401
from .merge import merge_traces  # noqa: F401
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    counter,
    gauge,
    histogram,
    metrics_server,
)
from .steps import StepTelemetry  # noqa: F401

__all__ = [
    "context",
    "events",
    "flight",
    "latency",
    "merge",
    "metrics",
    "profile",
    "Tracer",
    "TRACER",
    "FlightRecorder",
    "RECORDER",
    "merge_traces",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "counter",
    "gauge",
    "histogram",
    "metrics_server",
    "StepTelemetry",
]
