"""Entry point: ``python -m tensorframes_tpu.observability``."""

import sys

from .cli import main

sys.exit(main())
