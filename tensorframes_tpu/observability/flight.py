"""Crash flight recorder: an always-on black box of recent operations.

When a run dies — a guard-raise on a NaN step, a ``StaticAnalysisError``
at dispatch, an unhandled exception, or a ``kill -9`` that leaves no
Python at all — the postmortem question is always the same: *what was
the system doing right before?* The trace buffer answers it only if
someone was exporting traces; the metrics registry only in aggregate.
This module keeps a bounded, always-on ring of the recent
**operational** events (dispatches, retries, guard trips, fault
injections, checkpoint IO — and, since the fleet-supervision layer, the
``fleet.*`` record family: ``fleet.heartbeat_lost``,
``fleet.straggler``, ``fleet.abort`` / ``fleet.abort_seen`` /
``fleet.self_abort``, ``fleet.hung_dispatch``, ``fleet.rank_dead``,
``fleet.restart``; since the serving layer, the ``serving.*`` family:
``serving.start`` / ``serving.drain`` / ``serving.stop``,
``serving.flush``, ``serving.reject``, ``serving.deadline``,
``serving.error``; since the out-of-core data plane, the
``blockstore.*`` family: ``blockstore.spill``,
``blockstore.quarantine``, and the ``shuffle.*`` family:
``shuffle.exchange``, ``shuffle.quarantine``, ``shuffle.hang`` — the
last dumped as a postmortem naming the missing ranks when a peer dies
mid-exchange) and turns it into a redacted JSONL dump at the
moment of death, so ``read_blackbox()`` shows the whole fleet's history
after a crash.

Two storage layers:

* **In-memory ring** (``deque(maxlen=capacity)``) — always recording;
  the cost per record is a small dict build + append, noise next to the
  XLA dispatch or host IO it describes. Dumped to JSONL by
  :meth:`FlightRecorder.dump` (installed hooks call it on crash).
* **Disk spool** (armed by ``TFTPU_FLIGHT_DIR``) — every record is also
  appended, line-flushed, to a two-segment rotating file pair, so a
  ``kill -9`` (no Python runs, no hook fires) still leaves the last
  ``<= 2 * capacity`` records on disk. :func:`read_blackbox` reassembles
  them afterwards.

Dump triggers (all best-effort — the recorder must never turn a crash
into a different crash):

* unhandled exceptions via a chained ``sys.excepthook`` (installed at
  import when the spool dir is armed);
* ``StepGuard`` escalation to ``NonFiniteError`` (resilience/guards.py);
* ``StaticAnalysisError`` from strict-mode lint (analysis/diagnostics).

Records are **redacted** before they are written anywhere: values are
scalars/short strings only, array-likes degrade to shape+dtype
summaries, and fields whose names smell like credentials are blanked —
a postmortem artifact gets attached to tickets and uploaded to CI, it
must never carry tensor contents or secrets.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from ..utils import get_logger
from . import context as _context
from .metrics import counter as _counter

logger = get_logger(__name__)

__all__ = [
    "FlightRecorder",
    "RECORDER",
    "record",
    "dump",
    "install_excepthook",
    "read_blackbox",
    "set_spool_dir",
]

#: Ring capacity (records); the spool keeps at most twice this on disk.
DEFAULT_CAPACITY = 512

_MAX_STR = 240  # chars kept of any string field
_SECRET_HINTS = ("secret", "token", "password", "passwd", "api_key",
                 "apikey", "credential", "auth")

_RECORDS = _counter(
    "tftpu_flight_records_total",
    "Operational events captured by the flight recorder ring",
)
_DUMPS = _counter(
    "tftpu_flight_dumps_total",
    "Flight-recorder postmortem dumps written",
)


def _redact_value(v: Any) -> Any:
    """One field value → a JSON-safe, content-free form."""
    if v is None or isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        import math

        if isinstance(v, float) and not math.isfinite(v):
            return str(v)  # "nan"/"inf" — strict JSON has no token
        return v
    if isinstance(v, str):
        return v if len(v) <= _MAX_STR else v[:_MAX_STR] + "…"
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None and dtype is not None:
        # arrays NEVER dump contents: a black box rides CI artifacts
        return f"<array shape={tuple(shape)} dtype={dtype}>"
    if isinstance(v, (list, tuple)):
        if len(v) > 8:
            return f"<{type(v).__name__} len={len(v)}>"
        return [_redact_value(x) for x in v]
    if isinstance(v, dict):
        return redact_fields(v) if len(v) <= 8 else f"<dict len={len(v)}>"
    return _redact_value(str(v))


def redact_fields(fields: Dict[str, Any]) -> Dict[str, Any]:
    """Redact a record's fields: credential-smelling names are blanked,
    everything else passes through :func:`_redact_value`."""
    out: Dict[str, Any] = {}
    for k, v in fields.items():
        lk = str(k).lower()
        if any(h in lk for h in _SECRET_HINTS):
            out[k] = "[redacted]"
        else:
            out[k] = _redact_value(v)
    return out


def _exc_fields(exc: BaseException, tb_chars: int = 2000) -> Dict[str, Any]:
    tb = "".join(
        traceback.format_exception(type(exc), exc, exc.__traceback__)
    )
    return {
        "error": type(exc).__name__,
        "message": str(exc)[:_MAX_STR],
        "traceback": tb[-tb_chars:],
    }


class FlightRecorder:
    """Bounded operational-event ring with optional crash-safe spool."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        spool_dir: Optional[str] = None,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.total_records = 0
        self._spool_dir = spool_dir
        self._spool_file = None
        self._spool_lines = 0
        self._spool_path: Optional[str] = None
        self._dump_count = 0

    # -- spool --------------------------------------------------------------

    @property
    def spool_dir(self) -> Optional[str]:
        return self._spool_dir

    def set_spool_dir(self, directory: Optional[str]) -> None:
        """(Re)arm or disarm the disk spool."""
        with self._lock:
            self._close_spool_locked()
            self._spool_dir = directory

    def _close_spool_locked(self) -> None:
        if self._spool_file is not None:
            try:
                self._spool_file.close()
            except OSError:  # pragma: no cover - close on a dead fs
                pass
            self._spool_file = None
            self._spool_lines = 0
            self._spool_path = None

    def _spool_locked(self):
        if not self._spool_dir:
            return None
        if self._spool_file is None or self._spool_file.closed:
            os.makedirs(self._spool_dir, exist_ok=True)
            ctx = _context.snapshot()
            self._spool_path = os.path.join(
                self._spool_dir,
                f"flight_{ctx['run_id']}_p{ctx['process_index']}"
                f"_pid{os.getpid()}.jsonl",
            )
            self._spool_file = open(self._spool_path, "a")
            self._spool_lines = 0
        elif self._spool_lines >= self.capacity:
            # two-segment rotation: the previous segment replaces ".1",
            # bounding disk to <= 2*capacity lines however long the run
            self._spool_file.close()
            os.replace(self._spool_path, self._spool_path + ".1")
            self._spool_file = open(self._spool_path, "a")
            self._spool_lines = 0
        return self._spool_file

    # -- recording ----------------------------------------------------------

    def record(self, kind: str, **fields: Any) -> None:
        """Append one operational record (thread-safe, never raises)."""
        try:
            rec = {
                "kind": kind,
                "ts": round(time.time(), 6),
                **redact_fields(fields),
            }
            with self._lock:
                self._seq += 1
                rec["seq"] = self._seq
                self._ring.append(rec)
                self.total_records += 1
                f = self._spool_locked()
                if f is not None:
                    f.write(json.dumps(rec, default=str) + "\n")
                    f.flush()
                    self._spool_lines += 1
            _RECORDS.inc()
        except Exception as e:  # pragma: no cover - must never propagate
            logger.debug("flight record failed: %s", e)

    def records(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- postmortem ---------------------------------------------------------

    def dump(
        self,
        path: Optional[str] = None,
        reason: str = "manual",
        exc: Optional[BaseException] = None,
    ) -> Optional[str]:
        """Write the postmortem JSONL: one header line (context, reason,
        redacted exception) then the ring oldest-first. ``path=None``
        writes ``postmortem_<run>_p<rank>_pid<pid>_<n>.jsonl`` (n = the
        per-process dump counter) into the spool dir — or returns None
        when no spool dir is armed (nothing sensible to write to).
        Best-effort: returns None on IO failure instead of raising
        inside a dying process."""
        try:
            if path is None:
                if not self._spool_dir:
                    return None
                os.makedirs(self._spool_dir, exist_ok=True)
                ctx = _context.snapshot()
                # per-process dump counter in the name: a guard-raise
                # postmortem must survive a later crash dump (and vice
                # versa) — overwriting would destroy the first black box
                with self._lock:
                    self._dump_count += 1
                    n = self._dump_count
                path = os.path.join(
                    self._spool_dir,
                    f"postmortem_{ctx['run_id']}_p{ctx['process_index']}"
                    f"_pid{os.getpid()}_{n}.jsonl",
                )
            header: Dict[str, Any] = {
                "kind": "postmortem",
                "reason": reason,
                "ts": round(time.time(), 6),
                "pid": os.getpid(),
                **_context.snapshot(),
                "records": len(self._ring),
                "total_records": self.total_records,
            }
            if exc is not None:
                header.update(redact_fields(_exc_fields(exc)))
            with open(path, "w") as f:
                f.write(json.dumps(header, default=str) + "\n")
                for rec in self.records():
                    f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
            _DUMPS.inc()
            logger.warning(
                "flight recorder: postmortem (%s) → %s", reason, path
            )
            return path
        except Exception as e:  # pragma: no cover - dying process
            logger.debug("flight dump failed: %s", e)
            return None


    def _abandon_spool_after_fork(self) -> None:
        # forked child: the inherited handle points at the PARENT's
        # spool (parent rank/pid in the name) — drop it WITHOUT closing
        # (the fd is shared; per-record flush means no buffered bytes
        # are lost) so the child's first record reopens under its own
        # identity. No lock: the child is single-threaded here and the
        # parent's lock state is unreliable across fork.
        self._spool_file = None
        self._spool_lines = 0
        self._spool_path = None


#: Process-wide recorder; spool armed by TFTPU_FLIGHT_DIR at import.
RECORDER = FlightRecorder(
    capacity=int(os.environ.get("TFTPU_FLIGHT_EVENTS", DEFAULT_CAPACITY)),
    spool_dir=os.environ.get("TFTPU_FLIGHT_DIR") or None,
)

if hasattr(os, "register_at_fork"):  # pragma: no branch - posix
    os.register_at_fork(
        after_in_child=lambda: RECORDER._abandon_spool_after_fork()
    )


def record(kind: str, **fields: Any) -> None:
    """Record on the process-wide flight recorder."""
    RECORDER.record(kind, **fields)


def dump(
    path: Optional[str] = None,
    reason: str = "manual",
    exc: Optional[BaseException] = None,
) -> Optional[str]:
    """Dump the process-wide recorder's postmortem (see
    :meth:`FlightRecorder.dump`)."""
    return RECORDER.dump(path, reason=reason, exc=exc)


def set_spool_dir(directory: Optional[str]) -> None:
    """(Re)arm the process-wide recorder's disk spool. Arming also
    installs the crash excepthook — a spool dir means "I want black
    boxes", whether it arrived via env or this call."""
    RECORDER.set_spool_dir(directory)
    if directory:
        install_excepthook()


# -- crash hook -------------------------------------------------------------

_hook_installed = False


def install_excepthook() -> None:
    """Chain a postmortem dump into ``sys.excepthook`` (idempotent).
    The previous hook still runs — this observes death, it does not
    change how death looks."""
    global _hook_installed
    if _hook_installed:
        return
    prev = sys.excepthook

    def _flight_excepthook(tp, val, tb):
        try:
            RECORDER.record(
                "crash", error=tp.__name__, message=str(val)[:_MAX_STR]
            )
            RECORDER.dump(reason="crash", exc=val)
        finally:
            prev(tp, val, tb)

    sys.excepthook = _flight_excepthook
    _hook_installed = True


if os.environ.get("TFTPU_FLIGHT_DIR"):
    install_excepthook()


# -- black-box recovery -----------------------------------------------------

def read_blackbox(directory: str) -> List[Dict[str, Any]]:
    """Reassemble spooled flight records after an unclean death (e.g.
    ``kill -9``): reads every ``flight_*.jsonl`` segment pair under
    ``directory``, tolerating a torn final line (the kill can land
    mid-write), and returns records sorted by (file identity, seq)."""
    import glob as _glob

    out: List[Dict[str, Any]] = []
    for path in sorted(_glob.glob(os.path.join(directory, "flight_*.jsonl*"))):
        # ".1" rotated segment sorts after its live sibling; seq sorts
        # records globally anyway
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line from the kill
                    rec["_file"] = os.path.basename(path)
                    out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: (r.get("_file", "").split(".jsonl")[0],
                            r.get("seq", 0)))
    return out
