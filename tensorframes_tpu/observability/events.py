"""Structured event tracing → Chrome ``trace_event`` JSON.

The aggregate side of observability lives in ``utils/profiling.py``
(per-span totals) and ``observability/metrics.py`` (counters/gauges/
histograms). This module is the **timeline** side: begin/end spans with
natural nesting, instant events, monotonic microsecond timestamps, and
real thread ids, exported in the Chrome ``trace_event`` JSON format that
Perfetto (https://ui.perfetto.dev) and ``chrome://tracing`` open
directly. It layers ON TOP of ``utils/profiling.py`` — when tracing is
enabled, every ``profiling.span`` (the five verbs, checkpoint IO, …)
also lands on the timeline; disabling tracing costs one attribute check
per span.

Usage::

    from tensorframes_tpu.observability import events

    events.enable()
    with events.span("ingest", rows=100_000):
        ...
    events.instant("watermark", step=7)
    events.save("trace.json")           # open in Perfetto

The buffer is bounded (``max_events``): past the cap new events are
dropped and counted (``TRACER.dropped``) — a week-long run must not eat
the host's RAM. Spans are recorded as complete ("X"-phase) events at
span END, so nesting is reconstructed by time containment per thread;
a span that never exits (crash mid-body) leaves no partial event.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from ..utils import get_logger
from . import context as _context
from .metrics import counter as _counter

logger = get_logger(__name__)

__all__ = [
    "Tracer",
    "TRACER",
    "enable",
    "disable",
    "active",
    "clear",
    "span",
    "instant",
    "to_chrome_trace",
    "save",
    "save_shard",
]

#: Monotonic epoch for this process: every timestamp is microseconds
#: since this instant (Chrome traces need only a consistent monotonic
#: base; perf_counter is the highest-resolution clock available).
_EPOCH = time.perf_counter()
#: Wall-clock captured at the same instant as ``_EPOCH``: the anchor
#: that lets the cross-process merge aggregator place each process's
#: monotonic timeline on one shared real-time axis.
_EPOCH_UNIX_US = int(time.time() * 1e6)

# Events dropped at the full ring, as a registry counter (pre-registered
# so the family is always in the exposition): the in-object ``dropped``
# count is invisible to a metrics scrape, and a silently-truncated trace
# reads as "nothing else happened" — exactly the failure ISSUE 6's first
# satellite names.
_EVENTS_DROPPED = _counter(
    "tftpu_trace_events_dropped_total",
    "Trace events discarded because the tracer ring was full",
)


def _us(t_perf: float) -> float:
    return (t_perf - _EPOCH) * 1e6


def _clean_args(args: Dict[str, Any]) -> Dict[str, Any]:
    """Coerce event args to strict-JSON-safe values at emit time: numpy
    scalars via .item(), non-finite floats to null (strict JSON has no
    NaN/Inf token), anything else to str. A week of collected events
    must never make the end-of-run export raise."""
    import math

    out: Dict[str, Any] = {}
    for k, v in args.items():
        if not isinstance(v, (str, int, float, bool)) and v is not None:
            item = getattr(v, "item", None)
            if callable(item):
                try:
                    v = item()
                except Exception:
                    v = str(v)
            if not isinstance(v, (str, int, float, bool)) and v is not None:
                v = str(v)
        if isinstance(v, float) and not math.isfinite(v):
            v = None
        out[k] = v
    return out


class Tracer:
    """Bounded in-memory trace_event collector (thread-safe)."""

    def __init__(self, max_events: int = 200_000):
        self.max_events = max_events
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._named_threads: set = set()
        self.dropped = 0
        self.enabled = False

    # -- lifecycle ----------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._named_threads.clear()
            self.dropped = 0

    # -- recording ----------------------------------------------------------

    def _append(self, ev: Dict[str, Any], tid: int) -> None:
        with self._lock:
            # the cap is hard: a full buffer drops the event (counted),
            # and thread_name metadata is only added when there is room
            # for it AND the event it annotates — no unbounded growth
            # from thread churn in a long run
            if len(self._events) >= self.max_events:
                self.dropped += 1
                _EVENTS_DROPPED.inc()
                return
            if (
                tid not in self._named_threads
                and len(self._events) + 2 <= self.max_events
            ):
                self._named_threads.add(tid)
                self._events.append({
                    "ph": "M",
                    "name": "thread_name",
                    "pid": ev["pid"],
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                })
            self._events.append(ev)

    def emit_complete(
        self,
        name: str,
        t0_perf: float,
        dur_s: float,
        args: Optional[Dict[str, Any]] = None,
        cat: str = "tftpu",
    ) -> None:
        """Record a complete ("X") event from a perf_counter start + a
        duration — the hook ``profiling.span`` and the instrumented hot
        paths use, since they already hold both numbers."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        ev: Dict[str, Any] = {
            "ph": "X",
            "name": name,
            "cat": cat,
            "ts": _us(t0_perf),
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": tid,
        }
        if args:
            ev["args"] = _clean_args(args)
        self._append(ev, tid)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "tftpu", **args: Any) -> Iterator[None]:
        """Trace the body as one complete event (no-op when disabled)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit_complete(
                name, t0, time.perf_counter() - t0,
                args=args or None, cat=cat,
            )

    def instant(self, name: str, cat: str = "tftpu", **args: Any) -> None:
        """A zero-duration marker ("i" phase, thread scope)."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        ev: Dict[str, Any] = {
            "ph": "i",
            "s": "t",
            "name": name,
            "cat": cat,
            "ts": _us(time.perf_counter()),
            "pid": os.getpid(),
            "tid": tid,
        }
        if args:
            ev["args"] = _clean_args(args)
        self._append(ev, tid)

    # -- export -------------------------------------------------------------

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The JSON-object trace format: ``{"traceEvents": [...]}`` plus
        metadata — accepted by Perfetto and chrome://tracing. The
        ``otherData`` stamp (run_id, process_index, wall-clock epoch)
        is the shard-correlation contract ``observability merge`` reads:
        without it a multi-process run's traces are unjoinable."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "tensorframes_tpu.observability.events",
                "dropped_events": dropped,
                "run_id": _context.run_id(),
                "process_index": _context.process_index(),
                "pid": os.getpid(),
                "trace_epoch_unix_us": _EPOCH_UNIX_US,
            },
        }

    def save(self, path: str) -> str:
        """Write the trace JSON to ``path`` and return it."""
        trace = self.to_chrome_trace()
        with open(path, "w") as f:
            # default=str is the last line of defense: args are cleaned
            # at emit, but an exotic leaf must degrade to a string, not
            # lose the whole collected trace at the final write
            json.dump(trace, f, default=str)
        logger.info(
            "trace: wrote %d events to %s (open in https://ui.perfetto.dev)",
            len(trace["traceEvents"]), path,
        )
        return path

    def save_shard(self, directory: str) -> str:
        """Write this process's trace as a per-process SHARD —
        ``<dir>/trace_<run_id>_p<process_index>.json`` — the file layout
        ``observability merge`` globs to rebuild a whole-run timeline.
        Every process of a run calls this against one shared directory
        (rank in the name keeps writers collision-free)."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory,
            f"trace_{_context.run_id()}_p{_context.process_index()}.json",
        )
        return self.save(path)


#: Process-wide default tracer; the module-level helpers below and every
#: instrumented layer use this instance.
TRACER = Tracer()


def _abandon_buffer_after_fork() -> None:
    # forked worker: the parent's pre-fork events belong in the PARENT's
    # shard — replayed into every child shard they would appear once per
    # rank in the merged timeline. Enabled state is inherited (a tracing
    # parent wants tracing children); the monotonic/wall epoch pair stays
    # valid across fork, so child timestamps still anchor correctly.
    # No lock: the child is single-threaded at this instant.
    TRACER._events = []
    TRACER._named_threads = set()
    TRACER.dropped = 0


if hasattr(os, "register_at_fork"):  # pragma: no branch - posix
    os.register_at_fork(after_in_child=_abandon_buffer_after_fork)


def enable() -> None:
    """Start collecting events on the default tracer."""
    TRACER.enable()


def disable() -> None:
    TRACER.disable()


def active() -> bool:
    """True when the default tracer is collecting."""
    return TRACER.enabled


def clear() -> None:
    TRACER.clear()


def span(name: str, cat: str = "tftpu", **args: Any):
    """Context manager tracing the body on the default tracer."""
    return TRACER.span(name, cat=cat, **args)


def instant(name: str, cat: str = "tftpu", **args: Any) -> None:
    TRACER.instant(name, cat=cat, **args)


def to_chrome_trace() -> Dict[str, Any]:
    return TRACER.to_chrome_trace()


def save(path: str) -> str:
    return TRACER.save(path)


def save_shard(directory: str) -> str:
    """Write the default tracer's per-process shard into ``directory``."""
    return TRACER.save_shard(directory)
