"""Bench snapshots and the perf-regression diff.

The repo's perf story lives in ``BENCH_r*.json`` rounds: a driver
wrapper around one ``bench.py`` run whose ``tail`` holds the
``# name=value`` metric lines and whose ``parsed`` field holds the
final headline JSON. Those rounds record the 2.3-3.0x (PR 4) and 8.9x
(PR 5) wins — and nothing machine-checks that a later change doesn't
quietly give them back. This module makes the trajectory diffable:

* :func:`build_snapshot` / :func:`write_snapshot` — the structured
  snapshot bench.py emits (``TFTPU_BENCH_SNAPSHOT=path``): schema tag,
  run context, the full metrics dict, and the latency quantiles.
* :func:`load_metrics` — one loader for every artifact shape in the
  repo: a native snapshot, a committed ``BENCH_r*.json`` round (metrics
  recovered from its ``tail``), or raw ``bench.py`` stdout.
* :func:`diff_metrics` — per-metric comparison with direction inference
  (rows/sec up is good; wall-seconds up is bad) and per-metric
  thresholds. ``observability diff`` exits nonzero on regression — the
  CI gate (warn-only on CPU runners, where scheduler noise is real).
"""

from __future__ import annotations

import json
import re
import time
from typing import Dict, List, Mapping, Optional, Tuple

__all__ = [
    "SCHEMA",
    "build_snapshot",
    "write_snapshot",
    "load_metrics",
    "parse_bench_text",
    "diff_metrics",
    "DEFAULT_THRESHOLD",
]

SCHEMA = "tftpu-bench-snapshot/1"

#: Default relative-change threshold: CPU bench noise on shared machines
#: runs tens of percent (dev/bench_check.py uses factor 2 for the same
#: reason), so only a >=50% move counts as a regression by default; a
#: genuine 2x latency regression is 100% and always trips.
DEFAULT_THRESHOLD = 0.5

_METRIC_LINE = re.compile(
    r"^#\s*([A-Za-z0-9_.]+)=(-?[0-9][0-9_.eE+-]*)\s*$"
)
_LATENCY_LINE = re.compile(r"^#\s*latency\s*\|\s*(\S+)\s+(.*)$")
_KV = re.compile(r"([A-Za-z0-9_]+)=([-0-9.eE+]+)s?")

_HIGHER_BETTER = ("_per_sec", "per_sec_", "_per_chip", "_speedup")
_LOWER_BETTER_SUFFIX = ("_s", "_seconds", "_ms", "_us")
_LOWER_BETTER_SUBSTR = ("wall_s", "_p50", "_p95", "_p99",
                        ".p50", ".p95", ".p99", ".mean", "compile_s")


def direction(name: str) -> int:
    """+1 = higher is better, -1 = lower is better, 0 = unknown (the
    diff reports unknown-direction metrics but never gates on them)."""
    if name.endswith((".count", "_count", "_total")):
        # counts are run-length-shaped, not quality-shaped: a longer
        # run dispatches more, and that is not a regression
        return 0
    if any(h in name for h in _HIGHER_BETTER):
        return 1
    if any(s in name for s in _LOWER_BETTER_SUBSTR):
        return -1
    if name.endswith(_LOWER_BETTER_SUFFIX):
        return -1
    return 0


# ---------------------------------------------------------------------------
# building (bench.py side)
# ---------------------------------------------------------------------------

def build_snapshot(
    metrics: Mapping[str, float], meta: Optional[Mapping] = None
) -> Dict:
    """Assemble the structured bench snapshot: metrics + the latency
    quantile summary + run context."""
    from . import context as _context
    from .latency import quantile_summary, series_key

    latency = {}
    for row in quantile_summary():
        latency[series_key(row["labels"])] = {
            k: row[k] for k in ("count", "mean", "p50", "p95", "p99")
        }
    snap = {
        "schema": SCHEMA,
        "ts": round(time.time(), 3),
        **_context.snapshot(),
        "metrics": {
            k: float(v) for k, v in metrics.items()
            if isinstance(v, (int, float))
        },
        "latency": latency,
    }
    if meta:
        snap["meta"] = dict(meta)
    return snap


def write_snapshot(
    path: str, metrics: Mapping[str, float], meta: Optional[Mapping] = None
) -> str:
    with open(path, "w") as f:
        json.dump(build_snapshot(metrics, meta), f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# ---------------------------------------------------------------------------
# loading (any artifact shape in the repo)
# ---------------------------------------------------------------------------

def parse_bench_text(text: str) -> Dict[str, float]:
    """Metrics from ``bench.py`` stdout (or a BENCH round's ``tail``):
    ``# name=value`` comment rows, ``# latency |`` quantile rows
    (flattened to ``latency.<series>.<q>``), and the final headline
    JSON line's ``value``."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        m = _METRIC_LINE.match(line)
        if m:
            try:
                out[m.group(1)] = float(m.group(2))
            except ValueError:
                continue
            continue
        m = _LATENCY_LINE.match(line)
        if m:
            series = m.group(1)
            for k, v in _KV.findall(m.group(2)):
                try:
                    out[f"latency.{series}.{k}"] = float(v)
                except ValueError:
                    continue
            continue
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and isinstance(
                obj.get("value"), (int, float)
            ):
                out["headline.value"] = float(obj["value"])
    return out


def _flatten_snapshot(snap: Dict) -> Dict[str, float]:
    out = dict(snap.get("metrics") or {})
    for series, qs in (snap.get("latency") or {}).items():
        for k, v in qs.items():
            if isinstance(v, (int, float)):
                out[f"latency.{series}.{k}"] = float(v)
    return out


def load_metrics(path: str) -> Tuple[Dict[str, float], Dict]:
    """Load ``{metric: value}`` plus a small meta dict from any of: a
    native snapshot (:data:`SCHEMA`), a committed ``BENCH_r*.json``
    round, raw bench stdout, or a metrics-registry JSONL export."""
    with open(path) as f:
        text = f.read()
    # registry JSONL: one {"name": ..., "kind": ...} object per line
    first = text.lstrip()[:1]
    if first == "{" and "\n" in text.strip():
        lines = text.strip().splitlines()
        try:
            rows = [json.loads(ln) for ln in lines]
            if all(isinstance(r, dict) and "name" in r and "kind" in r
                   for r in rows):
                return _metrics_from_registry_rows(rows), {
                    "source": "metrics-jsonl", "path": path,
                }
        except json.JSONDecodeError:
            pass
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        if obj.get("schema") == SCHEMA:
            meta = {
                "source": "snapshot", "path": path,
                "ts": obj.get("ts"), "run_id": obj.get("run_id"),
            }
            return _flatten_snapshot(obj), meta
        if "tail" in obj:  # a driver BENCH_r*.json round
            metrics = parse_bench_text(obj.get("tail") or "")
            parsed = obj.get("parsed")
            if isinstance(parsed, dict) and isinstance(
                parsed.get("value"), (int, float)
            ):
                metrics.setdefault("headline.value", float(parsed["value"]))
            return metrics, {
                "source": "bench-round", "path": path, "n": obj.get("n"),
            }
    # raw bench stdout
    return parse_bench_text(text), {"source": "bench-text", "path": path}


def _metrics_from_registry_rows(rows: List[Dict]) -> Dict[str, float]:
    """Registry-JSONL rows → flat metrics: counters/gauges by value,
    histograms by derived mean and p50/p95/p99 (re-estimated from the
    exported cumulative buckets)."""
    out: Dict[str, float] = {}
    for r in rows:
        labels = r.get("labels") or {}
        suffix = "".join(
            f".{k}.{v}" for k, v in sorted(labels.items())
        )
        base = r["name"] + suffix
        if r["kind"] in ("counter", "gauge"):
            out[base] = float(r.get("value", 0.0))
            continue
        count = int(r.get("count", 0))
        if count <= 0:
            continue
        out[base + ".count"] = float(count)
        out[base + ".mean"] = float(r.get("sum", 0.0)) / count
        from .metrics import quantile_from_cumulative

        cum = []
        for bound, c in (r.get("buckets") or {}).items():
            b = float("inf") if bound in ("+Inf", "inf") else float(bound)
            cum.append((b, int(c)))
        cum.sort(key=lambda t: t[0])
        for q in (0.5, 0.95, 0.99):
            v = quantile_from_cumulative(cum, count, q)
            if v is not None:
                out[f"{base}.p{int(q * 100)}"] = v
    return out


# ---------------------------------------------------------------------------
# diffing
# ---------------------------------------------------------------------------

def diff_metrics(
    old: Mapping[str, float],
    new: Mapping[str, float],
    threshold: float = DEFAULT_THRESHOLD,
    per_metric: Optional[Mapping[str, float]] = None,
) -> Dict:
    """Compare two metric dicts; returns ``{"rows": [...],
    "regressions": [...], "improvements": [...], "only_old": [...],
    "only_new": [...]}``.

    A metric regresses when it moves against its direction by more than
    its threshold: higher-better ``new < old * (1 - t)``, lower-better
    ``new > old * (1 + t)``. Unknown-direction metrics are reported
    (``"?"``) but never gate. ``per_metric`` overrides the global
    threshold by exact metric name."""
    per_metric = dict(per_metric or {})
    rows, regressions, improvements = [], [], []
    common = sorted(set(old) & set(new))
    for name in common:
        a, b = float(old[name]), float(new[name])
        d = direction(name)
        t = per_metric.get(name, threshold)
        ratio = (b / a) if a else None
        status = "ok"
        if d == 0:
            status = "?"
        elif a == 0 and b == 0:
            status = "ok"
        elif a == 0:
            status = "?"  # no base to compare against
        elif d > 0 and b < a * (1.0 - t):
            status = "regression"
        elif d < 0 and b > a * (1.0 + t):
            status = "regression"
        elif d > 0 and b > a * (1.0 + t):
            status = "improvement"
        elif d < 0 and b < a * (1.0 - t):
            status = "improvement"
        row = {
            "metric": name, "old": a, "new": b, "ratio": ratio,
            "direction": {1: "higher", -1: "lower", 0: "?"}[d],
            "threshold": t, "status": status,
        }
        rows.append(row)
        if status == "regression":
            regressions.append(row)
        elif status == "improvement":
            improvements.append(row)
    return {
        "rows": rows,
        "regressions": regressions,
        "improvements": improvements,
        "only_old": sorted(set(old) - set(new)),
        "only_new": sorted(set(new) - set(old)),
    }
